"""One-off probe: where does the ResNet-50 train step spend its time?

Times forward-only, forward+backward, and the full FusedTrainer step at
the same batch, plus XLA's own cost analysis of the compiled step.
"""
import time

import jax
import jax.numpy as jnp
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import models
from mxnet_tpu.trainer import FusedTrainer

BATCH = 256


def timed(label, fn, fetch, iters=20):
    fn()
    fetch()
    tic = time.perf_counter()
    for _ in range(iters):
        out = fn()
    fetch()
    dt = (time.perf_counter() - tic) / iters
    print(f"{label}: {dt*1e3:.2f} ms/iter, {BATCH/dt:.0f} img/s")
    return dt


def main():
    net = models.get_symbol("resnet-50", num_classes=1000)
    tr = FusedTrainer(net, optimizer="sgd",
                      optimizer_params={"lr": 0.1, "momentum": 0.9,
                                        "rescale_grad": 1.0 / BATCH},
                      dtype=jnp.bfloat16)
    tr.init(data=(BATCH, 3, 224, 224))
    rs = np.random.RandomState(0)
    batch = {"data": jax.device_put(
        rs.uniform(0, 1, (BATCH, 3, 224, 224)).astype(np.float32)),
        "softmax_label": jax.device_put(
            rs.randint(0, 1000, BATCH).astype(np.float32))}

    def fetch():
        name = sorted(tr.params)[0]
        return float(np.asarray(tr.params[name]).ravel()[0])

    # full step
    dt_full = timed("full step", lambda: tr.step(**batch), fetch)

    # fwd-only (eval path, is_train False)
    out_box = {}

    def run_eval():
        out_box["o"] = tr.eval(**batch)

    def fetch_eval():
        return float(np.asarray(out_box["o"][0]).ravel()[0])

    dt_eval = timed("fwd only (eval)", run_eval, fetch_eval)

    # fwd+bwd without optimizer: grads via value_and_grad of mean loss
    graph_fn = tr._graph_fn
    params32 = dict(tr.params)
    aux = dict(tr.aux)
    key = jax.random.PRNGKey(0)

    def loss_fn(p, batch):
        cp = {k: v.astype(jnp.bfloat16) for k, v in p.items()}
        ca = {k: v.astype(jnp.bfloat16) for k, v in aux.items()}
        args = dict(cp)
        args["data"] = batch["data"].astype(jnp.bfloat16)
        args["softmax_label"] = batch["softmax_label"]
        outs, _ = graph_fn(args, ca, key, True)
        return sum(jnp.sum(o.astype(jnp.float32)) for o in outs)

    gfn = jax.jit(jax.grad(loss_fn))
    gbox = {}

    def run_grad():
        gbox["g"] = gfn(params32, batch)

    def fetch_grad():
        k = sorted(gbox["g"])[0]
        return float(np.asarray(gbox["g"][k]).ravel()[0])

    dt_grad = timed("fwd+bwd (no opt)", run_grad, fetch_grad)

    # XLA cost analysis of the full compiled step
    lowered = tr._step_fn.lower(tr.params, tr.aux, tr.opt_state,
                                {k: v for k, v in batch.items()}, key)
    compiled = lowered.compile()
    ca = compiled.cost_analysis()
    ca = ca[0] if isinstance(ca, (list, tuple)) else ca
    flops = ca.get("flops", float("nan"))
    print(f"XLA flops/step: {flops/1e9:.1f} GFLOP "
          f"({flops/BATCH/1e9:.2f} GFLOP/img)"
          f" -> {flops/dt_full/1e12:.1f} TFLOP/s achieved")
    for key_ in ("bytes accessed", "bytes accessed0{}", "utilization0{}"):
        if key_ in ca:
            print(f"  {key_}: {ca[key_]:.3e}")


if __name__ == "__main__":
    main()
