#!/usr/bin/env python
"""Kill stray distributed-training processes on this host.

Parity: tools/kill-mxnet.py (reference) — the ops-side cleanup tool for
runs whose launcher died: finds processes whose environment carries the
launcher's role variables (MXTPU_ROLE / DMLC_ROLE) or whose command line
matches the given pattern, and SIGTERMs (then SIGKILLs) them.
"""
from __future__ import annotations

import argparse
import os
import signal
import sys
import time


def find_procs(pattern):
    victims = []
    me = os.getpid()
    for pid in os.listdir("/proc"):
        if not pid.isdigit() or int(pid) == me:
            continue
        try:
            with open(f"/proc/{pid}/environ", "rb") as f:
                env = f.read().decode(errors="replace")
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmd = f.read().decode(errors="replace").replace("\0", " ")
        except OSError:
            continue
        launched = "MXTPU_ROLE=" in env or "DMLC_ROLE=" in env
        if launched or (pattern and pattern in cmd):
            victims.append((int(pid), cmd.strip()))
    return victims


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("pattern", nargs="?", default=None,
                    help="also kill processes whose cmdline contains this")
    ap.add_argument("--dry-run", action="store_true")
    args = ap.parse_args()
    victims = find_procs(args.pattern)
    if not victims:
        print("nothing to kill")
        return
    for pid, cmd in victims:
        print(f"{'would kill' if args.dry_run else 'killing'} {pid}: {cmd[:100]}")
        if not args.dry_run:
            try:
                os.kill(pid, signal.SIGTERM)
            except OSError:
                pass
    if args.dry_run:
        return
    time.sleep(2)
    for pid, _ in victims:
        try:
            os.kill(pid, signal.SIGKILL)
        except OSError:
            pass


if __name__ == "__main__":
    main()
