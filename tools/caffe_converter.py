#!/usr/bin/env python
"""Caffe prototxt -> mxnet_tpu Symbol converter (parity:
tools/caffe_converter/convert_symbol.py).

The reference parses deploy.prototxt with caffe's protobuf bindings;
neither caffe nor caffe.proto exists in this image, so this converter
ships its own minimal prototxt (protobuf text-format) reader and maps
the common layer types onto the symbol API:

    Input/Data, Convolution, Deconvolution, InnerProduct, Pooling,
    ReLU, Sigmoid, TanH, Dropout, LRN, BatchNorm (+Scale), Concat,
    Eltwise (SUM/PROD/MAX), Flatten, Softmax, SoftmaxWithLoss

Usage::

    python caffe_converter.py deploy.prototxt out_prefix
    # writes out_prefix-symbol.json

or programmatically: ``net, inputs = convert_symbol(open(f).read())``.
"""
import json
import re
import sys

# --------------------------------------------------------------------------
# prototxt (protobuf text format) parser
# --------------------------------------------------------------------------
_TOKEN = re.compile(r"""
    \s*(?:
        (?P<comment>\#[^\n]*)
      | (?P<brace>[{}])
      | (?P<colon>:)
      | (?P<string>"(?:[^"\\]|\\.)*")
      | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
      | (?P<number>-?\d+(?:\.\d*)?(?:[eE][+-]?\d+)?)
    )""", re.VERBOSE)


def _tokens(text):
    pos = 0
    while pos < len(text):
        m = _TOKEN.match(text, pos)
        if not m:
            if text[pos:].strip() == "":
                return
            raise ValueError(f"prototxt parse error at: {text[pos:pos+40]!r}")
        pos = m.end()
        kind = m.lastgroup
        if kind == "comment" or kind is None:
            continue
        yield kind, m.group(kind)


def parse_prototxt(text):
    """Parse protobuf text format into nested dicts; repeated fields
    become lists."""
    toks = list(_tokens(text))
    i = 0

    def parse_block():
        nonlocal i
        out = {}
        while i < len(toks):
            kind, val = toks[i]
            if kind == "brace" and val == "}":
                i += 1
                return out
            if kind != "ident":
                raise ValueError(f"expected field name, got {val!r}")
            field = val
            i += 1
            kind, val = toks[i]
            if kind == "colon":
                i += 1
                kind, val = toks[i]
                if kind == "string":
                    value = val[1:-1]
                elif kind == "number":
                    value = float(val) if ("." in val or "e" in val.lower()) \
                        else int(val)
                elif kind == "ident":
                    value = {"true": True, "false": False}.get(val, val)
                else:
                    raise ValueError(f"bad value for {field}: {val!r}")
                i += 1
            elif kind == "brace" and val == "{":
                i += 1
                value = parse_block()
            else:
                raise ValueError(f"expected ':' or '{{' after {field}")
            if field in out:
                if not isinstance(out[field], list):
                    out[field] = [out[field]]
                out[field].append(value)
            else:
                out[field] = value
        return out

    return parse_block()


def _as_list(v):
    if v is None:
        return []
    return v if isinstance(v, list) else [v]


def _first_int(param, key, default):
    v = param.get(key)
    if v is None:
        return default
    return int(_as_list(v)[0])


# --------------------------------------------------------------------------
# layer -> symbol mapping
# --------------------------------------------------------------------------
def convert_symbol(prototxt_text):
    """Returns (output Symbol, {input_name: shape_or_None})."""
    import os

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(
        __file__)), ".."))
    from mxnet_tpu import symbol as sym

    net = parse_prototxt(prototxt_text)
    layers = _as_list(net.get("layer")) or _as_list(net.get("layers"))
    blobs = {}
    inputs = {}

    # old-style top-level input declaration
    for name, dims in zip(_as_list(net.get("input")),
                          _as_list(net.get("input_shape"))):
        shape = tuple(int(d) for d in _as_list(dims.get("dim")))
        blobs[name] = sym.Variable(name)
        inputs[name] = shape
    if "input" in net and "input_dim" in net:
        name = _as_list(net["input"])[0]
        dims = tuple(int(d) for d in _as_list(net["input_dim"]))
        blobs[name] = sym.Variable(name)
        inputs[name] = dims

    last = None
    blob_src = {}  # blob name -> producing layer type (for Scale folding)
    for layer in layers:
        ltype = str(layer.get("type"))
        name = layer.get("name", ltype)
        bottom_names = [b for b in _as_list(layer.get("bottom")) if b in blobs]
        bottoms = [blobs[b] for b in bottom_names]
        tops = _as_list(layer.get("top")) or [name]
        data = bottoms[0] if bottoms else None

        if ltype in ("Input", "Data", "MemoryData", "DummyData"):
            shape = None
            sp = layer.get("input_param", {}).get("shape") \
                or layer.get("dummy_data_param", {}).get("shape")
            if sp:
                shape = tuple(int(d)
                              for d in _as_list(_as_list(sp)[0].get("dim")))
            out = sym.Variable(tops[0])
            inputs[tops[0]] = shape
        elif ltype == "Convolution":
            p = layer.get("convolution_param", {})
            k = _first_int(p, "kernel_size", 1)
            out = sym.Convolution(
                data, num_filter=int(p["num_output"]), kernel=(k, k),
                stride=(_first_int(p, "stride", 1),) * 2,
                pad=(_first_int(p, "pad", 0),) * 2,
                num_group=int(p.get("group", 1)),
                no_bias=not p.get("bias_term", True), name=name)
        elif ltype == "Deconvolution":
            p = layer.get("convolution_param", {})
            k = _first_int(p, "kernel_size", 1)
            out = sym.Deconvolution(
                data, num_filter=int(p["num_output"]), kernel=(k, k),
                stride=(_first_int(p, "stride", 1),) * 2,
                pad=(_first_int(p, "pad", 0),) * 2,
                no_bias=not p.get("bias_term", True), name=name)
        elif ltype == "InnerProduct":
            p = layer.get("inner_product_param", {})
            out = sym.FullyConnected(sym.Flatten(data),
                                     num_hidden=int(p["num_output"]),
                                     no_bias=not p.get("bias_term", True),
                                     name=name)
        elif ltype == "Pooling":
            p = layer.get("pooling_param", {})
            pool = {0: "max", 1: "avg", "MAX": "max", "AVE": "avg"}.get(
                p.get("pool", 0), "max")
            if p.get("global_pooling"):
                out = sym.Pooling(data, global_pool=True, kernel=(1, 1),
                                  pool_type=pool, name=name)
            else:
                k = _first_int(p, "kernel_size", 1)
                out = sym.Pooling(
                    data, kernel=(k, k),
                    stride=(_first_int(p, "stride", 1),) * 2,
                    pad=(_first_int(p, "pad", 0),) * 2, pool_type=pool,
                    # caffe pools are ceil-mode; 'full' is the parity
                    pooling_convention="full", name=name)
        elif ltype == "ReLU":
            out = sym.Activation(data, act_type="relu", name=name)
        elif ltype == "Sigmoid":
            out = sym.Activation(data, act_type="sigmoid", name=name)
        elif ltype == "TanH":
            out = sym.Activation(data, act_type="tanh", name=name)
        elif ltype == "Dropout":
            p = layer.get("dropout_param", {})
            out = sym.Dropout(data, p=float(p.get("dropout_ratio", 0.5)),
                              name=name)
        elif ltype == "LRN":
            p = layer.get("lrn_param", {})
            out = sym.LRN(data, nsize=_first_int(p, "local_size", 5),
                          alpha=float(p.get("alpha", 1e-4)),
                          beta=float(p.get("beta", 0.75)), name=name)
        elif ltype == "BatchNorm":
            p = layer.get("batch_norm_param", {})
            # fix_gamma=False: the gamma/beta of the caffe Scale layer that
            # always follows BatchNorm live here (see Scale folding below)
            out = sym.BatchNorm(
                data, use_global_stats=bool(p.get("use_global_stats", True)),
                eps=float(p.get("eps", 1e-5)), fix_gamma=False, name=name)
        elif ltype == "Scale":
            # caffe pairs BatchNorm with a Scale layer for gamma/beta;
            # BatchNorm(fix_gamma=False) already carries them, so a Scale
            # directly after a BatchNorm folds into it as identity here.
            # A standalone Scale (learned per-channel affine elsewhere in
            # the net) must NOT silently disappear.
            if not bottom_names or blob_src.get(bottom_names[0]) != "BatchNorm":
                raise ValueError(
                    f"standalone Scale layer {name!r} (bottom produced by "
                    f"{blob_src.get(bottom_names[0] if bottom_names else None)!r}) "
                    "is unsupported: only Scale-after-BatchNorm folds away")
            out = data
        elif ltype == "Concat":
            p = layer.get("concat_param", {})
            out = sym.Concat(*bottoms, dim=int(p.get("axis", 1)), name=name)
        elif ltype == "Eltwise":
            p = layer.get("eltwise_param", {})
            op = p.get("operation", "SUM")  # str enum or numeric code
            out = bottoms[0]
            for b in bottoms[1:]:
                if op in ("SUM", 1):
                    out = out + b
                elif op in ("PROD", 0):
                    out = out * b
                elif op in ("MAX", 2):
                    out = sym.maximum(out, b)
                else:
                    raise ValueError(f"unknown Eltwise operation {op!r}")
        elif ltype == "Flatten":
            out = sym.Flatten(data, name=name)
        elif ltype in ("Softmax", "SoftmaxWithLoss"):
            out = sym.SoftmaxOutput(data, name=name)
        elif ltype in ("Accuracy", "Silence"):
            continue
        else:
            raise ValueError(f"unsupported caffe layer type {ltype!r} "
                             f"(layer {name})")
        for top in tops:
            blobs[top] = out
            # record unconditionally: after an in-place BN->Scale pair the
            # blob's producer becomes "Scale", so a SECOND Scale reading it
            # fails the BatchNorm check instead of silently folding
            blob_src[top] = ltype
        last = out
    return last, inputs


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) != 2:
        print("usage: caffe_converter.py deploy.prototxt out_prefix")
        return 1
    with open(argv[0]) as f:
        net, inputs = convert_symbol(f.read())
    net.save(argv[1] + "-symbol.json")
    print(json.dumps({"inputs": {k: list(v) if v else None
                                 for k, v in inputs.items()},
                      "outputs": net.list_outputs()}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
