#!/usr/bin/env python
"""Decode-serving process: continuous batching over a transformer-LM
checkpoint (mxnet_tpu/serving/).

The deployment entrypoint the C-predict ABI story was missing: one
process owns the bound KVDecoder, admits concurrent request streams
over HTTP, and batches their decode steps into one jitted program per
tick.  Ops surface: ``/metrics`` (Prometheus), ``/healthz``,
``POST /generate`` — see docs/serving.md for the runbook.

    # serve a save_checkpoint()-style transformer_lm checkpoint
    python tools/serve.py --prefix ckpt/lm --epoch 10 \
        --num-layers 4 --num-heads 8 --max-len 512 --port 9200

    # smoke/demo: a randomly initialized tiny LM (no checkpoint needed)
    python tools/serve.py --demo --port 9200

    curl -s localhost:9200/generate -d \
        '{"prompt": [1, 2, 3], "max_tokens": 16}'

Knobs (flags override env): MXTPU_SERVE_SLOTS, MXTPU_SERVE_QUEUE,
MXTPU_SERVE_DEADLINE_MS, MXTPU_PREDICT_INT8 (docs/how_to/env_var.md
round 10).
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def _parse_args(argv=None):
    ap = argparse.ArgumentParser(
        description="continuous-batching decode server")
    ap.add_argument("--prefix", help="checkpoint prefix (save_checkpoint)")
    ap.add_argument("--epoch", type=int, default=0)
    ap.add_argument("--demo", action="store_true",
                    help="serve a randomly initialized tiny LM (smoke)")
    ap.add_argument("--num-layers", type=int, default=2)
    ap.add_argument("--num-heads", type=int, default=2)
    ap.add_argument("--d-model", type=int, default=64,
                    help="demo model width (checkpoints carry their own)")
    ap.add_argument("--vocab-size", type=int, default=256,
                    help="demo vocab (checkpoints carry their own)")
    ap.add_argument("--max-len", type=int, default=128,
                    help="KV-cache length = prompt + generation budget")
    ap.add_argument("--dtype", default="float32",
                    choices=["float32", "bfloat16"])
    ap.add_argument("--int8", action="store_true",
                    help="post-training int8 weight quantization "
                         "(or MXTPU_PREDICT_INT8=1)")
    ap.add_argument("--slots", type=int, default=None,
                    help="decode slots (MXTPU_SERVE_SLOTS, default 4)")
    ap.add_argument("--queue", type=int, default=None,
                    help="admission queue bound (MXTPU_SERVE_QUEUE, 16)")
    ap.add_argument("--deadline-ms", type=int, default=None,
                    help="default per-request deadline "
                         "(MXTPU_SERVE_DEADLINE_MS, 30000)")
    ap.add_argument("--port", type=int, default=9200)
    ap.add_argument("--addr", default="127.0.0.1")
    return ap.parse_args(argv)


def build_decoder(args):
    """KVDecoder from a checkpoint (or random demo params)."""
    import jax.numpy as jnp
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu.models.decode import KVDecoder

    quantize = "int8" if (args.int8 or os.environ.get(
        "MXTPU_PREDICT_INT8", "0").lower() not in ("", "0", "false")) \
        else None
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    if args.demo:
        from mxnet_tpu import models

        net = models.transformer.transformer_lm(
            num_layers=args.num_layers, num_heads=args.num_heads,
            d_model=args.d_model, seq_len=args.max_len,
            vocab_size=args.vocab_size)
        ex = net.simple_bind(ctx=mx.cpu(), grad_req="null",
                             data=(1, args.max_len),
                             softmax_label=(1, args.max_len))
        rs = np.random.RandomState(0)
        params = {}
        for name, arr in ex.arg_dict.items():
            if name in ("data", "softmax_label"):
                continue
            arr[:] = rs.normal(0, 0.08, arr.shape).astype(np.float32)
            params[name] = arr
    else:
        if not args.prefix:
            raise SystemExit("need --prefix (or --demo)")
        _, params, _ = mx.model.load_checkpoint(args.prefix, args.epoch)
    return KVDecoder(params, num_layers=args.num_layers,
                     num_heads=args.num_heads, max_len=args.max_len,
                     dtype=dtype, quantize=quantize)


def main(argv=None):
    args = _parse_args(argv)
    from mxnet_tpu import telemetry
    from mxnet_tpu.serving import serve_decoder

    telemetry.enable()  # a server without metrics is not operable
    decoder = build_decoder(args)
    server, scheduler = serve_decoder(
        decoder, port=args.port, addr=args.addr, num_slots=args.slots,
        queue_size=args.queue, default_deadline_ms=args.deadline_ms)
    host, port = server.server_address[:2]
    print(f"serving on http://{host}:{port}  "
          f"(slots={scheduler.num_slots} queue={scheduler.queue_size} "
          f"deadline_ms={scheduler.default_deadline_ms} "
          f"int8={decoder.quantize == 'int8'})", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        print("shutting down", flush=True)
    finally:
        server.shutdown()
        scheduler.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
