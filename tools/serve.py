#!/usr/bin/env python
"""Decode-serving process: continuous batching over a transformer-LM
checkpoint (mxnet_tpu/serving/), as one replica or as a routed fleet.

The deployment entrypoint the C-predict ABI story was missing: one
process owns the bound KVDecoder, admits concurrent request streams
over HTTP, and batches their decode steps into one jitted program per
tick.  Ops surface: ``/metrics`` (Prometheus), ``/healthz``,
``POST /generate``, ``POST /admin/drain|undrain`` — see docs/serving.md
for the runbook.

    # serve a save_checkpoint()-style transformer_lm checkpoint
    python tools/serve.py --prefix ckpt/lm --epoch 10 \
        --num-layers 4 --num-heads 8 --max-len 512 --port 9200

    # smoke/demo: a randomly initialized tiny LM (no checkpoint needed)
    python tools/serve.py --demo --port 9200

    # paged KV cache with prefix reuse (16-token pages)
    python tools/serve.py --demo --kv-block 16

    # a routed 2-replica local fleet (router + 2 replica subprocesses)
    python tools/serve.py --router --fleet 2 --demo --port 9100

    # router over existing replicas / a coordinator registry
    python tools/serve.py --router --replicas h1:9200,h2:9200
    python tools/serve.py --router --coord 10.0.0.1:8476

    curl -s localhost:9200/generate -d \
        '{"prompt": [1, 2, 3], "max_tokens": 16}'

SIGTERM drains gracefully: the scheduler stops admitting, queued and
in-flight requests finish, then the process exits 0 — so a plain
``kill`` IS the restart step of the rolling-upgrade runbook.

Request tracing + SLO plane (docs/tracing.md): ``--trace`` (or
``MXTPU_TRACE=1``) turns on span recording — the router mints/forwards
W3C ``traceparent`` per request, every process serves its span buffer
at ``GET /spans.json``, the router serves burn rates at ``GET /slo``,
and ``tools/fleetstat.py trace <id> --router host:port`` joins one
request's spans into a clock-corrected chrome trace.

Knobs (flags override env): MXTPU_SERVE_SLOTS, MXTPU_SERVE_QUEUE,
MXTPU_SERVE_DEADLINE_MS, MXTPU_PREDICT_INT8, MXTPU_KV_BLOCK,
MXTPU_PREFIX_CACHE, MXTPU_SERVE_REPLICAS, MXTPU_ROUTER_SCRAPE_S,
MXTPU_ROUTER_RETRIES, MXTPU_TRACE, MXTPU_TRACE_SAMPLE,
MXTPU_SLO_TTFT_MS, MXTPU_SLO_AVAIL (docs/how_to/env_var.md rounds
10 + 19 + 20).
"""
import argparse
import os
import signal
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def _parse_args(argv=None):
    ap = argparse.ArgumentParser(
        description="continuous-batching decode server / fleet router")
    ap.add_argument("--prefix", help="checkpoint prefix (save_checkpoint)")
    ap.add_argument("--epoch", type=int, default=0)
    ap.add_argument("--demo", action="store_true",
                    help="serve a randomly initialized tiny LM (smoke)")
    ap.add_argument("--num-layers", type=int, default=2)
    ap.add_argument("--num-heads", type=int, default=2)
    ap.add_argument("--d-model", type=int, default=64,
                    help="demo model width (checkpoints carry their own)")
    ap.add_argument("--vocab-size", type=int, default=256,
                    help="demo vocab (checkpoints carry their own)")
    ap.add_argument("--max-len", type=int, default=128,
                    help="KV-cache length = prompt + generation budget")
    ap.add_argument("--dtype", default="float32",
                    choices=["float32", "bfloat16"])
    ap.add_argument("--int8", action="store_true",
                    help="post-training int8 weight quantization "
                         "(or MXTPU_PREDICT_INT8=1)")
    ap.add_argument("--slots", type=int, default=None,
                    help="decode slots (MXTPU_SERVE_SLOTS, default 4)")
    ap.add_argument("--queue", type=int, default=None,
                    help="admission queue bound (MXTPU_SERVE_QUEUE, 16)")
    ap.add_argument("--deadline-ms", type=int, default=None,
                    help="default per-request deadline "
                         "(MXTPU_SERVE_DEADLINE_MS, 30000)")
    ap.add_argument("--kv-block", type=int, default=None,
                    help="paged KV cache page size in tokens "
                         "(MXTPU_KV_BLOCK; 0/unset = contiguous)")
    ap.add_argument("--register", action="store_true",
                    help="self-register this replica with the PR-13 "
                         "coordinator (--coord / MXTPU_COORD_ADDR)")
    ap.add_argument("--router", action="store_true",
                    help="run the fleet router instead of a replica")
    ap.add_argument("--fleet", type=int, default=0, metavar="N",
                    help="with --router: spawn N local replica "
                         "subprocesses (same model flags) and route "
                         "over them")
    ap.add_argument("--replicas", default=None,
                    help="with --router: static host:port list "
                         "(MXTPU_SERVE_REPLICAS)")
    ap.add_argument("--coord", default=None,
                    help="coordinator host:port (MXTPU_COORD_ADDR): "
                         "replica self-registration / router discovery")
    ap.add_argument("--scrape-s", type=float, default=None,
                    help="router healthz scrape interval "
                         "(MXTPU_ROUTER_SCRAPE_S, 1s)")
    ap.add_argument("--retries", type=int, default=None,
                    help="router idempotent re-routes per request "
                         "(MXTPU_ROUTER_RETRIES, 2)")
    ap.add_argument("--trace", action="store_true",
                    help="record request spans (MXTPU_TRACE=1): "
                         "/spans.json per process, /slo + traceparent "
                         "minting on the router — docs/tracing.md")
    ap.add_argument("--port", type=int, default=9200)
    ap.add_argument("--addr", default="127.0.0.1")
    return ap.parse_args(argv)


def build_decoder(args):
    """KVDecoder from a checkpoint (or random demo params)."""
    import jax.numpy as jnp
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu.models.decode import KVDecoder

    quantize = "int8" if (args.int8 or os.environ.get(
        "MXTPU_PREDICT_INT8", "0").lower() not in ("", "0", "false")) \
        else None
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    if args.demo:
        from mxnet_tpu import models

        net = models.transformer.transformer_lm(
            num_layers=args.num_layers, num_heads=args.num_heads,
            d_model=args.d_model, seq_len=args.max_len,
            vocab_size=args.vocab_size)
        ex = net.simple_bind(ctx=mx.cpu(), grad_req="null",
                             data=(1, args.max_len),
                             softmax_label=(1, args.max_len))
        rs = np.random.RandomState(0)
        params = {}
        for name, arr in ex.arg_dict.items():
            if name in ("data", "softmax_label"):
                continue
            arr[:] = rs.normal(0, 0.08, arr.shape).astype(np.float32)
            params[name] = arr
    else:
        if not args.prefix:
            raise SystemExit("need --prefix (or --demo)")
        _, params, _ = mx.model.load_checkpoint(args.prefix, args.epoch)
    return KVDecoder(params, num_layers=args.num_layers,
                     num_heads=args.num_heads, max_len=args.max_len,
                     dtype=dtype, quantize=quantize)


def _arm_sigterm():
    """SIGTERM/SIGINT -> a stop event the main loop polls, so ``kill``
    triggers the graceful drain instead of an abrupt death."""
    stop = threading.Event()

    def _handler(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _handler)
    try:
        signal.signal(signal.SIGINT, _handler)
    except ValueError:
        pass
    return stop


def _main_replica(args):
    from mxnet_tpu import telemetry
    from mxnet_tpu.serving import serve_decoder

    telemetry.enable()  # a server without metrics is not operable
    if args.trace:
        telemetry.tracing.enable_tracing()
    stop = _arm_sigterm()
    decoder = build_decoder(args)
    server, scheduler = serve_decoder(
        decoder, port=args.port, addr=args.addr, num_slots=args.slots,
        queue_size=args.queue, default_deadline_ms=args.deadline_ms,
        kv_block=args.kv_block)
    host, port = server.server_address[:2]
    client = None
    if args.register or args.coord:
        from mxnet_tpu.serving import register_replica

        client = register_replica(f"{host}:{port}",
                                  coordinator=args.coord)
        print(f"registered with coordinator {client.addr} as "
              f"{client.member}", flush=True)
    paged = scheduler.paged_stats()
    print(f"serving on http://{host}:{port}  "
          f"(slots={scheduler.num_slots} queue={scheduler.queue_size} "
          f"deadline_ms={scheduler.default_deadline_ms} "
          f"int8={decoder.quantize == 'int8'} "
          f"paged={paged['block'] if paged else 0})", flush=True)
    try:
        while not stop.wait(0.5):
            pass
        # the PR-11 drain, wired to the signal (ISSUE 15): stop
        # admitting, let queued + in-flight requests finish (bounded by
        # their deadlines), then exit 0 — `kill` == the restart step of
        # the rolling-upgrade runbook
        print("SIGTERM: draining (in-flight requests finishing)",
              flush=True)
        scheduler.drain()
        while not scheduler.drained:
            time.sleep(0.05)
        print("drained, exiting", flush=True)
    finally:
        if client is not None:
            client.leave(why="drained")
        server.shutdown()
        scheduler.close()
    return 0


def _spawn_fleet(args):
    """Spawn ``--fleet N`` replica subprocesses (same model flags,
    ephemeral ports) and collect their addresses from the 'serving on'
    line.  Children die with us (SIGTERM -> graceful drain)."""
    import re
    import subprocess

    flags = [sys.executable, os.path.abspath(__file__)]
    if args.demo:
        flags.append("--demo")
    else:
        flags += ["--prefix", args.prefix or "", "--epoch",
                  str(args.epoch)]
    flags += ["--num-layers", str(args.num_layers),
              "--num-heads", str(args.num_heads),
              "--d-model", str(args.d_model),
              "--vocab-size", str(args.vocab_size),
              "--max-len", str(args.max_len),
              "--dtype", args.dtype,
              "--port", "0", "--addr", args.addr]
    if args.int8:
        flags.append("--int8")
    if args.slots is not None:
        flags += ["--slots", str(args.slots)]
    if args.queue is not None:
        flags += ["--queue", str(args.queue)]
    if args.deadline_ms is not None:
        flags += ["--deadline-ms", str(args.deadline_ms)]
    if args.kv_block is not None:
        flags += ["--kv-block", str(args.kv_block)]
    if args.trace:
        flags.append("--trace")   # one flag traces the whole fleet
    procs, addrs = [], []
    for _ in range(args.fleet):
        procs.append(subprocess.Popen(
            flags, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True))
    try:
        for p in procs:
            addr, deadline = None, time.time() + 180
            while time.time() < deadline:
                line = p.stdout.readline()
                if not line:
                    break
                sys.stdout.write("[replica %d] %s" % (p.pid, line))
                m = re.search(r"serving on http://([0-9.]+:[0-9]+)", line)
                if m:
                    addr = m.group(1)
                    break
            if addr is None:
                raise SystemExit(
                    f"replica pid {p.pid} never reported its address")
            addrs.append(addr)
            # keep the pipe drained so the child never blocks on stdout
            t = threading.Thread(
                target=lambda f=p.stdout: [None for _ in f],
                daemon=True)
            t.start()
    except BaseException:
        for p in procs:
            p.kill()
        raise
    return procs, addrs


def _main_router(args):
    from mxnet_tpu import telemetry
    from mxnet_tpu.serving import ReplicaRouter, start_router

    telemetry.enable()
    if args.trace:
        telemetry.tracing.enable_tracing()
    stop = _arm_sigterm()
    procs = []
    replicas = [a.strip() for a in (args.replicas or "").split(",")
                if a.strip()] or None
    if args.fleet:
        procs, spawned = _spawn_fleet(args)
        replicas = (replicas or []) + spawned
    router = ReplicaRouter(replicas=replicas, coordinator=args.coord,
                           scrape_s=args.scrape_s, retries=args.retries)
    server = start_router(router, port=args.port, addr=args.addr)
    host, port = server.server_address[:2]
    n = len(router.replicas())
    print(f"routing on http://{host}:{port} over {n} replica(s) "
          f"(scrape every {router.scrape_s}s, retries {router.retries}"
          f"{', coordinator ' + args.coord if args.coord else ''}"
          f"{', tracing on' if args.trace else ''}) — "
          f"GET /slo for burn rates, /spans.json for the span buffer",
          flush=True)
    try:
        while not stop.wait(0.5):
            pass
        print("SIGTERM: stopping router"
              + (" and draining local fleet" if procs else ""),
              flush=True)
    finally:
        for p in procs:
            p.terminate()       # SIGTERM -> each replica drains
        for p in procs:
            try:
                p.wait(timeout=120)
            except Exception:  # noqa: BLE001 — last resort on shutdown
                p.kill()
        server.shutdown()
        router.stop()
    return 0


def main(argv=None):
    args = _parse_args(argv)
    if args.router:
        return _main_router(args)
    return _main_replica(args)


if __name__ == "__main__":
    sys.exit(main())
