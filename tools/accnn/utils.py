"""Graph-surgery helpers for ACCNN (parity: tools/accnn/utils.py —
the reference rewrites the symbol's JSON node list to swap layers for
their low-rank decompositions; same mechanism here against this
package's JSON schema, symbol.py tojson/load_json).
"""
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))))

import mxnet_tpu as mx  # noqa: E402


def load_model(prefix, epoch):
    symbol, arg_params, aux_params = mx.model.load_checkpoint(prefix, epoch)
    return symbol, {k: v.asnumpy() for k, v in arg_params.items()}, \
        {k: v.asnumpy() for k, v in aux_params.items()}


def save_model(prefix, epoch, symbol, arg_params, aux_params):
    mx.model.save_checkpoint(
        prefix, epoch, symbol,
        {k: mx.nd.array(v) for k, v in arg_params.items()},
        {k: mx.nd.array(v) for k, v in aux_params.items()})


def rewrite_graph(symbol, handlers):
    """Rebuild the symbol's JSON graph, letting ``handlers[op]`` expand
    chosen nodes into several.

    handler(node, inputs, emit) -> output entry
      - node:   the original JSON node dict
      - inputs: the node's input entries already mapped to the new graph
      - emit(op, name, attrs, inputs, is_aux=False) -> entry in the new
        graph (use op="null" for new variables)
    Returning None keeps the node unchanged.  Unconsumed null nodes
    (e.g. the replaced conv's weight) are dropped automatically by
    emitting variables lazily.
    """
    g = json.loads(symbol.tojson())
    new_nodes = []
    entry_map = {}  # old node id -> new entry [id, out_idx, 0]

    def emit(op, name, attrs, inputs, is_aux=False):
        new_nodes.append({"op": op, "name": name,
                          "attrs": {k: json.dumps(v) if not isinstance(v, str)
                                    else v for k, v in (attrs or {}).items()},
                          "extra_attrs": {}, "is_aux": is_aux,
                          "inputs": [list(e) for e in inputs]})
        return [len(new_nodes) - 1, 0, 0]

    # null nodes are emitted lazily on first use so orphaned params vanish
    lazy = {}

    def resolve(old_entry):
        oid, oidx, _ = old_entry
        if oid in entry_map:
            e = entry_map[oid]
            return [e[0], oidx, 0]
        node = g["nodes"][oid]
        assert node["op"] == "null", node
        if oid not in lazy:
            lazy[oid] = emit("null", node["name"], node.get("attrs", {}),
                             [], node.get("is_aux", False))
        entry_map[oid] = lazy[oid]
        return [lazy[oid][0], oidx, 0]

    for oid, node in enumerate(g["nodes"]):
        if node["op"] == "null":
            continue  # lazily emitted
        inputs = [resolve(e) for e in node["inputs"]]
        handler = handlers.get(node["op"])
        out = handler(node, inputs, emit) if handler else None
        if out is None:
            out = emit(node["op"], node["name"], node.get("attrs", {}),
                       inputs, node.get("is_aux", False))
        entry_map[oid] = out

    heads = []
    for e in g["heads"]:
        ne = entry_map[e[0]]
        heads.append([ne[0], e[1], 0])
    out = {"nodes": new_nodes, "heads": heads}
    if "arg_nodes" in g:
        out["arg_nodes"] = [i for i, n in enumerate(new_nodes)
                            if n["op"] == "null"]
    from mxnet_tpu import symbol as sym_mod

    return sym_mod.load_json(json.dumps(out))
