"""Vertical-horizontal low-rank conv decomposition (parity:
tools/accnn/acc_conv.py — Jaderberg-style SVD factorization of a k×k
conv into a k×1 conv with K filters followed by a 1×k conv).

W (N,C,y,x) reshapes to (C·y, N·x); its rank-K SVD splits into
V (K,C,y,1) and H (N,K,1,x) with the singular values' square roots
folded into both factors.  The bias rides on the horizontal conv.
"""
import json

import numpy as np


def matricize(W):
    """(N,C,y,x) -> the (C·y, N·x) matrix whose SVD factorizes the conv
    (single source of truth — rank_selection's spectra must match)."""
    n, c, y, x = W.shape
    return W.transpose(1, 2, 0, 3).reshape(c * y, n * x)


def decompose_weights(W, b, K):
    n, c, y, x = W.shape
    U, D, Qt = np.linalg.svd(matricize(W), full_matrices=False)
    K = min(K, len(D))
    sqrt_d = np.sqrt(D[:K])
    V = (U[:, :K] * sqrt_d).T.reshape(K, c, y, 1)
    H = (Qt[:K].T * sqrt_d).reshape(n, x, K).transpose(0, 2, 1)[:, :, None, :]
    return V.astype(W.dtype), H.astype(W.dtype), b


def make_conv_handler(ranks, arg_params, new_params, replaced=None):
    """rewrite_graph handler replacing each ranked conv with its V/H
    pair; decomposed weights land in new_params, replaced layer names in
    ``replaced`` (so the caller only drops params it actually swapped)."""

    def handler(node, inputs, emit):
        name = node["name"]
        if name not in ranks:
            return None
        attrs = {k: json.loads(v) if isinstance(v, str) else v
                 for k, v in node["attrs"].items()}
        kernel = tuple(attrs["kernel"])
        if kernel[0] == 1 or kernel[1] == 1:
            return None  # already rank-1 spatially
        if tuple(attrs.get("dilate", (1, 1))) != (1, 1) \
                or int(attrs.get("num_group", 1)) != 1:
            return None  # V/H split would change semantics
        stride = tuple(attrs.get("stride", (1, 1)))
        pad = tuple(attrs.get("pad", (0, 0)))
        num_filter = int(attrs["num_filter"])
        K = int(ranks[name])

        W = arg_params[name + "_weight"]
        b = arg_params.get(name + "_bias",
                           np.zeros(num_filter, dtype=W.dtype))
        V, H, b2 = decompose_weights(W, b, K)
        new_params[name + "_v_weight"] = V
        new_params[name + "_h_weight"] = H
        new_params[name + "_h_bias"] = b2
        if replaced is not None:
            replaced.add(name)

        vw = emit("null", name + "_v_weight", {}, [])
        conv_v = emit("Convolution", name + "_v",
                      {"kernel": [kernel[0], 1], "stride": [stride[0], 1],
                       "pad": [pad[0], 0], "num_filter": V.shape[0],
                       "no_bias": True},
                      [inputs[0], vw])
        hw = emit("null", name + "_h_weight", {}, [])
        hb = emit("null", name + "_h_bias", {}, [])
        return emit("Convolution", name + "_h",
                    {"kernel": [1, kernel[1]], "stride": [1, stride[1]],
                     "pad": [0, pad[1]], "num_filter": num_filter},
                    [conv_v, hw, hb])

    return handler
