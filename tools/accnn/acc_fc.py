"""Truncated-SVD FullyConnected decomposition (parity:
tools/accnn/acc_fc.py): W (M,D) ≈ W2 (M,K) · W1 (K,D), bias on the
second layer."""
import numpy as np


def decompose_fc(W, b, K):
    U, D, Qt = np.linalg.svd(W, full_matrices=False)
    K = min(K, len(D))
    sqrt_d = np.sqrt(D[:K])
    W1 = (Qt[:K].T * sqrt_d).T          # (K, D)
    W2 = U[:, :K] * sqrt_d              # (M, K)
    return W1.astype(W.dtype), W2.astype(W.dtype), b


def make_fc_handler(ranks, arg_params, new_params, replaced=None):
    def handler(node, inputs, emit):
        name = node["name"]
        if name not in ranks:
            return None
        W = arg_params[name + "_weight"]
        b = arg_params.get(name + "_bias",
                           np.zeros(W.shape[0], dtype=W.dtype))
        K = int(ranks[name])
        W1, W2, b2 = decompose_fc(W, b, K)
        new_params[name + "_a_weight"] = W1
        new_params[name + "_b_weight"] = W2
        new_params[name + "_b_bias"] = b2
        if replaced is not None:
            replaced.add(name)
        w1 = emit("null", name + "_a_weight", {}, [])
        fc1 = emit("FullyConnected", name + "_a",
                   {"num_hidden": W1.shape[0], "no_bias": True},
                   [inputs[0], w1])
        w2 = emit("null", name + "_b_weight", {}, [])
        b2n = emit("null", name + "_b_bias", {}, [])
        return emit("FullyConnected", name + "_b",
                    {"num_hidden": W2.shape[0]}, [fc1, w2, b2n])

    return handler
