#!/usr/bin/env python
"""ACCNN driver (parity: tools/accnn/accnn.py): load a checkpoint,
pick per-layer ranks (DP under --speedup, or an explicit JSON config),
rewrite every spatial conv into its vertical/horizontal low-rank pair
and chosen FCs into truncated-SVD pairs, save the compressed
checkpoint.

  python accnn.py --model prefix --epoch N --data-shape 3,224,224 \
                  --speedup 2 --save-model prefix-acc
  python accnn.py ... --config ranks.json   # {"conv1": 12, "fc1": 64}
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

from acc_conv import make_conv_handler  # noqa: E402
from acc_fc import make_fc_handler  # noqa: E402
from rank_selection import select_ranks  # noqa: E402
from utils import load_model, rewrite_graph, save_model  # noqa: E402


def conv_layer_shapes(symbol, data_shape):
    """{conv name: (N, C, y, x, out_h, out_w)} for every spatial conv,
    via the symbol's own shape inference."""
    g = json.loads(symbol.tojson())
    internals = symbol.get_internals()
    out_names = internals.list_outputs()
    arg_shapes, out_shapes, _ = internals.infer_shape(
        data=(1,) + tuple(data_shape))
    arg_dict = dict(zip(internals.list_arguments(), arg_shapes))
    out_dict = dict(zip(out_names, out_shapes))
    shapes = {}
    for node in g["nodes"]:
        if node["op"] != "Convolution":
            continue
        name = node["name"]
        attrs = node["attrs"]
        kernel = json.loads(attrs["kernel"])
        if kernel[0] == 1 or kernel[1] == 1:
            continue
        if tuple(json.loads(attrs.get("dilate", "[1, 1]"))) != (1, 1) \
                or int(attrs.get("num_group", "1")) != 1:
            continue  # the V/H handler declines these; don't rank them
        wshape = arg_dict[name + "_weight"]
        oshape = out_dict[name + "_output"]
        shapes[name] = (wshape[0], wshape[1], wshape[2], wshape[3],
                        oshape[2], oshape[3])
    return shapes


def compress(symbol, arg_params, aux_params, ranks):
    new_params = dict(arg_params)
    conv_ranks = {n: k for n, k in ranks.items()
                  if n + "_weight" in arg_params
                  and arg_params[n + "_weight"].ndim == 4}
    fc_ranks = {n: k for n, k in ranks.items()
                if n + "_weight" in arg_params
                and arg_params[n + "_weight"].ndim == 2}
    replaced = set()
    handlers = {
        "Convolution": make_conv_handler(conv_ranks, arg_params, new_params,
                                         replaced),
        "FullyConnected": make_fc_handler(fc_ranks, arg_params, new_params,
                                          replaced),
    }
    new_sym = rewrite_graph(symbol, handlers)
    # drop only the originals the handlers actually swapped (a ranked
    # conv the handler declined — 1-dim kernel, dilated, grouped — keeps
    # its weights)
    for n in replaced:
        new_params.pop(n + "_weight", None)
        new_params.pop(n + "_bias", None)
    keep = set(new_sym.list_arguments())
    new_params = {k: v for k, v in new_params.items() if k in keep}
    return new_sym, new_params, dict(aux_params)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", required=True)
    ap.add_argument("--epoch", type=int, default=0)
    ap.add_argument("--data-shape", default="3,224,224")
    ap.add_argument("--speedup", type=float, default=2.0)
    ap.add_argument("--config", help="JSON {layer: rank} overriding the DP")
    ap.add_argument("--save-model", required=True)
    args = ap.parse_args()

    symbol, arg_params, aux_params = load_model(args.model, args.epoch)
    data_shape = tuple(int(v) for v in args.data_shape.split(","))
    if args.config:
        ranks = {k: int(v) for k, v in
                 json.load(open(args.config)).items()}
    else:
        shapes = conv_layer_shapes(symbol, data_shape)
        ranks = select_ranks(arg_params, shapes, args.speedup)
    print("ranks:", ranks)
    new_sym, new_args, new_aux = compress(symbol, arg_params, aux_params,
                                          ranks)
    before = sum(v.size for v in arg_params.values())
    after = sum(v.size for v in new_args.values())
    print(f"params {before} -> {after} ({after / before:.2%})")
    save_model(args.save_model, args.epoch, new_sym, new_args, new_aux)
    print(f"saved {args.save_model}-{args.epoch:04d}.params")


if __name__ == "__main__":
    main()
