"""Rank selection for ACCNN (parity: tools/accnn/rank_selection.py —
the reference allocates per-layer ranks by dynamic programming over the
SVD energy spectra under a target speedup).

Each k×k conv layer's decomposition cost scales ~ K·(C·y + N·x)·HW
against the original N·C·y·x·HW, so for a requested overall speedup S
the DP picks the rank vector maximizing retained spectral energy
subject to  sum(decomposed FLOPs) <= sum(original FLOPs)/S.
"""
import numpy as np

from acc_conv import matricize

GRID = 16  # rank candidates per layer (fractions of full rank)


def spectra(arg_params, layers):
    """Singular-value energy spectra per layer name (values-only SVD of
    the same matricization acc_conv decomposes)."""
    return {name: np.linalg.svd(matricize(arg_params[name + "_weight"]),
                                compute_uv=False) ** 2
            for name in layers}


def select_ranks(arg_params, conv_shapes, speedup):
    """conv_shapes: {name: (N, C, y, x, out_h, out_w)}.  Returns
    {name: rank} maximizing retained energy under the FLOPs budget."""
    layers = list(conv_shapes)
    energy = spectra(arg_params, layers)
    orig_flops, options = 0, {}
    for name in layers:
        n, c, y, x, oh, ow = conv_shapes[name]
        orig = n * c * y * x * oh * ow
        orig_flops += orig
        full = len(energy[name])
        opts = []
        for i in range(1, GRID + 1):
            k = max(1, int(round(full * i / GRID)))
            flops = k * (c * y + n * x) * oh * ow
            frac = float(energy[name][:k].sum() / energy[name].sum())
            opts.append((k, flops, frac))
        options[name] = opts
    budget = orig_flops / speedup

    # DP over layers with a discretized budget axis; each bin carries
    # the FULL choice vector so backtracking cannot drift.  Bin count
    # scales with depth: at a fixed 200 bins the per-layer minimum cost
    # of one bin would make any net deeper than 200 conv layers read as
    # infeasible, and ceil-quantization would eat the budget
    BINS = max(200, 8 * len(layers))
    scale = budget / BINS
    NEG = -1e18
    dp = np.full(BINS + 1, NEG)
    dp[0] = 0.0
    picks = [None] * (BINS + 1)
    picks[0] = []
    for name in layers:
        nxt = np.full(BINS + 1, NEG)
        nxt_picks = [None] * (BINS + 1)
        for k_i, (k, flops, frac) in enumerate(options[name]):
            cost = max(1, int(np.ceil(flops / scale)))
            if cost > BINS:
                continue
            gain = np.log(max(frac, 1e-12))
            for b in range(cost, BINS + 1):
                if dp[b - cost] <= NEG / 2:
                    continue
                cand = dp[b - cost] + gain
                if cand > nxt[b]:
                    nxt[b] = cand
                    nxt_picks[b] = picks[b - cost] + [k]
        dp, picks = nxt, nxt_picks
    best = int(np.argmax(dp))
    if dp[best] <= NEG / 2:
        raise ValueError(f"speedup {speedup}x infeasible even at rank 1")
    return dict(zip(layers, picks[best]))
