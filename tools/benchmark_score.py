#!/usr/bin/env python
"""Inference throughput sweep (parity: example/image-classification/
benchmark_score.py — the script behind every inference table in the
reference's perf.md).

Times jitted forward passes with device-resident inputs and a bytes-fetch
sync (tunneled backends can ack block_until_ready at dispatch), printing
img/s per (model, batch).

Usage:
  python tools/benchmark_score.py [--models resnet-50,inception-v3]
                                  [--batches 1,32] [--iters 30]
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np  # noqa: E402


def score(model, batch, iters, dtype_name):
    import jax
    import jax.numpy as jnp

    from mxnet_tpu import models
    from mxnet_tpu.executor import _build_graph_fn

    dtype = jnp.bfloat16 if dtype_name == "bf16" else jnp.float32
    image = (3, 299, 299) if model == "inception-v3" else (3, 224, 224)
    net = models.get_symbol(model, num_classes=1000)
    gfn = _build_graph_fn(net)
    rs = np.random.RandomState(0)
    arg_shapes, _, aux_shapes = net.infer_shape(
        data=(batch,) + image, softmax_label=(batch,))
    args = {n: jax.device_put(jnp.asarray(
                rs.uniform(-0.1, 0.1, s).astype(np.float32), dtype))
            for n, s in zip(net.list_arguments(), arg_shapes)}
    aux = {n: jax.device_put(jnp.asarray(
               rs.uniform(0.1, 1.0, s).astype(np.float32), dtype))
           for n, s in zip(net.list_auxiliary_states(), aux_shapes)}
    key = jax.random.PRNGKey(0)

    @jax.jit
    def fwd(args, aux):
        outs, _ = gfn(args, aux, key, False)
        return outs[0]

    out = fwd(args, aux)
    float(np.asarray(out).ravel()[0])  # compile + real sync
    tic = time.perf_counter()
    for _ in range(iters):
        out = fwd(args, aux)
    float(np.asarray(out).ravel()[0])
    dt = (time.perf_counter() - tic) / iters
    return batch / dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--models", default="alexnet,vgg,inception-bn,"
                                        "inception-v3,resnet-50,resnet-152")
    ap.add_argument("--batches", default="1,32")
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--dtype", default="bf16", choices=("bf16", "fp32"))
    args = ap.parse_args()

    for model in args.models.split(","):
        for b in (int(x) for x in args.batches.split(",")):
            try:
                r = score(model, b, args.iters, args.dtype)
                print(f"{model} batch={b}: {r:.1f} img/s", flush=True)
            except Exception as exc:  # noqa: BLE001 — sweep keeps going
                print(f"{model} batch={b}: FAILED {exc!r}", flush=True)


if __name__ == "__main__":
    main()
