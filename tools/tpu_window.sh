#!/bin/bash
# Round-4 second TPU window: the follow-up payloads after the headline
# bench landed (tools/tpu_watch.sh attempt 1, docs/measured/).  Runs each
# payload once when the backend answers, writing per-payload output files:
#
#   peak     - tools/probe_peak.py       (MXU + HBM roofline corners)
#   profile  - tools/probe_profile.py    (xprof op-level time split)
#   predict  - tools/bench_predict.py    (single-dispatch path, f32 + bf16)
#
# Usage: nohup setsid bash tools/tpu_window.sh >/tmp/tpu_window/driver.log 2>&1 &
OUT=/tmp/tpu_window
mkdir -p "$OUT"
cd /root/repo || exit 1
export PYTHONPATH=/root/.axon_site:/root/repo
export JAX_PLATFORMS=axon

attempt=0
while true; do
  attempt=$((attempt + 1))
  echo "[window] attempt $attempt $(date -u +%H:%M:%S)" >> "$OUT/driver.log"
  timeout 600 env BENCH_DEVICE_CHECK=1 BENCH_INIT_TIMEOUT_S=560 \
    python bench.py > "$OUT/probe" 2>&1
  if ! grep -q '"device_check"' "$OUT/probe"; then
    echo "[window] attempt $attempt: backend down" >> "$OUT/driver.log"
    sleep 120
    continue
  fi
  echo "[window] attempt $attempt: BACKEND UP" >> "$OUT/driver.log"

  [ -f "$OUT/peak.ok" ] || { timeout 900 python tools/probe_peak.py \
      > "$OUT/peak" 2>&1 && grep -q "hbm axpy" "$OUT/peak" \
      && touch "$OUT/peak.ok"; }
  [ -f "$OUT/predict.ok" ] || { { timeout 900 python tools/bench_predict.py \
      --iters 20 > "$OUT/predict" 2>&1 \
      && timeout 900 python tools/bench_predict.py --iters 20 \
         --dtype bfloat16 >> "$OUT/predict" 2>&1; } \
      && grep -q "predict_b32" "$OUT/predict" && touch "$OUT/predict.ok"; }
  [ -f "$OUT/profile.ok" ] || { timeout 1200 python tools/probe_profile.py \
      > "$OUT/profile" 2>&1 && grep -q "wrote" "$OUT/profile" \
      && touch "$OUT/profile.ok"; }
  [ -f "$OUT/variants.ok" ] || { timeout 1500 python \
      tools/probe_resnet_variants.py > "$OUT/variants" 2>&1 \
      && grep -q "nobn" "$OUT/variants" && touch "$OUT/variants.ok"; }
  [ -f "$OUT/tputests.ok" ] || { timeout 1800 env MXTPU_TPU_TESTS=1 \
      python -m pytest tests/test_tpu_consistency.py -q \
      > "$OUT/tputests" 2>&1 \
      && grep -qE "passed" "$OUT/tputests" && touch "$OUT/tputests.ok"; }

  if [ -f "$OUT/peak.ok" ] && [ -f "$OUT/predict.ok" ] \
     && [ -f "$OUT/profile.ok" ] && [ -f "$OUT/variants.ok" ] \
     && [ -f "$OUT/tputests.ok" ]; then
    echo "[window] attempt $attempt: ALL DONE" >> "$OUT/driver.log"
    exit 0
  fi
  echo "[window] attempt $attempt: partial, retrying" >> "$OUT/driver.log"
  sleep 120
done
