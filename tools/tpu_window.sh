#!/bin/bash
# Round-5 TPU window watcher.  Polls the backend; when it answers, runs the
# round-5 payload set once each (correctness before perf, per VERDICT r04
# item 5), writing per-payload output files under /tmp/tpu_window:
#
#   tputests - MXTPU_TPU_TESTS=1 pytest tpu_consistency + bf16 + flash-attn
#   bench    - full bench.py capture (headline + extras) on the live chip
#   peak     - tools/probe_peak.py        (MXU + HBM roofline corners)
#   profile  - tools/probe_profile.py     (xprof op-level time split)
#   variants - tools/probe_resnet_variants.py (BN-cost A/B)
#   predict  - tools/bench_predict.py f32+bf16, overlap off/on A/B
#   lmmfu    - tools/probe_lm_mfu.py      (compute-bound LM MFU headline)
#
# Usage: nohup setsid bash tools/tpu_window.sh >/tmp/tpu_window/driver.log 2>&1 &
OUT=/tmp/tpu_window
mkdir -p "$OUT"
cd /root/repo || exit 1
export PYTHONPATH=/root/.axon_site:/root/repo
export JAX_PLATFORMS=axon

stage_one() {  # $1 = payload name, $2 = destination filename
  cp -f "$OUT/$1" "/root/repo/docs/measured/$2" \
    && git -C /root/repo add "docs/measured/$2" \
    || echo "[window] stage $1 -> $2 FAILED" >> "$OUT/driver.log"
}

stage_all() {
  # successful payload outputs land in the repo's artifact tree AND the
  # git index: if the round ends moments later, even a commit -a style
  # end-of-round snapshot captures them.  Idempotent — re-run each loop
  # so a transient cp/git failure heals on the next pass.
  for n in tputests trainchk peak profile variants predict lmmfu gap \
           score; do
    [ -f "$OUT/$n.ok" ] && stage_one "$n" "${n}_r05.txt"
  done
  # names match their consumers: bench.py's artifact glob wants
  # bench_r*_tpu*.json (the one JSON line, not raw stdout), and
  # bench_models.py documents bench_models_r*.txt
  [ -f "$OUT/modelbench.ok" ] && stage_one modelbench bench_models_r05.txt
  if [ -f "$OUT/bench.ok" ]; then
    grep '"resnet50_train' "$OUT/bench" | tail -1 \
      > /root/repo/docs/measured/bench_r05_tpu_v5e.json \
      && git -C /root/repo add docs/measured/bench_r05_tpu_v5e.json \
      || echo "[window] stage bench FAILED" >> "$OUT/driver.log"
  fi
}

# Stand down before the driver's own end-of-round bench: a real TPU
# chip is single-process, and the watcher holding the backend when the
# driver's bench.py initializes would fail the round's one
# driver-captured measurement.  MXTPU_WINDOW_CUTOFF is epoch seconds;
# falls back to the $OUT/cutoff file so keepalive relaunches (whose
# environment may predate the setting) inherit it.
CUTOFF="${MXTPU_WINDOW_CUTOFF:-$(cat "$OUT/cutoff" 2>/dev/null || echo 0)}"
case "$CUTOFF" in *[!0-9]*|"") CUTOFF=0 ;; esac

past_cutoff() {
  [ "$CUTOFF" -gt 0 ] && [ "$(date -u +%s)" -ge "$CUTOFF" ]
}

attempt=0
while true; do
  if past_cutoff; then
    stage_all
    echo "[window] cutoff reached; standing down for the driver bench" \
      >> "$OUT/driver.log"
    touch "$OUT/alldone"  # keepalive stands down too
    exit 0
  fi
  attempt=$((attempt + 1))
  echo "[window] attempt $attempt $(date -u +%H:%M:%S)" >> "$OUT/driver.log"
  timeout 600 env BENCH_DEVICE_CHECK=1 BENCH_INIT_TIMEOUT_S=560 \
    python bench.py > "$OUT/probe" 2>&1
  if ! grep -q '"device_check"' "$OUT/probe"; then
    echo "[window] attempt $attempt: backend down" >> "$OUT/driver.log"
    sleep 120
    continue
  fi
  echo "[window] attempt $attempt: BACKEND UP" >> "$OUT/driver.log"

  # 1. numerics on silicon — correctness outranks perf
  [ -f "$OUT/tputests.ok" ] || past_cutoff || { timeout 2400 env MXTPU_TPU_TESTS=1 \
      python -m pytest tests/test_tpu_consistency.py \
      tests/test_bf16_consistency.py tests/test_flash_attention.py -q \
      > "$OUT/tputests" 2>&1 \
      && grep -qE "passed" "$OUT/tputests" \
      && ! grep -qE "failed|error" "$OUT/tputests" \
      && touch "$OUT/tputests.ok"; }
  # 1b. end-to-end training convergence on the chip (fast, <3 min)
  [ -f "$OUT/trainchk.ok" ] || past_cutoff || { [ -f tools/tpu_train_check.py ] \
      && timeout 900 python tools/tpu_train_check.py > "$OUT/trainchk" 2>&1 \
      && grep -q "TRAIN-ON-DEVICE OK" "$OUT/trainchk" \
      && touch "$OUT/trainchk.ok"; }
  # 2. the headline bench, full extras — the round's own clean capture
  [ -f "$OUT/bench.ok" ] || past_cutoff || { timeout 1500 env BENCH_INIT_TIMEOUT_S=560 \
      python bench.py > "$OUT/bench" 2>&1 \
      && grep -q '"resnet50_train' "$OUT/bench" \
      && ! grep -q '"error"' "$OUT/bench" && touch "$OUT/bench.ok"; }
  # 3. roofline probes
  [ -f "$OUT/peak.ok" ] || past_cutoff || { timeout 900 python tools/probe_peak.py \
      > "$OUT/peak" 2>&1 && grep -q "hbm axpy" "$OUT/peak" \
      && touch "$OUT/peak.ok"; }
  [ -f "$OUT/profile.ok" ] || past_cutoff || { timeout 1200 python tools/probe_profile.py \
      > "$OUT/profile" 2>&1 && grep -q "wrote" "$OUT/profile" \
      && touch "$OUT/profile.ok"; }
  [ -f "$OUT/variants.ok" ] || past_cutoff || { timeout 1500 python \
      tools/probe_resnet_variants.py > "$OUT/variants" 2>&1 \
      && grep -q "nobn" "$OUT/variants" && touch "$OUT/variants.ok"; }
  # 4. predictor path, f32 + bf16 (bench_predict runs its own overlap A/B
  #    when the predictor supports it)
  [ -f "$OUT/predict.ok" ] || past_cutoff || { { timeout 900 python tools/bench_predict.py \
      --iters 20 > "$OUT/predict" 2>&1 \
      && timeout 900 python tools/bench_predict.py --iters 20 \
         --dtype bfloat16 >> "$OUT/predict" 2>&1; } \
      && grep -q "predict_b32" "$OUT/predict" && touch "$OUT/predict.ok"; }
  # 5. compute-bound LM MFU headline (probe lands later this round)
  [ -f "$OUT/lmmfu.ok" ] || past_cutoff || { [ -f tools/probe_lm_mfu.py ] \
      && timeout 1800 python tools/probe_lm_mfu.py > "$OUT/lmmfu" 2>&1 \
      && grep -q "mfu" "$OUT/lmmfu" && touch "$OUT/lmmfu.ok"; }
  # 6. framework-vs-raw gap decomposition (host vs device vs ceiling)
  [ -f "$OUT/gap.ok" ] || past_cutoff || { [ -f tools/probe_gap.py ] \
      && timeout 1500 python tools/probe_gap.py > "$OUT/gap" 2>&1 \
      && grep -q "framework b" "$OUT/gap" && touch "$OUT/gap.ok"; }
  # 7. model-family re-capture: every perf.md figure gets a raw artifact
  [ -f "$OUT/modelbench.ok" ] || past_cutoff || { [ -f tools/bench_models.py ] \
      && timeout 2400 python tools/bench_models.py > "$OUT/modelbench" 2>&1 \
      && grep -q "tokens_per_sec" "$OUT/modelbench" \
      && ! grep -q "FAILED" "$OUT/modelbench" \
      && touch "$OUT/modelbench.ok"; }
  # 8. inference sweep behind the published 7-model table
  [ -f "$OUT/score.ok" ] || past_cutoff || { timeout 2400 python \
      tools/benchmark_score.py --batches 32 > "$OUT/score" 2>&1 \
      && grep -qi "resnet-152" "$OUT/score" \
      && ! grep -qiE "FAILED|error" "$OUT/score" \
      && touch "$OUT/score.ok"; }

  if [ -f "$OUT/tputests.ok" ] && [ -f "$OUT/bench.ok" ] \
     && [ -f "$OUT/peak.ok" ] && [ -f "$OUT/profile.ok" ] \
     && [ -f "$OUT/variants.ok" ] && [ -f "$OUT/predict.ok" ] \
     && { [ ! -f tools/probe_lm_mfu.py ] || [ -f "$OUT/lmmfu.ok" ]; } \
     && { [ ! -f tools/probe_gap.py ] || [ -f "$OUT/gap.ok" ]; } \
     && { [ ! -f tools/bench_models.py ] || [ -f "$OUT/modelbench.ok" ]; } \
     && { [ ! -f tools/tpu_train_check.py ] || [ -f "$OUT/trainchk.ok" ]; } \
     && [ -f "$OUT/score.ok" ]; then
    stage_all
    echo "[window] attempt $attempt: ALL DONE" >> "$OUT/driver.log"
    touch "$OUT/alldone"  # tells tpu_keepalive.sh to stand down
    exit 0
  fi
  stage_all
  echo "[window] attempt $attempt: partial, retrying" >> "$OUT/driver.log"
  sleep 120
done
