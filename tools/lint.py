#!/usr/bin/env python
"""Invariant lint gate — CLI over mxnet_tpu/analysis (docs/static_analysis.md).

Runs the static rule families (host-sync escape analysis, trace-purity,
lock-order/shared-state, env-knob drift) over the package source and
exits nonzero on any unsuppressed violation, so it slots straight into
pre-commit/CI without pytest:

    python tools/lint.py                      # full suite, text report
    python tools/lint.py --rules host-sync,env-docs
    python tools/lint.py --json               # structured findings
    python tools/lint.py --write-baseline lint_baseline.json
    python tools/lint.py --baseline lint_baseline.json   # only NEW findings

Exit codes: 0 clean, 1 violations, 2 internal/usage error.

The analysis package is pure stdlib; this script loads it standalone so
linting never pays (or depends on) the jax import.
"""
import argparse
import importlib.util
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_analysis():
    """Load mxnet_tpu.analysis WITHOUT executing mxnet_tpu/__init__.py
    (which imports jax).  Registering the submodule spec directly makes
    its relative imports resolve against itself."""
    name = "mxnet_tpu.analysis"
    if name in sys.modules:
        return sys.modules[name]
    if "mxnet_tpu" not in sys.modules:
        # synthetic parent so the submodule import machinery resolves
        # without executing mxnet_tpu/__init__.py (no __init__ exec =
        # no jax import). Fine for this short-lived CLI process only.
        import types
        parent = types.ModuleType("mxnet_tpu")
        parent.__path__ = [os.path.join(ROOT, "mxnet_tpu")]
        sys.modules["mxnet_tpu"] = parent
    pkg_dir = os.path.join(ROOT, "mxnet_tpu", "analysis")
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    try:
        spec.loader.exec_module(mod)
    except Exception:
        del sys.modules[name]
        raise
    return mod


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="tools/lint.py",
        description="static invariant lint over mxnet_tpu/")
    ap.add_argument("--rules", default="",
                    help="comma list: host-sync,trace-purity,locks,env-docs "
                         "(default: all)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the structured finding list as JSON")
    ap.add_argument("--baseline", default="",
                    help="suppress findings whose keys are in this baseline "
                         "file (adopt-then-ratchet mode)")
    ap.add_argument("--write-baseline", default="",
                    help="write current unsuppressed finding keys to this "
                         "file and exit 0")
    ap.add_argument("--allowlist", default=None,
                    help="override the allowlist path "
                         "(default tools/lint_allowlist.json)")
    ap.add_argument("--root", default=ROOT, help=argparse.SUPPRESS)
    ap.add_argument("-v", "--verbose", action="store_true",
                    help="show suppressed findings and full call chains")
    args = ap.parse_args(argv)

    try:
        analysis = _load_analysis()
    except Exception as e:  # noqa: BLE001 — loader problems are exit-2
        print(f"lint: cannot load mxnet_tpu/analysis: {e!r}", file=sys.stderr)
        return 2
    rules = [r.strip() for r in args.rules.split(",") if r.strip()] or None
    try:
        findings, _, _ = analysis.run_all(
            root=args.root, rules=rules, allowlist_path=args.allowlist)
    except ValueError as e:
        print(f"lint: {e}", file=sys.stderr)
        return 2

    if args.baseline:
        try:
            with open(args.baseline, "r", encoding="utf-8") as fh:
                base = set(json.load(fh).get("keys", []))
        except (OSError, ValueError) as e:
            print(f"lint: cannot read baseline {args.baseline}: {e}",
                  file=sys.stderr)
            return 2
        for f in findings:
            if not f.suppressed and f.key in base:
                f.suppressed_by = f"baseline:{args.baseline}"

    active = [f for f in findings if not f.suppressed]
    if args.write_baseline:
        doc = {"keys": sorted({f.key for f in active})}
        with open(args.write_baseline, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, indent=2)
        print(f"lint: wrote {len(doc['keys'])} baseline key(s) to "
              f"{args.write_baseline}")
        return 0

    if args.as_json:
        print(analysis.render_json(
            findings, meta={"rules": rules or sorted(analysis.RULES)}))
    else:
        print(analysis.render_text(findings, verbose=args.verbose,
                                   show_suppressed=args.verbose))
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
