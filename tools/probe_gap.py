"""Probe: decompose the framework-vs-raw-JAX ResNet-50 gap on the chip.

Round 4 measured framework b32 = 2361 img/s vs a raw-JAX NHWC probe at
2610 (docs/measured/probe_nhwc_r04.txt) — ~10% overhead that is by
construction not roofline.  This probe splits it:

  device  — framework step time with the device saturated (the bench
            discipline: async steps, one trailing fetch barrier)
  host    — wall time of step() WITHOUT waiting for the device (pure
            python/dispatch cost per call: pytree flatten, _shard_batch,
            jit-cache lookup, PjRt enqueue)
  raw     — the hand-written NHWC train step from tools/probe_nhwc.py,
            same batch, same discipline (the honest ceiling)

If device ~= raw, the remaining delta is host-side and amortizes with
batch size; if device > raw, the compiled step itself is heavier
(layout/cast/fusion loss) and the HLO needs attention.

Run on the bench chip:  python tools/probe_gap.py [batch ...]
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def framework(batch, iters=40):
    import jax
    import jax.numpy as jnp

    import mxnet_tpu  # noqa: F401
    from mxnet_tpu import models
    from mxnet_tpu.trainer import FusedTrainer

    net = models.get_symbol("resnet-50", num_classes=1000)
    tr = FusedTrainer(net, optimizer="sgd",
                      optimizer_params={"lr": 0.1, "momentum": 0.9,
                                        "rescale_grad": 1.0 / batch},
                      dtype=jnp.bfloat16)
    tr.init(data=(batch, 3, 224, 224))
    rs = np.random.RandomState(0)
    staged = {"data": jax.device_put(
        rs.uniform(0, 1, (batch, 3, 224, 224)).astype(np.float32)),
        "softmax_label": jax.device_put(
            rs.randint(0, 1000, batch).astype(np.float32))}
    pname = sorted(tr.params)[0]

    def barrier():
        return float(np.asarray(tr.params[pname]).ravel()[0])

    for _ in range(6):
        tr.step(**staged)
    barrier()

    tic = time.perf_counter()
    for _ in range(iters):
        tr.step(**staged)
    barrier()
    dev_dt = (time.perf_counter() - tic) / iters

    # host-only: the same calls, but timed WITHOUT the trailing barrier —
    # per-call wall time is the python+dispatch cost while the device
    # queue stays ahead (valid because dev_dt >> host_dt)
    tic = time.perf_counter()
    for _ in range(iters):
        tr.step(**staged)
    host_dt = (time.perf_counter() - tic) / iters
    barrier()
    note = ""
    if host_dt >= dev_dt:
        # the no-barrier loop came out SLOWER than the barriered one:
        # the split's premise (dev >> host) failed this window — the
        # call is host/transport-bound and the % is not a clean split
        note = "  [host-bound window: split premise failed]"
    print(f"framework b{batch}: {batch / dev_dt:8.1f} img/s   "
          f"step {dev_dt * 1e3:6.2f} ms   host-side {host_dt * 1e3:5.2f} ms "
          f"({host_dt / dev_dt * 100:4.1f}%){note}", flush=True)

    # the fix the host-side split motivates: k steps per dispatch
    # (FusedTrainer.step_multi) pays the call cost once per k steps
    k = 8
    stacked = {k_: jnp.stack([v] * k) for k_, v in staged.items()}
    tr.step_multi(**stacked)  # compile
    barrier()
    calls = max(iters // k, 2)
    tic = time.perf_counter()
    for _ in range(calls):
        tr.step_multi(**stacked)
    barrier()
    multi_dt = (time.perf_counter() - tic) / (calls * k)
    print(f"framework b{batch} multi(k={k}): {batch / multi_dt:8.1f} img/s   "
          f"step {multi_dt * 1e3:6.2f} ms", flush=True)


if __name__ == "__main__":
    import jax

    print("devices:", jax.devices(), flush=True)
    batches = [int(a) for a in sys.argv[1:]] or [32, 128]
    for b in batches:
        framework(b)
    # the raw ceiling, same session/same chip state (tools/ is not a
    # package: load the probe module by path)
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "probe_nhwc", os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "probe_nhwc.py"))
    probe_nhwc = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(probe_nhwc)
    for b in batches:
        probe_nhwc.run("NHWC", b)
