"""Probe: ResNet-50 train-step throughput, NCHW vs NHWC lowering (pure JAX).

Decides whether a channels-last executor pass is worth building: identical
topology/params, only conv dimension_numbers + stat axes differ.  Run on the
real chip:  python tools/probe_nhwc.py [batch ...]
"""
import os
import sys
import time
from functools import partial

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

# ResNet-50 (v1) stage spec: (n_blocks, channels)
STAGES = [(3, 256), (4, 512), (6, 1024), (3, 2048)]


def conv(x, w, stride, layout):
    if layout == "NHWC":
        dn = ("NHWC", "HWIO", "NHWC")
    else:
        dn = ("NCHW", "OIHW", "NCHW")
    kh = w.shape[0] if layout == "NHWC" else w.shape[2]
    pad = (kh - 1) // 2
    return jax.lax.conv_general_dilated(
        x, w, (stride, stride), [(pad, pad), (pad, pad)],
        dimension_numbers=jax.lax.conv_dimension_numbers(x.shape, w.shape, dn))


def bn(x, gamma, beta, layout):
    axes = (0, 1, 2) if layout == "NHWC" else (0, 2, 3)
    shape = (1, 1, 1, -1) if layout == "NHWC" else (1, -1, 1, 1)
    x32 = x.astype(jnp.float32)
    n = x.size // x.shape[3 if layout == "NHWC" else 1]
    mean = jnp.sum(x32, axes) / n
    var = jnp.maximum(jnp.sum(jnp.square(x32), axes) / n - jnp.square(mean), 0.0)
    out = (x32 - mean.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape) + 1e-3)
    return (out * gamma.reshape(shape) + beta.reshape(shape)).astype(x.dtype)


def make_params(layout, rng):
    def w(cin, cout, k):
        arr = rng.normal(0, 0.05, (cout, cin, k, k)).astype(np.float32)
        if layout == "NHWC":
            arr = arr.transpose(2, 3, 1, 0)  # OIHW -> HWIO
        return jnp.asarray(arr, jnp.bfloat16)

    params = {"stem": w(3, 64, 7), "stem_g": jnp.ones(64), "stem_b": jnp.zeros(64)}
    cin = 64
    for si, (blocks, cout) in enumerate(STAGES):
        mid = cout // 4
        for bi in range(blocks):
            p = f"s{si}b{bi}"
            params[p + "c1"] = w(cin, mid, 1)
            params[p + "c2"] = w(mid, mid, 3)
            params[p + "c3"] = w(mid, cout, 1)
            if cin != cout:
                params[p + "proj"] = w(cin, cout, 1)
            for j, c in (("1", mid), ("2", mid), ("3", cout)):
                params[p + "g" + j] = jnp.ones(c)
                params[p + "b" + j] = jnp.zeros(c)
            cin = cout
    params["fc"] = jnp.asarray(rng.normal(0, 0.01, (2048, 1000)), jnp.bfloat16)
    return params


def forward(params, x, layout):
    x = conv(x, params["stem"], 2, layout)
    x = jax.nn.relu(bn(x, params["stem_g"], params["stem_b"], layout))
    window = (1, 3, 3, 1) if layout == "NHWC" else (1, 1, 3, 3)
    strides = (1, 2, 2, 1) if layout == "NHWC" else (1, 1, 2, 2)
    pads = [(0, 0), (1, 1), (1, 1), (0, 0)] if layout == "NHWC" else [(0, 0), (0, 0), (1, 1), (1, 1)]
    x = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, window, strides, pads)
    cin = 64
    for si, (blocks, cout) in enumerate(STAGES):
        for bi in range(blocks):
            p = f"s{si}b{bi}"
            stride = 2 if (bi == 0 and si > 0) else 1
            sc = x
            if cin != cout:
                sc = conv(x, params[p + "proj"], stride, layout)
            h = jax.nn.relu(bn(conv(x, params[p + "c1"], 1, layout),
                               params[p + "g1"], params[p + "b1"], layout))
            h = jax.nn.relu(bn(conv(h, params[p + "c2"], stride, layout),
                               params[p + "g2"], params[p + "b2"], layout))
            h = bn(conv(h, params[p + "c3"], 1, layout),
                   params[p + "g3"], params[p + "b3"], layout)
            x = jax.nn.relu(h + sc)
            cin = cout
    axes = (1, 2) if layout == "NHWC" else (2, 3)
    x = jnp.mean(x.astype(jnp.float32), axis=axes)
    return x.astype(jnp.bfloat16) @ params["fc"]


def loss_fn(params, x, y, layout):
    logits = forward(params, x, layout).astype(jnp.float32)
    return jnp.mean(-jax.nn.log_softmax(logits)[jnp.arange(x.shape[0]), y])


@partial(jax.jit, static_argnames=("layout",), donate_argnums=(0, 1))
def train_step(params, mom, x, y, layout):
    loss, grads = jax.value_and_grad(loss_fn)(params, x, y, layout)
    new_p, new_m = {}, {}
    for k, g in grads.items():
        m = mom[k] * 0.9 + g.astype(jnp.float32)
        new_m[k] = m
        new_p[k] = (params[k].astype(jnp.float32) - 0.1 * m).astype(params[k].dtype)
    return new_p, new_m, loss


def run(layout, batch, iters=30):
    rng = np.random.RandomState(0)
    params = make_params(layout, rng)
    mom = {k: jnp.zeros(v.shape, jnp.float32) for k, v in params.items()}
    shape = (batch, 224, 224, 3) if layout == "NHWC" else (batch, 3, 224, 224)
    x = jnp.asarray(rng.uniform(0, 1, shape), jnp.bfloat16)
    y = jnp.asarray(rng.randint(0, 1000, batch), jnp.int32)
    for _ in range(5):
        params, mom, loss = train_step(params, mom, x, y, layout)
    _ = float(np.asarray(loss))
    tic = time.perf_counter()
    for _ in range(iters):
        params, mom, loss = train_step(params, mom, x, y, layout)
    _ = float(np.asarray(loss))  # fetch real bytes: trustworthy barrier
    dt = time.perf_counter() - tic
    img_s = batch * iters / dt
    mfu = img_s * 3 * 4.089e9 / 197e12
    print(f"{layout} b{batch}: {img_s:8.1f} img/s   mfu={mfu:.3f}", flush=True)


if __name__ == "__main__":
    print("devices:", jax.devices(), flush=True)
    batches = [int(a) for a in sys.argv[1:]] or [128]
    for b in batches:
        for layout in ("NHWC", "NCHW"):
            run(layout, b)
