#!/bin/bash
# Detached TPU chip-watch loop (VERDICT r3 item #1).
#
# The axon backend was unavailable for all of round 3 (init hangs or errors
# after ~25 min).  This loop probes the backend on a cadence and, the moment
# init succeeds, runs the NHWC layout probe and the full bench (b32 headline
# + inference + b256 extras), writing per-attempt output files so success is
# greppable from the attempt file rather than an accumulated log.
#
# Usage:  nohup setsid bash tools/tpu_watch.sh >/tmp/tpu_watch/driver.log 2>&1 &
OUT=/tmp/tpu_watch
mkdir -p "$OUT"
cd /root/repo || exit 1
export PYTHONPATH=/root/.axon_site:/root/repo
export JAX_PLATFORMS=axon

attempt=0
while true; do
  attempt=$((attempt + 1))
  f="$OUT/attempt_$(printf '%03d' "$attempt")"
  echo "[watch] attempt $attempt $(date -u +%H:%M:%S)" >> "$OUT/driver.log"

  # 1. cheap probe: can the backend produce a device at all?
  timeout 600 env BENCH_DEVICE_CHECK=1 BENCH_INIT_TIMEOUT_S=560 \
    python bench.py > "$f.probe" 2>&1
  if ! grep -q '"device_check"' "$f.probe"; then
    echo "[watch] attempt $attempt: backend down" >> "$OUT/driver.log"
    sleep 120
    continue
  fi
  echo "[watch] attempt $attempt: BACKEND UP" >> "$OUT/driver.log"

  # 2. layout probe (NHWC vs NCHW raw-jax ceiling) — tells us what the
  #    executor pass should be able to reach
  timeout 900 python tools/probe_nhwc.py 32 128 256 > "$f.nhwc" 2>&1

  # 3. the real bench: b32 headline + inference + b256 extras
  timeout 1200 env BENCH_INIT_TIMEOUT_S=560 BENCH_EXTRAS_TIMEOUT_S=600 \
    python bench.py > "$f.bench" 2>&1

  if grep -q '"resnet50_train_imgs_per_sec_per_chip"' "$f.bench" \
     && ! grep -q '"error"' "$f.bench"; then
    cp "$f.bench" "$OUT/SUCCESS.bench"
    cp "$f.nhwc" "$OUT/SUCCESS.nhwc" 2>/dev/null
    # predict-ABI throughput (VERDICT r3 #8) — best-effort extra
    timeout 900 python tools/bench_predict.py > "$f.predict" 2>&1 \
      && cp "$f.predict" "$OUT/SUCCESS.predict"
    echo "[watch] attempt $attempt: SUCCESS" >> "$OUT/driver.log"
    exit 0
  fi
  echo "[watch] attempt $attempt: bench incomplete, retrying" >> "$OUT/driver.log"
  sleep 120
done
