#!/bin/bash
# Keepalive for tools/tpu_window.sh: relaunches the watcher if it dies
# (observed once this round during a restart shuffle), stops for good
# once the watcher reports ALL DONE.
#
# Usage: nohup setsid bash tools/tpu_keepalive.sh >/tmp/tpu_window/keepalive.log 2>&1 &
OUT=/tmp/tpu_window
mkdir -p "$OUT"
cd /root/repo || exit 1
while true; do
  if [ -f "$OUT/alldone" ]; then
    echo "[keepalive] alldone marker present; exiting $(date -u +%H:%M:%S)"
    exit 0
  fi
  if ! pgrep -f "tools/tpu_window.sh" > /dev/null; then
    echo "[keepalive] watcher not running; relaunching $(date -u +%H:%M:%S)"
    setsid bash /root/repo/tools/tpu_window.sh \
      >> "$OUT/driver.log" 2>&1 < /dev/null &
  fi
  sleep 300
done
