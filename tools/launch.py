#!/usr/bin/env python
"""Cluster launcher for distributed training.

Parity: tools/launch.py (reference) + the dmlc-core tracker: spawn
``-n`` worker and ``-s`` server processes running the same command, with
roles assigned via environment variables (DMLC_ROLE et al.; server
processes detect the role at ``import mxnet_tpu`` and serve — see
mxnet_tpu/kvstore_server.py).

Launchers:
- ``local``  (default): N workers + S servers as subprocesses on this
  host — the mode the reference's nightly dist tests use
  (tests/nightly/test_all.sh:37 ``launch.py -n 4 --launcher local``).
- ``ssh``: one process per host from ``-H hostfile`` (round-robin),
  sharing the same env contract over ``ssh -q``.  Limitation: server
  ports are probed on the launcher, not the remote hosts — pick hosts
  with those ports free (a bind failure surfaces as workers timing out
  after their 120s connect-retry window).
Other reference launchers (mpi/sge/yarn) map to cluster schedulers that
do not exist for TPU pods — there, use ``--launcher pod`` which simply
execs the command once per host under `jax.distributed` coordinates
(GKE/xmanager-style schedulers start one process per host already).

On TPU pods the sync data-parallel path needs NO server processes
(gradients ride ICI/DCN collectives inside the step); ``-s`` is for the
parameter-server semantics (dist_async / server-side optimizer).
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _role_env(base, role, rank, args, servers):
    env = dict(base)
    env.update({
        "MXTPU_ROLE": role,
        "MXTPU_NUM_WORKERS": str(args.num_workers),
        "MXTPU_NUM_SERVERS": str(args.num_servers),
        "MXTPU_PS_SERVERS": ",".join(servers),
        # DMLC aliases so reference scripts reading these keep working
        "DMLC_ROLE": role,
        "DMLC_NUM_WORKER": str(args.num_workers),
        "DMLC_NUM_SERVER": str(args.num_servers),
    })
    if role == "server":
        env["MXTPU_SERVER_RANK"] = str(rank)
    else:
        env["MXTPU_RANK"] = str(rank)
        env["DMLC_RANK"] = str(rank)
    return env


def launch_local(args, command):
    servers = [f"127.0.0.1:{p}" for p in _free_ports(args.num_servers)]
    procs = []
    try:
        for i in range(args.num_servers):
            procs.append(subprocess.Popen(
                command, env=_role_env(os.environ, "server", i, args, servers)))
        workers = []
        for i in range(args.num_workers):
            p = subprocess.Popen(
                command, env=_role_env(os.environ, "worker", i, args, servers))
            procs.append(p)
            workers.append(p)
        rc = 0
        for p in workers:
            rc = p.wait() or rc
        for p in procs:
            if p.poll() is None:
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    p.kill()
        return rc
    except BaseException:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        raise


def launch_ssh(args, command):
    if not args.hostfile:
        raise SystemExit("--launcher ssh requires -H/--hostfile")
    with open(args.hostfile) as f:
        hosts = [h.strip() for h in f if h.strip() and not h.startswith("#")]
    ports = _free_ports(args.num_servers)
    # servers round-robin over hosts; workers likewise
    servers = [f"{hosts[i % len(hosts)]}:{ports[i]}" for i in range(args.num_servers)]
    procs = []
    cmd_str = " ".join(command)

    def remote(host, env):
        env_str = " ".join(f"{k}={v}" for k, v in env.items()
                           if k.startswith(("MXTPU_", "DMLC_")))
        return subprocess.Popen(
            ["ssh", "-q", "-o", "StrictHostKeyChecking=no", host,
             f"cd {os.getcwd()} && env {env_str} {cmd_str}"])

    for i in range(args.num_servers):
        procs.append(remote(hosts[i % len(hosts)],
                            _role_env({}, "server", i, args, servers)))
    rc = 0
    workers = []
    for i in range(args.num_workers):
        p = remote(hosts[i % len(hosts)], _role_env({}, "worker", i, args, servers))
        procs.append(p)
        workers.append(p)
    for p in workers:
        rc = p.wait() or rc
    for p in procs:
        if p.poll() is None:
            p.terminate()
    return rc


def launch_pod(args, command):
    """One-process-per-host schedulers (TPU pods): just exec with worker
    env; jax.distributed coordinates (parallel/dist.py)."""
    env = dict(os.environ)
    env.setdefault("MXTPU_NUM_WORKERS", str(args.num_workers))
    os.execvpe(command[0], command, env)


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("-s", "--num-servers", type=int, default=0)
    ap.add_argument("--launcher", default="local",
                    choices=["local", "ssh", "pod"])
    ap.add_argument("-H", "--hostfile", default=None)
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args()
    command = [c for c in args.command if c != "--"]
    if not command:
        raise SystemExit("no command given")
    if args.launcher == "local":
        sys.exit(launch_local(args, command))
    elif args.launcher == "ssh":
        sys.exit(launch_ssh(args, command))
    else:
        launch_pod(args, command)


if __name__ == "__main__":
    main()
