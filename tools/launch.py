#!/usr/bin/env python
"""Cluster launcher for distributed training.

Parity: tools/launch.py (reference) + the dmlc-core tracker: spawn
``-n`` worker and ``-s`` server processes running the same command, with
roles assigned via environment variables (DMLC_ROLE et al.; server
processes detect the role at ``import mxnet_tpu`` and serve — see
mxnet_tpu/kvstore_server.py).

Launchers:
- ``local``  (default): N workers + S servers as subprocesses on this
  host — the mode the reference's nightly dist tests use
  (tests/nightly/test_all.sh:37 ``launch.py -n 4 --launcher local``).
  ``--max-restarts R`` auto-restarts a crashed worker up to R times
  with ``MXTPU_KV_RECOVERY=1`` (the kvstore_dist.h:35-39 recovery
  contract: skip re-init/re-barrier, the servers still hold the model),
  logging the rank and exit code of every death.
- ``ssh``: one process per host from ``-H hostfile`` (round-robin),
  sharing the same env contract over ``ssh -q``.  Server ports are
  probed ON the remote host that will bind them (a port free on the
  launcher is not necessarily free there — the old launcher-side probe
  surfaced remote bind failures as workers timing out 120s later).
- ``pod``: one-process-per-host schedulers (TPU pods) — exec the
  command once with worker env; jax.distributed coordinates
  (parallel/dist.py).
- ``elastic`` (docs/multihost.md): the collective dist_sync mode with
  generation-epoch fault tolerance.  The launcher runs the membership
  coordinator (mxnet_tpu.parallel.coordinator) and relaunches the
  training world one **generation** at a time: a worker death shrinks
  the next generation to the survivors (who left at a checkpoint
  boundary with exit code 43 — EXIT_HOST_LOST), a crashed rank with
  restart budget rejoins at a later generation and the world
  re-expands.  Workers resume from the survival-layer checkpoint
  (MXTPU_CKPT_DIR) and re-bind on the new mesh shape.

On TPU pods the sync data-parallel path needs NO server processes
(gradients ride ICI/DCN collectives inside the step); ``-s`` is for the
parameter-server semantics (dist_async / server-side optimizer).
"""
from __future__ import annotations

import argparse
import json
import logging
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

# keep in sync with mxnet_tpu.parallel.dist.EXIT_HOST_LOST (this script
# must stay importable without the package on the PYTHONPATH)
EXIT_HOST_LOST = 43

logging.basicConfig(level=logging.INFO,
                    format="%(asctime)s launch.py %(message)s")
_log = logging.getLogger("launch")


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


_REMOTE_PROBE = (
    "import socket\n"
    "ss=[socket.socket() for _ in range({n})]\n"
    "[s.bind(('0.0.0.0',0)) for s in ss]\n"
    "print(','.join(str(s.getsockname()[1]) for s in ss))\n"
    "[s.close() for s in ss]\n"
)


def _remote_free_ports(host, n):
    """Probe ``n`` free ports ON ``host`` (the machine that will bind
    them) — a launcher-side probe only proves the port is free HERE."""
    if n <= 0:
        return []
    out = subprocess.run(
        ["ssh", "-q", "-o", "StrictHostKeyChecking=no", host,
         f"python3 -c \"{_REMOTE_PROBE.format(n=n)}\""],
        capture_output=True, text=True, timeout=60)
    if out.returncode != 0:
        raise SystemExit(
            f"port probe on {host} failed (rc={out.returncode}): "
            f"{out.stderr.strip()[:500]}")
    return [int(p) for p in out.stdout.strip().split(",")]


def _role_env(base, role, rank, args, servers):
    env = dict(base)
    env.update({
        "MXTPU_ROLE": role,
        "MXTPU_NUM_WORKERS": str(args.num_workers),
        "MXTPU_NUM_SERVERS": str(args.num_servers),
        "MXTPU_PS_SERVERS": ",".join(servers),
        # DMLC aliases so reference scripts reading these keep working
        "DMLC_ROLE": role,
        "DMLC_NUM_WORKER": str(args.num_workers),
        "DMLC_NUM_SERVER": str(args.num_servers),
    })
    if role == "server":
        env["MXTPU_SERVER_RANK"] = str(rank)
    else:
        env["MXTPU_RANK"] = str(rank)
        env["DMLC_RANK"] = str(rank)
    return env


def launch_local(args, command):
    servers = [f"127.0.0.1:{p}" for p in _free_ports(args.num_servers)]
    procs = []
    try:
        for i in range(args.num_servers):
            procs.append(subprocess.Popen(
                command, env=_role_env(os.environ, "server", i, args, servers)))
        workers = {}   # rank -> Popen
        restarts = {i: args.max_restarts for i in range(args.num_workers)}
        for i in range(args.num_workers):
            workers[i] = subprocess.Popen(
                command, env=_role_env(os.environ, "worker", i, args, servers))
        rc = 0
        pending = set(workers)
        while pending:
            time.sleep(0.2)
            for rank in sorted(pending):
                p = workers[rank]
                wrc = p.poll()
                if wrc is None:
                    continue
                if wrc != 0 and restarts.get(rank, 0) > 0:
                    restarts[rank] -= 1
                    _log.warning(
                        "worker %d exited with code %d; restarting with "
                        "MXTPU_KV_RECOVERY=1 (%d restart(s) left)",
                        rank, wrc, restarts[rank])
                    env = _role_env(os.environ, "worker", rank, args,
                                    servers)
                    # the recovery contract (kvstore_dist.h:35-39): the
                    # servers still hold the model; the restarted worker
                    # must not re-init keys or wait on long-gone barriers
                    env["MXTPU_KV_RECOVERY"] = "1"
                    workers[rank] = subprocess.Popen(command, env=env)
                    continue
                if wrc != 0:
                    _log.error("worker %d exited with code %d "
                               "(no restarts left)", rank, wrc)
                rc = wrc or rc
                pending.discard(rank)
        for p in list(workers.values()) + procs:
            if p.poll() is None:
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    p.kill()
        return rc
    except BaseException:
        for p in list(procs) + [w for w in locals().get("workers", {}).values()]:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        raise


def launch_ssh(args, command):
    if not args.hostfile:
        raise SystemExit("--launcher ssh requires -H/--hostfile")
    with open(args.hostfile) as f:
        hosts = [h.strip() for h in f if h.strip() and not h.startswith("#")]
    # probe server ports on the host that will BIND them: round-robin
    # the server ranks over hosts first, then ask each host for as many
    # free ports as it will run servers
    server_hosts = [hosts[i % len(hosts)] for i in range(args.num_servers)]
    per_host = {}
    for h in server_hosts:
        per_host[h] = per_host.get(h, 0) + 1
    host_ports = {h: _remote_free_ports(h, n) for h, n in per_host.items()}
    servers = []
    for h in server_hosts:
        servers.append(f"{h}:{host_ports[h].pop(0)}")
    procs = []
    cmd_str = " ".join(command)

    def remote(host, env):
        env_str = " ".join(f"{k}={v}" for k, v in env.items()
                           if k.startswith(("MXTPU_", "DMLC_")))
        return subprocess.Popen(
            ["ssh", "-q", "-o", "StrictHostKeyChecking=no", host,
             f"cd {os.getcwd()} && env {env_str} {cmd_str}"])

    for i in range(args.num_servers):
        procs.append(remote(server_hosts[i],
                            _role_env({}, "server", i, args, servers)))
    rc = 0
    workers = []
    for i in range(args.num_workers):
        p = remote(hosts[i % len(hosts)], _role_env({}, "worker", i, args, servers))
        procs.append(p)
        workers.append(p)
    for p in workers:
        rc = p.wait() or rc
    for p in procs:
        if p.poll() is None:
            p.terminate()
    return rc


def launch_pod(args, command):
    """One-process-per-host schedulers (TPU pods): just exec with worker
    env; jax.distributed coordinates (parallel/dist.py)."""
    env = dict(os.environ)
    env.setdefault("MXTPU_NUM_WORKERS", str(args.num_workers))
    os.execvpe(command[0], command, env)


# --------------------------------------------------------------- elastic
def _coord_post(addr, path, payload):
    req = urllib.request.Request(
        f"http://{addr}{path}", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read())


def _wait_coordinator(addr, timeout=60):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(f"http://{addr}/healthz",
                                        timeout=2) as resp:
                json.loads(resp.read())
                return
        except OSError:
            time.sleep(0.2)
    raise SystemExit(f"coordinator on {addr} never came up")


def _cluster_progress(addr, n_members):
    """min batches-trained across the current world per /cluster, or
    None until every member has joined and reported progress."""
    try:
        with urllib.request.urlopen(f"http://{addr}/cluster",
                                    timeout=5) as resp:
            status = json.loads(resp.read())
    except OSError:
        return None
    members = status.get("members", {})
    if len(members) < n_members:
        return None
    return min(m.get("progress", 0) for m in members.values())


def launch_elastic(args, command):
    """Generation-at-a-time supervisor for collective dist_sync
    (docs/multihost.md lifecycle): membership lives in the coordinator,
    compute worlds are immutable per generation, and every membership
    change is a relaunch of the surviving (or re-expanded) world that
    resumes from the survival-layer checkpoint."""
    coord_port = _free_ports(1)[0]
    coord_addr = f"127.0.0.1:{coord_port}"
    coord_env = dict(os.environ)
    coord_env.setdefault("JAX_PLATFORMS", "cpu")  # detector needs no chips
    coord = subprocess.Popen(
        [sys.executable, "-m", "mxnet_tpu.parallel.coordinator",
         "--port", str(coord_port)], env=coord_env)
    try:
        _wait_coordinator(coord_addr)
        generation = 0
        # stable member slots: env/restart budget follows the SLOT, the
        # per-generation rank is its index in the current world
        world = list(range(args.num_workers))
        restarts = {i: args.max_restarts for i in range(args.num_workers)}
        rejoin_after = []     # slots relaunching into a later generation
        announced = set()
        fabric_retries = args.fabric_retries
        while world:
            jax_port = _free_ports(1)[0]
            _log.info("generation %d: world=%s (jax coordinator :%d)",
                      generation, world, jax_port)
            # sync the membership authority to THIS generation and clear
            # stale leases — a dead incarnation expiring mid-generation
            # must not push the fresh world out
            _coord_post(coord_addr, "/advance",
                        {"generation": generation})
            procs = {}
            for rank, slot in enumerate(world):
                env = dict(os.environ)
                env.update({
                    "MXTPU_ROLE": "worker",
                    "MXTPU_RANK": str(rank),
                    "DMLC_RANK": str(rank),
                    "MXTPU_NUM_WORKERS": str(len(world)),
                    "DMLC_NUM_WORKER": str(len(world)),
                    "MXTPU_COORDINATOR": f"127.0.0.1:{jax_port}",
                    "MXTPU_COORD_ADDR": coord_addr,
                    "MXTPU_DIST_GENERATION": str(generation),
                    "MXTPU_ELASTIC_SLOT": str(slot),
                })
                if generation > 0:
                    env["MXTPU_KV_RECOVERY"] = "1"
                procs[slot] = subprocess.Popen(command, env=env)
            # a standby announcement mid-generation tells the running
            # workers (via the generation bump) to leave at their next
            # boundary so the world can re-expand — gated on the shrunk
            # world having made REAL progress (every member trained
            # >= --rejoin-progress batches per its heartbeat reports),
            # so a rejoin never preempts a world still booting
            rcs = {}
            deadline = None
            last_probe = 0.0
            while len(rcs) < len(procs):
                time.sleep(0.2)
                now = time.monotonic()
                if (rejoin_after and announced != set(rejoin_after)
                        and now - last_probe > 0.5):
                    last_probe = now
                    progress = _cluster_progress(coord_addr, len(world))
                    if progress is not None \
                            and progress >= args.rejoin_progress:
                        for slot in rejoin_after:
                            if slot not in announced:
                                _coord_post(coord_addr, "/join",
                                            {"member": f"slot{slot}",
                                             "standby": True})
                                announced.add(slot)
                                _log.info("announced rejoin of slot %d "
                                          "(next generation)", slot)
                for slot, p in procs.items():
                    if slot in rcs:
                        continue
                    rc = p.poll()
                    if rc is None:
                        continue
                    rcs[slot] = rc
                    if rc == 0:
                        _log.info("slot %d finished (generation %d)",
                                  slot, generation)
                    elif rc == EXIT_HOST_LOST:
                        _log.info("slot %d left generation %d at a "
                                  "checkpoint boundary (exit %d)",
                                  slot, generation, rc)
                    else:
                        _log.warning("slot %d crashed with exit code %d "
                                     "in generation %d", slot, rc,
                                     generation)
                    if deadline is None and rc != 0:
                        # once one member is gone the rest must follow
                        # (watchdog-bounded); give them that long, then
                        # reap stragglers
                        deadline = now + args.exit_grace
                if deadline is not None and now > deadline:
                    for slot, p in procs.items():
                        if slot not in rcs:
                            _log.warning("slot %d still running past the "
                                         "exit grace; killing", slot)
                            p.kill()
            if all(rc == 0 for rc in rcs.values()):
                return 0
            survivors = [s for s in world if rcs[s] == EXIT_HOST_LOST]
            crashed = [s for s in world if rcs[s] not in (0, EXIT_HOST_LOST)]
            finished = [s for s in world if rcs[s] == 0]
            # collateral classification: once one member really dies
            # (or leaves), the shared collective fabric hard-aborts the
            # others (gloo std::terminate -> SIGABRT) faster than they
            # can reach their checkpoint boundary.  A SIGABRT next to
            # any OTHER outcome is collateral: the slot continues as a
            # survivor (resuming from its last periodic checkpoint) and
            # pays no restart budget.  A generation where EVERY member
            # aborts is a fabric failure (transient collective-runtime
            # breakage, no member at fault): relaunch the same world,
            # budget untouched, bounded by --fabric-retries.
            aborted = [s for s in crashed if rcs[s] == -signal.SIGABRT]
            primary = [s for s in crashed if rcs[s] != -signal.SIGABRT]
            if aborted and (primary or survivors or finished):
                for slot in aborted:
                    _log.info(
                        "slot %d (SIGABRT) is collateral of the "
                        "generation-%d failure; rejoining as a survivor",
                        slot, generation)
                survivors += aborted
                crashed = primary
            elif aborted and not (primary or survivors or finished):
                if fabric_retries <= 0:
                    _log.error("generation %d: collective fabric failed "
                               "and no fabric retries left", generation)
                    return 1
                fabric_retries -= 1
                generation += 1
                _log.warning(
                    "generation %d: every member aborted (collective "
                    "fabric failure); relaunching world unchanged as "
                    "generation %d (%d fabric retries left)",
                    generation - 1, generation, fabric_retries)
                continue
            next_world = sorted(survivors + rejoin_after)
            rejoin_after = []
            announced.clear()
            for slot in crashed:
                if restarts[slot] > 0:
                    restarts[slot] -= 1
                    rejoin_after.append(slot)
                    _log.warning(
                        "slot %d (exit %d) rejoins at a later generation "
                        "(%d restart(s) left)", slot, rcs[slot],
                        restarts[slot])
                else:
                    _log.error("slot %d (exit %d) has no restarts left; "
                               "world shrinks permanently", slot,
                               rcs[slot])
            if not next_world and rejoin_after:
                # everyone died but restart budget remains: the next
                # generation IS the rejoiners
                next_world = sorted(rejoin_after)
                rejoin_after = []
            if finished and next_world:
                # some members finished while others still want a
                # generation (e.g. a collateral abort near the end):
                # relaunch only the unfinished — they resume from the
                # checkpoint and complete the same schedule
                _log.warning("generation %d: slots %s finished; "
                             "relaunching %s to complete", generation,
                             finished, next_world)
            generation += 1
            world = next_world
        _log.error("no members left with restart budget; giving up")
        return 1
    finally:
        coord.terminate()
        try:
            coord.wait(timeout=10)
        except subprocess.TimeoutExpired:
            coord.kill()


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("-s", "--num-servers", type=int, default=0)
    ap.add_argument("--launcher", default="local",
                    choices=["local", "ssh", "pod", "elastic"])
    ap.add_argument("-H", "--hostfile", default=None)
    ap.add_argument("--max-restarts", type=int, default=0,
                    help="restart a crashed worker up to N times "
                         "(MXTPU_KV_RECOVERY=1 / elastic rejoin)")
    ap.add_argument("--rejoin-progress", type=int, default=3,
                    help="elastic: batches every member of the shrunk "
                         "generation must have trained (per heartbeat "
                         "progress reports) before a restarted slot "
                         "announces its rejoin")
    ap.add_argument("--exit-grace", type=float, default=90.0,
                    help="elastic: seconds the remaining members of a "
                         "broken generation get to reach their "
                         "checkpoint boundary before being reaped")
    ap.add_argument("--fabric-retries", type=int, default=3,
                    help="elastic: relaunches granted (budget-free) "
                         "when a whole generation dies to a collective-"
                         "fabric abort rather than a member crash")
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args()
    command = [c for c in args.command if c != "--"]
    if not command:
        raise SystemExit("no command given")
    if args.launcher == "local":
        sys.exit(launch_local(args, command))
    elif args.launcher == "ssh":
        sys.exit(launch_ssh(args, command))
    elif args.launcher == "elastic":
        sys.exit(launch_elastic(args, command))
    else:
        launch_pod(args, command)


if __name__ == "__main__":
    main()
