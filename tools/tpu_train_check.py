"""On-silicon training convergence check (parity: the reference's
tests/python/train suite — test_mlp/test_conv assert accuracy, not just
op numerics).  Trains two small models through the bf16 FusedTrainer on
the REAL chip and asserts accuracy above floor; the window watcher
commits the output as the 'training works on silicon' artifact.

Run on the bench chip:  python tools/tpu_train_check.py
CPU smoke:  MXTPU_PLATFORM=cpu python tools/tpu_train_check.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def check_mlp():
    import jax.numpy as jnp

    from mxnet_tpu import sym
    from mxnet_tpu.initializer import Xavier
    from mxnet_tpu.trainer import FusedTrainer

    np.random.seed(0)  # the initializer draws from the global RNG
    rs = np.random.RandomState(0)
    x = rs.uniform(-1, 1, (512, 32)).astype(np.float32)
    y = ((x[:, :16].sum(1) - x[:, 16:].sum(1)) > 0).astype(np.float32)
    net = sym.SoftmaxOutput(sym.FullyConnected(sym.Activation(
        sym.FullyConnected(sym.Variable("data"), num_hidden=64, name="fc1"),
        act_type="relu"), num_hidden=2, name="fc2"), name="softmax")
    tr = FusedTrainer(net, optimizer="sgd",
                      optimizer_params={"lr": 0.1},
                      dtype=jnp.bfloat16, initializer=Xavier())
    tr.init(data=(128, 32))
    for epoch in range(15):
        for i in range(4):
            tr.step(data=x[i * 128:(i + 1) * 128],
                    softmax_label=y[i * 128:(i + 1) * 128])
    out = np.asarray(tr.eval(data=x[:128])[0])
    acc = float(((out[:, 1] > out[:, 0]) == (y[:128] > 0)).mean())
    print(f"mlp_train_acc: {acc:.3f}", flush=True)
    assert acc > 0.95, acc


def check_conv():
    import jax.numpy as jnp

    from mxnet_tpu import sym
    from mxnet_tpu.trainer import FusedTrainer

    np.random.seed(1)  # the initializer draws from the global RNG
    rs = np.random.RandomState(1)
    n = 512
    x = rs.uniform(0, 0.2, (n, 1, 16, 16)).astype(np.float32)
    y = rs.randint(0, 2, n)
    for i, c in enumerate(y):  # class lights the left or right half
        x[i, 0, :, (0 if c == 0 else 8):(8 if c == 0 else 16)] += 0.8
    y = y.astype(np.float32)
    net = sym.Variable("data")
    net = sym.Convolution(net, num_filter=8, kernel=(3, 3), pad=(1, 1),
                          name="c1")
    net = sym.BatchNorm(net, name="bn1")
    net = sym.Activation(net, act_type="relu")
    net = sym.SoftmaxOutput(sym.FullyConnected(sym.Flatten(net),
                                               num_hidden=2, name="fc"),
                            name="softmax")
    tr = FusedTrainer(net, optimizer="sgd", optimizer_params={"lr": 0.1},
                      dtype=jnp.bfloat16)  # default Uniform init: Xavier
    #                                        over-scales this shallow
    #                                        conv+BN stack (tested A/B)
    tr.init(data=(64, 1, 16, 16))
    for epoch in range(15):
        for i in range(8):
            tr.step(data=x[i * 64:(i + 1) * 64],
                    softmax_label=y[i * 64:(i + 1) * 64])
    out = np.asarray(tr.eval(data=x[:64])[0])
    acc = float(((out[:, 1] > out[:, 0]) == (y[:64] > 0)).mean())
    print(f"conv_bn_train_acc: {acc:.3f}", flush=True)
    assert acc > 0.95, acc


def main():
    if os.environ.get("MXTPU_PLATFORM") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    print("devices:", jax.devices(), flush=True)
    tic = time.perf_counter()
    check_mlp()
    check_conv()
    print(f"TRAIN-ON-DEVICE OK ({time.perf_counter() - tic:.1f}s)",
          flush=True)


if __name__ == "__main__":
    main()
