#!/usr/bin/env python
"""Parse training logs into per-epoch tables (parity: tools/parse_log.py).

Reads a log produced by Module.fit / Speedometer and prints
``epoch  train-acc  valid-acc  time`` in markdown or csv — the format the
reference's CI accuracy gates grep (tests/nightly/test_all.sh check_val).
"""
from __future__ import annotations

import argparse
import re
import sys

RE_EPOCH_METRIC = re.compile(
    r"Epoch\[(\d+)\]\s+(?:Batch\s+\[\d+\]\s+.*?)?Train-([\w-]+)=([\d.naif]+)", re.I)
RE_VAL_METRIC = re.compile(r"Epoch\[(\d+)\]\s+Validation-([\w-]+)=([\d.naif]+)", re.I)
RE_TIME = re.compile(r"Epoch\[(\d+)\]\s+Time cost=([\d.]+)")


def parse(lines):
    rows = {}
    for line in lines:
        m = RE_EPOCH_METRIC.search(line)
        if m:
            rows.setdefault(int(m.group(1)), {})[f"train-{m.group(2)}"] = float(m.group(3))
        m = RE_VAL_METRIC.search(line)
        if m:
            rows.setdefault(int(m.group(1)), {})[f"valid-{m.group(2)}"] = float(m.group(3))
        m = RE_TIME.search(line)
        if m:
            rows.setdefault(int(m.group(1)), {})["time"] = float(m.group(2))
    return rows


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("logfile", nargs="?", default="-")
    ap.add_argument("--format", default="markdown", choices=["markdown", "csv"])
    args = ap.parse_args()
    lines = (sys.stdin if args.logfile == "-" else open(args.logfile)).readlines()
    rows = parse(lines)
    if not rows:
        print("no epochs found", file=sys.stderr)
        return
    cols = sorted({c for r in rows.values() for c in r})
    sep = "," if args.format == "csv" else " | "
    print(sep.join(["epoch"] + cols))
    if args.format == "markdown":
        print(sep.join(["---"] * (len(cols) + 1)))
    for epoch in sorted(rows):
        vals = [f"{rows[epoch].get(c, float('nan')):.6g}" for c in cols]
        print(sep.join([str(epoch)] + vals))


if __name__ == "__main__":
    main()
