#!/usr/bin/env python
"""Pack an image folder (+ optional .lst) into RecordIO shards.

Parity: tools/im2rec.py + tools/im2rec.cc (reference).  Two modes:

1. ``--list``: walk an image root, emit ``prefix.lst`` lines of
   ``index \\t label \\t relpath`` (label = folder index, like the
   reference's per-directory labelling), with optional train/test split.
2. pack (default): read ``prefix.lst``, load + optionally resize each
   image, and write ``prefix.rec`` (+ ``prefix.idx``) via the
   MXRecordIO/IRHeader format shared with the C++ reader (src/recordio.cc).

Record payload layout is byte-compatible with python/mxnet/recordio.py's
``pack_img`` so ImageRecordIter / ImageRecordUInt8Iter consume the output
directly.
"""
from __future__ import annotations

import argparse
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

EXTS = (".jpg", ".jpeg", ".png", ".bmp")


def make_list(args):
    root = args.root
    classes = sorted(
        d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d)))
    entries = []
    if classes:
        for label, cls in enumerate(classes):
            for dirpath, _, files in os.walk(os.path.join(root, cls)):
                for f in sorted(files):
                    if f.lower().endswith(EXTS):
                        rel = os.path.relpath(os.path.join(dirpath, f), root)
                        entries.append((rel, float(label)))
    else:  # flat folder: label 0
        for f in sorted(os.listdir(root)):
            if f.lower().endswith(EXTS):
                entries.append((f, 0.0))
    if args.shuffle:
        random.Random(args.seed).shuffle(entries)

    n_test = int(len(entries) * args.test_ratio)
    splits = [("", entries[n_test:])] if n_test == 0 else [
        ("_train", entries[n_test:]), ("_test", entries[:n_test])]
    for suffix, part in splits:
        path = f"{args.prefix}{suffix}.lst"
        with open(path, "w") as f:
            for i, (rel, label) in enumerate(part):
                f.write(f"{i}\t{label}\t{rel}\n")
        print(f"wrote {path} ({len(part)} entries)")


def read_list(path):
    with open(path) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            idx = int(parts[0])
            labels = [float(x) for x in parts[1:-1]]
            yield idx, labels, parts[-1]


def pack(args):
    # --list with --test-ratio writes prefix_train.lst/prefix_test.lst;
    # pack every split that exists so the documented two-step workflow
    # works for both the plain and the split case
    candidates = [args.prefix] + [args.prefix + s for s in ("_train", "_test")]
    prefixes = [p for p in candidates if os.path.exists(p + ".lst")]
    if not prefixes:
        raise SystemExit(f"{args.prefix}.lst not found — run with --list first")
    for prefix in prefixes:
        _pack_one(args, prefix)


def _pack_one(args, prefix):
    import numpy as np

    from mxnet_tpu import recordio
    from mxnet_tpu.image import imdecode_np, imencode

    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    count = 0
    for idx, labels, rel in read_list(prefix + ".lst"):
        path = os.path.join(args.root, rel)
        with open(path, "rb") as f:
            buf = f.read()
        if args.resize > 0 or args.quality != 95 or args.center_crop:
            img = imdecode_np(buf)
            if args.resize > 0:
                h, w = img.shape[:2]
                scale = args.resize / min(h, w)
                from PIL import Image

                im = Image.fromarray(img).resize(
                    (max(1, int(w * scale)), max(1, int(h * scale))))
                img = np.asarray(im)
            if args.center_crop:
                h, w = img.shape[:2]
                s = min(h, w)
                y, x = (h - s) // 2, (w - s) // 2
                img = img[y:y + s, x:x + s]
            buf = imencode(img, quality=args.quality,
                           img_fmt=args.encoding)
        label = labels[0] if len(labels) == 1 else np.array(labels, np.float32)
        header = recordio.IRHeader(0, label, idx, 0)
        rec.write_idx(idx, recordio.pack(header, buf))
        count += 1
        if count % 1000 == 0:
            print(f"packed {count}")
    rec.close()
    print(f"wrote {prefix}.rec ({count} records)")


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("prefix", help="output prefix for .lst/.rec/.idx")
    ap.add_argument("root", help="image root directory")
    ap.add_argument("--list", action="store_true",
                    help="generate .lst instead of packing")
    ap.add_argument("--shuffle", type=int, default=1)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--test-ratio", type=float, default=0.0)
    ap.add_argument("--resize", type=int, default=0,
                    help="resize shorter edge to this (0 = keep)")
    ap.add_argument("--center-crop", action="store_true")
    ap.add_argument("--quality", type=int, default=95)
    ap.add_argument("--encoding", default=".jpg", choices=[".jpg", ".png"])
    args = ap.parse_args()
    if args.list:
        make_list(args)
    else:
        pack(args)


if __name__ == "__main__":
    main()
