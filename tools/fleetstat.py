#!/usr/bin/env python
"""Fleet operator CLI (docs/multihost.md): one view of an N-host world.

Status mode talks to the coordinator's ``GET /fleet`` endpoint
(parallel/coordinator.py federates every member's ``/metrics.json`` and
the heartbeat step timings behind it):

    python tools/fleetstat.py --coord 10.0.0.1:8476        # one-shot
    python tools/fleetstat.py --watch 5                    # refresh loop
    python tools/fleetstat.py --json                       # raw /fleet

``merge-trace`` folds per-host flight-record dumps (telemetry
``dump_flight_record``: ring + identity + clock offset) into ONE
chrome-trace JSON with a lane per host, every lane shifted onto the
coordinator timebase by its dump's RTT-midpoint clock-offset estimate —
a cross-host stall is one picture instead of N files:

    python tools/fleetstat.py merge-trace dumps/*.json -o fleet_trace.json

``trace`` is the per-REQUEST twin (docs/tracing.md): it sweeps
``GET /spans.json`` over a serving router and every replica the router
knows, keeps one trace id's spans, dedupes shared buffers, corrects
each process's clock by its payload's offset estimate, prints the span
listing in start order, and (with ``-o``) writes a chrome trace with a
lane per (host, pid, service):

    python tools/fleetstat.py trace 4bf92f3577b34da6a3ce929d0e0e4736 \\
        --router 10.0.0.9:8700 -o trace.json

``--slo`` renders the router's ``GET /slo`` burn-rate table (multi-
window error-budget burn + slowest-TTFT exemplar trace ids).

Stdlib-only on purpose: this runs on an operator workstation or a bare
pod VM without the mxnet_tpu (or jax) install.
"""
import argparse
import json
import os
import sys
import time
import urllib.request


def fetch_json(addr, path, timeout=10.0):
    with urllib.request.urlopen("http://%s%s" % (addr, path),
                                timeout=timeout) as resp:
        return json.loads(resp.read())


def fetch_fleet(addr, timeout=10.0):
    return fetch_json(addr, "/fleet", timeout=timeout)


def render_router(fleet):
    """Human rendering of a serving ROUTER's /fleet JSON
    (serving/router.py): one row per replica with the drain state an
    operator watches during a rolling upgrade, and the paged-KV
    occupancy when the replica runs the paged backend."""
    lines = []
    reps = fleet.get("replicas") or {}
    lines.append("serving fleet: %d replica(s), %d healthy, scrape "
                 "every %.1fs" % (len(reps), fleet.get("healthy") or 0,
                                  float(fleet.get("scrape_interval_s")
                                        or 0.0)))
    lines.append("%-22s %-6s %-9s %5s %5s %6s %6s %-18s" % (
        "replica", "state", "draining", "slots", "occ", "queue",
        "ticks", "paged pages (free/total, prefix)"))
    for addr in sorted(reps):
        r = reps[addr]
        hz = r.get("health") or {}
        paged = hz.get("paged")
        lines.append("%-22s %-6s %-9s %5s %5s %6s %6s %-18s%s" % (
            addr[:22],
            "up" if r.get("ok") else "DEAD",
            str(hz.get("status", "?")) if r.get("draining") else "no",
            hz.get("slots", "-"), hz.get("occupied", "-"),
            hz.get("queue_depth", "-"), hz.get("ticks", "-"),
            ("%s/%s, %s prefix" % (paged.get("pages_free"),
                                   paged.get("pages_total"),
                                   paged.get("prefix_pages")))
            if paged else "-",
            "" if r.get("ok") else "  <- " + str(r.get("error"))[:40]))
    lines.append("%d merged metric families (GET /fleet on the router "
                 "for the full catalog)" % len(fleet.get("metrics") or {}))
    return "\n".join(lines)


def _ms(seconds):
    return "-" if seconds is None else "%.1f" % (float(seconds) * 1e3)


def render(fleet):
    """Human one-screen rendering of the /fleet JSON."""
    lines = []
    lines.append(
        "generation %s   hosts_alive %s   step_skew %.2fx   "
        "scrape every %.1fs" % (
            fleet.get("generation"), fleet.get("hosts_alive"),
            float(fleet.get("step_skew_ratio") or 0.0),
            float(fleet.get("scrape_interval_s") or 0.0)))
    strag = fleet.get("straggler")
    if strag:
        lines.append(
            "STRAGGLER: %s (host %s) at %.2fx the fleet median "
            "(%.1fms vs %.1fms)" % (
                strag.get("member"), strag.get("host"),
                float(strag.get("ratio") or 0.0),
                float(strag.get("step_wall_s") or 0.0) * 1e3,
                float(strag.get("fleet_median_s") or 0.0) * 1e3))
    lines.append("%-28s %-14s %4s %-6s %9s %9s %8s %8s %7s" % (
        "member", "host", "rank", "role", "lease_age", "progress",
        "step_ms", "disp_ms", "scrape"))
    hosts = fleet.get("hosts") or {}
    for mid in sorted(hosts):
        m = hosts[mid]
        steps = m.get("steps") or {}
        mark = " <- straggler" if strag and strag.get("member") == mid \
            else ""
        lines.append("%-28s %-14s %4s %-6s %9s %9s %8s %8s %7s%s" % (
            mid[:28], str(m.get("host", "?"))[:14], m.get("rank"),
            str(m.get("role", "train"))[:6],
            "%.1fs" % float(m.get("lease_age_s") or 0.0),
            m.get("progress", 0), _ms(steps.get("step_wall_s")),
            _ms(steps.get("dispatch_s")),
            "ok" if m.get("scrape_ok") else
            ("err" if m.get("telemetry") else "-"), mark))
    dead = fleet.get("dead") or []
    if dead:
        lines.append("dead: " + ", ".join(
            "%s (g%s)" % (d.get("member"), d.get("generation"))
            for d in dead[-8:]))
    lines.append("%d merged metric families (GET /fleet for the full "
                 "host-labeled catalog)" % len(fleet.get("metrics") or {}))
    return "\n".join(lines)


def merge_trace(paths, out_path):
    """Merge flight-record dumps into one chrome trace with per-host
    lanes on a common timebase.

    Each dump's ``identity`` names its lane (host/rank/generation) and
    carries ``clock.offset_s`` = (coordinator clock - local clock): a
    record stamped at local time ``t`` lands at coordinator time
    ``t + offset_s``, so lanes from hosts with skewed clocks still line
    up.  Ring records become complete ("X") events — the record's ``t``
    is stamped at step END, so each slice spans ``[t - wall, t]``.
    Returns ``(out_path, n_events)``."""
    events = []
    lanes = []
    t_min = None
    for i, path in enumerate(sorted(paths)):
        with open(path) as f:
            dump = json.load(f)
        ident = dump.get("identity") or {}
        host = str(ident.get("host", "host%d" % i))
        rank = ident.get("rank", i)
        gen = ident.get("generation", 0)
        offset = float((ident.get("clock") or {}).get("offset_s") or 0.0)
        pid = i  # one lane per dump; the label carries host/rank/gen
        lanes.append((pid, "%s rank%s g%s" % (host, rank, gen)))
        for rec in dump.get("ring") or ():
            t = rec.get("t")
            if t is None:
                continue
            dur_s = float(rec.get("wall_s") or rec.get("dispatch_s") or 0.0)
            end_us = (float(t) + offset) * 1e6
            start_us = end_us - dur_s * 1e6
            t_min = start_us if t_min is None else min(t_min, start_us)
            events.append({
                "ph": "X", "pid": pid, "tid": 0,
                "ts": start_us, "dur": max(dur_s * 1e6, 1.0),
                "name": "step %s" % rec.get("step", rec.get("seq", "?")),
                "cat": str(rec.get("loop", "step")),
                "args": {k: v for k, v in rec.items()
                         if isinstance(v, (int, float, str))
                         and k not in ("t",)},
            })
    t_min = t_min or 0.0
    for e in events:
        e["ts"] = round(e["ts"] - t_min, 3)
    meta = [{"ph": "M", "pid": pid, "tid": 0, "name": "process_name",
             "args": {"name": label}} for pid, label in lanes]
    with open(out_path, "w") as f:
        json.dump({"traceEvents": meta + events,
                   "displayTimeUnit": "ms"}, f, indent=1)
    return out_path, len(events)


def gather_spans(router_addr, trace_id, timeout=10.0):
    """One trace's spans from the whole serving fleet: the router's
    ``/spans.json`` plus every replica's (addresses learned from the
    router's ``/healthz`` registry), deduped by span id — an in-process
    test fleet shares ONE buffer, so the same span can arrive from
    every endpoint — with each payload's ``clock.offset_s`` applied to
    its spans' end stamps (``_t_corr``).  Sorted by corrected START."""
    payloads = []
    try:
        payloads.append(fetch_json(router_addr, "/spans.json", timeout))
    except OSError as exc:
        print("fleetstat: router %s /spans.json unreachable: %s"
              % (router_addr, exc), file=sys.stderr)
    replicas = ()
    try:
        hz = fetch_json(router_addr, "/healthz", timeout)
        replicas = sorted(hz.get("replicas") or {})
    except OSError:
        pass
    for addr in replicas:
        try:
            payloads.append(fetch_json(addr, "/spans.json", timeout))
        except OSError:
            continue
    seen = set()
    out = []
    for p in payloads:
        offset = float((p.get("clock") or {}).get("offset_s") or 0.0)
        for s in p.get("spans") or ():
            if s.get("trace") != trace_id or s.get("sid") in seen:
                continue
            seen.add(s.get("sid"))
            s = dict(s)
            s["_t_corr"] = float(s.get("t") or 0.0) + offset
            s["_lane"] = (str(p.get("host", "?")), p.get("pid", 0),
                          str(s.get("svc", "?")))
            out.append(s)
    out.sort(key=lambda s: s["_t_corr"] - float(s.get("dur_s") or 0.0))
    return out


def render_spans(trace_id, spans):
    """Span listing in corrected start order, offsets relative to the
    trace's first span."""
    t0 = min(s["_t_corr"] - float(s.get("dur_s") or 0.0) for s in spans)
    lanes = sorted({s["_lane"] for s in spans})
    lines = ["trace %s: %d span(s) across %d lane(s)"
             % (trace_id, len(spans), len(lanes))]
    lines.append("%10s %10s  %-8s %-14s %s" % (
        "start", "dur", "svc", "span", "attrs"))
    for s in spans:
        dur_s = float(s.get("dur_s") or 0.0)
        attrs = " ".join(
            "%s=%s" % (k, v) for k, v in sorted(s.items())
            if not k.startswith("_")
            and k not in ("t", "dur_s", "name", "svc", "trace", "sid",
                          "parent"))
        lines.append("%8.2fms %8.2fms  %-8s %-14s %s" % (
            (s["_t_corr"] - dur_s - t0) * 1e3, dur_s * 1e3,
            s["_lane"][2], str(s.get("name", "?")), attrs))
    return "\n".join(lines)


def write_trace(spans, out_path):
    """Chrome trace over the corrected timebase: one lane per (host,
    pid, service); each span drawn ``[t - dur, t]`` (same convention as
    :func:`merge_trace`).  Returns ``(path, n_events, n_lanes)``."""
    lanes = {}
    events = []
    t_min = None
    for s in spans:
        pid = lanes.setdefault(s["_lane"], len(lanes))
        dur_s = float(s.get("dur_s") or 0.0)
        end_us = s["_t_corr"] * 1e6
        start_us = end_us - dur_s * 1e6
        t_min = start_us if t_min is None else min(t_min, start_us)
        events.append({
            "ph": "X", "pid": pid, "tid": 0,
            "ts": start_us, "dur": max(dur_s * 1e6, 1.0),
            "name": str(s.get("name", "?")),
            "cat": str(s.get("svc", "span")),
            "args": {k: v for k, v in s.items()
                     if isinstance(v, (int, float, str))
                     and not k.startswith("_") and k != "t"},
        })
    t_min = t_min or 0.0
    for e in events:
        e["ts"] = round(e["ts"] - t_min, 3)
    meta = [{"ph": "M", "pid": pid, "tid": 0, "name": "process_name",
             "args": {"name": "%s pid%s %s" % lane}}
            for lane, pid in lanes.items()]
    with open(out_path, "w") as f:
        json.dump({"traceEvents": meta + events,
                   "displayTimeUnit": "ms"}, f, indent=1)
    return out_path, len(events), len(lanes)


def render_slo(slo):
    """Human rendering of the router's GET /slo burn-rate payload."""
    obj = slo.get("objectives") or {}
    lines = ["SLO: ttft <= %sms, availability >= %s (error budget %s)"
             % (obj.get("ttft_ms"), obj.get("availability"),
                slo.get("error_budget"))]
    lines.append("%-8s %9s %10s %11s %9s %10s" % (
        "window", "requests", "bad_avail", "burn_avail", "bad_ttft",
        "burn_ttft"))
    windows = slo.get("windows") or {}
    for label in sorted(windows, key=lambda w: float(w.rstrip("s"))):
        w = windows[label]
        burn = w.get("burn_rate") or {}
        lines.append("%-8s %9s %10s %11s %9s %10s" % (
            label, w.get("requests"), w.get("bad_availability"),
            burn.get("availability"), w.get("bad_ttft"),
            burn.get("ttft")))
    viol = slo.get("violations_total") or {}
    lines.append("violations since start: availability=%s ttft=%s"
                 % (viol.get("availability"), viol.get("ttft")))
    exemplars = slo.get("exemplars") or []
    if exemplars:
        lines.append("slowest-TTFT exemplar traces (fleetstat.py trace "
                     "<id> --router ...):")
        for e in exemplars:
            lines.append("  %s  %8.2fms" % (e.get("trace"),
                                            float(e.get("ttft_ms") or 0)))
    return "\n".join(lines)


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "trace":
        ap = argparse.ArgumentParser(
            prog="fleetstat.py trace",
            description="join one request's spans from the router and "
                        "every replica into a clock-corrected listing "
                        "and (with -o) a chrome trace")
        ap.add_argument("trace_id", help="32-hex trace id (from "
                        "X-MXTPU-Trace, /slo exemplars, or the reply "
                        "body)")
        ap.add_argument("--router", required=True, metavar="ADDR",
                        help="serving router host:port")
        ap.add_argument("-o", "--out", default=None,
                        help="also write a chrome-trace JSON here")
        ap.add_argument("--timeout", type=float, default=10.0)
        args = ap.parse_args(argv[1:])
        spans = gather_spans(args.router, args.trace_id,
                             timeout=args.timeout)
        if not spans:
            print("fleetstat: no spans for trace %s (is MXTPU_TRACE=1 "
                  "on the fleet, and was the request sampled?)"
                  % args.trace_id, file=sys.stderr)
            return 1
        print(render_spans(args.trace_id, spans))
        if args.out:
            out, n, nl = write_trace(spans, args.out)
            print("wrote %s (%d events, %d lanes) — open in "
                  "chrome://tracing" % (out, n, nl))
        return 0

    if argv and argv[0] == "merge-trace":
        ap = argparse.ArgumentParser(
            prog="fleetstat.py merge-trace",
            description="merge per-host flight dumps into one chrome trace")
        ap.add_argument("dumps", nargs="+", help="flight-record JSONs")
        ap.add_argument("-o", "--out", default="fleet_trace.json")
        args = ap.parse_args(argv[1:])
        out, n = merge_trace(args.dumps, args.out)
        print("wrote %s (%d events, %d lanes) — open in chrome://tracing"
              % (out, n, len(args.dumps)))
        return 0

    ap = argparse.ArgumentParser(
        prog="fleetstat.py",
        description="fleet status from the coordinator's (or serving "
                    "router's) GET /fleet")
    ap.add_argument("--coord",
                    default=os.environ.get("MXTPU_COORD_ADDR",
                                           "127.0.0.1:8476"),
                    help="coordinator host:port (default: "
                         "$MXTPU_COORD_ADDR or 127.0.0.1:8476)")
    ap.add_argument("--router", default=None, metavar="ADDR",
                    help="serving router host:port: render the replica "
                         "table (drain state + paged-KV occupancy) "
                         "instead of the coordinator view")
    ap.add_argument("--watch", nargs="?", const=5.0, type=float,
                    default=None, metavar="SEC",
                    help="refresh every SEC seconds (default 5)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="print the raw /fleet (or /slo) JSON")
    ap.add_argument("--slo", action="store_true",
                    help="render the router's GET /slo burn-rate table "
                         "instead of the fleet view")
    ap.add_argument("target", nargs="?", default=None, metavar="ADDR",
                    help="bare host:port shorthand — treated as --router "
                         "ADDR (e.g. `fleetstat.py localhost:9100 --slo`)")
    args = ap.parse_args(argv)
    if args.target is not None and args.router is None:
        args.router = args.target
    target = args.router or args.coord
    if args.slo:
        try:
            slo = fetch_json(target, "/slo")
        except OSError as exc:
            print("fleetstat: router %s /slo unreachable: %s"
                  % (target, exc), file=sys.stderr)
            return 1
        print(json.dumps(slo, indent=1) if args.as_json
              else render_slo(slo))
        return 0
    while True:
        try:
            fleet = fetch_fleet(target)
        except OSError as exc:
            print("fleetstat: %s %s unreachable: %s"
                  % ("router" if args.router else "coordinator",
                     target, exc), file=sys.stderr)
            if args.watch is None:
                return 1
            time.sleep(args.watch)
            continue
        print(json.dumps(fleet, indent=1) if args.as_json
              else (render_router(fleet) if args.router
                    else render(fleet)), flush=True)
        if args.watch is None:
            return 0
        time.sleep(args.watch)
        print("---- %s" % time.strftime("%H:%M:%S"), flush=True)


if __name__ == "__main__":
    sys.exit(main())
