"""Build + execute the tutorial notebooks (examples/notebooks/ +
examples/recommenders/demo1-MF.ipynb).

Parity: the reference ships tutorials as Jupyter notebooks
(example/MXNetTutorialTemplate.ipynb + example/recommenders/demo*.ipynb,
example/notebooks/).  This repo's notebooks are GENERATED from this
script (single source of truth, no stale-output drift) and committed
WITH executed outputs: `python tools/make_notebooks.py` rebuilds and
re-executes them on the cpu platform; CI smoke re-executes via
tests/test_examples_smoke.py when MXTPU_EXAMPLE_TESTS=1.
"""
import os
import sys

import nbformat
from nbclient import NotebookClient

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SETUP = """\
import os
os.environ.setdefault("MXTPU_PLATFORM", "cpu")  # notebooks run anywhere
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import nd, sym
np.random.seed(0); mx.random.seed(0)
print("devices:", mx.context.num_devices(), "default ctx:", mx.context.current_context())"""


def nb_of(title, intro, cells):
    nb = nbformat.v4.new_notebook()
    nb.metadata["kernelspec"] = {"name": "python3",
                                 "display_name": "Python 3",
                                 "language": "python"}
    nb.cells = [nbformat.v4.new_markdown_cell(f"# {title}\n\n{intro}")]
    for kind, src in cells:
        if kind == "md":
            nb.cells.append(nbformat.v4.new_markdown_cell(src))
        else:
            nb.cells.append(nbformat.v4.new_code_cell(src))
    return nb


def basics_notebook():
    return nb_of(
        "NDArray and Symbol basics",
        "The two halves of the API: **imperative** `mx.nd` arrays that "
        "compute eagerly on the accelerator, and **symbolic** `mx.sym` "
        "graphs compiled by XLA into one fused program.  This tutorial "
        "walks the road between them.\n\n"
        "Prerequisites: a working install (nothing to build — "
        "`import mxnet_tpu` from the repo root).",
        [
            ("code", SETUP),
            ("md", "## Imperative: NDArray\n\n`mx.nd` mirrors the "
                   "reference's `mx.nd`: create, slice, and mutate "
                   "arrays; every op dispatches to the device."),
            ("code", "a = nd.array(np.arange(6).reshape(2, 3))\n"
                     "b = nd.ones((2, 3)) * 2\n"
                     "c = a * b + 1\n"
                     "print(c.shape, c.asnumpy())"),
            ("code", "# in-place updates and slicing work like numpy\n"
                     "c[0] = -1\n"
                     "print(c.asnumpy())\n"
                     "print('row sums:', nd.sum(c, axis=1).asnumpy())"),
            ("md", "## Symbolic: build a graph once, run it compiled\n\n"
                   "A `Symbol` records structure only.  `simple_bind` "
                   "infers every shape, allocates arrays, and compiles "
                   "the whole graph into one XLA program."),
            ("code", "x = sym.Variable('x')\n"
                     "y = sym.FullyConnected(x, num_hidden=4, name='fc')\n"
                     "z = sym.Activation(y, act_type='relu')\n"
                     "print('args:', z.list_arguments())\n"
                     "arg_shapes, out_shapes, _ = z.infer_shape(x=(5, 3))\n"
                     "print('out:', out_shapes)"),
            ("code", "exe = z.simple_bind(ctx=mx.cpu(), x=(5, 3))\n"
                     "exe.arg_dict['x'][:] = np.random.rand(5, 3)\n"
                     "exe.arg_dict['fc_weight'][:] = "
                     "np.random.rand(4, 3) * 0.1\n"
                     "exe.arg_dict['fc_bias'][:] = 0\n"
                     "out = exe.forward()[0]\n"
                     "print(out.shape, out.asnumpy().round(3))"),
            ("md", "## Gradients\n\n`forward(is_train=True)` + "
                   "`backward()` runs the fused forward+backward "
                   "program; gradients land in `grad_dict`."),
            ("code", "loss = sym.sum(z)\n"
                     "exe = loss.simple_bind(ctx=mx.cpu(), x=(5, 3), "
                     "grad_req='write')\n"
                     "exe.arg_dict['x'][:] = np.random.rand(5, 3)\n"
                     "exe.arg_dict['fc_weight'][:] = 0.1\n"
                     "exe.arg_dict['fc_bias'][:] = 0\n"
                     "exe.forward(is_train=True)\n"
                     "exe.backward()\n"
                     "print('d loss / d fc_weight:\\n', "
                     "exe.grad_dict['fc_weight'].asnumpy().round(3))"),
            ("md", "## Where to next\n\n"
                   "- `train_mnist_module.ipynb` — the Module training "
                   "loop\n"
                   "- `docs/how_to/perf.md` — the fused-trainer fast "
                   "path and TPU performance notes\n"
                   "- `examples/` — full workloads (vision, speech, "
                   "rcnn, GAN, recommenders, transformer-LM)"),
        ])


def mnist_notebook():
    return nb_of(
        "Training with Module",
        "`mx.mod.Module` owns the executor, optimizer, and metric "
        "plumbing — `fit()` is the reference's canonical training entry "
        "point.  Here: a small MLP on a synthetic MNIST-like problem "
        "(blob images whose class is their bright quadrant), so the "
        "notebook runs anywhere in seconds.",
        [
            ("code", SETUP),
            ("md", "## Data\n\nFour classes; class *c* lights up "
                   "quadrant *c* of an 8×8 image.  `NDArrayIter` is the "
                   "in-memory iterator (reference: `mx.io.NDArrayIter`)."),
            ("code", "def make_data(n):\n"
                     "    X = np.random.rand(n, 1, 8, 8).astype('float32') * 0.2\n"
                     "    y = np.random.randint(0, 4, n)\n"
                     "    for i, c in enumerate(y):\n"
                     "        r, col = divmod(int(c), 2)\n"
                     "        X[i, 0, r*4:(r+1)*4, col*4:(col+1)*4] += 0.8\n"
                     "    return X, y.astype('float32')\n"
                     "Xtr, ytr = make_data(2048)\n"
                     "Xva, yva = make_data(512)\n"
                     "train_iter = mx.io.NDArrayIter(Xtr, ytr, batch_size=64, shuffle=True)\n"
                     "val_iter = mx.io.NDArrayIter(Xva, yva, batch_size=64)"),
            ("md", "## Network + fit"),
            ("code", "net = sym.Variable('data')\n"
                     "net = sym.Flatten(net)\n"
                     "net = sym.Activation(sym.FullyConnected(net, num_hidden=64, name='fc1'), act_type='relu')\n"
                     "net = sym.FullyConnected(net, num_hidden=4, name='fc2')\n"
                     "net = sym.SoftmaxOutput(net, name='softmax')\n"
                     "import logging; logging.basicConfig(level=logging.INFO)\n"
                     "mod = mx.mod.Module(net, context=mx.cpu())\n"
                     "mod.fit(train_iter, eval_data=val_iter, num_epoch=3,\n"
                     "        optimizer='sgd', optimizer_params={'learning_rate': 0.2},\n"
                     "        eval_metric='acc')"),
            ("md", "## Evaluate + checkpoint round trip"),
            ("code", "score = dict(mod.score(val_iter, mx.metric.create('acc')))\n"
                     "print('validation accuracy:', round(score['accuracy'], 3))\n"
                     "assert score['accuracy'] > 0.9"),
            ("code", "import tempfile, os\n"
                     "d = tempfile.mkdtemp()\n"
                     "mod.save_checkpoint(os.path.join(d, 'mlp'), 3)\n"
                     "sym2, arg, aux = mx.model.load_checkpoint(os.path.join(d, 'mlp'), 3)\n"
                     "mod2 = mx.mod.Module(sym2, context=mx.cpu())\n"
                     "mod2.bind(data_shapes=[('data', (64, 1, 8, 8))], for_training=False)\n"
                     "mod2.set_params(arg, aux)\n"
                     "score2 = dict(mod2.score(val_iter, mx.metric.create('acc')))\n"
                     "print('reloaded accuracy:', round(score2['accuracy'], 3))\n"
                     "assert abs(score2['accuracy'] - score['accuracy']) < 1e-6"),
            ("md", "## Next\n\nFor the TPU fast path use "
                   "`mxnet_tpu.trainer.FusedTrainer` (whole step = one "
                   "XLA program; `fit()`-shaped API) — see "
                   "`docs/how_to/perf.md`."),
        ])


def mf_notebook():
    return nb_of(
        "Recommenders demo 1: matrix factorization",
        "The `examples/recommenders` walkthrough as a notebook "
        "(reference: `example/recommenders/demo1-MF.ipynb`): learn "
        "user/item embeddings whose dot product predicts ratings on a "
        "synthetic low-rank matrix.  The script twins "
        "(`matrix_fact.py`, `implicit.py`) run the same models "
        "standalone; `implicit.py` adds negative sampling + ranking "
        "metrics.",
        [
            ("code", SETUP),
            ("code", "USERS, ITEMS, RANK = 200, 150, 6\n"
                     "gu = np.random.randn(USERS, RANK).astype('float32') * 0.7\n"
                     "gi = np.random.randn(ITEMS, RANK).astype('float32') * 0.7\n"
                     "users = np.random.randint(0, USERS, 20000)\n"
                     "items = np.random.randint(0, ITEMS, 20000)\n"
                     "ratings = (gu[users] * gi[items]).sum(1) + np.random.randn(20000).astype('float32') * 0.1\n"
                     "print('rating std:', ratings.std().round(2))"),
            ("md", "## Model: dot-product of embeddings\n\n"
                   "`Embedding` is an index-gather into a learned "
                   "table; the score is the dot of the two latent "
                   "vectors (LinearRegressionOutput = L2 loss)."),
            ("code", "user = sym.Variable('user'); item = sym.Variable('item')\n"
                     "u = sym.Embedding(user, input_dim=USERS, output_dim=RANK, name='user_embed')\n"
                     "v = sym.Embedding(item, input_dim=ITEMS, output_dim=RANK, name='item_embed')\n"
                     "pred = sym.sum(u * v, axis=1)\n"
                     "net = sym.LinearRegressionOutput(pred, sym.Variable('score_label'), name='score')"),
            ("code", "import logging; logging.basicConfig(level=logging.INFO)\n"
                     "it = mx.io.NDArrayIter({'user': users.astype('float32'), 'item': items.astype('float32')},\n"
                     "                       {'score_label': ratings}, batch_size=128, shuffle=True)\n"
                     "mod = mx.mod.Module(net, data_names=('user', 'item'), label_names=('score_label',))\n"
                     "mod.fit(it, num_epoch=8, optimizer='adam',\n"
                     "        optimizer_params={'learning_rate': 0.02},\n"
                     "        initializer=mx.init.Normal(0.1), eval_metric='rmse')"),
            ("code", "rmse = dict(mod.score(it, mx.metric.create('rmse')))['rmse']\n"
                     "print('train rmse:', round(rmse, 3))\n"
                     "assert rmse < 0.8"),
            ("md", "## Next\n\n`implicit.py` in this directory drops "
                   "the ratings: binary implicit feedback, negative "
                   "sampling (`negativesample.py`), pairwise AUC and "
                   "HitRate@10 (`recotools.py`)."),
        ])


def template_notebook():
    return nb_of(
        "Tutorial template",
        "Structure for new tutorials (parity: the reference's "
        "MXNetTutorialTemplate).  Keep this shape:\n\n"
        "1. **Title + one-paragraph promise** — what the reader can do "
        "afterwards.\n"
        "2. **Prerequisites** — what must already work, with links.\n"
        "3. **Setup cell** — imports, seeds, platform pin (copy the "
        "one below).\n"
        "4. **Sections** — alternate a markdown explanation with the "
        "smallest runnable code cell that proves it.\n"
        "5. **Assertions** — tutorials are CI'd "
        "(tests/test_examples_smoke.py re-executes them): every claim "
        "a cell makes should be asserted, not narrated.\n"
        "6. **Next steps** — where the reader goes from here.",
        [
            ("code", SETUP),
            ("md", "## Section heading\n\nOne idea per section.  Say "
                   "what the next cell shows and why it matters."),
            ("code", "# the smallest code that demonstrates the idea\n"
                     "a = nd.ones((2, 2))\n"
                     "assert a.asnumpy().sum() == 4.0\n"
                     "print('claims are asserted, not narrated')"),
            ("md", "## Next steps\n\nLink the tutorials and docs that "
                   "build on this one."),
        ])


def build(execute=True):
    # MXTPU_NOTEBOOK_OUT redirects the written files (the smoke test
    # re-executes into a scratch tree so volatile outputs — timings,
    # temp paths — never dirty the committed notebooks)
    root = os.environ.get("MXTPU_NOTEBOOK_OUT", REPO)
    out = {
        os.path.join(root, "examples", "notebooks",
                     "basics_ndarray_symbol.ipynb"): basics_notebook(),
        os.path.join(root, "examples", "notebooks",
                     "train_mnist_module.ipynb"): mnist_notebook(),
        os.path.join(root, "examples", "notebooks",
                     "TutorialTemplate.ipynb"): template_notebook(),
        os.path.join(root, "examples", "recommenders",
                     "demo1-MF.ipynb"): mf_notebook(),
    }
    for path, nb in out.items():
        os.makedirs(os.path.dirname(path), exist_ok=True)
        if execute:
            client = NotebookClient(nb, timeout=600,
                                    resources={"metadata": {
                                        "path": os.path.dirname(path)}})
            client.execute()
        nbformat.write(nb, path)
        print("wrote", os.path.relpath(path, REPO), flush=True)


if __name__ == "__main__":
    os.environ.setdefault("MXTPU_PLATFORM", "cpu")
    # the jupyter KERNEL is a child process: it needs the repo on
    # PYTHONPATH (sys.path edits here don't reach it)
    os.environ["PYTHONPATH"] = REPO + os.pathsep + \
        os.environ.get("PYTHONPATH", "")
    sys.path.insert(0, REPO)
    build(execute="--no-execute" not in sys.argv)
