"""Probe: xprof A/B of the framework ResNet-50 train step vs the raw-JAX
NHWC probe step at the SAME batch, one session, one chip state.

probe_gap.py shows the framework's compiled b32 step is heavier than the
raw ceiling (14.1 vs 11.6 ms in the r05 window) — a delta that is by
construction framework HLO, not roofline.  This dumps the top HLO ops by
self time for each side so the delta can be attributed (layout transposes,
master-weight casts, BN stat traffic, optimizer fusion misses).

Run on the bench chip:  python tools/probe_gap_profile.py [batch]
"""
import glob
import os
import shutil
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

BATCH = int(sys.argv[1]) if len(sys.argv) > 1 else 32
LOGBASE = "/tmp/mxtpu_gapprof"


def _capture(tag, stepper, barrier):
    logdir = os.path.join(LOGBASE, tag)
    shutil.rmtree(logdir, ignore_errors=True)
    import jax

    for _ in range(5):
        stepper()
    barrier()
    with jax.profiler.trace(logdir):
        for _ in range(10):
            stepper()
        barrier()
    return glob.glob(os.path.join(logdir, "**", "*.xplane.pb"), recursive=True)


def _top_ops(xplanes, n=22):
    """Sum self-time per HLO op name over the capture; return the top n."""
    from xprof.convert import raw_to_tool_data as rtd

    data, _ = rtd.xspace_to_tool_data(xplanes, "hlo_stats", {})
    if isinstance(data, (bytes, bytearray)):
        data = data.decode("utf-8", "replace")
    import json

    table = json.loads(data)
    # google-viz table: {"cols": [{label}], "rows": [{"c": [{"v"}]}]};
    # locate columns by label so a schema shuffle can't mis-attribute
    labels = [c.get("label", "") for c in table["cols"]]
    icat = labels.index("HLO op category")
    itime = labels.index("Total self time (us)")
    agg = {}
    for row in table["rows"]:
        cells = row["c"]
        try:
            t = float(cells[itime]["v"])
            cat = str((cells[icat] or {}).get("v"))  # gviz null cells
        except (TypeError, ValueError, KeyError, IndexError, AttributeError):
            continue
        agg[cat] = agg.get(cat, 0.0) + t
    total = sum(agg.values()) or 1.0
    out = sorted(agg.items(), key=lambda kv: -kv[1])[:n]
    return [(cat, t, t / total) for cat, t in out], total


def framework():
    import jax
    import jax.numpy as jnp

    from mxnet_tpu import models
    from mxnet_tpu.trainer import FusedTrainer

    net = models.get_symbol("resnet-50", num_classes=1000)
    tr = FusedTrainer(net, optimizer="sgd",
                      optimizer_params={"lr": 0.1, "momentum": 0.9,
                                        "rescale_grad": 1.0 / BATCH},
                      dtype=jnp.bfloat16)
    tr.init(data=(BATCH, 3, 224, 224))
    rs = np.random.RandomState(0)
    batch = {"data": jax.device_put(
        rs.uniform(0, 1, (BATCH, 3, 224, 224)).astype(np.float32)),
        "softmax_label": jax.device_put(
            rs.randint(0, 1000, BATCH).astype(np.float32))}
    pname = sorted(tr.params)[0]
    return (lambda: tr.step(**batch),
            lambda: float(np.asarray(tr.params[pname]).ravel()[0]))


def raw():
    import importlib.util

    import jax.numpy as jnp

    spec = importlib.util.spec_from_file_location(
        "probe_nhwc", os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "probe_nhwc.py"))
    probe_nhwc = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(probe_nhwc)
    rng = np.random.RandomState(0)
    params = probe_nhwc.make_params("NHWC", rng)
    mom = {k: jnp.zeros(v.shape, jnp.float32) for k, v in params.items()}
    x = jnp.asarray(rng.uniform(0, 1, (BATCH, 224, 224, 3)), jnp.bfloat16)
    y = jnp.asarray(rng.randint(0, 1000, BATCH), jnp.int32)
    state = {"p": params, "m": mom, "loss": None}

    def stepper():
        state["p"], state["m"], state["loss"] = probe_nhwc.train_step(
            state["p"], state["m"], x, y, "NHWC")

    return stepper, lambda: float(np.asarray(state["loss"]))


def main():
    import jax

    print("devices:", jax.devices(), flush=True)
    sides = {}
    for tag, build in (("framework", framework), ("raw", raw)):
        stepper, barrier = build()
        xplanes = _capture(tag, stepper, barrier)
        if not xplanes:
            print(tag, "capture produced no xplane files")
            return
        sides[tag], total = _top_ops(xplanes)
        print(f"\n== {tag} b{BATCH}: device self-time by HLO category "
              f"(total {total / 1e3:.2f} ms over capture) ==", flush=True)
        for cat, t, frac in sides[tag]:
            print(f"  {t / 1e3:8.2f} ms  {frac * 100:5.1f}%  {cat}")
    # the diff the probe exists for: categories where the framework spends
    # materially more device time than the raw step
    fw = dict((c, t) for c, t, _ in sides["framework"])
    rw = dict((c, t) for c, t, _ in sides["raw"])
    print("\n== framework minus raw (ms over capture; +ve = framework heavier) ==")
    for cat in sorted(set(fw) | set(rw),
                      key=lambda c: -(fw.get(c, 0.0) - rw.get(c, 0.0))):
        d = fw.get(cat, 0.0) - rw.get(cat, 0.0)
        if abs(d) > 100:  # > 0.1 ms over the 10-step capture
            print(f"  {d / 1e3:+8.2f} ms  {cat}")


if __name__ == "__main__":
    main()
