"""Probe: transformer-LM training MFU on the real chip.

ResNet-50-with-BN is HBM-bound on v5e (docs/measured/probe_nhwc_r04.txt
caps at ~0.175 MFU), so the framework's compute-bound headline is the
transformer LM: big matmuls (qkv/proj/ffn/head) dominate and the MXU can
actually be fed.  This probe sweeps model/batch configs through the SAME
FusedTrainer + symbol path bench.py uses (no hand-written raw-JAX model)
and reports model-FLOP MFU per config.

FLOP accounting (conservative, causal-halved):
  train FLOPs/token = 6*N_mat + 6*L*T*D
where N_mat counts matmul params only (embedding gathers are free) —
the standard 6N rule with flash attention's causal block skipping
(ops/flash_attention.py:48-63) counted at half the full T^2 cost.

Run on the bench chip:  python tools/probe_lm_mfu.py
CPU smoke:  MXTPU_PLATFORM=cpu python tools/probe_lm_mfu.py --smoke
"""
import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

PEAK_BF16 = 197e12  # v5e dense bf16 peak (bench.py table)


def lm_train_flops_per_token(L, D, d_ff, T, V):
    # the one shared accounting rule (models/transformer.py) — bench.py's
    # transformer_lm_mfu extra uses the same function
    from mxnet_tpu.models.transformer import lm_train_flops_per_token as f

    return f(L, D, d_ff, T, V)


def run_config(name, L, H, D, d_ff, T, V, B, iters=12, peak=PEAK_BF16):
    import jax
    import jax.numpy as jnp

    from mxnet_tpu import models
    from mxnet_tpu.trainer import FusedTrainer

    lm = models.transformer.transformer_lm(
        num_layers=L, num_heads=H, d_model=D, d_ff=d_ff, seq_len=T,
        vocab_size=V)
    tr = FusedTrainer(lm, optimizer="adam", optimizer_params={"lr": 1e-4},
                      dtype=jnp.bfloat16)
    tr.init(data=(B, T), softmax_label=(B, T))
    rs = np.random.RandomState(0)
    toks = jax.device_put(rs.randint(0, V, (B, T)).astype(np.float32))
    labs = jax.device_put(rs.randint(0, V, (B, T)).astype(np.float32))
    pname = sorted(tr.params)[0]

    def barrier():
        return float(np.asarray(tr.params[pname]).ravel()[0])

    tr.step(data=toks, softmax_label=labs)  # compile
    barrier()
    tr.step(data=toks, softmax_label=labs)  # settle
    barrier()
    tic = time.perf_counter()
    for _ in range(iters):
        tr.step(data=toks, softmax_label=labs)
    barrier()
    dt = time.perf_counter() - tic
    tok_s = B * T * iters / dt
    fpt = lm_train_flops_per_token(L, D, d_ff, T, V)
    mfu = tok_s * fpt / peak
    print(f"{name}: L{L} H{H} D{D} ff{d_ff} T{T} V{V} B{B}  "
          f"{tok_s:9.0f} tok/s  {tok_s * fpt / 1e12:6.1f} TF/s  "
          f"mfu={mfu:.3f}", flush=True)
    return mfu


def run_one_subprocess(name, cfg, iters, extra_env=None, timeout=420):
    """One config in its own process: a failed/OOMed config must not
    poison the rest of the sweep (the first on-silicon capture lost 3
    configs to a RESOURCE_EXHAUSTED cascade after one real OOM — the
    tunnel backend does not reliably free buffers across configs)."""
    env = dict(os.environ)
    env.update(extra_env or {})
    spec = json.dumps({"name": name, "cfg": cfg, "iters": iters})
    try:
        r = subprocess.run([sys.executable, os.path.abspath(__file__),
                            "--one", spec], env=env, capture_output=True,
                           text=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        print(f"{name}: FAILED timeout", flush=True)
        return 0.0
    for line in r.stdout.splitlines():
        if "mfu=" in line:
            print(line, flush=True)
            return float(line.rsplit("mfu=", 1)[1])
    tail = (r.stdout + r.stderr).strip().splitlines()
    print(f"{name}: FAILED {tail[-1] if tail else 'no output'}", flush=True)
    return 0.0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny config on cpu (plumbing check only)")
    ap.add_argument("--iters", type=int, default=12)
    ap.add_argument("--one", type=str, default=None,
                    help="(internal) JSON spec: run one config and exit")
    args = ap.parse_args()

    if args.one:
        spec = json.loads(args.one)
        run_config(spec["name"], iters=spec["iters"], **spec["cfg"])
        return

    if os.environ.get("MXTPU_PLATFORM") == "cpu" or args.smoke:
        import jax

        jax.config.update("jax_platforms", "cpu")
        run_config("smoke", L=2, H=4, D=128, d_ff=512, T=128, V=512, B=2,
                   iters=3)
        return

    import jax

    print("devices:", jax.devices(), flush=True)
    from mxnet_tpu.models.transformer import MFU_HEADLINE_CONFIG as HC

    head = dict(L=HC["num_layers"], H=HC["num_heads"], D=HC["d_model"],
                d_ff=HC["d_ff"], T=HC["seq_len"], V=HC["vocab_size"])
    # medium-first: if the big config OOMs or hangs, the smaller numbers
    # are already on stdout
    configs = [
        ("lm-560m-b8",  dict(head, B=8)),   # bench.py's headline config
        ("lm-220m-b8",  dict(L=12, H=16, D=1024, d_ff=4096, T=1024,
                             V=32768, B=8)),
        ("lm-220m-b16", dict(L=12, H=16, D=1024, d_ff=4096, T=1024,
                             V=32768, B=16)),
        ("lm-small-b8", dict(L=4, H=8, D=512, d_ff=2048, T=512,
                             V=8192, B=8)),  # bench.py extras continuity
    ]
    best = (None, 0.0, None)
    for name, cfg in configs:
        mfu = run_one_subprocess(name, cfg, args.iters)
        if mfu > best[1]:
            best = (name, mfu, cfg)
    print(f"best: {best[0]} mfu={best[1]:.3f}", flush=True)

    # flash-attention tile sweep on the winner (MXTPU_FLASH_BLOCK_Q/K
    # are read at trace time, so each setting builds a fresh trainer)
    if best[2] is not None:
        tile_best = ("128x128", best[1])
        for bq, bk in ((256, 256), (128, 512), (512, 128)):
            mfu = run_one_subprocess(
                f"{best[0]}-blk{bq}x{bk}", best[2], args.iters,
                extra_env={"MXTPU_FLASH_BLOCK_Q": str(bq),
                           "MXTPU_FLASH_BLOCK_K": str(bk)})
            if mfu > tile_best[1]:
                tile_best = (f"{bq}x{bk}", mfu)
        print(f"best-tiles: {best[0]} blk{tile_best[0]} "
              f"mfu={tile_best[1]:.3f}", flush=True)


if __name__ == "__main__":
    main()
