"""One-off probe: chip peak sanity + conv layout comparison (NCHW vs NHWC).

Times (a) a big bf16 matmul against the v5e's 197 TFLOP/s peak, (b) a
ResNet-50-style conv tower forward+backward in NCHW vs NHWC dimension
numbers, to find where the MFU is going.
"""
import time

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, *args, iters=20):
    fn(*args)[0].block_until_ready() if isinstance(fn(*args), tuple) else None
    out = fn(*args)
    jax.block_until_ready(out)
    tic = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    # pull one byte to defeat any dispatch-side ack
    np.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[:1]
    return (time.perf_counter() - tic) / iters


def matmul_peak():
    n = 8192
    a = jnp.ones((n, n), jnp.bfloat16)
    b = jnp.ones((n, n), jnp.bfloat16)
    f = jax.jit(lambda x, y: x @ y)
    dt = timeit(f, a, b)
    tf = 2 * n**3 / dt / 1e12
    print(f"matmul {n}x{n} bf16: {dt*1e3:.2f} ms, {tf:.1f} TFLOP/s")


def conv_tower(layout):
    # a mid-network ResNet block shape at batch 256
    if layout == "NCHW":
        dn = ("NCHW", "OIHW", "NCHW")
        x = jnp.ones((256, 256, 28, 28), jnp.bfloat16)
        w1 = jnp.ones((256, 256, 3, 3), jnp.bfloat16)
    else:
        dn = ("NHWC", "HWIO", "NHWC")
        x = jnp.ones((256, 28, 28, 256), jnp.bfloat16)
        w1 = jnp.ones((3, 3, 256, 256), jnp.bfloat16)

    def f(x, w):
        def body(x):
            for _ in range(8):
                x = jax.lax.conv_general_dilated(
                    x, w, (1, 1), "SAME", dimension_numbers=dn)
                x = jax.nn.relu(x)
            return jnp.sum(x.astype(jnp.float32))

        l, g = jax.value_and_grad(body)(x)
        return l, g

    jf = jax.jit(f)
    dt = timeit(jf, x, w1, iters=10)
    flops = 8 * 2 * 256 * 28 * 28 * 256 * 256 * 9 * 3  # fwd+2bwd
    print(f"conv tower {layout}: {dt*1e3:.2f} ms, {flops/dt/1e12:.1f} TFLOP/s model")


if __name__ == "__main__":
    print(jax.devices())
    matmul_peak()
    conv_tower("NCHW")
    conv_tower("NHWC")
