"""Inference throughput through the predict path (the C-ABI surface).

The reference's headline inference table (docs/how_to/perf.md:69-98)
is measured through its predictor, not the training executor's eval
graph.  This tool does the same here: build a ResNet-50 checkpoint,
load it with mxnet_tpu.predict (the module `src/c_predict.cc` embeds —
the perl/C clients call exactly this code), and time forward at batch
1 and 32.

Run on the bench chip:  python tools/bench_predict.py
CPU smoke:              MXTPU_PLATFORM=cpu python tools/bench_predict.py \
                            --model mlp --iters 20
"""
import argparse
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def build_checkpoint(model, prefix):
    import mxnet_tpu as mx
    from mxnet_tpu import models, sym

    if model == "resnet-50":
        net = models.get_symbol("resnet-50", num_classes=1000)
        data_shape = (3, 224, 224)
    else:
        net = sym.SoftmaxOutput(
            sym.FullyConnected(sym.Activation(sym.FullyConnected(
                sym.Variable("data"), num_hidden=64, name="fc1"),
                act_type="relu"), num_hidden=10, name="fc2"),
            sym.Variable("softmax_label"), name="softmax")
        data_shape = (32,)

    ex = net.simple_bind(ctx=mx.cpu(), data=(1,) + data_shape)
    np.random.seed(0)
    init = mx.init.Xavier()
    arg_params = {}
    for name, arr in ex.arg_dict.items():
        if name not in ("data", "softmax_label"):
            init(name, arr)
            arg_params[name] = arr
    aux_params = {k: v for k, v in ex.aux_dict.items()}
    mx.model.save_checkpoint(prefix, 0, net, arg_params, aux_params)
    return data_shape


def bench_batch(prefix, data_shape, batch, iters, dev_type, dtype=None):
    """Three disciplines over the same predictor:

    dispatch —  forward xN, one trailing fetch (upload-bound ceiling)
    serial   —  forward + get_output every call (the naive client loop:
                full upload+compute+fetch round trip per sample)
    overlap  —  forward_async/get_async, 4 tickets in flight (the
                transport-hiding path; uploads, computes and fetches of
                consecutive calls pipeline)
    """
    from mxnet_tpu import predict

    p = predict.create(prefix, 0, {"data": (batch,) + data_shape},
                       dev_type=dev_type, dtype=dtype)
    x = np.random.RandomState(0).uniform(
        0, 1, (batch,) + data_shape).astype(np.float32)
    p.forward(data=x)
    np.asarray(p.get_output(0))  # compile + settle; fetch = real barrier
    res = {}
    tic = time.perf_counter()
    for _ in range(iters):
        p.forward(data=x)
    np.asarray(p.get_output(0))
    res["dispatch"] = batch * iters / (time.perf_counter() - tic)

    tic = time.perf_counter()
    for _ in range(iters):
        p.forward(data=x)
        np.asarray(p.get_output(0))
    res["serial"] = batch * iters / (time.perf_counter() - tic)

    depth = 4
    tic = time.perf_counter()
    pending = []
    for _ in range(iters):
        pending.append(p.forward_async(data=x))
        if len(pending) >= depth:
            p.get_async(pending.pop(0))
    while pending:
        p.get_async(pending.pop(0))
    res["overlap"] = batch * iters / (time.perf_counter() - tic)
    return res


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet-50",
                    choices=["resnet-50", "mlp"])
    ap.add_argument("--iters", type=int, default=50)
    ap.add_argument("--batches", type=int, nargs="+", default=[1, 32])
    ap.add_argument("--dtype", default=None, choices=["bfloat16"],
                    help="inference compute precision (bf16 casts fuse "
                         "into the compiled program)")
    args = ap.parse_args()

    platform = os.environ.get("MXTPU_PLATFORM")
    if platform == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
        dev_type = "cpu"
    else:
        dev_type = "tpu"

    with tempfile.TemporaryDirectory() as tmp:
        prefix = os.path.join(tmp, "m")
        data_shape = build_checkpoint(args.model, prefix)
        print(f"predict-path throughput: {args.model}, dev={dev_type} "
              f"(P100 predictor baselines: b1 113.76, b32 713.17 img/s)")
        for b in args.batches:
            res = bench_batch(prefix, data_shape, b, args.iters, dev_type,
                              dtype=args.dtype)
            rate = res["dispatch"]
            line = f"predict_b{b}: {rate:.1f} img/s"
            if args.model == "resnet-50":
                base = 113.76 if b == 1 else (713.17 if b == 32 else None)
                if base:
                    line += f"  ({rate / base:.2f}x P100 predictor)"
            line += (f"   serial {res['serial']:.1f}"
                     f"   overlap(d4) {res['overlap']:.1f}"
                     f"   [{res['overlap'] / max(res['serial'], 1e-9):.2f}x]")
            print(line, flush=True)


if __name__ == "__main__":
    main()
