#!/usr/bin/env python
"""Data-pipeline throughput benchmark.

Parity target: the reference documents >1K images decoded per second with
4 decode threads (docs/how_to/perf.md:161, "Data IO" section) for the
ImageRecordIter path.  This tool measures the same stages on this
framework:

  1. recordio read      — native frame scanner (src/recordio.cc)
  2. jpeg decode        — PIL/libjpeg in worker processes or threads
  3. decode + augment   — resize/crop pipeline (image.py ImageIter)

Usage: python tools/bench_io.py [--n 2000] [--threads 4] [--size 224]
Prints one line per stage: images/s.
"""
import argparse
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np  # noqa: E402


def make_record_file(path, n, side=256):
    """Write n synthetic jpeg records (label + jpeg payload)."""
    from mxnet_tpu import recordio
    from mxnet_tpu.image import imencode

    rs = np.random.RandomState(0)
    writer = recordio.MXRecordIO(path, "w")
    # a realistic photographic-complexity image compresses to ~20-40KB
    base = rs.randint(0, 255, (side, side, 3)).astype(np.uint8)
    for i in range(n):
        # vary content a little so decode work is not degenerate
        img = np.roll(base, i % side, axis=0)
        payload = recordio.pack_img(
            recordio.IRHeader(0, float(i % 1000), i, 0), img, quality=90)
        writer.write(payload)
    writer.close()


def bench_read(path, n):
    from mxnet_tpu import recordio

    reader = recordio.MXRecordIO(path, "r")
    tic = time.perf_counter()
    count = 0
    while True:
        rec = reader.read()
        if rec is None:
            break
        count += 1
    dt = time.perf_counter() - tic
    reader.close()
    return count / dt


def bench_raw_decode(path, threads):
    """Pure jpeg decode through the iterator's worker pool — the stage the
    reference's >1K img/s @ 4 threads figure measures."""
    from concurrent.futures import ThreadPoolExecutor

    from mxnet_tpu import recordio
    from mxnet_tpu.image import imdecode_np

    reader = recordio.MXRecordIO(path, "r")
    payloads = []
    while True:
        rec = reader.read()
        if rec is None:
            break
        payloads.append(recordio.unpack(rec)[1])
    reader.close()
    pool = ThreadPoolExecutor(max_workers=threads)
    list(pool.map(imdecode_np, payloads[:64]))  # warmup
    tic = time.perf_counter()
    list(pool.map(imdecode_np, payloads))
    dt = time.perf_counter() - tic
    pool.shutdown()
    return len(payloads) / dt


def bench_pipeline(path, threads, size):
    """Full ImageRecordIter path: shard read -> decode -> augment -> batch."""
    from mxnet_tpu import image as img_mod

    it = img_mod.ImageRecordIter(
        path_imgrec=path, data_shape=(3, size, size), batch_size=50,
        preprocess_threads=threads, shuffle=False)
    next(iter(it))  # warmup (thread spin-up)
    it.reset()
    tic = time.perf_counter()
    count = 0
    for batch in it:
        count += batch.data[0].shape[0]
    dt = time.perf_counter() - tic
    return count / dt


def bench_device_prefetch(path, threads, size, depth=2):
    """Full stacked pipeline: ImageRecordIter -> PrefetchingIter ->
    DevicePrefetchIter, consumed by a simulated compute step — measures
    the rate the TRAINER sees with host prep AND device staging
    overlapped."""
    import jax

    from mxnet_tpu import image as img_mod, io as mio

    it = mio.DevicePrefetchIter(
        mio.PrefetchingIter(img_mod.ImageRecordIter(
            path_imgrec=path, data_shape=(3, size, size), batch_size=50,
            preprocess_threads=threads, shuffle=False)),
        depth=depth)
    batch = next(iter(it))  # warmup
    jax.block_until_ready(batch.data[0].jax_array)
    tic = time.perf_counter()
    count = 0
    for batch in it:
        # a consumer touch per batch (sum) stands in for the train step
        jax.block_until_ready(batch.data[0].jax_array.sum())
        count += batch.data[0].shape[0]
    dt = time.perf_counter() - tic
    return count / dt


def bench_mp_pipeline(path, workers, size, batches=30):
    """Sharded-host multi-process pipeline: N decode processes ->
    shared-memory ring -> this process staging to device
    (mp_io.MultiProcessImageRecordIter).  The process fan-out is the
    scale-out answer where thread counts stop helping (GIL/allocator
    contention on the python stages)."""
    from mxnet_tpu.image import MultiProcessImageRecordIter

    it = MultiProcessImageRecordIter(
        path_imgrec=path, data_shape=(3, size, size), batch_size=50,
        num_workers=workers, stall_timeout=180)
    try:
        src = iter(it)
        next(src)  # worker spin-up + first decode out of the timing
        tic = time.perf_counter()
        count = 0
        for batch in src:
            count += batch.data[0].shape[0]
            if count >= batches * 50:
                break
        dt = time.perf_counter() - tic
        return count / dt
    finally:
        it.close()


def sweep(args):
    """Thread-scaling table + host-CPU ceiling model."""
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "bench.rec")
        make_record_file(path, args.n)
        ncores = os.cpu_count() or 1
        print(f"io scaling sweep: n={args.n} images, host cores={ncores}")
        print(f"{'threads':>8} {'decode img/s':>13} {'pipeline img/s':>15} "
              f"{'staged img/s':>13}")
        per_thread = []
        for t in args.sweep:
            dec = bench_raw_decode(path, t)
            pipe = bench_pipeline(path, t, args.size)
            staged = bench_device_prefetch(path, t, args.size)
            per_thread.append((t, dec, pipe, staged))
            print(f"{t:>8} {dec:>13.0f} {pipe:>15.0f} {staged:>13.0f}")
        best_dec = max(d for _, d, _, _ in per_thread)
        best_pipe = max(p for _, _, p, _ in per_thread)
        # ceiling model: decode is GIL-free native libjpeg, so it scales
        # with PHYSICAL cores; this box's core count bounds what any
        # thread count can show
        print(f"host_cores: {ncores}")
        print(f"best_decode_img_s: {best_dec:.0f}")
        print(f"best_pipeline_img_s: {best_pipe:.0f}")
        chip_demand = 5600  # ResNet-50 img/s at MFU 0.35 on v5e
        need = chip_demand / max(best_pipe, 1.0)
        print(f"chip_demand_img_s: {chip_demand}")
        print(f"hosts_or_core_multiple_needed: {need:.1f}")


def main():
    # the host pipeline is what's being measured; on a box whose
    # accelerator plugin can hang at init (the axon plugin ignores
    # JAX_PLATFORMS), pin the cpu platform before any staging runs
    if os.environ.get("MXTPU_PLATFORM", "cpu") == "cpu":
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:  # noqa: BLE001 — a backend already won the race
            pass
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=2000)
    ap.add_argument("--threads", type=int, default=4)
    ap.add_argument("--size", type=int, default=224)
    ap.add_argument("--sweep", type=int, nargs="*", default=None,
                    help="measure a thread-scaling table at these "
                         "thread counts (e.g. --sweep 1 2 4 8)")
    args = ap.parse_args()
    if args.sweep is not None:
        args.sweep = args.sweep or [1, 2, 4, 8]
        return sweep(args)

    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "bench.rec")
        make_record_file(path, args.n)
        rec_rate = bench_read(path, args.n)
        print("recordio_read: %.0f rec/s" % rec_rate)
        dec_rate = bench_raw_decode(path, args.threads)
        print("decode(threads=%d): %.0f img/s" % (args.threads, dec_rate))
        pipe_rate = bench_pipeline(path, args.threads, args.size)
        print("pipeline(threads=%d): %.0f img/s" % (args.threads, pipe_rate))
        # the same pipeline with the host staging arena disabled — shows
        # what pooled batch buffers buy (storage.py stage_to_device)
        from mxnet_tpu import storage

        print("pipeline_pool_bytes: %d" % storage.pool_bytes())
        with storage.pooling_disabled():
            nopool_rate = bench_pipeline(path, args.threads, args.size)
        print("pipeline_no_pool(threads=%d): %.0f img/s" %
              (args.threads, nopool_rate))
        target = 1000.0
        print("target_1k_met: %s" % ("yes" if dec_rate >= target else "no"))
        for w in (1, 2, 4):
            mp_rate = bench_mp_pipeline(path, w, args.size)
            print("mp_pipeline(workers=%d): %.0f img/s" % (w, mp_rate))


if __name__ == "__main__":
    main()
