"""Probe: decompose the ResNet-50 train-step HBM ceiling.

Variants of the raw-JAX NHWC step (tools/probe_nhwc.py):
  base      - the probe_nhwc step as-is (bf16 compute, f32 BN stats)
  bf16stats - BN statistics accumulated straight from bf16 activations
              (jnp.sum(..., dtype=f32): reads stay bf16, accumulator f32)
  nobn      - BN replaced by a per-channel scale+shift (no batch stats):
              the upper bound showing what the stats passes cost
  b512      - base at batch 512 (does more batch amortize anything left?)

Interpretation: if nobn >> base, the BN stat/normalize passes are the
HBM traffic to attack; if bf16stats ~= base, XLA already fuses the f32
casts into the reductions and there is nothing left on that axis.

Run on a chip: python tools/probe_resnet_variants.py
"""
import os
import sys
import time
from functools import partial

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from tools.probe_nhwc import STAGES, conv, make_params

PEAK = 197e12


def bn_variant(x, gamma, beta, mode):
    c = x.shape[3]
    shape = (1, 1, 1, -1)
    if mode == "nobn":
        return x * gamma.reshape(shape).astype(x.dtype) \
            + beta.reshape(shape).astype(x.dtype)
    n = x.size // c
    if mode == "bf16stats":
        mean = jnp.sum(x, (0, 1, 2), dtype=jnp.float32) / n
        var = jnp.maximum(
            jnp.sum(jnp.square(x), (0, 1, 2), dtype=jnp.float32) / n
            - jnp.square(mean), 0.0)
    else:  # base
        x32 = x.astype(jnp.float32)
        mean = jnp.sum(x32, (0, 1, 2)) / n
        var = jnp.maximum(jnp.sum(jnp.square(x32), (0, 1, 2)) / n
                          - jnp.square(mean), 0.0)
    out = (x.astype(jnp.float32) - mean.reshape(shape)) \
        * jax.lax.rsqrt(var.reshape(shape) + 1e-3)
    return (out * gamma.reshape(shape) + beta.reshape(shape)).astype(x.dtype)


def forward(params, x, mode):
    x = conv(x, params["stem"], 2, "NHWC")
    x = jax.nn.relu(bn_variant(x, params["stem_g"], params["stem_b"], mode))
    x = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, (1, 3, 3, 1),
                              (1, 2, 2, 1),
                              [(0, 0), (1, 1), (1, 1), (0, 0)])
    cin = 64
    for si, (blocks, cout) in enumerate(STAGES):
        for bi in range(blocks):
            p = f"s{si}b{bi}"
            stride = 2 if (bi == 0 and si > 0) else 1
            sc = x
            if cin != cout:
                sc = conv(x, params[p + "proj"], stride, "NHWC")
            h = jax.nn.relu(bn_variant(
                conv(x, params[p + "c1"], 1, "NHWC"),
                params[p + "g1"], params[p + "b1"], mode))
            h = jax.nn.relu(bn_variant(
                conv(h, params[p + "c2"], stride, "NHWC"),
                params[p + "g2"], params[p + "b2"], mode))
            h = bn_variant(conv(h, params[p + "c3"], 1, "NHWC"),
                           params[p + "g3"], params[p + "b3"], mode)
            x = jax.nn.relu(h + sc)
            cin = cout
    x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
    return x.astype(jnp.bfloat16) @ params["fc"]


def loss_fn(params, x, y, mode):
    logits = forward(params, x, mode).astype(jnp.float32)
    return jnp.mean(-jax.nn.log_softmax(logits)[jnp.arange(x.shape[0]), y])


@partial(jax.jit, static_argnames=("mode",), donate_argnums=(0, 1))
def train_step(params, mom, x, y, mode):
    loss, grads = jax.value_and_grad(loss_fn)(params, x, y, mode)
    new_p, new_m = {}, {}
    for k, g in grads.items():
        m = mom[k] * 0.9 + g.astype(jnp.float32)
        new_m[k] = m
        new_p[k] = (params[k].astype(jnp.float32) - 0.1 * m).astype(
            params[k].dtype)
    return new_p, new_m, loss


def run(mode, batch, iters=30):
    rng = np.random.RandomState(0)
    params = make_params("NHWC", rng)
    mom = {k: jnp.zeros(v.shape, jnp.float32) for k, v in params.items()}
    x = jnp.asarray(rng.uniform(0, 1, (batch, 224, 224, 3)), jnp.bfloat16)
    y = jnp.asarray(rng.randint(0, 1000, batch), jnp.int32)
    for _ in range(5):
        params, mom, loss = train_step(params, mom, x, y, mode)
    _ = float(np.asarray(loss))
    tic = time.perf_counter()
    for _ in range(iters):
        params, mom, loss = train_step(params, mom, x, y, mode)
    _ = float(np.asarray(loss))
    dt = time.perf_counter() - tic
    img_s = batch * iters / dt
    mfu = img_s * 3 * 4.089e9 / PEAK
    print(f"{mode:10s} b{batch}: {img_s:8.1f} img/s   mfu={mfu:.3f}",
          flush=True)


if __name__ == "__main__":
    print("devices:", jax.devices(), flush=True)
    for mode in ("base", "bf16stats", "nobn"):
        run(mode, 128)
    run("base", 512, iters=12)
