"""Model-family training/forward throughput capture — ONE script that
re-measures every perf.md model-family number so each published figure
has a committed raw artifact (docs/measured/bench_models_r*.txt).

Covers (bf16, one chip, device-staged synthetic data, fetch-barrier
timing — the bench.py discipline):
  inception-v3   train b32            (reference perf.md:132-139 P100
                                       129.98 img/s)
  lstm-ptb       train 2x200 seq35    (example/rnn/lstm_bucketing.py
                 b32 vocab10k          config)
  ssd-vgg16-300  forward b32          (reference example/ssd)
  transformer-lm train 12L d512 T1024 (beyond-reference family)
                 b8 flash-attention

Run on the bench chip:  python tools/bench_models.py [--iters N]
CPU smoke:  MXTPU_PLATFORM=cpu python tools/bench_models.py --smoke
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _timed(tr, feed, iters, warmup=3):
    import numpy as _np

    pname = sorted(tr.params)[0]

    def barrier():
        return float(_np.asarray(tr.params[pname]).ravel()[0])

    for _ in range(warmup):
        tr.step(**feed)
    barrier()
    tic = time.perf_counter()
    for _ in range(iters):
        tr.step(**feed)
    barrier()
    return time.perf_counter() - tic


def bench_inception(iters, smoke=False):
    import jax
    import jax.numpy as jnp

    from mxnet_tpu import models
    from mxnet_tpu.trainer import FusedTrainer

    net = models.get_symbol("inception-v3", num_classes=1000)
    b = 32 if not smoke else 1
    tr = FusedTrainer(net, optimizer="sgd",
                      optimizer_params={"lr": 0.1, "momentum": 0.9,
                                        "rescale_grad": 1.0 / b},
                      dtype=jnp.bfloat16)
    tr.init(data=(b, 3, 299, 299))
    rs = np.random.RandomState(0)
    feed = {"data": jax.device_put(
        rs.uniform(0, 1, (b, 3, 299, 299)).astype(np.float32)),
        "softmax_label": jax.device_put(
            rs.randint(0, 1000, b).astype(np.float32))}
    dt = _timed(tr, feed, iters)
    print(f"inception_v3_train_b{b}: {b * iters / dt:.1f} img/s "
          f"({dt / iters * 1e3:.1f} ms/step)", flush=True)


def bench_lstm_ptb(iters, smoke=False):
    import jax
    import jax.numpy as jnp

    from mxnet_tpu import models
    from mxnet_tpu.trainer import FusedTrainer

    from mxnet_tpu.models import lstm

    b, seq, hid = (32, 35, 200) if not smoke else (2, 8, 16)
    layers = 2
    net = lstm.get_symbol(num_classes=10000, seq_len=seq,
                          num_hidden=hid, num_embed=hid,
                          num_lstm_layer=layers)
    # the unrolled graph's initial c/h are DATA (zero-fed each step, the
    # example/rnn contract), not trainable params
    states = [f"l{i}_init_{s}" for i in range(layers) for s in "ch"]
    tr = FusedTrainer(net, data_names=("data", *states),
                      optimizer="sgd",
                      optimizer_params={"lr": 1.0, "rescale_grad": 1.0 / b},
                      dtype=jnp.bfloat16)
    shapes = {s: (b, hid) for s in states}
    tr.init(data=(b, seq), softmax_label=(b, seq), **shapes)
    rs = np.random.RandomState(0)
    zeros = jax.device_put(np.zeros((b, hid), np.float32))
    feed = {"data": jax.device_put(
        rs.randint(0, 10000, (b, seq)).astype(np.float32)),
        "softmax_label": jax.device_put(
            rs.randint(0, 10000, (b, seq)).astype(np.float32)),
        **{s: zeros for s in states}}
    dt = _timed(tr, feed, iters)
    print(f"lstm_ptb_train_tokens_per_sec: {b * seq * iters / dt:.0f} "
          f"({dt / iters * 1e3:.1f} ms/step)", flush=True)


def bench_ssd_forward(iters, smoke=False):
    import jax
    import jax.numpy as jnp

    from mxnet_tpu import models
    from mxnet_tpu.trainer import FusedTrainer

    b, hw = (32, 300) if not smoke else (1, 96)
    net = models.get_symbol("ssd-vgg16", num_classes=20)
    tr = FusedTrainer(net, optimizer="sgd", optimizer_params={"lr": 0.1},
                      dtype=jnp.bfloat16,
                      label_names=("label",))
    tr.init(data=(b, 3, hw, hw), label=(b, 8, 5))
    rs = np.random.RandomState(0)
    data = jax.device_put(
        rs.uniform(0, 1, (b, 3, hw, hw)).astype(np.float32))
    label = jax.device_put(np.full((b, 8, 5), -1.0, np.float32))
    # eval (forward-only) discipline: the published number is forward
    out = tr.eval(data=data, label=label)
    float(np.asarray(out[0]).ravel()[0])
    tic = time.perf_counter()
    for _ in range(iters):
        out = tr.eval(data=data, label=label)
    float(np.asarray(out[0]).ravel()[0])
    dt = time.perf_counter() - tic
    print(f"ssd_vgg16_300_fwd_b{b}: {b * iters / dt:.1f} img/s "
          f"({dt / iters * 1e3:.1f} ms/fwd)", flush=True)


def bench_transformer_lm(iters, smoke=False):
    import jax
    import jax.numpy as jnp

    from mxnet_tpu import models
    from mxnet_tpu.models.transformer import lm_train_flops_per_token
    from mxnet_tpu.trainer import FusedTrainer

    if smoke:
        L, H, D, T, V, b = 2, 2, 64, 64, 512, 2
    else:
        L, H, D, T, V, b = 12, 8, 512, 1024, 16000, 8
    lm = models.transformer.transformer_lm(
        num_layers=L, num_heads=H, d_model=D, seq_len=T, vocab_size=V)
    tr = FusedTrainer(lm, optimizer="adam", optimizer_params={"lr": 1e-4},
                      dtype=jnp.bfloat16)
    tr.init(data=(b, T), softmax_label=(b, T))
    rs = np.random.RandomState(0)
    feed = {"data": jax.device_put(
        rs.randint(0, V, (b, T)).astype(np.float32)),
        "softmax_label": jax.device_put(
            rs.randint(0, V, (b, T)).astype(np.float32))}
    dt = _timed(tr, feed, iters)
    tok_s = b * T * iters / dt
    fpt = lm_train_flops_per_token(L, D, 4 * D, T, V)
    print(f"transformer_lm_12L_d512_train_tokens_per_sec: {tok_s:.0f} "
          f"({dt / iters * 1e3:.1f} ms/step, mfu={tok_s * fpt / 197e12:.3f})",
          flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--only", choices=["inception", "lstm", "ssd", "lm"])
    args = ap.parse_args()
    if os.environ.get("MXTPU_PLATFORM") == "cpu" or args.smoke:
        import jax

        jax.config.update("jax_platforms", "cpu")
        args.smoke = True
        args.iters = min(args.iters, 2)
    import jax

    print("devices:", jax.devices(), flush=True)
    benches = {"inception": bench_inception, "lstm": bench_lstm_ptb,
               "ssd": bench_ssd_forward, "lm": bench_transformer_lm}
    picks = [args.only] if args.only else list(benches)
    for name in picks:
        try:
            benches[name](args.iters, smoke=args.smoke)
        except Exception as exc:  # noqa: BLE001 — keep capturing the rest
            print(f"{name}: FAILED {exc!r}", flush=True)


if __name__ == "__main__":
    main()
