"""One-off probe: capture an xprof trace of the ResNet-50 train step and
print the top HLO ops by self time (framework_op_stats via xprof)."""
import glob
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import models
from mxnet_tpu.trainer import FusedTrainer

BATCH = 256
LOGDIR = "/tmp/mxtpu_prof"


def main():
    net = models.get_symbol("resnet-50", num_classes=1000)
    tr = FusedTrainer(net, optimizer="sgd",
                      optimizer_params={"lr": 0.1, "momentum": 0.9,
                                        "rescale_grad": 1.0 / BATCH},
                      dtype=jnp.bfloat16)
    tr.init(data=(BATCH, 3, 224, 224))
    rs = np.random.RandomState(0)
    batch = {"data": jax.device_put(
        rs.uniform(0, 1, (BATCH, 3, 224, 224)).astype(np.float32)),
        "softmax_label": jax.device_put(
            rs.randint(0, 1000, BATCH).astype(np.float32))}

    def fetch():
        name = sorted(tr.params)[0]
        return float(np.asarray(tr.params[name]).ravel()[0])

    for _ in range(3):
        tr.step(**batch)
    fetch()

    with jax.profiler.trace(LOGDIR):
        for _ in range(5):
            tr.step(**batch)
        fetch()

    xplanes = glob.glob(os.path.join(LOGDIR, "**", "*.xplane.pb"),
                        recursive=True)
    print("xplane files:", xplanes)
    if not xplanes:
        return
    from xprof.convert import raw_to_tool_data as rtd

    for tool in ("framework_op_stats", "hlo_stats"):
        try:
            data, _ = rtd.xspace_to_tool_data(xplanes, tool, {})
            out = os.path.join(LOGDIR, tool + ".out")
            mode = "wb" if isinstance(data, (bytes, bytearray)) else "w"
            with open(out, mode) as f:
                f.write(data)
            print("wrote", out, "bytes", len(data))
        except Exception as exc:  # noqa: BLE001
            print(tool, "failed:", repr(exc))


if __name__ == "__main__":
    main()
