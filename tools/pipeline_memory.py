"""Pipeline schedule comparison: GPipe-autodiff vs 1F1B memory + bubble.

Compiles the SAME heterogeneous transformer-LM pipeline train step two
ways on the virtual 8-device CPU mesh and reports XLA's own per-device
temp-buffer numbers (compiled.memory_analysis()):

* GPipe: jax.grad through pipeline_apply_tree — autodiff stashes every
  tick's residuals, so activation memory grows with the number of
  microbatches M.
* 1F1B: make_pipeline_train_step — boundary-input stash of static depth
  2S+1, so activation memory is flat in M (the verdict-r3 #4 memory win),
  at one extra stage forward per microbatch (remat trade).

Run:  python tools/pipeline_memory.py [--stages 4] [--micro 4 8 16 32]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from mxnet_tpu.parallel import pipeline as pp  # noqa: E402
from mxnet_tpu.parallel.mesh import create_mesh  # noqa: E402

from mxnet_tpu.ops.loss import token_nll as nll  # noqa: E402


def tblock(p, h):
    m = h.mean(-1, keepdims=True)
    v = ((h - m) ** 2).mean(-1, keepdims=True)
    x = (h - m) * jax.lax.rsqrt(v + 1e-5) * p["ln_g"] + p["ln_b"]
    B, T, D = x.shape
    H, dh = 4, D // 4
    qkv = x @ p["qkv_w"]
    q, k, v_ = jnp.split(qkv, 3, axis=-1)
    sh = lambda a: a.reshape(B, T, H, dh).transpose(0, 2, 1, 3)
    s = (sh(q) @ sh(k).transpose(0, 1, 3, 2)) / np.sqrt(dh)
    s = jnp.where(jnp.tril(jnp.ones((T, T), bool)), s, -1e9)
    att = (jax.nn.softmax(s, -1) @ sh(v_)).transpose(0, 2, 1, 3).reshape(B, T, D)
    h = h + att @ p["proj_w"]
    f = jax.nn.gelu((h @ p["fi_w"]))
    return h + f @ p["fo_w"]


def build(S, D, vocab, rs):
    def bp():
        g = lambda *s: jnp.asarray(rs.normal(0, .05, s).astype(np.float32))
        return {"ln_g": jnp.ones(D), "ln_b": jnp.zeros(D),
                "qkv_w": g(D, 3 * D), "proj_w": g(D, D),
                "fi_w": g(D, 4 * D), "fo_w": g(4 * D, D)}

    fns, trees = [], []
    for s in range(S):
        tree = {"blk": bp()}
        if s == 0:
            tree["embed"] = jnp.asarray(
                rs.normal(0, .1, (vocab, D)).astype(np.float32))
            fns.append(lambda p, ids: tblock(
                p["blk"], p["embed"][ids.astype(jnp.int32)]))
        elif s == S - 1:
            tree["head"] = jnp.asarray(
                rs.normal(0, .1, (D, vocab)).astype(np.float32))
            fns.append(lambda p, h: tblock(p["blk"], h) @ p["head"])
        else:
            fns.append(lambda p, h: tblock(p["blk"], h))
        trees.append(tree)
    return fns, trees


def temp_bytes(compiled):
    ma = compiled.memory_analysis()
    return getattr(ma, "temp_size_in_bytes", None)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--stages", type=int, default=4)
    ap.add_argument("--micro", type=int, nargs="+", default=[4, 8, 16, 32])
    ap.add_argument("--d-model", type=int, default=128)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--mb", type=int, default=4)
    args = ap.parse_args()

    S, D, T, mb, vocab = args.stages, args.d_model, args.seq, args.mb, 256
    rs = np.random.RandomState(0)
    mesh = create_mesh((S,), ("pipe",), devices=jax.devices("cpu")[:S])
    fns, trees = build(S, D, vocab, rs)
    stacked, meta = pp.union_stack(trees, mesh)

    print(f"pipeline memory/bubble: S={S} stages, D={D}, T={T}, mb={mb} "
          f"(XLA temp bytes per compile, CPU mesh)")
    print(f"{'M':>4} {'gpipe_bub':>10} {'1f1b_bub':>9} {'GPipe temp':>14} "
          f"{'1F1B temp':>14} {'ratio':>6}")
    for M in args.micro:
        xs = jnp.asarray(rs.randint(0, vocab, (M, mb, T)), jnp.float32)
        ys = jnp.asarray(rs.randint(0, vocab, (M, mb, T)), jnp.float32)

        def gpipe_loss(params, xs, ys):
            outs = pp.pipeline_apply_tree(fns, params, meta, xs, mesh)
            tot = 0.0
            for m in range(M):
                tot = tot + nll(outs[m], ys[m])
            return tot / M

        gp = jax.jit(jax.value_and_grad(gpipe_loss)).lower(
            stacked, xs, ys).compile()
        f1 = pp.make_pipeline_train_step(fns, nll, meta, mesh).lower(
            stacked, xs, ys).compile()
        g_b, f_b = temp_bytes(gp), temp_bytes(f1)
        ratio = f"{g_b / f_b:.2f}" if (g_b and f_b) else "n/a"
        fmt = lambda b: f"{b:,}" if b is not None else "n/a"
        print(f"{M:>4} {pp.bubble_fraction(S, M):>10.3f} "
              f"{pp.bubble_fraction_1f1b(S, M):>9.3f} "
              f"{fmt(g_b):>14} {fmt(f_b):>14} {ratio:>6}")
        # sanity: same math
        (gl, _), (fl, _) = gp(stacked, xs, ys), f1(stacked, xs, ys)
        assert abs(float(gl) - float(fl)) < 1e-4, (float(gl), float(fl))


if __name__ == "__main__":
    main()
