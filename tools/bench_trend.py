#!/usr/bin/env python
"""Bench regression sentinel (ROADMAP item 3's honesty gate, automated).

Rounds r03–r05 silently embedded committed artifacts after backend-init
timeouts and the bench trajectory read stale numbers as live ones for
three PRs.  This tool makes the trajectory itself a tested artifact:

- loads every committed ``BENCH_r*.json`` / ``MULTICHIP_r*.json`` round,
  separating LIVE captures from artifact fallbacks (an embedded
  ``last_measured`` or an ``error`` field);
- prints a per-metric trend table over the live rounds (fallback rounds
  shown, but excluded from the series — stale numbers must not anchor a
  comparison);
- exits nonzero when (a) any metric moved the WRONG way by more than
  ``BENCH_TREND_TOL`` (default 0.15) between the two most recent live
  rounds that carry it, (b) the newest committed round is an artifact
  fallback, or (c) ``--current-fallback`` says the round being captured
  RIGHT NOW fell back (bench.py's ``_fail`` path passes this, so a
  non-live round is loud in its own log, not a footnote N PRs later).

Direction is inferred from the metric name: ``*_ms`` / ``*_us`` /
``*_seconds`` / latency / overhead-style metrics regress UP, throughput/
MFU-style metrics regress DOWN.

    python tools/bench_trend.py [--dir REPO] [--tol 0.15]
    python tools/bench_trend.py --current-fallback "backend init timed out"

Exit codes: 0 trajectory clean, 1 regression or fallback, 2 usage error.
Stdlib-only (no mxnet_tpu/jax import): safe in any CI stage.
"""
import argparse
import glob
import json
import os
import re
import sys

# derived / configuration values that are not perf metrics
EXCLUDE_KEYS = {
    "vs_baseline", "init_attempts", "batch", "steps_per_call",
    "fallback_streak", "dist_generations", "n_devices", "bench_trend_rc",
    "eval_forward_vs_p100_infer_baseline",
}
_LOWER_IS_BETTER = ("_ms", "_us", "_seconds", "latency", "_p50", "_p99",
                    "overhead", "stall", "_bytes_per_replica",
                    # serving-fleet metrics (round 19): router re-routes
                    # and shed requests are failures — they regress UP
                    "retry", "retries", "unavailable",
                    # tracing + SLO metrics (round 20): budget burn,
                    # objective violations, and tracing overhead all
                    # regress UP
                    "burn_rate", "violations",
                    # autotune metrics (round 21): search wall cost and
                    # per-step kernel microseconds regress UP (already
                    # implied by _ms/_us, pinned explicitly so a rename
                    # cannot silently flip them; *_speedup stays
                    # higher-is-better by omission)
                    "search_ms", "us_per_step",
                    # perf-attribution plane (round 22): stall was
                    # already pinned above; time lost waiting on the
                    # input pipeline regresses UP too
                    "data_wait")
# Explicit higher-is-better overrides, checked FIRST (round 22): mfu
# and tokens_per_sec regress DOWN by name, so a lower-is-better token
# sneaking into a future metric name (e.g. "mfu_stall_adjusted") can
# never silently flip the headline utilization/throughput directions.
_HIGHER_IS_BETTER = ("mfu", "tokens_per_sec")


def lower_is_better(name: str) -> bool:
    n = name.lower()
    if any(tok in n for tok in _HIGHER_IS_BETTER):
        return False
    return any(tok in n for tok in _LOWER_IS_BETTER)


def _is_fallback(parsed: dict) -> bool:
    return bool(parsed.get("error")) or "last_measured" in parsed


def _flatten(parsed: dict) -> dict:
    """Numeric metrics of one live round; the headline ``value`` is
    renamed to the round's ``metric`` so every series has a real name."""
    out = {}
    headline = parsed.get("metric")
    for key, val in parsed.items():
        if key in EXCLUDE_KEYS or isinstance(val, bool) \
                or not isinstance(val, (int, float)):
            continue
        out[headline if key == "value" and headline else key] = float(val)
    return out


def load_rounds(dirpath: str, pattern: str) -> list:
    """Committed rounds matching ``pattern`` (e.g. BENCH_r[0-9]*.json),
    sorted by round number: [{n, file, fallback, reason, metrics}].
    Rounds with no ``parsed`` payload at all (the early MULTICHIP
    artifacts record only rc/device counts) are not part of the
    trajectory."""
    rounds = []
    for path in glob.glob(os.path.join(glob.escape(dirpath), pattern)):
        m = re.search(r"_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue  # unreadable round: not part of the trajectory
        if "parsed" not in doc:
            continue
        parsed = doc.get("parsed") or {}
        fell = _is_fallback(parsed)
        rounds.append({
            "n": int(m.group(1)),
            "file": os.path.basename(path),
            "fallback": fell,
            "reason": str(parsed.get("error") or "")[:160],
            "metrics": {} if fell else _flatten(parsed),
        })
    rounds.sort(key=lambda r: r["n"])
    return rounds


def analyze(rounds: list, tol: float):
    """(series, regressions): series maps metric -> [(round, value)]
    over live rounds; regressions are last-vs-previous moves worse than
    ``tol`` in the metric's bad direction."""
    series = {}
    for r in rounds:
        for name, val in r["metrics"].items():
            series.setdefault(name, []).append((r["n"], val))
    regressions = []
    for name, pts in sorted(series.items()):
        if len(pts) < 2:
            continue
        (prev_n, prev_v), (last_n, last_v) = pts[-2], pts[-1]
        if prev_v == 0:
            continue
        change = (last_v - prev_v) / abs(prev_v)
        lower = lower_is_better(name)
        if (change > tol) if lower else (change < -tol):
            regressions.append({
                "metric": name, "prev_round": prev_n, "prev": prev_v,
                "last_round": last_n, "last": last_v,
                "change_pct": round(change * 100.0, 1),
                "direction": "lower-is-better" if lower
                             else "higher-is-better"})
    return series, regressions


def _fmt(v: float) -> str:
    return "%g" % (round(v, 4) if abs(v) < 100 else round(v, 1))


def render_table(rounds: list, series: dict) -> str:
    lines = []
    live = [r["n"] for r in rounds if not r["fallback"]]
    fell = [r["n"] for r in rounds if r["fallback"]]
    lines.append("rounds: live %s%s" % (
        live or "(none)",
        ("  fallback %s" % fell) if fell else ""))
    for r in rounds:
        if r["fallback"]:
            lines.append("  r%02d %s: ARTIFACT FALLBACK (%s)"
                         % (r["n"], r["file"], r["reason"] or "?"))
    width = max([len(n) for n in series] or [6]) + 2
    header = "%-*s %s" % (width, "metric",
                          " ".join("%12s" % ("r%02d" % n) for n in live))
    lines.append(header)
    for name in sorted(series):
        by_round = dict(series[name])
        lines.append("%-*s %s" % (
            width, name,
            " ".join("%12s" % (_fmt(by_round[n]) if n in by_round else "-")
                     for n in live)))
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="bench_trend.py",
        description="trend table + regression gate over committed "
                    "BENCH_r*/MULTICHIP_r* rounds")
    ap.add_argument("--dir",
                    default=os.path.dirname(os.path.dirname(
                        os.path.abspath(__file__))),
                    help="directory holding the committed rounds "
                         "(default: repo root)")
    ap.add_argument("--tol", type=float,
                    default=float(os.environ.get("BENCH_TREND_TOL",
                                                 "0.15") or 0.15),
                    help="relative worsening tolerated between the two "
                         "newest live rounds (default $BENCH_TREND_TOL "
                         "or 0.15)")
    ap.add_argument("--current-fallback", default=None, metavar="REASON",
                    help="the round being captured right now fell back "
                         "to a committed artifact: fail loudly with this "
                         "reason (bench.py's _fail path sets it)")
    args = ap.parse_args(argv)

    families = [("BENCH", load_rounds(args.dir, "BENCH_r[0-9]*.json")),
                ("MULTICHIP",
                 load_rounds(args.dir, "MULTICHIP_r[0-9]*.json"))]
    if not any(rounds for _, rounds in families):
        print("bench_trend: no BENCH_r*/MULTICHIP_r* rounds under %s"
              % args.dir, file=sys.stderr)
        return 2

    failed = False
    if args.current_fallback:
        failed = True
        print("FAIL: the round being captured NOW is an artifact "
              "fallback: %s" % args.current_fallback)
    for family, rounds in families:
        if not rounds:
            continue
        series, regressions = analyze(rounds, args.tol)
        print("== %s ==" % family)
        print(render_table(rounds, series))
        if rounds[-1]["fallback"]:
            failed = True
            print("FAIL: newest committed %s round (r%02d) is an "
                  "artifact fallback (%s) — fix the harness/backend "
                  "before trusting the trajectory"
                  % (family, rounds[-1]["n"],
                     rounds[-1]["reason"] or "?"))
        for reg in regressions:
            failed = True
            print("FAIL: %s regressed %+.1f%% (%s): r%02d %s -> "
                  "r%02d %s (tol %.0f%%)" % (
                      reg["metric"], reg["change_pct"], reg["direction"],
                      reg["prev_round"], _fmt(reg["prev"]),
                      reg["last_round"], _fmt(reg["last"]),
                      args.tol * 100.0))
    if not failed:
        print("ok: no regression beyond %.0f%% and the newest round is "
              "a live capture" % (args.tol * 100.0))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
