"""Probe: the chip's two roofline corners, measured not quoted.

1. MXU corner — dense bf16 matmul MFU at sizes from 2k to 8k: how close
   can ANY program get to the datasheet peak (v5e: 197 TFLOP/s)?
2. HBM corner — streaming read+write bandwidth via y = a*x + y over
   arrays far larger than VMEM (v5e datasheet: 819 GB/s).

The iteration loop runs ON DEVICE (lax.fori_loop) so one dispatch
covers all iterations: on a tunneled chip, per-call dispatch latency is
hundreds of ms and a host-side loop measures the transport, not the
silicon (the first capture of this probe did exactly that — 45 GB/s
"HBM bandwidth" that was really 30 serialized round trips).

Together with tools/probe_nhwc.py (the ResNet-50 train step itself)
these pin where that workload sits on the roofline: if matmul MFU is
high and the train step's implied bytes/s ~= the measured stream
bandwidth, the step is HBM-bound and its MFU ceiling is a property of
the workload's arithmetic intensity, not the framework.

Run on a chip:  python tools/probe_peak.py
"""
import time

import jax
import jax.numpy as jnp
import numpy as np

PEAK_TFLOPS = 197.0   # v5e bf16 datasheet
PEAK_GBS = 819.0      # v5e HBM datasheet


def matmul_mfu(n, iters=None):
    if iters is None:
        # constant total FLOP across sizes, so the single dispatch+fetch
        # round trip is amortized equally (~55 TFLOP ≈ 300ms at peak)
        iters = max(1, round(50 * (8192 / n) ** 3))
    a = jnp.asarray(np.random.RandomState(0).normal(size=(n, n)),
                    jnp.bfloat16)
    b = jnp.asarray(np.random.RandomState(1).normal(size=(n, n)),
                    jnp.bfloat16)

    @jax.jit
    def chain(a, b):
        # chained matmuls (each consumes the last result) so the device
        # loop can't be folded away or overlapped into nothing
        def body(_, c):
            return jax.lax.dot(
                c, b, preferred_element_type=jnp.float32
            ).astype(jnp.bfloat16)

        return jax.lax.fori_loop(0, iters, body, a)

    def fetch(out):
        # block_until_ready can acknowledge at dispatch on tunneled
        # backends (bench.py's discipline) — pulling real bytes is the
        # only barrier that can't lie
        return float(np.asarray(out[0, 0], np.float32))

    fetch(chain(a, b))                          # compile + warm
    tic = time.perf_counter()
    fetch(chain(a, b))                          # ONE dispatch, iters matmuls
    dt = time.perf_counter() - tic
    tflops = 2.0 * n * n * n * iters / dt / 1e12
    print(f"matmul {n}x{n}x{n} bf16: {tflops:8.1f} TFLOP/s  "
          f"mfu={tflops / PEAK_TFLOPS:.3f}", flush=True)


def hbm_bandwidth(mb=512, iters=100):
    n = mb * 1024 * 1024 // 4
    x = jnp.zeros((n,), jnp.float32)
    y = jnp.ones((n,), jnp.float32)

    @jax.jit
    def axpy_loop(x, y):
        def body(_, c):
            return 1.0001 * c + y

        return jax.lax.fori_loop(0, iters, body, x)

    def fetch(out):
        return float(np.asarray(out[0], np.float32))

    fetch(axpy_loop(x, y))
    tic = time.perf_counter()
    fetch(axpy_loop(x, y))
    dt = time.perf_counter() - tic
    # per iter: read c, read y, write out = 3 * mb
    gbs = 3 * mb * iters / 1024 / dt
    print(f"hbm axpy {mb}MB: {gbs:8.1f} GB/s  "
          f"of datasheet {PEAK_GBS:.0f} ({gbs / PEAK_GBS:.2f})", flush=True)


if __name__ == "__main__":
    print("devices:", jax.devices(), flush=True)
    for n in (2048, 4096, 8192):
        matmul_mfu(n)
    hbm_bandwidth()
