"""Probe: the chip's two roofline corners, measured not quoted.

1. MXU corner — dense bf16 matmul MFU at sizes from 2k to 8k: how close
   can ANY program get to the datasheet peak (v5e: 197 TFLOP/s)?
2. HBM corner — streaming read+write bandwidth via y = a*x + y over
   arrays far larger than VMEM (v5e datasheet: 819 GB/s).

Together with tools/probe_nhwc.py (the ResNet-50 train step itself)
these pin where that workload sits on the roofline: if matmul MFU is
high and the train step's implied bytes/s ~= the measured stream
bandwidth, the step is HBM-bound and its MFU ceiling is a property of
the workload's arithmetic intensity, not the framework.

Run on a chip:  python tools/probe_peak.py
"""
import time

import jax
import jax.numpy as jnp
import numpy as np

PEAK_TFLOPS = 197.0   # v5e bf16 datasheet
PEAK_GBS = 819.0      # v5e HBM datasheet


def matmul_mfu(n, iters=20):
    a = jnp.asarray(np.random.RandomState(0).normal(size=(n, n)),
                    jnp.bfloat16)
    b = jnp.asarray(np.random.RandomState(1).normal(size=(n, n)),
                    jnp.bfloat16)

    @jax.jit
    def chain(a, b):
        # two chained matmuls so the loop body can't be folded away
        c = jax.lax.dot(a, b, preferred_element_type=jnp.float32)
        return c.astype(jnp.bfloat16)

    out = chain(a, b)
    jax.block_until_ready(out)
    tic = time.perf_counter()
    for _ in range(iters):
        out = chain(out, b)
    _ = float(jnp.asarray(out[0, 0], jnp.float32))  # fetch = real barrier
    dt = time.perf_counter() - tic
    tflops = 2.0 * n * n * n * iters / dt / 1e12
    print(f"matmul {n}x{n}x{n} bf16: {tflops:8.1f} TFLOP/s  "
          f"mfu={tflops / PEAK_TFLOPS:.3f}", flush=True)


def hbm_bandwidth(mb=512, iters=30):
    n = mb * 1024 * 1024 // 4
    x = jnp.zeros((n,), jnp.float32)
    y = jnp.ones((n,), jnp.float32)

    @jax.jit
    def axpy(x, y):
        return 1.0001 * x + y

    out = axpy(x, y)
    jax.block_until_ready(out)
    tic = time.perf_counter()
    for _ in range(iters):
        out = axpy(out, y)
    _ = float(out[0])
    dt = time.perf_counter() - tic
    # per iter: read x, read y, write out = 3 * mb
    gbs = 3 * mb * iters / 1024 / dt
    print(f"hbm axpy {mb}MB: {gbs:8.1f} GB/s  "
          f"of datasheet {PEAK_GBS:.0f} ({gbs / PEAK_GBS:.2f})", flush=True)


if __name__ == "__main__":
    print("devices:", jax.devices(), flush=True)
    for n in (2048, 4096, 8192):
        matmul_mfu(n)
    hbm_bandwidth()
