#!/usr/bin/env python
"""Step-time explain CLI (docs/perf_attr.md): where does the wall GO?

Renders the perf-attribution plane's ``/profile`` document — the ranked
per-program table (device wall, MFU, roofline verdict, memory) and the
step-time bucket decomposition with its sums-to-step-wall sanity line.
The source can be a live process or a file:

    python tools/explain.py localhost:9100          # GET /profile
    python tools/explain.py profile.json            # saved payload
    python tools/explain.py flight_dump.json        # dump's "perf" key

``diff`` compares two captures (before/after a change) program by
program and bucket by bucket, in each metric's regression direction:

    python tools/explain.py diff before.json after.json

Exit codes: 0 rendered, 1 source unreachable/unparseable, 2 usage.
Stdlib-only on purpose (fleetstat.py's contract): runs on an operator
workstation or a bare pod VM without the mxnet_tpu (or jax) install.
"""
import argparse
import json
import sys
import urllib.request


def load_profile(source, timeout=10.0):
    """The profile document from ``host:port`` (GET /profile), a saved
    payload file, or a flight-record dump (whose ``perf`` key holds the
    untruncated document)."""
    if ":" in source and not source.endswith(".json"):
        with urllib.request.urlopen("http://%s/profile" % source,
                                    timeout=timeout) as resp:
            return json.loads(resp.read())
    with open(source) as f:
        doc = json.load(f)
    if "programs" not in doc and isinstance(doc.get("perf"), dict):
        doc = doc["perf"]  # flight dump: the plane rides under "perf"
    if "programs" not in doc:
        raise ValueError(
            "%s is neither a /profile payload nor a flight dump with a "
            "'perf' section" % source)
    return doc


def _fmt_flops(v):
    if v is None:
        return "-"
    for unit, div in (("G", 1e9), ("M", 1e6), ("K", 1e3)):
        if v >= div:
            return "%.1f%s" % (v / div, unit)
    return "%.0f" % v


def _fmt_bytes(v):
    if v is None:
        return "-"
    for unit, div in (("GiB", 2**30), ("MiB", 2**20), ("KiB", 2**10)):
        if v >= div:
            return "%.1f%s" % (v / div, unit)
    return "%dB" % v


def _fmt_mfu(v):
    return "-" if v is None else "%.3f" % v


def render(prof):
    """One-screen rendering: header, ranked program table, bucket
    decomposition + the sums-to-step-wall sanity line."""
    lines = []
    kind = prof.get("device_kind", "?")
    peak = prof.get("peak_flops")
    balance = prof.get("machine_balance")
    lines.append(
        "perf attribution on %s  peak %s  machine balance %s  %s" % (
            kind,
            ("%g TFLOP/s" % (peak / 1e12)) if peak else "UNKNOWN",
            ("%.1f flops/byte" % balance) if balance else "?",
            "armed" if prof.get("armed") else
            "DISARMED (set MXTPU_PERF_ATTR=1)"))

    programs = prof.get("programs") or []
    total_wall = sum(p.get("wall_s") or 0.0 for p in programs)
    lines.append("%-42s %9s %5s %7s %6s %-13s %8s %9s" % (
        "program", "wall_ms", "share", "disp", "mfu", "roofline",
        "flops", "peak_mem"))
    for p in programs:
        wall = p.get("wall_s") or 0.0
        share = (wall / total_wall * 100.0) if total_wall > 0 else 0.0
        lines.append("%-42s %9.1f %4.0f%% %7d %6s %-13s %8s %9s" % (
            str(p.get("program", "?"))[:42], wall * 1e3, share,
            p.get("dispatches") or 0, _fmt_mfu(p.get("mfu")),
            str(p.get("roofline", "unknown")),
            _fmt_flops(p.get("flops")),
            _fmt_bytes(p.get("peak_memory"))))
    if not programs:
        lines.append("  (no programs attributed yet — has a dispatch "
                     "run with the plane armed?)")
    shown, known = len(programs), prof.get("programs_total")
    if known is not None and known > shown:
        lines.append("  ... %d more program(s) below the top-%d cut "
                     "(MXTPU_PROFILE_TOPN)" % (known - shown, shown))

    buckets = prof.get("buckets") or {}
    steps = prof.get("steps") or {}
    step_wall = float(steps.get("wall_s") or 0.0)
    nsteps = int(steps.get("count") or 0)
    lines.append("")
    lines.append("step-time decomposition over %d step(s), %.1fms total:"
                 % (nsteps, step_wall * 1e3))
    in_sum = 0.0
    for name in sorted(buckets,
                       key=lambda n: -float(buckets[n].get("seconds", 0))):
        b = buckets[name]
        sec = float(b.get("seconds") or 0.0)
        in_step = bool(b.get("in_step"))
        if in_step:
            in_sum += sec
        share = (sec / step_wall * 100.0) \
            if in_step and step_wall > 0 else None
        lines.append("  %-16s %9.1fms %6s  x%d%s" % (
            name, sec * 1e3,
            ("%4.0f%%" % share) if share is not None else "",
            int(b.get("count") or 0),
            "" if in_step else "  (outside steps)"))
    if step_wall > 0:
        div = abs(in_sum - step_wall) / step_wall
        lines.append(
            "  sanity: in-step buckets sum to %.1fms of %.1fms step wall "
            "(%.1f%% apart)%s" % (
                in_sum * 1e3, step_wall * 1e3, div * 100.0,
                "" if div <= 0.10 else
                "  <- DIVERGED >10%: a stamp is missing a bucket"))
    elif not buckets:
        lines.append("  (no step buckets yet)")
    return "\n".join(lines)


def _index(prof):
    return {p.get("program"): p for p in prof.get("programs") or []}


def diff(prof_a, prof_b):
    """A-vs-B rendering: per-program wall/MFU movement and the bucket
    deltas, flagged in each metric's bad direction (wall up = worse,
    MFU down = worse — the same conventions bench_trend.py pins)."""
    lines = []
    a_idx, b_idx = _index(prof_a), _index(prof_b)
    lines.append("%-42s %10s %10s %8s %7s %7s" % (
        "program", "wall_ms A", "wall_ms B", "Δwall%", "mfu A", "mfu B"))
    for label in sorted(set(a_idx) | set(b_idx),
                        key=lambda n: -(b_idx.get(n, a_idx.get(n, {}))
                                        .get("wall_s") or 0.0)):
        pa, pb = a_idx.get(label), b_idx.get(label)
        wa = (pa or {}).get("wall_s")
        wb = (pb or {}).get("wall_s")
        if wa and wb:
            dw = "%+.1f%%" % ((wb - wa) / wa * 100.0)
        else:
            dw = "new" if pa is None else ("gone" if pb is None else "-")
        lines.append("%-42s %10s %10s %8s %7s %7s" % (
            str(label)[:42],
            "-" if wa is None else "%.1f" % (wa * 1e3),
            "-" if wb is None else "%.1f" % (wb * 1e3),
            dw, _fmt_mfu((pa or {}).get("mfu")),
            _fmt_mfu((pb or {}).get("mfu"))))

    ba = prof_a.get("buckets") or {}
    bb = prof_b.get("buckets") or {}
    sa = float((prof_a.get("steps") or {}).get("wall_s") or 0.0)
    sb = float((prof_b.get("steps") or {}).get("wall_s") or 0.0)
    na = int((prof_a.get("steps") or {}).get("count") or 0)
    nb = int((prof_b.get("steps") or {}).get("count") or 0)
    lines.append("")
    lines.append("buckets (per-step ms so A and B compare across "
                 "different step counts):")
    lines.append("%-16s %12s %12s %8s" % ("bucket", "A ms/step",
                                          "B ms/step", "Δ"))
    for name in sorted(set(ba) | set(bb)):
        va = (float(ba[name].get("seconds") or 0.0) / na * 1e3) \
            if name in ba and na else None
        vb = (float(bb[name].get("seconds") or 0.0) / nb * 1e3) \
            if name in bb and nb else None
        if va and vb:
            d = "%+.1f%%" % ((vb - va) / va * 100.0)
        else:
            d = "-"
        lines.append("%-16s %12s %12s %8s" % (
            name, "-" if va is None else "%.2f" % va,
            "-" if vb is None else "%.2f" % vb, d))
    if na and nb and sa and sb:
        lines.append("step wall: %.2f -> %.2f ms/step (%+.1f%%)" % (
            sa / na * 1e3, sb / nb * 1e3,
            (sb / nb - sa / na) / (sa / na) * 100.0))
    return "\n".join(lines)


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "diff":
        ap = argparse.ArgumentParser(
            prog="explain.py diff",
            description="compare two profile captures (file or "
                        "host:port each)")
        ap.add_argument("a", help="baseline capture")
        ap.add_argument("b", help="candidate capture")
        ap.add_argument("--timeout", type=float, default=10.0)
        args = ap.parse_args(argv[1:])
        try:
            prof_a = load_profile(args.a, timeout=args.timeout)
            prof_b = load_profile(args.b, timeout=args.timeout)
        except (OSError, ValueError) as exc:
            print("explain: %s" % exc, file=sys.stderr)
            return 1
        print(diff(prof_a, prof_b))
        return 0

    ap = argparse.ArgumentParser(
        prog="explain.py",
        description="render a perf-attribution profile (live GET "
                    "/profile, saved payload, or flight dump)")
    ap.add_argument("source", help="host:port, profile JSON, or "
                    "flight-record dump")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="print the raw profile JSON")
    ap.add_argument("--timeout", type=float, default=10.0)
    args = ap.parse_args(argv)
    try:
        prof = load_profile(args.source, timeout=args.timeout)
    except (OSError, ValueError) as exc:
        print("explain: %s" % exc, file=sys.stderr)
        return 1
    print(json.dumps(prof, indent=1) if args.as_json else render(prof))
    return 0


if __name__ == "__main__":
    sys.exit(main())
