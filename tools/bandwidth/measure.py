#!/usr/bin/env python
"""Measure kvstore / collective aggregation bandwidth.

Parity: tools/bandwidth/measure.py (reference) — times repeated
push+pull of model-sized gradient sets through a kvstore and reports
GB/s, so users can check comm cost < compute cost per batch
(docs/how_to/perf.md:148-154).

TPU-native addition: ``--kv-store collective`` times the same payload as
an in-step psum over the device mesh (the path FusedTrainer uses), which
is what actually rides ICI on pods.
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def model_sizes(network, num_classes=1000):
    """Parameter sizes (floats) for a named model, via symbol shape
    inference (parity: the reference infers from the symbol zoo)."""
    import mxnet_tpu as mx
    from mxnet_tpu import models

    net = models.get_symbol(network, num_classes=num_classes)
    arg_shapes, _, _ = net.infer_shape(data=(2, 3, 224, 224))
    import numpy as np

    names = net.list_arguments()
    return [int(np.prod(s)) for n, s in zip(names, arg_shapes)
            if n not in ("data", "softmax_label")]


def measure_kvstore(kv_type, sizes, num_devices, repeat):
    import numpy as np

    import mxnet_tpu as mx

    kv = mx.kv.create(kv_type)
    arrays = [[mx.nd.array(np.ones(s, np.float32)) for _ in range(num_devices)]
              for s in sizes]
    outs = [[mx.nd.zeros((s,)) for _ in range(num_devices)] for s in sizes]
    for i, s in enumerate(sizes):
        kv.init(i, mx.nd.zeros((s,)))
    total_bytes = sum(sizes) * 4 * 2 * num_devices  # push + pull, all devs
    t0 = time.time()
    for _ in range(repeat):
        for i in range(len(sizes)):
            kv.push(i, [a.reshape((sizes[i],)) for a in arrays[i]],
                    priority=-i)
        for i in range(len(sizes)):
            kv.pull(i, out=outs[i], priority=-i)
        for o in outs:
            o[0].wait_to_read()
    dt = time.time() - t0
    return total_bytes * repeat / dt / 1e9, dt / repeat


def measure_collective(sizes, num_devices, repeat):
    """psum over an n-device mesh — the fused-step gradient path."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    devices = jax.devices()[:num_devices]
    mesh = Mesh(np.array(devices), ("data",))

    @jax.jit
    def allreduce(*xs):
        f = shard_map(lambda *ys: tuple(jax.lax.psum(y, "data") for y in ys),
                      mesh=mesh, in_specs=P("data"), out_specs=P("data"))
        return f(*xs)

    args = [jax.device_put(
        np.ones((num_devices, s), np.float32),
        NamedSharding(mesh, P("data"))) for s in sizes]
    jax.block_until_ready(allreduce(*args))
    t0 = time.time()
    for _ in range(repeat):
        out = allreduce(*args)
    jax.block_until_ready(out)
    dt = time.time() - t0
    total_bytes = sum(sizes) * 4 * 2 * num_devices
    return total_bytes * repeat / dt / 1e9, dt / repeat


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--network", default="resnet-50")
    ap.add_argument("--kv-store", default="device",
                    help="local | device | dist_* | collective")
    ap.add_argument("--num-devices", type=int, default=1)
    ap.add_argument("--repeat", type=int, default=5)
    ap.add_argument("--num-classes", type=int, default=1000)
    args = ap.parse_args()

    sizes = model_sizes(args.network, args.num_classes)
    print(f"{args.network}: {len(sizes)} params, "
          f"{sum(sizes) * 4 / 1e6:.1f} MB")
    if args.kv_store == "collective":
        gbs, per_iter = measure_collective(sizes, args.num_devices, args.repeat)
    else:
        gbs, per_iter = measure_kvstore(args.kv_store, sizes,
                                        args.num_devices, args.repeat)
    print(f"kvstore={args.kv_store} devices={args.num_devices} "
          f"bandwidth={gbs:.2f} GB/s per-iter={per_iter * 1e3:.1f} ms")


if __name__ == "__main__":
    main()
