"""Reference-checkpoint interoperability.

Reads (and writes) the reference's on-disk formats so models trained
there load here directly:

- ``load_params`` / ``save_params`` — the NDArray-list binary behind
  ``prefix-0001.params`` (format per src/ndarray/ndarray.cc:593-694:
  uint64 magic 0x112 + reserved, then a dmlc vector of arrays — each a
  TShape (uint32 ndim + uint32 dims), a Context (int32 dev_type +
  int32 dev_id), an int32 mshadow type flag, and raw row-major bytes —
  then a dmlc vector of name strings, ``arg:``/``aux:`` prefixed by
  model.save_checkpoint).
- ``load_symbol_json`` — reference/nnvm symbol JSON, including the
  legacy upgrades src/nnvm/legacy_json_util.cc performs (pre-0.9 files
  carry per-node ``param`` dicts instead of ``attr``/``attrs``, and
  2-element input entries without a version field).
- ``load_checkpoint`` — the pair, mirroring model.load_checkpoint.

Nothing here depends on the reference's code — only on the documented
byte layout above.
"""
from __future__ import annotations

import json
import struct
from typing import Dict, Tuple

import numpy as np

from .base import MXNetError

_MAGIC = 0x112
# mshadow type flags (mshadow/base.h TypeFlag)
_DTYPES = {0: np.float32, 1: np.float64, 2: np.float16, 3: np.uint8,
           4: np.int32}
_FLAGS = {np.dtype(v): k for k, v in _DTYPES.items()}


class _Reader:
    def __init__(self, data: bytes):
        self.d = data
        self.o = 0

    def take(self, n):
        if self.o + n > len(self.d):
            raise MXNetError("truncated .params file")
        out = self.d[self.o:self.o + n]
        self.o += n
        return out

    def u64(self):
        return struct.unpack("<Q", self.take(8))[0]

    def u32(self):
        return struct.unpack("<I", self.take(4))[0]

    def i32(self):
        return struct.unpack("<i", self.take(4))[0]


def _read_ndarray(r: _Reader) -> np.ndarray:
    ndim = r.u32()                       # TShape::Save
    if ndim == 0:
        return None                      # is_none() array
    shape = struct.unpack("<%dI" % ndim, r.take(4 * ndim))
    r.i32()                              # Context dev_type (ignored: host)
    r.i32()                              # Context dev_id
    flag = r.i32()
    dt = _DTYPES.get(flag)
    if dt is None:
        raise MXNetError(f"unknown mshadow type flag {flag}")
    count = int(np.prod(shape)) if ndim else 1
    raw = r.take(count * np.dtype(dt).itemsize)
    return np.frombuffer(raw, dtype=dt).reshape(shape).copy()


def _read_names(r: _Reader):
    n = r.u64()
    return [r.take(r.u64()).decode() for _ in range(n)]


def load_params_raw(fname: str) -> Dict:
    """Read a reference NDArray-list file -> {original_name: NDArray}
    (names exactly as stored, including any ``arg:``/``aux:`` prefixes)."""
    from .ndarray import NDArray

    with open(fname, "rb") as f:
        r = _Reader(f.read())
    if r.u64() != _MAGIC:
        raise MXNetError(f"{fname}: not a reference NDArray file "
                         "(bad magic)")
    r.u64()  # reserved
    n = r.u64()
    arrays = [_read_ndarray(r) for _ in range(n)]
    names = _read_names(r)
    if names and len(names) != len(arrays):
        raise MXNetError(f"{fname}: {len(arrays)} arrays but "
                         f"{len(names)} names")
    if not names:
        names = [str(i) for i in range(len(arrays))]
    return {name: NDArray(a) for name, a in zip(names, arrays)
            if a is not None}


def load_params(fname: str) -> Tuple[Dict, Dict]:
    """Read a reference ``.params`` file -> (arg_params, aux_params),
    splitting the ``arg:``/``aux:`` name prefixes the reference's
    save_checkpoint writes (unprefixed names land in arg_params)."""
    arg, aux = {}, {}
    for name, v in load_params_raw(fname).items():
        if name.startswith("aux:"):
            aux[name[4:]] = v
        elif name.startswith("arg:"):
            arg[name[4:]] = v
        else:
            arg[name] = v
    return arg, aux


def save_params(fname: str, arg_params: Dict, aux_params: Dict = None):
    """Write arg/aux dicts in the reference's binary format (the inverse
    of load_params; lets checkpoints flow back to the reference)."""
    chunks = [struct.pack("<QQ", _MAGIC, 0)]
    items = [("arg:" + k, v) for k, v in (arg_params or {}).items()]
    items += [("aux:" + k, v) for k, v in (aux_params or {}).items()]
    chunks.append(struct.pack("<Q", len(items)))
    for _, v in items:
        a = np.ascontiguousarray(np.asarray(
            v.asnumpy() if hasattr(v, "asnumpy") else v))
        if a.dtype not in _FLAGS:
            a = a.astype(np.float32)
        if a.ndim == 0:
            # ndim==0 means "none array" in the reference format (the
            # reader stops after the shape) — store scalars as (1,)
            a = a.reshape(1)
        chunks.append(struct.pack("<I", a.ndim))
        chunks.append(struct.pack("<%dI" % a.ndim, *a.shape))
        chunks.append(struct.pack("<ii", 1, 0))     # cpu context
        chunks.append(struct.pack("<i", _FLAGS[a.dtype]))
        chunks.append(a.tobytes())
    chunks.append(struct.pack("<Q", len(items)))
    for name, _ in items:
        b = name.encode()
        chunks.append(struct.pack("<Q", len(b)))
        chunks.append(b)
    with open(fname, "wb") as f:
        f.write(b"".join(chunks))


# ---------------------------------------------------------------------------
# symbol JSON (incl. legacy upgrade)
# ---------------------------------------------------------------------------
_OP_RENAMES = {
    # pre-0.9 names upgraded by legacy_json_util.cc
    "BatchNorm_v1": "BatchNorm",
    "Convolution_v1": "Convolution",
    "Pooling_v1": "Pooling",
}


def load_symbol_json(text: str):
    """Build a Symbol from reference/nnvm JSON.

    Handles every vintage the reference's loader handles: per-node attr
    dicts under ``param`` (pre-0.9), ``attr`` or ``attrs``; 2- or
    3-element input references; aux inputs recognized from the op
    registry so BatchNorm moving stats round-trip as auxiliary states.
    """
    from . import ops
    from .symbol import Symbol, _Node

    data = json.loads(text)
    if "nodes" not in data:
        raise MXNetError("not a symbol JSON file (no 'nodes')")
    nodes = []
    aux_entries = set()  # (node_id,) of variables that feed aux slots
    jnodes = data["nodes"]
    # first pass: find which variable nodes feed aux arg positions
    for jn in jnodes:
        opname = _OP_RENAMES.get(jn["op"], jn["op"])
        if opname == "null":
            continue
        try:
            od = ops.get(opname)
        except Exception as exc:
            raise MXNetError(
                f"symbol JSON references unknown op {opname!r}") from exc
        if not od.aux_names:
            continue
        attrs = _node_attrs(jn)
        arg_names = list(od.resolve_arg_names(attrs)) + list(od.aux_names)
        for pos, ref in enumerate(jn["inputs"]):
            if pos < len(arg_names) and arg_names[pos] in od.aux_names:
                aux_entries.add(ref[0])
    for i, jn in enumerate(jnodes):
        opname = _OP_RENAMES.get(jn["op"], jn["op"])
        if opname == "null":
            node = _Node(None, jn["name"], is_aux=i in aux_entries,
                         extra_attrs=_extra_attrs(jn))
        else:
            attrs = _node_attrs(jn)
            node = _Node(opname, jn["name"], attrs=attrs,
                         extra_attrs=_extra_attrs(jn))
            node.inputs = [(nodes[ref[0]], ref[1] if len(ref) > 1 else 0)
                           for ref in jn["inputs"]]
            # pre-0.9 JSON omits aux inputs (BatchNorm moving stats):
            # append default-named variables, the same upgrade the
            # reference applies (legacy_json_util.cc
            # UpgradeJSON_000800_000900 — DefaultVarName "{op}_{arg}")
            od = ops.get(opname)
            expected = list(od.resolve_arg_names(attrs)) + list(od.aux_names)
            while len(node.inputs) < len(expected):
                arg_name = expected[len(node.inputs)]
                var = _Node(None, f"{jn['name']}_{arg_name}",
                            is_aux=arg_name in od.aux_names)
                node.inputs.append((var, 0))
        nodes.append(node)
    heads = data.get("heads") or [[len(nodes) - 1, 0, 0]]
    return Symbol([(nodes[h[0]], h[1] if len(h) > 1 else 0) for h in heads])


def _node_attrs(jn):
    """Op parameters: pre-0.9 files keep them under ``param`` (with user
    annotations separately under ``attr``); newer files merge everything
    into ``attrs``."""
    if "param" in jn:
        return dict(jn["param"])
    return dict(jn.get("attrs") or jn.get("attr") or {})


def _extra_attrs(jn):
    """User annotations (ctx_group, lr_mult, ...) — only separable in the
    legacy layout where op params live under ``param``."""
    if "param" in jn:
        return dict(jn.get("attr") or {})
    return {}


def load_symbol(fname: str):
    with open(fname) as f:
        return load_symbol_json(f.read())


def load_checkpoint(prefix: str, epoch: int):
    """Parity: model.load_checkpoint over reference-format files:
    ``prefix-symbol.json`` + ``prefix-%04d.params``."""
    sym = load_symbol(f"{prefix}-symbol.json")
    arg, aux = load_params("%s-%04d.params" % (prefix, epoch))
    return sym, arg, aux
