"""Implementation behind the general C API (src/c_api.cc).

Parity model: include/mxnet/c_api.h (reference, 115 fns) — this module
carries the logic for the subset that language bindings actually consume
(SURVEY.md App B: NDArray lifecycle, symbol composition, executor
bind/forward/backward, kvstore init/push/pull).  The native layer
(libmxtpu_capi.so) embeds CPython, marshals C buffers, and calls these
functions; XLA does the math, exactly like the predict ABI
(src/c_predict.cc).

Handles on the C side are plain ``PyObject*``; every function here takes
and returns Python objects that the C layer owns via refcounts.  Errors
propagate as exceptions — the C layer converts them to -1 +
MXGetLastError, mirroring the reference's c_api_error.
"""
from __future__ import annotations

import numpy as np

from . import ndarray as nd
from . import symbol as _symbol_mod
from .base import MXNetError
from .context import cpu, Context
from .ndarray import NDArray


def _ctx(dev_type, dev_id):
    # dev_type follows the predict ABI: 1 = cpu, 2 = accelerator
    if int(dev_type) == 1:
        return cpu(int(dev_id))
    from .context import default_accelerator_context

    return default_accelerator_context()


# ------------------------------------------------------------------ NDArray
def ndarray_create(shape, dev_type, dev_id):
    return nd.zeros(tuple(int(s) for s in shape), ctx=_ctx(dev_type, dev_id))


def ndarray_shape(arr):
    return [int(s) for s in arr.shape]


def ndarray_sync_copy_from(arr, buf):
    """buf: C float32 buffer (bytes/memoryview) of exactly arr.size items.

    The copy MUST be materialized before returning: ``buf`` views borrowed
    C memory the caller may free the moment this returns (sync semantics,
    like the reference's SyncCopyFromCPU WaitToWrite)."""
    src = np.frombuffer(buf, dtype=np.float32, count=arr.size).copy()
    arr[:] = src.reshape(arr.shape)
    arr._read().block_until_ready()


def ndarray_sync_copy_to(arr):
    """Returns float32 bytes; blocks until the value is computed."""
    return np.ascontiguousarray(arr.asnumpy(), dtype=np.float32).tobytes()


def ndarray_wait_all():
    from . import engine

    engine.wait_all()


# ------------------------------------------------------------------- Symbol
class _AtomicSymbol:
    """MXSymbolCreateAtomicSymbol result: an op + attrs awaiting Compose
    (parity: c_api.cc CreateAtomicSymbol -> Symbol::Compose)."""

    def __init__(self, op, attrs):
        self.op = op
        self.attrs = attrs
        self.symbol = None  # set by compose


def symbol_list_atomic_creators():
    from .ops import registry

    return sorted(registry.list_ops())


def symbol_create_atomic(op_name, keys, vals):
    from . import sym

    if not hasattr(sym, op_name):
        raise MXNetError(f"unknown operator {op_name!r}")
    return _AtomicSymbol(op_name, dict(zip(keys, vals)))


def symbol_create_variable(name):
    return _symbol_mod.Variable(name)


def symbol_compose(handle, name, keys, args):
    """Fill an atomic symbol's inputs (keys may be empty = positional)."""
    from . import sym

    if isinstance(handle, _AtomicSymbol):
        fn = getattr(sym, handle.op)
        kwargs = dict(handle.attrs)
        if name:
            kwargs["name"] = name
        inputs = [_sym(a) if isinstance(a, _AtomicSymbol) else a for a in args]
        if keys:
            kwargs.update(dict(zip(keys, inputs)))
            handle.symbol = fn(**kwargs)
        else:
            handle.symbol = fn(*inputs, **kwargs)
        return handle
    raise MXNetError("Compose target must be an atomic symbol")


def _sym(handle):
    if isinstance(handle, _AtomicSymbol):
        if handle.symbol is None:
            raise MXNetError(f"atomic symbol {handle.op!r} is not composed yet")
        return handle.symbol
    return handle


def symbol_from_json(json_str):
    return _symbol_mod.load_json(json_str)


def symbol_to_json(handle):
    return _sym(handle).tojson()


def symbol_list_arguments(handle):
    return _sym(handle).list_arguments()


def symbol_list_outputs(handle):
    return _sym(handle).list_outputs()


def symbol_list_auxiliary_states(handle):
    return _sym(handle).list_auxiliary_states()


def symbol_infer_shape(handle, keys, shapes):
    s = _sym(handle)
    arg_shapes, out_shapes, aux_shapes = s.infer_shape(
        **{k: tuple(v) for k, v in zip(keys, shapes)})
    to_list = lambda shs: [[int(d) for d in sh] for sh in shs]  # noqa: E731
    return to_list(arg_shapes), to_list(out_shapes), to_list(aux_shapes)


# ----------------------------------------------------------------- Executor
def executor_simple_bind(handle, dev_type, dev_id, grad_req, keys, shapes):
    s = _sym(handle)
    return s.simple_bind(ctx=_ctx(dev_type, dev_id), grad_req=grad_req,
                         **{k: tuple(v) for k, v in zip(keys, shapes)})


def executor_forward(ex, is_train):
    ex.forward(is_train=bool(is_train))


def executor_backward(ex):
    ex.backward()


def executor_num_outputs(ex):
    return len(ex.outputs)


def executor_output(ex, index):
    return ex.outputs[int(index)]


def executor_arg_array(ex, name):
    try:
        return ex.arg_dict[name]
    except KeyError:
        raise MXNetError(f"no argument named {name!r}")


def executor_grad_array(ex, name):
    g = ex.grad_dict.get(name)
    if g is None:
        raise MXNetError(f"no gradient for {name!r} (grad_req null?)")
    return g


def executor_arg_names(ex):
    return list(ex.arg_dict.keys())


# ------------------------------------------------------------------ KVStore
def kvstore_create(kv_type):
    from . import kvstore

    return kvstore.create(kv_type.decode() if isinstance(kv_type, bytes)
                          else kv_type)


def kvstore_init(kv, keys, vals):
    kv.init(list(keys), list(vals))


def kvstore_push(kv, keys, vals, priority):
    kv.push(list(keys), list(vals), priority=int(priority))


def kvstore_pull(kv, keys, outs, priority):
    kv.pull(list(keys), out=list(outs), priority=int(priority))


def kvstore_set_updater(kv, py_callback):
    """py_callback(key:int, recv:NDArray, local:NDArray) — the C layer
    wraps the user's C function pointer in a Python callable."""
    kv._set_updater(py_callback)


def kvstore_rank(kv):
    return int(kv.rank)


def kvstore_num_workers(kv):
    return int(kv.num_workers)


# --------------------------------------------------------------------- misc
def random_seed(seed):
    from . import random as _random

    _random.seed(int(seed))


# ------------------------------------------------------------ imperative
def imperative_invoke(op_name, inputs, keys, vals):
    """MXImperativeInvoke: run a registered op imperatively on NDArray
    handles; returns the list of output NDArrays."""
    from . import ndarray as nd
    from . import ops as _ops

    # only REGISTERED ops: a bare getattr would expose every module
    # attribute (classes, helpers, np/jax) to the C ABI
    if op_name not in _ops.list_ops():
        raise ValueError(f"unknown imperative op {op_name!r}")
    fn = getattr(nd, op_name, None)
    if fn is None:
        raise ValueError(f"op {op_name!r} has no imperative binding")
    attrs = dict(zip(keys, vals))
    out = fn(*inputs, **attrs)
    return list(out) if isinstance(out, (list, tuple)) else [out]


# -------------------------------------------------------------- data iter
class _IterState:
    __slots__ = ("it", "batch")

    def __init__(self, it):
        self.it = it
        self.batch = None


def _parse_iter_val(v):
    import ast

    try:
        return ast.literal_eval(v)
    except (ValueError, SyntaxError):
        return v


def _iter_registry():
    from . import io as mio

    return {
        "MNISTIter": mio.MNISTIter,
        "CSVIter": mio.CSVIter,
        "ImageRecordIter": mio.ImageRecordIter,
    }


def list_data_iters():
    return sorted(_iter_registry())


def data_iter_create(name, keys, vals):
    reg = _iter_registry()
    if name not in reg:
        raise ValueError(f"unknown iterator {name!r}; have {sorted(reg)}")
    kwargs = {k: _parse_iter_val(v) for k, v in zip(keys, vals)}
    return _IterState(reg[name](**kwargs))


def data_iter_next(state):
    try:
        state.batch = state.it.next()
        return 1
    except StopIteration:
        state.batch = None
        return 0


def data_iter_before_first(state):
    state.it.reset()
    state.batch = None


def _batch_part(state, part):
    if state.batch is None:
        raise ValueError("no current batch; call MXDataIterNext first")
    arrs = getattr(state.batch, part)
    if not arrs:
        raise ValueError(f"batch has no {part}")
    return arrs[0]


def data_iter_data(state):
    return _batch_part(state, "data")


def data_iter_label(state):
    return _batch_part(state, "label")


def data_iter_pad(state):
    if state.batch is None:
        raise ValueError("no current batch; call MXDataIterNext first")
    return int(state.batch.pad or 0)
