"""RNN toolkit (parity: python/mxnet/rnn/)."""
from .rnn_cell import (BaseRNNCell, BidirectionalCell, DropoutCell,
                       FusedRNNCell, GRUCell, LSTMCell, LSTMPCell,
                       ModifierCell, RNNCell, RNNParams, SequentialRNNCell,
                       ZoneoutCell)
from .io import BucketSentenceIter
