"""RNN toolkit (parity: python/mxnet/rnn/)."""
from .rnn_cell import (BaseRNNCell, BidirectionalCell, DropoutCell,
                       FusedRNNCell, GRUCell, LSTMCell, LSTMPCell,
                       ModifierCell, RNNCell, RNNParams, SequentialRNNCell,
                       ZoneoutCell)
from .io import BucketSentenceIter


def rnn_unroll(cell, length, inputs=None, begin_state=None,
               input_prefix="", layout="NTC"):
    """Parity: rnn/rnn.py rnn_unroll — the module-level unroll the
    reference exposes alongside cell.unroll()."""
    return cell.unroll(length, inputs=inputs, begin_state=begin_state,
                       input_prefix=input_prefix, layout=layout)
