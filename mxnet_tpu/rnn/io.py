"""Bucketed sequence iterator (parity: python/mxnet/rnn/io.py
BucketSentenceIter) — bins variable-length sentences by bucket, pads to the
bucket length, yields batches with bucket_key so BucketingModule switches
executors (= jit cache entries on TPU, SURVEY.md §5.7)."""
from __future__ import annotations

import bisect
import random as pyrandom

import numpy as np

from .. import ndarray as nd
from ..io import DataBatch, DataDesc, DataIter


class BucketSentenceIter(DataIter):
    def __init__(self, sentences, batch_size, buckets=None, invalid_label=-1,
                 data_name="data", label_name="softmax_label", dtype="float32",
                 layout="NT", init_states=None):
        super().__init__()
        if not buckets:
            buckets = [i for i, j in enumerate(np.bincount([len(s) for s in sentences]))
                       if j >= batch_size]
        buckets.sort()
        ndiscard = 0
        self.data = [[] for _ in buckets]
        for sentence in sentences:
            buck = bisect.bisect_left(buckets, len(sentence))
            if buck == len(buckets):
                ndiscard += 1
                continue
            buff = np.full((buckets[buck],), invalid_label, dtype=dtype)
            buff[: len(sentence)] = sentence
            self.data[buck].append(buff)
        self.data = [np.asarray(i, dtype=dtype) for i in self.data]
        self.batch_size = batch_size
        self.buckets = buckets
        self.data_name = data_name
        self.label_name = label_name
        self.invalid_label = invalid_label
        self.nddata = []
        self.ndlabel = []
        self.major_axis = layout.find("N")
        self.default_bucket_key = max(buckets)
        # init_states: [(name, shape)] appended to provide_data with zero
        # arrays per batch (parity: the v0.9 lstm_bucketing pattern that
        # feeds l*_init_c/h shapes through the iterator)
        self.init_states = list(init_states or [])
        self._init_arrays = [nd.array(np.zeros(s, dtype))
                             for _, s in self.init_states]
        self.provide_data = [DataDesc(data_name, (batch_size, self.default_bucket_key))] + \
            [DataDesc(n, s) for n, s in self.init_states]
        self.provide_label = [DataDesc(label_name, (batch_size, self.default_bucket_key))]
        self.idx = []
        for i, buck in enumerate(self.data):
            self.idx.extend([(i, j) for j in range(0, len(buck) - batch_size + 1,
                                                   batch_size)])
        self.curr_idx = 0
        self.reset()

    def reset(self):
        self.curr_idx = 0
        pyrandom.shuffle(self.idx)
        for buck in self.data:
            np.random.shuffle(buck)
        self.nddata = []
        self.ndlabel = []
        for buck in self.data:
            label = np.empty_like(buck)
            label[:, :-1] = buck[:, 1:]
            label[:, -1] = self.invalid_label
            self.nddata.append(buck)
            self.ndlabel.append(label)

    def next(self):
        if self.curr_idx == len(self.idx):
            raise StopIteration
        i, j = self.idx[self.curr_idx]
        self.curr_idx += 1
        data = self.nddata[i][j : j + self.batch_size]
        label = self.ndlabel[i][j : j + self.batch_size]
        return DataBatch(
            [nd.array(data)] + self._init_arrays, [nd.array(label)], pad=0,
            bucket_key=self.buckets[i],
            provide_data=[DataDesc(self.data_name, data.shape)] +
                         [DataDesc(n, s) for n, s in self.init_states],
            provide_label=[DataDesc(self.label_name, label.shape)])
