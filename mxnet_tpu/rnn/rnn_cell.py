"""RNN cells for explicit unrolling.

Parity: python/mxnet/rnn/rnn_cell.py (reference): RNNCell, LSTMCell,
GRUCell, FusedRNNCell, SequentialRNNCell, BidirectionalCell, DropoutCell,
ZoneoutCell, ModifierCell + unroll.  Gate orders match the reference
(LSTM: i, g, f, o — rnn_cell.py:264-277).
"""
from __future__ import annotations

from .. import symbol
from ..base import MXNetError


class RNNParams:
    """Parameter container (parity: rnn_cell.py RNNParams)."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._params = {}

    def get(self, name, **kwargs):
        name = self._prefix + name
        if name not in self._params:
            self._params[name] = symbol.Variable(name, **kwargs)
        return self._params[name]


class BaseRNNCell:
    """Parity: rnn_cell.py BaseRNNCell."""

    def __init__(self, prefix="", params=None):
        if params is None:
            params = RNNParams(prefix)
            self._own_params = True
        else:
            self._own_params = False
        self._prefix = prefix
        self._params = params
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1

    def __call__(self, inputs, states):
        raise NotImplementedError

    @property
    def params(self):
        self._own_params = False
        return self._params

    @property
    def state_shape(self):
        raise NotImplementedError

    @property
    def _gate_names(self):
        return ()

    def begin_state(self, func=symbol.Variable, **kwargs):
        """Parity: BaseRNNCell.begin_state."""
        assert not self._modified
        states = []
        for info in self.state_shape:
            self._init_counter += 1
            state = func(f"{self._prefix}begin_state_{self._init_counter}", **kwargs)
            states.append(state)
        return states

    def unpack_weights(self, args):
        return dict(args)

    def pack_weights(self, args):
        return dict(args)

    def unroll(self, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC", merge_outputs=None):
        """Parity: BaseRNNCell.unroll."""
        self.reset()
        if inputs is None:
            inputs = [symbol.Variable(f"{input_prefix}t{i}_data") for i in range(length)]
        elif isinstance(inputs, symbol.Symbol):
            assert len(inputs) == 1
            axis = layout.find("T")
            inputs = symbol.SliceChannel(inputs, axis=axis, num_outputs=length,
                                         squeeze_axis=True)
        if begin_state is None:
            begin_state = self.begin_state()
        states = begin_state
        outputs = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
        if merge_outputs:
            outputs = [symbol.expand_dims(o, axis=1) for o in outputs]
            outputs = symbol.Concat(*outputs, dim=1)
        return outputs, states


class RNNCell(BaseRNNCell):
    """Vanilla RNN cell (parity: rnn_cell.py RNNCell:161)."""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._activation = activation
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_shape(self):
        return [(0, self._num_hidden)]

    def __call__(self, inputs, states):
        self._counter += 1
        name = f"{self._prefix}t{self._counter}_"
        i2h = symbol.FullyConnected(inputs, weight=self._iW, bias=self._iB,
                                    num_hidden=self._num_hidden, name=f"{name}i2h")
        h2h = symbol.FullyConnected(states[0], weight=self._hW, bias=self._hB,
                                    num_hidden=self._num_hidden, name=f"{name}h2h")
        output = symbol.Activation(i2h + h2h, act_type=self._activation,
                                   name=f"{name}out")
        return output, [output]


def _lstm_step(name, inputs, prev_h, prev_c, num_hidden, iW, iB, hW, hB):
    """The shared LSTM recurrence (gate order i, g(tanh), f, o):
    returns (next_h, next_c)."""
    i2h = symbol.FullyConnected(inputs, weight=iW, bias=iB,
                                num_hidden=num_hidden * 4, name=f"{name}i2h")
    h2h = symbol.FullyConnected(prev_h, weight=hW, bias=hB,
                                num_hidden=num_hidden * 4, name=f"{name}h2h")
    slice_gates = symbol.SliceChannel(i2h + h2h, num_outputs=4,
                                      name=f"{name}slice")
    in_gate = symbol.Activation(slice_gates[0], act_type="sigmoid")
    in_transform = symbol.Activation(slice_gates[1], act_type="tanh")
    forget_gate = symbol.Activation(slice_gates[2], act_type="sigmoid")
    out_gate = symbol.Activation(slice_gates[3], act_type="sigmoid")
    next_c = forget_gate * prev_c + in_gate * in_transform
    next_h = out_gate * symbol.Activation(next_c, act_type="tanh")
    return next_h, next_c


class LSTMCell(BaseRNNCell):
    """LSTM cell (parity: rnn_cell.py LSTMCell:224; gate order i,g,f,o)."""

    def __init__(self, num_hidden, prefix="lstm_", params=None, forget_bias=1.0):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_shape(self):
        return [(0, self._num_hidden), (0, self._num_hidden)]

    @property
    def _gate_names(self):
        return ["_i", "_f", "_c", "_o"]

    def __call__(self, inputs, states):
        self._counter += 1
        name = f"{self._prefix}t{self._counter}_"
        next_h, next_c = _lstm_step(name, inputs, states[0], states[1],
                                    self._num_hidden, self._iW, self._iB,
                                    self._hW, self._hB)
        return next_h, [next_h, next_c]


class LSTMPCell(BaseRNNCell):
    """LSTM with a linear projection of the hidden state (LSTMP,
    Sak et al. 2014 — the acoustic-model cell the reference builds
    inline in example/speech-demo/lstm_proj.py:49-56): the recurrence
    and the output both use ``r_t = W_r h_t`` with ``num_proj`` units,
    shrinking the h2h matmul from H×4H to P×4H.  State = [r, c]."""

    def __init__(self, num_hidden, num_proj, prefix="lstmp_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._num_proj = num_proj
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")
        self._pW = self.params.get("proj_weight")

    @property
    def state_shape(self):
        return [(0, self._num_proj), (0, self._num_hidden)]

    def __call__(self, inputs, states):
        self._counter += 1
        name = f"{self._prefix}t{self._counter}_"
        next_h, next_c = _lstm_step(name, inputs, states[0], states[1],
                                    self._num_hidden, self._iW, self._iB,
                                    self._hW, self._hB)
        next_r = symbol.FullyConnected(next_h, weight=self._pW, no_bias=True,
                                       num_hidden=self._num_proj,
                                       name=f"{name}proj")
        return next_r, [next_r, next_c]


class GRUCell(BaseRNNCell):
    """GRU cell (parity: rnn_cell.py GRUCell)."""

    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_shape(self):
        return [(0, self._num_hidden)]

    def __call__(self, inputs, states):
        self._counter += 1
        name = f"{self._prefix}t{self._counter}_"
        prev_state_h = states[0]
        i2h = symbol.FullyConnected(inputs, weight=self._iW, bias=self._iB,
                                    num_hidden=self._num_hidden * 3, name=f"{name}i2h")
        h2h = symbol.FullyConnected(prev_state_h, weight=self._hW, bias=self._hB,
                                    num_hidden=self._num_hidden * 3, name=f"{name}h2h")
        i2h_r, i2h_z, i2h = symbol.SliceChannel(i2h, num_outputs=3, name=f"{name}i2h_slice")
        h2h_r, h2h_z, h2h = symbol.SliceChannel(h2h, num_outputs=3, name=f"{name}h2h_slice")
        reset_gate = symbol.Activation(i2h_r + h2h_r, act_type="sigmoid")
        update_gate = symbol.Activation(i2h_z + h2h_z, act_type="sigmoid")
        next_h_tmp = symbol.Activation(i2h + reset_gate * h2h, act_type="tanh")
        next_h = update_gate * prev_state_h + (1.0 - update_gate) * next_h_tmp
        return next_h, [next_h]


class FusedRNNCell(BaseRNNCell):
    """Fused multi-layer RNN backed by the RNN op (parity: rnn_cell.py
    FusedRNNCell, which wraps the cuDNN op; here lax.scan)."""

    def __init__(self, num_hidden, num_layers=1, mode="lstm", bidirectional=False,
                 dropout=0.0, get_next_state=False, prefix=None, params=None):
        if prefix is None:
            prefix = f"{mode}_"
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._get_next_state = get_next_state
        self._parameters = self.params.get("parameters")

    @property
    def state_shape(self):
        dirs = 2 if self._bidirectional else 1
        n = [(self._num_layers * dirs, 0, self._num_hidden)]
        if self._mode == "lstm":
            n.append((self._num_layers * dirs, 0, self._num_hidden))
        return n

    def unroll(self, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC", merge_outputs=None):
        self.reset()
        if isinstance(inputs, symbol.Symbol):
            if layout == "NTC":
                inputs = symbol.SwapAxis(inputs, dim1=0, dim2=1)  # -> TNC
        else:
            inputs = [symbol.expand_dims(i, axis=0) for i in inputs]
            inputs = symbol.Concat(*inputs, dim=0)
        if begin_state is None:
            begin_state = self.begin_state()
        states = list(begin_state)
        kwargs = {"state": states[0]}
        if self._mode == "lstm":
            kwargs["state_cell"] = states[1]
        rnn = symbol.RNN(inputs, parameters=self._parameters,
                         mode=self._mode, state_size=self._num_hidden,
                         num_layers=self._num_layers,
                         bidirectional=self._bidirectional, p=self._dropout,
                         state_outputs=self._get_next_state,
                         name=f"{self._prefix}rnn", **kwargs)
        if self._get_next_state:
            outputs = rnn[0]
            next_states = [rnn[i] for i in range(1, len(rnn))]
        else:
            outputs, next_states = rnn, []
        if layout == "NTC":
            outputs = symbol.SwapAxis(outputs, dim1=0, dim2=1)
        return outputs, next_states


class SequentialRNNCell(BaseRNNCell):
    """Stack cells (parity: rnn_cell.py SequentialRNNCell)."""

    def __init__(self, params=None):
        super().__init__(prefix="", params=params)
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)

    @property
    def state_shape(self):
        return sum([c.state_shape for c in self._cells], [])

    def begin_state(self, **kwargs):
        assert not self._modified
        return sum([c.begin_state(**kwargs) for c in self._cells], [])

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        pos = 0
        for cell in self._cells:
            n = len(cell.state_shape)
            state = states[pos : pos + n]
            pos += n
            inputs, state = cell(inputs, state)
            next_states.extend(state)
        return inputs, next_states


class BidirectionalCell(BaseRNNCell):
    """Parity: rnn_cell.py BidirectionalCell."""

    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        super().__init__(prefix="", params=params)
        self._l_cell = l_cell
        self._r_cell = r_cell
        self._output_prefix = output_prefix

    @property
    def state_shape(self):
        return self._l_cell.state_shape + self._r_cell.state_shape

    def begin_state(self, **kwargs):
        return self._l_cell.begin_state(**kwargs) + self._r_cell.begin_state(**kwargs)

    def unroll(self, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC", merge_outputs=None):
        self.reset()
        if isinstance(inputs, symbol.Symbol):
            axis = layout.find("T")
            inputs = symbol.SliceChannel(inputs, axis=axis, num_outputs=length,
                                         squeeze_axis=True)
            inputs = list(inputs)
        if begin_state is None:
            begin_state = self.begin_state()
        nl = len(self._l_cell.state_shape)
        l_outputs, l_states = self._l_cell.unroll(
            length, inputs=inputs, begin_state=begin_state[:nl], layout=layout)
        r_outputs, r_states = self._r_cell.unroll(
            length, inputs=list(reversed(inputs)),
            begin_state=begin_state[nl:], layout=layout)
        if isinstance(r_outputs, list):
            r_outputs = list(reversed(r_outputs))
        outputs = [
            symbol.Concat(l, r, dim=1, name=f"{self._output_prefix}t{i}")
            for i, (l, r) in enumerate(zip(l_outputs, r_outputs))
        ]
        return outputs, l_states + r_states


class ModifierCell(BaseRNNCell):
    """Parity: rnn_cell.py ModifierCell."""

    def __init__(self, base_cell):
        super().__init__()
        base_cell._modified = True
        self.base_cell = base_cell

    @property
    def params(self):
        self._own_params = False
        return self.base_cell.params

    @property
    def state_shape(self):
        return self.base_cell.state_shape

    def begin_state(self, init_sym=symbol.Variable, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=init_sym, **kwargs)
        self.base_cell._modified = True
        return begin


class DropoutCell(BaseRNNCell):
    """Parity: rnn_cell.py DropoutCell — dropout as a cell."""

    def __init__(self, dropout=0.0, prefix="dropout_", params=None):
        super().__init__(prefix=prefix, params=params)
        self.dropout = dropout

    @property
    def state_shape(self):
        return []

    def __call__(self, inputs, states):
        if self.dropout > 0:
            inputs = symbol.Dropout(inputs, p=self.dropout)
        return inputs, states


class ZoneoutCell(ModifierCell):
    """Parity: rnn_cell.py ZoneoutCell — stochastic state preservation."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self.prev_output = None

    def __call__(self, inputs, states):
        cell = self.base_cell
        next_output, next_states = cell(inputs, states)
        if self.zoneout_outputs > 0.0:
            prev = self.prev_output
            if prev is None:
                prev = next_output * 0.0
            mask = symbol.Dropout(next_output * 0.0 + 1.0, p=self.zoneout_outputs)
            next_output = mask * next_output * (1.0 - self.zoneout_outputs) + \
                (1.0 - mask * (1.0 - self.zoneout_outputs)) * prev
        if self.zoneout_states > 0.0:
            new_states = []
            for ns, s in zip(next_states, states):
                mask = symbol.Dropout(ns * 0.0 + 1.0, p=self.zoneout_states)
                new_states.append(mask * ns * (1.0 - self.zoneout_states) +
                                  (1.0 - mask * (1.0 - self.zoneout_states)) * s)
            next_states = new_states
        self.prev_output = next_output
        return next_output, next_states
