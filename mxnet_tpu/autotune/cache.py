"""The schedule cache: pure lookup side of the autotuner.

This module is import-light (stdlib only; jax is touched lazily and
exactly once, to name the device kind) and side-effect-free on the hot
path: :func:`schedule_for` is safe to call from *traced* code — it
reads a process-wide memo (populated from the on-disk cache at most
once) and never touches telemetry, the clock, or the device.  All
measuring, counting and persistence lives in
:mod:`mxnet_tpu.autotune.search`.

Cache layout (``MXTPU_SCHEDULE_CACHE``) — one JSON document::

    {"version": 1,
     "entries": {
       "<device_kind>": {
         "<kernel>|<keysig>": {"schedule": {...}, "best_us": 12.3,
                               "trials": 5}}}}

Entries are segregated by *device kind* (``jax.devices()[0]
.device_kind``, sanitized): a CPU-rig search can never pollute the
schedules a TPU host will load, and one shared cache file serves a
heterogeneous fleet.  A corrupt, unreadable or version-mismatched file
degrades to an empty cache — defaults win, nothing raises.

Modes (parsed by :func:`cache_spec` from ``MXTPU_SCHEDULE_CACHE``):

- unset / ``""`` / ``off`` / ``0`` — autotuning off: every consumer
  uses its built-in default schedule;
- ``readonly:<path>`` — load winners, never search, never write
  (production serving: tuned elsewhere, pinned here);
- ``search:<path>`` or a bare ``<path>`` — load winners, search on
  miss, persist new winners atomically.
"""
from __future__ import annotations

import json
import os
import re
import threading

__all__ = [
    "SCHEMA_VERSION", "cache_spec", "device_kind", "prime",
    "schedule_for", "record", "fingerprint", "load_file", "reset",
]

SCHEMA_VERSION = 1

_lock = threading.RLock()


class _CacheState:
    """Process-wide lookup state, held as attributes (not module
    globals) because :func:`schedule_for` runs at trace time and the
    trace-purity lint rightly bans ``global`` rebinding there."""

    def __init__(self):
        # (device_kind, kernel, keysig) -> schedule dict
        self.memo = {}
        # paths whose on-disk entries were folded into memo already
        self.loaded = set()
        # bumped on every record() and first disk load — composed into
        # the executor program-cache key (fingerprint), so a schedule
        # change invalidates programs that baked the old winner in
        self.epoch = 0
        self.kind = None


_state = _CacheState()


def cache_spec():
    """``(mode, path)`` from ``MXTPU_SCHEDULE_CACHE``: ``("off", None)``,
    ``("readonly", path)`` or ``("search", path)``."""
    raw = os.environ.get("MXTPU_SCHEDULE_CACHE", "").strip()
    if raw.lower() in ("", "0", "off", "false"):
        return ("off", None)
    if raw.startswith("readonly:"):
        return ("readonly", raw[len("readonly:"):])
    if raw.startswith("search:"):
        return ("search", raw[len("search:"):])
    return ("search", raw)


def device_kind() -> str:
    """Sanitized ``jax.devices()[0].device_kind`` — the segregation key
    of the on-disk cache.  Memoized; the one place this module touches
    jax."""
    if _state.kind is None:
        import jax

        kind = getattr(jax.devices()[0], "device_kind", "unknown")
        _state.kind = re.sub(r"[^A-Za-z0-9_.-]+", "_",
                             str(kind)).strip("_") or "unknown"
    return _state.kind


def load_file(path):
    """Parse one cache file; ``{}`` for anything unusable (missing,
    unreadable, bad JSON, wrong schema version, wrong shape)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return {}
    if not isinstance(doc, dict) or doc.get("version") != SCHEMA_VERSION:
        return {}
    entries = doc.get("entries")
    return entries if isinstance(entries, dict) else {}


def _fold_disk(path):
    """Merge ``path``'s entries for THIS device kind into the memo
    (memo entries win: in-process winners are fresher)."""
    kind = device_kind()
    loaded = 0
    for ks, ent in (load_file(path).get(kind) or {}).items():
        if "|" not in ks or not isinstance(ent, dict):
            continue
        kernel, keysig = ks.split("|", 1)
        sched = ent.get("schedule")
        if isinstance(sched, dict):
            _state.memo.setdefault((kind, kernel, keysig), sched)
            loaded += 1
    if loaded:
        _state.epoch += 1


def prime():
    """The MUTATING half of lookup: resolve the device kind and fold
    the on-disk cache into the memo.  Host-side bind/tune sites call
    this (``fingerprint`` at every executor bind, ``search.ensure`` at
    every tuning site) so :func:`schedule_for` can stay a pure READ
    even when tracing reaches it."""
    mode, path = cache_spec()
    if mode == "off":
        return
    device_kind()
    with _lock:
        if path not in _state.loaded:
            _state.loaded.add(path)
            _fold_disk(path)


def schedule_for(kernel: str, keysig: str, default):
    """The tuned schedule for ``(kernel, keysig)`` on this device kind,
    or ``default`` when autotuning is off / nothing is cached.

    PURE lookup — no telemetry, no clock, no device, no writes of any
    kind: callable from traced code (the residual epilogue picks its
    ``block_rows`` here at trace time).  The memo it reads is primed by
    the host-side bind paths (:func:`prime`); an unprimed process just
    gets defaults.  Hit/miss accounting happens in ``search.ensure``,
    which owns the measuring side."""
    mode, path = cache_spec()
    if mode == "off":
        return default
    with _lock:
        if _state.kind is None or path not in _state.loaded:
            return default
        return _state.memo.get((_state.kind, kernel, keysig), default)


def record(kernel: str, keysig: str, schedule, best_us, trials,
           persist=True):
    """Install a search winner in the memo and (in ``search`` mode,
    when ``persist``) merge it into the on-disk cache atomically
    (tmp file + ``os.replace``; existing entries for other kernels and
    device kinds are preserved)."""
    kind = device_kind()
    mode, path = cache_spec()
    with _lock:
        _state.memo[(kind, kernel, keysig)] = dict(schedule)
        _state.epoch += 1
        if not (persist and mode == "search" and path):
            return
        entries = load_file(path)
        entries.setdefault(kind, {})["%s|%s" % (kernel, keysig)] = {
            "schedule": dict(schedule),
            "best_us": round(float(best_us), 3),
            "trials": int(trials),
        }
        tmp = "%s.tmp.%d" % (path, os.getpid())
        try:
            with open(tmp, "w") as f:
                json.dump({"version": SCHEMA_VERSION, "entries": entries},
                          f, indent=1, sort_keys=True)
            os.replace(tmp, path)
        except OSError:
            # persistence is best-effort: the in-memory winner still
            # applies to this process
            try:
                os.remove(tmp)
            except OSError:
                pass


def fingerprint():
    """What the executor composes into its program-cache key: the cache
    mode + path select which winners load, the epoch invalidates
    programs that traced an older winner.  Called host-side at every
    bind, so it doubles as the priming hook — the disk cache is folded
    in BEFORE the epoch is read and BEFORE tracing consults
    :func:`schedule_for`."""
    prime()
    mode, path = cache_spec()
    with _lock:
        return (mode, path, _state.epoch)


def reset():
    """Forget every in-memory winner and disk load (test isolation)."""
    with _lock:
        _state.memo.clear()
        _state.loaded.clear()
        _state.epoch += 1
