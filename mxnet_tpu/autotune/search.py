"""The measuring side of the autotuner: bounded schedule search.

TVM's observation (arXiv:1802.04799), scoped to our two Pallas
consumers: no single hand-picked tiling wins across shapes and device
generations, but a SMALL per-(kernel, shape, dtype, device_kind) search
— warmup + best-of-k wall timing of each candidate, winner cached —
recovers the headroom at a one-time cost.  Searching happens only at
bind/admit-time call sites (``PagedSlots`` construction, an explicit
epilogue ``tune()``), NEVER per tick: ``measure`` blocks on the device
by design and is a declared ``analysis/config.py`` boundary, and the
steady-state loops only ever see the already-chosen schedule through
the pure :func:`~mxnet_tpu.autotune.cache.schedule_for`.
"""
from __future__ import annotations

import os
import time

from .. import telemetry as _tm
from . import cache as _cache

__all__ = ["trials_budget", "measure", "ensure"]

# --- autotune metric families (docs/telemetry.md) ---------------------------
_TM_TRIALS = _tm.counter(
    "autotune_trials_total",
    "candidate schedules benchmarked by the autotuner, per kernel "
    "(zero on a warm schedule cache: every consumer should hit)",
    labels=("kernel",))
_TM_CACHE = _tm.counter(
    "autotune_cache_total",
    "schedule-cache lookups at tuning call sites: hit = a persisted or "
    "in-process winner was reused, miss = none existed yet (a miss in "
    "search mode triggers a bounded search; in readonly mode the "
    "consumer keeps its default schedule)",
    labels=("result",))
_TM_BEST = _tm.gauge(
    "autotune_best_us",
    "best-of-k microseconds of the winning schedule at its last "
    "search, per kernel",
    labels=("kernel",))


def trials_budget() -> int:
    """``MXTPU_AUTOTUNE_TRIALS`` — max candidates measured per search
    (default 16; 0 disables searching while still honoring cached
    winners)."""
    try:
        return max(int(os.environ.get("MXTPU_AUTOTUNE_TRIALS", "16")
                       or 16), 0)
    except ValueError:
        return 16


def measure(fn, warmup=2, best_of=5):
    """Best-of-k wall microseconds of ``fn()`` (which must return
    device values; they are blocked on).  The autotuner's sanctioned
    sync boundary — never reachable from a steady-state loop."""
    import jax

    for _ in range(max(warmup, 0)):
        jax.block_until_ready(fn())
    best = float("inf")
    for _ in range(max(best_of, 1)):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        best = min(best, time.perf_counter() - t0)
    return best * 1e6


def ensure(kernel: str, keysig: str, default, candidates, bench_fn,
           warmup=2, best_of=5):
    """The tuning call site: return the schedule to install for
    ``(kernel, keysig)`` on this device kind.

    - cache ``off``: ``default``, no counters — the autotuner is out of
      the picture entirely;
    - cached winner (in-process or loaded from disk): count a ``hit``,
      return it — zero trials;
    - miss in ``readonly`` mode: count the miss, return ``default``;
    - miss in ``search`` mode: measure up to :func:`trials_budget`
      ``candidates`` through ``bench_fn(candidate) -> fn`` (the returned
      thunk is timed with warmup + best-of-k), record + persist the
      winner, return it.  A candidate whose build raises is skipped (a
      lowering's shape gate may reject it); if every candidate fails,
      ``default`` wins.

    ``default`` should normally appear in ``candidates`` so a search
    can never do worse than not searching.
    """
    mode, _path = _cache.cache_spec()
    if mode == "off":
        return default
    _cache.prime()
    sentinel = object()
    got = _cache.schedule_for(kernel, keysig, sentinel)
    if got is not sentinel:
        _TM_CACHE.inc(result="hit")
        return got
    _TM_CACHE.inc(result="miss")
    if mode == "readonly":
        return default
    best_sched, best_us, trials = None, float("inf"), 0
    budget = trials_budget()
    for cand in candidates:
        if trials >= budget:
            break
        try:
            fn = bench_fn(cand)
            us = measure(fn, warmup=warmup, best_of=best_of)
        except Exception:  # noqa: BLE001 — candidate rejected by its gate
            continue
        trials += 1
        if us < best_us:
            best_sched, best_us = cand, us
    if trials:
        _TM_TRIALS.inc(trials, kernel=kernel)
    if best_sched is None:
        return default
    _TM_BEST.set(best_us, kernel=kernel)
    _cache.record(kernel, keysig, best_sched, best_us, trials)
    if _tm.perf.enabled():
        _log_winner_roofline(kernel, best_us, trials)
    return best_sched


def _log_winner_roofline(kernel: str, best_us: float, trials: int):
    """Achieved-vs-roofline context for a search winner (perf plane,
    docs/perf_attr.md): when a cost row exists for a program whose
    label mentions the kernel, compare the winner's achieved wall to
    the analytical roofline floor — max(flops/peak_flops,
    bytes/peak_bw) — else just name the peaks the consumer's live MFU
    will be measured against.  Logging only; never raises."""
    import logging

    try:
        kind = _tm.perf.device_kind()
        pf = _tm.perf.peak_flops(kind)
        pb = _tm.perf.peak_bytes_per_sec(kind)
        row = next((r for r in _tm.perf.cost_table()
                    if kernel in r["program"]), None)
        msg = ("autotune: %s winner %.1fus over %d trials on %s"
               % (kernel, best_us, trials, kind))
        if row and pf and pb and (row["flops"] or row["bytes_accessed"]):
            floor_s = max((row["flops"] or 0.0) / pf,
                          (row["bytes_accessed"] or 0.0) / pb)
            msg += (" (roofline floor %.1fus, achieved %.0f%% of it)"
                    % (floor_s * 1e6,
                       100.0 * floor_s * 1e6 / best_us if best_us else 0.0))
        elif pf:
            msg += " (peak %.0f TFLOP/s, no cost row yet)" % (pf / 1e12)
        logging.getLogger("mxnet_tpu.autotune").info(msg)
    except Exception:  # noqa: BLE001 — reporting must never break a search
        pass
