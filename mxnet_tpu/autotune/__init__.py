"""Schedule autotuner (ISSUE 18): TVM-style search over Pallas
candidate configs per (kernel, shape signature, dtype, device kind),
winners persisted in the on-disk ``MXTPU_SCHEDULE_CACHE``.

Two halves with a hard purity line between them:

- :mod:`.cache` — the PURE lookup plane.  ``schedule_for`` is callable
  from traced code (no telemetry, no clock, no device);
  ``fingerprint`` is what ``executor._compiled_programs`` composes into
  the program-cache key so a new winner invalidates programs that
  traced the old one.
- :mod:`.search` — the measuring plane.  ``ensure`` runs the bounded
  search (``MXTPU_AUTOTUNE_TRIALS``) at bind/admit call sites only and
  owns the ``autotune_*`` telemetry families.

Consumers: the paged-attention kernel (``ops/paged_attention.py``,
tuned at ``PagedSlots`` construction) and the residual epilogue's
``block_rows`` (``ops/residual_epilogue.py``).  ``docs/autotune.md``
is the runbook, including how to make another kernel tunable.
"""
from .cache import (  # noqa: F401
    SCHEMA_VERSION, cache_spec, device_kind, fingerprint, load_file,
    prime, record, reset, schedule_for,
)
from .search import ensure, measure, trials_budget  # noqa: F401
