"""Custom operators written in Python.

Parity: python/mxnet/operator.py (808 LoC: CustomOp, CustomOpProp,
register, plus the legacy PythonOp/NumpyOp/NDArrayOp) and the C++ side
src/operator/custom-inl.h:29-249 / MXCustomOpRegister.

TPU-native design: instead of ctypes callback trampolines run as async
engine ops (FnProperty::kAsync), user Python runs on the host via
``jax.pure_callback`` — the XLA-sanctioned escape hatch — wired into the
graph with ``jax.custom_vjp`` so user-defined backward passes compose with
the rest of the autodiff'd computation.  Shape/type inference happens at
trace time through the prop's ``infer_shape``/``infer_type`` exactly like
the reference's CustomOpProp callbacks.
"""
from __future__ import annotations


import jax
import numpy as np

from .base import MXNetError

_PROPS: dict[str, type] = {}


class CustomOp:
    """Base class for user ops (parity: operator.py CustomOp).

    Subclasses implement forward/backward on host arrays.  ``assign``
    honors the req semantics (write/add/null) like the reference.
    """

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        if req in ("null", 0):
            return
        src = np.asarray(src.asnumpy() if hasattr(src, "asnumpy") else src)
        if req in ("add", "add_to"):
            dst._npvalue[...] = dst._npvalue + src
        else:  # write / inplace
            dst._npvalue[...] = src


class CustomOpProp:
    """Op metadata + factory (parity: operator.py CustomOpProp)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]] * len(self.list_outputs()), []

    def infer_type(self, in_type):
        t = in_type[0] if in_type else np.float32
        return ([t] * len(self.list_arguments()),
                [t] * len(self.list_outputs()),
                [t] * len(self.list_auxiliary_states()))

    def need_top_grad(self):
        return self.need_top_grad_

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad():
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes):
        raise NotImplementedError


def register(reg_name):
    """Parity: mx.operator.register — decorator registering a CustomOpProp
    under ``op_type`` for use as ``mx.sym.Custom(..., op_type=reg_name)``."""

    def deco(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise MXNetError("can only register subclasses of CustomOpProp")
        _PROPS[reg_name] = prop_cls
        # a re-registered op_type may change list_outputs(); stale cached
        # counts would mis-shape every later sym.Custom graph pass
        from .ops.custom import invalidate_num_outputs_cache

        invalidate_num_outputs_cache(reg_name)
        # a structurally-identical graph bound after a re-register must
        # not reuse programs traced through the OLD prop — the signature
        # only sees op_type, not the class behind it
        from .executor import program_cache_clear

        program_cache_clear()
        return prop_cls

    return deco


def get_prop(op_type: str, config=None) -> CustomOpProp:
    """Instantiate the registered Prop.  ``config`` carries the user
    kwargs from the sym.Custom call, passed to the Prop constructor AS
    STRINGS (reference parity: custom-inl.h forwards the symbol's
    key/value attrs to CustomOpProp.__init__ — e.g.
    weighted_logistic_regression's pos_grad_scale)."""
    try:
        cls = _PROPS[op_type]
    except KeyError:
        raise MXNetError(f"custom op type '{op_type}' is not registered "
                         "(use @mx.operator.register)") from None
    # canonical text for sequence kwargs is the TUPLE form ('(1, 2)') —
    # what the reference frontend's str(v) emits for the tuple kwargs
    # users write (kernel=(3, 3)).  frozen_attrs round-trips every
    # sequence as a tuple through the imperative jit cache, so
    # canonicalizing lists to tuples here makes both frontends (and both
    # sides of the cache) stringify identically.
    kwargs = {k: (str(tuple(v)) if isinstance(v, (list, tuple)) else str(v))
              for k, v in (config or {}).items()}
    return cls(**kwargs)


class _HostArray:
    """Minimal NDArray-alike handed to user forward/backward callbacks:
    supports .asnumpy(), .shape, .dtype, and in-place writes through
    CustomOp.assign."""

    __slots__ = ("_npvalue",)

    def __init__(self, arr):
        self._npvalue = np.asarray(arr)

    def asnumpy(self):
        return self._npvalue

    @property
    def shape(self):
        return self._npvalue.shape

    @property
    def dtype(self):
        return self._npvalue.dtype

    def __array__(self, dtype=None):
        return self._npvalue if dtype is None else self._npvalue.astype(dtype)


# ---------------------------------------------------------------------------
# Legacy numpy-callback op styles kept for API parity
# (reference: PythonOp/NumpyOp/NDArrayOp in python/mxnet/operator.py; the
# reference itself marks them deprecated in favor of CustomOp).
# ---------------------------------------------------------------------------
class PythonOp:
    """Deprecated base (parity: operator.py PythonOp).  Use CustomOp."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def __call__(self, *args, **kwargs):
        return self.get_symbol(*args, **kwargs)

    def get_symbol(self, *args, **kwargs):
        raise NotImplementedError

    def forward(self, in_data, out_data):
        raise NotImplementedError

    def backward(self, out_grad, in_data, out_data, in_grad):
        raise NotImplementedError

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]]

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def need_top_grad(self):
        return self.need_top_grad_


class NumpyOp(PythonOp):
    """Parity shim for the deprecated NumpyOp: adapts the simple
    forward(in_data, out_data) protocol onto the CustomOp machinery."""

    def get_symbol(self, *args, **kwargs):
        from . import symbol as sym

        outer = self
        name = f"_numpy_op_{type(self).__name__}_{id(self):x}"

        class _Prop(CustomOpProp):
            def __init__(self):
                super().__init__(need_top_grad=outer.need_top_grad())

            def list_arguments(self):
                return outer.list_arguments()

            def list_outputs(self):
                return outer.list_outputs()

            def infer_shape(self, in_shape):
                # 2-tuple returns are normalized at the Custom op's
                # call site (ops/custom.py), the single shim for both
                # the NumpyOp and direct-CustomOpProp paths
                return outer.infer_shape(in_shape)

            def create_operator(self, ctx, in_shapes, in_dtypes):
                class _Op(CustomOp):
                    def forward(self, is_train, req, in_data, out_data, aux):
                        outer.forward([d.asnumpy() for d in in_data],
                                      [o._npvalue for o in out_data])

                    def backward(self, req, out_grad, in_data, out_data,
                                 in_grad, aux):
                        outer.backward([g.asnumpy() for g in out_grad],
                                       [d.asnumpy() for d in in_data],
                                       [o.asnumpy() for o in out_data],
                                       [g._npvalue for g in in_grad])

                return _Op()

        if name not in _PROPS:
            _PROPS[name] = _Prop
        return sym._make_symbol_fn("Custom")(*args, op_type=name, **kwargs)


NDArrayOp = NumpyOp  # reference exposes both protocols; one shim serves
