"""Fault-injection harness (``MXTPU_FAULT_PLAN``) — the chaos half of the
survival layer (docs/fault_tolerance.md).

The paper's parameter-server design (ps-lite) assumed workers and
servers die and rejoin; the recovery paths that make that survivable —
dist retry/backoff, checkpoint fallback, serving admission guards —
only stay correct if they are *exercised*.  This module injects failures
at named sites so tests (and chaos soaks) can prove every fault path
terminates in either recovery or a clean, named error carrying the
flight-record dump, never a hang or silent corruption.

Plan grammar (comma-separated entries, one per site; last entry for a
site wins)::

    MXTPU_FAULT_PLAN="kv_push:err:0.01,dist_send:drop:0.05,ckpt_write:crash_after:3"

    <site> : <mode> : <arg>

Modes:

``err:<p>``
    Raise :class:`InjectedFault` at the site with probability ``p``
    (``err:1`` = every hit).
``drop:<p>``
    Simulated transport loss with probability ``p`` — the call site
    interprets it (dist send/recv: the socket breaks mid-RPC; the
    retry/backoff path must recover).
``err_first:<n>`` / ``drop_first:<n>``
    Deterministic variants: fail the first ``n`` hits of the site, then
    pass forever — the shape tests use to pin "fails once, recovers".
``crash_after:<n>``
    Let ``n`` hits pass, then hard-kill the process (``os._exit(137)``)
    on hit ``n+1`` — a preemption simulator for kill/resume tests.

Sites wired in this codebase: ``kv_push`` / ``kv_pull`` (kvstore eager +
fused batched entry), ``dist_send`` / ``dist_recv`` (KVStoreDist RPC
transport), ``ckpt_write`` (checkpoint writer), ``serve_admit`` (serving
admission), ``dist_barrier`` (cross-host barrier — a drop simulates the
dead-peer timeout and raises ``HostLostError`` without the wait),
``coord_heartbeat`` (coordinator client heartbeat — a drop loses the
beat so the lease decays and the coordinator declares the host dead),
``host_crash`` (fired per step from the coordinator poll —
``crash_after:n`` is the SIGKILL-shaped mid-training death the elastic
chaos tests use), ``slow_step`` (flight-recorder step record — a drop
parks the host ``MXTPU_FAULT_SLOW_S`` per step, the injected-straggler
the fleet skew detector must name), ``replica_kill`` (fired per
serving engine tick — ``crash_after:n`` is the SIGKILL-shaped
mid-request replica death the serving router's re-route/502 paths must
survive, tests/test_serving_fleet.py), ``serve_slow`` (fired per
serving engine tick — a ``drop`` parks the engine thread
``MXTPU_FAULT_SLOW_S`` per tick, so queue wait and TTFT genuinely
inflate: the injected latency the SLO plane's burn-rate and exemplar
paths are tested against, tests/test_tracing.py).  Any other site
string is legal —
call sites define the namespace; unknown sites in a plan simply never
fire.

Draws are deterministic under ``MXTPU_FAULT_SEED`` (default 0) so a
failing chaos soak replays exactly.  Every injected fault counts in
``fault_injected_total{site,mode}``.
"""
from __future__ import annotations

import logging
import os
import random
import threading

from .base import MXNetError
from . import telemetry as _tm

__all__ = ["InjectedFault", "plan", "active", "fire", "maybe_fail",
           "should_drop", "reset"]

_logger = logging.getLogger("mxnet_tpu.faults")

# --- telemetry families (docs/telemetry.md) --------------------------------
_TM_INJECTED = _tm.counter(
    "fault_injected_total",
    "faults injected by the MXTPU_FAULT_PLAN harness at a named site "
    "(mode=err/drop/crash)", labels=("site", "mode"))

_MODES = ("err", "drop", "err_first", "drop_first", "crash_after")


class InjectedFault(MXNetError):
    """A failure injected by ``MXTPU_FAULT_PLAN`` (never raised in
    production configurations — the plan env is the only trigger)."""


class _Entry:
    __slots__ = ("site", "mode", "arg", "hits")

    def __init__(self, site, mode, arg):
        self.site = site
        self.mode = mode
        self.arg = arg
        self.hits = 0


_lock = threading.Lock()
_state = {"raw": None, "plan": {}, "rng": None}


def _parse(raw: str):
    entries = {}
    for item in raw.split(","):
        item = item.strip()
        if not item:
            continue
        parts = item.split(":")
        if len(parts) != 3:
            raise MXNetError(
                f"MXTPU_FAULT_PLAN entry {item!r}: expected "
                "'<site>:<mode>:<arg>' "
                "(e.g. 'kv_push:err:0.01,ckpt_write:crash_after:3')")
        site, mode, arg = (p.strip() for p in parts)
        if mode not in _MODES:
            raise MXNetError(
                f"MXTPU_FAULT_PLAN entry {item!r}: unknown mode {mode!r} "
                f"(supported: {', '.join(_MODES)})")
        try:
            if mode in ("err", "drop"):
                val = float(arg)
                if not 0.0 <= val <= 1.0:
                    raise ValueError
            else:
                val = int(arg)
                if val < 0:
                    raise ValueError
        except ValueError:
            kind = ("a probability in [0, 1]" if mode in ("err", "drop")
                    else "a non-negative integer")
            raise MXNetError(
                f"MXTPU_FAULT_PLAN entry {item!r}: arg must be {kind}, "
                f"got {arg!r}") from None
        entries[site] = _Entry(site, mode, val)
    return entries


def plan() -> dict:
    """The parsed plan (site -> entry), re-read when the env changes so
    monkeypatched tests see their plan without a process restart."""
    raw = os.environ.get("MXTPU_FAULT_PLAN", "")
    with _lock:
        if raw != _state["raw"]:
            _state["plan"] = _parse(raw) if raw.strip() else {}
            _state["raw"] = raw
            _state["rng"] = random.Random(
                int(os.environ.get("MXTPU_FAULT_SEED", "0") or 0))
        return _state["plan"]


def active() -> bool:
    return bool(plan())


def reset():
    """Forget hit counters and the RNG stream (test isolation)."""
    with _lock:
        _state["raw"] = None
        _state["plan"] = {}
        _state["rng"] = None


def fire(site: str):
    """Evaluate the plan at ``site``.  Returns ``None`` (no fault),
    ``"err"`` or ``"drop"``; a tripped ``crash_after`` never returns
    (``os._exit(137)`` — the SIGKILL-shaped exit preemption tests
    expect).  Counts ``fault_injected_total{site,mode}``."""
    entries = plan()
    if not entries:
        return None
    e = entries.get(site)
    if e is None:
        return None
    with _lock:
        e.hits += 1
        hits = e.hits
        rng = _state["rng"]
        if e.mode in ("err", "drop"):
            tripped = rng.random() < e.arg
            action = e.mode if tripped else None
        elif e.mode in ("err_first", "drop_first"):
            action = e.mode.split("_")[0] if hits <= e.arg else None
        else:  # crash_after
            action = "crash" if hits > e.arg else None
    if action is None:
        return None
    if _tm.enabled():
        _TM_INJECTED.inc(site=site, mode=action)
    if action == "crash":
        _logger.error("MXTPU_FAULT_PLAN: crashing at site %r after %d "
                      "hits (crash_after:%d)", site, hits - 1, e.arg)
        # best-effort black box before the simulated preemption
        _tm.health.auto_dump("fault")
        os._exit(137)
    _logger.warning("MXTPU_FAULT_PLAN: injected %r at site %r (hit %d)",
                    action, site, hits)
    return action


def maybe_fail(site: str) -> bool:
    """Common call-site helper: raises :class:`InjectedFault` on ``err``
    (message names the site), returns True on ``drop`` (the caller
    simulates the transport loss), False when nothing fired."""
    action = fire(site)
    if action == "err":
        # the named error carries the black box (when
        # MXTPU_FLIGHT_RECORD names a dump path)
        dump = _tm.health.auto_dump("fault")
        raise InjectedFault(
            f"injected fault at site {site!r} (MXTPU_FAULT_PLAN)"
            + (f" (flight record: {dump})" if dump else ""))
    return action == "drop"


def should_drop(site: str) -> bool:
    """True when the plan asks this hit of ``site`` to lose its payload
    (``drop``/``drop_first``); ``err`` entries raise from here too so a
    transport site honors both shapes."""
    return maybe_fail(site)
