"""Network visualization (parity: python/mxnet/visualization.py:
plot_network graphviz rendering + print_summary table)."""
from __future__ import annotations


from .base import MXNetError
from .symbol import Symbol


def _node_label(node):
    op = node.op or "null"
    if op == "null":
        return node.name
    attrs = node.attrs or {}
    extras = []
    for k in ("num_hidden", "kernel", "stride", "num_filter", "pool_type",
              "act_type"):
        if k in attrs:
            extras.append(f"{k}={attrs[k]}")
    label = f"{node.name}\\n{op}"
    if extras:
        label += "\\n" + ", ".join(extras)
    return label


_OP_COLOR = {
    "Convolution": "#fb8072", "Deconvolution": "#fb8072",
    "FullyConnected": "#fb8072",
    "BatchNorm": "#bebada", "Activation": "#ffffb3", "LeakyReLU": "#ffffb3",
    "Pooling": "#80b1d3", "Concat": "#fdb462", "Flatten": "#fdb462",
    "Reshape": "#fdb462", "SoftmaxOutput": "#b3de69",
}


def plot_network(symbol, title="plot", shape=None, node_attrs=None,
                 hide_weights=True):
    """Build a graphviz Digraph of the symbol (parity:
    visualization.py plot_network).  Returns a ``graphviz.Digraph`` when
    the graphviz package is importable, else an object exposing
    ``.source`` with the DOT text (so tests and headless boxes work
    without the binary)."""
    if not isinstance(symbol, Symbol):
        raise MXNetError("plot_network requires a Symbol")
    node_attrs = node_attrs or {}

    shapes = {}
    if shape is not None:
        arg_shapes, out_shapes, _ = symbol.infer_shape(**shape)
        internals = symbol.get_internals()
        names = internals.list_outputs()
        try:
            _, int_shapes, _ = internals.infer_shape(**shape)
            shapes = dict(zip(names, int_shapes))
        except MXNetError:
            pass

    nodes = symbol.nodes
    weights = set()
    if hide_weights:
        for node in nodes:
            if node.op:
                for inp, _idx in node.inputs:
                    if inp.op is None and inp.name.endswith(
                            ("_weight", "_bias", "_gamma", "_beta",
                             "_moving_mean", "_moving_var")):
                        weights.add(inp.name)

    lines = [f'digraph "{title}" {{', "  rankdir=BT;"]
    id2name = {}
    for node in nodes:
        if node.name in weights:
            continue
        id2name[id(node)] = node.name
        color = _OP_COLOR.get(node.op or "", "#8dd3c7")
        style = {"shape": "box", "fillcolor": color, "style": "filled",
                 **node_attrs}
        attr_txt = ", ".join(f'{k}="{v}"' for k, v in style.items())
        lines.append(f'  "{node.name}" [label="{_node_label(node)}", {attr_txt}];')
    for node in nodes:
        if node.name in weights or not node.op:
            continue
        for inp, _idx in node.inputs:
            if inp.name in weights or id(inp) not in id2name:
                continue
            label = ""
            out_name = inp.name if inp.op is None else inp.name + "_output"
            if shapes.get(out_name):
                label = f' [label="{"x".join(map(str, shapes[out_name]))}"]'
            lines.append(f'  "{inp.name}" -> "{node.name}"{label};')
    lines.append("}")
    dot_src = "\n".join(lines)

    try:
        import graphviz  # type: ignore

        g = graphviz.Source(dot_src)
        return g
    except ImportError:
        class _Dot:
            source = dot_src

            def render(self, *a, **k):
                raise MXNetError("graphviz not installed")

            def __repr__(self):
                return self.source

        return _Dot()


def print_summary(symbol, shape=None, line_length=120, positions=(.44, .64,
                                                                  .74, 1.)):
    """Parity: visualization.py print_summary — layer table with output
    shapes, param counts and previous-layer links; returns total params."""
    if not isinstance(symbol, Symbol):
        raise MXNetError("print_summary requires a Symbol")
    shapes = {}
    if shape is not None:
        internals = symbol.get_internals()
        names = internals.list_outputs()
        _, int_shapes, _ = internals.infer_shape(**shape)
        shapes = dict(zip(names, int_shapes))
        arg_names = symbol.list_arguments()
        arg_shape_list, _, _ = symbol.infer_shape(**shape)
        arg_shapes = dict(zip(arg_names, arg_shape_list))
    else:
        arg_shapes = {}

    positions = [int(line_length * p) for p in positions]
    fields = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    def print_row(cols):
        line = ""
        for txt, pos in zip(cols, positions):
            line = (line + str(txt))[:pos].ljust(pos)
        print(line)

    print("_" * line_length)
    print_row(fields)
    print("=" * line_length)

    total = 0
    for node in symbol.nodes:
        if node.op is None:
            continue
        out_name = node.name + "_output"
        out_shape = shapes.get(out_name, "")
        params = 0
        prevs = []
        for inp, _idx in node.inputs:
            if inp.op is None:
                if inp.name in arg_shapes and (
                        inp.name.endswith(("_weight", "_bias", "_gamma",
                                           "_beta", "_moving_mean",
                                           "_moving_var"))):
                    s = arg_shapes[inp.name]
                    n = 1
                    for d in s:
                        n *= d
                    params += n
                else:
                    prevs.append(inp.name)
            else:
                prevs.append(inp.name)
        total += params
        print_row([f"{node.name} ({node.op})", out_shape, params,
                   ",".join(prevs)])
    print("=" * line_length)
    print(f"Total params: {total}")
    print("_" * line_length)
    return total
