"""Legacy data-parallel executor manager (FeedForward's engine room).

Parity: python/mxnet/executor_manager.py (reference): `_split_input_slice`
(:15), `_check_arguments` (:41), `_load_data`/`_load_label` (:60-80),
`DataParallelExecutorManager` (:279).  The modern Module path uses
module/executor_group.py; this module keeps the older API surface alive
on top of the same TPU-native SPMD executor group (one compiled program,
batch sharded over the mesh's ``data`` axis) so reference scripts using
the manager directly keep working.
"""
from __future__ import annotations

import logging

from .base import MXNetError
from .module.executor_group import DataParallelExecutorGroup, _split_input_slice


def _check_arguments(symbol):
    """Parity: executor_manager.py:41 — reject duplicated arg/aux names."""
    arg_names = symbol.list_arguments()
    if len(set(arg_names)) != len(arg_names):
        raise MXNetError(
            "Find duplicated argument name, please make the weight name "
            f"non-duplicated, arguments are {arg_names}")
    aux_names = symbol.list_auxiliary_states()
    if len(set(aux_names)) != len(aux_names):
        raise MXNetError(
            "Find duplicated auxiliary param name, please make the weight "
            f"name non-duplicated, auxiliary params are {aux_names}")


def _load_general(data, targets):
    """Parity: executor_manager.py:60 — load a list of arrays into a list
    of targets (NDArray or (slice, NDArray) pairs)."""
    for d_src, d_targets in zip(data, targets):
        if hasattr(d_targets, "copyto"):  # NDArray target
            d_src.copyto(d_targets)
        else:
            for sl, d_dst in d_targets:
                d_src[sl.start:sl.stop].copyto(d_dst)


def _load_data(batch, targets):
    _load_general(batch.data, targets)


def _load_label(batch, targets):
    _load_general(batch.label, targets)


class DataParallelExecutorManager:
    """Parity: executor_manager.py:279.  Helper class to manage
    multiple executors for data parallelism — on TPU, one SPMD executor
    group over the context mesh."""

    def __init__(self, symbol, ctx, train_data, arg_names=None,
                 param_names=None, aux_names=None, work_load_list=None,
                 logger=None, sym_gen=None):
        if logger is None:
            logger = logging
        num_device = len(ctx)
        logger.info("Start training with %s", str(ctx))

        if work_load_list is None:
            work_load_list = [1] * num_device
        if len(work_load_list) != num_device:
            raise MXNetError("Invalid settings for work load.")

        self.ctx = ctx
        self.symbol = symbol
        self.sym_gen = sym_gen
        self.data_names = [d[0] for d in train_data.provide_data]
        self.label_names = [l[0] for l in train_data.provide_label]

        arg_names = arg_names or symbol.list_arguments()
        self.arg_names = arg_names
        if param_names is None:
            param_names = [n for n in arg_names
                           if n not in self.data_names + self.label_names]
        self.param_names = param_names
        self.aux_names = aux_names or symbol.list_auxiliary_states()
        _check_arguments(symbol)

        self.slices = _split_input_slice(train_data.batch_size, work_load_list)
        self.execgrp = DataParallelExecutorGroup(
            symbol, ctx, work_load_list,
            train_data.provide_data, train_data.provide_label,
            param_names, for_training=True, inputs_need_grad=False)
        self.execgrp_bucket = {}
        if sym_gen is not None and getattr(train_data, "default_bucket_key", None) is not None:
            self.execgrp_bucket[train_data.default_bucket_key] = self.execgrp
        self.curr_execgrp = self.execgrp

    def install_monitor(self, monitor):
        self.execgrp.install_monitor(monitor)

    def set_params(self, arg_params, aux_params):
        self.execgrp.set_params(arg_params, aux_params)

    def copy_to(self, arg_params, aux_params):
        """Copy current params into the given dicts (parity: :340)."""
        self.execgrp.get_params(arg_params, aux_params)

    @property
    def param_arrays(self):
        return self.curr_execgrp.param_arrays

    @property
    def grad_arrays(self):
        return self.curr_execgrp.grad_arrays

    @property
    def aux_arrays(self):
        ex = self.curr_execgrp.execs[0]
        return [[ex.aux_dict[name]] for name in self.aux_names]

    def load_data_batch(self, data_batch):
        """Parity: :365 — switch bucket executor if needed, stage batch."""
        if self.sym_gen is not None and getattr(data_batch, "bucket_key", None) is not None:
            key = data_batch.bucket_key
            if key not in self.execgrp_bucket:
                symbol = self.sym_gen(key)
                self.execgrp_bucket[key] = DataParallelExecutorGroup(
                    symbol, self.ctx, None,
                    data_batch.provide_data, data_batch.provide_label,
                    self.param_names, for_training=True,
                    inputs_need_grad=False, shared_group=self.execgrp)
            self.curr_execgrp = self.execgrp_bucket[key]
        self._curr_batch = data_batch

    def forward(self, is_train=False):
        self.curr_execgrp.forward(self._curr_batch, is_train=is_train)

    def backward(self):
        self.curr_execgrp.backward()

    def update_metric(self, metric, labels):
        self.curr_execgrp.update_metric(metric, labels)
