"""Data iterators.

Parity: python/mxnet/io.py + src/io/ (reference).  The reference's C++
iterators (MNISTIter, CSVIter, ImageRecordIter — MXNET_REGISTER_IO_ITER,
SURVEY.md Appendix A) have Python-frontend equivalents here; the staged
pipeline design (shard -> parallel decode -> batch -> prefetch,
src/io/iter_image_recordio.cc:150-487) is preserved in image.py/recordio.py
with a thread prefetcher feeding device transfers.
"""
from __future__ import annotations

import gzip
import os
import struct
import threading
import time
from collections import namedtuple

import numpy as np

from . import ndarray as nd
from . import telemetry as _tm
from .base import MXNetError
from .ndarray import NDArray

DataDesc = namedtuple("DataDesc", ["name", "shape"])

# --- telemetry families (docs/telemetry.md).  Stacked pipelines (e.g.
# ImageRecordIter -> PrefetchingIter) report per stage: filter by the
# `iterator` label for the stage you care about. -----------------------------
_TM_BATCHES = _tm.counter(
    "data_batches_total", "batches produced, per iterator class",
    labels=("iterator",))
_TM_BATCH_WAIT = _tm.histogram(
    "data_batch_wait_seconds",
    "time the consumer spent inside next() waiting for a batch "
    "(input-pipeline starvation when the upstream stage is prefetched)",
    labels=("iterator",))


def _record_batch(it, t0):
    """One produced batch: count it and record the consumer wait."""
    name = type(it).__name__
    _TM_BATCHES.inc(iterator=name)
    _TM_BATCH_WAIT.observe(time.perf_counter() - t0, iterator=name)


class DataBatch:
    """Parity: io.py DataBatch."""

    def __init__(self, data, label=None, pad=0, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter:
    """Parity: io.py DataIter base."""

    def __init__(self):
        self.batch_size = 0

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        t0 = time.perf_counter() if _tm.enabled() else None
        if self.iter_next():
            batch = DataBatch(
                data=self.getdata(), label=self.getlabel(),
                pad=self.getpad(), index=self.getindex(),
            )
            if t0 is not None:
                _record_batch(self, t0)
            return batch
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError


def _init_data(data, allow_empty, default_name):
    """Parity: io.py _init_data — normalize array/dict/list input."""
    if data is None:
        if not allow_empty:
            raise ValueError("data cannot be None")
        return []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {f"_{i}_{default_name}": d for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise TypeError("data must be NDArray, numpy array, list or dict")
    return [
        (k, np.asarray(v.asnumpy() if isinstance(v, NDArray) else v, dtype=np.float32))
        for k, v in data.items()
    ]


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (parity: io.py NDArrayIter): shuffle,
    pad/discard/roll_over last-batch handling, data+label dicts."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data", label_name="softmax_label"):
        super().__init__()
        self.data = _init_data(data, False, data_name)
        self.label = _init_data(label, True, label_name)
        self.num_data = self.data[0][1].shape[0]

        if shuffle:
            idx = np.random.permutation(self.num_data)
            self.data = [(k, v[idx]) for k, v in self.data]
            self.label = [(k, v[idx]) for k, v in self.label]

        if last_batch_handle == "discard":
            new_n = self.num_data - self.num_data % batch_size
            self.data = [(k, v[:new_n]) for k, v in self.data]
            self.label = [(k, v[:new_n]) for k, v in self.label]
            self.num_data = new_n

        assert self.num_data >= batch_size, "batch_size must be <= data size"
        self.batch_size = batch_size
        self.cursor = -batch_size
        self.last_batch_handle = last_batch_handle

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:]) for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:]) for k, v in self.label]

    def reset(self):
        if self.last_batch_handle == "roll_over" and self.cursor > self.num_data:
            self.cursor = -self.batch_size + (self.cursor % self.num_data) % self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def _getdata(self, data_source):
        if self.cursor + self.batch_size <= self.num_data:
            return [nd.array(v[self.cursor : self.cursor + self.batch_size]) for _, v in data_source]
        # padding with wrap-around (parity: NDArrayIter pad mode)
        pad = self.batch_size - (self.num_data - self.cursor)
        return [
            nd.array(np.concatenate([v[self.cursor :], v[:pad]], axis=0))
            for _, v in data_source
        ]

    def getdata(self):
        return self._getdata(self.data)

    def getlabel(self):
        return self._getdata(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0


class ResizeIter(DataIter):
    """Resize (truncate/loop) another iterator to `size` batches per epoch
    (parity: io.py ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__()
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        self.batch_size = data_iter.batch_size

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Thread-prefetching wrapper (parity: io.py PrefetchingIter; the C++
    analogue is PrefetcherIter, src/io/iter_prefetcher.h:50-155).  One
    producer thread per underlying iter keeps a double buffer full, so host
    batch prep overlaps device compute — the same overlap the reference gets
    from dmlc::ThreadedIter."""

    def __init__(self, iters, rename_data=None, rename_label=None):
        super().__init__()
        if not isinstance(iters, list):
            iters = [iters]
        self.n_iter = len(iters)
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self.batch_size = self.provide_data[0][1][0]
        self.current_batch = [None] * self.n_iter
        self.next_batch = [None] * self.n_iter
        self.started = True
        self.data_ready = [threading.Event() for _ in range(self.n_iter)]
        self.data_taken = [threading.Event() for _ in range(self.n_iter)]
        for e in self.data_taken:
            e.set()

        def prefetch(i):
            while True:
                self.data_taken[i].wait()
                if not self.started:
                    break
                try:
                    self.next_batch[i] = self.iters[i].next()
                except StopIteration:
                    self.next_batch[i] = None
                self.data_taken[i].clear()
                self.data_ready[i].set()

        self.prefetch_threads = [
            threading.Thread(target=prefetch, args=[i], daemon=True)
            for i in range(self.n_iter)
        ]
        for t in self.prefetch_threads:
            t.start()

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum(
            [
                [DataDesc(r[n], s) for n, s in i.provide_data]
                for r, i in zip(self.rename_data, self.iters)
            ],
            [],
        )

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum(
            [
                [DataDesc(r[n], s) for n, s in i.provide_label]
                for r, i in zip(self.rename_label, self.iters)
            ],
            [],
        )

    def __del__(self):
        self.started = False
        for e in self.data_taken:
            e.set()

    def reset(self):
        for e in self.data_ready:
            e.wait()
        for i in self.iters:
            i.reset()
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()

    def iter_next(self):
        for e in self.data_ready:
            e.wait()
        if self.next_batch[0] is None:
            return False
        self.current_batch = DataBatch(
            sum([b.data for b in self.next_batch], []),
            sum([b.label for b in self.next_batch], []),
            self.next_batch[0].pad,
            self.next_batch[0].index,
        )
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()
        return True

    def next(self):
        t0 = time.perf_counter() if _tm.enabled() else None
        if self.iter_next():
            if t0 is not None:
                _record_batch(self, t0)
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class DevicePrefetchIter(DataIter):
    """Device-staging prefetcher: keeps up to ``depth`` batches ALREADY
    transferred to the accelerator while compute runs.

    PrefetchingIter overlaps host batch PREP with compute; this overlaps
    the host->device copy too.  jax transfers are dispatched
    asynchronously, so a producer thread calling ``device_put`` ``depth``
    batches ahead hides the PCIe/tunnel latency behind the training
    step — the TPU-shaped analogue of the reference's PrefetcherIter
    feeding pinned GPU memory (src/io/iter_prefetcher.h:50-155).  Stack
    as ImageRecordIter -> PrefetchingIter -> DevicePrefetchIter for the
    full decode/stage/compute pipeline.
    """

    def __init__(self, base_iter, depth=2, device=None):
        super().__init__()
        import queue as _queue
        import threading as _threading

        import jax

        self._base = base_iter
        self.batch_size = base_iter.batch_size
        self._device = device or jax.devices()[0]
        self._q = _queue.Queue(maxsize=max(1, int(depth)))
        self._stop = False
        self._thread = None
        self._threading = _threading
        self._start()

    def _start(self):
        import jax

        def producer():
            from .ndarray import NDArray

            try:
                for batch in self._base:
                    if self._stop:
                        return
                    staged = DataBatch(
                        [NDArray(jax.device_put(d._read()
                                                if isinstance(d, NDArray)
                                                else d, self._device))
                         for d in batch.data],
                        [NDArray(jax.device_put(l._read()
                                                if isinstance(l, NDArray)
                                                else l, self._device))
                         for l in batch.label],
                        batch.pad, batch.index)
                    self._q.put(staged)
            except Exception as exc:  # surface in the consumer
                self._q.put(exc)
                return
            self._q.put(None)

        self._thread = self._threading.Thread(target=producer, daemon=True)
        self._thread.start()

    @property
    def provide_data(self):
        return self._base.provide_data

    @property
    def provide_label(self):
        return self._base.provide_label

    def reset(self):
        self._stop = True
        # unblock the producer (it may be parked on a full queue), wait
        # for it to die, then drain EVERYTHING — stale batches and the
        # None sentinel would otherwise replay/terminate the next epoch
        while self._thread.is_alive():
            try:
                self._q.get(timeout=0.1)
            except Exception:
                pass
        while True:
            try:
                self._q.get_nowait()
            except Exception:
                break
        self._base.reset()
        self._stop = False
        self._exhausted = False
        self._start()

    def iter_next(self):
        try:
            self._current = self.next()
            return True
        except StopIteration:
            return False

    def getdata(self):
        return self._current.data

    def getlabel(self):
        return self._current.label

    def getpad(self):
        return self._current.pad

    def next(self):
        if getattr(self, "_exhausted", False):
            # the producer is dead and the sentinel consumed; a blocking
            # get() here would hang forever
            raise StopIteration
        t0 = time.perf_counter() if _tm.enabled() else None
        item = self._q.get()
        if item is None:
            self._exhausted = True
            raise StopIteration
        if isinstance(item, Exception):
            self._exhausted = True
            raise item
        self._current = item
        if t0 is not None:
            _record_batch(self, t0)
        return item


def step_multi_feeds(data_iter, steps_per_call,
                     data_names=("data",), label_names=("softmax_label",),
                     drop_remainder=False):
    """Group a DataIter's batches into ``FusedTrainer.step_multi`` feeds
    WITHOUT host re-stacking.

    Yields dicts mapping input name -> a k-tuple of per-step raw device
    arrays; ``step_multi`` stacks them inside the compiled program, so a
    pipeline like ``ImageRecordIter -> PrefetchingIter ->
    DevicePrefetchIter -> step_multi_feeds`` feeds k-step scans entirely
    from device-resident batches (the round-5 ``step_multi`` regression
    was exactly the host stack+transfer this path eliminates)::

        for feed in io.step_multi_feeds(it, 8):
            trainer.step_multi(_donate=True, **feed)

    The per-step arrays are handed to the trainer single-use (pass
    ``_donate=True`` when nothing else reads the batches).  A trailing
    group shorter than ``steps_per_call`` is yielded as-is — one extra
    compile for that k — unless ``drop_remainder``.
    """
    from .ndarray import NDArray

    def raw(x):
        if isinstance(x, NDArray):
            return x._read()
        return x

    names = list(data_names) + list(label_names)
    group = []
    for batch in data_iter:
        group.append([raw(a) for a in
                      list(batch.data) + list(batch.label or [])])
        if len(group) == int(steps_per_call):
            yield {n: tuple(g[i] for g in group)
                   for i, n in enumerate(names)}
            group = []
    if group and not drop_remainder:
        yield {n: tuple(g[i] for g in group) for i, n in enumerate(names)}


class MNISTIter(NDArrayIter):
    """MNIST idx-format reader (parity: src/io/iter_mnist.cc:241).

    Reads the standard idx files (optionally gzipped); flat=True yields
    (batch, 784), else (batch, 1, 28, 28).
    """

    def __init__(self, image="train-images-idx3-ubyte", label="train-labels-idx1-ubyte",
                 batch_size=128, shuffle=True, flat=False, silent=False, seed=0,
                 input_shape=None, **kwargs):
        images = self._read_idx(image)
        labels = self._read_idx(label)
        images = images.astype(np.float32) / 255.0
        if flat:
            images = images.reshape(images.shape[0], -1)
        else:
            images = images.reshape(images.shape[0], 1, images.shape[1], images.shape[2])
        if shuffle:
            rs = np.random.RandomState(seed)
            idx = rs.permutation(images.shape[0])
            images, labels = images[idx], labels[idx]
        super().__init__(images, labels.astype(np.float32), batch_size=batch_size,
                         shuffle=False, last_batch_handle="discard")

    @staticmethod
    def _read_idx(path):
        opener = gzip.open if path.endswith(".gz") else open
        if not os.path.exists(path) and os.path.exists(path + ".gz"):
            path, opener = path + ".gz", gzip.open
        with opener(path, "rb") as f:
            magic = struct.unpack(">I", f.read(4))[0]
            ndim = magic & 0xFF
            dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
            data = np.frombuffer(f.read(), dtype=np.uint8)
            return data.reshape(dims)


class CSVIter(NDArrayIter):
    """CSV reader (parity: src/io/iter_csv.cc:131)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=128, **kwargs):
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.float32)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32)
            label = label.reshape((data.shape[0],) + tuple(label_shape)).squeeze()
        else:
            label = np.zeros((data.shape[0],), dtype=np.float32)
        super().__init__(data, label, batch_size=batch_size,
                         last_batch_handle="discard")


def ImageRecordIter(*args, **kwargs):
    """Parity: ImageRecordIter (src/io/iter_image_recordio.cc:459) — full
    RecordIO image pipeline; implemented in image.py."""
    from .image import ImageRecordIter as _impl

    return _impl(*args, **kwargs)
