"""Analyzer configuration: the declared invariants.

This file is the single place where the package names its steady-state
entry points, its sanctioned sync boundaries, and the primitive sets
each rule family matches on.  Growing the system (a new trainer loop,
a new background thread) means growing THIS file — the lint then
proves the new surface obeys the same invariants.
"""

# --------------------------------------------------------------- host-sync
# Steady-state entry points: code reachable from these must never block
# on the device.  These are the per-batch/per-tick hot loops the
# zero-host-sync counter tests (test_async_pipeline / test_parallel /
# test_amp / test_checkpoint) sample dynamically.
ENTRY_POINTS = (
    "mxnet_tpu.module.base_module.BaseModule._fit_epochs",
    "mxnet_tpu.trainer.FusedTrainer.step",
    "mxnet_tpu.trainer.FusedTrainer.step_multi",
    "mxnet_tpu.serving.scheduler.SlotScheduler._tick",
    "mxnet_tpu.kvstore_fused.FusedUpdateEngine.handle_push",
    "mxnet_tpu.kvstore_fused.FusedUpdateEngine.handle_pull",
    "mxnet_tpu.checkpoint.snapshot",
    "mxnet_tpu.checkpoint.CheckpointManager.save",
    # elastic membership poll: runs every batch inside the fit loops —
    # must stay pure host-side flag reads (ISSUE 13)
    "mxnet_tpu.parallel.coordinator.CoordinatorClient.step_poll",
    # fleet plane steady-state loops (ISSUE 14): the heartbeat carries
    # the flight-ring step-timing feed, the coordinator's federation
    # sweep scrapes member /metrics.json — both must stay pure
    # host-side (HTTP + ring reads), never touching the device
    "mxnet_tpu.parallel.coordinator.CoordinatorClient._heartbeat_loop",
    "mxnet_tpu.telemetry.fleet.FleetScraper.scrape_once",
    # serving fleet (ISSUE 15): the router's replica-health scrape loop
    # and the paged-KV allocator tick (page allocation, block tables,
    # prefix index) are pure host-side bookkeeping — the device only
    # ever sees the jitted step/prefill dispatches
    "mxnet_tpu.serving.router.ReplicaRouter.scrape_once",
    "mxnet_tpu.serving.paged_kv.PagedSlots.step",
    # tracing + SLO plane (ISSUE 16): the per-request router relay and
    # the span-buffer flush behind GET /spans.json are steady-state
    # host paths — spans are pure dict/ring writes, never a device sync
    "mxnet_tpu.serving.router.ReplicaRouter.route_generate",
    "mxnet_tpu.telemetry.tracing.spans_payload",
    # perf-attribution plane (ISSUE 20): the per-scrape gauge fold and
    # the /profile payload walk the host-side ledgers the hot loops fed
    # with perf_counter stamps — pure dict arithmetic, never a device
    # touch; the ledger writers (record_dispatch/record_step_buckets)
    # are covered through the fit/tick entry points above
    "mxnet_tpu.telemetry.perf.publish_gauges",
    "mxnet_tpu.telemetry.perf.profile_payload",
)

# Sanctioned sync boundaries: the analyzer does not descend into these.
# Each entry is qualname -> why syncing behind it is the design, not a
# leak.  A boundary is NOT a free pass for its callers — the call site
# itself stays on the hot path; only the callee's interior is excused.
BOUNDARIES = {
    "mxnet_tpu.engine.AsyncWindow.drain":
        "the explicit epoch/checkpoint-boundary drain — THE sanctioned "
        "sync point of the bounded-window design",
    "mxnet_tpu.engine.AsyncWindow._wait_one":
        "window-full backpressure: blocking when MXTPU_ASYNC_DEPTH is "
        "exceeded is the bounded-depth contract",
    "mxnet_tpu.telemetry.health.sentinel_check":
        "sentinel reporting boundary: syncs parked device futures only "
        "at drain/window-overflow sites by contract (PR 5)",
    "mxnet_tpu.checkpoint.CheckpointWrite.__init__":
        "background writer thread: device->host fetch + file IO run "
        "off-loop; capture only dispatches jnp.copy",
    "mxnet_tpu.monitor.Monitor.toc_print":
        "opt-in debugging Monitor: interval-gated stat rendering syncs "
        "by contract (PR-5 keeps the per-batch tic() sync-free; "
        "production loops install no monitor)",
    # autotuner (ISSUE 18): schedule search is a bind/admit-time
    # activity ONLY — PagedSlots construction and explicit tune() call
    # sites.  measure() blocks on each candidate by design; the
    # steady-state loops see tuned schedules exclusively through the
    # pure autotune.cache.schedule_for lookup, which never syncs.
    "mxnet_tpu.autotune.search.measure":
        "the autotuner's candidate timer: warmup + best-of-k "
        "block_until_ready at bind/admit-time search sites — never "
        "reachable from a steady-state tick",
    # perf-attribution plane (ISSUE 20): the cost capture re-lowers the
    # already-compiled program once per program lifetime (first
    # dispatch, guarded by per-program flags and the MXTPU_PERF_ATTR
    # arm) — compile() is a cache lookup; never a per-batch activity
    "mxnet_tpu.telemetry.perf.attach_cost_analysis":
        "one-time per-program compile-cache probe for the analytical "
        "cost row at first dispatch — flag-guarded at every call site, "
        "never per batch, no device sync (lower/compile only)",
}

# Device->host sync primitives, matched as method names on any receiver.
SYNC_METHODS = frozenset({
    "asnumpy", "wait_to_read", "item", "tolist", "block_until_ready",
})
# …and as resolved/dotted calls (module functions).
SYNC_CALLS = frozenset({
    "jax.device_get", "device_get",
})
# numpy module aliases whose asarray/array on an NDArray-typed argument
# is a hidden host sync (goes through NDArray.__array__ -> asnumpy).
NUMPY_MODULES = frozenset({"numpy"})
NUMPY_SYNC_FUNCS = frozenset({"asarray", "array", "ascontiguousarray"})
# builtins that trigger NDArray.__float__/__int__/__bool__ host syncs
# when applied to an NDArray-typed argument.
BUILTIN_CASTS = frozenset({"float", "int", "bool"})
# NDArray-ish class names for the cheap local type inference.
NDARRAY_CLASSES = frozenset({"NDArray", "RowSparseNDArray"})

# ------------------------------------------------------------ trace-purity
# Extra trace roots beyond what static jit/pallas/scan detection finds:
# whole modules whose functions are traced by construction.
TRACED_MODULES = (
    "mxnet_tpu.optim_rules",      # fused/flat/sparse optimizer kernels
)
# Decorators that mark a function as an op implementation — op bodies
# are traced by the executor's graph_fn.
OP_REGISTER_DECORATORS = frozenset({
    "register()", "registry.register()", "ops.register()",
})
# jax entry points whose function argument becomes traced code.
TRACING_CALLS = frozenset({
    "jit", "pallas_call", "scan", "vmap", "pmap", "custom_vjp",
    "custom_jvp", "checkpoint", "remat", "shard_map", "while_loop",
    "fori_loop", "cond", "switch", "defvjp", "defjvp",
})
# Module prefixes that must not be called from traced code (host-impure).
TRACE_BANNED_MODULE_PREFIXES = (
    ("time", "host clock read inside a traced function"),
    ("numpy.random", "host RNG inside a traced function (use the ctx key)"),
    ("random", "host RNG inside a traced function (use the ctx key)"),
    ("mxnet_tpu.telemetry", "telemetry from traced code runs at trace "
                            "time only and vanishes from the compiled "
                            "program — record at the dispatch site"),
)
# Telemetry instrument method names (module-global Counter/Gauge/
# Histogram objects created from the telemetry registry).
TELEMETRY_INSTRUMENT_METHODS = frozenset({"inc", "observe", "set", "dec"})
# Parameter names that are NOT traced arrays in op-impl signatures.
UNTRACED_PARAM_NAMES = frozenset({
    "self", "cls", "ctx", "attrs", "key", "is_train", "platform",
    "mesh", "sharding", "axis", "name",
})

# ------------------------------------------------------------------- locks
# Thread-entry markers: functions handed to these run on another thread.
THREAD_TARGET_CALLS = frozenset({
    "Thread", "threading.Thread", "Timer", "threading.Timer",
})
THREAD_REGISTER_CALLS = frozenset({
    "signal.signal", "atexit.register", "weakref.finalize",
})
# Method names that are thread entries by framework contract.
THREAD_ENTRY_METHOD_NAMES = frozenset({
    "do_GET", "do_POST", "do_PUT", "do_DELETE", "handle", "handle_error",
    "service_actions", "run",
})
# Lock-ish constructors (Condition aliases the lock it wraps).
LOCK_CONSTRUCTORS = frozenset({
    "Lock", "RLock", "Condition", "threading.Lock", "threading.RLock",
    "threading.Condition",
})

# --------------------------------------------------------------- env-docs
ENV_VAR_PATTERN = r"\b((?:MXTPU|BENCH)_[A-Z0-9_]+)\b"
ENV_DOC = "docs/how_to/env_var.md"
# Extra scan surface beyond mxnet_tpu/ (repo-relative).
ENV_EXTRA_FILES = ("bench.py",)
ENV_EXTRA_DIRS = ("tools",)
# Documented knobs that are read outside the scanned surface (tests/,
# pytest.ini, examples) — documented-but-not-in-source is fine for these.
ENV_DOC_ONLY_OK = frozenset({
    "MXTPU_TPU_TESTS",      # read by tests/test_tpu_consistency.py gate
    "MXTPU_LC_PLATFORM",    # read by examples/transformer-lm/train_long_context.py
})
