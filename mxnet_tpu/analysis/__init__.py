"""Static invariant lint engine (docs/static_analysis.md).

The repo's load-bearing guarantees — zero per-batch host syncs on the
steady-state training/serving loops, trace-purity of everything that
enters a jitted program, and thread safety across the background
machinery — were historically enforced by runtime counter tests that
cover only the loops they instrument.  This package proves the same
invariants *statically*, over the whole ``mxnet_tpu`` source tree, on
every PR, the way compiler-framework stacks gate IR rewrites with
structural validity checks (TVM, arXiv:1802.04799; Relay,
arXiv:1810.00952) instead of sampled execution.

Everything here is stdlib-only (``ast`` + ``tokenize``) so the suite
runs without importing jax or the package under analysis —
``tools/lint.py`` loads it standalone for pre-commit use.

Rule families (see each module for the model and its approximations):

- ``host_sync``    — escape analysis: no device→host sync primitive
                     reachable from a declared steady-state entry point.
- ``trace_purity`` — functions that get traced must not touch host
                     state (telemetry, time, np.random, captured-state
                     mutation, host branching on traced values).
- ``locks``        — lock-acquisition-order cycles (deadlock
                     candidates) and attributes written from multiple
                     thread domains with no common lock (race
                     candidates).
- ``env_docs``     — every MXTPU_*/BENCH_* knob read in source is
                     documented in docs/how_to/env_var.md and vice
                     versa.

Violations are suppressed only by an inline annotation with a reason
(``# sync-ok: <why>``, ``# trace-ok: <why>``, ``# lock-ok: <why>``,
``# race-ok: <why>``) or an allowlist entry (tools/lint_allowlist.json)
— a bare annotation with no reason is itself a violation.
"""
from .report import Finding, render_text, render_json          # noqa: F401
from .astutil import PackageIndex, load_package                # noqa: F401
from .callgraph import CallGraph                               # noqa: F401
from .engine import run_all, RULES, repo_root                  # noqa: F401
