"""Rule orchestration: load → build graph → run rules → suppress.

``run_all`` is the one entry both ``tools/lint.py`` and
``tests/test_lint.py`` call; it returns the full finding list with
suppression state applied (inline annotations first, then the
committed allowlist), sorted for stable output.
"""
import os

from . import annotations, env_docs, host_sync, locks, trace_purity
from .astutil import load_package
from .callgraph import CallGraph

RULES = {
    "host-sync": host_sync.run,
    "trace-purity": trace_purity.run,
    "locks": locks.run,          # lock-order + shared-state
    "env-docs": env_docs.run,
}

DEFAULT_ALLOWLIST = os.path.join("tools", "lint_allowlist.json")


def repo_root():
    """The repo root this package sits in (…/mxnet_tpu/analysis/ -> …)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def run_all(root=None, rules=None, allowlist_path=None, index=None,
            graph=None):
    """Run the selected rule families; -> (findings, index, graph).

    Findings come back with ``suppressed_by`` already applied; callers
    gate on ``[f for f in findings if not f.suppressed]``.
    """
    root = root or repo_root()
    selected = list(RULES) if not rules else list(rules)
    for name in selected:
        if name not in RULES:
            raise ValueError(f"unknown rule family {name!r}; "
                             f"have {sorted(RULES)}")
    if index is None:
        index = load_package(root)
    if graph is None and any(r != "env-docs" for r in selected):
        # env-docs is a text scan; only the reachability rules pay for
        # the call graph
        graph = CallGraph(index)
    findings = []
    for name in selected:
        findings.extend(RULES[name](index, graph))
    extra = annotations.apply_annotations(index, findings)
    if set(selected) == set(RULES):
        # stray-annotation sweep only makes sense on a full run — a
        # partial run would see every other family's markers as stale
        extra += annotations.scan_stray_annotations(index, findings)
    if allowlist_path is None:
        allowlist_path = os.path.join(root, DEFAULT_ALLOWLIST)
    allow = annotations.load_allowlist(allowlist_path)
    extra += annotations.apply_allowlist(
        findings, allow, os.path.relpath(allowlist_path, root)
        if os.path.exists(allowlist_path) else "")
    findings.extend(extra)
    findings.sort(key=lambda f: (f.rule, f.path, f.line, f.symbol, f.detail))
    return findings, index, graph
