"""Rule family 2 — trace-purity lint.

Anything that executes under ``jax.jit`` / ``pallas_call`` / ``lax``
control-flow tracing runs ONCE at trace time; host side effects there
(telemetry, clocks, host RNG, mutating captured state) silently bake
one stale value into the compiled program or vanish on cache hits, and
Python-side branching on traced values either concretizes (hidden
sync) or crashes on abstract tracers.  This rule finds every traced
function statically and walks its transitive callees:

trace roots
    - any function reference passed to a jax tracing entry point
      (``jit``, ``pallas_call``, ``scan``, ``while_loop``, ``cond``,
      ``custom_vjp``/``defvjp``, ``shard_map``, …) or decorated with
      one;
    - every op implementation registered via ``@register(...)`` in
      ``mxnet_tpu/ops/`` (the executor's graph_fn traces those);
    - every function of the modules in config.TRACED_MODULES
      (``optim_rules`` — the bucket/sentinel/loss-scale kernels).

checks, per reached function
    - calls into banned host modules (``time``, ``numpy.random``,
      ``random``, ``mxnet_tpu.telemetry``) and ``print``;
    - calls on module-global telemetry instruments (``_TM_X.inc``);
    - host-sync primitives (shared with the host-sync rule);
    - mutation of captured state (``self.attr = …``, ``global``
      writes, subscript/attr stores into closed-over names);
    - Python branching on a traced parameter (bare-name truthiness or
      comparison in ``if``/``while`` — ``x.shape``/``x.ndim`` stay
      static on tracers and are not flagged), checked on root
      functions where parameterhood is known.

Every violation names the trace root that reaches it.
"""
import ast

from . import config
from .astutil import dotted
from .callgraph import iter_body_calls, iter_body_nodes
from .host_sync import sync_sites
from .report import Finding

# Boundaries for the purity walk: trace-time helpers that are allowed
# host behavior by contract (filled as triage demands, like
# config.BOUNDARIES for host-sync).
TRACE_BOUNDARIES = {}


def _resolve_fn_ref(index, graph, fi, node):
    """Resolve an expression used as a function *reference* (not call)
    to a qualname, mirroring the call-graph's name resolution.  ``fi``
    may be a module-level shim (qualname == module, no class)."""
    if isinstance(node, ast.Name):
        name = node.id
        nested = f"{fi.qualname}.<locals>.{name}"
        if nested in index.functions:
            return nested
        if fi.parent:
            sibling = f"{fi.parent}.<locals>.{name}"
            if sibling in index.functions:
                return sibling
        flat = f"{fi.module}.{name}"
        if flat in index.functions:
            return flat
        target = index.modules[fi.module].imports.get(name)
        if target in index.functions:
            return target
    elif isinstance(node, ast.Attribute):
        recv = dotted(node.value)
        if recv == "self" and fi.cls:
            return index.mro_method(fi.cls, node.attr)
        if recv and isinstance(node.value, ast.Name) and \
                hasattr(fi.node, "body"):
            # local object: v = ClassName(...); jit(v.method)
            mi = index.modules[fi.module]
            for sub in iter_body_nodes(fi.node):
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                        and isinstance(sub.targets[0], ast.Name) \
                        and sub.targets[0].id == recv \
                        and isinstance(sub.value, ast.Call):
                    cls = index.resolve_class(dotted(sub.value.func), mi)
                    if cls:
                        return index.mro_method(cls, node.attr)
    return None


class _ModuleShim:
    """FunctionInfo stand-in for module-level statements so the fn-ref
    resolver works on top-level ``f = jax.jit(g)`` assignments."""

    def __init__(self, mi):
        self.qualname = mi.name
        self.module = mi.name
        self.cls = ""
        self.parent = ""
        self.relpath = mi.relpath

        class _NoBody:
            pass

        self.node = _NoBody()   # no .body: local-var scan is skipped


def _is_jax_recv(mi, recv):
    head = recv.split(".")[0] if recv else ""
    target = mi.imports.get(head, head)
    return target.split(".")[0] in ("jax", "pl", "pallas", "lax") or \
        target.startswith("jax.")


def find_trace_roots(index, graph):
    """-> {qualname: how} for every statically-traced function."""
    roots = {}
    # whole traced modules (optimizer kernels)
    for qn, fi in index.functions.items():
        if fi.module in config.TRACED_MODULES:
            roots.setdefault(qn, f"function in traced module {fi.module}")
    # op implementations
    for qn, fi in index.functions.items():
        if not fi.module.startswith("mxnet_tpu.ops"):
            continue
        for dec in fi.decorators:
            if dec in config.OP_REGISTER_DECORATORS or \
                    dec.endswith(".register()"):
                roots.setdefault(qn, "op implementation (@register)")
    # jit/pallas/lax-control-flow decorators and call arguments
    for qn, fi in index.functions.items():
        mi = index.modules[fi.module]
        for dec in fi.decorators:
            base = dec.rsplit(".", 1)[-1].rstrip("()")
            if base in config.TRACING_CALLS and ("jax" in dec or "jit" in dec
                                                 or "pallas" in dec):
                roots.setdefault(qn, f"decorated @{dec.rstrip('()')}")
        for call in iter_body_calls(fi.node):
            _scan_tracing_call(index, graph, fi, mi, call, roots)
    # module-level `f = jax.jit(g)` assignments
    for mi in index.modules.values():
        shim = _ModuleShim(mi)
        for node in ast.iter_child_nodes(mi.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    _scan_tracing_call(index, graph, shim, mi, sub, roots)
    return roots


def _scan_tracing_call(index, graph, fi, mi, call, roots):
    func = call.func
    if not isinstance(func, (ast.Attribute, ast.Name)):
        return
    name = func.attr if isinstance(func, ast.Attribute) else func.id
    if name not in config.TRACING_CALLS:
        return
    if isinstance(func, ast.Attribute):
        recv = dotted(func.value)
        # defvjp hangs off a custom_vjp object, any receiver ok
        if name not in ("defvjp", "defjvp") and not _is_jax_recv(mi, recv):
            return
    else:
        target = mi.imports.get(name, "")
        if not target.startswith("jax"):
            return
    for arg in list(call.args) + [kw.value for kw in call.keywords]:
        # look through one wrapper call: jit(wrap(f)), partial(f, …)
        cands = [arg]
        if isinstance(arg, ast.Call):
            cands = list(arg.args) + [kw.value for kw in arg.keywords]
        for cand in cands:
            ref = _resolve_fn_ref(index, graph, fi, cand)
            if ref:
                roots.setdefault(
                    ref, f"passed to {name} at {fi.relpath}:{call.lineno}")


def _module_instruments(index):
    """Per module: names of module-global telemetry instrument objects
    (assigned from a call into mxnet_tpu.telemetry*)."""
    out = {}
    for modname, mi in index.modules.items():
        names = set()
        for node in ast.iter_child_nodes(mi.tree):
            if not (isinstance(node, ast.Assign) and
                    isinstance(node.value, ast.Call)):
                continue
            text = dotted(node.value.func)
            head = text.split(".")[0] if text else ""
            target = mi.imports.get(head, head)
            full = text.replace(head, target, 1) if text else ""
            if target.startswith("mxnet_tpu.telemetry") or \
                    full.startswith("mxnet_tpu.telemetry"):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        names.add(tgt.id)
        out[modname] = names
    return out


def _local_names(fn_node):
    """Names bound in the function scope (params, assignments, loop and
    with targets, nested defs, imports)."""
    names = set()
    args = fn_node.args
    for a in (args.posonlyargs + args.args + args.kwonlyargs +
              ([args.vararg] if args.vararg else []) +
              ([args.kwarg] if args.kwarg else [])):
        names.add(a.arg)
    for node in iter_body_nodes(fn_node):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            names.add(node.id)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for a in node.names:
                names.add((a.asname or a.name).split(".")[0])
    for child in ast.iter_child_nodes(fn_node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
            names.add(child.name)
    return names


def _traced_params(fi):
    args = fi.node.args
    pos = [a.arg for a in (args.posonlyargs + args.args)]
    return {p for p in pos if p not in config.UNTRACED_PARAM_NAMES
            and not p.startswith("_")
            # static selector/config params by naming convention
            and not p.endswith(("_name", "_names", "_params", "_attrs"))}


def purity_violations(index, fi, instruments, is_root):
    mi = index.modules[fi.module]
    # --- banned calls
    for call in iter_body_calls(fi.node):
        func = call.func
        if isinstance(func, ast.Name) and func.id == "print":
            yield (call.lineno, "print", "print() inside traced code runs "
                   "at trace time only")
            continue
        if not isinstance(func, ast.Attribute):
            continue
        recv = dotted(func.value)
        if not recv:
            continue
        head = recv.split(".")[0]
        target = mi.imports.get(head, head)
        resolved = recv.replace(head, target, 1)
        full = f"{resolved}.{func.attr}"
        for prefix, why in config.TRACE_BANNED_MODULE_PREFIXES:
            if resolved == prefix or resolved.startswith(prefix + ".") or \
                    full == prefix:
                yield (call.lineno, prefix, f"{recv}.{func.attr}(): {why}")
                break
        else:
            if (recv in instruments.get(fi.module, ()) and
                    func.attr in config.TELEMETRY_INSTRUMENT_METHODS):
                yield (call.lineno, "telemetry-instrument",
                       f"{recv}.{func.attr}() telemetry write from traced "
                       "code — move to the dispatch site")
    # --- host syncs inside trace
    for lineno, prim, desc in sync_sites(index, fi):
        yield (lineno, f"sync:{prim}",
               f"{desc} — forces concretization inside a traced function")
    # --- captured-state mutation (constructors exempt: __init__ writes
    # populate a brand-new object, they don't mutate captured state)
    if fi.name in ("__init__", "__new__", "__post_init__"):
        return
    local = None
    for node in iter_body_nodes(fi.node):
        if isinstance(node, ast.Global):
            yield (node.lineno, "captured-mutation",
                   f"global statement mutates module state from traced "
                   f"code ({', '.join(node.names)})")
        tgt_list = []
        if isinstance(node, ast.Assign):
            tgt_list = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            tgt_list = [node.target]
        for tgt in tgt_list:
            if isinstance(tgt, ast.Attribute):
                base = dotted(tgt.value).split(".")[0]
                if base == "self":
                    yield (tgt.lineno, "captured-mutation",
                           f"self.{tgt.attr} = … mutates captured object "
                           "state inside traced code")
                    continue
            if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                base = dotted(tgt.value).split(".")[0] if \
                    dotted(tgt.value) else (
                        tgt.value.id if isinstance(tgt.value, ast.Name)
                        else "")
                if not base:
                    continue
                if local is None:
                    local = _local_names(fi.node)
                if base not in local and base != "self":
                    yield (tgt.lineno, "captured-mutation",
                           f"store into captured/global '{base}' inside "
                           "traced code")
    # --- host branching on traced params (roots only: parameterhood known)
    if is_root:
        params = _traced_params(fi)
        for node in iter_body_nodes(fi.node):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            bad = _branch_on_param(node.test, params)
            if bad:
                yield (node.lineno, "traced-branch",
                       f"Python branch on traced value '{bad}' — use "
                       "jnp.where/lax.cond (static facts like .shape "
                       "are fine and not flagged)")


def _branch_on_param(test, params):
    if isinstance(test, ast.Name) and test.id in params:
        return test.id
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _branch_on_param(test.operand, params)
    if isinstance(test, ast.BoolOp):
        for v in test.values:
            bad = _branch_on_param(v, params)
            if bad:
                return bad
    if isinstance(test, ast.Compare):
        ops = test.ops
        if any(isinstance(o, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
               for o in ops):
            return None
        for side in [test.left] + list(test.comparators):
            if isinstance(side, ast.Name) and side.id in params:
                return side.id
    return None


def run(index, graph):
    roots = find_trace_roots(index, graph)
    instruments = _module_instruments(index)
    witness = graph.reachable(sorted(roots), boundaries=frozenset(
        TRACE_BOUNDARIES))
    findings = []
    for qn in sorted(witness):
        if qn in TRACE_BOUNDARIES:
            continue
        fi = index.functions[qn]
        # find the root whose witness chain reaches qn
        cur, root = qn, qn
        while witness.get(cur, (None, None))[0] is not None:
            cur = witness[cur][0]
        root = cur
        how = roots.get(root, "")
        for lineno, kind, desc in purity_violations(
                index, fi, instruments, is_root=qn in roots):
            findings.append(Finding(
                rule="trace-purity", path=fi.relpath, line=lineno,
                symbol=qn, detail=kind,
                message=f"impure traced code: {desc} "
                        f"[trace root: {root} — {how or 'transitive'}]",
                chain=graph.chain(witness, qn)))
    return findings
