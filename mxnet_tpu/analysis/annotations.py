"""Suppression grammar: inline annotations + the committed allowlist.

Inline annotations live in source comments on the flagged line or the
line directly above it (for lines that are too long already):

    x = out.asnumpy()          # sync-ok: epoch boundary, window drained
    # trace-ok: static shape read, not a traced value
    if attrs_rank > 2: ...

Markers: ``sync-ok`` (host-sync), ``trace-ok`` (trace-purity),
``lock-ok`` (lock-order), ``race-ok`` (shared-state).  The reason
after the colon is mandatory — an annotation with an empty reason is
reported as its own violation instead of suppressing anything, so the
reviewed-reason discipline is machine-enforced.

The allowlist (tools/lint_allowlist.json) suppresses findings by their
stable ``key`` for cases where an inline comment can't sit at the
site (cross-file findings like lock cycles, or generated evidence).
Entries are ``{"key": ..., "reason": ...}``; a missing/empty reason
invalidates the entry.  Unused entries are reported so the file can't
rot.
"""
import json
import os
import re

from .report import Finding

MARKERS = {
    "host-sync": "sync-ok",
    "trace-purity": "trace-ok",
    "lock-order": "lock-ok",
    "shared-state": "race-ok",
}

_ANN_RE = re.compile(r"#\s*(sync-ok|trace-ok|lock-ok|race-ok)\s*:?\s*(.*)")


def find_annotation(index, relpath, lineno, marker):
    """Return (reason, ann_lineno) if the flagged line carries the marker
    inline, or any line of the contiguous pure-comment block directly
    above it does; (None, 0) otherwise.  An empty reason returns
    ('', line).  The reason may continue onto following comment lines —
    only the marker line's text is machine-read."""
    candidates = [lineno]
    ln = lineno - 1
    while ln >= 1 and index.source_line(relpath, ln).strip().startswith("#"):
        candidates.append(ln)
        ln -= 1
    for ln in candidates:
        text = index.source_line(relpath, ln)
        m = _ANN_RE.search(text)
        if m and m.group(1) == marker:
            if ln != lineno and text.split("#")[0].strip():
                continue  # annotation lines above must be pure comments
            return m.group(2).strip().rstrip("."), ln
    return None, 0


_SITE_RE = re.compile(r"\(([^\s():]+\.py):(\d+)\)")


def _candidate_sites(f):
    """Annotation anchor points for a finding: its own site plus — for
    the multi-site rules (a race has two writes, a lock cycle has edge
    evidence across files) — every file:line its chain cites."""
    sites = []
    if f.path and f.line:
        sites.append((f.path, f.line))
    if f.rule in ("shared-state", "lock-order"):
        for step in f.chain:
            for m in _SITE_RE.finditer(step):
                sites.append((m.group(1), int(m.group(2))))
    return sites


def apply_annotations(index, findings):
    """Mark findings suppressed by a valid inline annotation; emit
    annotation-missing-reason findings for bare markers."""
    extra = []
    for f in findings:
        marker = MARKERS.get(f.rule)
        if not marker:
            continue
        for path, line in _candidate_sites(f):
            reason, ann_ln = find_annotation(index, path, line, marker)
            if reason is None:
                continue
            if reason:
                f.suppressed_by = f"annotation:{reason}"
            else:
                extra.append(Finding(
                    rule="annotation", path=path, line=ann_ln,
                    symbol=f.symbol, detail=f"bare-{marker}",
                    message=f"# {marker}: annotation without a reason "
                            f"(suppressing nothing; add the why)"))
            break
    return extra


def scan_stray_annotations(index, findings):
    """Annotations that no finding matched are likely stale (the code
    they excused moved or was fixed) — report them so they get cleaned."""
    claimed = set()
    for f in findings:
        if f.suppressed_by.startswith("annotation:"):
            marker = MARKERS[f.rule]
            for path, line in _candidate_sites(f):
                reason, ann_ln = find_annotation(index, path, line, marker)
                if reason:
                    claimed.add((path, ann_ln, marker))
                claimed.add((path, line, marker))
    extra = []
    for mi in index.modules.values():
        for ln, text in enumerate(mi.lines, 1):
            m = _ANN_RE.search(text)
            if not m:
                continue
            marker = m.group(1)
            if ((mi.relpath, ln, marker) in claimed or
                    (mi.relpath, ln + 1, marker) in claimed):
                continue
            extra.append(Finding(
                rule="annotation", path=mi.relpath, line=ln,
                symbol=mi.name, detail=f"stale-{marker}",
                message=f"# {marker}: annotation matches no current "
                        "finding — stale, remove it"))
    return extra


def load_allowlist(path):
    """-> {key: reason}; raises ValueError on malformed entries."""
    if not path or not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    out = {}
    for i, entry in enumerate(doc if isinstance(doc, list)
                              else doc.get("entries", [])):
        key = entry.get("key", "")
        reason = (entry.get("reason") or "").strip()
        if not key or not reason:
            raise ValueError(
                f"allowlist entry {i} needs both 'key' and a non-empty "
                f"'reason': {entry!r}")
        out[key] = reason
    return out


def apply_allowlist(findings, allowlist, allowlist_path=""):
    """Suppress findings whose key is allowlisted; report unused keys."""
    used = set()
    for f in findings:
        if f.suppressed:
            continue
        reason = allowlist.get(f.key)
        if reason is not None:
            f.suppressed_by = f"allowlist:{reason}"
            used.add(f.key)
    extra = []
    for key in sorted(set(allowlist) - used):
        extra.append(Finding(
            rule="annotation", path=allowlist_path, line=0, symbol=key,
            detail="stale-allowlist",
            message=f"allowlist entry matches no current finding "
                    f"(stale): {key}"))
    return extra
