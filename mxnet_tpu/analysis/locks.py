"""Rule family 3 — lock-order and shared-state analyzer.

The threaded surface (telemetry registry + HTTP exporters, serving
scheduler/server, AsyncWindow users, checkpoint writer, fault sites,
KVStoreDist server) grows with every PR toward the multi-host runtime.
Two static checks over all of it:

lock-order (deadlock candidates)
    Lock identities are ``(owner, attr)`` — ``self.X =
    threading.Lock()/RLock()/Condition()`` class attributes and
    module-global locks.  ``threading.Condition(self.lock)`` aliases
    the lock it wraps.  A walk of every function tracks the
    ``with``-stack of held locks; acquiring B while holding A adds an
    order edge A→B, both lexically and through resolved calls (callee
    summaries, fixpoint).  Cycles in the order graph are deadlock
    candidates; re-acquiring a held non-reentrant Lock is a
    self-deadlock candidate.

shared-state (race candidates)
    Thread entry points: ``threading.Thread(target=…)`` /
    ``Timer(…)`` targets, ``signal.signal`` / ``atexit.register`` /
    ``weakref.finalize`` callbacks, ``do_*``/``handle`` HTTP handler
    methods, and ``run`` on Thread subclasses.  For every class that
    owns a background entry, each ``self.attr`` write site is placed
    in the thread domains that reach it (the background roots' call
    closures, plus "main" for the public API closure).  An attribute
    written from two different domains with no common lock held at the
    two sites is a race candidate.  ``__init__`` writes are
    construction-time and skipped.
"""
import ast
from collections import defaultdict

from . import config
from .astutil import dotted
from .callgraph import iter_body_calls
from .report import Finding

REENTRANT = ("RLock", "Condition")  # Condition() wraps an RLock by default


# --------------------------------------------------------------- discovery
def _lock_ctor(call, mi):
    """-> ('Lock'|'RLock'|'Condition', wrapped_attr_or_None) or None."""
    if not isinstance(call, ast.Call):
        return None
    text = dotted(call.func)
    if not text:
        return None
    head = text.split(".")[0]
    resolved = text.replace(head, mi.imports.get(head, head), 1)
    base = text.rsplit(".", 1)[-1]
    if text in config.LOCK_CONSTRUCTORS or \
            resolved in ("threading." + b for b in
                         ("Lock", "RLock", "Condition")):
        wrapped = None
        if base == "Condition" and call.args:
            a = call.args[0]
            if isinstance(a, ast.Attribute) and \
                    isinstance(a.value, ast.Name) and a.value.id == "self":
                wrapped = a.attr
        return base, wrapped
    return None


def discover_locks(index):
    """-> locks: {(owner, attr): kind}, aliases: {(owner, attr): (owner, attr)}
    where owner is a class qualname or module name."""
    locks, aliases = {}, {}
    for cqn, ci in index.classes.items():
        mi = index.modules[ci.module]
        for node in ast.walk(ci.node):
            if not isinstance(node, ast.Assign):
                continue
            got = _lock_ctor(node.value, mi)
            if not got:
                continue
            kind, wrapped = got
            for tgt in node.targets:
                if isinstance(tgt, ast.Attribute) and \
                        isinstance(tgt.value, ast.Name) and \
                        tgt.value.id == "self":
                    ident = (cqn, tgt.attr)
                    locks[ident] = kind
                    if wrapped:
                        aliases[ident] = (cqn, wrapped)
    for modname, mi in index.modules.items():
        for node in ast.iter_child_nodes(mi.tree):
            if not isinstance(node, ast.Assign):
                continue
            got = _lock_ctor(node.value, mi)
            if not got:
                continue
            kind, _ = got
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    locks[(modname, tgt.id)] = kind
    return locks, aliases


def _canon(ident, aliases):
    seen = set()
    while ident in aliases and ident not in seen:
        seen.add(ident)
        ident = aliases[ident]
    return ident


class LockModel:
    def __init__(self, index, graph):
        self.index = index
        self.graph = graph
        self.locks, self.aliases = discover_locks(index)
        # per-function: [(held_tuple, acquired_ident, lineno)]
        self.acquisitions = defaultdict(list)
        # per-function: [(held_tuple, CallSite)]
        self.calls_under = defaultdict(list)
        # per-function: [(held_tuple, attr_name, lineno)] self-writes
        self.self_writes = defaultdict(list)
        for qn, fi in index.functions.items():
            self._walk_function(qn, fi)
        self.summary = self._fixpoint_summaries()

    # ---------------------------------------------------------- per-function
    def _resolve_lock_expr(self, fi, node):
        """with-item / receiver expression -> lock ident or None."""
        idx = self.index
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and node.value.id == "self":
            cls = fi.cls
            while cls:
                ident = (cls, node.attr)
                if ident in self.locks:
                    return _canon(ident, self.aliases)
                ci = idx.classes.get(cls)
                cls = (idx.resolve_class(ci.bases[0], idx.modules[ci.module])
                       if ci and ci.bases else None)
            return None
        if isinstance(node, ast.Name):
            ident = (fi.module, node.id)
            if ident in self.locks:
                return _canon(ident, self.aliases)
            target = self.index.modules[fi.module].imports.get(node.id, "")
            if "." in target:
                mod, name = target.rsplit(".", 1)
                ident = (mod, name)
                if ident in self.locks:
                    return _canon(ident, self.aliases)
        # self._attr.lock style / typed attr receivers
        text = dotted(node)
        if text.startswith("self.") and fi.cls and text.count(".") == 2:
            _, attr, lockattr = text.split(".")
            ci = idx.classes.get(fi.cls)
            cls = ci.attr_types.get(attr) if ci else None
            if cls and (cls, lockattr) in self.locks:
                return _canon((cls, lockattr), self.aliases)
        return None

    def _walk_function(self, qn, fi):
        def visit(stmts, held):
            for st in stmts:
                if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                    continue
                if isinstance(st, ast.With):
                    new = list(held)
                    for item in st.items:
                        ident = self._resolve_lock_expr(fi, item.context_expr)
                        if ident:
                            self.acquisitions[qn].append(
                                (tuple(new), ident, st.lineno))
                            new.append(ident)
                    self._scan_exprs(qn, fi, st.items, held)
                    visit(st.body, new)
                    continue
                # .acquire() outside a with
                for call in ast.walk(st):
                    if isinstance(call, ast.Call) and \
                            isinstance(call.func, ast.Attribute) and \
                            call.func.attr == "acquire":
                        ident = self._resolve_lock_expr(fi, call.func.value)
                        if ident:
                            self.acquisitions[qn].append(
                                (tuple(held), ident, call.lineno))
                self._scan_stmt(qn, fi, st, held)
                for attr in ("body", "orelse", "finalbody"):
                    sub = getattr(st, attr, None)
                    if sub:
                        visit(sub, held)
                for h in getattr(st, "handlers", []) or []:
                    visit(h.body, held)

        visit(fi.node.body, [])

    def _scan_stmt(self, qn, fi, st, held):
        """Record calls + self-attr writes at this held context, without
        descending into compound-statement bodies (visit() does that)."""
        shallow = [st]
        if isinstance(st, (ast.If, ast.While)):
            shallow = [st.test]
        elif isinstance(st, ast.For):
            shallow = [st.iter, st.target]
        elif isinstance(st, ast.Try):
            shallow = []
        self._scan_exprs(qn, fi, shallow, held)
        if isinstance(st, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            tgts = st.targets if isinstance(st, ast.Assign) else [st.target]
            for tgt in tgts:
                els = tgt.elts if isinstance(tgt, ast.Tuple) else [tgt]
                for el in els:
                    # self.x = … and container writes self.x[i] = …
                    if isinstance(el, ast.Subscript):
                        el = el.value
                    if isinstance(el, ast.Attribute) and \
                            isinstance(el.value, ast.Name) and \
                            el.value.id == "self":
                        self.self_writes[qn].append(
                            (tuple(held), el.attr, el.lineno))

    def _scan_exprs(self, qn, fi, nodes, held):
        sites = {id(s.node): s for s in self.graph.sites(qn)}
        for root in nodes:
            if root is None or isinstance(root, str):
                continue
            for sub in ast.walk(root if not hasattr(root, "context_expr")
                                else root.context_expr):
                if isinstance(sub, ast.Call) and id(sub) in sites:
                    self.calls_under[qn].append((tuple(held), sites[id(sub)]))

    # -------------------------------------------------------------- summaries
    def _fixpoint_summaries(self):
        """qualname -> set of lock idents acquired transitively inside."""
        summary = {qn: {a[1] for a in acqs}
                   for qn, acqs in self.acquisitions.items()}
        for qn in self.index.functions:
            summary.setdefault(qn, set())
        for _ in range(12):  # bounded fixpoint; call depth in-package is small
            changed = False
            for qn in self.index.functions:
                acc = summary[qn]
                before = len(acc)
                for _, site in self.calls_under.get(qn, ()):
                    for tgt in site.targets:
                        acc |= summary.get(tgt, set())
                if len(acc) != before:
                    changed = True
            if not changed:
                break
        return summary


def _lock_name(ident):
    owner, attr = ident
    return f"{owner.rsplit('.', 1)[-1]}.{attr}" if "." in owner else \
        f"{owner}.{attr}"


def lock_order_findings(index, graph, model):
    edges = defaultdict(list)   # (A, B) -> evidence strings
    findings = []
    for qn, acqs in model.acquisitions.items():
        fi = index.functions[qn]
        for held, ident, lineno in acqs:
            for h in held:
                if h == ident:
                    if model.locks.get(ident) not in REENTRANT:
                        findings.append(Finding(
                            rule="lock-order", path=fi.relpath, line=lineno,
                            symbol=qn,
                            detail=f"self-deadlock:{_lock_name(ident)}",
                            message=f"re-acquires non-reentrant "
                                    f"{_lock_name(ident)} already held in "
                                    f"{qn} — self-deadlock"))
                    continue
                edges[(h, ident)].append(
                    f"{qn} ({fi.relpath}:{lineno}) holds "
                    f"{_lock_name(h)} then takes {_lock_name(ident)}")
    # inter-procedural edges: call under held lock -> callee acquisitions
    seen_self = set()
    for qn, pairs in model.calls_under.items():
        fi = index.functions[qn]
        for held, site in pairs:
            if not held:
                continue
            for tgt in site.targets:
                for ident in model.summary.get(tgt, ()):
                    for h in held:
                        if h == ident:
                            # re-entry through a call chain: deadlock
                            # for a non-reentrant Lock
                            if model.locks.get(ident) in REENTRANT or \
                                    (qn, tgt, ident) in seen_self:
                                continue
                            seen_self.add((qn, tgt, ident))
                            findings.append(Finding(
                                rule="lock-order", path=fi.relpath,
                                line=site.lineno, symbol=qn,
                                detail=("self-deadlock:"
                                        f"{_lock_name(ident)}"),
                                message=f"{qn} holds non-reentrant "
                                        f"{_lock_name(ident)} and calls "
                                        f"{tgt}, which re-acquires it — "
                                        "self-deadlock candidate"))
                            continue
                        edges[(h, ident)].append(
                            f"{qn} ({fi.relpath}:{site.lineno}) holds "
                            f"{_lock_name(h)} and calls {tgt} which "
                            f"acquires {_lock_name(ident)}")
    # cycle detection (DFS over the order graph)
    adj = defaultdict(set)
    for (a, b) in edges:
        adj[a].add(b)
    findings.extend(_cycles(adj, edges))
    return findings


def _cycles(adj, edges):
    findings = []
    seen_cycles = set()
    for start in sorted(adj):
        stack = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in sorted(adj.get(node, ())):
                if nxt == start and len(path) > 1:
                    cyc = frozenset(path)
                    if cyc in seen_cycles:
                        continue
                    seen_cycles.add(cyc)
                    names = " -> ".join(_lock_name(p) for p in
                                        path + [start])
                    ev = []
                    hops = list(zip(path, path[1:] + [start]))
                    for hop in hops:
                        ev.extend(edges.get(hop, [])[:2])
                    findings.append(Finding(
                        rule="lock-order", path="", line=0,
                        symbol="cycle:" + "|".join(
                            sorted(_lock_name(p) for p in path)),
                        detail="cycle",
                        message=f"lock-order cycle (deadlock candidate): "
                                f"{names}",
                        chain=tuple(ev)))
                elif nxt not in path and len(path) < 6:
                    stack.append((nxt, path + [nxt]))
    return findings


# ------------------------------------------------------------ shared state
def thread_entries(index, graph):
    """-> {qualname: how} background-thread entry functions."""
    out = {}
    for qn, fi in index.functions.items():
        mi = index.modules[fi.module]
        for call in iter_body_calls(fi.node):
            text = dotted(call.func)
            if not text:
                continue
            head = text.split(".")[0]
            resolved = text.replace(head, mi.imports.get(head, head), 1)
            base = text.rsplit(".", 1)[-1]
            refs = []
            if base in ("Thread", "Timer") and (
                    resolved.startswith("threading.") or text == base):
                refs = [kw.value for kw in call.keywords
                        if kw.arg in ("target", "function")]
                if base == "Timer" and len(call.args) >= 2:
                    refs.append(call.args[1])
            elif resolved in config.THREAD_REGISTER_CALLS or \
                    text in config.THREAD_REGISTER_CALLS:
                if base == "finalize" and len(call.args) >= 2:
                    refs = [call.args[1]]
                elif base == "signal" and len(call.args) >= 2:
                    refs = [call.args[1]]
                else:
                    refs = call.args[:1]
            for r in refs:
                from .trace_purity import _resolve_fn_ref
                ref = _resolve_fn_ref(index, graph, fi, r)
                if ref:
                    out.setdefault(
                        ref, f"{base} target at {fi.relpath}:{call.lineno}")
    for cqn, ci in index.classes.items():
        thread_subclass = any(b.rsplit(".", 1)[-1] == "Thread"
                              for b in ci.bases)
        handler = any("Handler" in b or "Server" in b for b in ci.bases)
        for name, mqn in ci.methods.items():
            if name == "run" and thread_subclass:
                out.setdefault(mqn, "Thread subclass run()")
            elif name in config.THREAD_ENTRY_METHOD_NAMES and \
                    name != "run" and handler:
                out.setdefault(mqn, f"handler method {name}()")
    return out


def shared_state_findings(index, graph, model):
    entries = thread_entries(index, graph)
    if not entries:
        return []
    # closure of each background root over the call graph
    bg_reach = {}
    for root in entries:
        bg_reach[root] = set(graph.reachable((root,)))
    findings = []
    by_class = defaultdict(list)   # class qualname -> bg roots in that class
    for root in entries:
        fi = index.functions[root]
        cls = fi.cls
        if not cls and fi.parent:
            cls = index.functions[fi.parent].cls
        if cls:
            by_class[cls].append(root)
    for cqn, roots in sorted(by_class.items()):
        ci = index.classes[cqn]
        # main domain: the PUBLIC API only — private helpers join a
        # domain by being reached from a public method or a bg root
        mains = [mqn for name, mqn in ci.methods.items()
                 if mqn not in entries and not name.startswith("_")]
        main_reach = set(graph.reachable(mains))
        # collect write sites per attr from methods + their nested defs
        writes = defaultdict(list)  # attr -> (domain, qn, line, held)
        members = [qn for qn in index.functions
                   if qn.startswith(cqn + ".")]
        for qn in members:
            if qn.endswith(".__init__") or ".__init__." in qn:
                continue
            for held, attr, lineno in model.self_writes.get(qn, ()):
                domains = {r for r in roots if qn in bg_reach[r]}
                if qn in main_reach:
                    domains.add("main")
                for d in domains:
                    writes[attr].append((d, qn, lineno, frozenset(held)))
        for attr, sites in sorted(writes.items()):
            domains = {d for d, *_ in sites}
            if len(domains) < 2:
                continue
            # find a conflicting pair: different domains, no common lock
            conflict = None
            for i, (d1, q1, l1, h1) in enumerate(sites):
                for d2, q2, l2, h2 in sites[i + 1:]:
                    if d1 != d2 and not (h1 & h2):
                        conflict = ((d1, q1, l1, h1), (d2, q2, l2, h2))
                        break
                if conflict:
                    break
            if not conflict:
                continue
            (d1, q1, l1, h1), (d2, q2, l2, h2) = conflict
            fi1, fi2 = index.functions[q1], index.functions[q2]

            def _dom(d):
                return "main thread" if d == "main" else f"bg:{d}"

            def _held(h):
                return ("{" + ", ".join(sorted(_lock_name(x) for x in h))
                        + "}") if h else "no lock"
            findings.append(Finding(
                rule="shared-state", path=fi1.relpath, line=l1,
                symbol=f"{cqn}.{attr}", detail=f"race:{attr}",
                message=f"self.{attr} written from {_dom(d1)} "
                        f"({q1}:{l1}, {_held(h1)}) and {_dom(d2)} "
                        f"({fi2.relpath}:{l2} in {q2}, {_held(h2)}) "
                        "with no common lock — race candidate",
                chain=(f"{q1} ({fi1.relpath}:{l1}) holds {_held(h1)}",
                       f"{q2} ({fi2.relpath}:{l2}) holds {_held(h2)}")))
    return findings


def run(index, graph):
    model = LockModel(index, graph)
    return lock_order_findings(index, graph, model) + \
        shared_state_findings(index, graph, model)
