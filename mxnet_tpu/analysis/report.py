"""Finding model + renderers for the invariant lint engine.

A :class:`Finding` is one structured violation: rule id, location,
human message, the enclosing symbol, and (for the reachability rules)
the call chain from the entry point to the offending site.  ``key`` is
a *stable* fingerprint — no line numbers — so allowlist/baseline
entries survive unrelated edits to the file.
"""
import json
from dataclasses import dataclass, field


@dataclass
class Finding:
    rule: str                 # host-sync | trace-purity | lock-order | shared-state | env-docs | annotation
    path: str                 # repo-relative file path ('' for cross-file findings)
    line: int                 # 1-based; 0 when the finding has no single site
    symbol: str               # enclosing function qualname / env var / lock cycle id
    message: str              # one-line human statement of the defect
    chain: tuple = ()         # evidence: ("qualname (file:line)", ...) entry→site
    detail: str = ""          # fingerprint detail (primitive name, lock pair, ...)
    suppressed_by: str = ""   # "annotation:<reason>" | "allowlist:<reason>" | ""
    key: str = field(default="", compare=False)

    def __post_init__(self):
        if not self.key:
            self.key = f"{self.rule}|{self.path}|{self.symbol}|{self.detail}"

    @property
    def suppressed(self):
        return bool(self.suppressed_by)

    def to_dict(self):
        return {
            "rule": self.rule, "path": self.path, "line": self.line,
            "symbol": self.symbol, "message": self.message,
            "chain": list(self.chain), "detail": self.detail,
            "suppressed_by": self.suppressed_by, "key": self.key,
        }


def render_text(findings, verbose=False, show_suppressed=False):
    """Plain-text report: one block per active finding, grouped by rule."""
    lines = []
    active = [f for f in findings if not f.suppressed]
    shown = findings if show_suppressed else active
    by_rule = {}
    for f in shown:
        by_rule.setdefault(f.rule, []).append(f)
    for rule in sorted(by_rule):
        group = by_rule[rule]
        lines.append(f"== {rule} ({sum(1 for f in group if not f.suppressed)}"
                     f" violation(s), {sum(1 for f in group if f.suppressed)}"
                     " suppressed) ==")
        for f in group:
            mark = "  [suppressed: %s]" % f.suppressed_by if f.suppressed else ""
            loc = f"{f.path}:{f.line}" if f.path else "(repo)"
            lines.append(f"{loc}: {f.message}{mark}")
            if f.chain and (verbose or not f.suppressed):
                for i, step in enumerate(f.chain):
                    lines.append("    " + ("  " * i) + "-> " + step)
        lines.append("")
    lines.append(f"{len(active)} violation(s), "
                 f"{len(findings) - len(active)} suppressed.")
    return "\n".join(lines)


def render_json(findings, meta=None):
    active = [f for f in findings if not f.suppressed]
    doc = {
        "violations": len(active),
        "suppressed": len(findings) - len(active),
        "findings": [f.to_dict() for f in findings],
    }
    if meta:
        doc.update(meta)
    return json.dumps(doc, indent=2, sort_keys=True)
