"""AST loading + indexing for the invariant lint engine.

Parses every module of the package under analysis (stdlib ``ast`` only
— nothing is imported or executed) and builds the indexes every rule
family shares:

- modules:   dotted name -> :class:`ModuleInfo` (tree, source lines,
             import alias map)
- functions: qualname -> :class:`FunctionInfo` for every ``def`` —
             module functions, methods, *and* nested functions (the
             trace roots built inside ``_build_graph_fn``-style
             factories live there); nested defs are qualified
             ``parent.<locals>.name`` like the runtime does.
- classes:   qualname -> :class:`ClassInfo` with a method table, base
             names, and ``self.x = ClassName(...)`` attribute-type
             bindings (the call graph's cheap receiver-type inference).

Qualnames are source-level, e.g. ``mxnet_tpu.trainer.FusedTrainer.step``.
"""
import ast
import os
from dataclasses import dataclass, field


@dataclass
class ModuleInfo:
    name: str                     # dotted module name
    path: str                     # absolute file path
    relpath: str                  # repo-relative path (report currency)
    tree: ast.Module
    lines: list                   # raw source lines (1-based access via line())
    imports: dict = field(default_factory=dict)   # alias -> dotted target

    def line(self, lineno):
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""


@dataclass
class FunctionInfo:
    qualname: str
    module: str                   # owning module dotted name
    cls: str                      # owning class qualname or ""
    name: str                     # bare name
    node: object                  # ast.FunctionDef / AsyncFunctionDef
    relpath: str
    lineno: int
    parent: str = ""              # enclosing function qualname (nested defs)
    decorators: tuple = ()        # decorator source dumps for cheap matching

    @property
    def is_method(self):
        return bool(self.cls) and not self.parent


@dataclass
class ClassInfo:
    qualname: str
    module: str
    name: str
    node: object
    bases: tuple = ()             # base-class names as written (dotted text)
    methods: dict = field(default_factory=dict)      # bare -> qualname
    attr_types: dict = field(default_factory=dict)   # self attr -> class qualname


def _expr_text(node):
    """Compact source-ish text for an expression (dotted names only)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _expr_text(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    if isinstance(node, ast.Call):
        return _expr_text(node.func) + "()"
    if isinstance(node, ast.Subscript):
        return _expr_text(node.value) + "[]"
    return ""


def dotted(node):
    """Dotted-name text for Name/Attribute chains, else ''."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else ""
    return ""


class PackageIndex:
    def __init__(self, root):
        self.root = root                      # repo root (for relpaths)
        self.modules = {}                     # dotted -> ModuleInfo
        self.functions = {}                   # qualname -> FunctionInfo
        self.by_name = {}                     # bare fn name -> [qualname]
        self.classes = {}                     # class qualname -> ClassInfo
        self.class_by_name = {}               # bare class name -> [qualname]
        self._relpath_mod = {}                # relpath -> ModuleInfo

    # ------------------------------------------------------------ loading
    def add_module(self, modname, path, is_pkg=False):
        with open(path, "r", encoding="utf-8") as fh:
            src = fh.read()
        tree = ast.parse(src, filename=path)
        relpath = os.path.relpath(path, self.root)
        mi = ModuleInfo(modname, path, relpath, tree, src.splitlines())
        mi.imports = _import_map(tree, modname, is_pkg)
        self.modules[modname] = mi
        self._relpath_mod[relpath] = mi
        self._index_defs(mi)
        return mi

    def _index_defs(self, mi):
        def visit(node, scope, cls, parent_fn):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qn = f"{scope}.{child.name}"
                    fi = FunctionInfo(
                        qualname=qn, module=mi.name, cls=cls, name=child.name,
                        node=child, relpath=mi.relpath, lineno=child.lineno,
                        parent=parent_fn,
                        decorators=tuple(_expr_text(d) or ast.dump(d)
                                         for d in child.decorator_list))
                    self.functions[qn] = fi
                    self.by_name.setdefault(child.name, []).append(qn)
                    visit(child, qn + ".<locals>", cls, qn)
                elif isinstance(child, ast.ClassDef):
                    cqn = f"{scope}.{child.name}"
                    ci = ClassInfo(qualname=cqn, module=mi.name,
                                   name=child.name, node=child,
                                   bases=tuple(dotted(b) for b in child.bases))
                    self.classes[cqn] = ci
                    self.class_by_name.setdefault(child.name, []).append(cqn)
                    visit(child, cqn, cqn, parent_fn)
                else:
                    visit(child, scope, cls, parent_fn)

        visit(mi.tree, mi.name, "", "")
        # method tables + self.x = ClassName(...) attribute types
        for cqn, ci in self.classes.items():
            if ci.module != mi.name:
                continue
            for m in ast.iter_child_nodes(ci.node):
                if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    ci.methods[m.name] = f"{cqn}.{m.name}"
            for sub in ast.walk(ci.node):
                if not (isinstance(sub, ast.Assign) and
                        isinstance(sub.value, ast.Call)):
                    continue
                ctor = self.resolve_class(dotted(sub.value.func), mi)
                if not ctor:
                    continue
                for tgt in sub.targets:
                    if (isinstance(tgt, ast.Attribute) and
                            isinstance(tgt.value, ast.Name) and
                            tgt.value.id == "self"):
                        ci.attr_types[tgt.attr] = ctor

    # ---------------------------------------------------------- resolution
    def resolve_class(self, text, mi):
        """Resolve dotted constructor text in module mi to a class qualname."""
        if not text:
            return None
        head, _, rest = text.partition(".")
        target = mi.imports.get(head)
        if target:
            cand = target + ("." + rest if rest else "")
        elif not rest:
            cand = f"{mi.name}.{head}"
        else:
            cand = None
        if cand and cand in self.classes:
            return cand
        # unique bare-name fallback inside the package
        bare = text.rsplit(".", 1)[-1]
        hits = self.class_by_name.get(bare, [])
        return hits[0] if len(hits) == 1 else None

    def module_of(self, fi):
        return self.modules[fi.module]

    def source_line(self, relpath, lineno):
        mi = self._relpath_mod.get(relpath)
        return mi.line(lineno) if mi else ""

    def class_of(self, fi):
        return self.classes.get(fi.cls)

    def mro_method(self, cls_qn, name):
        """Resolve a method by walking package-local base classes."""
        seen = set()
        stack = [cls_qn]
        while stack:
            cqn = stack.pop(0)
            if cqn in seen:
                continue
            seen.add(cqn)
            ci = self.classes.get(cqn)
            if ci is None:
                continue
            if name in ci.methods:
                return ci.methods[name]
            for b in ci.bases:
                base = self.resolve_class(b, self.modules[ci.module])
                if base:
                    stack.append(base)
        return None


def _import_map(tree, modname, is_pkg=False):
    """alias -> absolute dotted target for every import in the module."""
    out = {}
    # the package a level-1 relative import refers to
    parts = modname.split(".") if is_pkg else modname.split(".")[:-1]
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    out[a.asname] = a.name
                else:
                    out[a.name.split(".")[0]] = a.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                base = parts[:len(parts) - (node.level - 1)]
                prefix = ".".join(base + ([node.module] if node.module else []))
            else:
                prefix = node.module or ""
            for a in node.names:
                if a.name == "*":
                    continue
                out[a.asname or a.name] = (f"{prefix}.{a.name}"
                                           if prefix else a.name)
    return out


def load_package(repo_root, package="mxnet_tpu", extra_files=(),
                 exclude_dirs=("analysis",)):
    """Parse every .py under ``repo_root/package`` (plus ``extra_files``,
    repo-relative, loaded as pseudo-modules) into a PackageIndex.
    ``exclude_dirs`` (package-relative subdir names) defaults to the
    analyzer itself: its docstrings/config quote the very markers and
    primitives it hunts, and fixture tests cover it instead."""
    idx = PackageIndex(repo_root)
    pkg_dir = os.path.join(repo_root, package)
    for dirpath, dirnames, filenames in os.walk(pkg_dir):
        rel_dir = os.path.relpath(dirpath, pkg_dir)
        top = rel_dir.split(os.sep)[0]
        if top in exclude_dirs:
            continue
        dirnames[:] = [d for d in sorted(dirnames)
                       if d not in ("__pycache__",)]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, repo_root)
            mod = rel[:-3].replace(os.sep, ".")
            is_pkg = mod.endswith(".__init__")
            if is_pkg:
                mod = mod[: -len(".__init__")]
            idx.add_module(mod, path, is_pkg=is_pkg)
    for rel in extra_files:
        path = os.path.join(repo_root, rel)
        if not os.path.exists(path):
            continue
        mod = rel[:-3].replace(os.sep, ".") if rel.endswith(".py") else rel
        idx.add_module(mod, path)
    return idx
