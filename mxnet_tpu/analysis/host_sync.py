"""Rule family 1 — host-sync escape analysis.

The zero-per-batch-host-sync property (PRs 4/5/7/10/11) says: from a
steady-state entry point (``Module.fit`` step body, the fused trainer
step, a serving tick, a fused KV push/pull, a checkpoint capture), no
device→host synchronization primitive may execute.  The runtime
counter tests sample this per loop; this rule proves it over the whole
call graph:

    flag every sync primitive lexically inside any function reachable
    from an entry point, unless the site carries ``# sync-ok: <why>``
    or the traversal was stopped by a registered boundary function.

Primitives: ``.asnumpy() / .wait_to_read() / .item() / .tolist() /
.block_until_ready()`` on anything, ``jax.device_get``, and — through
a branch-sensitive local type walk — ``np.asarray``-family calls and
``float()/int()/bool()`` casts applied to values known to be NDArray
(those dispatch to ``NDArray.__array__``/``__float__`` = ``asnumpy``).
"""
import ast

from . import config
from .astutil import dotted
from .callgraph import iter_body_calls
from .report import Finding


def _narrowed_ndarrayish(fn_node):
    """-> {ast.Call id: set of ndarray-ish names in scope at that call}.

    Branch-sensitive: ``isinstance(x, NDArray)`` narrows x inside the
    if-body only (and un-narrows it in the else); ``x = NDArray(...)``
    narrows x for the rest of the block.  Cheap and local by design —
    it exists to catch the `np.asarray(nd)` / `float(nd)` shape of
    sync, not to type the package.
    """
    out = {}

    def isinstance_target(test):
        if (isinstance(test, ast.Call) and isinstance(test.func, ast.Name)
                and test.func.id == "isinstance"
                and len(test.args) == 2
                and isinstance(test.args[0], ast.Name)):
            classes = test.args[1]
            names = ([dotted(classes)] if not isinstance(classes, ast.Tuple)
                     else [dotted(e) for e in classes.elts])
            if any(n.rsplit(".", 1)[-1] in config.NDARRAY_CLASSES
                   for n in names if n):
                return test.args[0].id
        return None

    def mark(node, env):
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                out.setdefault(id(sub), set()).update(env)

    def visit_block(stmts, env):
        env = set(env)
        for st in stmts:
            if isinstance(st, ast.If):
                tgt = isinstance_target(st.test)
                mark(st.test, env)
                visit_block(st.body, env | {tgt} if tgt else env)
                visit_block(st.orelse, env - {tgt} if tgt else env)
                continue
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            if isinstance(st, ast.Assign) and len(st.targets) == 1 and \
                    isinstance(st.targets[0], ast.Name):
                name = st.targets[0].id
                mark(st.value, env)
                ctor = ""
                if isinstance(st.value, ast.Call):
                    ctor = dotted(st.value.func).rsplit(".", 1)[-1]
                if ctor in config.NDARRAY_CLASSES:
                    env.add(name)
                else:
                    env.discard(name)
                continue
            if isinstance(st, (ast.For, ast.While, ast.With, ast.Try)):
                mark(getattr(st, "test", None) or getattr(st, "iter", None)
                     or st, env)
                for attr in ("body", "orelse", "finalbody"):
                    visit_block(getattr(st, attr, []) or [], env)
                for h in getattr(st, "handlers", []) or []:
                    visit_block(h.body, env)
                continue
            mark(st, env)
        return env

    visit_block(fn_node.body, set())
    return out


def _numpy_recv(recv, mi):
    head = recv.split(".")[0] if recv else ""
    target = mi.imports.get(head, "")
    return target.split(".")[0] in config.NUMPY_MODULES


def _arg_name(call):
    if call.args and isinstance(call.args[0], ast.Name):
        return call.args[0].id
    return None


def sync_sites(index, fi):
    """All sync-primitive call sites lexically in one function:
    yields (lineno, primitive, description)."""
    mi = index.modules[fi.module]
    ndarrayish = None
    for call in iter_body_calls(fi.node):
        func = call.func
        if isinstance(func, ast.Attribute):
            name = func.attr
            recv = dotted(func.value)
            if name in config.SYNC_METHODS:
                yield (call.lineno, name,
                       f".{name}() on {recv or 'expression'}")
                continue
            head = recv.split(".")[0] if recv else ""
            resolved = mi.imports.get(head, head)
            if (f"{resolved}.{name}" in config.SYNC_CALLS
                    or name in ("device_get",)):
                yield (call.lineno, name, f"{recv}.{name}() blocks for "
                       "the device value")
                continue
            if (_numpy_recv(recv, mi) and
                    name in config.NUMPY_SYNC_FUNCS):
                if ndarrayish is None:
                    ndarrayish = _narrowed_ndarrayish(fi.node)
                arg = _arg_name(call)
                if arg and arg in ndarrayish.get(id(call), ()):
                    yield (call.lineno, f"np.{name}",
                           f"np.{name}({arg}) on an NDArray goes through "
                           "__array__ -> asnumpy")
        elif isinstance(func, ast.Name) and func.id in config.BUILTIN_CASTS:
            if ndarrayish is None:
                ndarrayish = _narrowed_ndarrayish(fi.node)
            arg = _arg_name(call)
            if arg and arg in ndarrayish.get(id(call), ()):
                yield (call.lineno, func.id,
                       f"{func.id}({arg}) on an NDArray triggers "
                       f"__{func.id}__ -> host sync")


def run(index, graph):
    boundaries = frozenset(config.BOUNDARIES)
    witness = graph.reachable(config.ENTRY_POINTS, boundaries=boundaries)
    findings = []
    missing = [e for e in config.ENTRY_POINTS
               if e not in index.functions]
    for e in missing:
        findings.append(Finding(
            rule="host-sync", path="", line=0, symbol=e,
            detail="missing-entry",
            message=f"declared steady-state entry point {e} does not "
                    "exist — update analysis/config.py"))
    for qn in sorted(witness):
        if qn in boundaries:
            continue  # interior excused by registration
        fi = index.functions[qn]
        for lineno, prim, desc in sync_sites(index, fi):
            findings.append(Finding(
                rule="host-sync", path=fi.relpath, line=lineno,
                symbol=qn, detail=prim,
                message=f"host sync on a steady-state path: {desc} "
                        f"(in {qn})",
                chain=graph.chain(witness, qn)))
    return findings
