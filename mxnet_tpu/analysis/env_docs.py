"""Env-knob drift lint — the static analog of the telemetry docs-drift
test.  Every ``MXTPU_*``/``BENCH_*`` environment variable appearing in
``mxnet_tpu/``, ``bench.py``, or ``tools/`` must be documented in
``docs/how_to/env_var.md``, and every knob the doc catalogs must still
exist in the scanned surface (modulo config.ENV_DOC_ONLY_OK, for knobs
read by tests/examples outside the scan).  Plain text scan on both
sides — a knob mentioned only in a comment still names a real contract
and must be documented or renamed."""
import os
import re

from . import config
from .report import Finding


def _scan_file(path):
    """-> {var: first lineno} for env-pattern hits in one file."""
    out = {}
    try:
        with open(path, "r", encoding="utf-8") as fh:
            for ln, line in enumerate(fh, 1):
                for m in re.finditer(config.ENV_VAR_PATTERN, line):
                    out.setdefault(m.group(1), ln)
    except OSError:
        pass
    return out


def scan_source(root):
    """-> {var: (relpath, lineno)} over the configured source surface."""
    hits = {}

    def take(path):
        rel = os.path.relpath(path, root)
        for var, ln in _scan_file(path).items():
            hits.setdefault(var, (rel, ln))

    pkg = os.path.join(root, "mxnet_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in sorted(dirnames)
                       if d not in ("__pycache__", "analysis")]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                take(os.path.join(dirpath, fn))
    for rel in config.ENV_EXTRA_FILES:
        take(os.path.join(root, rel))
    for d in config.ENV_EXTRA_DIRS:
        dpath = os.path.join(root, d)
        if not os.path.isdir(dpath):
            continue
        for fn in sorted(os.listdir(dpath)):
            if fn.endswith((".py", ".sh")):
                take(os.path.join(dpath, fn))
    return hits


def run(index, graph):
    root = index.root
    src = scan_source(root)
    doc_path = os.path.join(root, config.ENV_DOC)
    doc = _scan_file(doc_path)
    findings = []
    for var in sorted(set(src) - set(doc)):
        rel, ln = src[var]
        findings.append(Finding(
            rule="env-docs", path=rel, line=ln, symbol=var,
            detail="undocumented",
            message=f"{var} is read in source but missing from "
                    f"{config.ENV_DOC}"))
    for var in sorted(set(doc) - set(src) - config.ENV_DOC_ONLY_OK):
        findings.append(Finding(
            rule="env-docs", path=config.ENV_DOC, line=doc[var],
            symbol=var, detail="stale-doc",
            message=f"{var} is documented but no longer read anywhere "
                    "in mxnet_tpu/, bench.py, or tools/"))
    return findings
