"""Approximate whole-package call graph.

Resolution strategy, in decreasing precision (every edge remembers how
it was made so reports can say "virtual" when the match was by name):

1.  plain names — local nested def, module-level def, or an imported
    function (``from .x import f``) resolved through the import map;
2.  ``self.m(...)`` — the enclosing class's method table, walking
    package-local base classes (single inheritance is all the package
    uses);
3.  ``self._attr.m(...)`` / local-var ``v.m(...)`` — cheap receiver
    typing: ``self._attr = ClassName(...)`` bindings collected at
    index time, plus per-function ``v = ClassName(...)`` assignments;
4.  virtual fallback — any ``x.m(...)`` whose bare name is defined by
    at most ``virtual_max`` package functions resolves to all of them,
    unless the name sits on the stoplist of ubiquitous method names
    (those would wire the graph into a hairball of false edges).

This over-approximates (extra edges) by design: for invariant linting
a false edge costs a reviewed annotation, a missing edge costs a
silent invariant hole.  The stoplist + boundaries keep the noise
bounded in practice.
"""
import ast
from dataclasses import dataclass

from .astutil import dotted

# Method names too common to fan out on: resolving `x.get()` to every
# `get` in the package would connect unrelated subsystems.  The second
# block is jnp/np array-method names — `x.reshape(...)` in traced code
# is an array op, not `Executor.reshape`.
VIRTUAL_STOPLIST = frozenset({
    "get", "set", "put", "add", "items", "keys", "values", "append",
    "extend", "pop", "copy", "close", "read", "write", "run", "start",
    "join", "send", "recv", "open", "flush", "next", "reset", "clear",
    "remove", "insert", "index", "count", "sort", "split", "strip",
    "format", "encode", "decode", "update", "load", "save", "create",
    "name", "shape", "dtype", "wait", "stop", "step", "push", "pull",
    "__init__", "__call__", "__enter__", "__exit__",
    # generic callable names (op.fn, self._func, cb(...) …): fanning
    # out on these invents edges between unrelated subsystems
    "fn", "f", "func", "function", "callback", "hook", "thunk",
    # array-method names (jnp/np/NDArray surface)
    "reshape", "astype", "transpose", "take", "sum", "mean", "max",
    "min", "prod", "dot", "flatten", "ravel", "squeeze", "clip",
    "round", "repeat", "cumsum", "argmax", "argmin", "any", "all",
    "broadcast_to", "swapaxes", "view", "fill", "flip", "nonzero",
})


@dataclass
class CallSite:
    caller: str          # qualname of the function containing the call
    name: str            # bare called name ('' when the callee is opaque)
    recv: str            # receiver text: '', 'self', 'self._engine', 'np', …
    lineno: int
    node: object         # the ast.Call
    targets: tuple = ()  # resolved qualnames
    virtual: bool = False


def iter_body_calls(fn_node):
    """Every ast.Call lexically in this function, NOT descending into
    nested def/class bodies (their calls belong to the nested scope).
    Lambdas stay with the enclosing function."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))


def iter_body_nodes(fn_node):
    """All statement/expression nodes of a function body, not descending
    into nested defs/classes."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


class CallGraph:
    def __init__(self, index, virtual_max=4, stoplist=VIRTUAL_STOPLIST):
        self.index = index
        self.virtual_max = virtual_max
        self.stoplist = stoplist
        self.calls = {}          # qualname -> [CallSite]
        self._toplevels = {m.split(".")[0] for m in index.modules}
        self._build()

    # ------------------------------------------------------------- build
    def _build(self):
        for qn, fi in self.index.functions.items():
            local_types = self._local_types(fi)
            sites = []
            for call in iter_body_calls(fi.node):
                sites.append(self._resolve(fi, call, local_types))
            self.calls[qn] = sites

    def _local_types(self, fi):
        """name -> class qualname for `v = ClassName(...)` and
        `v = self._attr` (typed attr) assignments in this function."""
        mi = self.index.modules[fi.module]
        ci = self.index.classes.get(fi.cls)
        out = {}
        for node in iter_body_nodes(fi.node):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            tgt = node.targets[0]
            if not isinstance(tgt, ast.Name):
                continue
            if isinstance(node.value, ast.Call):
                cls = self.index.resolve_class(dotted(node.value.func), mi)
                if cls:
                    out[tgt.id] = cls
            elif (ci is not None and isinstance(node.value, ast.Attribute)
                  and isinstance(node.value.value, ast.Name)
                  and node.value.value.id == "self"):
                cls = ci.attr_types.get(node.value.attr)
                if cls:
                    out[tgt.id] = cls
        return out

    def _resolve(self, fi, call, local_types):
        idx = self.index
        mi = idx.modules[fi.module]
        func = call.func
        if isinstance(func, ast.Name):
            name = func.id
            # nested def in this very function
            nested = f"{fi.qualname}.<locals>.{name}"
            if nested in idx.functions:
                return CallSite(fi.qualname, name, "", call.lineno, call,
                                (nested,))
            # module-level def
            flat = f"{fi.module}.{name}"
            if flat in idx.functions:
                return CallSite(fi.qualname, name, "", call.lineno, call,
                                (flat,))
            target = mi.imports.get(name)
            if target:
                if target in idx.functions:
                    return CallSite(fi.qualname, name, "", call.lineno, call,
                                    (target,))
                if target in idx.classes:
                    init = idx.mro_method(target, "__init__")
                    return CallSite(fi.qualname, name, "", call.lineno, call,
                                    (init,) if init else ())
            # constructor by bare class name in same module
            cls = idx.resolve_class(name, mi)
            if cls:
                init = idx.mro_method(cls, "__init__")
                return CallSite(fi.qualname, name, "", call.lineno, call,
                                (init,) if init else ())
            return CallSite(fi.qualname, name, "", call.lineno, call)
        if isinstance(func, ast.Attribute):
            name = func.attr
            recv = dotted(func.value) or ""
            targets, virtual = self._resolve_attr(fi, mi, recv, name,
                                                  local_types)
            return CallSite(fi.qualname, name, recv, call.lineno, call,
                            tuple(targets), virtual)
        return CallSite(fi.qualname, "", "", call.lineno, call)

    def _resolve_attr(self, fi, mi, recv, name, local_types):
        idx = self.index
        # module alias receiver: np.foo, _tm.span, checkpoint.save, …
        head = recv.split(".")[0] if recv else ""
        if recv and head in mi.imports:
            target = mi.imports[head]
            rest = recv[len(head) + 1:] if "." in recv else ""
            base = target + ("." + rest if rest else "")
            cand = f"{base}.{name}"
            if cand in idx.functions:
                return [cand], False
            if base in idx.classes:
                m = idx.mro_method(base, name)
                if m:
                    return [m], False
            if target.split(".")[0] not in self._toplevels:
                # external module (jnp.arange, np.pad, …): the callee
                # lives outside the package — fanning out to same-named
                # package functions would invent edges
                return [], False
        # self.m()
        if recv == "self" and fi.cls:
            m = idx.mro_method(fi.cls, name)
            if m:
                return [m], False
        # self._attr.m() through attr types
        if recv.startswith("self.") and fi.cls and recv.count(".") == 1:
            ci = idx.classes.get(fi.cls)
            cls = ci.attr_types.get(recv.split(".", 1)[1]) if ci else None
            if cls:
                m = idx.mro_method(cls, name)
                if m:
                    return [m], False
        # typed local receiver
        if recv in local_types:
            m = idx.mro_method(local_types[recv], name)
            if m:
                return [m], False
        # virtual fan-out by bare name
        if name not in self.stoplist:
            hits = idx.by_name.get(name, [])
            if 0 < len(hits) <= self.virtual_max:
                return list(hits), True
        return [], False

    # ----------------------------------------------------------- queries
    def sites(self, qualname):
        return self.calls.get(qualname, ())

    def reachable(self, roots, boundaries=frozenset(), into_nested=True):
        """BFS from ``roots``; returns {qualname: (parent_qualname,
        CallSite)} witness tree (roots map to (None, None)).  Traversal
        does not descend INTO boundary functions (they may sync/branch
        by contract) but boundaries themselves appear in the result.
        Nested defs of a reached function are NOT auto-included — they
        run only if called (or jitted, which rules handle separately)."""
        seen = {}
        queue = []
        for r in roots:
            if r in self.index.functions and r not in seen:
                seen[r] = (None, None)
                queue.append(r)
        while queue:
            qn = queue.pop(0)
            if qn in boundaries:
                continue
            for site in self.sites(qn):
                for tgt in site.targets:
                    if tgt not in seen and tgt in self.index.functions:
                        seen[tgt] = (qn, site)
                        queue.append(tgt)
        return seen

    def chain(self, witness, qualname):
        """Entry→qualname evidence chain as printable steps."""
        steps = []
        cur = qualname
        while cur is not None:
            parent, site = witness.get(cur, (None, None))
            fi = self.index.functions.get(cur)
            if site is not None and parent is not None:
                pfi = self.index.functions[parent]
                steps.append(f"{parent} calls {site.name or '<call>'} "
                             f"({pfi.relpath}:{site.lineno})")
            elif fi is not None:
                steps.append(f"{cur} ({fi.relpath}:{fi.lineno}) [entry]")
            cur = parent
        return tuple(reversed(steps))
