"""Training callbacks.

Parity surface: python/mxnet/callback.py in the reference — periodic
checkpointing (do_checkpoint :39, module_checkpoint :11), throughput
logging (Speedometer :89), metric logging (log_train_metric :62) and a
console progress bar.  The implementations here are original; behavior
notes:

- ``Speedometer(auto_reset=True)`` (the reference default) reports
  *per-interval* metric values — the metric is reset after each report so
  successive lines show fresh windows, not cumulative-since-epoch numbers.
- speed is computed from the actually elapsed batch count since the last
  report (robust to callers that invoke the callback at uneven cadence),
  where the reference assumes exactly ``frequent`` batches per window.

Each callback receives a ``BatchEndParam``-style object with attributes
``epoch``, ``nbatch``, ``eval_metric`` (mirroring the namedtuple built in
python/mxnet/model.py).
"""
from __future__ import annotations

import logging
import time

from . import telemetry as _tm

# Speedometer parity through the registry: the same windowed samples/sec
# the log line reports, scrapeable from /metrics (docs/telemetry.md)
_TM_SPEED = _tm.gauge(
    "speedometer_samples_per_sec",
    "throughput of the last completed Speedometer window")
_TM_SPEED_SAMPLES = _tm.counter(
    "speedometer_samples_total",
    "samples covered by completed Speedometer windows")


def _log_prefix() -> str:
    """``[rank/size@generation]`` on multi-host runs: N workers'
    Speedometer lines interleave in the elastic launcher's output and
    must stay attributable (parallel.dist.log_prefix)."""
    from .parallel import dist as _dist

    return _dist.log_prefix()


def module_checkpoint(mod, prefix, period=1, save_optimizer_states=False):
    """Epoch-end checkpoint callback bound to a Module.

    Returns a callback for ``Module.fit(epoch_end_callback=...)`` that
    writes ``prefix-symbol.json`` / ``prefix-NNNN.params`` (and optimizer
    ``.states`` when requested) every ``period`` epochs.
    """
    every = max(1, int(period))

    def _save(epoch, sym=None, arg=None, aux=None):
        done = epoch + 1
        if done % every == 0:
            mod.save_checkpoint(prefix, done, save_optimizer_states)

    return _save


def do_checkpoint(prefix, period=1):
    """Epoch-end checkpoint callback for the legacy FeedForward path.

    Unlike :func:`module_checkpoint` the symbol/params arrive through the
    callback arguments, so this works with any estimator that passes them.
    """
    from .model import save_checkpoint

    every = max(1, int(period))

    def _save(epoch, sym, arg, aux):
        done = epoch + 1
        if done % every == 0:
            save_checkpoint(prefix, done, sym, arg, aux)

    return _save


def log_train_metric(period, auto_reset=False):
    """Log the training metric every ``period`` batches.

    With ``auto_reset`` the metric restarts after each log line, so values
    cover only the batches since the previous line.
    """

    def _log(param):
        if param.nbatch % period != 0 or param.eval_metric is None:
            return
        for name, value in param.eval_metric.get_name_value():
            logging.info("Iter[%d] Batch[%d] Train-%s=%f",
                         param.epoch, param.nbatch, name, value)
        if auto_reset:
            param.eval_metric.reset_local()

    return _log


class Speedometer:
    """Batch-end callback printing samples/sec (and metric values).

    Parameters mirror the reference (callback.py:89): ``batch_size``,
    ``frequent`` (report every N batches), ``auto_reset`` (default True —
    reset the metric after each report so the printed values are
    per-interval).
    """

    def __init__(self, batch_size, frequent=50, auto_reset=True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.auto_reset = auto_reset
        self._mark = None  # (wall time, nbatch) at the last report/epoch start
        self._last_stamp = None  # metric state at the last value report

    def __call__(self, param):
        now = time.time()
        if self._mark is None or param.nbatch < self._mark[1]:
            # first call of an epoch (nbatch restarted): open a new window
            self._mark = (now, param.nbatch)
            return
        if param.nbatch % self.frequent != 0:
            return
        t0, b0 = self._mark
        elapsed, nbatches = now - t0, param.nbatch - b0
        if elapsed <= 0 or nbatches <= 0:
            # degenerate window (e.g. epoch restarted at the same nbatch):
            # re-mark so the next window doesn't span the gap
            self._mark = (now, param.nbatch)
            return
        speed = nbatches * self.batch_size / elapsed
        _TM_SPEED.set(speed)
        _TM_SPEED_SAMPLES.inc(nbatches * self.batch_size)
        # perf plane armed: " mfu=0.42 top=dispatch" rides the log line
        # (pure host reads of the attribution ledgers — the same
        # no-added-syncs contract as the update_stamp() guard below)
        perf_sfx = _tm.perf.speedometer_suffix()
        metric = param.eval_metric
        if metric is not None:
            # "values needed" boundary guard: get_name_value() is the
            # device->host sync of the fused-metric pipeline, so with
            # auto_reset=False only pay it when the metric actually
            # received updates since the last report — update_stamp() is
            # sync-free.  auto_reset windows always report (the reset is
            # part of their contract); metrics without the stamp API
            # (user subclasses) always report.
            stamp_fn = getattr(metric, "update_stamp", None)
            stamp = stamp_fn() if stamp_fn is not None else None
            if (self.auto_reset or stamp_fn is None
                    or stamp != self._last_stamp):
                parts = "".join(
                    "\tTrain-%s=%f" % nv
                    for nv in metric.get_name_value())
                logging.info(
                    "%sEpoch[%d] Batch [%d]\tSpeed: %.2f samples/sec%s%s",
                    _log_prefix(), param.epoch, param.nbatch, speed,
                    perf_sfx, parts)
                if self.auto_reset:
                    # reset only the local window: the epoch-end Train-*
                    # log (base_module.fit -> get_global_name_value) must
                    # still cover the whole epoch
                    metric.reset_local()
                # re-stamp AFTER reading: the read itself drains the
                # fused window into the host accumulators
                self._last_stamp = (stamp_fn() if stamp_fn is not None
                                    else None)
            else:
                logging.info(
                    "%sEpoch[%d] Batch [%d]\tSpeed: %.2f samples/sec%s",
                    _log_prefix(), param.epoch, param.nbatch, speed,
                    perf_sfx)
        else:
            logging.info("%sIter[%d] Batch [%d]\tSpeed: %.2f samples/sec%s",
                         _log_prefix(), param.epoch, param.nbatch, speed,
                         perf_sfx)
        self._mark = (now, param.nbatch)


class ProgressBar:
    """Console progress bar over a known total number of batches."""

    def __init__(self, total, length=80):
        self.total = total
        self.length = length

    def __call__(self, param):
        frac = min(max(param.nbatch / float(self.total), 0.0), 1.0)
        fill = int(self.length * frac + 0.5)
        bar = "=" * fill + "-" * (self.length - fill)
        logging.info("[%s] %d%%", bar, int(frac * 100 + 0.999))
