"""Metrics core: labeled Counter / Gauge / Histogram families + spans.

Role model: the operator profiler is the reference's only runtime lens
(src/engine/profiler.{h,cc} — per-op timelines); TVM/nGraph-style stacks
grew per-layer *metrics* on top of traces to drive their optimization
loops.  This module is that layer for mxnet_tpu: a process-wide registry
of named metric families that every subsystem (executor, engine, kvstore,
io, trainer) emits through, with one switch (`MXTPU_TELEMETRY` /
:func:`enable`) governing all of it.

Design constraints:

- **zero-cost-when-disabled** — every record path checks one module-level
  flag before any label resolution, dict lookup, or timestamping, so hot
  paths (engine.track on every chunk write, wait_for_var on every read)
  pay a single predictable branch when telemetry is off;
- **thread-safe** — io prefetch threads, kvstore engine workers, and the
  checkpoint writer all emit concurrently; one registry lock serializes
  family creation, one lock per family serializes its samples;
- **one timeline** — :func:`span` / :func:`timed` emit BOTH a latency
  histogram observation and a chrome-trace complete event through the
  profiler's sink (profiler.record, same monotonic timebase), so host
  spans land next to op spans and xprof device traces.
"""
from __future__ import annotations

import os
import re
import threading
import time
from contextlib import contextmanager
from typing import Dict, Optional, Sequence, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry",
    "enabled", "enable", "disable",
    "counter", "gauge", "histogram", "get_registry", "reset",
    "span", "timed", "DEFAULT_BUCKETS",
]

_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"[a-zA-Z_][a-zA-Z0-9_]*$")

# Prometheus-conventional latency buckets (seconds).
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


class _State:
    __slots__ = ("enabled",)

    def __init__(self, on: bool):
        self.enabled = on


_state = _State(os.environ.get("MXTPU_TELEMETRY", "0").lower()
                not in ("", "0", "false"))


def enabled() -> bool:
    """Is the telemetry runtime recording?"""
    return _state.enabled


def enable(on: bool = True):
    """Turn metric recording on (or off with ``on=False``).  Disabled is
    the default unless ``MXTPU_TELEMETRY=1`` is set in the environment."""
    _state.enabled = bool(on)


def disable():
    enable(False)


def sanitize_name(name: str) -> str:
    """Coerce an arbitrary string into a valid Prometheus metric name."""
    name = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    if not name or not _NAME_RE.match(name):
        name = "_" + name
    return name


class MetricFamily:
    """One named metric with a fixed label-name schema and per-label-value
    samples.  Subclasses define the sample record type and record verbs."""

    typename = "untyped"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = ()):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln) or ln.startswith("__"):
                raise ValueError(f"invalid label name {ln!r}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._samples: Dict[Tuple[str, ...], object] = {}

    # ------------------------------------------------------------------ labels
    def _key(self, labels: dict) -> Tuple[str, ...]:
        if tuple(labels) != self.labelnames and \
                set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {tuple(labels)}")
        return tuple(str(labels[ln]) for ln in self.labelnames)

    def clear(self):
        with self._lock:
            self._samples.clear()

    def samples(self):
        """[(label_values_tuple, sample)] — a consistent snapshot."""
        with self._lock:
            return list(self._samples.items())


class Counter(MetricFamily):
    """Monotonically increasing value (e.g. ``*_total`` counts/bytes)."""

    typename = "counter"

    def inc(self, amount: float = 1.0, **labels):
        if not _state.enabled:
            return
        if amount < 0:
            raise ValueError("counters cannot decrease")
        key = self._key(labels)
        with self._lock:
            self._samples[key] = self._samples.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        key = self._key(labels)
        with self._lock:
            return float(self._samples.get(key, 0.0))

    def total(self) -> float:
        """Sum over every label combination (test/report convenience)."""
        with self._lock:
            return float(sum(self._samples.values()))


class Gauge(MetricFamily):
    """Point-in-time value that can go up and down."""

    typename = "gauge"

    def set(self, value: float, **labels):
        if not _state.enabled:
            return
        key = self._key(labels)
        with self._lock:
            self._samples[key] = float(value)

    def inc(self, amount: float = 1.0, **labels):
        if not _state.enabled:
            return
        key = self._key(labels)
        with self._lock:
            self._samples[key] = self._samples.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels):
        self.inc(-amount, **labels)

    def value(self, **labels) -> float:
        key = self._key(labels)
        with self._lock:
            return float(self._samples.get(key, 0.0))


class _HistSample:
    __slots__ = ("counts", "sum", "count")

    def __init__(self, n_buckets: int):
        self.counts = [0] * n_buckets  # per-bucket (non-cumulative)
        self.sum = 0.0
        self.count = 0


class Histogram(MetricFamily):
    """Bucketed distribution (latencies, sizes).  Exported in Prometheus
    cumulative-bucket form (``_bucket{le=...}`` + ``_sum`` + ``_count``)."""

    typename = "histogram"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = (),
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help, labelnames)
        bl = sorted(float(b) for b in buckets)
        if not bl:
            raise ValueError("histogram needs at least one bucket")
        self.buckets = tuple(bl)  # +Inf is implicit

    def observe(self, value: float, **labels):
        if not _state.enabled:
            return
        key = self._key(labels)
        value = float(value)
        with self._lock:
            s = self._samples.get(key)
            if s is None:
                s = self._samples[key] = _HistSample(len(self.buckets) + 1)
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    s.counts[i] += 1
                    break
            else:
                s.counts[-1] += 1  # +Inf bucket
            s.sum += value
            s.count += 1

    def count(self, **labels) -> int:
        key = self._key(labels)
        with self._lock:
            s = self._samples.get(key)
            return s.count if s is not None else 0

    def sum(self, **labels) -> float:
        key = self._key(labels)
        with self._lock:
            s = self._samples.get(key)
            return s.sum if s is not None else 0.0


class Registry:
    """Name -> family map.  Families register once (module import time);
    get-or-create keeps re-imports and notebooks idempotent."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, MetricFamily] = {}

    def get_or_create(self, cls, name, help="", labelnames=(), **kwargs):
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if type(fam) is not cls:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.typename}, not {cls.typename}")
                if tuple(labelnames) != fam.labelnames:
                    raise ValueError(
                        f"metric {name!r} already registered with labels "
                        f"{fam.labelnames}, not {tuple(labelnames)}")
                return fam
            fam = cls(name, help, labelnames, **kwargs)
            self._families[name] = fam
            return fam

    def get(self, name: str) -> Optional[MetricFamily]:
        with self._lock:
            return self._families.get(name)

    def collect(self):
        """Families in registration order (stable export order)."""
        with self._lock:
            return list(self._families.values())

    def reset(self):
        """Zero every family's samples.  Families stay registered —
        instrumented modules hold references created at import time."""
        for fam in self.collect():
            fam.clear()


_default_registry = Registry()


def get_registry() -> Registry:
    return _default_registry


def reset():
    """Zero all metric values in the default registry (test isolation)."""
    _default_registry.reset()


def counter(name, help="", labels=()) -> Counter:
    return _default_registry.get_or_create(Counter, name, help, labels)


def gauge(name, help="", labels=()) -> Gauge:
    return _default_registry.get_or_create(Gauge, name, help, labels)


def histogram(name, help="", labels=(), buckets=DEFAULT_BUCKETS) -> Histogram:
    return _default_registry.get_or_create(Histogram, name, help, labels,
                                           buckets=buckets)


# ---------------------------------------------------------------------------
# spans — one region, two sinks: a latency histogram (this registry) and a
# chrome-trace complete event (profiler.record), so `telemetry.span` regions
# line up with op spans and xprof device slices on one timeline.
# ---------------------------------------------------------------------------
@contextmanager
def span(name: str, category: str = "host", device: str = "host",
         sync=None, histogram_name: Optional[str] = None, trace=None,
         **labels):
    """Time a region.

    When the profiler is running, emits a chrome-trace event named
    ``name`` under ``category`` (profiler parity — same sink and timebase
    as op spans).  When telemetry is enabled, observes the duration into
    histogram ``histogram_name`` (default: sanitized ``<name>_seconds``)
    with ``labels``.  ``trace`` additionally lands the region in the
    distributed-tracing span buffer under that trace id when request
    tracing is on (``telemetry/tracing.py`` — the ``GET /spans.json``
    lens).  ``sync`` is an optional zero-arg callable run before
    closing (e.g. ``block_until_ready``) so async dispatch doesn't
    under-report.  When every sink is off the region runs untimed.
    """
    from .. import profiler as _prof
    from . import tracing as _tracing

    prof_on = _prof.is_running()
    trace_on = trace is not None and _tracing.trace_on()
    if not (prof_on or _state.enabled or trace_on):
        yield
        return
    us0 = _prof.now_us() if prof_on else 0.0
    t0 = time.perf_counter()
    try:
        yield
    finally:
        if sync is not None:
            try:
                sync()
            except Exception:
                pass
        dt = time.perf_counter() - t0
        if prof_on:
            _prof.record(name, device, us0, _prof.now_us(), category)
        if trace_on:
            _tracing.record_span(name, category, trace, dt, **labels)
        if _state.enabled:  # re-check: may have flipped inside the region
            hname = histogram_name or sanitize_name(name) + "_seconds"
            histogram(hname, f"wall time of {name} (seconds)",
                      labels=tuple(labels)).observe(dt, **labels)


def timed(name: str, category: str = "host", **labels):
    """Decorator form of :func:`span`."""

    def deco(fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with span(name, category=category, **labels):
                return fn(*args, **kwargs)

        return wrapper

    return deco
