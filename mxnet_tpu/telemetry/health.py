"""Training health layer: device-memory accounting, fused NaN/Inf
sentinel, and a crash flight recorder.

The async training stack (fused updates, fused metrics, the bounded
in-flight window) moved the classic failure modes of a production TPU
run — device OOM, silent NaN/Inf divergence, a hang inside the
in-flight window — off the host thread where nothing observes them.
The registry (registry.py) answers "how fast"; this module answers "how
healthy" and "why did it die", the per-program memory/cost attribution
that fused-execution stacks (arXiv:2004.13336 sharded updates, TVM
arXiv:1802.04799) rely on to keep compiled execution debuggable.

Three subsystems, one module:

- **device-memory accounting** — every bind/plan/step-build records a
  per-program memory attribution row (argument/output/temp/peak bytes;
  the compiled program's ``memory_analysis()`` on real accelerators via
  :func:`attach_compiled_analysis`, shape math as the CPU fallback).
  Dispatch sites call :func:`reraise_if_oom` so a RESOURCE_EXHAUSTED
  error surfaces a ranked memory report (top programs by peak bytes +
  live-array breakdown) chained onto the original exception instead of
  a bare allocator message.
- **fused numerics sentinel** (``MXTPU_SENTINEL``, default off) — the
  fused-update bucket programs and the FusedTrainer step compute an
  isfinite-per-key mask and a gradient-norm scalar INSIDE the already-
  jitted program; :func:`sentinel_record` parks the resulting device
  scalars without reading them, and :func:`sentinel_check` (called at
  the same reporting boundaries that drain fused metrics) performs the
  only host sync — so a clean epoch keeps the zero-per-batch-sync
  property.  A non-finite flag raises :class:`NumericsError` (or warns,
  ``MXTPU_SENTINEL=warn``) naming the step id, site/bucket, and keys.
- **flight recorder** (``MXTPU_FLIGHT_RECORD``, default on) — a bounded
  ring of per-step records (step id, pipeline depth, dispatch latency,
  program signature, sentinel backlog) that :func:`dump_flight_record`
  writes together with the registry snapshot, the program-cache
  contents, and the memory report as ONE JSON — the black box read
  after a crash.  ``Module.fit``/``FusedTrainer.fit`` auto-dump on an
  uncaught exception (when ``MXTPU_FLIGHT_RECORD`` names a path) and a
  ``SIGUSR1`` dumps a live run without stopping it.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
import warnings
from collections import OrderedDict, deque

from ..base import MXNetError
from . import registry as _reg
from .exporters import json_snapshot

__all__ = [
    "NumericsError", "DeviceOOMError",
    "sentinel_mode", "sentinel_record", "sentinel_check", "sentinel_pending",
    "record_program", "attach_compiled_analysis", "program_table",
    "memory_report", "format_memory_report", "is_oom", "reraise_if_oom",
    "donation_saved",
    "flight_enabled", "record_step", "flight_ring", "dump_flight_record",
    "auto_dump",
    "host_identity", "set_clock_offset", "clock_offset", "step_time_stats",
]

_logger = logging.getLogger("mxnet_tpu.telemetry")

# --- telemetry families (docs/telemetry.md) --------------------------------
_TM_PROG_MEM = _reg.gauge(
    "program_memory_bytes",
    "per-compiled-program memory attribution recorded at bind/plan time "
    "(component=argument/output/temp/peak; source is memory_analysis on "
    "accelerators, shape math on CPU)", labels=("program", "component"))
_TM_OOM = _reg.counter(
    "device_memory_oom_total",
    "RESOURCE_EXHAUSTED errors intercepted at a dispatch site (each one "
    "re-raised as DeviceOOMError carrying the ranked memory report)",
    labels=("site",))
_TM_DONATED = _reg.counter(
    "device_memory_donated_bytes_total",
    "bytes of buffers donated to XLA per dispatch (in-place reuse the "
    "allocator never has to double-buffer)", labels=("site",))
_TM_SENT_REC = _reg.counter(
    "sentinel_records_total",
    "sentinel accumulations enqueued device-side (no host sync)",
    labels=("site",))
_TM_SENT_SYNC = _reg.counter(
    "sentinel_sync_total",
    "host syncs of parked sentinel state (site=boundary: a reporting "
    "boundary drained it; overflow: the pending window hit "
    "MXTPU_SENTINEL_WINDOW; manual: an explicit sentinel_check)",
    labels=("site",))
_TM_SENT_BAD = _reg.counter(
    "sentinel_nonfinite_total",
    "non-finite (key, step) gradient flags the sentinel attributed",
    labels=("site",))
_TM_SENT_NORM = _reg.gauge(
    "sentinel_grad_norm",
    "last synced gradient norm from the sentinel's in-program "
    "accumulator", labels=("site",))
_TM_FLIGHT_REC = _reg.counter(
    "flight_recorder_records_total",
    "per-step records appended to the flight-recorder ring")
_TM_FLIGHT_DUMP = _reg.counter(
    "flight_recorder_dumps_total",
    "flight-record JSON dumps written", labels=("trigger",))


class NumericsError(MXNetError):
    """Non-finite gradients detected by the fused sentinel."""


class DeviceOOMError(MXNetError):
    """Device RESOURCE_EXHAUSTED, re-raised with the memory report."""


# ---------------------------------------------------------------------------
# device-memory accounting
# ---------------------------------------------------------------------------
_PROG_CAP = 128
_programs: "OrderedDict[str, dict]" = OrderedDict()
_programs_lock = threading.Lock()


def record_program(program: str, argument: int = 0, output: int = 0,
                   temp: int = 0, alias: int = 0, peak=None,
                   source: str = "shape_math"):
    """Record (or refresh) one program's memory attribution row.

    Called at bind time (executor), plan build (kvstore_fused), and
    step build (trainer).  Rows are kept host-side regardless of the
    telemetry switch so the OOM report works in any configuration; the
    ``program_memory_bytes`` gauge mirrors them when recording is on.
    """
    if peak is None:
        peak = max(int(argument) + int(output) + int(temp) - int(alias), 0)
    entry = {"program": str(program), "argument_bytes": int(argument),
             "output_bytes": int(output), "temp_bytes": int(temp),
             "alias_bytes": int(alias), "peak_bytes": int(peak),
             "source": source}
    with _programs_lock:
        _programs[entry["program"]] = entry
        _programs.move_to_end(entry["program"])
        while len(_programs) > _PROG_CAP:
            _programs.popitem(last=False)
    if _reg.enabled():
        for comp in ("argument", "output", "temp", "peak"):
            _TM_PROG_MEM.set(entry[f"{comp}_bytes"],
                             program=entry["program"], component=comp)
    return entry


def attach_compiled_analysis(program: str, jitted, *args, **kwargs) -> bool:
    """Refresh a program's row from the COMPILED executable's memory
    analysis (XLA CompiledMemoryStats: argument/output/temp/alias bytes).

    Only attempted off-CPU — on real accelerators ``lower().compile()``
    shares the jit's compilation cache so this costs one lookup, while
    XLA:CPU reports nothing useful (the bind-time shape math stands as
    the documented CPU fallback).  Returns True when the row was
    upgraded."""
    import jax

    try:
        if jax.default_backend() == "cpu":
            return False
        mem = jitted.lower(*args, **kwargs).compile().memory_analysis()
        record_program(
            program,
            argument=getattr(mem, "argument_size_in_bytes", 0),
            output=getattr(mem, "output_size_in_bytes", 0),
            temp=getattr(mem, "temp_size_in_bytes", 0),
            alias=getattr(mem, "alias_size_in_bytes", 0),
            source="memory_analysis")
        return True
    except Exception:  # noqa: BLE001 — attribution must never break a bind
        return False


def program_table():
    """Current attribution rows, ranked by peak bytes (descending)."""
    with _programs_lock:
        rows = list(_programs.values())
    return sorted(rows, key=lambda r: r["peak_bytes"], reverse=True)


def donation_saved(nbytes: int, site: str):
    """Count bytes donated to XLA at a dispatch site."""
    if _reg.enabled() and nbytes > 0:
        _TM_DONATED.inc(nbytes, site=site)


def memory_report() -> dict:
    """Ranked per-program memory table + live device-array breakdown."""
    from .. import engine as _engine

    return {"programs": program_table(), "live": _engine.live_memory()}


def format_memory_report(report=None, top: int = 10) -> str:
    """Human-readable rendering of :func:`memory_report` (the text that
    rides on a DeviceOOMError)."""
    report = report or memory_report()
    lines = ["programs ranked by peak bytes:"]
    rows = report["programs"][:top]
    if not rows:
        lines.append("  (no programs recorded)")
    for r in rows:
        lines.append(
            "  %-48s peak=%d arg=%d out=%d temp=%d alias=%d (%s)" % (
                r["program"][:48], r["peak_bytes"], r["argument_bytes"],
                r["output_bytes"], r["temp_bytes"], r["alias_bytes"],
                r["source"]))
    live = report["live"]
    lines.append("live device arrays: %d (%d bytes)"
                 % (live["arrays"], live["bytes"]))
    for t in live.get("top", []):
        lines.append("  %12d bytes  %s %s"
                     % (t["bytes"], t["dtype"], t["shape"]))
    return "\n".join(lines)


_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "resource_exhausted",
                "Resource exhausted", "Out of memory", "out of memory")


def is_oom(exc) -> bool:
    """Does this exception look like a device allocator failure?"""
    msg = str(exc)
    return any(m in msg for m in _OOM_MARKERS)


def reraise_if_oom(exc, site: str):
    """Dispatch-site guard: when ``exc`` is RESOURCE_EXHAUSTED-shaped,
    log the ranked memory report and raise :class:`DeviceOOMError`
    (report attached, original exception chained).  Any other exception
    returns so the caller re-raises it unchanged."""
    if not is_oom(exc):
        return
    _TM_OOM.inc(site=site)
    try:
        text = format_memory_report()
    except Exception:  # noqa: BLE001 — the report must not mask the OOM
        text = "(memory report unavailable)"
    _logger.error("device OOM at %s\n%s", site, text)
    raise DeviceOOMError(
        f"device memory exhausted at {site}.\n{text}") from exc


# ---------------------------------------------------------------------------
# fused numerics sentinel
# ---------------------------------------------------------------------------
_pending: deque = deque()
_pending_lock = threading.Lock()


def sentinel_mode():
    """MXTPU_SENTINEL: None (off, default) | 'raise' | 'warn'."""
    raw = os.environ.get("MXTPU_SENTINEL", "0").strip().lower()
    if raw in ("", "0", "off", "false", "no"):
        return None
    if raw == "warn":
        return "warn"
    return "raise"


def sentinel_window() -> int:
    """MXTPU_SENTINEL_WINDOW — parked records before a forced sync."""
    try:
        return max(int(os.environ.get("MXTPU_SENTINEL_WINDOW", "1024")), 8)
    except ValueError:
        return 1024


def sentinel_pending() -> int:
    return len(_pending)


def sentinel_record(site: str, step: int, names, finite, norm=None,
                    packed_norm=False):
    """Park one program's sentinel outputs WITHOUT reading them.

    ``finite`` is a device array of 0/1 flags — one per key (1-D), or
    one per (step, key) for a multi-step dispatch (2-D, row i is step
    ``step + i``).  ``norm`` is the program's gradient-norm scalar;
    with ``packed_norm`` the norm rides as the LAST entry of ``finite``
    instead (one output leaf per dispatch, the cheapest shape for the
    hot loop).  The arrays stay device futures until
    :func:`sentinel_check` syncs them at a reporting boundary,
    preserving the hot loop's zero-per-batch-sync property."""
    with _pending_lock:
        _pending.append({"site": site, "step": int(step),
                         "names": tuple(names), "finite": finite,
                         "norm": norm, "packed": bool(packed_norm)})
        overflow = len(_pending) > sentinel_window()
    if _reg.enabled():
        _TM_SENT_REC.inc(site=site)
    if overflow:
        sentinel_check(site="overflow")


def sentinel_check(site: str = "boundary"):
    """Sync every parked sentinel record (the fused path's ONLY
    device→host sentinel sync) and attribute non-finite flags.

    Returns the offender list ``[(step, site, key_name), ...]``; raises
    :class:`NumericsError` naming them under ``MXTPU_SENTINEL=raise``
    (warns under ``warn``).  No-op when nothing is parked."""
    import numpy as np

    with _pending_lock:
        if not _pending:
            return []
        recs = list(_pending)
        _pending.clear()
    if _reg.enabled():
        _TM_SENT_SYNC.inc(site=site)
    offenders = []
    for r in recs:
        f = np.asarray(r["finite"])
        if f.ndim == 0:
            f = f.reshape(1)
        rows = f.reshape(1, -1) if f.ndim == 1 else f
        steps = ([r["step"]] if f.ndim == 1
                 else [r["step"] + i for i in range(rows.shape[0])])
        norm = r["norm"]
        if r.get("packed"):
            norm = rows[-1, -1]
            rows = rows[:, :-1]
        for row, step_id in zip(rows, steps):
            for j, ok in enumerate(row):
                if not ok:
                    name = (r["names"][j] if j < len(r["names"])
                            else f"#{j}")
                    offenders.append((step_id, r["site"], name))
        if norm is not None and _reg.enabled():
            try:
                _TM_SENT_NORM.set(float(np.asarray(norm)),
                                  site=r["site"])
            except Exception:  # noqa: BLE001
                pass
    if not offenders:
        return []
    if _reg.enabled():
        for _, osite, _ in offenders:
            _TM_SENT_BAD.inc(site=osite)
    msg = ("non-finite gradient(s) detected by MXTPU_SENTINEL: "
           + "; ".join(f"step {s} [{b}] key {n!r}"
                       for s, b, n in offenders[:16])
           + (f" (+{len(offenders) - 16} more)"
              if len(offenders) > 16 else ""))
    if sentinel_mode() == "raise":
        raise NumericsError(msg)
    warnings.warn(msg, RuntimeWarning, stacklevel=2)
    return offenders


# ---------------------------------------------------------------------------
# fleet identity + cross-host clock correlation (telemetry/fleet.py)
# ---------------------------------------------------------------------------
def host_identity() -> dict:
    """Who this process is in the fleet: host / pid / rank / generation.

    Env view on purpose (``MXTPU_RANK`` / ``MXTPU_DIST_GENERATION``, the
    same contract parallel/dist.py reads) — stamping a flight dump or a
    health probe must never initialize jax backends."""
    import socket

    def _int_env(name, alt=None):
        try:
            return int(os.environ.get(name, os.environ.get(alt, "0")
                                      if alt else "0") or 0)
        except ValueError:
            return 0

    return {"host": socket.gethostname(), "pid": os.getpid(),
            "rank": _int_env("MXTPU_RANK", "DMLC_RANK"),
            "generation": _int_env("MXTPU_DIST_GENERATION")}


_clock = {"offset_s": 0.0, "rtt_s": None, "at": None, "source": "none"}
_clock_lock = threading.Lock()


def set_clock_offset(offset_s: float, rtt_s=None, source="coordinator"):
    """Record this host's clock-offset estimate vs the coordinator.

    ``offset_s`` is (coordinator clock - local clock): the coordinator
    client derives it from each heartbeat's RTT midpoint (reply carries
    the server's wall time; offset = server_time - (send+recv)/2).  The
    estimate rides every flight dump so ``tools/fleetstat.py
    merge-trace`` can put per-host lanes on one timebase."""
    with _clock_lock:
        _clock["offset_s"] = float(offset_s)
        _clock["rtt_s"] = None if rtt_s is None else float(rtt_s)
        _clock["at"] = time.time()
        _clock["source"] = str(source)


def clock_offset() -> dict:
    """Latest clock-offset estimate ({offset_s, rtt_s, at, source})."""
    with _clock_lock:
        return dict(_clock)


def step_time_stats(window: int = 32) -> dict:
    """Per-step timing summary from the newest ``window`` flight-ring
    records — the straggler-detection feed the coordinator heartbeat
    reports.  Pure host-side ring reads (the records were stamped by
    the fit loops without syncing the device), so attaching this to
    every heartbeat preserves the zero-per-batch-host-sync property.

    Returns ``{count}`` plus, when the ring has them, ``step_wall_s``
    (mean wall seconds per step: explicit ``wall_s`` fields, falling
    back to deltas of the records' wall stamps), ``dispatch_s`` (mean
    dispatch latency) and ``last_step_t``."""
    recs = flight_ring()[-max(int(window), 2):]
    walls, disps = [], []
    prev_t = None
    for r in recs:
        w = r.get("wall_s")
        t = r.get("t")
        if w is None and prev_t is not None and t is not None:
            w = t - prev_t
        if t is not None:
            prev_t = t
        if w is not None and 0 <= w:
            walls.append(float(w))
        d = r.get("dispatch_s")
        if d is not None:
            disps.append(float(d))
    out = {"count": len(recs)}
    if walls:
        out["step_wall_s"] = sum(walls) / len(walls)
    if disps:
        out["dispatch_s"] = sum(disps) / len(disps)
    if recs and recs[-1].get("t") is not None:
        out["last_step_t"] = recs[-1]["t"]
    return out


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------
_OFF = ("0", "off", "false", "no")


def _flight_raw() -> str:
    return os.environ.get("MXTPU_FLIGHT_RECORD", "1").strip()


def flight_enabled() -> bool:
    """MXTPU_FLIGHT_RECORD gate (default on — a ring append per step)."""
    return _flight_raw().lower() not in _OFF


def _auto_dump_path():
    """A pathy MXTPU_FLIGHT_RECORD value enables crash auto-dump there."""
    raw = _flight_raw()
    if raw.lower() in _OFF or raw in ("1", "on", "true", "yes"):
        return None
    return raw


def flight_ring_size() -> int:
    try:
        return max(int(os.environ.get("MXTPU_FLIGHT_RING", "256")), 4)
    except ValueError:
        return 256


_ring: deque = deque(maxlen=flight_ring_size())
_ring_lock = threading.Lock()
_step_seq = 0


def _fault_slow_s() -> float:
    """MXTPU_FAULT_SLOW_S — seconds the ``slow_step`` fault site parks
    each step (default 0.05): the injected-straggler knob."""
    try:
        return max(float(os.environ.get("MXTPU_FAULT_SLOW_S", "0.05")), 0.0)
    except ValueError:
        return 0.05


def record_step(**fields):
    """Append one per-step record to the ring (host-only, no sync).

    Callers pass whatever is cheap at the dispatch site — step/epoch
    ids, pipeline depth, dispatch latency, program signature; a global
    sequence number, wall-clock stamp, and the sentinel backlog are
    added here.

    Fault site ``slow_step`` (docs/fault_tolerance.md): a ``drop``
    parks the host ``MXTPU_FAULT_SLOW_S`` here before stamping, so the
    ring's step walls — and everything downstream of them: heartbeat
    step stats, the coordinator's skew computation — see a genuinely
    slow host.  The straggler-detection tests and bench ride this."""
    global _ring, _step_seq
    if not flight_enabled():
        return None
    from .. import faults as _faults

    if _faults.active() and _faults.should_drop("slow_step"):
        time.sleep(_fault_slow_s())
    rec = dict(fields)
    with _ring_lock:
        _step_seq += 1
        rec.setdefault("seq", _step_seq)
        rec.setdefault("t", time.time())
        rec.setdefault("sentinel_pending", len(_pending))
        if _ring.maxlen != flight_ring_size():
            _ring = deque(_ring, maxlen=flight_ring_size())
        _ring.append(rec)
    if _reg.enabled():
        _TM_FLIGHT_REC.inc()
    return rec


def flight_ring():
    """Snapshot of the ring, oldest first."""
    with _ring_lock:
        return list(_ring)


def _default_dump_name() -> str:
    """Rank/generation-aware dump filename: N workers per host (or per
    generation) must never overwrite each other's black boxes."""
    ident = host_identity()
    return ("mxtpu_flight_record_r%d_g%d_%d.json"
            % (ident["rank"], ident["generation"], ident["pid"]))


def dump_flight_record(path=None, trigger: str = "manual") -> str:
    """Write the flight record as ONE JSON: the step-record ring, the
    registry snapshot, the compiled-program cache contents, the ranked
    memory report, the sentinel state, and this host's fleet identity
    (host/rank/generation + the coordinator clock-offset estimate, so
    ``tools/fleetstat.py merge-trace`` can lane and align it).
    Returns the path written."""
    from .. import executor as _executor

    if path is None:
        path = _auto_dump_path() or _default_dump_name()
    if os.path.isdir(path):
        path = os.path.join(path, _default_dump_name())
    with _executor._program_cache_lock:
        cache_keys = [repr(k)[:200] for k in _executor._program_cache]
    payload = {
        "version": 2,
        "time": time.time(),
        "trigger": trigger,
        "identity": {
            **host_identity(),
            "clock": clock_offset(),
            "coordinator": os.environ.get("MXTPU_COORD_ADDR",
                                          "").strip() or None,
        },
        "ring": flight_ring(),
        "registry": json_snapshot(),
        "program_cache": {
            "capacity": _executor.program_cache_capacity(),
            "size": len(cache_keys),
            "entries": cache_keys,
        },
        "memory": memory_report(),
        "sentinel": {"mode": sentinel_mode() or "off",
                     "pending": len(_pending)},
    }
    # the span buffer rides every dump (lazy import: tracing needs this
    # module's identity/clock helpers) — a post-mortem keeps the last
    # requests' traces, not just aggregate rings
    from . import tracing as _tracing

    payload["spans"] = _tracing.spans()
    # the perf-attribution ledgers ride too (lazy import, same reason):
    # untruncated (topn<=0) so a post-mortem never reads a cut table
    from . import perf as _perf

    payload["perf"] = _perf.profile_payload(topn=0)
    with open(path, "w") as f:
        json.dump(payload, f, indent=1, default=str)
    _TM_FLIGHT_DUMP.inc(trigger=trigger)
    return path


def _step_suffix_path(path: str) -> str:
    """``.../flight.json`` -> ``.../flight_step<seq>.json`` — each auto
    dump gets its own file keyed by the flight ring's step sequence, so
    a SIGUSR1 (or repeated faults) never clobbers the previous dump."""
    root, ext = os.path.splitext(path)
    return f"{root}_step{_step_seq}{ext or '.json'}"


def _prune_dumps(path: str):
    """Bounded dump retention: keep the newest ``MXTPU_FLIGHT_RING``
    step-suffixed dumps sharing this path's stem (same knob as the
    in-memory ring — the black boxes rotate like the records do)."""
    import glob
    import re

    root, ext = os.path.splitext(path)
    base = re.sub(r"_step\d+$", "", root)
    pat = re.compile(re.escape(base) + r"_step(\d+)" + re.escape(ext or
                                                                 ".json")
                     + "$")
    found = []
    for f in glob.glob(glob.escape(base) + "_step*" + (ext or ".json")):
        m = pat.match(f)
        if m:
            found.append((int(m.group(1)), f))
    found.sort()
    for _, f in found[:-flight_ring_size()] if found else []:
        try:
            os.remove(f)
        except OSError:
            pass


def auto_dump(trigger: str):
    """Best-effort dump for crash/signal/fault paths.

    ``exception``/``fault`` dump only when ``MXTPU_FLIGHT_RECORD``
    names a path (an uncaught exception must not litter the cwd by
    default); ``signal`` always dumps (the operator asked).  Dumps are
    step-suffixed and rotated (``MXTPU_FLIGHT_RING`` files max) so
    successive triggers never clobber each other.  Never raises;
    returns the path written (or None)."""
    try:
        if not flight_enabled():
            return None
        path = _auto_dump_path()
        if path is None and trigger != "signal":
            return None
        if path is None:
            path = _default_dump_name()
        if os.path.isdir(path):
            path = os.path.join(path, _default_dump_name())
        if trigger != "exception":
            # live-run triggers (SIGUSR1, injected faults) recur: each
            # dump gets a step-id suffix and the set rotates under the
            # MXTPU_FLIGHT_RING retention; the terminal exception dump
            # keeps the exact configured path (one per process death)
            path = _step_suffix_path(path)
        out = dump_flight_record(path, trigger=trigger)
        if trigger != "exception":
            _prune_dumps(out)
        return out
    except Exception:  # noqa: BLE001 — a dump failure must not mask the crash
        _logger.exception("flight-record auto-dump failed")
        return None


def _install_sigusr1():
    """SIGUSR1 -> dump the flight record of a live run (main thread
    only; chains any previously-installed handler)."""
    import signal

    try:
        prev = signal.getsignal(signal.SIGUSR1)

        def _handler(signum, frame):
            auto_dump("signal")
            if callable(prev) and prev not in (signal.SIG_DFL,
                                               signal.SIG_IGN):
                prev(signum, frame)

        signal.signal(signal.SIGUSR1, _handler)
    except (ValueError, OSError, AttributeError):
        pass  # non-main thread / platform without SIGUSR1


if flight_enabled():
    _install_sigusr1()
