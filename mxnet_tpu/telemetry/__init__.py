"""Unified telemetry runtime.

One process-wide metrics registry (labeled Counter/Gauge/Histogram), a
``span()``/``@timed`` API feeding the profiler's chrome-trace sink, and
exporters (Prometheus text + ``/metrics`` endpoint, JSON snapshot,
periodic logging).  Every subsystem — executor, engine, kvstore, io,
trainer — emits through this package; see ``docs/telemetry.md`` for the
metric catalog.

Quickstart::

    import mxnet_tpu as mx
    mx.telemetry.enable()                       # or MXTPU_TELEMETRY=1
    srv = mx.telemetry.start_http_server(9100)  # GET /metrics
    ... train ...
    print(mx.telemetry.generate_text())         # Prometheus exposition

Env knobs: ``MXTPU_TELEMETRY=1`` enables recording at import;
``MXTPU_TELEMETRY_HTTP_PORT=<port>`` additionally serves ``/metrics``
(``0`` binds an ephemeral port; a taken port auto-increments to the
next free one so multi-worker-per-host runs sharing the env value never
collide — :func:`http_address` reports what was actually bound).
Disabled (the default) every record call is a single flag check — safe
to leave instrumentation on hot paths.
"""
from __future__ import annotations

import os as _os

from .registry import (  # noqa: F401
    Counter, Gauge, Histogram, Registry, DEFAULT_BUCKETS,
    counter, gauge, histogram, get_registry, reset,
    enabled, enable, disable, span, timed, sanitize_name,
)
from .exporters import (  # noqa: F401
    generate_text, json_snapshot, dump_json, start_http_server,
    LoggingReporter,
)
from . import health  # noqa: F401
from .health import (  # noqa: F401
    NumericsError, DeviceOOMError, dump_flight_record, record_step,
    flight_ring, sentinel_check, sentinel_record, memory_report,
    format_memory_report,
)
from . import tracing  # noqa: F401
from .tracing import (  # noqa: F401
    SloPlane, record_span, spans_payload, trace_on, enable_tracing,
    mint_traceparent, parse_traceparent,
)
from . import perf  # noqa: F401
from .perf import profile_payload  # noqa: F401

_http_server = None
_port = _os.environ.get("MXTPU_TELEMETRY_HTTP_PORT")
if _port:
    enable()
    _http_server = start_http_server(int(_port), max_tries=16)


def http_address():
    """``host:port`` of the import-time ``/metrics`` server
    (``MXTPU_TELEMETRY_HTTP_PORT``), or None when none is running —
    what the coordinator join advertises for fleet federation."""
    if _http_server is None:
        return None
    host, port = _http_server.server_address[:2]
    return "%s:%d" % (host, port)
