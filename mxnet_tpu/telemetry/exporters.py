"""Exporters: Prometheus text exposition, JSON snapshot, /metrics HTTP
endpoint (stdlib http.server), and a periodic logging reporter.

The text format follows the Prometheus exposition format v0.0.4
(`# HELP` / `# TYPE` headers, escaped label values, cumulative histogram
buckets with an explicit ``+Inf``) so any Prometheus-compatible scraper
can consume the endpoint unmodified — no client library dependency.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Optional

from .registry import (Counter, Gauge, Histogram, Registry, get_registry)

__all__ = ["generate_text", "json_snapshot", "dump_json",
           "start_http_server", "LoggingReporter"]


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    f = float(v)
    if f != f:
        # the sentinel's grad-norm gauge goes NaN on a diverged run —
        # the exposition must keep serving exactly then
        return "NaN"
    return repr(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _labels_str(names, values, extra=()):
    pairs = [f'{n}="{_escape_label(v)}"' for n, v in zip(names, values)]
    pairs += [f'{n}="{_escape_label(v)}"' for n, v in extra]
    return "{" + ",".join(pairs) + "}" if pairs else ""


def generate_text(registry: Optional[Registry] = None) -> str:
    """Render the registry in Prometheus text exposition format."""
    registry = registry or get_registry()
    out = []
    for fam in registry.collect():
        out.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
        out.append(f"# TYPE {fam.name} {fam.typename}")
        if isinstance(fam, Histogram):
            for key, s in fam.samples():
                cum = 0
                for ub, c in zip(fam.buckets, s.counts):
                    cum += c
                    le = _fmt_value(ub)
                    out.append(
                        f"{fam.name}_bucket"
                        f"{_labels_str(fam.labelnames, key, [('le', le)])}"
                        f" {cum}")
                cum += s.counts[-1]
                out.append(
                    f"{fam.name}_bucket"
                    f"{_labels_str(fam.labelnames, key, [('le', '+Inf')])}"
                    f" {cum}")
                ls = _labels_str(fam.labelnames, key)
                out.append(f"{fam.name}_sum{ls} {_fmt_value(s.sum)}")
                out.append(f"{fam.name}_count{ls} {s.count}")
        else:
            for key, v in fam.samples():
                ls = _labels_str(fam.labelnames, key)
                out.append(f"{fam.name}{ls} {_fmt_value(v)}")
    return "\n".join(out) + ("\n" if out else "")


def json_snapshot(registry: Optional[Registry] = None) -> dict:
    """Registry contents as one JSON-serializable dict (programmatic
    consumption / file dumps; chrome-trace stays the profiler's job)."""
    registry = registry or get_registry()
    snap = {"timestamp": time.time(), "metrics": {}}
    for fam in registry.collect():
        entry = {"type": fam.typename, "help": fam.help,
                 "labelnames": list(fam.labelnames), "samples": []}
        if isinstance(fam, Histogram):
            entry["buckets"] = list(fam.buckets)
            for key, s in fam.samples():
                entry["samples"].append({
                    "labels": dict(zip(fam.labelnames, key)),
                    "counts": list(s.counts),
                    "sum": s.sum, "count": s.count,
                })
        else:
            for key, v in fam.samples():
                entry["samples"].append({
                    "labels": dict(zip(fam.labelnames, key)), "value": v})
        snap["metrics"][fam.name] = entry
    return snap


def dump_json(filename: str, registry: Optional[Registry] = None) -> str:
    """Write :func:`json_snapshot` to ``filename``; returns the path."""
    with open(filename, "w") as f:
        json.dump(json_snapshot(registry), f, indent=1)
    return filename


def start_http_server(port: int = 0, addr: str = "127.0.0.1",
                      registry: Optional[Registry] = None,
                      max_tries: int = 1):
    """Serve ``/metrics`` (Prometheus text) and ``/metrics.json`` on a
    daemon thread.  ``port=0`` binds an ephemeral port — read it back
    from the returned server's ``server_address``.  ``max_tries`` > 1
    auto-increments past ports already bound (multi-worker-per-host
    runs sharing one ``MXTPU_TELEMETRY_HTTP_PORT`` value must not fight
    over the socket — each worker lands on the next free port and
    advertises the bound one through its coordinator join).  Call
    ``.shutdown()`` to stop."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    reg = registry or get_registry()

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            path = self.path.split("?", 1)[0]
            if path in ("/", "/metrics", "/metrics.json"):
                # fold the perf-attribution ledgers into program_mfu/
                # program_roofline right before the render — derived
                # gauges are computed per scrape, not per batch (lazy
                # import: perf pulls health which pulls this module)
                from . import perf as _perf

                _perf.publish_gauges()
            if path in ("/", "/metrics"):
                body = generate_text(reg).encode("utf-8")
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif path == "/metrics.json":
                body = json.dumps(json_snapshot(reg)).encode("utf-8")
                ctype = "application/json"
            elif path == "/profile":
                # the perf-attribution plane's ranked-programs document
                # (docs/perf_attr.md): cost rows x runtime ledger x peak
                # table, rendered by tools/explain.py
                from . import perf as _perf

                body = json.dumps(_perf.profile_payload(),
                                  default=str).encode("utf-8")
                ctype = "application/json"
            elif path == "/spans.json":
                # the bounded trace-span buffer + identity/clock offset
                # (telemetry/tracing.py) — every metrics endpoint in the
                # fleet serves it, so fleetstat.py trace can join spans
                # from training hosts too, not just the serving fleet
                from . import tracing as _tracing

                body = json.dumps(_tracing.spans_payload(),
                                  default=str).encode("utf-8")
                ctype = "application/json"
            elif path == "/healthz":
                # liveness probe, distinct from the scrape endpoint:
                # answers "is the process serving" without the cost (or
                # cardinality) of a full exposition render
                from . import health as _health

                ident = _health.host_identity()
                payload = {
                    "status": "ok",
                    "families": len(reg.collect()),
                    "flight_ring_len": len(_health.flight_ring()),
                    # fleet topology self-assembly (ISSUE-14): a scraper
                    # probing health endpoints alone learns who this
                    # process is and where its membership authority lives
                    "rank": ident["rank"],
                    "generation": ident["generation"],
                    "coordinator_addr": os.environ.get(
                        "MXTPU_COORD_ADDR", "").strip() or None,
                }
                # cluster-health gauges ride along when their families
                # exist (ISSUE-13): the dead-worker count the PS /
                # coordinator tracks, and the elastic generation — the
                # two numbers an operator probing a sick cluster needs
                for fam_name, key in (("kvstore_dead_workers",
                                       "kvstore_dead_workers"),
                                      ("dist_generation",
                                       "dist_generation"),
                                      ("dist_hosts_alive",
                                       "dist_hosts_alive")):
                    for fam in reg.collect():
                        if fam.name == fam_name:
                            vals = [v for _, v in fam.samples()]
                            if vals:
                                payload[key] = max(vals)
                body = json.dumps(payload).encode("utf-8")
                ctype = "application/json"
            else:
                self.send_error(404)
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # scrapers are chatty; stay quiet
            pass

    last_exc = None
    for i in range(max(int(max_tries), 1)):
        try:
            srv = ThreadingHTTPServer((addr, port + i if port else 0),
                                      _Handler)
            break
        except OSError as exc:
            last_exc = exc
    else:
        raise last_exc
    srv.daemon_threads = True
    thread = threading.Thread(target=srv.serve_forever, daemon=True,
                              name="mxtpu-telemetry-http")
    thread.start()
    return srv


def _dist_log_prefix() -> str:
    """``rank/size@generation`` log prefix in multi-host runs (import is
    lazy: parallel.dist imports this package at module load)."""
    try:
        from ..parallel import dist as _dist

        return _dist.log_prefix()
    except Exception:  # noqa: BLE001 — logging must never require dist
        return ""


class LoggingReporter:
    """Periodically log a compact snapshot (counters + gauges + histogram
    count/mean) — the "tail the training log" consumption mode, Speedometer
    generalized to every registered metric.  Lines carry the
    ``[rank/size@generation]`` prefix in multi-host runs so interleaved
    elastic-launcher logs stay attributable."""

    def __init__(self, interval: float = 60.0, logger=None,
                 registry: Optional[Registry] = None, level=logging.INFO):
        self.interval = float(interval)
        self.logger = logger or logging.getLogger("mxnet_tpu.telemetry")
        self.level = level
        self.registry = registry or get_registry()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def report_once(self):
        parts = []
        for fam in self.registry.collect():
            for key, s in fam.samples():
                tag = fam.name
                if key:
                    tag += "{" + ",".join(
                        f"{n}={v}" for n, v in zip(fam.labelnames, key)) + "}"
                if isinstance(fam, Histogram):
                    mean = s.sum / s.count if s.count else 0.0
                    parts.append(f"{tag} n={s.count} mean={mean:.6g}s")
                else:
                    parts.append(f"{tag}={s:.6g}" if isinstance(s, float)
                                 else f"{tag}={s}")
        if parts:
            self.logger.log(self.level, "%stelemetry: %s",
                            _dist_log_prefix(), "  ".join(parts))

    def start(self):
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval):
                try:
                    self.report_once()
                except Exception:  # noqa: BLE001 — reporting must not kill
                    self.logger.exception("telemetry reporter failed")

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="mxtpu-telemetry-report")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
