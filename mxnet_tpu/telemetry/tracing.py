"""Request-scoped distributed tracing + the serving-fleet SLO plane.

PRs 1/5/14 rebuilt the source paper's engine profiler as process
metrics, a flight recorder, and a fleet-federated metrics plane — all
*aggregate* lenses.  Nothing could answer "where did THIS request's
21 ms of p99 TTFT go?" across router -> replica -> scheduler ->
paged-KV.  This module is that per-request lens, plus the burn-rate SLO
evaluation the future autoscaler (ROADMAP item 3) will close its
control loop over.

**Trace propagation.**  The router mints a W3C-``traceparent``-style
header per ``POST /generate``::

    traceparent: 00-<32 hex trace-id>-<16 hex parent-span-id>-<2 hex flags>

(flags bit 0 = sampled, exactly the W3C grammar), forwards it on every
re-route attempt (same trace id, fresh parent span id), and the replica
server threads it through :class:`~mxnet_tpu.serving.scheduler.Request`.
Sampling is decided ONCE at mint time (``MXTPU_TRACE_SAMPLE``) and
rides the flags byte, so every hop agrees without coordination.

**Spans.**  :func:`record_span` appends one flat dict to a bounded
per-process ring (``MXTPU_SPAN_RING``) — a pure host-side deque write,
never a device sync (``tools/lint.py`` proves the tick-path callers;
``spans_payload`` is a declared ``analysis/config.py:ENTRY_POINTS``
flush path).  Spans are stamped with the END wall time ``t`` plus
``dur_s`` (the flight-ring convention), so ``tools/fleetstat.py trace
<id>`` can join router + replica buffers onto one clock-corrected
timebase via the PR-14 ``identity.clock.offset_s`` machinery.  The
request's terminal span additionally lands in the PR-5 flight ring
(``health.record_step(loop="serve", ...)``), so a crash dump carries
the last requests too.

**SLO plane.**  :class:`SloPlane` turns the router's per-request
records into multi-window (5 s / 60 s) burn rates against two
objectives — ``availability`` (request relayed without a 5xx/transport
failure) and ``ttft`` (time-to-first-token under ``MXTPU_SLO_TTFT_MS``)
— both targeting the ``MXTPU_SLO_AVAIL`` good-fraction.  burn rate =
observed bad fraction / error budget ``(1 - MXTPU_SLO_AVAIL)``: 1.0
burns the budget exactly at the objective, >1 is an alert.  The plane
keeps exemplar trace ids for the SLOWEST ``serve_ttft_seconds``
observations, so a burning SLO links straight to offending traces
(``GET /slo`` on the router; ``fleetstat.py --slo`` renders the table).

Env knobs (docs/how_to/env_var.md round 20): ``MXTPU_TRACE``,
``MXTPU_TRACE_SAMPLE``, ``MXTPU_SPAN_RING``, ``MXTPU_SLO_TTFT_MS``,
``MXTPU_SLO_AVAIL``.  Span model + runbook: docs/tracing.md.
"""
from __future__ import annotations

import os
import re
import threading
import time
from collections import deque

from . import registry as _reg

__all__ = [
    "trace_on", "enable_tracing", "sample_rate", "span_ring_size",
    "mint_traceparent", "parse_traceparent", "child_traceparent",
    "mint_span_id", "record_span", "spans", "spans_payload",
    "clear_spans", "slo_ttft_ms", "slo_avail", "SloPlane", "TICK_EVERY",
]

# --- tracing + SLO metric families (docs/telemetry.md) ----------------------
_TM_SPANS = _reg.counter(
    "trace_spans_total",
    "spans recorded into the bounded per-process span buffer "
    "(GET /spans.json) by emitting component", labels=("svc",))
_TM_SLO_BURN = _reg.gauge(
    "slo_burn_rate",
    "SLO error-budget burn rate per objective and trailing window: "
    "observed bad fraction / (1 - MXTPU_SLO_AVAIL); 1.0 burns the "
    "budget exactly at the objective, >1 pages",
    labels=("objective", "window"))
_TM_SLO_VIOL = _reg.counter(
    "slo_violations_total",
    "requests that violated an SLO objective: availability (5xx or "
    "transport failure through the router) or ttft (time-to-first-"
    "token above MXTPU_SLO_TTFT_MS)", labels=("objective",))

# Decode-tick span cadence: with tracing on, every TICK_EVERY-th engine
# tick emits one span per sampled live request (a per-tick span per
# request would swamp the ring at decode rates).  Tests lower it to 1.
TICK_EVERY = 16

_TP_RE = re.compile(r"^00-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")


class _State:
    __slots__ = ("enabled",)

    def __init__(self, on):
        self.enabled = on


_state = _State(os.environ.get("MXTPU_TRACE", "0").lower()
                not in ("", "0", "false", "off"))


def trace_on() -> bool:
    """Is span recording on?  (``MXTPU_TRACE=1`` at import, or
    :func:`enable_tracing` at runtime.)  One attribute read — cheap
    enough to guard every tick-path call site."""
    return _state.enabled


def enable_tracing(on: bool = True):
    """Turn span recording on/off at runtime (bench A/B, tests)."""
    _state.enabled = bool(on)


def sample_rate() -> float:
    """``MXTPU_TRACE_SAMPLE`` — fraction of routed requests minted with
    the W3C sampled flag (default 1.0).  Unsampled requests still get a
    trace id (log/exemplar correlation) but record no spans."""
    try:
        return min(max(float(
            os.environ.get("MXTPU_TRACE_SAMPLE", "1") or 1.0), 0.0), 1.0)
    except ValueError:
        return 1.0


def span_ring_size() -> int:
    """``MXTPU_SPAN_RING`` — bounded span-buffer capacity (default
    2048 spans; the oldest are overwritten)."""
    try:
        return max(int(os.environ.get("MXTPU_SPAN_RING", "2048")), 16)
    except ValueError:
        return 2048


def slo_ttft_ms() -> float:
    """``MXTPU_SLO_TTFT_MS`` — the TTFT objective threshold
    (default 250 ms)."""
    try:
        return max(float(os.environ.get("MXTPU_SLO_TTFT_MS", "250")
                         or 250.0), 0.0)
    except ValueError:
        return 250.0


def slo_avail() -> float:
    """``MXTPU_SLO_AVAIL`` — target good fraction for BOTH objectives
    (default 0.99: 99% of requests succeed, 99% under the TTFT
    threshold).  The error budget is ``1 - MXTPU_SLO_AVAIL``."""
    try:
        v = float(os.environ.get("MXTPU_SLO_AVAIL", "0.99") or 0.99)
    except ValueError:
        return 0.99
    return min(max(v, 0.0), 0.999999)


# ---------------------------------------------------------------------------
# trace-id grammar (W3C traceparent, version 00)
# ---------------------------------------------------------------------------
def mint_span_id() -> str:
    return os.urandom(8).hex()


def mint_traceparent(sampled=None) -> str:
    """A fresh ``00-<trace>-<span>-<flags>`` header.  ``sampled=None``
    decides via ``MXTPU_TRACE_SAMPLE`` (always False when tracing is
    off — unsampled ids still correlate logs and SLO exemplars)."""
    if sampled is None:
        sampled = trace_on() and os.urandom(1)[0] < sample_rate() * 256.0
    return "00-%s-%s-%02x" % (os.urandom(16).hex(), mint_span_id(),
                              1 if sampled else 0)


def parse_traceparent(header):
    """``{"trace", "parent", "sampled"}`` from a traceparent header, or
    None when absent/malformed (a bad client header degrades to a fresh
    trace, never a 4xx)."""
    if not header or not isinstance(header, str):
        return None
    m = _TP_RE.match(header.strip().lower())
    if m is None:
        return None
    return {"trace": m.group(1), "parent": m.group(2),
            "sampled": bool(int(m.group(3), 16) & 1)}


def child_traceparent(trace: str, sampled: bool, span=None) -> str:
    """Same trace, fresh parent span id — what the router forwards on
    each (re-)route attempt.  Pass ``span`` to reuse a pre-minted id
    (the router records its attempt span under the SAME id it
    forwards, so the replica's spans parent it exactly)."""
    return "00-%s-%s-%02x" % (trace, span or mint_span_id(),
                              1 if sampled else 0)


# ---------------------------------------------------------------------------
# the bounded per-process span buffer (GET /spans.json)
# ---------------------------------------------------------------------------
_spans: deque = deque(maxlen=span_ring_size())
_spans_lock = threading.Lock()
_span_seq = 0


def record_span(name, svc, trace, dur_s, t=None, parent=None, span=None,
                **attrs):
    """Append one span: a pure host-side dict + deque write (the lint
    proves the tick-path callers never sync the device through here).

    ``t`` is the END wall-clock stamp (``time.time()`` now when omitted)
    and ``dur_s`` the span length — the flight-ring convention, so
    cross-host joins shift ``t`` by the clock offset and draw
    ``[t - dur_s, t]``.  ``trace`` may be None for ambient process
    events (e.g. a step-time KV eviction with no admitting request).
    Extra ``attrs`` land flat on the record; reserved keys lose."""
    global _spans, _span_seq
    rec = dict(attrs)
    with _spans_lock:
        _span_seq += 1
        sid = span or ("%d-%d" % (os.getpid(), _span_seq))
        rec.update(sid=sid, trace=trace, parent=parent, name=str(name),
                   svc=str(svc), t=(time.time() if t is None else float(t)),
                   dur_s=float(dur_s))
        if _spans.maxlen != span_ring_size():
            _spans = deque(_spans, maxlen=span_ring_size())
        _spans.append(rec)
    _TM_SPANS.inc(svc=str(svc))
    return rec


def spans(trace=None):
    """Snapshot of the buffer, oldest first (optionally one trace's)."""
    with _spans_lock:
        out = list(_spans)
    if trace is not None:
        out = [s for s in out if s.get("trace") == trace]
    return out


def clear_spans():
    """Drop the buffer (bench A/B runs, test isolation)."""
    with _spans_lock:
        _spans.clear()


def spans_payload(trace=None) -> dict:
    """The ``GET /spans.json`` body: this process's identity + clock
    offset (so ``fleetstat.py trace`` lanes and aligns it with the
    PR-14 offset machinery) and the span snapshot.  Declared in
    ``analysis/config.py:ENTRY_POINTS`` — the flush path must stay a
    pure host-side buffer read."""
    from . import health as _health

    ident = _health.host_identity()
    return {"host": ident["host"], "pid": ident["pid"],
            "rank": ident["rank"], "clock": _health.clock_offset(),
            "trace_on": trace_on(), "spans": spans(trace)}


# ---------------------------------------------------------------------------
# the SLO plane (router-side)
# ---------------------------------------------------------------------------
class SloPlane:
    """Multi-window burn rates over per-request records.

    :meth:`record` is on the router's per-request path: one bounded
    deque append + counter bumps under a lock.  :meth:`snapshot` (the
    ``GET /slo`` body; also called from the router's scrape sweep so the
    gauges stay fresh without polling) recomputes each trailing
    window's bad fraction and burn rate, and returns the slowest-TTFT
    exemplar trace ids."""

    WINDOWS = (5.0, 60.0)

    def __init__(self, ttft_ms=None, avail=None, capacity=4096,
                 max_exemplars=8):
        self.ttft_s = (slo_ttft_ms() if ttft_ms is None
                       else float(ttft_ms)) / 1e3
        self.avail = slo_avail() if avail is None else float(avail)
        self.max_exemplars = int(max_exemplars)
        self._lock = threading.Lock()
        self._records = deque(maxlen=int(capacity))
        self._violations = {"availability": 0, "ttft": 0}
        self._exemplars = []          # [(ttft_s, trace, t)] slowest first

    def record(self, ok, ttft_s=None, trace=None):
        """One terminal routed request: ``ok`` = relayed without a
        5xx/transport failure; ``ttft_s`` when the replica reported
        one.  Returns the (availability, ttft) violation pair."""
        bad_avail = not ok
        bad_ttft = ttft_s is not None and ttft_s > self.ttft_s
        with self._lock:
            self._records.append(
                (time.time(), bool(ok), ttft_s, trace))
            if bad_avail:
                self._violations["availability"] += 1
            if bad_ttft:
                self._violations["ttft"] += 1
            if ttft_s is not None:
                self._exemplars.append((float(ttft_s), trace, time.time()))
                self._exemplars.sort(key=lambda e: -e[0])
                del self._exemplars[self.max_exemplars:]
        if bad_avail:
            _TM_SLO_VIOL.inc(objective="availability")
        if bad_ttft:
            _TM_SLO_VIOL.inc(objective="ttft")
        return bad_avail, bad_ttft

    def snapshot(self) -> dict:
        now = time.time()
        with self._lock:
            recs = list(self._records)
            viol = dict(self._violations)
            exemplars = list(self._exemplars)
        budget = max(1.0 - self.avail, 1e-9)
        windows = {}
        for w in self.WINDOWS:
            sel = [r for r in recs if r[0] >= now - w]
            n = len(sel)
            bad_avail = sum(1 for r in sel if not r[1])
            with_ttft = [r for r in sel if r[2] is not None]
            bad_ttft = sum(1 for r in with_ttft if r[2] > self.ttft_s)
            label = "%ds" % int(w)
            burn_avail = (bad_avail / n) / budget if n else 0.0
            burn_ttft = (bad_ttft / len(with_ttft)) / budget \
                if with_ttft else 0.0
            _TM_SLO_BURN.set(burn_avail, objective="availability",
                             window=label)
            _TM_SLO_BURN.set(burn_ttft, objective="ttft", window=label)
            windows[label] = {
                "requests": n,
                "bad_availability": bad_avail,
                "bad_ttft": bad_ttft,
                "burn_rate": {"availability": round(burn_avail, 4),
                              "ttft": round(burn_ttft, 4)},
            }
        return {
            "objectives": {"ttft_ms": round(self.ttft_s * 1e3, 3),
                           "availability": self.avail},
            "error_budget": round(budget, 9),
            "windows": windows,
            "violations_total": viol,
            "exemplars": [
                {"trace": tr, "ttft_ms": round(tt * 1e3, 3), "t": at}
                for tt, tr, at in exemplars],
        }
