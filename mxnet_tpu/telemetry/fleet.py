"""Fleet observability plane: metrics federation + straggler detection.

PR 13 made the runtime multi-host (collective ``dist_sync``, coordinator
membership, elastic generations) but every observability surface stayed
strictly per-process: an N-host world is N disconnected ``/metrics``
dashboards and N flight-recorder black boxes.  This module is the
cross-host lens — TVM-style stacks (arXiv:1802.04799) showed that
measurement feeding back into optimization is what closes MFU gaps, and
a fleet cannot optimize what only one host can see:

- :class:`FleetScraper` — the coordinator (already the membership
  authority, parallel/coordinator.py) scrapes each member's
  ``/metrics.json`` endpoint on a background thread every
  ``MXTPU_FLEET_SCRAPE_S`` and keeps the latest per-member snapshot.
  :func:`merge_snapshots` folds those into host-labeled merged families,
  served by the coordinator at ``GET /fleet`` (per-host rows + merged
  metrics + generation/liveness) and rendered by ``tools/fleetstat.py``.
- **straggler detection** — member heartbeats carry per-step wall /
  dispatch timings sampled from the flight-recorder ring
  (:func:`telemetry.health.step_time_stats`, pure host-side).  The
  coordinator computes the per-generation step-time skew (slowest
  host's mean step wall over the fleet median), publishes the
  ``dist_step_skew_ratio`` / ``dist_straggler_host`` gauge families,
  and names a sustained straggler in ``/cluster`` and ``/fleet`` —
  the signal the elastic launcher (drop the sick host) and future
  autotuning (ROADMAP item 3) both need.

The scrape loop and the heartbeat feed are steady-state background
loops: both are declared in ``analysis/config.py:ENTRY_POINTS`` so the
lint gate proves they never touch the device.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time

from . import registry as _reg

__all__ = [
    "FleetScraper", "merge_snapshots", "fleet_scrape_s", "straggler_ratio",
    "fetch_json", "post_json",
    "STRAGGLER_MIN_STEPS", "STRAGGLER_SUSTAIN",
]

_logger = logging.getLogger("mxnet_tpu.telemetry.fleet")

# --- telemetry families (docs/telemetry.md) --------------------------------
_TM_SKEW = _reg.gauge(
    "dist_step_skew_ratio",
    "per-generation step-time skew: the slowest member's mean step wall "
    "time over the median of the OTHER members (heartbeat-reported "
    "flight-ring timings); sustained values above MXTPU_STRAGGLER_RATIO "
    "flag a straggler")
_TM_STRAGGLER = _reg.gauge(
    "dist_straggler_host",
    "1 while the labeled member is flagged as the sustained straggler of "
    "the current generation (0 once it recovers or leaves)",
    labels=("host",))
_TM_SCRAPE = _reg.counter(
    "fleet_scrape_total",
    "per-member /metrics.json federation scrapes by the coordinator's "
    "fleet thread", labels=("result",))
_TM_SCRAPE_SEC = _reg.histogram(
    "fleet_scrape_seconds",
    "wall time of one federation sweep over every member that "
    "advertised a telemetry endpoint")

#: A member's heartbeat step stats enter the skew computation only once
#: this many ring records back them (one noisy first step must not flag
#: a whole host).
STRAGGLER_MIN_STEPS = 3
#: Consecutive coordinator monitor sweeps the skew must stay above the
#: threshold before the straggler is *named* ("sustained": one GC pause
#: is not a sick host; sweeps run every lease/4 seconds).
STRAGGLER_SUSTAIN = 2


def fleet_scrape_s() -> float:
    """MXTPU_FLEET_SCRAPE_S — federation scrape interval (default 5s)."""
    try:
        return max(float(os.environ.get("MXTPU_FLEET_SCRAPE_S", "5")), 0.1)
    except ValueError:
        return 5.0


def straggler_ratio() -> float:
    """MXTPU_STRAGGLER_RATIO — step-wall skew over the fleet median at
    which a member counts as straggling (default 2.0; <=1 disables)."""
    try:
        return float(os.environ.get("MXTPU_STRAGGLER_RATIO", "2.0"))
    except ValueError:
        return 2.0


def fetch_json(addr: str, path: str, timeout: float):
    """One bounded GET against a member endpoint — a dead member must
    cost at most ``timeout``, never hang the sweep.  Shared by the
    coordinator federation scrape and the serving router
    (serving/router.py)."""
    import http.client

    host, port = str(addr).rsplit(":", 1)
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        data = resp.read()
        if resp.status != 200:
            raise OSError(f"{addr}{path}: HTTP {resp.status}")
        return json.loads(data)
    finally:
        conn.close()


def post_json(addr: str, path: str, payload: dict, timeout: float):
    """One bounded JSON POST against a member endpoint (the router's
    /admin/drain fan-out rides this)."""
    import http.client

    host, port = str(addr).rsplit(":", 1)
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
    try:
        body = json.dumps(payload or {}).encode()
        conn.request("POST", path, body,
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        data = resp.read()
        if resp.status != 200:
            raise OSError(f"{addr}{path}: HTTP {resp.status}")
        return json.loads(data)
    finally:
        conn.close()


def fetch_spans(addr: str, trace=None, timeout: float = 5.0):
    """One member's ``GET /spans.json`` payload (identity + clock
    offset + span buffer — telemetry/tracing.py), optionally filtered
    to one trace id client-side.  ``fleetstat.py trace`` sweeps this
    over the router and every replica."""
    payload = fetch_json(addr, "/spans.json", timeout=timeout)
    if trace is not None:
        payload["spans"] = [s for s in payload.get("spans") or []
                            if s.get("trace") == trace]
    return payload


_fetch_json = fetch_json  # internal alias (pre-ISSUE-15 name)


class FleetScraper:
    """Background metrics federation for the coordinator.

    ``targets_fn`` returns the current ``{member: telemetry_addr}`` map
    (the coordinator snapshots it from live leases, so dead members drop
    out of the sweep automatically).  Each sweep replaces the snapshot
    wholesale; a member that failed its scrape keeps an ``ok=False``
    row with the error, so ``/fleet`` distinguishes "no endpoint" from
    "endpoint dead".
    """

    def __init__(self, targets_fn, interval_s=None):
        self._targets_fn = targets_fn
        self.interval_s = (fleet_scrape_s() if interval_s is None
                          else float(interval_s))
        self._lock = threading.Lock()
        self._snap: dict = {}
        self._stop = threading.Event()
        self._thread = None

    def scrape_once(self) -> dict:
        """One federation sweep: GET every member's ``/metrics.json``.
        Pure host-side HTTP — never touches the device (lint-enforced:
        this is an ENTRY_POINTS steady-state loop)."""
        targets = dict(self._targets_fn() or {})
        t0 = time.perf_counter()
        results = {}
        for member, addr in targets.items():
            try:
                snap = _fetch_json(addr, "/metrics.json",
                                   timeout=min(self.interval_s, 5.0))
                results[member] = {"addr": addr, "ok": True,
                                   "at": time.time(),
                                   "metrics": snap.get("metrics") or {}}
                if _reg.enabled():
                    _TM_SCRAPE.inc(result="ok")
            except Exception as exc:  # noqa: BLE001 — one dead member must not kill the sweep
                results[member] = {"addr": addr, "ok": False,
                                   "at": time.time(), "error": repr(exc)}
                if _reg.enabled():
                    _TM_SCRAPE.inc(result="error")
        if _reg.enabled():
            _TM_SCRAPE_SEC.observe(time.perf_counter() - t0)
        with self._lock:
            self._snap = results
        return results

    def snapshot(self) -> dict:
        """Latest per-member scrape results (member -> row)."""
        with self._lock:
            return {k: dict(v) for k, v in self._snap.items()}

    def start(self):
        if self._thread is not None:
            return self
        self._stop.clear()

        def _loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.scrape_once()
                except Exception:  # noqa: BLE001 — the sweep must survive
                    _logger.exception("fleet scrape sweep failed")

        self._thread = threading.Thread(target=_loop, daemon=True,
                                        name="mxtpu-fleet-scrape")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


def merge_snapshots(per_member: dict) -> dict:
    """Fold per-member ``/metrics.json`` snapshots into ONE catalog of
    host-labeled families: every sample gains a leading ``host`` label
    carrying the member id, so `sum by (host) (...)`-style queries work
    on the merged view exactly as they would on a real federation
    endpoint.  ``per_member`` maps member id -> the ``metrics`` dict of
    that member's snapshot (exporters.json_snapshot shape)."""
    out: dict = {}
    for member in sorted(per_member):
        for name, fam in (per_member[member] or {}).items():
            dst = out.get(name)
            if dst is None:
                dst = out[name] = {
                    "type": fam.get("type", "untyped"),
                    "help": fam.get("help", ""),
                    "labelnames": ["host"] + list(fam.get("labelnames", ())),
                }
                if "buckets" in fam:
                    dst["buckets"] = list(fam["buckets"])
                dst["samples"] = []
            for s in fam.get("samples", ()):
                row = dict(s)
                row["labels"] = {"host": member, **(s.get("labels") or {})}
                dst["samples"].append(row)
    return out
