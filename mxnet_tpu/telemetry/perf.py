"""Per-program performance attribution plane (docs/perf_attr.md).

bench.py answers "how fast is the build this round"; this module
answers "where does the step time GO" while a real run is running.
Three ledgers, all host-side, all pure arithmetic:

- **analytical cost rows** — at a program's first dispatch the plane
  reads the COMPILED executable's ``cost_analysis()`` (analytical
  FLOPs / bytes-accessed straight from the optimized HLO, the ground
  truth hand-maintained formulas like ``TRAIN_FLOPS_PER_IMG`` drift
  away from) and records one row per compiled program, keyed by the
  same ``structural_signature``-derived label the PR-5 memory rows
  use.  Backends without ``cost_analysis`` fall back to an "unknown"
  row — the capture never raises and never runs when the plane is
  disarmed.
- **runtime attribution** — the already-timed dispatch sites
  (executor fwd/fwdbwd, FusedTrainer.step, the serving tick) feed a
  per-program cumulative host-wall ledger, and the fit loops split
  each step's wall into ``data_wait`` / ``dispatch`` /
  ``window_stall`` buckets (plus the epoch-boundary ``boundary_sync``
  drain) from perf_counter stamps they already take — zero new
  per-batch device syncs by construction.
- **roofline/MFU** — analytical FLOPs over measured wall against the
  device-kind peak table (hoisted here from bench.py so bench and
  telemetry can never disagree) yields a live ``program_mfu``; the
  operational intensity (flops/byte) against the machine balance
  (peak FLOP/s over peak bytes/s) yields the classic roofline verdict
  — a ratio >= 1 means the program SHOULD be compute-bound.

Armed by ``MXTPU_PERF_ATTR=1`` (or :func:`enable`); served on
``GET /profile`` and ``/metrics.json``; rendered by
``tools/explain.py``; folded into the flight dump.
"""
from __future__ import annotations

import os
import threading
from collections import OrderedDict

from . import registry as _reg

__all__ = [
    "PEAK_TFLOPS", "PEAK_GBPS",
    "peak_flops", "peak_bytes_per_sec", "machine_balance", "device_kind",
    "enabled", "enable", "disable",
    "attach_cost_analysis", "record_cost", "cost_table",
    "record_dispatch", "record_step_buckets", "record_bucket",
    "runtime_table", "bucket_table",
    "publish_gauges", "profile_payload", "speedometer_suffix", "reset",
]

# ---------------------------------------------------------------------------
# device peaks (single source of truth — bench.py imports these)
# ---------------------------------------------------------------------------
# (substring, peak TFLOP/s) matched against jax's device_kind, first hit
# wins — "v5p" must precede "v5", and the nominal "cpu" row stays LAST
# so it can never shadow an accelerator kind.  bf16 peaks per chip.
# The "cpu" entry is a NOMINAL attribution reference (0.1 TFLOP/s), not
# a hardware claim: it exists so MFU-shaped numbers stay comparable
# across CPU CI runs instead of degenerating to null.
PEAK_TFLOPS = (
    ("v6", 918.0),
    ("v5p", 459.0),
    ("v5", 197.0),
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 45.0),
    ("cpu", 0.1),
)
# (substring, peak HBM GB/s) — the denominator of machine balance for
# the roofline verdict.  Same matching rules; the "cpu" row is the same
# kind of nominal reference as its FLOP/s twin.
PEAK_GBPS = (
    ("v6", 1640.0),
    ("v5p", 2765.0),
    ("v5", 819.0),
    ("v4", 1228.0),
    ("v3", 900.0),
    ("v2", 700.0),
    ("cpu", 50.0),
)


def peak_flops(kind):
    """Peak FLOP/s for a jax ``device_kind`` string (None when the kind
    is not in the table — callers surface that, never guess)."""
    k = str(kind or "").lower()
    for sub, tflops in PEAK_TFLOPS:
        if sub in k:
            return tflops * 1e12
    return None


def peak_bytes_per_sec(kind):
    """Peak memory bytes/s for a jax ``device_kind`` (None on a miss)."""
    k = str(kind or "").lower()
    for sub, gbps in PEAK_GBPS:
        if sub in k:
            return gbps * 1e9
    return None


def machine_balance(kind):
    """FLOPs per byte at which this device flips from memory- to
    compute-bound (peak FLOP/s over peak bytes/s); None off-table."""
    pf, pb = peak_flops(kind), peak_bytes_per_sec(kind)
    return (pf / pb) if pf and pb else None


_device_kind = None


def device_kind():
    """The local device kind, resolved once and cached ("unknown" when
    the backend cannot be asked)."""
    global _device_kind
    if _device_kind is None:
        try:
            import jax

            _device_kind = str(jax.devices()[0].device_kind)
        except Exception:  # noqa: BLE001 — attribution must never raise
            _device_kind = "unknown"
    return _device_kind


# ---------------------------------------------------------------------------
# arming
# ---------------------------------------------------------------------------
def _env_armed() -> bool:
    return os.environ.get("MXTPU_PERF_ATTR", "").strip().lower() \
        not in ("", "0", "false", "off", "no")


_armed = _env_armed()


def enabled() -> bool:
    """Is the attribution plane armed (``MXTPU_PERF_ATTR`` / enable())?"""
    return _armed


def enable():
    global _armed
    _armed = True


def disable():
    global _armed
    _armed = False


# ---------------------------------------------------------------------------
# telemetry families (docs/telemetry.md)
# ---------------------------------------------------------------------------
_TM_PROG_COST = _reg.gauge(
    "program_cost",
    "per-compiled-program analytical cost captured from the executable's "
    "cost_analysis() at first dispatch (component=flops/bytes_accessed/"
    "peak_memory; flops and bytes are per call)",
    labels=("program", "component"))
_TM_PROG_WALL = _reg.counter(
    "program_wall_seconds",
    "cumulative host wall attributed to each compiled program at its "
    "dispatch site (perf plane; MXTPU_PERF_ATTR)",
    labels=("program",))
_TM_MFU = _reg.gauge(
    "program_mfu",
    "model FLOPs utilization per program: analytical FLOPs x dispatches "
    "over measured wall x device peak (perf plane)",
    labels=("program",))
_TM_ROOFLINE = _reg.gauge(
    "program_roofline",
    "operational intensity (flops/byte) over machine balance — >= 1 "
    "means the program should be compute-bound, < 1 memory-bound",
    labels=("program",))
_TM_STEP_TIME = _reg.counter(
    "step_time_seconds",
    "cumulative step wall split into buckets (data_wait/dispatch/"
    "window_stall per step; boundary_sync at epoch boundaries; "
    "sample at serving ticks)",
    labels=("bucket",))

# ---------------------------------------------------------------------------
# ledgers (host-side, capped, lock-guarded — exporter threads read them)
# ---------------------------------------------------------------------------
_CAP = 128
_lock = threading.Lock()
_costs: "OrderedDict[str, dict]" = OrderedDict()
_runtime: "OrderedDict[str, dict]" = OrderedDict()
_buckets: "OrderedDict[str, dict]" = OrderedDict()
_steps = {"count": 0, "wall_s": 0.0}


def attach_cost_analysis(program: str, jitted, *args, **kwargs) -> bool:
    """Capture one compiled program's analytical cost row.

    Call ONCE per program at its first dispatch (the jit's compilation
    cache makes ``compile()`` a lookup; the re-trace behind ``lower()``
    is a one-time cost paid only while the plane is armed — never per
    batch).  Backends whose executable lacks ``cost_analysis`` (or
    raise from it) get an "unknown" row; this function never raises.
    Returns True when a real cost row landed."""
    if not _armed:
        return False
    flops = bytes_acc = None
    source = "unknown"
    try:
        cost = jitted.lower(*args, **kwargs).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        f = float(cost.get("flops", -1.0))
        b = float(cost.get("bytes accessed", -1.0))
        flops = f if f > 0 else None
        bytes_acc = b if b > 0 else None
        if flops is not None or bytes_acc is not None:
            source = "cost_analysis"
    except Exception:  # noqa: BLE001 — attribution must never break dispatch
        pass
    record_cost(program, flops=flops, bytes_accessed=bytes_acc,
                source=source)
    return source == "cost_analysis"


def record_cost(program: str, flops=None, bytes_accessed=None,
                peak_memory=None, source: str = "unknown"):
    """Record (or refresh) one program's cost row.  ``peak_memory``
    defaults to the PR-5 memory row's peak bytes for the same label —
    the two planes share the program key on purpose."""
    if peak_memory is None:
        from . import health as _health

        try:
            for row in _health.program_table():
                if row["program"] == program:
                    peak_memory = row.get("peak_bytes")
                    break
        except Exception:  # noqa: BLE001
            peak_memory = None
    entry = {
        "program": str(program),
        "flops": float(flops) if flops else None,
        "bytes_accessed": float(bytes_accessed) if bytes_accessed else None,
        "peak_memory": int(peak_memory) if peak_memory else None,
        "source": source,
    }
    with _lock:
        _costs[entry["program"]] = entry
        _costs.move_to_end(entry["program"])
        while len(_costs) > _CAP:
            _costs.popitem(last=False)
    if _reg.enabled():
        for comp in ("flops", "bytes_accessed", "peak_memory"):
            if entry[comp] is not None:
                _TM_PROG_COST.set(float(entry[comp]),
                                  program=entry["program"], component=comp)
    return entry


def record_dispatch(program: str, seconds: float):
    """Fold one dispatch's host wall into the program's runtime ledger.
    No-op when the plane is disarmed; pure dict arithmetic when armed."""
    if not _armed or program is None:
        return
    with _lock:
        row = _runtime.get(program)
        if row is None:
            row = _runtime[program] = {"program": str(program),
                                       "wall_s": 0.0, "dispatches": 0}
            while len(_runtime) > _CAP:
                _runtime.popitem(last=False)
        row["wall_s"] += float(seconds)
        row["dispatches"] += 1
    _TM_PROG_WALL.inc(float(seconds), program=str(program))


def record_step_buckets(wall_s: float, **buckets):
    """Fold one step's decomposition into the bucket ledger.  The
    buckets of one call partition that step's wall by construction
    (the stamps nest), so the ledger's step buckets always sum to the
    accumulated step wall."""
    if not _armed:
        return
    with _lock:
        _steps["count"] += 1
        _steps["wall_s"] += float(wall_s)
        for name, sec in buckets.items():
            b = _buckets.get(name)
            if b is None:
                b = _buckets[name] = {"seconds": 0.0, "count": 0,
                                      "in_step": True}
            b["seconds"] += float(sec)
            b["count"] += 1
            b["in_step"] = True
    for name, sec in buckets.items():
        _TM_STEP_TIME.inc(float(sec), bucket=name)


def record_bucket(name: str, seconds: float):
    """Fold a NON-step bucket (epoch-boundary drain, serving admit) —
    reported alongside the step buckets but outside the sums-to-step-
    wall identity."""
    if not _armed:
        return
    with _lock:
        b = _buckets.get(name)
        if b is None:
            b = _buckets[name] = {"seconds": 0.0, "count": 0,
                                  "in_step": False}
        b["seconds"] += float(seconds)
        b["count"] += 1
    _TM_STEP_TIME.inc(float(seconds), bucket=name)


def cost_table():
    with _lock:
        return [dict(r) for r in _costs.values()]


def runtime_table():
    with _lock:
        return [dict(r) for r in _runtime.values()]


def bucket_table():
    with _lock:
        return {n: dict(b) for n, b in _buckets.items()}


def reset(costs: bool = True):
    """Clear the ledgers (tests, and bench warmup isolation).  Pass
    ``costs=False`` to keep the compile-time cost rows — bench resets
    runtime between warmup and the timed loop without re-compiling."""
    global _device_kind
    with _lock:
        _runtime.clear()
        _buckets.clear()
        _steps["count"] = 0
        _steps["wall_s"] = 0.0
        if costs:
            _costs.clear()
    if costs:
        _device_kind = None


# ---------------------------------------------------------------------------
# derivation + surfaces
# ---------------------------------------------------------------------------
def _derive(rt, cost, peak, balance):
    """(mfu, intensity, ratio, verdict) for one program from its
    runtime row + cost row against the device peaks; Nones where a
    term is unknown."""
    mfu = intensity = ratio = None
    verdict = "unknown"
    flops = cost.get("flops") if cost else None
    nbytes = cost.get("bytes_accessed") if cost else None
    wall = rt.get("wall_s") or 0.0
    n = rt.get("dispatches") or 0
    if flops and peak and wall > 0.0 and n > 0:
        mfu = (flops * n) / (wall * peak)
    if flops and nbytes:
        intensity = flops / nbytes
        if balance:
            ratio = intensity / balance
            verdict = "compute_bound" if ratio >= 1.0 else "memory_bound"
    return mfu, intensity, ratio, verdict


def publish_gauges():
    """Fold the ledgers into the ``program_mfu`` / ``program_roofline``
    gauge families.  Called by the exporter right before a scrape
    renders (and by :func:`profile_payload`) — pure host arithmetic
    over the locked ledgers, never a device touch (ENTRY_POINTS)."""
    if not (_armed and _reg.enabled()):
        return
    kind = device_kind()
    peak, balance = peak_flops(kind), machine_balance(kind)
    with _lock:
        rows = [dict(r) for r in _runtime.values()]
        costs = {p: dict(c) for p, c in _costs.items()}
    for rt in rows:
        mfu, _, ratio, _ = _derive(rt, costs.get(rt["program"]),
                                   peak, balance)
        if mfu is not None:
            _TM_MFU.set(mfu, program=rt["program"])
        if ratio is not None:
            _TM_ROOFLINE.set(ratio, program=rt["program"])


def profile_payload(topn=None) -> dict:
    """The ``GET /profile`` document: ranked programs (device wall,
    MFU, roofline verdict, memory), the step-bucket decomposition, and
    the peaks the numbers were derived against.  ``topn`` defaults to
    ``MXTPU_PROFILE_TOPN`` (20); <= 0 means unranked-complete (the
    flight dump uses that so a post-mortem never reads a truncated
    table)."""
    if topn is None:
        try:
            topn = int(os.environ.get("MXTPU_PROFILE_TOPN", "20") or 20)
        except ValueError:
            topn = 20
    publish_gauges()
    kind = device_kind()
    peak, bw = peak_flops(kind), peak_bytes_per_sec(kind)
    balance = machine_balance(kind)
    with _lock:
        rt = {p: dict(r) for p, r in _runtime.items()}
        costs = {p: dict(c) for p, c in _costs.items()}
        buckets = {n: dict(b) for n, b in _buckets.items()}
        steps = dict(_steps)
    programs = []
    for label in set(rt) | set(costs):
        row_rt = rt.get(label, {"wall_s": 0.0, "dispatches": 0})
        cost = costs.get(label)
        mfu, intensity, ratio, verdict = _derive(row_rt, cost, peak,
                                                 balance)
        programs.append({
            "program": label,
            "wall_s": row_rt.get("wall_s", 0.0),
            "dispatches": row_rt.get("dispatches", 0),
            "flops": cost.get("flops") if cost else None,
            "bytes_accessed": cost.get("bytes_accessed") if cost else None,
            "peak_memory": cost.get("peak_memory") if cost else None,
            "cost_source": cost["source"] if cost else "unknown",
            "mfu": mfu,
            "intensity": intensity,
            "roofline_ratio": ratio,
            "roofline": verdict,
        })
    programs.sort(key=lambda p: p["wall_s"], reverse=True)
    total = len(programs)
    if topn and topn > 0:
        programs = programs[:topn]
    return {
        "version": 1,
        "armed": enabled(),
        "device_kind": kind,
        "peak_flops": peak,
        "peak_bytes_per_sec": bw,
        "machine_balance": balance,
        "programs": programs,
        "programs_total": total,
        "buckets": buckets,
        "steps": steps,
    }


def speedometer_suffix() -> str:
    """`` mfu=0.42 top=dispatch`` for the epoch log line: the MFU of
    the program with the most attributed wall plus the dominant step
    bucket.  Pure host reads of the ledgers — adds zero device syncs
    to the Speedometer; empty when disarmed or before any data."""
    if not _armed:
        return ""
    kind = device_kind()
    peak, balance = peak_flops(kind), machine_balance(kind)
    with _lock:
        rows = [dict(r) for r in _runtime.values()]
        costs = {p: dict(c) for p, c in _costs.items()}
        buckets = [(n, b["seconds"]) for n, b in _buckets.items()
                   if b.get("in_step")]
    parts = []
    if rows:
        top = max(rows, key=lambda r: r["wall_s"])
        mfu, _, _, _ = _derive(top, costs.get(top["program"]), peak,
                               balance)
        if mfu is not None:
            parts.append("mfu=%.2f" % mfu)
    if buckets:
        dom = max(buckets, key=lambda kv: kv[1])[0]
        parts.append("top=%s" % dom)
    return (" " + " ".join(parts)) if parts else ""
