"""Engine-lite: ordering and synchronization over PjRt's async dispatch.

The reference's dependency engine (src/engine/threaded_engine.{h,cc};
include/mxnet/engine.h:75-229) exists to (a) run ops asynchronously off the
Python thread, (b) serialize writers / parallelize readers per variable, and
(c) expose WaitForVar/WaitForAll sync points.  On TPU, (a) and (b) are
native properties of the substrate: every jitted call dispatches
asynchronously on the PjRt stream, and XLA's buffer ordering serializes
access per buffer.  What remains host-side is a *thin* layer:

- per-NDArray version counters (parity: ThreadedVar versioning,
  src/engine/threaded_engine.h:44-227) so views/mutation interact sanely,
- wait_to_read/wait_to_write -> jax block_until_ready,
- WaitForAll -> block on all live arrays,
- the profiler hook points that the reference wraps around op execution
  (src/engine/profiler.h:20-137).

There are deliberately no worker threads: XLA owns scheduling.  The
"NaiveEngine" debugging fallback (src/engine/naive_engine.cc) maps to
MXNET_ENGINE_TYPE=NaiveEngine, which makes every imperative invoke block —
the same bisection tool for ruling out async effects.
"""
from __future__ import annotations

import os
import time
import weakref
from collections import deque

import jax
import numpy as np

from . import telemetry as _tm
from .base import get_env

_live_arrays: "weakref.WeakValueDictionary[int, object]" = weakref.WeakValueDictionary()
_counter = 0
_live_bytes = 0.0

# --- telemetry families (docs/telemetry.md) --------------------------------
_TM_LIVE = _tm.gauge(
    "engine_live_arrays",
    "live device arrays currently tracked for wait_for_all")
_TM_LIVE_BYTES = _tm.gauge(
    "engine_live_bytes",
    "total bytes of the live tracked device arrays (running total while "
    "telemetry is enabled; the OOM report's live breakdown recomputes "
    "exactly on demand)")
_TM_NAIVE = _tm.gauge(
    "engine_naive_mode",
    "1 when MXNET_ENGINE_TYPE=NaiveEngine (every dispatch blocks)")
_TM_WAIT_SEC = _tm.histogram(
    "engine_wait_seconds",
    "time the host blocked on device results (wait_to_read / "
    "wait_for_all)", labels=("call",))
_TM_PIPE_DEPTH = _tm.gauge(
    "engine_pipeline_depth",
    "training steps currently in flight in the loop's bounded async "
    "window")
_TM_HOST_STALL = _tm.histogram(
    "trainer_host_stall_seconds",
    "host time blocked on an in-flight step (site=window: the async "
    "window was full; site=boundary: an epoch/checkpoint boundary "
    "drained it)", labels=("site",))


def _engine_is_naive() -> bool:
    naive = get_env("MXNET_ENGINE_TYPE",
                    "ThreadedEnginePerDevice") == "NaiveEngine"
    _TM_NAIVE.set(1.0 if naive else 0.0)
    return naive


def _arr_nbytes(arr) -> int:
    try:
        return int(arr.size) * np.dtype(arr.dtype).itemsize
    except Exception:  # noqa: BLE001 — non-array trackees count as 0
        return 0


def _on_array_freed(nbytes):
    global _live_bytes
    _live_bytes -= nbytes
    if _tm.enabled():
        _TM_LIVE_BYTES.set(max(_live_bytes, 0.0))


def track(arr) -> int:
    """Register a live device array so wait_for_all can reach it."""
    global _counter, _live_bytes
    _counter += 1
    try:
        _live_arrays[_counter] = arr
    except TypeError:
        pass
    if _tm.enabled():
        _TM_LIVE.set(len(_live_arrays))
        nbytes = _arr_nbytes(arr)
        if nbytes:
            # size accounting rides the same weakref lifetime as the
            # tracking dict: the finalizer gives the gauge its decrement
            _live_bytes += nbytes
            try:
                weakref.finalize(arr, _on_array_freed, nbytes)
            except TypeError:
                _live_bytes -= nbytes
                nbytes = 0
        _TM_LIVE_BYTES.set(max(_live_bytes, 0.0))
    return _counter


def live_memory(top: int = 10) -> dict:
    """Exact live-array breakdown computed on demand (count, total
    bytes, the ``top`` largest arrays) — the OOM report's live view,
    independent of the telemetry switch."""
    items = []
    total = 0
    for arr in list(_live_arrays.values()):
        nbytes = _arr_nbytes(arr)
        total += nbytes
        try:
            items.append((nbytes, str(np.dtype(arr.dtype)),
                          str(tuple(arr.shape))))
        except Exception:  # noqa: BLE001
            pass
    items.sort(reverse=True)
    return {"arrays": len(items), "bytes": total,
            "top": [{"bytes": b, "dtype": d, "shape": s}
                    for b, d, s in items[:top]]}


def on_push(result):
    """Called after every imperative op dispatch.

    Under NaiveEngine semantics every push synchronizes immediately —
    parity with src/engine/naive_engine.cc:16-198 where exec happens on
    the pushing thread.
    """
    if _engine_is_naive():
        jax.block_until_ready(result)
    return result


def wait_for_var(arr):
    """Parity: Engine::WaitForVar (include/mxnet/engine.h:180)."""
    if _tm.enabled():
        t0 = time.perf_counter()
        jax.block_until_ready(arr)
        _TM_WAIT_SEC.observe(time.perf_counter() - t0, call="wait_for_var")
        return
    jax.block_until_ready(arr)


def wait_for_all():
    """Parity: Engine::WaitForAll (include/mxnet/engine.h:184) — drains
    both the device stream (live arrays) and the host task engine."""
    t0 = time.perf_counter() if _tm.enabled() else None
    for arr in list(_live_arrays.values()):
        try:
            jax.block_until_ready(arr)
        except Exception:
            pass
    if _host_engine:
        _host_engine.wait_all()
    if t0 is not None:
        _TM_WAIT_SEC.observe(time.perf_counter() - t0, call="wait_for_all")
    # the device is drained: a reporting boundary — fold any parked
    # sentinel state (no-op unless MXTPU_SENTINEL recorded something)
    _tm.health.sentinel_check("boundary")


def async_depth(default: int = 2) -> int:
    """MXTPU_ASYNC_DEPTH — max training steps the host may run ahead of
    the device (the bounded in-flight window of Module.fit /
    BaseModule.score / FusedTrainer.fit).  NaiveEngine forces depth 1:
    every dispatch already blocks, so a deeper window would only hide
    the bisection tool's effect."""
    try:
        depth = int(os.environ.get("MXTPU_ASYNC_DEPTH", default))
    except ValueError:
        depth = default
    if _engine_is_naive():
        return 1
    return max(1, depth)


class AsyncWindow:
    """Bounded in-flight step window for training loops.

    PjRt dispatches every jitted call asynchronously, so a loop that
    never reads values can run arbitrarily far ahead of the device —
    unbounded queued programs and host-staged batches.  ``push()``
    registers a handle (the raw output arrays of a dispatched step);
    once more than ``depth`` steps are in flight the OLDEST step is
    blocked on, keeping the host at most ``depth`` steps ahead while
    batches ``depth`` deep still overlap with device compute.  With
    fused metrics this window is the only place the steady-state loop
    waits — ``trainer_host_stall_seconds{site=window}`` shows it, and
    ``engine_pipeline_depth`` tracks the live depth.

    ``drain()`` blocks on everything in flight (epoch end, checkpoint,
    any boundary that needs the device caught up).
    """

    def __init__(self, depth=None):
        self.depth = async_depth() if depth is None else max(1, int(depth))
        self._dq = deque()

    def __len__(self):
        return len(self._dq)

    def push(self, handle):
        """Register a dispatched step; blocks only when the window is
        full.  ``handle`` is a jax array or a list of them (NDArrays are
        unwrapped without a sync)."""
        if isinstance(handle, (list, tuple)):
            handle = [h._read() if hasattr(h, "_read") else h for h in handle]
        elif hasattr(handle, "_read"):
            handle = handle._read()
        self._dq.append(handle)
        if _tm.enabled():
            _TM_PIPE_DEPTH.set(len(self._dq))
        while len(self._dq) > self.depth:
            self._wait_one("window")

    def _wait_one(self, site):
        handle = self._dq.popleft()
        if _tm.enabled():
            t0 = time.perf_counter()
            jax.block_until_ready(handle)
            _TM_HOST_STALL.observe(time.perf_counter() - t0, site=site)
            _TM_PIPE_DEPTH.set(len(self._dq))
            return
        jax.block_until_ready(handle)

    def drain(self, site: str = "boundary"):
        while self._dq:
            self._wait_one(site)
        # epoch/checkpoint boundaries are the fused paths' reporting
        # points: sync the numerics sentinel HERE (never per batch), so
        # a NaN step surfaces at the same place fused metrics drain
        _tm.health.sentinel_check("boundary")


class _Variable:
    """Host-side var handle (parity: Engine::NewVariable).

    Only bookkeeping: version bumps on write let callers detect staleness;
    actual read/write ordering is enforced by XLA buffer semantics.
    """

    __slots__ = ("version",)

    def __init__(self):
        self.version = 0

    def on_write(self):
        self.version += 1


# ---------------------------------------------------------------------------
# Host task engine — the native C++ scheduler for host-side async work.
#
# Device compute ordering belongs to XLA; what the reference *also* ran
# through its engine was host work: IO prefetch, checkpoint writes, kvstore
# staging (e.g. KVStoreDist pushes ZPush lambdas through PushAsync,
# src/kvstore/kvstore_dist.h:103-121).  That role lives here, backed by
# libmxtpu's threaded var-ordered scheduler (src/engine.cc).
# ---------------------------------------------------------------------------
_host_engine = None


def host_engine():
    """Singleton NativeEngine, or None when libmxtpu is unavailable."""
    global _host_engine
    if _host_engine is None:
        try:
            from ._native import NativeEngine

            _host_engine = NativeEngine(
                num_threads=get_env("MXNET_CPU_WORKER_NTHREADS", 0, int))
        except Exception:
            _host_engine = False
    return _host_engine or None


def push(fn, const_vars=(), mutable_vars=(), priority=0):
    """Parity: Engine::PushAsync (include/mxnet/engine.h:125) for host
    tasks.  Falls back to synchronous execution without libmxtpu."""
    eng = host_engine()
    if eng is None or _engine_is_naive():
        fn()
        return
    eng.push(fn, const_vars=const_vars, mutable_vars=mutable_vars,
             priority=priority)


def new_host_var():
    """Parity: Engine::NewVariable for host-task ordering."""
    eng = host_engine()
    return eng.new_var() if eng is not None else 0


def wait_for_host_var(var):
    eng = host_engine()
    if eng is not None:
        eng.wait_for_var(var)


def wait_for_all_host():
    eng = host_engine()
    if eng is not None:
        eng.wait_all()
