"""Constant folding: evaluate constant subgraphs once at bind time.

Parity target: nnvm's constant folding / Relay FoldConstant
(arXiv:1810.00952) and nGraph's constant propagation
(arXiv:1801.08058).  A subgraph is constant when every leaf is a
no-input creation op (``_zeros``/``_ones``/``_full``/``_arange``/...)
and every interior op is deterministic (no PRNG, no aux state, no
Custom/native escape hatch).  The frontier of each maximal constant
region — the constant node some non-constant consumer (or an output
head) reads — is evaluated ONCE here, eagerly, and baked into the
graph as a ``_literal`` node carrying the raw bytes; everything feeding
it stops being traced, dispatched, or re-evaluated per forward.

Leaves themselves are not worth folding (one creation op either way,
and a materialized literal would bloat the structural signature), so a
node is only folded when it has at least one input — i.e. an actual
computation collapses.  Results larger than ``FOLD_MAX_BYTES`` stay
unfolded: baking megabytes into attrs would make every signature hash
scan them.
"""
from __future__ import annotations

import numpy as np

from .. import ops
from ..symbol import _Node
from . import register_pass
from .common import clone_rewrite

# ops that must never fold even when their inputs are constant: PRNG
# draws differ per step, Custom/native ops may touch external state
_BLOCKLIST = {"Custom", "_Native", "_NDArray"}

FOLD_MAX_BYTES = 1 << 16


@ops.register("_literal", arg_names=())
def _literal(ctx, **attrs):
    """A folded constant: raw bytes + dtype + shape baked into attrs.

    Evaluated under jit the array is a captured constant — XLA embeds
    it into the executable exactly like the reference embeds folded
    nnvm constants into the cached op sequence.  The bytes live in
    attrs (not a side table) so ``structural_signature`` keys on the
    VALUE: two graphs folding to different constants never share a
    compiled program.
    """
    import jax.numpy as jnp

    arr = np.frombuffer(attrs["data"], dtype=np.dtype(attrs["dtype"]))
    return jnp.asarray(arr.reshape(tuple(attrs["shape"])))


def _is_const(node, const):
    if node.is_variable:
        return False
    od = ops.get(node.op)
    if od.needs_rng or od.aux_names or node.op in _BLOCKLIST:
        return False
    return all(const.get(id(src), False) for src, _ in node.inputs)


def _eval_const(node, values):
    """Eagerly evaluate one constant node (memoized); returns the tuple
    of output arrays.  Runs the registered op fns directly — jnp ops
    execute eagerly here, once, at pass time."""
    got = values.get(id(node))
    if got is not None:
        return got
    ins = [_eval_const(src, values)[oidx] for src, oidx in node.inputs]
    od = ops.get(node.op)
    res = od.fn(ops.OpCtx(is_train=False), *ins, **node.attrs)
    if not isinstance(res, tuple):
        res = (res,)
    values[id(node)] = res
    return res


@register_pass("constant_fold", training_safe=True)
def constant_fold(symbol):
    """Fold the frontier of every maximal constant subgraph into
    ``_literal`` nodes.  Training-safe: a constant has no gradient path
    (no variable ancestors), so fwd+bwd binds fold identically."""
    const: dict = {}
    for node in symbol.nodes:
        if not node.is_variable:
            const[id(node)] = _is_const(node, const)

    values: dict = {}

    def rewrite(node, new_inputs):
        if not const.get(id(node)) or not node.inputs:
            return None
        if node.op == "_literal":
            return None  # already folded (idempotent re-runs)
        try:
            outs = _eval_const(node, values)
        except Exception:  # noqa: BLE001 — an op that refuses eager
            return None    # evaluation simply stays in the graph
        host = [np.asarray(o) for o in outs]
        if sum(h.nbytes for h in host) > FOLD_MAX_BYTES:
            return None
        entries = []
        for k, h in enumerate(host):
            lit = _Node("_literal",
                        node.name if len(host) == 1 else f"{node.name}_{k}",
                        attrs={"data": h.tobytes(), "dtype": h.dtype.name,
                               "shape": tuple(int(s) for s in h.shape)},
                        extra_attrs=node.extra_attrs)
            entries.append((lit, 0))
        return entries

    return clone_rewrite(symbol, rewrite)
