"""Shared graph-rewrite machinery for the pass pipeline.

Every pass is Symbol -> Symbol over the lightweight ``_Node`` DAG in
symbol.py.  Nodes are treated as immutable: a rewrite never mutates a
node in place (the original symbol stays bound to the executor as the
user-facing interface), it rebuilds the affected slice of the graph
bottom-up and shares every untouched node with the input symbol.
Reconstruction from the output entries doubles as dead-node pruning —
anything the new heads cannot reach simply is not part of the result
(the same property the reference gets from nnvm's IndexedGraph).
"""
from __future__ import annotations

from ..symbol import Symbol, _Node


def op_node_count(symbol: Symbol) -> int:
    """Number of op (non-variable) nodes — the pass-effect metric."""
    return sum(1 for n in symbol.nodes if not n.is_variable)


def consumer_counts(symbol: Symbol):
    """{(id(node), out_idx): number of consumers}, counting each output
    head of the symbol as one extra consumer (an entry a head exposes is
    observable and must not be rewritten away as 'internal')."""
    counts: dict = {}
    for node in symbol.nodes:
        for src, oidx in node.inputs:
            key = (id(src), oidx)
            counts[key] = counts.get(key, 0) + 1
    for node, oidx in symbol._outputs:
        key = (id(node), oidx)
        counts[key] = counts.get(key, 0) + 1
    return counts


def clone_rewrite(symbol: Symbol, rewrite):
    """Rebuild ``symbol`` bottom-up through ``rewrite``.

    ``rewrite(node, new_inputs)`` is called once per op node in topo
    order with the node's inputs already remapped into the new graph.
    It returns either ``None`` — keep the node (re-created only if its
    inputs actually moved, shared otherwise) — or a list of replacement
    entries, one per node output.  Variables are always shared: they are
    the bind interface and passes must never rename or copy them.
    """
    memo: dict = {}
    for node in symbol.nodes:
        if node.is_variable:
            memo[id(node)] = ((node, 0),)
            continue
        new_inputs = [memo[id(src)][oidx] for src, oidx in node.inputs]
        replaced = rewrite(node, new_inputs)
        if replaced is not None:
            memo[id(node)] = tuple(replaced)
            continue
        if all(e[0] is src and e[1] == oidx
               for e, (src, oidx) in zip(new_inputs, node.inputs)):
            memo[id(node)] = tuple(
                (node, k) for k in range(node.num_outputs()))
        else:
            clone = _Node(node.op, node.name, attrs=node.attrs,
                          inputs=new_inputs, extra_attrs=node.extra_attrs)
            memo[id(node)] = tuple(
                (clone, k) for k in range(clone.num_outputs()))
    return Symbol([memo[id(n)][i] for n, i in symbol._outputs])
