"""Residual-epilogue fusion pass.

Matches the residual tails ``models/resnet.py`` (and friends) produce —
the ``conv3 + shortcut`` sum flowing into the next unit's norm/ReLU —
and collapses them into the fused epilogue ops of
``ops/residual_epilogue.py``, so the Pallas kernel (TPU) or the
single-expression lax form replaces XLA's separate elementwise kernels
without any model-code change (the ``prefuse`` shape applied to the
residual pattern instead of unary chains).

Two patterns, innermost-first:

- ``Activation[relu](elemwise_add(a, b))``
  -> ``_residual_epilogue(a, b)``
- ``Activation[relu](BatchNorm(elemwise_add(a, b)))``
  -> ``_residual_epilogue_bn(a, b, gamma, beta | mean, var)``
  (BN attrs carried over; the op replays the exact train-mode
  composite and fuses only where the stats are static — see the op's
  docstring — which is what keeps this pass ``training_safe``.)

Safety: every interior value (the add's output, the BN's output) must
have exactly ONE consumer and must not be exposed as a graph head —
otherwise the observable value would be rewritten away.  ctx_group
nodes never reach here (placed graphs skip the pipeline).
"""
from __future__ import annotations

from ..symbol import Symbol, _Node
from . import register_pass
from .common import consumer_counts

_ADD_OPS = frozenset({"elemwise_add", "_plus", "_add", "_Plus"})


def _is_relu(node):
    return (node.op == "Activation"
            and str(node.attrs.get("act_type", "relu")) == "relu")


def _sole(entry, counts):
    return counts.get((id(entry[0]), entry[1]), 0) == 1


@register_pass("residual_epilogue", training_safe=True)
def residual_epilogue(symbol: Symbol) -> Symbol:
    counts = consumer_counts(symbol)

    # id(relu node) -> ("plain", add_node) | ("bn", bn_node, add_node)
    matches: dict = {}
    for node in symbol.nodes:
        if node.is_variable or not _is_relu(node) or len(node.inputs) != 1:
            continue
        src, oidx = node.inputs[0]
        if oidx != 0 or src.is_variable:
            continue
        if src.op in _ADD_OPS and _sole(node.inputs[0], counts):
            matches[id(node)] = ("plain", src)
        elif src.op == "BatchNorm" and _sole(node.inputs[0], counts):
            inner, iidx = src.inputs[0]
            if (not inner.is_variable and inner.op in _ADD_OPS
                    and iidx == 0 and _sole(src.inputs[0], counts)):
                matches[id(node)] = ("bn", src, inner)
    if not matches:
        return symbol

    memo: dict = {}
    for node in symbol.nodes:
        if node.is_variable:
            memo[id(node)] = ((node, 0),)
            continue
        m = matches.get(id(node))
        if m is not None and m[0] == "plain":
            add = m[1]
            fused = _Node(
                "_residual_epilogue", node.name, attrs={},
                inputs=[memo[id(s)][i] for s, i in add.inputs],
                extra_attrs=node.extra_attrs)
            memo[id(node)] = ((fused, 0),)
            continue
        if m is not None:
            _, bn, add = m
            # inputs: add's (a, b) then BN's gamma/beta + moving stats
            # (the aux pair must stay LAST: _eval_node maps the op's
            # aux_names onto the trailing inputs)
            ins = [memo[id(s)][i] for s, i in add.inputs]
            ins += [memo[id(s)][i] for s, i in bn.inputs[1:]]
            fused = _Node("_residual_epilogue_bn", node.name,
                          attrs=dict(bn.attrs), inputs=ins,
                          extra_attrs=node.extra_attrs)
            memo[id(node)] = ((fused, 0),)
            continue
        # interior nodes of a match still get memo entries (the fused
        # node reads memo of the ADD'S inputs); reconstruction from the
        # heads prunes them from the result
        new_inputs = [memo[id(src)][oidx] for src, oidx in node.inputs]
        if all(e[0] is src and e[1] == oidx
               for e, (src, oidx) in zip(new_inputs, node.inputs)):
            memo[id(node)] = tuple(
                (node, k) for k in range(node.num_outputs()))
        else:
            clone = _Node(node.op, node.name, attrs=node.attrs,
                          inputs=new_inputs, extra_attrs=node.extra_attrs)
            memo[id(node)] = tuple(
                (clone, k) for k in range(clone.num_outputs()))
    return Symbol([memo[id(n)][i] for n, i in symbol._outputs])
