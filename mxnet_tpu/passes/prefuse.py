"""Elementwise-chain pre-fusion.

XLA already fuses elementwise chains into one kernel at compile time —
what it cannot remove is the *Python* cost of each node: a registry
lookup, an ``_eval_node`` frame, and a jaxpr equation per op at every
trace, plus a dispatch leaf in graphs that fall back to eager.  This
pass collapses maximal single-consumer chains of ``ops/elemwise.py``
primitives (unary math, scalar binaries, clip, smooth_l1, Cast) into
ONE ``_fused_elemwise`` node whose attrs carry the op program, so a
chain of k ops traces as one node.  The reference gets the same effect
statically from mshadow expression templates; Relay calls the shape
FuseOps (arXiv:1810.00952).

Fusion safety: every primitive in the fusible set is a pure
elementwise map (no PRNG, no aux, shape-preserving up to dtype), so
the fused node commutes with layout transposes exactly like its parts —
the executor's NHWC pass treats ``_fused_elemwise`` as a layout-
transparent unary op.  Gradients come from jax.vjp straight through
the replayed chain: identical math to the unfused graph.
"""
from __future__ import annotations

from .. import ops
from ..base import frozen_attrs
from ..ops import elemwise as _ew
from ..symbol import Symbol, _Node
from . import register_pass
from .common import consumer_counts

# fusible primitives: single-input, single-output, elementwise, pure.
# BlockGrad is included — lax.stop_gradient is per-element and jax.vjp
# handles it inside the replayed chain exactly as it does standalone.
FUSIBLE = (frozenset(_ew._UNARY) | frozenset(_ew._SCALAR)
           | {"_copy", "identity", "BlockGrad", "stop_gradient",
              "Cast", "cast", "clip", "smooth_l1"})

MIN_CHAIN = 2


@ops.register("_fused_elemwise", arg_names=("data",))
def _fused_elemwise(ctx, data, **attrs):
    """Replay a pre-fused elementwise chain (attrs['ops'] = tuple of
    (opname, frozen_attrs) in application order)."""
    out = data
    for opname, fattrs in attrs["ops"]:
        od = ops.get(opname)
        out = od.fn(ctx, out, **dict(fattrs))
    return out


def _fusible(node):
    return (not node.is_variable and node.op in FUSIBLE
            and len(node.inputs) == 1 and node.num_outputs() == 1
            and "ctx_group" not in node.extra_attrs)


@register_pass("prefuse", training_safe=True)
def prefuse(symbol):
    """Collapse maximal fusible chains into single ``_fused_elemwise``
    nodes.  A chain link requires the producer to be consumed ONLY by
    the next op in the chain and by no output head — interior values
    must not be observable."""
    counts = consumer_counts(symbol)

    # chain[id(tail)] = (list of chain nodes head..tail, feed entry)
    chains: dict = {}
    chain_member: set = set()
    for node in reversed(symbol.nodes):  # tails appear after their heads
        if id(node) in chain_member or not _fusible(node):
            continue
        run = [node]
        cur = node
        while True:
            src, oidx = cur.inputs[0]
            if (_fusible(src) and oidx == 0
                    and counts.get((id(src), 0), 0) == 1):
                run.append(src)
                cur = src
            else:
                break
        if len(run) >= MIN_CHAIN:
            run.reverse()  # head..tail
            chains[id(node)] = (run, run[0].inputs[0])
            chain_member.update(id(n) for n in run)

    if not chains:
        return symbol

    memo: dict = {}
    for node in symbol.nodes:
        if node.is_variable:
            memo[id(node)] = ((node, 0),)
            continue
        chain = chains.get(id(node))
        if chain is not None:
            run, (feed_node, feed_idx) = chain
            program = tuple((n.op, frozen_attrs(n.attrs)) for n in run)
            fused = _Node("_fused_elemwise", node.name,
                          attrs={"ops": program},
                          inputs=[memo[id(feed_node)][feed_idx]],
                          extra_attrs=node.extra_attrs)
            memo[id(node)] = ((fused, 0),)
            continue
        new_inputs = [memo[id(src)][oidx] for src, oidx in node.inputs]
        if all(e[0] is src and e[1] == oidx
               for e, (src, oidx) in zip(new_inputs, node.inputs)):
            memo[id(node)] = tuple(
                (node, k) for k in range(node.num_outputs()))
        else:
            clone = _Node(node.op, node.name, attrs=node.attrs,
                          inputs=new_inputs, extra_attrs=node.extra_attrs)
            memo[id(node)] = tuple(
                (clone, k) for k in range(clone.num_outputs()))
    return Symbol([memo[id(n)][i] for n, i in symbol._outputs])
