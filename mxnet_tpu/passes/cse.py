"""Common-subexpression elimination over the symbolic graph.

Two op nodes are merged when they agree on op type, attrs, extra
attrs, and (recursively deduplicated) input entries — the same notion
of structural identity ``Symbol.structural_signature`` hashes, and like
the signature it deliberately ignores internal op-node *names*: a graph
written twice (``a*b + a*b``) and a graph written once with a shared
subexpression (``m = a*b; m + m``) rewrite to the identical DAG, so
they also converge on the same program-cache entry.

Exclusions: PRNG ops (two Dropout nodes draw different masks — merging
would correlate them) and the Custom/native escape hatches (opaque,
possibly stateful).  Aux-carrying ops (BatchNorm) merge only when every
input including the aux-state variables is shared, in which case the
duplicate would have produced byte-identical aux updates anyway.
"""
from __future__ import annotations

from .. import ops
from ..base import frozen_attrs
from ..symbol import Symbol, _Node
from . import register_pass

_BLOCKLIST = {"Custom", "_Native", "_NDArray"}


@register_pass("cse", training_safe=True)
def cse(symbol):
    """Merge structurally identical nodes; duplicates become unreachable
    and are pruned by reconstruction.  Training-safe: the merged node
    is the same pure function of the same inputs, so vjp sums the
    cotangents from all former consumers exactly as the duplicated
    graph would have accumulated them."""
    memo: dict = {}
    seen: dict = {}
    for node in symbol.nodes:
        if node.is_variable:
            memo[id(node)] = ((node, 0),)
            continue
        new_inputs = [memo[id(src)][oidx] for src, oidx in node.inputs]
        unchanged = all(e[0] is src and e[1] == oidx
                        for e, (src, oidx) in zip(new_inputs, node.inputs))
        if unchanged:
            cand = node
        else:
            cand = _Node(node.op, node.name, attrs=node.attrs,
                         inputs=new_inputs, extra_attrs=node.extra_attrs)
        entries = tuple((cand, k) for k in range(cand.num_outputs()))
        od = ops.get(node.op)
        if not od.needs_rng and node.op not in _BLOCKLIST:
            try:
                key = (node.op, frozen_attrs(node.attrs),
                       tuple(sorted(node.extra_attrs.items())),
                       tuple((id(e[0]), e[1]) for e in new_inputs))
            except TypeError:  # unhashable attr value: leave the node be
                key = None
            if key is not None:
                prev = seen.get(key)
                if prev is not None:
                    entries = prev
                else:
                    seen[key] = entries
        memo[id(node)] = entries
    return Symbol([memo[id(n)][i] for n, i in symbol._outputs])
