"""Graph-rewrite pass pipeline over ``Symbol`` graphs.

The source paper's one-line identity includes "a graph optimization
layer on top" — nnvm passes over the symbolic graph before execution.
This package is that layer for the TPU-native stack: semantics-
preserving rewrites applied at bind time, BEFORE the executor's
``_build_graph_fn`` traces the graph, in the spirit of Relay
(arXiv:1810.00952) and nGraph (arXiv:1801.08058).

Pipeline (registration order = run order; docs/graph_passes.md):

- ``constant_fold``  evaluate constant subgraphs once, bake literals
- ``cse``            merge structurally identical nodes
- ``dce``            drop identity/no-op nodes, prune dead ones
- ``residual_epilogue`` fuse relu(add)/relu(BN(add)) residual tails
                     into the Pallas epilogue ops (docs/amp.md)
- ``amp_cast``       MXTPU_AMP=bf16 precision policy as Cast insertion
                     (no-op — same symbol object — when AMP is off)
- ``prefuse``        collapse elementwise chains into one fused node
- ``convbn_fold``    inference-only Conv+BN weight folding (needs the
                     parameter values; Predictor/serving path only)

Selection: ``MXTPU_GRAPH_PASSES`` — default/empty/``on`` runs the whole
pipeline, ``0``/``off`` disables everything, a comma list
(``cse,dce``) runs exactly the named passes in pipeline order.

Cache interaction: the executor keys its process-wide program cache on
the POST-pass ``structural_signature``, so differently-written but
equivalent graphs (a duplicated subexpression vs a shared one, a
dead-reshape variant, alpha-renamed op nodes) converge on ONE compiled
entry.

Training safety: a pass declaring ``training_safe=True`` is applied to
every whole-graph bind — forward AND the fused fwd+bwd program trace
the rewritten graph, and jax.vjp differentiates straight through the
rewrites (which is exact: each rewrite forwards the same pure
function).  ``training_safe=False`` passes never run there.  ctx-group
*placed* (multi-device segmented) graphs skip the pipeline entirely:
their execution plan is keyed by node identity.
"""
from __future__ import annotations

import os
import time
from dataclasses import dataclass
from collections import OrderedDict
from typing import Callable

from .. import telemetry as _tm
from ..base import MXNetError

# --- telemetry families (docs/telemetry.md "Graph passes") -----------------
_TM_PASS_SEC = _tm.histogram(
    "graph_pass_seconds",
    "wall time of one graph-rewrite pass application at bind",
    labels=("pass",))
_TM_PASS_REMOVED = _tm.counter(
    "graph_pass_nodes_removed_total",
    "op nodes removed from bound graphs, per rewrite pass",
    labels=("pass",))
_TM_CONVBN = _tm.counter(
    "graph_pass_convbn_folded_total",
    "Conv+BatchNorm pairs folded into conv weights on inference binds")


@dataclass
class PassDef:
    """One registered graph pass.

    ``training_safe`` is a REQUIRED declaration: True means the rewrite
    preserves fwd outputs and bwd gradients and may run on training
    binds; False restricts it to inference-only call sites.  The pass
    lint in tests/test_passes.py enforces that every registered pass
    declares it and has a named parity test.
    """

    name: str
    fn: Callable
    training_safe: bool
    needs_params: bool = False
    doc: str = ""


PASSES: "OrderedDict[str, PassDef]" = OrderedDict()


def register_pass(name, *, training_safe, needs_params=False):
    """Register a pass; registration order defines pipeline order."""

    def deco(fn):
        PASSES[name] = PassDef(name=name, fn=fn,
                               training_safe=bool(training_safe),
                               needs_params=needs_params,
                               doc=fn.__doc__ or "")
        return fn

    return deco


def enabled_passes():
    """Pass names selected by MXTPU_GRAPH_PASSES, in pipeline order."""
    raw = os.environ.get("MXTPU_GRAPH_PASSES", "").strip().lower()
    if raw in ("0", "off", "false", "no", "none", "disable", "disabled"):
        return []
    if raw in ("", "1", "on", "true", "yes", "default", "all"):
        return list(PASSES)
    names = {p.strip() for p in raw.split(",") if p.strip()}
    unknown = sorted(names - set(PASSES))
    if unknown:
        raise MXNetError(
            f"MXTPU_GRAPH_PASSES names unknown passes {unknown}; "
            f"registered: {list(PASSES)}")
    return [n for n in PASSES if n in names]


def convbn_fold_enabled() -> bool:
    return "convbn_fold" in enabled_passes()


def apply_graph_passes(symbol):
    """Run every enabled training-safe graph pass over ``symbol``.

    This is the executor's bind-time hook: pure graph-in/graph-out
    passes only (``needs_params`` passes like convbn_fold have their
    own inference-path entry point).  Returns the input symbol
    unchanged when the pipeline is disabled.
    """
    names = enabled_passes()
    if not names:
        return symbol
    from .common import op_node_count

    for name in names:
        p = PASSES[name]
        if p.needs_params or not p.training_safe:
            continue
        before = op_node_count(symbol)
        t0 = time.perf_counter()
        symbol = p.fn(symbol)
        _TM_PASS_SEC.observe(time.perf_counter() - t0, **{"pass": name})
        removed = before - op_node_count(symbol)
        if removed > 0:
            _TM_PASS_REMOVED.inc(removed, **{"pass": name})
    return symbol


def apply_convbn_fold(symbol, arg_params, aux_params):
    """Telemetry-counted Conv+BN fold (the inference-bind entry point
    used by Predictor / serving).  Honors MXTPU_GRAPH_PASSES selection;
    returns ``(symbol, arg_params, aux_params, n_folded)``."""
    if not convbn_fold_enabled():
        return symbol, dict(arg_params or {}), dict(aux_params or {}), 0
    t0 = time.perf_counter()
    symbol, arg_params, aux_params, n = fold_conv_bn(
        symbol, arg_params, aux_params)
    _TM_PASS_SEC.observe(time.perf_counter() - t0,
                         **{"pass": "convbn_fold"})
    if n > 0:
        _TM_CONVBN.inc(n)
        _TM_PASS_REMOVED.inc(n, **{"pass": "convbn_fold"})
    return symbol, arg_params, aux_params, n


def pipeline_report(symbol):
    """Per-pass node counts for the enabled graph passes (bench.py's
    ``_passes_micro``): [{'pass', 'nodes_before', 'nodes_after'}, ...]."""
    from .common import op_node_count

    rows = []
    for name in enabled_passes():
        p = PASSES[name]
        if p.needs_params or not p.training_safe:
            continue
        before = op_node_count(symbol)
        symbol = p.fn(symbol)
        rows.append({"pass": name, "nodes_before": before,
                     "nodes_after": op_node_count(symbol)})
    return rows


# pass modules register themselves in PIPELINE ORDER
from . import constant_fold  # noqa: E402,F401
from . import cse  # noqa: E402,F401
from . import dce  # noqa: E402,F401
# residual_epilogue after dce (identity nodes between add/BN/relu are
# gone by then); amp_cast after it (the fused epilogue ops are
# pass-through for the precision policy) and before prefuse (inserted
# Casts join elementwise chains)
from . import residual_epilogue  # noqa: E402,F401
from . import amp_cast  # noqa: E402,F401
from . import prefuse  # noqa: E402,F401
from . import convbn  # noqa: E402,F401
from .convbn import fold_conv_bn  # noqa: E402,F401
from .common import op_node_count  # noqa: E402,F401
