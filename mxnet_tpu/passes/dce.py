"""Identity / no-op elimination + dead-node pruning.

The structural rewrites nnvm gets from its identity-elimination passes:

- ``_copy`` / ``identity`` nodes forward their input (in a pure traced
  graph the copy is meaningless — NDArray copy semantics live at the
  eager layer, not inside the compiled program),
- a ``transpose`` whose permutation is the identity is dropped,
- a ``transpose``-of-``transpose`` whose composed permutation is the
  identity cancels to the original entry (both-axes-None — double full
  reverse — cancels for any rank).  This composes with the executor's
  NHWC layout pass: ``transpose`` is layout-opaque there, so a
  cancelling pair that survives to trace time would force a spurious
  NHWC->NCHW->NHWC round trip mid-chain,
- ``Reshape(Reshape(x, s1), s2)`` collapses to ``Reshape(x, s2)`` when
  the outer target has no ``0`` dim codes (a ``0`` copies a dim from
  the *inner* reshape's output, so collapsing would change its
  meaning; ``-1`` is total-size-derived and the total is preserved).

Everything the rewritten heads can no longer reach — including nodes
orphaned by CSE or constant folding earlier in the pipeline — is
pruned by reconstruction.
"""
from __future__ import annotations

from ..base import parse_attr
from ..symbol import _Node
from . import register_pass
from .common import clone_rewrite


def _transpose_axes(node):
    """Normalized axes tuple of a transpose node, or None for the
    default full reverse."""
    axes = parse_attr(node.attrs.get("axes", None))
    if axes in (None, ()):
        return None
    return tuple(int(a) for a in axes)


def _reshape_target(node):
    shape = parse_attr(node.attrs.get("shape",
                                      node.attrs.get("target_shape", None)))
    if shape is None:
        return None
    return tuple(int(s) for s in shape)


@register_pass("dce", training_safe=True)
def dce(symbol):
    """Drop no-op nodes and prune everything no output depends on.
    Training-safe: every elimination forwards the exact producing
    entry, so cotangents flow through untouched."""

    def rewrite(node, new_inputs):
        # canonical registered names; the alias spellings also appear in
        # graphs loaded from external nnvm JSON (interop path)
        op = node.op
        if op in ("_copy", "identity"):
            return [new_inputs[0]]
        if op == "transpose":
            axes = _transpose_axes(node)
            if axes is not None and axes == tuple(range(len(axes))):
                return [new_inputs[0]]
            src, oidx = new_inputs[0]
            if not src.is_variable and src.op == "transpose" and oidx == 0:
                inner = _transpose_axes(src)
                if axes is None and inner is None:
                    return [src.inputs[0]]  # reverse twice = identity
                if (axes is not None and inner is not None
                        and len(axes) == len(inner)
                        and all(inner[a] == i for i, a in enumerate(axes))):
                    return [src.inputs[0]]
        if op in ("Reshape", "reshape"):
            src, oidx = new_inputs[0]
            if (not src.is_variable and src.op in ("Reshape", "reshape")
                    and oidx == 0):
                target = _reshape_target(node)
                if target is not None and 0 not in target:
                    collapsed = _Node("Reshape", node.name, attrs=node.attrs,
                                      inputs=[src.inputs[0]],
                                      extra_attrs=node.extra_attrs)
                    return [(collapsed, 0)]
        return None

    return clone_rewrite(symbol, rewrite)
