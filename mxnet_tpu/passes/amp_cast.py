"""AMP bf16 cast-insertion pass (``MXTPU_AMP=bf16``).

nGraph's argument (arXiv:1801.08058) applied to precision instead of
layout: a framework-level policy applied as an IR transform beats
per-model hand-casting.  With the policy armed, every bind rewrites
the same way — Module, Predictor, serving, tests — and the program
cache keys on the post-pass signature, so an AMP bind and an fp32 bind
of the same net are two distinct cached programs.

Policy (docs/amp.md):

- **allow list** (compute bf16): Convolution / Deconvolution /
  FullyConnected / dot / batch_dot / FlashAttention — the MXU ops
  where bf16 is the fast path.  EVERY float input (data, weights,
  bias) is cast so the op never promotes back to f32 via a mixed
  operand.
- **deny list** (cast back to f32): the softmax family, the loss
  output ops, and whole-tensor reductions — the places where bf16's
  ~8-bit mantissa visibly hurts.  Only op-produced inputs are cast:
  variables feeding a loss are labels/targets whose dtype (often
  integer-valued) must pass through untouched.
- everything else is **pass-through**: elementwise chains, pooling,
  reshapes run in whatever dtype arrives.  The norm ops need no deny
  entry — ops/nn.py BatchNorm/LayerNorm accumulate their statistics
  in f32 internally regardless of the compute dtype (that is the
  "norm statistics stay fp32" half of the policy).

With ``MXTPU_AMP`` unset the pass returns the INPUT SYMBOL OBJECT —
not a copy — so signatures, program-cache keys, and numerics are
bit-identical to a build without this pass.

Gradients: jax.vjp through an inserted ``Cast`` transposes to a cast
back, so parameter gradients leave the fused fwd+bwd in the parameter
dtype (f32 weights get f32 grads) — the fp32-master story for
f32-stored params is simply "the params are the masters"; bf16-stored
params take the bucket-master path in kvstore_fused.py.
"""
from __future__ import annotations

from .. import amp as _amp
from ..symbol import Symbol, _Node
from . import register_pass

# MXU ops whose float inputs are cast to the AMP compute dtype
AMP_ALLOW = frozenset({
    "Convolution", "Deconvolution", "FullyConnected",
    "dot", "batch_dot", "FlashAttention",
})

# ops whose op-produced inputs are cast back to f32: softmax family,
# loss outputs, whole-tensor reductions (sum/mean/... and their
# aliases).  Norm layers are deliberately absent — their statistics are
# f32 by construction (ops/nn.py).
AMP_DENY = frozenset({
    "softmax", "log_softmax", "SoftmaxActivation",
    "SoftmaxOutput", "Softmax", "softmax_cross_entropy",
    "LinearRegressionOutput", "LogisticRegressionOutput",
    "MAERegressionOutput", "SVMOutput", "MakeLoss",
    "sum", "sum_axis", "mean", "prod", "nansum", "nanprod",
    "max", "max_axis", "min", "min_axis", "norm",
})


@register_pass("amp_cast", training_safe=True)
def amp_cast(symbol: Symbol) -> Symbol:
    """Insert the policy's Cast nodes (no-op unless MXTPU_AMP=bf16)."""
    dtype = _amp.amp_dtype()
    if dtype is None:
        return symbol
    compute = "bfloat16"

    memo: dict = {}
    casts: dict = {}  # (id(node), oidx, dtype) -> cast entry
    inserted = 0

    def cast_entry(entry, dt):
        nonlocal inserted
        src, oidx = entry
        if not src.is_variable:
            if src.op == "Cast" and str(src.attrs.get("dtype")) == dt:
                return entry
            if dt == compute and src.op in AMP_ALLOW:
                return entry  # an allow op already produces bf16
        key = (id(src), oidx, dt)
        got = casts.get(key)
        if got is None:
            node = _Node("Cast", f"{src.name}_amp_{dt}",
                         attrs={"dtype": dt}, inputs=[entry])
            got = (node, 0)
            casts[key] = got
            inserted += 1
        return got

    for node in symbol.nodes:
        if node.is_variable:
            memo[id(node)] = ((node, 0),)
            continue
        new_inputs = [memo[id(src)][oidx] for src, oidx in node.inputs]
        if node.op in AMP_ALLOW:
            new_inputs = [cast_entry(e, compute) for e in new_inputs]
        elif node.op in AMP_DENY:
            # only op-produced inputs: variables here are labels /
            # targets whose dtype must pass through untouched
            new_inputs = [e if e[0].is_variable else cast_entry(e, "float32")
                          for e in new_inputs]
        if all(e[0] is src and e[1] == oidx
               for e, (src, oidx) in zip(new_inputs, node.inputs)):
            memo[id(node)] = tuple(
                (node, k) for k in range(node.num_outputs()))
        else:
            clone = _Node(node.op, node.name, attrs=node.attrs,
                          inputs=new_inputs, extra_attrs=node.extra_attrs)
            memo[id(node)] = tuple(
                (clone, k) for k in range(clone.num_outputs()))
    if not inserted:
        return symbol
    _amp.count_cast_nodes(inserted)
    return Symbol([memo[id(n)][i] for n, i in symbol._outputs])
