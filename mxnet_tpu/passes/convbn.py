"""Inference-mode Conv+BatchNorm folding.

At inference a BatchNorm over frozen moving statistics is an affine map
per channel:  ``y = (x - mean) * gamma/sqrt(var+eps) + beta``.  When
``x`` is the output of a Convolution, that affine folds INTO the conv:

    scale_c = gamma_c / sqrt(var_c + eps)          (ones when fix_gamma)
    W'_c    = W_c * scale_c
    b'_c    = beta_c + (b_c - mean_c) * scale_c    (b_c = 0 when no_bias)

so the rewritten graph runs one conv where the original ran a conv plus
a full normalization — on the ``Predictor``/serving path this removes a
per-channel multiply-add over every conv activation (nGraph's
CoreFusion and TVM's FoldScaleAxis do exactly this, arXiv:1801.08058 /
1802.04799).  The fold must happen BEFORE post-training int8
quantization: per-channel scales computed from unfolded weights would
bake the wrong dynamic range once the BN scale lands in the weights
(serving/quantize.py calls this first for that reason).

Inference-only: train-mode BN normalizes with batch statistics and
updates the moving stats — folding would change the math, so this pass
never runs inside ``apply_graph_passes`` (it is registered
``training_safe=False`` and needs the parameter VALUES anyway, which
graph-level bind passes do not see).

Safety conditions per (conv, bn) pair — all structural, all checked:
the conv feeds ONLY the BN (any other consumer sees pre-BN
activations), the conv's weight/bias and the BN's gamma/beta/moving
stats are variables consumed only here (weight sharing would corrupt
the other consumer), and every needed value is present in the params.
"""
from __future__ import annotations

import numpy as np

from ..base import parse_attr, parse_bool
from ..symbol import Symbol, _Node
from . import register_pass
from .common import consumer_counts


def _value(params, name):
    v = params.get(name)
    if v is None:
        return None
    return np.asarray(v.asnumpy() if hasattr(v, "asnumpy") else v)


def _wrap_like(template_params, arr):
    """Return ``arr`` in the container flavor the params dict uses
    (NDArray when any existing value is one, raw numpy otherwise)."""
    if any(hasattr(v, "asnumpy") for v in template_params.values()):
        from .. import ndarray as nd

        return nd.array(arr)
    return arr


def _sole_var(entry, counts):
    node, oidx = entry
    return (node.is_variable
            and counts.get((id(node), oidx), 0) == 1)


def fold_conv_bn(symbol, arg_params, aux_params):
    """Fold every eligible Conv->BN pair.

    Returns ``(symbol, arg_params, aux_params, n_folded)`` — new dicts,
    inputs untouched.  Weights are recomputed in float64 and cast back
    to the original weight dtype, keeping the fold's own rounding noise
    below the bf16/int8 tolerances downstream.
    """
    arg_params = dict(arg_params or {})
    aux_params = dict(aux_params or {})
    counts = consumer_counts(symbol)

    folds: dict = {}  # id(bn node) -> fold plan
    for node in symbol.nodes:
        if node.is_variable or node.op != "BatchNorm":
            continue
        if len(node.inputs) != 5:
            continue
        (conv, conv_idx) = node.inputs[0]
        if conv.is_variable or conv.op != "Convolution" or conv_idx != 0:
            continue
        if counts.get((id(conv), 0), 0) != 1:
            continue  # someone else reads the pre-BN activation
        gamma_e, beta_e, mean_e, var_e = node.inputs[1:5]
        if not all(_sole_var(e, counts)
                   for e in (gamma_e, beta_e, mean_e, var_e)):
            continue
        if len(conv.inputs) < 2:
            continue
        weight_e = conv.inputs[1]
        bias_e = conv.inputs[2] if len(conv.inputs) > 2 else None
        if not _sole_var(weight_e, counts):
            continue
        if bias_e is not None and not _sole_var(bias_e, counts):
            continue

        w = _value(arg_params, weight_e[0].name)
        beta = _value(arg_params, beta_e[0].name)
        mean = _value(aux_params, mean_e[0].name)
        var = _value(aux_params, var_e[0].name)
        if any(v is None for v in (w, beta, mean, var)):
            continue
        fix_gamma = parse_bool(node.attrs.get("fix_gamma", True))
        gamma = None if fix_gamma else _value(arg_params, gamma_e[0].name)
        if not fix_gamma and gamma is None:
            continue
        bias = (_value(arg_params, bias_e[0].name)
                if bias_e is not None else None)
        eps = float(parse_attr(node.attrs.get("eps", 1e-3)))

        scale = 1.0 / np.sqrt(var.astype(np.float64) + eps)
        if gamma is not None:
            scale = scale * gamma.astype(np.float64)
        w_dtype = w.dtype
        w64 = w.astype(np.float64) * scale.reshape((-1,) + (1,) * (w.ndim - 1))
        b64 = beta.astype(np.float64) - mean.astype(np.float64) * scale
        if bias is not None:
            b64 = b64 + bias.astype(np.float64) * scale
        folds[id(node)] = {
            "conv": conv,
            "weight_name": weight_e[0].name,
            "bias_entry": bias_e,
            "bias_name": (bias_e[0].name if bias_e is not None
                          else f"{conv.name}_bias"),
            "drop_args": [gamma_e[0].name, beta_e[0].name],
            "drop_aux": [mean_e[0].name, var_e[0].name],
            "w": w64.astype(w_dtype),
            "b": b64.astype(w_dtype),
        }

    if not folds:
        return symbol, arg_params, aux_params, 0

    memo: dict = {}
    for node in symbol.nodes:
        if node.is_variable:
            memo[id(node)] = ((node, 0),)
            continue
        plan = folds.get(id(node))
        if plan is not None:
            conv = plan["conv"]
            data_entry = memo[id(conv.inputs[0][0])][conv.inputs[0][1]]
            weight_entry = memo[id(conv.inputs[1][0])][0]
            if plan["bias_entry"] is not None:
                bias_entry = memo[id(plan["bias_entry"][0])][0]
            else:
                bias_entry = (_Node(None, plan["bias_name"]), 0)
            attrs = dict(conv.attrs)
            attrs["no_bias"] = False
            folded = _Node("Convolution", conv.name, attrs=attrs,
                           inputs=[data_entry, weight_entry, bias_entry],
                           extra_attrs=conv.extra_attrs)
            memo[id(node)] = ((folded, 0),)
            continue
        new_inputs = [memo[id(src)][oidx] for src, oidx in node.inputs]
        if all(e[0] is src and e[1] == oidx
               for e, (src, oidx) in zip(new_inputs, node.inputs)):
            memo[id(node)] = tuple(
                (node, k) for k in range(node.num_outputs()))
        else:
            clone = _Node(node.op, node.name, attrs=node.attrs,
                          inputs=new_inputs, extra_attrs=node.extra_attrs)
            memo[id(node)] = tuple(
                (clone, k) for k in range(clone.num_outputs()))
    rewritten = Symbol([memo[id(n)][i] for n, i in symbol._outputs])

    for plan in folds.values():
        arg_params[plan["weight_name"]] = _wrap_like(arg_params, plan["w"])
        arg_params[plan["bias_name"]] = _wrap_like(arg_params, plan["b"])
        for name in plan["drop_args"]:
            arg_params.pop(name, None)
        for name in plan["drop_aux"]:
            aux_params.pop(name, None)
    return rewritten, arg_params, aux_params, len(folds)


@register_pass("convbn_fold", training_safe=False, needs_params=True)
def convbn_fold(symbol, arg_params, aux_params):
    """Pass-registry entry point (telemetry-counted wrapper lives in
    ``passes.apply_convbn_fold``); see :func:`fold_conv_bn`."""
    return fold_conv_bn(symbol, arg_params, aux_params)
