"""Image IO + augmentation pipeline.

Parity: python/mxnet/image.py (ImageIter, CreateAugmenter) and the C++
ImageRecordIter stack (src/io/iter_image_recordio.cc:150-487 +
image_aug_default.cc).  The staged design is preserved (SURVEY.md §3.5):

  RecordIO shard read (part_index/num_parts)     [reader]
    -> parallel JPEG decode + augment             [thread pool,
       (crop/mirror/resize/HSL)                    preprocess_threads]
    -> batch assembly (NCHW float32)              [batcher]
    -> prefetch                                   [PrefetchingIter]

Codec: Pillow (the image lives as HWC RGB uint8 between stages, like the
reference's cv::Mat).  No OpenCV in this stack.
"""
from __future__ import annotations

import io as _io
import logging
import os
import queue
import random as pyrandom
import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from . import ndarray as nd
from .base import MXNetError, get_env
from .io import DataBatch, DataDesc, DataIter
from .recordio import MXIndexedRecordIO, MXRecordIO, unpack

_RAW_MAGIC = b"MXTPURAW"


def imencode(img, quality=95, img_fmt=".jpg"):
    """Encode HWC uint8 RGB -> bytes (parity: cv::imencode use in
    tools/im2rec.cc)."""
    img = np.asarray(img)
    try:
        from PIL import Image

        buf = _io.BytesIO()
        fmt = "JPEG" if "jpg" in img_fmt or "jpeg" in img_fmt else "PNG"
        Image.fromarray(img.astype(np.uint8)).save(buf, format=fmt, quality=quality)
        return buf.getvalue()
    except ImportError:
        # raw fallback: magic + shape + bytes
        h, w, c = img.shape
        return _RAW_MAGIC + np.array([h, w, c], np.int32).tobytes() + \
            img.astype(np.uint8).tobytes()


def imdecode_np(buf: bytes) -> np.ndarray:
    """Decode bytes -> HWC uint8 RGB numpy (parity: cv::imdecode).

    JPEG streams go through the native libjpeg decoder (src/
    jpeg_decode.cc) — it runs without the GIL, so ImageRecordIter's
    decode threads scale like the reference's OpenMP workers.  Everything
    else (PNG, raw) falls back to PIL."""
    if buf[:8] == _RAW_MAGIC:
        h, w, c = np.frombuffer(buf[8:20], np.int32)
        return np.frombuffer(buf[20:], np.uint8).reshape(h, w, c).copy()
    if buf[:2] == b"\xff\xd8":  # JPEG SOI
        from . import _native

        out = _native.decode_jpeg(buf)
        if out is not None:
            return out
    from PIL import Image

    img = Image.open(_io.BytesIO(buf))
    return np.asarray(img.convert("RGB"))


def imdecode(buf, channels=3, **kwargs):
    """Parity: mx.image.imdecode (src/io/image_io.cc _imdecode op) —
    returns an NDArray (H, W, C)."""
    return nd.array(imdecode_np(bytes(buf)).astype(np.float32))


# ---------------------------------------------------------------------------
# augmenters (parity: image.py CreateAugmenter :233 + image_aug_default.cc)
# ---------------------------------------------------------------------------
def _resize_shorter(img, size):
    from PIL import Image

    h, w = img.shape[:2]
    if h < w:
        new_h, new_w = size, int(w * size / h)
    else:
        new_h, new_w = int(h * size / w), size
    return np.asarray(Image.fromarray(img).resize((new_w, new_h), Image.BILINEAR))


def _fixed_crop(img, x0, y0, w, h):
    return img[y0 : y0 + h, x0 : x0 + w]


def _center_crop(img, size):
    h, w = img.shape[:2]
    x0 = (w - size[0]) // 2
    y0 = (h - size[1]) // 2
    return _fixed_crop(img, x0, y0, size[0], size[1])


def _rand_crop(img, size):
    h, w = img.shape[:2]
    x0 = pyrandom.randint(0, max(w - size[0], 0))
    y0 = pyrandom.randint(0, max(h - size[1], 0))
    return _fixed_crop(img, x0, y0, size[0], size[1])


class Augmenter:
    def __call__(self, img):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size):
        self.size = size

    def __call__(self, img):
        return _resize_shorter(img, self.size)


class ForceResizeAug(Augmenter):
    def __init__(self, size):
        self.size = size  # (w, h)

    def __call__(self, img):
        from PIL import Image

        return np.asarray(Image.fromarray(img).resize(self.size, Image.BILINEAR))


class RandomCropAug(Augmenter):
    def __init__(self, size):
        self.size = size

    def __call__(self, img):
        return _rand_crop(img, self.size)


class CenterCropAug(Augmenter):
    def __init__(self, size):
        self.size = size

    def __call__(self, img):
        return _center_crop(img, self.size)


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        self.p = p

    def __call__(self, img):
        if pyrandom.random() < self.p:
            return img[:, ::-1]
        return img


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        self.brightness = brightness

    def __call__(self, img):
        alpha = 1.0 + pyrandom.uniform(-self.brightness, self.brightness)
        return np.clip(img.astype(np.float32) * alpha, 0, 255).astype(np.uint8)


class ContrastJitterAug(Augmenter):
    def __init__(self, contrast):
        self.contrast = contrast

    def __call__(self, img):
        alpha = 1.0 + pyrandom.uniform(-self.contrast, self.contrast)
        gray = img.astype(np.float32).mean()
        return np.clip((img.astype(np.float32) - gray) * alpha + gray, 0, 255).astype(np.uint8)


class SaturationJitterAug(Augmenter):
    def __init__(self, saturation):
        self.saturation = saturation

    def __call__(self, img):
        alpha = 1.0 + pyrandom.uniform(-self.saturation, self.saturation)
        gray = img.astype(np.float32).mean(axis=2, keepdims=True)
        return np.clip(img.astype(np.float32) * alpha + gray * (1 - alpha), 0, 255).astype(np.uint8)


class LightingAug(Augmenter):
    """PCA lighting noise (parity: image_aug_default.cc random_illumination)."""

    def __init__(self, alphastd, eigval, eigvec):
        self.alphastd = alphastd
        self.eigval = np.asarray(eigval, np.float32)
        self.eigvec = np.asarray(eigvec, np.float32)

    def __call__(self, img):
        alpha = np.random.normal(0, self.alphastd, size=(3,))
        rgb = self.eigvec @ (alpha * self.eigval)
        return np.clip(img.astype(np.float32) + rgb, 0, 255).astype(np.uint8)


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, pca_noise=0, inter_method=2):
    """Parity: image.py CreateAugmenter (:233)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize))
    crop_size = (data_shape[2], data_shape[1])
    if rand_crop:
        auglist.append(RandomCropAug(crop_size))
    else:
        auglist.append(CenterCropAug(crop_size))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    if brightness > 0:
        auglist.append(BrightnessJitterAug(brightness))
    if contrast > 0:
        auglist.append(ContrastJitterAug(contrast))
    if saturation > 0:
        auglist.append(SaturationJitterAug(saturation))
    if pca_noise > 0:
        eigval = [55.46, 4.794, 1.148]
        eigvec = [[-0.5675, 0.7192, 0.4009],
                  [-0.5808, -0.0045, -0.8140],
                  [-0.5836, -0.6948, 0.4203]]
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    return auglist


# ---------------------------------------------------------------------------
# iterators
# ---------------------------------------------------------------------------
class ImageIter(DataIter):
    """Python image iterator over .rec or .lst+images (parity: image.py
    ImageIter :277)."""

    def __init__(self, batch_size, data_shape, label_width=1, path_imgrec=None,
                 path_imglist=None, path_root=None, path_imgidx=None,
                 shuffle=False, part_index=0, num_parts=1, aug_list=None,
                 imglist=None, data_name="data", label_name="softmax_label",
                 mean=None, std=None, **kwargs):
        super().__init__()
        assert path_imgrec or path_imglist or isinstance(imglist, list)
        if path_imgrec:
            if path_imgidx:
                self.imgrec = MXIndexedRecordIO(path_imgidx, path_imgrec, "r")
                self.imgidx = list(self.imgrec.keys)
            else:
                self.imgrec = MXRecordIO(path_imgrec, "r")
                self.imgidx = None
        else:
            self.imgrec = None

        self.imglist = None
        if path_imglist:
            self.imglist = {}
            with open(path_imglist) as fin:
                for line in fin:
                    parts = line.strip().split("\t")
                    label = np.array(parts[1:-1], dtype=np.float32)
                    self.imglist[int(parts[0])] = (label, parts[-1])
        elif isinstance(imglist, list):
            self.imglist = {}
            for i, item in enumerate(imglist):
                self.imglist[i] = (np.array(item[0], dtype=np.float32)
                                   if not np.isscalar(item[0])
                                   else np.array([item[0]], dtype=np.float32), item[1])

        self.path_root = path_root
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.shuffle = shuffle
        self.mean = np.asarray(mean, np.float32) if mean is not None else None
        self.std = np.asarray(std, np.float32) if std is not None else None
        if aug_list is None:
            aug_list = CreateAugmenter(data_shape)
        self.auglist = aug_list
        self.cur = 0
        if self.imglist is not None:
            self.seq = list(self.imglist.keys())
        elif self.imgidx is not None:
            self.seq = self.imgidx
        else:
            self.seq = None
        if num_parts > 1 and self.seq is not None:
            self.seq = self.seq[part_index::num_parts]
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        return [DataDesc("softmax_label", shape)]

    def reset(self):
        if self.shuffle and self.seq is not None:
            pyrandom.shuffle(self.seq)
        if self.imgrec is not None:
            self.imgrec.reset()
        self.cur = 0

    def next_sample(self):
        if self.seq is not None:
            if self.cur >= len(self.seq):
                raise StopIteration
            idx = self.seq[self.cur]
            self.cur += 1
            if self.imgrec is not None:
                s = self.imgrec.read_idx(idx)
                header, img = unpack(s)
                return header.label, img
            label, fname = self.imglist[idx]
            with open(os.path.join(self.path_root or "", fname), "rb") as f:
                return label, f.read()
        s = self.imgrec.read()
        if s is None:
            raise StopIteration
        header, img = unpack(s)
        return header.label, img

    def _process(self, raw):
        img = imdecode_np(raw)
        for aug in self.auglist:
            img = aug(img)
        img = img.astype(np.float32)
        if self.mean is not None:
            img = img - self.mean
        if self.std is not None:
            img = img / self.std
        return img.transpose(2, 0, 1)  # HWC -> CHW

    def next(self):
        from . import telemetry as _tm
        from .io import _TM_BATCHES

        if self.seq is not None and self.cur >= len(self.seq):
            # exhaustion check BEFORE the span (mirroring ImageRecordIter):
            # the epoch-end StopIteration must not record a spurious
            # data-io event on its way out
            raise StopIteration
        with _tm.span("ImageIter.next", category="data-io",
                      histogram_name="data_batch_wait_seconds",
                      iterator="ImageIter"):
            batch = self._next_impl()
        _TM_BATCHES.inc(iterator="ImageIter")
        return batch

    def _next_impl(self):
        from . import storage

        # pooled staging (parity: pooled_storage_manager.h recycling):
        # np.empty from the arena + explicit fill beats np.zeros'ing the
        # whole batch buffer every iteration; stage_to_device copies into
        # the jax array and recycles the buffer immediately
        batch_data = storage.staging_empty(
            (self.batch_size,) + self.data_shape, np.float32)
        batch_label = storage.staging_empty(
            (self.batch_size, self.label_width), np.float32)
        i = 0
        pad = 0
        staged = False
        try:
            try:
                while i < self.batch_size:
                    label, raw = self.next_sample()
                    batch_data[i] = self._process(raw)
                    lab = np.atleast_1d(np.asarray(label, np.float32))
                    batch_label[i, : self.label_width] = \
                        lab[: self.label_width]
                    i += 1
            except StopIteration:
                if i == 0:
                    raise
                pad = self.batch_size - i
                batch_data[i:] = 0.0
                batch_label[i:] = 0.0
            label_np = (batch_label[:, 0] if self.label_width == 1
                        else batch_label)
            label_arr = nd.array(label_np.copy())  # explicit copy off pool
            data_arr = nd.NDArray(storage.stage_to_device(batch_data))
            staged = True
            return DataBatch([data_arr], [label_arr], pad=pad)
        finally:
            # pool blocks only return via staging_free — a decode error
            # escaping here (bad JPEG) must not leak the batch buffer
            if not staged:
                storage.staging_free(batch_data)
            storage.staging_free(batch_label)


class ImageRecordIter(DataIter):
    """Threaded RecordIO image pipeline (parity: ImageRecordIter,
    src/io/iter_image_recordio.cc:459 registration).

    Stages mirror the reference: sharded record read -> thread-pool decode +
    augment (preprocess_threads, cf. OpenMP block :259-368) -> batch.
    Wrap with io.PrefetchingIter for the PrefetcherIter stage.
    """

    def __init__(self, path_imgrec, data_shape, batch_size, label_width=1,
                 shuffle=False, part_index=0, num_parts=1,
                 preprocess_threads=None, rand_crop=False, rand_mirror=False,
                 mean_r=0.0, mean_g=0.0, mean_b=0.0, scale=1.0, resize=0,
                 path_imgidx=None, round_batch=True, seed=0, **kwargs):
        super().__init__()
        self.batch_size = batch_size
        self.data_shape = tuple(int(x) for x in data_shape)
        self.label_width = label_width
        self.scale = scale
        mean = None
        if mean_r or mean_g or mean_b:
            mean = np.array([mean_r, mean_g, mean_b], np.float32)
        self.mean = mean
        nthread = preprocess_threads or get_env("MXNET_CPU_WORKER_NTHREADS", 4)
        self.pool = ThreadPoolExecutor(max_workers=nthread)
        self.aug = CreateAugmenter((self.data_shape if len(self.data_shape) == 3
                                    else (3,) + self.data_shape),
                                   resize=resize, rand_crop=rand_crop,
                                   rand_mirror=rand_mirror)
        # load records for sharding + shuffling.  Native path: libmxtpu
        # byte-range sharded scan (parity: dmlc::InputSplit used by
        # iter_image_recordio.cc); fallback: python reader + stride shard.
        self.records = []
        native_ok = False
        try:
            from . import _native

            if _native.available():
                rd = _native.NativeRecordReader(path_imgrec, part_index,
                                                num_parts)
                while True:
                    batch = rd.read_batch()  # one FFI crossing per batch
                    if not batch:
                        break
                    self.records.extend(batch)
                rd.close()
                native_ok = True
        except Exception:
            self.records = []
        if not native_ok:
            # byte-range sharding with record alignment (dmlc InputSplit
            # parity) — works over any registered filesystem (mem://,
            # s3:// adapters), unlike the local-only native scanner
            from .filesystem import InputSplit

            self.records = list(InputSplit(path_imgrec, part_index,
                                           num_parts))
        self.shuffle = shuffle
        self.seed = seed
        self.order = list(range(len(self.records)))
        self.cur = 0
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        return [DataDesc("softmax_label", shape)]

    def reset(self):
        if self.shuffle:
            rs = np.random.RandomState(self.seed)
            rs.shuffle(self.order)
            self.seed += 1
        self.cur = 0

    def _decode_one(self, rec):
        header, payload = unpack(rec)
        img = imdecode_np(payload)
        for aug in self.aug:
            img = aug(img)
        img = img.astype(np.float32)
        if self.mean is not None:
            img = img - self.mean
        if self.scale != 1.0:
            img = img * self.scale
        label = header.label
        lab = np.atleast_1d(np.asarray(label, np.float32))
        return img.transpose(2, 0, 1), lab

    def _next_into(self, data, labels):
        """Decode the next batch INTO caller-provided buffers (``data``
        shaped (batch,)+data_shape f32, ``labels`` (batch, label_width)
        f32); returns the pad count or raises StopIteration.

        This is the device-free core of ``next()``: the multi-process
        pipeline (mp_io.py) calls it from decode worker processes with
        shared-memory ring slots as the buffers, so pixels are written
        exactly once — straight into the cross-process ring."""
        if self.cur >= len(self.order):
            raise StopIteration
        idxs = self.order[self.cur : self.cur + self.batch_size]
        pad = self.batch_size - len(idxs)
        while len(idxs) < self.batch_size:
            # wrap-around padding; LOOPED so shards smaller than one
            # batch (realistic under per-process sharding) still fill
            # every row instead of leaving stale buffer contents
            idxs = idxs + self.order[: self.batch_size - len(idxs)]
        self.cur += self.batch_size

        def work(slot, rec):
            img, lab = self._decode_one(rec)
            data[slot] = img
            n = min(self.label_width, lab.size)
            labels[slot, :n] = lab[:n]
            labels[slot, n:] = 0.0

        list(self.pool.map(work, range(len(idxs)),
                           [self.records[i] for i in idxs]))
        return pad

    def next(self):
        from . import telemetry as _tm
        from . import storage
        from .io import _TM_BATCHES

        if self.cur >= len(self.order):
            raise StopIteration
        # data-io profiling (reference parity: profiler_imageiter.py —
        # iterator batches show up as events when the profiler runs);
        # the span also feeds data_batch_wait_seconds when telemetry is on
        with _tm.span("ImageRecordIter.next", category="data-io",
                      histogram_name="data_batch_wait_seconds",
                      iterator="ImageRecordIter"):
            # decode/augment on the thread pool; workers write straight
            # into the pooled staging buffer (copy-on-stage recycles it)
            data = storage.staging_empty(
                (self.batch_size,) + self.data_shape, np.float32)
            labels = np.empty((self.batch_size, self.label_width),
                              np.float32)
            try:
                pad = self._next_into(data, labels)
            except Exception:
                storage.staging_free(data)  # decode error must not leak
                raise
            label_out = labels[:, 0] if self.label_width == 1 else labels
            batch = DataBatch([nd.NDArray(storage.stage_to_device(data))],
                              [nd.array(label_out)], pad=pad)
        _TM_BATCHES.inc(iterator="ImageRecordIter")
        return batch


# sharded-host multi-process pipeline (N decode processes -> shared-memory
# ring -> this process); lives in mp_io.py, surfaced here beside the
# single-process ImageRecordIter it parallelizes
from .mp_io import MultiProcessImageRecordIter  # noqa: E402,F401
