"""Automatic mixed precision (AMP) — policy, master weights, and
in-trace dynamic loss scaling for the Module/Executor/KVStore path.

ROADMAP item 4's precision half: the FusedTrainer has carried bf16
compute + fp32 masters since PR 3, but every other workload runs the
Module path, where bf16 was only "grads survive".  This module makes
bf16 a **bind-time flag** instead of a per-model rewrite:

- ``MXTPU_AMP=bf16`` arms the ``amp_cast`` graph pass (passes/
  amp_cast.py): Convolution / FullyConnected / dot / batch_dot /
  FlashAttention compute in bf16 via inserted ``Cast`` nodes, while
  softmax, losses, and global reductions are cast back to fp32 (norm
  ops keep fp32 *statistics* internally by construction — ops/nn.py
  BatchNorm/LayerNorm accumulate moments in f32 whatever the compute
  dtype).  The pass runs in the PR-8 pipeline, so the program cache
  keys on the post-pass signature and the fused fwd+bwd traces the
  rewritten graph.  Unset, the pass returns the symbol object
  unchanged — bit-identical graphs, signatures, and cache keys.
- fp32 **master weights**: parameters stored bf16 (a ``type_dict``
  bind, a bf16 KVStore value, a bf16 embedding table) get a
  device-resident fp32 master carried as the LAST optimizer-state slot
  (reference ``multi_precision`` layout) — the fused bucket programs
  update the master in fp32 and emit the bf16 parameter cast inside
  the same jitted program; the sharded bucket keeps the master as a
  1/N-per-replica flat vector (arXiv:2004.13336), and sparse buckets
  keep fp32 master rows for bf16 tables.
- **dynamic loss scaling** (``MXTPU_LOSS_SCALE``, off by default):
  the scale is a DEVICE scalar.  It enters the jitted fwd+bwd as a
  traced argument and multiplies the gradient cotangents in-trace (at
  the vjp boundary — MXNet's loss-output ops discard the seed
  cotangent by reference contract, so seed-side scaling would silently
  not propagate through ``SoftmaxOutput``-style graphs); unscale +
  overflow detection fuse into the bucket update (the PR-5 sentinel's
  isfinite shape), skip-step is a ``jnp.where`` lattice over the
  bucket's outputs, and the halve/grow schedule
  (``MXTPU_LOSS_SCALE_WINDOW``) runs as one tiny jitted program over
  the per-bucket finite flags — scale, growth counter, and the
  overflow/skip counters all stay device-resident, so steady-state
  training keeps the zero-per-batch-host-sync property.  Host reads
  happen only in :meth:`LossScaler.report` (tests/bench/monitoring).

bf16 note: unlike fp16, bf16 shares float32's exponent range, so the
classic underflow motivation for loss scaling mostly disappears — what
remains valuable is the fused overflow detection + skip-step ladder,
which turns a divergence-producing Inf/NaN step into a skipped step
plus a halved scale instead of a corrupted model.  docs/amp.md is the
runbook.
"""
from __future__ import annotations

import functools
import os
import threading
import warnings

import jax
import jax.numpy as jnp
import numpy as np

from . import telemetry as _tm
from .base import MXNetError

__all__ = [
    "amp_enabled", "amp_dtype", "master_weights_wanted", "is_low_precision",
    "loss_scale_config", "scaling_active", "global_scaler", "reset_scaler",
    "LossScaler", "warn_no_master", "maybe_unscale_grad",
]

# --- telemetry families (docs/telemetry.md "AMP") --------------------------
_TM_SCALE = _tm.gauge(
    "amp_loss_scale",
    "current dynamic loss scale (mirrored from the device scalar at "
    "reporting boundaries — LossScaler.report(); never per step)")
_TM_OVERFLOW = _tm.counter(
    "amp_overflow_total",
    "optimizer steps on which a bucket saw a non-finite scaled gradient "
    "(device-accumulated; mirrored at reporting boundaries)")
_TM_CAST_NODES = _tm.counter(
    "amp_cast_nodes_total",
    "Cast nodes the amp_cast graph pass inserted into bound graphs "
    "(bind-time, host-side count)")
_TM_SKIPPED = _tm.counter(
    "amp_skipped_steps_total",
    "optimizer steps the loss-scale lattice skipped (weights/state held) "
    "— device-accumulated, mirrored at reporting boundaries")

_LOW_PRECISION = (jnp.bfloat16, jnp.float16)

_DEFAULT_INIT_SCALE = float(2 ** 15)
_DEFAULT_WINDOW = 2000
_MIN_SCALE = 1.0
_MAX_SCALE = float(2 ** 24)


def amp_enabled() -> bool:
    """MXTPU_AMP gate — off by default, ``bf16`` enables."""
    return amp_dtype() is not None


def amp_dtype():
    """The AMP compute dtype (jnp.bfloat16) or None when AMP is off.

    Only the bf16 policy exists: TPUs have no fast fp16 path, and bf16
    needs no rescaling tricks to train.  Unknown values raise rather
    than silently training full-precision under a typo'd knob."""
    raw = os.environ.get("MXTPU_AMP", "").strip().lower()
    if raw in ("", "0", "off", "false", "no", "none"):
        return None
    if raw in ("bf16", "bfloat16", "1", "on", "true", "yes"):
        return jnp.bfloat16
    raise MXNetError(
        f"MXTPU_AMP={raw!r}: unknown AMP policy (supported: 'bf16', "
        "'0'/'off')")


def is_low_precision(dtype) -> bool:
    return jnp.dtype(dtype) in [jnp.dtype(d) for d in _LOW_PRECISION]


def master_weights_wanted(optimizer, weight_dtype) -> bool:
    """Should this (optimizer, weight dtype) pair carry an fp32 master?

    True when the weight is low-precision AND the optimizer opted in
    (``multi_precision=True``) or the process-wide AMP policy is on —
    ``MXTPU_AMP=bf16`` implies masters for every bf16 parameter, the
    "first-class" default the reference makes per-optimizer opt-in."""
    if not is_low_precision(weight_dtype):
        return False
    return bool(getattr(optimizer, "multi_precision", False)) \
        or amp_enabled()


_warned_no_master = set()


def warn_no_master(name):
    """Warn ONCE per key when a low-precision weight updates without an
    fp32 master — silent precision loss (bf16 has ~8 mantissa bits;
    small updates round to nothing) should be visible, not the quiet
    default."""
    key = str(name)
    if key in _warned_no_master:
        return
    _warned_no_master.add(key)
    warnings.warn(
        f"parameter {key!r} has a low-precision dtype but updates "
        "WITHOUT fp32 master weights — small updates will round away. "
        "Pass multi_precision=True to the optimizer (or set "
        "MXTPU_AMP=bf16) to keep fp32 masters.", stacklevel=3)


def count_cast_nodes(n: int):
    if n > 0 and _tm.enabled():
        _TM_CAST_NODES.inc(n)


# ---------------------------------------------------------------------------
# dynamic loss scaling
# ---------------------------------------------------------------------------
def loss_scale_config():
    """(initial_scale, window) from MXTPU_LOSS_SCALE /
    MXTPU_LOSS_SCALE_WINDOW, or None when loss scaling is off.

    ``MXTPU_LOSS_SCALE``: ``0``/``off`` (default) disables; ``dynamic``
    uses the standard 2^15 start; a number is the initial scale (the
    schedule is always dynamic: halve on overflow, double after
    ``MXTPU_LOSS_SCALE_WINDOW`` consecutive clean steps)."""
    raw = os.environ.get("MXTPU_LOSS_SCALE", "").strip().lower()
    if raw in ("", "0", "off", "false", "no", "none"):
        return None
    if raw in ("1", "on", "true", "yes", "dynamic", "default"):
        init = _DEFAULT_INIT_SCALE
    else:
        try:
            init = float(raw)
        except ValueError:
            raise MXNetError(
                f"MXTPU_LOSS_SCALE={raw!r}: expected a number, "
                "'dynamic', or '0'/'off'") from None
        if not init > 0:
            raise MXNetError("MXTPU_LOSS_SCALE must be > 0")
    try:
        window = int(os.environ.get("MXTPU_LOSS_SCALE_WINDOW",
                                    str(_DEFAULT_WINDOW)))
    except ValueError:
        window = _DEFAULT_WINDOW
    return init, max(window, 1)


def scaling_active() -> bool:
    """Loss scaling rides the AMP policy: both knobs must be on."""
    return amp_enabled() and loss_scale_config() is not None


@functools.lru_cache(maxsize=16)
def _scale_step_fn(window: int, nflags: int):
    """One jitted lattice updating (scale, good, overflows, skipped)
    from the step's per-bucket finite flags — pure ``jnp.where``
    selects, no host value ever enters."""

    def step(scale, good, overflows, skipped, flags):
        fin = flags[0]
        for f in flags[1:]:
            fin = jnp.logical_and(fin, f)
        grown = jnp.minimum(scale * 2.0, _MAX_SCALE)
        shrunk = jnp.maximum(scale * 0.5, _MIN_SCALE)
        hit = good + 1 >= window
        new_scale = jnp.where(fin, jnp.where(hit, grown, scale), shrunk)
        new_good = jnp.where(fin, jnp.where(hit, 0, good + 1), 0)
        bad = (~fin).astype(jnp.int32)
        return new_scale, new_good, overflows + bad, skipped + bad

    from . import executor as _executor

    return jax.jit(_executor._count_traces(step, "amp_scale"))


class LossScaler:
    """Device-resident dynamic loss scaler.

    Every state item is a device scalar; the per-step path
    (:meth:`scale_raw` + :meth:`end_step`) never reads one back —
    reads happen only in :meth:`report`, which also mirrors the values
    into the ``amp_*`` telemetry families.  ``_sync_count`` counts
    those reads so tests can assert the hot loop performed none."""

    def __init__(self, init_scale=None, window=None):
        cfg = loss_scale_config()
        if init_scale is None:
            init_scale = cfg[0] if cfg else _DEFAULT_INIT_SCALE
        if window is None:
            window = cfg[1] if cfg else _DEFAULT_WINDOW
        self.window = int(window)
        self._lock = threading.Lock()
        self._sync_count = 0
        self._reported_overflows = 0
        self._reported_skipped = 0
        self._reset_device_state(float(init_scale))

    def _reset_device_state(self, scale):
        # plain jnp scalars are UNCOMMITTED: they may join any
        # computation (single-device or mesh) without a device clash
        self._scale = jnp.float32(scale)
        self._good = jnp.int32(0)
        self._overflows = jnp.int32(0)
        self._skipped = jnp.int32(0)

    # ------------------------------------------------------------- hot path
    def scale_raw(self):
        """The scale as a device scalar (traced into programs)."""
        return self._scale

    def inv_scale_raw(self):
        return 1.0 / self._scale

    def end_step(self, flags):
        """Fold one optimizer step's per-bucket finite flags into the
        scale lattice — one jitted dispatch, zero host syncs."""
        if not flags:
            return
        fn = _scale_step_fn(self.window, len(flags))
        with self._lock:
            (self._scale, self._good, self._overflows,
             self._skipped) = fn(self._scale, self._good,
                                 self._overflows, self._skipped,
                                 tuple(flags))

    # ------------------------------------------------------ boundary reads
    def report(self) -> dict:
        """Sync the device state (the ONLY host read) and mirror it
        into the amp_* telemetry families; returns the snapshot."""
        with self._lock:
            self._sync_count += 1
            snap = {
                "scale": float(np.asarray(self._scale)),
                "good_steps": int(np.asarray(self._good)),
                "overflow_total": int(np.asarray(self._overflows)),
                "skipped_steps_total": int(np.asarray(self._skipped)),
                "window": self.window,
            }
            if _tm.enabled():
                _TM_SCALE.set(snap["scale"])
                d_over = snap["overflow_total"] - self._reported_overflows
                d_skip = snap["skipped_steps_total"] - self._reported_skipped
                if d_over > 0:
                    _TM_OVERFLOW.inc(d_over)
                if d_skip > 0:
                    _TM_SKIPPED.inc(d_skip)
            self._reported_overflows = snap["overflow_total"]
            self._reported_skipped = snap["skipped_steps_total"]
        return snap


_scaler = None
_scaler_lock = threading.Lock()


def global_scaler() -> LossScaler:
    """The process-wide scaler (created lazily from the env knobs)."""
    global _scaler
    with _scaler_lock:
        if _scaler is None:
            _scaler = LossScaler()
        return _scaler


def reset_scaler():
    """Drop the process scaler (test isolation; next use re-reads env)."""
    global _scaler
    with _scaler_lock:
        _scaler = None
    _warned_no_master.clear()


def maybe_unscale_grad(grad):
    """Eager-path unscale hook (Updater fallback loops): divide a
    gradient by the live scale as an async device op.  The fused bucket
    programs unscale in-trace instead; this keeps interleaved eager
    updates numerically correct (the skip-step lattice does not apply
    on the eager path — docs/amp.md)."""
    if not scaling_active():
        return grad
    inv = global_scaler().inv_scale_raw()
    from .ndarray import NDArray

    if getattr(grad, "stype", "default") == "row_sparse":
        from .sparse import RowSparseNDArray

        vals = grad.data._read()
        return RowSparseNDArray(
            grad.indices,
            NDArray(vals * inv.astype(vals.dtype)), grad.shape)
    raw = grad._read()
    return NDArray(raw * inv.astype(raw.dtype))
