"""Optimizers.

Parity: python/mxnet/optimizer.py (reference): registry + create, the full
optimizer zoo (SGD:198, DCASGD:276, NAG:374, SGLD:422, ccSGD:487, Adam:493,
AdaGrad:583, RMSProp:632, AdaDelta:708, Test:762), lr/wd multipliers,
rescale_grad, clip_gradient, and ``get_updater`` (:780) for the kvstore
updater path.  Where the reference calls fused CUDA kernels
(src/operator/optimizer_op.cc), the hot optimizers dispatch to the fused
jitted ops in ops/optimizer_ops.py so clip+decay+update is one XLA kernel.
"""
from __future__ import annotations

import logging
import math
from typing import Dict, Optional

import numpy as np

from . import ndarray as nd
from .base import MXNetError
from .ndarray import NDArray

_OPT_REGISTRY: Dict[str, type] = {}


def register(klass):
    """Parity: Optimizer.register decorator."""
    name = klass.__name__.lower()
    _OPT_REGISTRY[name] = klass
    return klass


class Optimizer:
    """Base optimizer (parity: optimizer.py Optimizer)."""

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 **kwargs):
        if "lr" in kwargs:  # widely-used alias; silently dropping it would
            learning_rate = kwargs.pop("lr")  # train at the 0.01 default
        if kwargs:
            logging.warning("Optimizer: ignoring unknown arguments %s",
                            sorted(kwargs))
        # reference API: multi_precision=True keeps an fp32 master copy
        # as the LAST optimizer-state slot for low-precision weights and
        # runs the update in fp32 (optimizer.py SGD multi_precision).
        # MXTPU_AMP=bf16 implies it for every bf16 param (amp.py).
        self.multi_precision = bool(multi_precision)
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.clip_gradient = clip_gradient
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count: Dict[int, int] = {}
        self.idx2name = dict(param_idx2name or {})
        self.sym = sym
        self.lr_mult: Dict = {}
        self.wd_mult: Dict = {}
        self.set_lr_mult({})
        self.set_wd_mult({})

    @staticmethod
    def create_optimizer(name, **kwargs):
        """Parity: Optimizer.create_optimizer / mx.optimizer.create."""
        if name.lower() not in _OPT_REGISTRY:
            raise MXNetError(f"unknown optimizer {name}")
        return _OPT_REGISTRY[name.lower()](**kwargs)

    def create_state(self, index, weight):
        return None

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = {}
        if self.sym is not None:
            attr = self.sym.attr_dict()
            for name in self.sym.list_arguments():
                if name in attr and "__lr_mult__" in attr[name]:
                    self.lr_mult[name] = float(attr[name]["__lr_mult__"])
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = {}
        for n in self.idx2name.values():
            # reference behavior: no decay on bias/gamma/beta by default
            if not (n.endswith("_weight") or n.endswith("_gamma")):
                self.wd_mult[n] = 0.0
        if self.sym is not None:
            attr = self.sym.attr_dict()
            for name in self.sym.list_arguments():
                if name in attr and "__wd_mult__" in attr[name]:
                    self.wd_mult[name] = float(attr[name]["__wd_mult__"])
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index):
        lr = self.lr_scheduler(self.num_update) if self.lr_scheduler else self.lr
        if index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    # --------------------------------------------- fp32 master weights
    # which optimizer classes implement the master-state layout (SGD /
    # ccSGD / Adam / plain RMSProp); subclasses with different update
    # math (NAG) opt out or their state tuples would be misread
    master_supported = False

    def _use_master(self, weight) -> bool:
        """Does this weight's update run through an fp32 master?  True
        for low-precision weights when ``multi_precision`` (or the
        process AMP policy) is on — create_state then appends the
        master as the LAST state slot, and update()/the fused engine
        compute in fp32 and cast the fresh weight back."""
        from . import amp as _amp

        return self.master_supported \
            and _amp.master_weights_wanted(self, weight.dtype)

    def _master_state(self, weight):
        """The appended master slot: an fp32 copy of the weight."""
        return weight.astype(np.float32) if hasattr(weight, "astype") \
            else nd.array(np.asarray(weight, np.float32))

    def _warn_low_precision(self, index, weight):
        """Warn-once hook for low-precision updates WITHOUT masters."""
        from . import amp as _amp

        if _amp.is_low_precision(weight.dtype):
            _amp.warn_no_master(self.idx2name.get(index, index))

    # ------------------------------------------------- fused kvstore path
    def fused_rule(self):
        """(rule name, static hyperparams) for the bucketed jit-fused
        KVStore update path (kvstore_fused.py), or ``None`` when this
        optimizer must run the eager per-key updater.  The hyperparams
        must be host floats — they bake into the compiled bucket program
        (lr arrives separately, traced, via :meth:`fused_lr`; per-key wd
        is passed as the rule's static ``wd_mult``, so ``wd`` here is
        the 1.0 base the multiplier scales)."""
        return None

    def fused_lr(self, index):
        """Effective per-key lr for the fused path, computed on host
        AFTER ``_update_count(index)`` and fed to the bucket program as
        a traced scalar — lr schedules (and Adam's bias correction)
        never retrace the compiled update."""
        return self._get_lr(index)


# convenience alias (parity: mx.optimizer.create)
def create(name, **kwargs):
    return Optimizer.create_optimizer(name, **kwargs)


@register
class SGD(Optimizer):
    """SGD with momentum (parity: optimizer.py:198); dispatches to the
    fused sgd(_mom)_update kernels (optimizer_op.cc parity)."""

    master_supported = True

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self._use_master(weight):
            # (momentum?, master) — master LAST, all slots fp32
            mom = () if self.momentum == 0.0 else (
                nd.zeros(weight.shape, ctx=weight.context,
                         dtype=np.float32),)
            return mom + (self._master_state(weight),)
        self._warn_low_precision(index, weight)
        if self.momentum == 0.0:
            return None
        return nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        attrs = {"lr": lr, "wd": wd, "rescale_grad": self.rescale_grad,
                 "clip_gradient": self.clip_gradient or 0.0}
        if self._use_master(weight) and isinstance(state, (tuple, list)):
            master = state[-1]
            grad32 = grad.astype(np.float32)
            if self.momentum != 0.0:
                new_w, new_mom = nd.sgd_mom_update(
                    master, grad32, state[0], momentum=self.momentum,
                    **attrs)
                state[0]._set(new_mom._read())
            else:
                new_w = nd.sgd_update(master, grad32, **attrs)
            master._set(new_w._read())
            weight._set(new_w._read().astype(weight.dtype))
            return
        if state is not None:
            new_w, new_mom = nd.sgd_mom_update(weight, grad, state,
                                               momentum=self.momentum, **attrs)
            weight._set(new_w._read())
            state._set(new_mom._read())
        else:
            nd.sgd_update(weight, grad, out=weight, **attrs)

    def fused_rule(self):
        # exact-type gate: NAG subclasses SGD with different math and
        # must stay on the eager per-key updater (ccSGD is SGD math)
        if type(self) not in (SGD, CcSGD):
            return None
        return "sgd", {"momentum": float(self.momentum), "wd": 1.0,
                       "rescale_grad": float(self.rescale_grad),
                       "clip_gradient": float(self.clip_gradient or 0.0)}


@register
class NAG(SGD):
    """Nesterov accelerated SGD (parity: optimizer.py:374)."""

    master_supported = False  # custom update math; no master layout

    def update(self, index, weight, grad, state):
        # reference NAG (optimizer.py:374): mom = momentum*mom + grad';
        # weight -= lr * (grad' + momentum*mom)
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        grad = grad + wd * weight
        if state is not None:
            mom = state
            mom._set((self.momentum * mom + grad)._read())
            weight._set((weight - lr * (grad + self.momentum * mom))._read())
        else:
            weight._set((weight - lr * grad)._read())


@register
class SGLD(Optimizer):
    """Stochastic Gradient Langevin Dynamics (parity: optimizer.py:422)."""

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        noise = nd.normal(loc=0.0, scale=math.sqrt(lr), shape=weight.shape)
        weight._set((weight - lr / 2 * (grad + wd * weight) + noise)._read())


@register
class CcSGD(SGD):
    """Parity: ccSGD (optimizer.py:487) — same math as SGD here."""


@register
class Adam(Optimizer):
    """Adam (parity: optimizer.py:493) with bias correction; fused kernel."""

    master_supported = True

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        slots = (nd.zeros(weight.shape, ctx=weight.context),
                 nd.zeros(weight.shape, ctx=weight.context))
        if self._use_master(weight):
            return slots + (self._master_state(weight),)
        self._warn_low_precision(index, weight)
        return slots

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr, wd = self._get_lr(index), self._get_wd(index)
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        lr_t = lr * math.sqrt(coef2) / coef1
        use_master = self._use_master(weight) and len(state) == 3
        mean, var = state[0], state[1]
        target = state[2] if use_master else weight
        grad_in = grad.astype(np.float32) if use_master else grad
        new_w, new_mean, new_var = nd.adam_update(
            target, grad_in, mean, var, lr=lr_t, beta1=self.beta1,
            beta2=self.beta2,
            epsilon=self.epsilon, wd=wd, rescale_grad=self.rescale_grad,
            clip_gradient=self.clip_gradient or 0.0)
        if use_master:
            target._set(new_w._read())
            weight._set(new_w._read().astype(weight.dtype))
        else:
            weight._set(new_w._read())
        mean._set(new_mean._read())
        var._set(new_var._read())

    def fused_rule(self):
        if type(self) is not Adam:
            return None
        return "adam", {"wd": 1.0, "rescale_grad": float(self.rescale_grad),
                        "clip_gradient": float(self.clip_gradient or 0.0),
                        "beta1": float(self.beta1), "beta2": float(self.beta2),
                        "epsilon": float(self.epsilon)}

    def fused_lr(self, index):
        # the bias correction folds into the traced lr, exactly like the
        # eager update's host-computed lr_t — per-step, zero retraces
        t = self._index_update_count[index]
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        return self._get_lr(index) * math.sqrt(coef2) / coef1


@register
class AdaGrad(Optimizer):
    """Parity: optimizer.py:583."""

    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, ctx=weight.context)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        history = state
        history._set((history + grad * grad)._read())
        weight._set(
            (weight - lr * (grad / nd.sqrt(history + self.float_stable_eps) + wd * weight))._read()
        )


@register
class RMSProp(Optimizer):
    """Parity: optimizer.py:632 (Tieleman & Hinton variant w/ gamma1)."""

    master_supported = True  # plain variant only (centered is eager)

    def __init__(self, learning_rate=0.001, gamma1=0.95, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1, self.gamma2 = gamma1, gamma2
        self.epsilon = epsilon
        self.centered = centered
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (nd.zeros(weight.shape, ctx=weight.context),
                    nd.zeros(weight.shape, ctx=weight.context),
                    nd.zeros(weight.shape, ctx=weight.context))
        if self._use_master(weight):
            return (nd.zeros(weight.shape, ctx=weight.context),
                    self._master_state(weight))
        self._warn_low_precision(index, weight)
        return nd.zeros(weight.shape, ctx=weight.context)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        if not self.centered:
            use_master = self._use_master(weight) \
                and isinstance(state, (tuple, list))
            n = state[0] if use_master else state
            target = state[1] if use_master else weight
            grad_in = grad.astype(np.float32) if use_master else grad
            new_w, new_n = nd.rmsprop_update(
                target, grad_in, n, lr=lr, gamma1=self.gamma1,
                epsilon=self.epsilon,
                wd=wd, rescale_grad=self.rescale_grad,
                clip_gradient=self.clip_gradient or 0.0,
                clip_weights=self.clip_weights or 0.0)
            if use_master:
                target._set(new_w._read())
                weight._set(new_w._read().astype(weight.dtype))
            else:
                weight._set(new_w._read())
            n._set(new_n._read())
            return
        n, g, delta = state
        grad = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            grad = nd.clip(grad, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        n._set((self.gamma1 * n + (1 - self.gamma1) * grad * grad)._read())
        g._set((self.gamma1 * g + (1 - self.gamma1) * grad)._read())
        delta._set((self.gamma2 * delta - lr * grad / nd.sqrt(n - g * g + self.epsilon))._read())
        weight._set((weight + delta)._read())

    def fused_rule(self):
        if type(self) is not RMSProp or self.centered:
            return None
        return "rmsprop", {"wd": 1.0,
                           "rescale_grad": float(self.rescale_grad),
                           "clip_gradient": float(self.clip_gradient or 0.0),
                           "gamma1": float(self.gamma1),
                           "epsilon": float(self.epsilon),
                           "clip_weights": float(self.clip_weights or 0.0)}


@register
class AdaDelta(Optimizer):
    """Parity: optimizer.py:708."""

    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho, self.epsilon = rho, epsilon

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context),
                nd.zeros(weight.shape, ctx=weight.context))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        acc_g, acc_delta = state
        acc_g._set((self.rho * acc_g + (1 - self.rho) * grad * grad)._read())
        delta = nd.sqrt(acc_delta + self.epsilon) / nd.sqrt(acc_g + self.epsilon) * grad
        acc_delta._set((self.rho * acc_delta + (1 - self.rho) * delta * delta)._read())
        weight._set((weight - (delta + wd * weight))._read())


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (parity: optimizer.py:276)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous: Dict = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return (None, weight.copy())
        return (nd.zeros(weight.shape, ctx=weight.context), weight.copy())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        mom, prev = state
        comp = grad + self.lamda * grad * grad * (weight - prev)
        if mom is not None:
            mom._set((self.momentum * mom - lr * (comp + wd * weight))._read())
            update = mom
        else:
            update = -lr * (comp + wd * weight)
        prev._set(weight._read())
        weight._set((weight + update)._read())


@register
class Test(Optimizer):
    """Deterministic test optimizer: weight += grad (parity: optimizer.py:762
    — the kvstore-math test fixture)."""

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, ctx=weight.context)

    def update(self, index, weight, grad, state):
        weight._set((weight + grad * self.rescale_grad)._read())


class Updater:
    """Parity: get_updater closure (optimizer.py:780) — the callable handed
    to KVStore.set_updater; lazily creates per-key state."""

    def __init__(self, optimizer: Optimizer):
        self.optimizer = optimizer
        self.states: Dict = {}

    def ensure_state(self, index, weight):
        """Create-or-get the per-key optimizer state (the lazy half of
        ``__call__``; the fused kvstore engine calls it directly so the
        eager and bucketed paths share ONE state store — interleaving
        them mid-run stays consistent)."""
        if index not in self.states:
            self.states[index] = self.optimizer.create_state(index, weight)
        return self.states[index]

    def __call__(self, index, grad, weight):
        from . import amp as _amp

        # AMP dynamic loss scaling: the fused bucket programs unscale
        # in-trace; this eager entry divides by the live scale here so
        # fallback loops and fused steps interleave consistently (the
        # skip-step lattice does NOT apply on the eager path)
        grad = _amp.maybe_unscale_grad(grad)
        if getattr(grad, "stype", "default") == "row_sparse":
            # touched-rows-only lazy update (sparse.py): same jitted
            # row program as the fused sparse bucket, so eager and
            # fused interleave bit-identically
            from . import sparse as _sparse

            _sparse.eager_update(self.optimizer, self, index, weight,
                                 grad)
            return
        self.optimizer.update(index, weight, grad,
                              self.ensure_state(index, weight))

    def get_states(self):
        import pickle

        return pickle.dumps({k: _state_to_np(v) for k, v in self.states.items()})

    def set_states(self, states):
        import pickle

        raw = pickle.loads(states)
        self.states = {k: _state_from_np(v) for k, v in raw.items()}


def _state_to_np(state):
    if state is None:
        return None
    if isinstance(state, (tuple, list)):
        return tuple(_state_to_np(s) for s in state)
    return state.asnumpy()


def _state_from_np(state):
    if state is None:
        return None
    if isinstance(state, tuple):
        return tuple(_state_from_np(s) for s in state)
    return nd.array(state)


def get_updater(optimizer: Optimizer) -> Updater:
    return Updater(optimizer)
