"""Runtime kernel compilation from user-supplied source.

Parity: python/mxnet/rtc.py + src/common/mxrtc.cc (MXRtc: user CUDA
source strings compiled with NVRTC, cached CUfunction launched on
NDArrays).  The TPU-native analogue compiles user-supplied **Pallas**
kernel source: the source text defines the kernel body (a function of
input/output Refs), which is wrapped in ``pl.pallas_call`` and jitted.
Compilation is cached per (name, source); on CPU backends the kernel runs
in Pallas interpret mode so the feature works everywhere tests run.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import MXNetError
from .ndarray import NDArray


class Rtc:
    """Parity: mx.rtc.Rtc (python/mxnet/rtc.py:11-90).

    The reference signature was ``Rtc(name, inputs, outputs, kernel)``
    where kernel was raw CUDA C.  Here ``kernel`` is Python source that
    must define a function ``<name>(<in_refs>..., <out_refs>...)`` written
    against the Pallas API; the namespace exposes ``pl`` (jax.experimental
    .pallas), ``pltpu`` (TPU primitives, when importable), ``jnp``, ``jax``
    and ``lax``.

    inputs/outputs: [(argname, NDArray_template), ...] — templates fix
    shapes/dtypes exactly like the reference bound shapes at Rtc() time.
    """

    def __init__(self, name, inputs, outputs, kernel):
        from jax.experimental import pallas as pl

        self.name = name
        self._in_templates = list(inputs)
        self._out_templates = list(outputs)

        ns = {"pl": pl, "jnp": jnp, "jax": jax, "lax": jax.lax}
        try:
            from jax.experimental.pallas import tpu as pltpu

            ns["pltpu"] = pltpu
        except ImportError:  # CPU-only builds
            pass
        try:
            exec(compile(kernel, f"<rtc:{name}>", "exec"), ns)
        except SyntaxError as e:
            raise MXNetError(f"Rtc kernel '{name}' failed to parse: {e}") from e
        if name not in ns or not callable(ns[name]):
            raise MXNetError(
                f"Rtc kernel source must define a function named '{name}'")
        self._kernel = ns[name]

        self._out_shapes = tuple(
            jax.ShapeDtypeStruct(tuple(t.shape), t.dtype)
            for _, t in self._out_templates)
        self._compiled_cache = {}

    def _compiled(self, *raw):
        # interpret mode must track where the *inputs* live, not the
        # process default backend: CPU-resident arrays need interpret=True
        # even when a TPU is attached.
        from jax.experimental import pallas as pl

        platforms = {d.platform for a in raw
                     for d in getattr(a, "devices", lambda: set())()}
        on_tpu = platforms == {"tpu"} and platforms
        fn = self._compiled_cache.get(on_tpu)
        if fn is None:
            call = pl.pallas_call(self._kernel, out_shape=self._out_shapes,
                                  interpret=not on_tpu)
            fn = self._compiled_cache[on_tpu] = jax.jit(call)
        return fn(*raw)

    def push(self, inputs, outputs, grid_dims=None, block_dims=None):
        """Run the kernel (parity: MXRtcPush).  grid/block dims are
        accepted for signature parity; Pallas grids are fixed at build
        time, so they are validated but not re-applied."""
        if len(inputs) != len(self._in_templates):
            raise MXNetError(f"Rtc '{self.name}' expects "
                             f"{len(self._in_templates)} inputs")
        if len(outputs) != len(self._out_templates):
            raise MXNetError(f"Rtc '{self.name}' expects "
                             f"{len(self._out_templates)} outputs")
        raw = [x._read() if isinstance(x, NDArray) else jnp.asarray(x)
               for x in inputs]
        res = self._compiled(*raw)
        if not isinstance(res, (tuple, list)):
            res = (res,)
        for dst, val in zip(outputs, res):
            dst._set(val)
        return outputs
