"""Indexing ops (parity: src/operator/tensor/indexing_op.{h,cc}).

Embedding / take lower to XLA gather — the TPU path for what the reference
does with hand-written CUDA gather kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import parse_attr
from .registry import register


def _embedding_params(attrs, *in_shapes):
    inp = int(parse_attr(attrs["input_dim"]))
    out = int(parse_attr(attrs["output_dim"]))
    return {"weight": (inp, out)}


@register(
    "Embedding",
    arg_names=("data", "weight"),
    param_names=("weight",),
    infer_params=_embedding_params,
)
def _embedding(ctx, data, weight, **attrs):
    """Parity: Embedding (indexing_op.h).  data holds float indices (MXNet
    convention); output shape = data.shape + (output_dim,).  Out-of-range
    ids clip to the table bounds like ``take`` (and the reference's
    kernel) — unclipped they flowed straight into the XLA gather, whose
    out-of-bounds behavior is implementation-defined."""
    idx = jnp.clip(data.astype(jnp.int32), 0, weight.shape[0] - 1)
    return jnp.take(weight, idx, axis=0)


@register("take", arg_names=("a", "indices"))
def _take(ctx, a, indices, **attrs):
    """Parity: take (indexing_op.cc); axis=0 only in v0.9.4, clip mode."""
    idx = jnp.clip(indices.astype(jnp.int32), 0, a.shape[0] - 1)
    return jnp.take(a, idx, axis=0)


@register("batch_take", arg_names=("a", "indices"))
def _batch_take(ctx, a, indices, **attrs):
    """Parity: batch_take — per-row element pick (indexing_op.cc)."""
    idx = indices.astype(jnp.int32).reshape(-1)
    return a[jnp.arange(a.shape[0]), idx]


@register("one_hot", aliases=("_onehot_encode",))
def _one_hot(ctx, data, **attrs):
    """Parity: _onehot_encode NDArray function (src/ndarray/ndarray.cc:752).
    ``dtype`` is honored (it used to be hard-coded float32 regardless of
    the requested type)."""
    depth = int(parse_attr(attrs["depth"]))
    on = float(parse_attr(attrs.get("on_value", 1.0)))
    off = float(parse_attr(attrs.get("off_value", 0.0)))
    dtype = jnp.dtype(str(attrs.get("dtype", "float32")))
    oh = jax.nn.one_hot(data.astype(jnp.int32), depth, dtype=dtype)
    if on == 1.0 and off == 0.0:
        return oh
    return (oh * (on - off) + off).astype(dtype)


@register("choose_element_0index", arg_names=("lhs", "rhs"))
def _choose_element_0index(ctx, lhs, rhs, **attrs):
    """Parity: choose_element_0index (src/ndarray/ndarray.cc:755) — pick
    lhs[i, rhs[i]] per row."""
    idx = rhs.astype(jnp.int32).reshape(-1)
    return lhs[jnp.arange(lhs.shape[0]), idx]


@register("fill_element_0index", arg_names=("lhs", "mhs", "rhs"))
def _fill_element_0index(ctx, lhs, mhs, rhs, **attrs):
    """Parity: fill_element_0index (ndarray.cc:761) — lhs[i, rhs[i]] = mhs[i]."""
    idx = rhs.astype(jnp.int32).reshape(-1)
    return lhs.at[jnp.arange(lhs.shape[0]), idx].set(mhs.reshape(-1))
