"""Flash attention — Pallas TPU kernels with full custom-VJP backward.

The reference has no attention kernels at all (its long-sequence story is
bucketing, SURVEY.md §5.7); this is the TPU-native hot-op the framework's
sequence stack builds on: blockwise online-softmax attention computed in
VMEM (never materializing the (T, T) score matrix in HBM), forward +
backward as Pallas kernels on the MXU.

Used by parallel/ring_attention.py for the per-device local attention
(the ring rotates K/V shards; each local block product runs here) and
directly via ``flash_attention`` for single-chip long sequences.

Layout: (B, H, T, D).  T must divide by the block sizes and D by 8
(lane padding covers D < 128; 128-multiples tile the MXU best) —
``supports`` reports whether a shape qualifies, the auto dispatcher
(parallel/ring_attention.attention) falls back to the pure-lax path
otherwise, and direct calls with ragged shapes raise.
``interpret=True`` runs the same kernels on CPU for tests.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

NEG_INF = -1e30  # large-negative instead of -inf: keeps exp()/max() NaN-free


def supports(q_shape, block_q=128, block_k=128):
    """True when the Pallas path handles this shape without padding."""
    b, h, t, d = q_shape
    return t % block_q == 0 and t % block_k == 0 and d % 8 == 0


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal,
                block_q, block_k, seq_len):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale            # (block_q, d)
    d = q.shape[-1]

    num_k = seq_len // block_k
    if causal:
        # only blocks with k_start <= q_end participate
        num_k_live = (qi * block_q + block_q + block_k - 1) // block_k
    else:
        num_k_live = num_k

    def body(ki, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l = l * corr + jnp.sum(p, axis=-1)
        acc = acc * corr[:, None] + jnp.dot(
            p, v, preferred_element_type=jnp.float32)
        return m_new, l, acc

    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, num_k_live, body, (m0, l0, acc0))

    l_safe = jnp.maximum(l, 1e-20)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[0, :, 0] = m + jnp.log(l_safe)


# --------------------------------------------------------------------------
# backward
# --------------------------------------------------------------------------
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   *, scale, causal, block_q, block_k, seq_len):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, :, 0]
    delta = delta_ref[0, :, 0]
    d = q.shape[-1]

    num_k = seq_len // block_k
    num_k_live = ((qi * block_q + block_q + block_k - 1) // block_k
                  if causal else num_k)

    def body(ki, dq):
        k = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q * scale, k.T, preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        return dq + jnp.dot(ds, k, preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, num_k_live, body, jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, scale, causal, block_q, block_k,
                    seq_len):
    ki = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)                     # (block_k, d)
    v = v_ref[0].astype(jnp.float32)
    d = k.shape[-1]

    num_q = seq_len // block_q
    # causal: only q blocks with q_end >= k_start contribute
    q_start = (ki * block_k) // block_q if causal else 0

    def body(qi, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(qi * block_q, block_q), 0]
        delta = delta_ref[0, pl.ds(qi * block_q, block_q), 0]
        s = jnp.dot(q * scale, k.T, preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])                    # (block_q, block_k)
        dv = dv + jnp.dot(p.T, do, preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dk = dk + jnp.dot(ds.T, q, preferred_element_type=jnp.float32)
        return dk, dv

    z = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(q_start, num_q, body, (z, z))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


# --------------------------------------------------------------------------
# pallas_call plumbing
# --------------------------------------------------------------------------
def _fwd_impl(q, k, v, scale, causal, block_q, block_k, interpret):
    b, h, t, d = q.shape
    bh = b * h
    q3 = q.reshape(bh, t, d)
    k3 = k.reshape(bh, t, d)
    v3 = v.reshape(bh, t, d)
    grid = (bh, t // block_q)
    kern = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                             block_q=block_q, block_k=block_k, seq_len=t)
    o, lse = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, t, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, t, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            # trailing singleton lane dim: Mosaic requires the last two
            # block dims to be (8,128)-divisible or equal to the array
            # dims — a 2D (1, block_q) lse block violates that on real
            # TPUs (interpret mode never checks)
            pl.BlockSpec((1, block_q, 1), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, t, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3)
    return o.reshape(b, h, t, d), lse.reshape(b, h, t)


def _bwd_impl(q, k, v, o, lse, do, scale, causal, block_q, block_k,
              interpret):
    b, h, t, d = q.shape
    bh = b * h
    delta = jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32),
                    axis=-1)                             # (b, h, t)
    q3, k3, v3 = (x.reshape(bh, t, d) for x in (q, k, v))
    do3 = do.reshape(bh, t, d)
    lse3 = lse.reshape(bh, t, 1)
    delta3 = delta.reshape(bh, t, 1)

    dq_kern = functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                                block_q=block_q, block_k=block_k, seq_len=t)
    dq = pl.pallas_call(
        dq_kern,
        grid=(bh, t // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, t, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, t, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_q, 1), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_q, 1), lambda i, j: (i, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        interpret=interpret,
    )(q3, k3, v3, do3, lse3, delta3)

    dkv_kern = functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                                 block_q=block_q, block_k=block_k, seq_len=t)
    dk, dv = pl.pallas_call(
        dkv_kern,
        grid=(bh, t // block_k),
        in_specs=[
            pl.BlockSpec((1, t, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, t, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, t, 1), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, t, 1), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, t, d), v.dtype),
        ],
        interpret=interpret,
    )(q3, k3, v3, do3, lse3, delta3)
    return (dq.reshape(b, h, t, d), dk.reshape(b, h, t, d),
            dv.reshape(b, h, t, d))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal=False, scale=None, block_q=128,
                    block_k=128, interpret=False):
    """Blockwise exact attention; returns (B, H, T, D).

    The (T, T) score matrix only ever exists one (block_q, block_k) tile
    at a time in VMEM; memory is O(T·D) instead of O(T²)."""
    o, _ = _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret)
    return o


def _resolve_scale(scale, d):
    return scale if scale is not None else 1.0 / np.sqrt(d)


def _check_shape(shape, bq, bk):
    b, h, t, d = shape
    if t % bq or t % bk or d % 8:
        raise ValueError(
            f"flash_attention requires T divisible by block sizes "
            f"({bq}, {bk}) and D % 8 == 0; got T={t}, D={d}. "
            "Use parallel.ring_attention.attention(impl='auto') for "
            "automatic fallback.")


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    s = _resolve_scale(scale, q.shape[-1])
    bq = min(block_q, q.shape[2])
    bk = min(block_k, q.shape[2])
    _check_shape(q.shape, bq, bk)
    o, lse = _fwd_impl(q, k, v, s, causal, bq, bk, interpret)
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, do):
    q, k, v, o, lse = res
    s = _resolve_scale(scale, q.shape[-1])
    bq = min(block_q, q.shape[2])
    bk = min(block_k, q.shape[2])
    return _bwd_impl(q, k, v, o, lse, do, s, causal, bq, bk, interpret)


flash_attention.defvjp(_flash_fwd, _flash_bwd)


# --------------------------------------------------------------------------
# op registration: nd.FlashAttention / sym.FlashAttention
# --------------------------------------------------------------------------
def _register():
    from ..base import parse_attr, parse_bool
    from .registry import register

    @register("FlashAttention", arg_names=("query", "key", "value"))
    def _flash_attention_op(ctx, query, key, value, **attrs):
        """Exact blockwise attention over (B, H, T, D) inputs.

        No reference counterpart (SURVEY.md §5.7: the reference's
        long-sequence story is bucketing) — this is the TPU-native hot
        op behind the sequence stack.  impl: auto | flash |
        flash_interpret | lax."""
        causal = parse_bool(attrs.get("causal", False))
        scale = attrs.get("scale")
        scale = float(parse_attr(scale)) if scale is not None else None
        impl = str(attrs.get("impl", "auto"))
        from ..parallel.ring_attention import attention

        return attention(query, key, value, causal=causal, scale=scale,
                         impl=impl, platform=ctx.platform)


_register()
