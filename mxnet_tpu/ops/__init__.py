"""Operator library — importing this package registers the full op surface.

Layout mirrors the functional grouping of the reference's src/operator/
(SURVEY.md §2.1 rows 'Neural-net operators' / 'Tensor ops'):

- elemwise.py      unary/binary/scalar/logic/broadcast + ElementWiseSum
- reduce.py        reductions + arg-reductions
- matrix.py        dot/batch_dot, reshape family, slicing, ordering
- indexing.py      Embedding/take/one_hot
- init_sample.py   zeros/ones/arange + uniform/normal sampling
- nn.py            Conv/Deconv/FC/BN/Pool/Act/Dropout/LRN/Concat/...
- loss.py          *Output ops (custom_vjp backward), MakeLoss, CE
- sequence.py      SequenceLast/Mask/Reverse
- optimizer_ops.py fused sgd/adam/rmsprop update kernels
- spatial.py       GridGenerator/BilinearSampler/SpatialTransformer/ROI/...
- rnn_op.py        fused RNN op (lax.scan)
"""
from . import registry
from .registry import OpCtx, OpDef, get, exists, invoke, list_ops, register

from . import elemwise  # noqa: F401
from . import reduce  # noqa: F401
from . import matrix  # noqa: F401
from . import indexing  # noqa: F401
from . import init_sample  # noqa: F401
from . import nn  # noqa: F401
from . import loss  # noqa: F401
from . import sequence  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import rnn_op  # noqa: F401
from . import vision  # noqa: F401
from . import ctc  # noqa: F401
from . import custom  # noqa: F401
from . import flash_attention  # noqa: F401
from . import residual_epilogue  # noqa: F401
