"""Vision custom-tail ops.

TPU-native implementations of the ops the reference hand-writes in CUDA:
GridGenerator (src/operator/grid_generator-inl.h), BilinearSampler
(src/operator/bilinear_sampler-inl.h), SpatialTransformer
(src/operator/spatial_transformer-inl.h), ROIPooling
(src/operator/roi_pooling-inl.h), Correlation
(src/operator/correlation-inl.h), and the SSD multibox trio
(example/ssd/operator/multibox_{prior,target,detection}.{cc,cu}).

Design: everything is expressed as dense gather/where/reduce-window math —
static shapes, no data-dependent control flow — so XLA can fuse and tile it.
The inner sampling math (bilinear gather) vectorizes across the whole output
grid at once instead of the reference's one-thread-per-output-pixel CUDA
scheme.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError, parse_attr, normalize_tuple, parse_bool
from .registry import register


# --------------------------------------------------------------------------
# GridGenerator / BilinearSampler / SpatialTransformer
# --------------------------------------------------------------------------
def _target_shape(attrs):
    ts = normalize_tuple(parse_attr(attrs.get("target_shape", (0, 0))), 2,
                         "target_shape")
    return int(ts[0]), int(ts[1])


def _affine_grid(theta, h, w):
    """Normalized sampling grid for a batch of 2x3 affine matrices.

    Parity: GridGeneratorOp affine path (grid_generator-inl.h:60-92) —
    target coords are normalized to [-1, 1] with x varying fastest, and the
    source coords are ``theta @ [x, y, 1]``.
    """
    n = theta.shape[0]
    theta = theta.reshape(n, 2, 3)
    ys, xs = jnp.meshgrid(
        jnp.linspace(-1.0, 1.0, h) if h > 1 else jnp.zeros((1,)),
        jnp.linspace(-1.0, 1.0, w) if w > 1 else jnp.zeros((1,)),
        indexing="ij",
    )
    # rows of grid_dst: (x, y, 1) per target pixel
    grid_dst = jnp.stack([xs.ravel(), ys.ravel(), jnp.ones(h * w)], axis=0)
    src = jnp.einsum("nij,jk->nik", theta, grid_dst)  # (N, 2, H*W)
    return src.reshape(n, 2, h, w)


@register("GridGenerator")
def _grid_generator(ctx, data, **attrs):
    """Parity: GridGenerator (src/operator/grid_generator-inl.h).

    transform_type='affine': data (N, 6) -> grid (N, 2, H, W) from
    attr target_shape.  transform_type='warp': data is a flow field
    (N, 2, H, W); grid = normalize(flow + identity meshgrid)
    (grid_generator-inl.h:94-126).
    """
    transform_type = attrs.get("transform_type", "affine")
    if transform_type == "affine":
        h, w = _target_shape(attrs)
        if h <= 0 or w <= 0:
            raise MXNetError("GridGenerator(affine) requires target_shape")
        return _affine_grid(data, h, w)
    if transform_type == "warp":
        n, two, h, w = data.shape
        ys, xs = jnp.meshgrid(jnp.arange(h, dtype=data.dtype),
                              jnp.arange(w, dtype=data.dtype), indexing="ij")
        x = data[:, 0] + xs
        y = data[:, 1] + ys
        xn = jnp.where(w > 1, x * (2.0 / max(w - 1, 1)) - 1.0, jnp.zeros_like(x))
        yn = jnp.where(h > 1, y * (2.0 / max(h - 1, 1)) - 1.0, jnp.zeros_like(y))
        return jnp.stack([xn, yn], axis=1)
    raise MXNetError(f"unknown transform_type {transform_type!r}")


def _bilinear_sample(data, grid):
    """Sample data (N,C,H,W) at normalized grid (N,2,Ho,Wo); zeros outside.

    Parity: BilinearSamplerOp (bilinear_sampler-inl.h:44-90): real coords
    are ``(g + 1) * (size - 1) / 2``; each output is the 4-corner bilinear
    blend, with corners falling outside the image contributing zero.
    """
    n, c, h, w = data.shape
    xs = (grid[:, 0] + 1.0) * (w - 1) / 2.0  # (N, Ho, Wo)
    ys = (grid[:, 1] + 1.0) * (h - 1) / 2.0

    x0 = jnp.floor(xs)
    y0 = jnp.floor(ys)
    wx = xs - x0
    wy = ys - y0

    def corner(yc, xc, weight):
        valid = (xc >= 0) & (xc <= w - 1) & (yc >= 0) & (yc <= h - 1)
        xi = jnp.clip(xc, 0, w - 1).astype(jnp.int32)
        yi = jnp.clip(yc, 0, h - 1).astype(jnp.int32)
        # gather per batch: (N, C, Ho, Wo)
        batch = jnp.arange(n).reshape(n, 1, 1)
        vals = data[batch, :, yi, xi]  # (N, Ho, Wo, C)
        vals = jnp.moveaxis(vals, -1, 1)
        wgt = (weight * valid.astype(data.dtype))[:, None]
        return vals * wgt

    out = (
        corner(y0, x0, (1 - wy) * (1 - wx))
        + corner(y0, x0 + 1, (1 - wy) * wx)
        + corner(y0 + 1, x0, wy * (1 - wx))
        + corner(y0 + 1, x0 + 1, wy * wx)
    )
    return out.astype(data.dtype)


@register("BilinearSampler", arg_names=("data", "grid"))
def _bilinear_sampler(ctx, data, grid, **attrs):
    """Parity: BilinearSampler (src/operator/bilinear_sampler-inl.h)."""
    return _bilinear_sample(data, grid)


def _st_params(attrs, data_shape, *rest):
    return {"loc": (data_shape[0], 6)}


@register(
    "SpatialTransformer",
    arg_names=("data", "loc"),
    infer_params=_st_params,
)
def _spatial_transformer(ctx, data, loc, **attrs):
    """Parity: SpatialTransformer (src/operator/spatial_transformer-inl.h):
    affine grid from the localization net output + bilinear sampling.  The
    cuDNN path (cudnn_spatial_transformer-inl.h) fuses the same two stages.
    """
    h, w = _target_shape(attrs)
    if h <= 0 or w <= 0:
        h, w = data.shape[2], data.shape[3]
    grid = _affine_grid(loc, h, w)
    return _bilinear_sample(data, grid)


# --------------------------------------------------------------------------
# ROIPooling
# --------------------------------------------------------------------------
@register("ROIPooling", arg_names=("data", "rois"))
def _roi_pooling(ctx, data, rois, **attrs):
    """Parity: ROIPooling (src/operator/roi_pooling-inl.h).

    data (N,C,H,W); rois (R,5) = [batch_index, x1, y1, x2, y2] in image
    coordinates.  Coordinates are scaled by spatial_scale and *rounded*
    (roi_pooling-inl.h / .cu kernel), bins are [floor(i*bh), ceil((i+1)*bh))
    and max-pooled; empty bins emit 0.

    TPU shape: instead of one CUDA thread per output element doing a serial
    scan, we build separable row/column bin-membership masks and reduce with
    two masked maxes — a dense (R,C,PH,H,W-free) formulation XLA can fuse.
    """
    if "pooled_size" not in attrs:
        raise MXNetError("ROIPooling requires attribute pooled_size")
    pooled = normalize_tuple(parse_attr(attrs["pooled_size"]), 2, "pooled_size")
    ph, pw = int(pooled[0]), int(pooled[1])
    scale = float(parse_attr(attrs.get("spatial_scale", 1.0)))

    n, c, h, w = data.shape
    r = rois.shape[0]

    # C round(): half away from zero (the reference kernel's rounding);
    # jnp.round is banker's rounding and shifts bins at exact .5 products.
    def _cround(v):
        return jnp.sign(v) * jnp.floor(jnp.abs(v) + 0.5)

    batch_idx = jnp.clip(_cround(rois[:, 0]).astype(jnp.int32), 0, n - 1)
    x1 = _cround(rois[:, 1] * scale)
    y1 = _cround(rois[:, 2] * scale)
    x2 = _cround(rois[:, 3] * scale)
    y2 = _cround(rois[:, 4] * scale)
    roi_w = jnp.maximum(x2 - x1 + 1.0, 1.0)  # (R,)
    roi_h = jnp.maximum(y2 - y1 + 1.0, 1.0)
    bin_w = roi_w / pw
    bin_h = roi_h / ph

    def axis_mask(start, bin_size, nbins, size):
        # mask[r, b, p] = pixel p belongs to bin b of roi r
        b = jnp.arange(nbins, dtype=data.dtype)
        lo = jnp.floor(b[None, :] * bin_size[:, None] + start[:, None])
        hi = jnp.ceil((b[None, :] + 1.0) * bin_size[:, None] + start[:, None])
        lo = jnp.clip(lo, 0, size)
        hi = jnp.clip(hi, 0, size)
        p = jnp.arange(size, dtype=data.dtype)
        return (p[None, None, :] >= lo[:, :, None]) & (p[None, None, :] < hi[:, :, None])

    mask_h = axis_mask(y1, bin_h, ph, h)  # (R, PH, H)
    mask_w = axis_mask(x1, bin_w, pw, w)  # (R, PW, W)

    picked = data[batch_idx]  # (R, C, H, W)
    neg = jnp.asarray(-jnp.inf, dtype=data.dtype)
    # reduce H: (R, C, PH, W)
    tmp = jnp.where(mask_h[:, None, :, :, None], picked[:, :, None, :, :], neg)
    tmp = tmp.max(axis=3)
    # reduce W: (R, C, PH, PW)
    out = jnp.where(mask_w[:, None, None, :, :], tmp[:, :, :, None, :], neg)
    out = out.max(axis=4)
    return jnp.where(jnp.isfinite(out), out, 0.0).astype(data.dtype)


# --------------------------------------------------------------------------
# Correlation (FlowNet)
# --------------------------------------------------------------------------
@register(
    "Correlation",
    arg_names=("data1", "data2"),
    num_outputs=1,
)
def _correlation(ctx, data1, data2, **attrs):
    """Parity: Correlation (src/operator/correlation-inl.h).

    Patch cross-correlation between two feature maps over a displacement
    neighborhood.  Output channel k enumerates displacements
    (dy, dx) in stride2 * [-r, r]^2 with r = max_displacement/stride2;
    output (i, j) centers at border + (i, j)*stride1 in the padded map;
    values are averaged over kernel window and channels
    (correlation-inl.h top_height/top_width math).

    The displacement loop is a static Python unroll (D^2 shifted
    multiplies); per displacement the kernel-window sum is one
    reduce_window — both XLA-fusable, no scalar loops.
    """
    kernel_size = int(parse_attr(attrs.get("kernel_size", 1)))
    max_disp = int(parse_attr(attrs.get("max_displacement", 1)))
    stride1 = int(parse_attr(attrs.get("stride1", 1)))
    stride2 = int(parse_attr(attrs.get("stride2", 1)))
    pad_size = int(parse_attr(attrs.get("pad_size", 0)))
    is_multiply = parse_bool(attrs.get("is_multiply", True))

    n, c, h, w = data1.shape
    pad_cfg = ((0, 0), (0, 0), (pad_size, pad_size), (pad_size, pad_size))
    d1 = jnp.pad(data1, pad_cfg)
    d2 = jnp.pad(data2, pad_cfg)
    ph_, pw_ = h + 2 * pad_size, w + 2 * pad_size

    kernel_radius = (kernel_size - 1) // 2
    border = max_disp + kernel_radius
    top_h = int(math.ceil(float(ph_ - border * 2) / stride1))
    top_w = int(math.ceil(float(pw_ - border * 2) / stride1))
    if top_h < 1 or top_w < 1:
        raise MXNetError("Correlation: output would be empty")
    grid_radius = max_disp // stride2
    grid_width = 2 * grid_radius + 1

    norm = float(kernel_size * kernel_size * c)
    window = (1, 1, kernel_size, kernel_size)

    def window_sum(x):
        return jax.lax.reduce_window(
            x, 0.0, jax.lax.add, window, (1, 1, 1, 1), "VALID")

    # centers in padded coords: y = border + i*stride1; after VALID
    # reduce_window with kernel k, index (y - kernel_radius) is the window
    # whose *center* is y.
    ys = border - kernel_radius + stride1 * np.arange(top_h)
    xs = border - kernel_radius + stride1 * np.arange(top_w)

    outs = []
    for dyi in range(-grid_radius, grid_radius + 1):
        for dxi in range(-grid_radius, grid_radius + 1):
            dy, dx = dyi * stride2, dxi * stride2
            shifted = jnp.roll(d2, shift=(-dy, -dx), axis=(2, 3))
            if is_multiply:
                prod = d1 * shifted
            else:
                prod = jnp.abs(d1 - shifted)
            summed = window_sum(prod.sum(axis=1, keepdims=True)) / norm
            outs.append(summed[:, 0][:, ys][:, :, xs])
    return jnp.stack(outs, axis=1).astype(data1.dtype)


# --------------------------------------------------------------------------
# SSD multibox trio (example/ssd/operator/multibox_*.{cc,cu})
# --------------------------------------------------------------------------
def _parse_floats(val, default):
    v = parse_attr(val) if val is not None else default
    if isinstance(v, (int, float)):
        return (float(v),)
    return tuple(float(x) for x in v)


@register("MultiBoxPrior", aliases=("_contrib_MultiBoxPrior",))
def _multibox_prior(ctx, data, **attrs):
    """Parity: MultiBoxPrior (example/ssd/operator/multibox_prior-inl.h).

    Anchor generation per feature-map cell: num_anchors = |sizes| +
    |ratios| - 1 — each size with ratios[0], plus sizes[0] with each other
    ratio.  Centers at ((j+0.5)/W, (i+0.5)/H); box half-extents
    (s*sqrt(r)/2, s/sqrt(r)/2).  Output (1, H*W*A, 4) corner format.
    Pure constant-building math — computed with numpy at trace time.
    """
    sizes = _parse_floats(attrs.get("sizes"), (1.0,))
    ratios = _parse_floats(attrs.get("ratios"), (1.0,))
    clip = parse_bool(attrs.get("clip", False))
    h, w = data.shape[2], data.shape[3]

    combos = [(s, ratios[0]) for s in sizes] + [(sizes[0], r) for r in ratios[1:]]
    cy, cx = np.meshgrid((np.arange(h) + 0.5) / h, (np.arange(w) + 0.5) / w,
                         indexing="ij")
    anchors = []
    for s, r in combos:
        hw = s * math.sqrt(r) / 2.0
        hh = s / math.sqrt(r) / 2.0
        anchors.append(np.stack([cx - hw, cy - hh, cx + hw, cy + hh], axis=-1))
    out = np.stack(anchors, axis=2).reshape(1, -1, 4).astype(np.float32)
    if clip:
        out = np.clip(out, 0.0, 1.0)
    return jnp.asarray(out)


def _iou_matrix(a, b):
    """IoU between (A,4) and (B,4) corner boxes -> (A,B)."""
    ix1 = jnp.maximum(a[:, None, 0], b[None, :, 0])
    iy1 = jnp.maximum(a[:, None, 1], b[None, :, 1])
    ix2 = jnp.minimum(a[:, None, 2], b[None, :, 2])
    iy2 = jnp.minimum(a[:, None, 3], b[None, :, 3])
    iw = jnp.maximum(ix2 - ix1, 0.0)
    ih = jnp.maximum(iy2 - iy1, 0.0)
    inter = iw * ih
    area_a = jnp.maximum((a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1]), 0.0)
    area_b = jnp.maximum((b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]), 0.0)
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def _encode_loc(anchors, gt, variances):
    """Box regression targets (multibox_target-inl.h encoding)."""
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    ax = (anchors[:, 0] + anchors[:, 2]) / 2
    ay = (anchors[:, 1] + anchors[:, 3]) / 2
    gw = jnp.maximum(gt[:, 2] - gt[:, 0], 1e-12)
    gh = jnp.maximum(gt[:, 3] - gt[:, 1], 1e-12)
    gx = (gt[:, 0] + gt[:, 2]) / 2
    gy = (gt[:, 1] + gt[:, 3]) / 2
    v0, v1, v2, v3 = variances
    return jnp.stack([
        (gx - ax) / jnp.maximum(aw, 1e-12) / v0,
        (gy - ay) / jnp.maximum(ah, 1e-12) / v1,
        jnp.log(gw / jnp.maximum(aw, 1e-12)) / v2,
        jnp.log(gh / jnp.maximum(ah, 1e-12)) / v3,
    ], axis=-1)


@register(
    "MultiBoxTarget",
    arg_names=("anchor", "label", "cls_pred"),
    num_outputs=3,
    output_names=("loc_target", "loc_mask", "cls_target"),
    aliases=("_contrib_MultiBoxTarget",),
)
def _multibox_target(ctx, anchor, label, cls_pred, **attrs):
    """Parity: MultiBoxTarget (example/ssd/operator/multibox_target-inl.h).

    Anchor matching: each ground truth claims its best-IoU anchor
    (bipartite stage), then any anchor with IoU > overlap_threshold joins
    (threshold stage).  Unmatched anchors are background; hard negative
    mining keeps negative_mining_ratio * num_pos negatives ranked by
    background-class confidence (lowest background prob = hardest).
    Outputs: loc_target (N, A*4), loc_mask (N, A*4), cls_target (N, A)
    with 0 = background, cls_id + 1 = positive, -1 = ignored.
    """
    overlap_threshold = float(parse_attr(attrs.get("overlap_threshold", 0.5)))
    ignore_label = float(parse_attr(attrs.get("ignore_label", -1.0)))
    neg_ratio = float(parse_attr(attrs.get("negative_mining_ratio", -1.0)))
    neg_thresh = float(parse_attr(attrs.get("negative_mining_thresh", 0.5)))
    variances = _parse_floats(attrs.get("variances"), (0.1, 0.1, 0.2, 0.2))

    anchors = anchor.reshape(-1, 4)
    a = anchors.shape[0]

    def one_sample(lab, cls_p):
        # lab: (M, 5) [cls, x1, y1, x2, y2], cls < 0 => padding
        valid = lab[:, 0] >= 0  # (M,)
        gt = lab[:, 1:5]
        iou = _iou_matrix(anchors, gt)  # (A, M)
        iou = jnp.where(valid[None, :], iou, -1.0)

        # threshold matching: best gt per anchor
        best_gt = jnp.argmax(iou, axis=1)  # (A,)
        best_iou = jnp.max(iou, axis=1)
        matched = best_iou > overlap_threshold

        # bipartite: each valid gt claims its best anchor.  Padded gt rows
        # are routed to out-of-range index `a` so mode="drop" discards them
        # instead of racing with valid gts' scatter writes at anchor 0.
        best_anchor = jnp.where(valid, jnp.argmax(iou, axis=0), a)  # (M,)
        claimed = jnp.zeros((a,), bool).at[best_anchor].set(
            jnp.ones_like(valid), mode="drop")
        gt_of_claim = jnp.zeros((a,), jnp.int32).at[best_anchor].set(
            jnp.arange(gt.shape[0], dtype=jnp.int32), mode="drop")

        match_gt = jnp.where(claimed, gt_of_claim, best_gt)
        positive = claimed | matched

        cls_t = jnp.where(positive, lab[match_gt, 0] + 1.0, 0.0)
        loc_t = _encode_loc(anchors, gt[match_gt], variances)
        loc_t = loc_t * positive[:, None].astype(loc_t.dtype)
        loc_m = jnp.tile(positive[:, None].astype(jnp.float32), (1, 4))

        if neg_ratio > 0:
            num_pos = jnp.sum(positive.astype(jnp.float32))
            max_neg = neg_ratio * num_pos
            # hardness = max non-background confidence (higher = harder
            # negative); restrict to anchors below the mining IoU threshold
            probs = jax.nn.softmax(cls_p, axis=0)  # (num_classes+1, A)
            bg_prob = probs[0]
            neg_cand = (~positive) & (best_iou < neg_thresh)
            hardness = jnp.where(neg_cand, 1.0 - bg_prob, -1.0)
            order = jnp.argsort(-hardness)
            rank = jnp.zeros((a,), jnp.float32).at[order].set(
                jnp.arange(a, dtype=jnp.float32))
            keep_neg = neg_cand & (rank < max_neg)
            cls_t = jnp.where(positive, cls_t,
                              jnp.where(keep_neg, 0.0, ignore_label))
        return loc_t.reshape(-1), loc_m.reshape(-1), cls_t

    loc_t, loc_m, cls_t = jax.vmap(one_sample)(label, cls_pred)
    return loc_t, loc_m, cls_t


def _decode_loc(anchors, loc, variances):
    v0, v1, v2, v3 = variances
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    ax = (anchors[:, 0] + anchors[:, 2]) / 2
    ay = (anchors[:, 1] + anchors[:, 3]) / 2
    cx = loc[:, 0] * v0 * aw + ax
    cy = loc[:, 1] * v1 * ah + ay
    w = jnp.exp(jnp.clip(loc[:, 2] * v2, -10, 10)) * aw
    h = jnp.exp(jnp.clip(loc[:, 3] * v3, -10, 10)) * ah
    return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=-1)


@register(
    "MultiBoxDetection",
    arg_names=("cls_prob", "loc_pred", "anchor"),
    aliases=("_contrib_MultiBoxDetection",),
)
def _multibox_detection(ctx, cls_prob, loc_pred, anchor, **attrs):
    """Parity: MultiBoxDetection
    (example/ssd/operator/multibox_detection-inl.h): decode loc predictions
    against anchors, take per-anchor argmax class, threshold, then
    greedy NMS.  Output (N, A, 6) rows [cls_id, score, x1, y1, x2, y2]
    with cls_id = -1 for suppressed/invalid entries.

    NMS is a fixed-length lax.fori_loop over score-sorted boxes (jit-safe:
    A iterations, each a vectorized IoU row) instead of the reference's
    serial CPU/CUDA loop.
    """
    clip = parse_bool(attrs.get("clip", True))
    threshold = float(parse_attr(attrs.get("threshold", 0.01)))
    nms_threshold = float(parse_attr(attrs.get("nms_threshold", 0.5)))
    force_suppress = parse_bool(attrs.get("force_suppress", False))
    nms_topk = int(parse_attr(attrs.get("nms_topk", -1)))
    variances = _parse_floats(attrs.get("variances"), (0.1, 0.1, 0.2, 0.2))

    anchors = anchor.reshape(-1, 4)
    a = anchors.shape[0]

    def one_sample(probs, loc):
        # probs: (num_classes+1, A) with class 0 = background
        boxes = _decode_loc(anchors, loc.reshape(-1, 4), variances)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        cls_id = jnp.argmax(probs[1:], axis=0).astype(jnp.float32)  # (A,)
        score = jnp.max(probs[1:], axis=0)
        keep = score > threshold
        cls_id = jnp.where(keep, cls_id, -1.0)
        score = jnp.where(keep, score, 0.0)

        order = jnp.argsort(-score)
        cls_s, score_s, boxes_s = cls_id[order], score[order], boxes[order]

        # static nms_topk bounds both the IoU matrix (k x A instead of
        # A x A) and the sequential suppression loop: suppression only ever
        # flows *from* the top-k score-sorted rows, and past-topk entries
        # are dropped outright (parity: nms_topk, multibox_detection-inl.h)
        k = min(nms_topk, a) if nms_topk > 0 else a
        if k < a:
            cls_s = jnp.where(jnp.arange(a) < k, cls_s, -1.0)

        iou = _iou_matrix(boxes_s[:k], boxes_s)  # (k, A)

        def body(i, alive):
            same_cls = force_suppress | (cls_s == cls_s[i])
            sup = (iou[i] > nms_threshold) & same_cls & (jnp.arange(a) > i)
            kill = alive[i] & (cls_s[i] >= 0)
            return jnp.where(kill & sup, False, alive)

        alive = jax.lax.fori_loop(0, k, body, jnp.ones((a,), bool))
        cls_s = jnp.where(alive, cls_s, -1.0)
        return jnp.concatenate(
            [cls_s[:, None], score_s[:, None], boxes_s], axis=1)

    return jax.vmap(one_sample)(cls_prob, loc_pred)


# --------------------------------------------------------------------------
# Proposal — the RPN -> RoI stage of Faster R-CNN
# --------------------------------------------------------------------------
def _generate_anchors(stride, scales, ratios):
    """Base anchors around one stride cell (parity:
    example/rcnn/rcnn/processing/generate_anchor.py)."""
    base = np.array([0, 0, stride - 1, stride - 1], np.float32)
    w = base[2] - base[0] + 1
    h = base[3] - base[1] + 1
    cx = base[0] + 0.5 * (w - 1)
    cy = base[1] + 0.5 * (h - 1)
    anchors = []
    for r in ratios:
        size = w * h
        ws = np.round(np.sqrt(size / r))
        hs = np.round(ws * r)
        for s in scales:
            wss, hss = ws * s, hs * s
            anchors.append([cx - 0.5 * (wss - 1), cy - 0.5 * (hss - 1),
                            cx + 0.5 * (wss - 1), cy + 0.5 * (hss - 1)])
    return np.array(anchors, np.float32)


def _bbox_transform_inv(boxes, deltas):
    """Apply (dx, dy, dw, dh) regression deltas to boxes (x1y1x2y2)."""
    w = boxes[:, 2] - boxes[:, 0] + 1.0
    h = boxes[:, 3] - boxes[:, 1] + 1.0
    cx = boxes[:, 0] + 0.5 * (w - 1.0)
    cy = boxes[:, 1] + 0.5 * (h - 1.0)
    pcx = deltas[:, 0] * w + cx
    pcy = deltas[:, 1] * h + cy
    pw = jnp.exp(jnp.clip(deltas[:, 2], -10, 10)) * w
    ph = jnp.exp(jnp.clip(deltas[:, 3], -10, 10)) * h
    return jnp.stack([pcx - 0.5 * (pw - 1.0), pcy - 0.5 * (ph - 1.0),
                      pcx + 0.5 * (pw - 1.0), pcy + 0.5 * (ph - 1.0)],
                     axis=1)


@register(
    "Proposal",
    arg_names=("cls_prob", "bbox_pred", "im_info"),
    aliases=("_contrib_Proposal",),
)
def _proposal(ctx, cls_prob, bbox_pred, im_info, **attrs):
    """Parity: Proposal (example/rcnn operator / src/operator/contrib/
    proposal-inl.h): slide base anchors over the feature grid, decode RPN
    bbox deltas, clip to the image, drop tiny boxes, keep the
    pre_nms_top_n highest-scoring, greedy-NMS, emit post_nms_top_n RoIs
    as (batch_idx, x1, y1, x2, y2).

    TPU-native shape discipline: every stage is fixed-size — filtering is
    score masking, NMS is a fori_loop over the top-k rows of a dense IoU
    matrix, and the output is always (N*post_nms_top_n, 5) with
    suppressed slots filled by the highest-score survivor (RoIPooling of
    a duplicate row is harmless, matching the reference's pad-with-top-1).
    """
    stride = int(parse_attr(attrs.get("feature_stride", 16)))
    scales = _parse_floats(attrs.get("scales"), (8, 16, 32))
    ratios = _parse_floats(attrs.get("ratios"), (0.5, 1, 2))
    pre = int(parse_attr(attrs.get("rpn_pre_nms_top_n", 6000)))
    post = int(parse_attr(attrs.get("rpn_post_nms_top_n", 300)))
    nms_thresh = float(parse_attr(attrs.get("threshold", 0.7)))
    min_size = float(parse_attr(attrs.get("rpn_min_size", 16)))

    n, twice_a, fh, fw = cls_prob.shape
    num_anchors = twice_a // 2
    base = _generate_anchors(stride, scales, ratios)  # (A0, 4) static
    sx, sy = np.meshgrid(np.arange(fw) * stride, np.arange(fh) * stride)
    shifts = np.stack([sx.ravel(), sy.ravel(), sx.ravel(), sy.ravel()],
                      axis=1).astype(np.float32)          # (HW, 4)
    anchors = (shifts[:, None, :] + base[None, :, :]).reshape(-1, 4)
    anchors = jnp.asarray(anchors)                         # (HW*A0, 4)
    total = anchors.shape[0]
    k = min(pre, total)

    def one_sample(scores_map, deltas_map, info):
        # scores: foreground half of cls_prob — (A0, H, W) -> (HW*A0,)
        fg = scores_map[num_anchors:].reshape(num_anchors, fh, fw)
        scores = fg.transpose(1, 2, 0).reshape(-1)
        deltas = deltas_map.reshape(num_anchors, 4, fh, fw)
        deltas = deltas.transpose(2, 3, 0, 1).reshape(-1, 4)
        boxes = _bbox_transform_inv(anchors, deltas)
        boxes = jnp.stack([
            jnp.clip(boxes[:, 0], 0, info[1] - 1.0),
            jnp.clip(boxes[:, 1], 0, info[0] - 1.0),
            jnp.clip(boxes[:, 2], 0, info[1] - 1.0),
            jnp.clip(boxes[:, 3], 0, info[0] - 1.0)], axis=1)
        ms = min_size * info[2]
        valid = ((boxes[:, 2] - boxes[:, 0] + 1 >= ms)
                 & (boxes[:, 3] - boxes[:, 1] + 1 >= ms))
        scores = jnp.where(valid, scores, -1.0)

        order = jnp.argsort(-scores)[:k]
        boxes_s = boxes[order]
        scores_s = scores[order]

        def body(i, alive):
            # one IoU row per step (O(k) memory) — a dense k x k matrix
            # at the 6000-box default would cost ~144MB per sample
            row = _iou_matrix(jax.lax.dynamic_slice(boxes_s, (i, 0),
                                                    (1, 4)), boxes_s)[0]
            sup = (row > nms_thresh) & (jnp.arange(k) > i)
            si = jax.lax.dynamic_index_in_dim(scores_s, i, keepdims=False)
            ai = jax.lax.dynamic_index_in_dim(alive, i, keepdims=False)
            return jnp.where(ai & (si > 0) & sup, False, alive)

        alive = jax.lax.fori_loop(0, k, body, jnp.ones((k,), bool))
        keep_score = jnp.where(alive & (scores_s > 0), scores_s, -jnp.inf)
        sel = jnp.argsort(-keep_score)[:post]
        picked = boxes_s[sel]
        ok = keep_score[sel] > -jnp.inf
        # pad suppressed slots with the top survivor (index 0 of sel)
        picked = jnp.where(ok[:, None], picked, picked[0][None, :])
        if picked.shape[0] < post:
            # fewer candidates than post_nms_top_n: keep the contract of a
            # fixed (post, 4) output by repeating the top survivor
            pad = jnp.broadcast_to(picked[0],
                                   (post - picked.shape[0], 4))
            picked = jnp.concatenate([picked, pad], axis=0)
        return picked

    rois = jax.vmap(one_sample)(cls_prob, bbox_pred, im_info)  # (N, post, 4)
    batch_idx = jnp.repeat(jnp.arange(n, dtype=rois.dtype), post)
    return jnp.concatenate([batch_idx[:, None], rois.reshape(-1, 4)],
                           axis=1)


# --------------------------------------------------------------------------
# _CrossDeviceCopy — on TPU, GSPMD/jit inserts transfers; explicit op is
# an identity marker (parity: src/operator/cross_device_copy.cc).
# --------------------------------------------------------------------------
@register("_CrossDeviceCopy")
def _cross_device_copy(ctx, data, **attrs):
    """Parity: _CrossDeviceCopy (src/operator/cross_device_copy.cc).  The
    reference inserts this node at ctx_group boundaries; here sharding
    annotations drive ICI transfers, so the op is identity."""
    return data
