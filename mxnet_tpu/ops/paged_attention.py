"""Paged decode attention: walk the block table inside the kernel.

The PR-15 paged step *gather-materializes* a slot's whole KV table
every tick — ``pool[bt]`` + transpose + reshape rebuilds the contiguous
``(L, B, H, S, dh)`` layout before a single score is computed, paying
for every allocated page whether or not the slot's cursor ever reached
it.  This module computes the same decode attention straight off the
page pool, three lowerings behind one schedule-driven entry:

- **pallas** — the TPU kernel: grid over ``(B, H)`` (or flattened,
  a schedule knob), per-slot block table and cursors ride as scalar
  prefetch, and the kernel DMAs ONE ``(block, dh)`` VMEM tile per KV
  page from the HBM-resident pool — optionally only the pages the
  cursor has reached (``live_only``).  Decode is forward-only, so no
  custom VJP.  ``interpret=True`` runs the same kernel on CPU: the
  parity-test hook, bitwise against the gather path on aligned shapes.
- **pagewalk** — a lax lowering of the same idea for hosts without a
  TPU: a ``fori_loop`` whose trip count is the *live* page count
  (``max(cursor)``-bounded, a traced scalar — no host sync, no
  recompile), gathering ``chunk`` pages per iteration.  Same attention
  math, but loop-carried accumulation reassociates the reductions, so
  it is allclose-not-bitwise vs gather — which is why it is installed
  by the autotuner or an explicit ``MXTPU_PAGED_KERNEL=pagewalk``,
  never silently.
- **gather** — the PR-15 reference math on the materialized table, kept
  as the structural fallback behind :func:`supports` (same pattern as
  ``ops/residual_epilogue.py``) and as the search baseline every
  candidate must beat.

Schedules are plain dicts (``{"impl": ..., ...knobs}``) chosen by
``mxnet_tpu.autotune`` at ``PagedSlots`` construction — never per
tick.  See ``docs/autotune.md``.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = [
    "supports", "keysig", "default_schedule", "candidate_schedules",
    "paged_attention", "gather_tables", "make_bench_fn",
]

# the masking constant of the decode stack (== models.decode.NEG_INF;
# kept literal so this op module never imports the models package)
NEG_INF = -1e30

_PAGEWALK_CHUNKS = (1, 2, 4, 8)


def supports(block: int, dh: int, dtype) -> bool:
    """Can the Pallas kernel tile ``(block, dh)`` KV pages?  One page is
    one VMEM tile, so both dims must fill whole 8-row sublanes; wider
    lane padding is Mosaic's job.  Ragged shapes fall back to gather."""
    if jnp.dtype(dtype) not in (jnp.dtype(jnp.float32),
                                jnp.dtype(jnp.bfloat16)):
        return False
    return block % 8 == 0 and dh % 8 == 0 and block > 0 and dh > 0


def keysig(B: int, H: int, M: int, block: int, dh: int, dtype) -> str:
    """The autotuner shape signature of one decode-step workload."""
    return "b%dh%dm%dk%dd%d_%s" % (B, H, M, block, dh,
                                   jnp.dtype(dtype).name)


def default_schedule(platform: str, block: int, dh: int, dtype) -> dict:
    """What runs with no tuned winner: the kernel on a TPU whose shape
    qualifies, the bitwise gather path everywhere else."""
    if platform == "tpu" and supports(block, dh, dtype):
        return {"impl": "pallas", "grid": "bh", "live_only": True}
    return {"impl": "gather"}


def candidate_schedules(platform: str, block: int, dh: int, M: int,
                        dtype) -> list:
    """The search space for one shape signature.  Gather is always a
    candidate (the winner can never lose to not tuning); pagewalk chunk
    sizes must divide the block-table width; pallas variants (grid
    layout x live-page DMA) only where the compiled kernel can run."""
    cands = [{"impl": "gather"}]
    for ch in _PAGEWALK_CHUNKS:
        if ch <= M and M % ch == 0:
            cands.append({"impl": "pagewalk", "chunk": ch})
    if platform == "tpu" and supports(block, dh, dtype):
        for grid in ("bh", "flat"):
            for live in (True, False):
                cands.append({"impl": "pallas", "grid": grid,
                              "live_only": live})
    return cands


# ---------------------------------------------------------------- gather
def gather_tables(pool, bt, block: int):
    """``(P, L, H, blk, dh)[bt (B, M)] -> (L, B, H, M*blk, dh)`` — the
    PR-15 materialization, shared here so the op-level baseline and the
    serving gather path stay the same expression."""
    B, M = bt.shape
    _P, L, H, blk, dh = pool.shape
    t = pool[bt]                                 # (B, M, L, H, blk, dh)
    t = t.transpose(2, 0, 3, 1, 4, 5)            # (L, B, H, M, blk, dh)
    return t.reshape(L, B, H, M * block, dh)


def _attend(q, kc, vc, cursor):
    """The reference decode attention over a contiguous table slice —
    exactly the PR-15 step math (bitwise anchor for every lowering)."""
    S = kc.shape[2]
    dh = q.shape[-1]
    valid = jnp.arange(S)[None, :] <= cursor[:, None]
    scores = jnp.einsum("bhnd,bhsd->bhns", q, kc) \
        / jnp.sqrt(jnp.asarray(dh, q.dtype))
    scores = jnp.where(valid[:, None, None, :], scores, NEG_INF)
    att = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhns,bhsd->bhnd", att, vc)


def _gather_attention(q, pool_k, pool_v, bt, cursor, layer, block):
    kc = gather_tables(pool_k, bt, block)[layer]
    vc = gather_tables(pool_v, bt, block)[layer]
    return _attend(q, kc, vc, cursor)


# -------------------------------------------------------------- pagewalk
def _pagewalk_attention(q, pool_k, pool_v, bt, cursor, layer, block,
                        chunk):
    B, H, _n, dh = q.shape
    M = bt.shape[1]
    ch = int(chunk)
    if ch < 1 or M % ch:
        ch = 1                                   # always-valid fallback
    S = M * block
    qs = q[:, :, 0, :]                           # (B, H, dh)
    # live trip count: pages any slot's cursor has reached — a traced
    # scalar, so raggedness never retraces and never syncs the host
    n_live = (jnp.max(cursor) + block) // block
    n_it = (n_live + ch - 1) // ch
    scale = jnp.sqrt(jnp.asarray(dh, q.dtype))
    valid = (jnp.arange(S)[None, :] <= cursor[:, None])[:, None, :]

    def scores_body(it, buf):
        pgs = jax.lax.dynamic_slice(bt, (0, it * ch), (B, ch))
        k = pool_k[pgs, layer]                   # (B, ch, H, blk, dh)
        s = jnp.einsum("bhd,bchkd->bhck", qs, k) \
            .reshape(B, H, ch * block) / scale
        return jax.lax.dynamic_update_slice(buf, s, (0, 0, it * ch * block))

    scores = jax.lax.fori_loop(
        0, n_it, scores_body, jnp.full((B, H, S), NEG_INF, q.dtype))
    scores = jnp.where(valid, scores, NEG_INF)
    att = jax.nn.softmax(scores, axis=-1)        # dead pages: exact 0

    def ctx_body(it, acc):
        pgs = jax.lax.dynamic_slice(bt, (0, it * ch), (B, ch))
        v = pool_v[pgs, layer]
        a = jax.lax.dynamic_slice(
            att, (0, 0, it * ch * block),
            (B, H, ch * block)).reshape(B, H, ch, block)
        return acc + jnp.einsum("bhck,bchkd->bhd", a, v)

    ctx = jax.lax.fori_loop(
        0, n_it, ctx_body, jnp.zeros((B, H, dh), q.dtype))
    return ctx[:, :, None, :]


# ---------------------------------------------------------------- pallas
def _pallas_attention(q, pool_k, pool_v, bt, cursor, layer, block,
                      schedule, interpret):
    B, H, _n, dh = q.shape
    M = bt.shape[1]
    S = M * block
    flat = schedule.get("grid") == "flat"
    live_only = bool(schedule.get("live_only", True))

    def kernel(bt_ref, cur_ref, q_ref, pk_ref, pv_ref, o_ref,
               kbuf, vbuf, sem):
        if flat:
            i = pl.program_id(0)
            b, h = i // H, i % H
        else:
            b, h = pl.program_id(0), pl.program_id(1)
        cur = cur_ref[b]
        if live_only:
            # skipped (dead) pages leave vbuf unread-after-write garbage;
            # their attention weights are exact zeros, but 0 * NaN is
            # NaN — zero the value tiles so dead pages contribute exact
            # zeros like the gather path.  kbuf garbage is safe: dead
            # scores are replaced wholesale by NEG_INF below.
            vbuf[...] = jnp.zeros((S, dh), vbuf.dtype)
        for m in range(M):
            def _dma(m=m):
                pg = bt_ref[b, m]
                cp = pltpu.make_async_copy(
                    pk_ref.at[pg, layer, h],
                    kbuf.at[pl.ds(m * block, block)], sem)
                cp.start()
                cp.wait()
                cp = pltpu.make_async_copy(
                    pv_ref.at[pg, layer, h],
                    vbuf.at[pl.ds(m * block, block)], sem)
                cp.start()
                cp.wait()
            if live_only:
                pl.when(m * block <= cur)(_dma)
            else:
                _dma()
        qv = q_ref[0, 0]                                 # (1, dh)
        scores = jnp.einsum("nd,sd->ns", qv, kbuf[...]) \
            / jnp.sqrt(jnp.asarray(dh, qv.dtype))
        s_idx = jax.lax.broadcasted_iota(jnp.int32, (1, S), 1)
        scores = jnp.where(s_idx <= cur, scores, NEG_INF)
        att = jax.nn.softmax(scores, axis=-1)
        o_ref[0, 0] = jnp.einsum("ns,sd->nd", att, vbuf[...])

    if flat:
        grid = (B * H,)
        qmap = lambda i, *_: (i // H, i % H, 0, 0)
    else:
        grid = (B, H)
        qmap = lambda b, h, *_: (b, h, 0, 0)
    gs = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,                   # bt, cursor
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, 1, dh), qmap),
            pl.BlockSpec(memory_space=pltpu.ANY),   # pool_k stays in HBM
            pl.BlockSpec(memory_space=pltpu.ANY),   # pool_v stays in HBM
        ],
        out_specs=pl.BlockSpec((1, 1, 1, dh), qmap),
        scratch_shapes=[
            pltpu.VMEM((S, dh), q.dtype),
            pltpu.VMEM((S, dh), q.dtype),
            pltpu.SemaphoreType.DMA,
        ])
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B, H, 1, dh), q.dtype),
        grid_spec=gs,
        interpret=interpret,
    )(bt.astype(jnp.int32), cursor.astype(jnp.int32), q, pool_k, pool_v)


# ------------------------------------------------------------------ entry
def paged_attention(q, pool_k, pool_v, bt, cursor, layer, *, block,
                    schedule=None, interpret=False):
    """Decode attention for one layer straight off the page pool.

    ``q``: ``(B, H, 1, dh)``; ``pool_k``/``pool_v``: ``(P, L, H, block,
    dh)``; ``bt``: ``(B, M)`` page ids; ``cursor``: ``(B,)`` absolute
    positions (attend over ``[0, cursor[b]]``).  Returns ``(B, H, 1,
    dh)``.  ``schedule`` picks the lowering (``None`` = gather); shapes
    the Pallas gate rejects fall back to gather even when forced —
    ragged shapes never crash, they just take the reference path."""
    sched = schedule or {"impl": "gather"}
    impl = sched.get("impl", "gather")
    if impl == "pallas" and not supports(block, q.shape[-1], q.dtype):
        impl = "gather"
    if impl == "pallas":
        # a TPU kernel forced onto a host without one runs interpreted
        # (the parity tool) instead of failing to lower
        interp = bool(interpret or sched.get("interpret", False)
                      or jax.default_backend() != "tpu")
        return _pallas_attention(
            q, pool_k, pool_v, bt, cursor, layer, block, sched, interp)
    if impl == "pagewalk":
        return _pagewalk_attention(q, pool_k, pool_v, bt, cursor, layer,
                                   block, sched.get("chunk", 1))
    return _gather_attention(q, pool_k, pool_v, bt, cursor, layer, block)


# ------------------------------------------------------------- benchmark
def make_bench_fn(schedule, *, B, H, M, block, dh, L, dtype=jnp.float32):
    """A thunk timing one decode step's attention (all ``L`` layers)
    under ``schedule``, on a synthetic steady-state pool: per-slot
    cursors spread raggedly across the context (mean ~half full — the
    regime a serving mix actually sits in), block tables dense.  The
    gather baseline amortizes ONE materialization over all layers,
    exactly like the serving step, so the comparison is never rigged
    against it.  Used by the ``PagedSlots`` tuning call site and
    ``bench.py::_autotune_micro``."""
    S = M * block
    P = B * M + 1
    rs = np.random.RandomState(0)
    pool_k = jnp.asarray(rs.normal(size=(P, L, H, block, dh))
                         .astype(jnp.dtype(dtype).name))
    pool_v = jnp.asarray(rs.normal(size=(P, L, H, block, dh))
                         .astype(jnp.dtype(dtype).name))
    q = jnp.asarray(rs.normal(size=(B, H, 1, dh))
                    .astype(jnp.dtype(dtype).name))
    bt = jnp.asarray(
        rs.permutation(np.arange(1, P))[:B * M].reshape(B, M)
        .astype(np.int32))
    cursor = jnp.asarray(np.linspace(block, S - 1, B).astype(np.int32))

    sched = schedule or {"impl": "gather"}
    # the arrays are jit ARGUMENTS, not closure captures: captured
    # device values become compile-time constants and XLA folds part of
    # the work into the executable, timing a fiction
    if sched.get("impl", "gather") == "gather":
        def step(q, pool_k, pool_v, bt, cursor):
            kc = gather_tables(pool_k, bt, block)
            vc = gather_tables(pool_v, bt, block)
            return sum(_attend(q, kc[i], vc[i], cursor)
                       for i in range(L))
    else:
        def step(q, pool_k, pool_v, bt, cursor):
            return sum(
                paged_attention(q, pool_k, pool_v, bt, cursor, i,
                                block=block, schedule=sched)
                for i in range(L))
    jitted = jax.jit(step)
    return lambda: jitted(q, pool_k, pool_v, bt, cursor)
