"""Fused residual-epilogue kernel: (x + shortcut) * scale + bias -> ReLU.

The TVM argument (arXiv:1802.04799) in one op: the ``conv3 + shortcut``
tail of a ResNet bottleneck is a chain XLA leaves as several HBM-bound
elementwise kernels around the convolution — per-channel affine
(inference BatchNorm folded to scale/bias, or any affine), the residual
add, and the ReLU each re-read the activation.  This kernel computes
the whole epilogue in ONE NHWC Pallas pass over VMEM tiles: each
``(block_rows, C)`` tile of the ``(N*H*W, C)`` view is read once,
combined, and written once.

Three lowerings behind one ``custom_vjp`` function:

- **pallas**: the TPU kernel (``ctx.platform == "tpu"`` and the shape
  qualifies — C a lane multiple, rows tileable);
- **pallas interpret**: the same kernel interpreted on CPU (parity
  tests);
- **lax**: the plain jnp expression — CPU default and the fallback for
  shapes the kernel does not tile.  Same math, so tier-1 (CPU) runs
  identically whichever path a platform picks.

The row-block size is the autotuner's first tuned knob (ISSUE 18):
``_block_rows_for`` consults ``mxnet_tpu.autotune.schedule_for`` (the
pure lookup plane — safe at trace time) and :func:`tune` is the
bind-time search call site that installs a per-(rows, C, dtype) winner
in the ``MXTPU_SCHEDULE_CACHE``.

The backward is lax (elementwise selects + two per-channel reductions
— XLA fuses these fine; the win of the hand kernel is the forward,
which sits between two convolutions in the hot path).  The custom VJP
exists so autodiff never differentiates *through* the Pallas body.

Graph entry points (matched by passes/residual_epilogue.py so model
code does not change):

- ``_residual_epilogue(data, shortcut)``: plain ``relu(x + s)``.
- ``_residual_epilogue_bn(data, shortcut, gamma, beta | mean, var)``:
  ``relu(BatchNorm(x + s))``.  Train-mode batch statistics cannot fold
  into a per-channel affine, so with ``is_train`` (and no
  use_global_stats) the op REPLAYS the exact unfused composite —
  bit-identical math, aux updates included; inference folds the moving
  stats into (scale, bias) and runs the fused kernel.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from ..base import parse_attr, parse_bool
from .registry import register

# row-block of the (rows, C) view each grid step processes; rows are
# N*H*W of an NHWC activation, so real batches divide 256 comfortably.
# The DEFAULT — the autotuner's first tuned knob (ISSUE 18) can
# override it per (rows, C, dtype) through the schedule cache.
_BLOCK_ROWS = 256
# the search space tune() measures: default included (a search can
# never lose to not searching), 512 gives headroom above the default
_CANDIDATE_BLOCK_ROWS = (512, 256, 128, 64, 32, 16, 8)


def supports(rows: int, channels: int) -> bool:
    """Can the Pallas kernel tile this (rows, C) view without padding?
    C must fill whole 128-wide lanes; rows must split into row blocks
    (a multiple of 8 sublanes).  ResNet-50's residual tails (C = 256 /
    512 / 1024 / 2048, rows = N*H*W) all qualify."""
    if channels % 128 != 0:
        return False
    return rows % _default_block_rows(rows) == 0 and rows >= 8


def _default_block_rows(rows: int) -> int:
    if rows % _BLOCK_ROWS == 0:
        return _BLOCK_ROWS
    for b in (128, 64, 32, 16, 8):
        if rows % b == 0:
            return b
    return rows  # not tileable; supports() returns False upstream


def _keysig(rows: int, channels: int, dtype) -> str:
    return "r%dc%d_%s" % (rows, channels, jnp.dtype(dtype).name)


def _block_rows_for(rows: int, channels: int, dtype) -> int:
    """The row block the kernel tiles with: the tuned winner for this
    (rows, C, dtype) when the schedule cache holds one, the static
    default otherwise.  ``schedule_for`` is the autotuner's PURE plane
    — safe here even though this runs at trace time inside the jitted
    graph."""
    from .. import autotune as _autotune

    default = _default_block_rows(rows)
    sched = _autotune.schedule_for(
        "residual_epilogue", _keysig(rows, channels, dtype),
        {"block_rows": default})
    br = int(sched.get("block_rows", default))
    return br if (br > 0 and rows % br == 0) else default


def _epilogue_kernel(x_ref, s_ref, sc_ref, b_ref, o_ref):
    x = x_ref[...].astype(jnp.float32)
    s = s_ref[...].astype(jnp.float32)
    sc = sc_ref[...].astype(jnp.float32)   # (1, C), broadcasts over rows
    b = b_ref[...].astype(jnp.float32)
    o_ref[...] = jnp.maximum((x + s) * sc + b, 0.0).astype(o_ref.dtype)


def _pallas_fwd(x2, s2, scale, bias, interpret, block_rows=None):
    rows, c = x2.shape
    br = (int(block_rows) if block_rows
          else _block_rows_for(rows, c, x2.dtype))
    sc2 = scale.reshape(1, c)
    b2 = bias.reshape(1, c)
    return pl.pallas_call(
        _epilogue_kernel,
        grid=(rows // br,),
        in_specs=[
            pl.BlockSpec((br, c), lambda i: (i, 0)),
            pl.BlockSpec((br, c), lambda i: (i, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((br, c), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, c), x2.dtype),
        interpret=interpret,
    )(x2, s2, sc2, b2)


def _lax_fwd(x, s, scale, bias, channel_axis):
    bshape = [1] * x.ndim
    bshape[channel_axis] = x.shape[channel_axis]
    t = ((x + s).astype(jnp.float32) * scale.reshape(bshape)
         + bias.reshape(bshape))
    return jnp.maximum(t, 0.0).astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _epilogue(x, s, scale, bias, channel_axis, use_pallas, interpret):
    out, _ = _epilogue_fwd(x, s, scale, bias, channel_axis, use_pallas,
                           interpret)
    return out


def _epilogue_fwd(x, s, scale, bias, channel_axis, use_pallas, interpret):
    # trace-ok: use_pallas/channel_axis/interpret are custom_vjp
    # nondiff_argnums — static Python values at trace time, never tracers
    if use_pallas and channel_axis == x.ndim - 1:
        c = x.shape[-1]
        rows = int(np.prod(x.shape[:-1]))
        x2 = x.reshape(rows, c)
        s2 = s.reshape(rows, c)
        out = _pallas_fwd(x2, s2, scale, bias, interpret).reshape(x.shape)
    else:
        out = _lax_fwd(x, s, scale, bias, channel_axis)
    return out, (x, s, scale, out)


def _epilogue_bwd(channel_axis, use_pallas, interpret, res, g):
    x, s, scale, out = res
    bshape = [1] * x.ndim
    bshape[channel_axis] = x.shape[channel_axis]
    axes = tuple(a for a in range(x.ndim) if a != channel_axis)
    mask = (out > 0)
    g32 = jnp.where(mask, g.astype(jnp.float32), 0.0)
    gs = g32 * scale.reshape(bshape).astype(jnp.float32)
    total32 = (x + s).astype(jnp.float32)
    dscale = jnp.sum(g32 * total32, axis=axes)
    # bias is not saved (its value never enters the backward); its grad
    # adopts the scale's dtype — the pair is always allocated together
    dbias = jnp.sum(g32, axis=axes)
    return (gs.astype(x.dtype), gs.astype(s.dtype),
            dscale.astype(scale.dtype), dbias.astype(scale.dtype))


_epilogue.defvjp(_epilogue_fwd, _epilogue_bwd)


def residual_epilogue(x, s, scale=None, bias=None, channel_axis=-1,
                      platform=None, impl="auto", interpret=False):
    """Functional entry: ``relu((x + s) * scale + bias)``.

    ``impl``: ``auto`` (Pallas on TPU when the shape tiles, lax
    otherwise), ``lax``, ``pallas``, ``pallas_interpret`` (the kernel
    interpreted on CPU — the parity-test hook)."""
    channel_axis = channel_axis % x.ndim
    c = x.shape[channel_axis]
    if scale is None:
        scale = jnp.ones((c,), jnp.float32)
    if bias is None:
        bias = jnp.zeros((c,), jnp.float32)
    rows = int(np.prod(x.shape)) // max(c, 1)
    if impl == "pallas_interpret":
        use_pallas, interpret = True, True
    elif impl == "pallas":
        use_pallas = True
    elif impl == "lax":
        use_pallas = False
    else:  # auto: hand kernel only where it wins and tiles
        use_pallas = (platform == "tpu" and channel_axis == x.ndim - 1
                      and supports(rows, c))
    if use_pallas and (channel_axis != x.ndim - 1 or not supports(rows, c)):
        use_pallas = False  # shape gate even when forced (ragged shapes)
    return _epilogue(x, s, scale, bias, channel_axis, use_pallas,
                     bool(interpret))


def tune(rows, channels, dtype=jnp.float32, interpret=None):
    """Search ``block_rows`` for the ``(rows, C)`` epilogue view and
    install the winner in the schedule cache (a bind-time call site —
    benches and tests call this; the traced kernel only ever does the
    pure ``schedule_for`` lookup).  On a host without a TPU the kernel
    is measured in interpret mode — tuning the parity tool honestly
    rather than pretending to time hardware it does not have.  Returns
    the winning schedule dict (``{"block_rows": N}``)."""
    from .. import autotune as _autotune

    rows, channels = int(rows), int(channels)
    if not supports(rows, channels):
        return {"block_rows": _default_block_rows(rows)}
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    name = jnp.dtype(dtype).name
    rs = np.random.RandomState(0)
    x2 = jnp.asarray(rs.normal(size=(rows, channels)).astype(name))
    s2 = jnp.asarray(rs.normal(size=(rows, channels)).astype(name))
    scale = jnp.asarray(rs.normal(size=(channels,)).astype(np.float32))
    bias = jnp.asarray(rs.normal(size=(channels,)).astype(np.float32))

    def bench(cand):
        br = int(cand["block_rows"])
        if br <= 0 or rows % br:
            raise ValueError("block_rows %d does not tile %d rows"
                             % (br, rows))
        fn = jax.jit(functools.partial(
            _pallas_fwd, interpret=bool(interpret), block_rows=br))
        return lambda: fn(x2, s2, scale, bias)

    return _autotune.ensure(
        "residual_epilogue", _keysig(rows, channels, dtype),
        {"block_rows": _default_block_rows(rows)},
        [{"block_rows": b} for b in _CANDIDATE_BLOCK_ROWS
         if b <= rows and rows % b == 0],
        bench, warmup=1, best_of=3)


# ---------------------------------------------------------------------------
# op registrations (graph entry points for passes/residual_epilogue.py)
# ---------------------------------------------------------------------------
def _channel_axis(attrs, ndim):
    return ndim - 1 if attrs.get("__layout__") == "NHWC" else 1


@register("_residual_epilogue", arg_names=("data", "shortcut"))
def _residual_epilogue_op(ctx, data, shortcut, **attrs):
    """``relu(data + shortcut)`` as one fused epilogue (the affine is
    identity).  Lowering picked per ctx.platform; ``impl`` overrides."""
    ax = _channel_axis(attrs, data.ndim)
    return residual_epilogue(
        data, shortcut, channel_axis=ax, platform=ctx.platform,
        impl=str(attrs.get("impl", "auto")))


def _epi_bn_params(attrs, data_shape, *rest):
    if data_shape is None:
        raise TypeError("need data shape")
    ax = _channel_axis(attrs, len(data_shape))
    c = data_shape[ax]
    return {"gamma": (c,), "beta": (c,),
            "moving_mean": (c,), "moving_var": (c,)}


@register(
    "_residual_epilogue_bn",
    arg_names=("data", "shortcut", "gamma", "beta"),
    param_names=("gamma", "beta"),
    aux_names=("moving_mean", "moving_var"),
    infer_params=_epi_bn_params,
)
def _residual_epilogue_bn_op(ctx, data, shortcut, gamma, beta,
                             moving_mean, moving_var, **attrs):
    """``relu(BatchNorm(data + shortcut))``.

    Train mode (no use_global_stats) REPLAYS the exact unfused
    composite — the batch statistics cannot fold into a static affine,
    and replaying the same op fns keeps the rewrite bit-identical to
    the pass-off graph (the parity contract of passes/).  Inference
    folds the moving stats into (scale, bias) and runs the fused
    kernel; aux states pass through unchanged, like eval-mode
    BatchNorm."""
    from . import registry as _registry

    use_global = parse_bool(attrs.get("use_global_stats", False))
    if ctx.is_train and not use_global:
        total = data + shortcut
        out, aux_updates = _registry.get("BatchNorm").fn(
            ctx, total, gamma, beta, moving_mean, moving_var, **attrs)
        return jax.nn.relu(out), aux_updates
    eps = float(parse_attr(attrs.get("eps", 1e-3)))
    fix_gamma = parse_bool(attrs.get("fix_gamma", True))
    g32 = (jnp.ones_like(gamma) if fix_gamma else gamma).astype(jnp.float32)
    scale = g32 * jax.lax.rsqrt(moving_var.astype(jnp.float32) + eps)
    bias = beta.astype(jnp.float32) - moving_mean.astype(jnp.float32) * scale
    ax = _channel_axis(attrs, data.ndim)
    out = residual_epilogue(
        data, shortcut, scale, bias, channel_axis=ax,
        platform=ctx.platform, impl=str(attrs.get("impl", "auto")))
    return out, (moving_mean, moving_var)
