"""Output/loss ops with MXNet backward semantics.

The reference's *Output ops are identity-ish in forward and source their
own gradient in backward, ignoring the incoming head gradient (reference:
src/operator/softmax_output-inl.h:136 ``grad = (out - label) * grad_scale``;
src/operator/regression_output-inl.h:70-79 ``grad = grad_scale/num_output *
BackwardOp(out, label)``).  We reproduce this exactly with jax.custom_vjp:
the vjp discards the cotangent and emits the op-defined gradient, so
``executor.backward()`` with default ones head-grads matches the reference
bit-for-bit in structure.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import MXNetError, parse_attr, parse_bool
from .registry import register


def _norm_enum(attrs):
    n = attrs.get("normalization", "null")
    return n if n in ("null", "batch", "valid") else "null"


def _softmax_label_shape(attrs, data_shape, *rest):
    """Label shape inference (parity: SoftmaxOutputProp::InferShape):
    (N,) normally, (N, d...) for multi_output."""
    if parse_bool(attrs.get("multi_output", False)):
        return {"label": (data_shape[0],) + tuple(data_shape[2:])}
    return {"label": (data_shape[0],)}


def _regression_label_shape(attrs, data_shape, *rest):
    """Parity: RegressionOutputProp::InferShape — label matches data, with
    the 1-D special case for (N,1) outputs (regression_output-inl.h:108)."""
    if len(data_shape) == 2 and data_shape[1] == 1:
        return {"label": (data_shape[0],)}
    return {"label": tuple(data_shape)}


@register(
    "SoftmaxOutput",
    arg_names=("data", "label"),
    aliases=("Softmax",),
    infer_params=_softmax_label_shape,
)
def _softmax_output(ctx, data, label, **attrs):
    """Parity: SoftmaxOutput (src/operator/softmax_output-inl.h).

    Forward: softmax over axis 1 (multi_output softmaxes channel axis for
    (N,C,d...) inputs).  Backward: (p - onehot(label)) * grad_scale with
    null/batch/valid normalization and use_ignore masking — head gradient
    ignored (reference :136,:156-176,:203-224).  ``Softmax`` is the
    deprecated alias the reference keeps (softmax_output.cc registration).
    """
    grad_scale = float(parse_attr(attrs.get("grad_scale", 1.0)))
    ignore_label = float(parse_attr(attrs.get("ignore_label", -1.0)))
    use_ignore = parse_bool(attrs.get("use_ignore", False))
    multi_output = parse_bool(attrs.get("multi_output", False))
    normalization = _norm_enum(attrs)
    preserve_shape = parse_bool(attrs.get("preserve_shape", False))

    @jax.custom_vjp
    def fwd(data, label):
        return _softmax_fwd(data)

    def _softmax_fwd(data):
        if multi_output or preserve_shape or data.ndim <= 2:
            return jax.nn.softmax(data, axis=1 if data.ndim > 1 else 0)
        # default: flatten to (N, C)
        return jax.nn.softmax(data.reshape(data.shape[0], -1), axis=1).reshape(data.shape)

    def fwd_fwd(data, label):
        out = _softmax_fwd(data)
        return out, (out, label)

    def fwd_bwd(res, g):
        out, label = res
        if multi_output and out.ndim > 2:
            # label (N, d...) indexes channel axis 1
            lab = label.astype(jnp.int32)
            onehot = jax.nn.one_hot(lab, out.shape[1], dtype=out.dtype, axis=1)
            grad = out - onehot
            mask = (label != ignore_label) if use_ignore else None
        else:
            lab = label.reshape(-1).astype(jnp.int32)
            onehot = jax.nn.one_hot(lab, out.shape[-1], dtype=out.dtype)
            grad = out.reshape(lab.shape[0], -1) - onehot
            mask = (label.reshape(-1) != ignore_label) if use_ignore else None
            if mask is not None:
                grad = grad * mask[:, None].astype(grad.dtype)
            grad = grad.reshape(out.shape)
        if multi_output and mask is not None:
            grad = grad * jnp.expand_dims(mask, 1).astype(grad.dtype)
        scale = grad_scale
        if normalization == "batch":
            grad = grad / out.shape[0]
        elif normalization == "valid" and mask is not None:
            valid = jnp.maximum(jnp.sum(mask.astype(grad.dtype)), 1.0)
            grad = grad / valid
        elif normalization == "valid":
            # no ignore mask: every label is valid — divide by the TOTAL
            # label count (softmax_output-inl.h kValid), not the batch;
            # for multi_output that is N*d labels
            grad = grad / float(label.size)
        return (scale * grad, jnp.zeros_like(label))

    fwd.defvjp(fwd_fwd, fwd_bwd)
    return fwd(data, label)


def _regression_output(name, fwd_fn, bwd_fn, doc):
    @register(name, arg_names=("data", "label"),
              infer_params=_regression_label_shape)
    def _impl(ctx, data, label, **attrs):
        grad_scale = float(parse_attr(attrs.get("grad_scale", 1.0)))

        @jax.custom_vjp
        def fwd(data, label):
            return fwd_fn(data)

        def f(data, label):
            return fwd_fn(data), (fwd_fn(data), label)

        def b(res, g):
            out, label = res
            # reference: grad_scale / num_output where num_output =
            # label.size/batch (regression_output-inl.h:70-79)
            num_output = max(int(jnp.size(label)) // label.shape[0], 1) \
                if hasattr(label, "shape") and label.ndim > 0 else 1
            lab = label.reshape(out.shape)
            grad = bwd_fn(out, lab) * (grad_scale / num_output)
            return (grad, jnp.zeros_like(label))

        fwd.defvjp(f, b)
        return fwd(data, label)

    _impl.__doc__ = doc
    return _impl


_regression_output(
    "LinearRegressionOutput",
    lambda d: d,
    lambda o, l: o - l,
    "Parity: LinearRegressionOutput (regression_output-inl.h, kLinear).",
)
_regression_output(
    "LogisticRegressionOutput",
    jax.nn.sigmoid,
    lambda o, l: o - l,
    "Parity: LogisticRegressionOutput (regression_output-inl.h, kLogistic).",
)
_regression_output(
    "MAERegressionOutput",
    lambda d: d,
    lambda o, l: jnp.sign(o - l),
    "Parity: MAERegressionOutput (regression_output-inl.h, kMAE).",
)


@register("SVMOutput", arg_names=("data", "label"),
          infer_params=_softmax_label_shape)
def _svm_output(ctx, data, label, **attrs):
    """Parity: SVMOutput (src/operator/svm_output-inl.h); hinge-loss
    gradient (L1 or squared) with margin + regularization_coefficient."""
    margin = float(parse_attr(attrs.get("margin", 1.0)))
    reg = float(parse_attr(attrs.get("regularization_coefficient", 1.0)))
    use_linear = parse_bool(attrs.get("use_linear", False))

    @jax.custom_vjp
    def fwd(data, label):
        return data

    def f(data, label):
        return data, (data, label)

    def b(res, g):
        data, label = res
        lab = label.reshape(-1).astype(jnp.int32)
        onehot = jax.nn.one_hot(lab, data.shape[1], dtype=data.dtype)
        sign = 2.0 * onehot - 1.0  # +1 at true class, -1 elsewhere
        viol = (margin - sign * data) > 0
        if use_linear:  # L1-SVM: grad = -sign where margin violated
            grad = jnp.where(viol, -sign * reg, 0.0)
        else:  # L2-SVM: grad = -2*(margin - sign*x)*sign where violated
            grad = jnp.where(viol, -2.0 * (margin - sign * data) * sign * reg, 0.0)
        return (grad.astype(data.dtype), jnp.zeros_like(label))

    fwd.defvjp(f, b)
    return fwd(data, label)


@register("MakeLoss")
def _make_loss(ctx, data, **attrs):
    """Parity: MakeLoss (src/operator/make_loss-inl.h): identity forward,
    backward = grad_scale (normalized) regardless of head gradient."""
    grad_scale = float(parse_attr(attrs.get("grad_scale", 1.0)))
    normalization = _norm_enum(attrs)

    @jax.custom_vjp
    def fwd(data):
        return data

    def f(data):
        return data, data.shape

    def b(shape, g):
        import math

        scale = grad_scale
        if normalization == "batch":
            scale = scale / shape[0]
        elif normalization == "valid":
            scale = scale / max(math.prod(shape), 1)
        return (jnp.full(shape, scale, dtype=jnp.float32),)

    fwd.defvjp(f, b)
    return fwd(data)


@register("softmax_cross_entropy", arg_names=("data", "label"),
          infer_params=_softmax_label_shape)
def _softmax_cross_entropy(ctx, data, label, **attrs):
    """Parity: softmax_cross_entropy (src/operator/loss_binary_op.cc) —
    scalar summed CE between softmax(data) and integer labels."""
    logp = jax.nn.log_softmax(data, axis=-1)
    lab = label.reshape(-1).astype(jnp.int32)
    ce = -logp[jnp.arange(data.shape[0]), lab]
    return jnp.sum(ce).reshape((1,))


@register("SoftmaxActivation")
def _softmax_activation(ctx, data, **attrs):
    """Parity: SoftmaxActivation (src/operator/softmax_activation-inl.h);
    mode instance (softmax over trailing dims flattened) or channel."""
    mode = attrs.get("mode", "instance")
    if mode == "channel":
        return jax.nn.softmax(data, axis=1)
    return jax.nn.softmax(data.reshape(data.shape[0], -1), axis=1).reshape(data.shape)


def _kl_params(attrs, data_shape, *rest):
    return {"moving_avg": (data_shape[1],)}


@register(
    "IdentityAttachKLSparseReg",
    arg_names=("data",),
    aux_names=("moving_avg",),
    infer_params=_kl_params,
)
def _identity_attach_kl(ctx, data, moving_avg, **attrs):
    """Parity: IdentityAttachKLSparseReg
    (src/operator/identity_attach_KL_sparse_reg-inl.h): identity forward;
    backward adds KL-divergence sparsity penalty gradient computed from the
    moving average activation."""
    penalty = float(parse_attr(attrs.get("penalty", 0.001)))
    sparseness_target = float(parse_attr(attrs.get("sparseness_target", 0.1)))
    momentum = float(parse_attr(attrs.get("momentum", 0.9)))

    avg = jnp.mean(data, axis=tuple(i for i in range(data.ndim) if i != 1))
    new_avg = moving_avg * momentum + avg * (1 - momentum) if ctx.is_train else moving_avg

    @jax.custom_vjp
    def fwd(data, mavg):
        return data

    def f(data, mavg):
        return data, (data.shape, mavg)

    def b(res, g):
        shape, mavg = res
        rho = jnp.clip(mavg, 1e-6, 1 - 1e-6)
        kl_grad = penalty * (
            -sparseness_target / rho + (1.0 - sparseness_target) / (1.0 - rho)
        )
        bshape = (1, -1) + (1,) * (len(shape) - 2)
        return (g + kl_grad.reshape(bshape), jnp.zeros_like(mavg))

    fwd.defvjp(f, b)
    return fwd(data, moving_avg), (jax.lax.stop_gradient(new_avg),)


def token_nll(logits, labels):
    """Mean next-token negative log-likelihood on [..., T, V] logits vs
    [..., T] integer (or float-encoded) labels — the functional LM loss
    every workload/test/tool shares (examples/transformer-lm re-exports
    it; parity: the loss SoftmaxOutput computes implicitly in backward,
    reference src/operator/softmax_output-inl.h:224)."""
    lp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(
        lp, labels.astype(jnp.int32)[..., None], axis=-1).mean()
