"""Neural-network layer ops.

Parity: the reference's layer-op families in src/operator/*-inl.h
(Convolution, FullyConnected, BatchNorm, Pooling, Activation, LeakyReLU,
Dropout, LRN, Concat, SliceChannel, InstanceNorm, L2Normalization,
UpSampling, Pad, Crop — SURVEY.md Appendix A).  TPU-first mapping:

- Convolution  -> lax.conv_general_dilated (MXU); user-facing layout stays
  NCHW for API parity, XLA picks physical tiling (SURVEY.md §7 layout note).
- Pooling      -> lax.reduce_window.
- cuDNN autotune (cudnn_*-inl.h) has no analogue: XLA autotunes.
- All kernels fuse with surrounding elementwise ops at XLA level, replacing
  the reference's hand-fused mshadow expressions.
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError, conv_precision, mxu_precision, normalize_tuple, parse_attr, parse_bool
from .registry import register

# ---------------------------------------------------------------------------
# Convolution / Deconvolution
# ---------------------------------------------------------------------------
def _conv_dims(kernel):
    kernel = parse_attr(kernel)
    return len(tuple(kernel) if not isinstance(kernel, int) else (kernel,))


def _conv_dim_numbers(nd):
    spatial = "DHW"[-nd:] if nd <= 3 else None
    if spatial is None:
        raise MXNetError("Convolution supports 1/2/3 spatial dims")
    return ("NC" + spatial, "OI" + spatial, "NC" + spatial)


def _conv_attrs(attrs):
    nd = _conv_dims(attrs["kernel"])
    kernel = normalize_tuple(attrs["kernel"], nd, "kernel")
    stride = normalize_tuple(attrs.get("stride", (1,) * nd), nd, "stride")
    pad = normalize_tuple(attrs.get("pad", (0,) * nd), nd, "pad")
    dilate = normalize_tuple(attrs.get("dilate", (1,) * nd), nd, "dilate")
    num_filter = int(parse_attr(attrs["num_filter"]))
    num_group = int(parse_attr(attrs.get("num_group", 1)))
    no_bias = parse_bool(attrs.get("no_bias", False))
    return nd, kernel, stride, pad, dilate, num_filter, num_group, no_bias


def _conv_params(attrs, data_shape, *rest):
    nd, kernel, _, _, _, num_filter, num_group, no_bias = _conv_attrs(attrs)
    in_ch = data_shape[1]
    shapes = {"weight": (num_filter, in_ch // num_group) + kernel}
    if not no_bias:
        shapes["bias"] = (num_filter,)
    return shapes


def _no_bias_drop(attrs):
    return {"bias"} if parse_bool(attrs.get("no_bias", False)) else set()


@register(
    "Convolution",
    arg_names=("data", "weight", "bias"),
    param_names=("weight", "bias"),
    infer_params=_conv_params,
    optional_args=_no_bias_drop,
)
def _convolution(ctx, data, weight, bias=None, **attrs):
    """Parity: Convolution (src/operator/convolution-inl.h).

    weight layout (num_filter, C/group, *kernel) == reference OIHW.

    ``__layout__="NHWC"`` (injected by the executor's channels-last pass,
    2D convs only) runs the conv with NHWC activations — the TPU-native
    layout: XLA tiles the minor channel dim straight onto the MXU/VPU
    lanes instead of inserting layout-assignment transposes around every
    op.  The weight stays logically OIHW (checkpoint parity) and is fed
    to the conv with OIHW dimension numbers directly: the kernel spec is
    a permutation, so no transpose op enters the graph (an explicit
    OIHW->HWIO transpose here measurably materialized ~116 MB/step of
    weight copies in the ResNet-50 train step — fwd transpose plus its
    vjp mirror — instead of folding into layout assignment).
    """
    nd, kernel, stride, pad, dilate, num_filter, num_group, no_bias = _conv_attrs(attrs)
    precision = conv_precision(data, weight)
    if attrs.get("__layout__") == "NHWC" and nd == 2:
        kernel_arr = weight
        # __wlayout__="HWIO": the weight ARRAY is physically stored HWIO
        # (FusedTrainer keeps masters/momentum/cache in consumption
        # layout); otherwise it arrives logical OIHW and the kernel spec
        # permutation tells XLA — no transpose op either way
        wspec = attrs.get("__wlayout__", "OIHW")
        dn = jax.lax.conv_dimension_numbers(
            data.shape, weight.shape, ("NHWC", wspec, "NHWC"))
        bias_shape = (1,) * (nd + 1) + (-1,)
    else:
        kernel_arr = weight
        dn = jax.lax.conv_dimension_numbers(
            data.shape, weight.shape, _conv_dim_numbers(nd))
        bias_shape = (1, -1) + (1,) * nd
    out = jax.lax.conv_general_dilated(
        data,
        kernel_arr,
        window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=num_group,
        precision=precision,
    )
    if bias is not None:
        out = out + bias.reshape(bias_shape)
    return out


def _deconv_params(attrs, data_shape, *rest):
    nd, kernel, _, _, _, num_filter, num_group, no_bias = _conv_attrs(attrs)
    in_ch = data_shape[1]
    shapes = {"weight": (in_ch, num_filter // num_group) + kernel}
    if not no_bias:
        shapes["bias"] = (num_filter,)
    return shapes


@register(
    "Deconvolution",
    arg_names=("data", "weight", "bias"),
    param_names=("weight", "bias"),
    infer_params=_deconv_params,
    optional_args=_no_bias_drop,
    attr_defaults={"no_bias": True},
)
def _deconvolution(ctx, data, weight, bias=None, **attrs):
    """Parity: Deconvolution (src/operator/deconvolution-inl.h) — transposed
    conv; adj/target_shape attrs for output sizing."""
    nd, kernel, stride, pad, dilate, num_filter, num_group, no_bias = _conv_attrs(attrs)
    adj = normalize_tuple(attrs.get("adj", (0,) * nd), nd, "adj")
    if attrs.get("target_shape"):
        # reference InferShape: adj = target - ((in-1)*s - 2p + d*(k-1)+1)
        tgt = normalize_tuple(parse_attr(attrs["target_shape"]), nd,
                              "target_shape")
        adj = tuple(
            int(t) - ((i - 1) * s - 2 * p + d * (k - 1) + 1)
            for t, i, s, p, d, k in zip(tgt, data.shape[2:], stride, pad,
                                        dilate, kernel))
        if any(a < 0 or a >= s for a, s in zip(adj, stride)):
            raise MXNetError(
                f"Deconvolution: target_shape {tgt} unreachable from input "
                f"{data.shape[2:]} with stride {stride}")
    dn = jax.lax.conv_dimension_numbers(
        data.shape, (data.shape[1], num_filter // num_group) + kernel, _conv_dim_numbers(nd)
    )
    # Transposed convolution as gradient-of-conv: lhs dilation by stride.
    out = jax.lax.conv_general_dilated(
        data,
        jnp.flip(weight, axis=tuple(range(2, 2 + nd))).swapaxes(0, 1)
        if num_group == 1
        else _grouped_flip(weight, nd, num_group),
        window_strides=(1,) * nd,
        # out = (in-1)*s - 2p + d*(k-1) + 1 + adj (deconvolution-inl.h
        # InferShape); with lhs_dilation=s the dilated input is
        # (in-1)*s + 1, so symmetric pads of d*(k-1)-p (+adj on the high
        # side) land exactly there — no stride term in the padding
        padding=[
            (d * (k - 1) - p, d * (k - 1) - p + a)
            for k, p, s, d, a in zip(kernel, pad, stride, dilate, adj)
        ],
        lhs_dilation=stride,
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=num_group,
        precision=conv_precision(data, weight),
    )
    if bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * nd)
    return out


def _grouped_flip(weight, nd, groups):
    # weight (C_in, num_filter//g, *k) -> grouped transpose per group
    cin, fpg = weight.shape[0], weight.shape[1]
    w = weight.reshape((groups, cin // groups) + weight.shape[1:])
    w = jnp.flip(w, axis=tuple(range(3, 3 + nd)))
    w = w.swapaxes(1, 2).reshape((groups * fpg, cin // groups) + weight.shape[2:])
    return w


# ---------------------------------------------------------------------------
# FullyConnected
# ---------------------------------------------------------------------------
def _fc_params(attrs, data_shape, *rest):
    num_hidden = int(parse_attr(attrs["num_hidden"]))
    in_dim = int(np.prod(data_shape[1:]))
    shapes = {"weight": (num_hidden, in_dim)}
    if not parse_bool(attrs.get("no_bias", False)):
        shapes["bias"] = (num_hidden,)
    return shapes


@register(
    "FullyConnected",
    arg_names=("data", "weight", "bias"),
    param_names=("weight", "bias"),
    infer_params=_fc_params,
    optional_args=_no_bias_drop,
)
def _fully_connected(ctx, data, weight, bias=None, **attrs):
    """Parity: FullyConnected (src/operator/fully_connected-inl.h); always
    flattens trailing dims like the reference v0.9 op."""
    x = data.reshape((data.shape[0], -1))
    out = jnp.dot(x, weight.T, precision=mxu_precision(data, weight))
    if bias is not None:
        out = out + bias
    return out


# ---------------------------------------------------------------------------
# BatchNorm (aux: moving stats)
# ---------------------------------------------------------------------------
def _bn_params(attrs, data_shape, *rest):
    c = data_shape[1]
    return {
        "gamma": (c,),
        "beta": (c,),
        "moving_mean": (c,),
        "moving_var": (c,),
    }


@register(
    "BatchNorm",
    arg_names=("data", "gamma", "beta"),
    param_names=("gamma", "beta"),
    aux_names=("moving_mean", "moving_var"),
    infer_params=_bn_params,
)
def _batch_norm(ctx, data, gamma, beta, moving_mean, moving_var, **attrs):
    """Parity: BatchNorm (src/operator/batch_norm-inl.h).

    Defaults mirror the reference: eps=1e-3, momentum=0.9, fix_gamma=True.
    Returns (out, (new_moving_mean, new_moving_var)); in eval mode (or
    use_global_stats) the moving stats are used and passed through.
    """
    eps = float(parse_attr(attrs.get("eps", 1e-3)))
    momentum = float(parse_attr(attrs.get("momentum", 0.9)))
    fix_gamma = parse_bool(attrs.get("fix_gamma", True))
    use_global = parse_bool(attrs.get("use_global_stats", False))

    if fix_gamma:
        gamma = jnp.ones_like(gamma)
    if attrs.get("__layout__") == "NHWC":
        # channels-last execution (executor layout pass): stats reduce over
        # all-but-minor axes, which XLA fuses into the producing conv
        axes = tuple(range(data.ndim - 1))
        bshape = (1,) * (data.ndim - 1) + (-1,)
    else:
        axes = (0,) + tuple(range(2, data.ndim))
        bshape = (1, -1) + (1,) * (data.ndim - 2)

    if ctx.is_train and not use_global:
        # single-pass moments: sum and sum-of-squares reduce in ONE fused
        # read of the activation (f32 accumulation), halving the HBM
        # traffic of the two-pass mean-then-centered-var formulation —
        # the dominant cost of train-mode BN on TPU (profiled; same
        # E[x^2]-E[x]^2 trick as mshadow's batch_norm forward).
        # MXTPU_BN_STATS_DTYPE=compute keeps the reduction arithmetic in
        # the compute dtype (bf16 under mixed precision) with f32
        # accumulators (jnp.sum dtype=) — the traffic pattern
        # tools/probe_resnet_variants.py A/Bs, in case XLA does not fuse
        # the default path's f32 upcast into the reduction reads.
        # Squaring in bf16 would make E[x^2]-E[x]^2 catastrophically
        # cancellable whenever |mean| >> std (bf16's ~2^-9 relative
        # rounding on the two large terms swamps a small variance), so
        # the moments are SHIFTED by the moving mean first: x-c is
        # small, bf16 represents small values with the same relative
        # precision, and Var = E[(x-c)^2] - (E[x]-c)^2 subtracts two
        # small numbers.  Opt-in until the probe proves the win.
        n = 1.0
        for ax in axes:
            n *= data.shape[ax]
        if os.environ.get("MXTPU_BN_STATS_DTYPE") == "compute":
            shift = jax.lax.stop_gradient(moving_mean).astype(data.dtype)
            centered = data - shift.reshape(bshape)
            m1 = jnp.sum(centered, axis=axes, dtype=jnp.float32) / n
            sq = jnp.sum(jnp.square(centered), axis=axes,
                         dtype=jnp.float32) / n
            # add back the ROUNDED shift actually subtracted, not the
            # raw moving mean — they differ when aux arrives f32
            mean32 = m1 + shift.astype(jnp.float32)
            var32 = jnp.maximum(sq - jnp.square(m1), 0.0)
        else:
            data32 = data.astype(jnp.float32)  # fused into the reads
            mean32 = jnp.sum(data32, axis=axes) / n
            sqmean = jnp.sum(jnp.square(data32), axis=axes) / n
            var32 = jnp.maximum(sqmean - jnp.square(mean32), 0.0)
        mean = mean32.astype(data.dtype)
        var = var32.astype(data.dtype)
        new_mean = moving_mean * momentum + mean32 * (1 - momentum)
        new_var = moving_var * momentum + var32 * (1 - momentum)
    else:
        mean, var = moving_mean, moving_var
        new_mean, new_var = moving_mean, moving_var
    inv = jax.lax.rsqrt(var + eps)
    out = (data - mean.reshape(bshape)) * inv.reshape(bshape) * gamma.reshape(
        bshape
    ) + beta.reshape(bshape)
    return out, (jax.lax.stop_gradient(new_mean), jax.lax.stop_gradient(new_var))


def _ln_params(attrs, data_shape, *rest):
    axis = int(attrs.get("axis", -1))
    return {"gamma": (data_shape[axis],), "beta": (data_shape[axis],)}


@register(
    "LayerNorm",
    arg_names=("data", "gamma", "beta"),
    param_names=("gamma", "beta"),
    infer_params=_ln_params,
)
def _layer_norm(ctx, data, gamma, beta, **attrs):
    """Beyond-reference (post-dates v0.9): last-axis normalization, the
    transformer-era norm behind models/transformer.py.  Single-pass f32
    moments like BatchNorm above."""
    eps = float(parse_attr(attrs.get("eps", 1e-5)))
    axis = int(parse_attr(attrs.get("axis", -1)))
    x32 = data.astype(jnp.float32)
    mean = jnp.mean(x32, axis=axis, keepdims=True)
    var = jnp.maximum(
        jnp.mean(jnp.square(x32), axis=axis, keepdims=True)
        - jnp.square(mean), 0.0)
    out = (x32 - mean) * jax.lax.rsqrt(var + eps)
    bshape = [1] * data.ndim
    bshape[axis] = data.shape[axis]
    out = out * gamma.reshape(bshape).astype(jnp.float32)         + beta.reshape(bshape).astype(jnp.float32)
    return out.astype(data.dtype)


def _in_params(attrs, data_shape, *rest):
    c = data_shape[1]
    return {"gamma": (c,), "beta": (c,)}


@register(
    "InstanceNorm",
    arg_names=("data", "gamma", "beta"),
    param_names=("gamma", "beta"),
    infer_params=_in_params,
)
def _instance_norm(ctx, data, gamma, beta, **attrs):
    """Parity: InstanceNorm (src/operator/instance_norm-inl.h)."""
    eps = float(parse_attr(attrs.get("eps", 1e-3)))
    axes = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=axes, keepdims=True)
    var = jnp.var(data, axis=axes, keepdims=True)
    bshape = (1, -1) + (1,) * (data.ndim - 2)
    out = (data - mean) * jax.lax.rsqrt(var + eps)
    return out * gamma.reshape(bshape) + beta.reshape(bshape)


@register("L2Normalization")
def _l2_normalization(ctx, data, **attrs):
    """Parity: L2Normalization (src/operator/l2_normalization-inl.h);
    mode instance (default) / channel / spatial."""
    eps = float(parse_attr(attrs.get("eps", 1e-10)))
    mode = attrs.get("mode", "instance")
    if mode == "instance":
        axes = tuple(range(1, data.ndim))
    elif mode == "channel":
        axes = (1,)
    elif mode == "spatial":
        axes = tuple(range(2, data.ndim))
    else:
        raise MXNetError(f"L2Normalization: unknown mode {mode}")
    norm = jnp.sqrt(jnp.sum(jnp.square(data), axis=axes, keepdims=True) + eps)
    return data / norm


# ---------------------------------------------------------------------------
# Pooling
# ---------------------------------------------------------------------------
@register("Pooling")
def _pooling(ctx, data, **attrs):
    """Parity: Pooling (src/operator/pooling-inl.h).

    pool_type max/avg/sum; global_pool; pooling_convention valid (floor,
    default) or full (ceil, reference kFull).  avg counts padding like the
    reference's mshadow pool (count-include-pad).
    """
    nd = data.ndim - 2
    nhwc = attrs.get("__layout__") == "NHWC"
    spatial0 = 1 if nhwc else 2  # first spatial axis under this layout
    if parse_bool(attrs.get("global_pool", False)):
        kernel = data.shape[spatial0:spatial0 + nd]
        stride = (1,) * nd
        pad = (0,) * nd
    else:
        kernel = normalize_tuple(attrs["kernel"], nd, "kernel")
        stride = normalize_tuple(attrs.get("stride", (1,) * nd), nd, "stride")
        pad = normalize_tuple(attrs.get("pad", (0,) * nd), nd, "pad")
    pool_type = attrs.get("pool_type", "max")
    convention = attrs.get("pooling_convention", "valid")

    spatial_pads = []
    for i in range(nd):
        lo = pad[i]
        hi = pad[i]
        if convention == "full":
            size = data.shape[spatial0 + i] + 2 * pad[i] - kernel[i]
            rem = size % stride[i]
            if rem != 0:
                hi += stride[i] - rem  # ceil-mode: extend right edge
        spatial_pads.append((lo, hi))

    if nhwc:
        padding = [(0, 0)] + spatial_pads + [(0, 0)]
        window = (1,) + tuple(kernel) + (1,)
        strides = (1,) + tuple(stride) + (1,)
    else:
        padding = [(0, 0), (0, 0)] + spatial_pads
        window = (1, 1) + tuple(kernel)
        strides = (1, 1) + tuple(stride)
    if pool_type == "max":
        # NB: XLA's select-and-scatter backward measured FASTER on TPU than
        # a 9-offset mask-trick custom VJP (strided scatters re-read dx at
        # input resolution per offset) — keep the default VJP
        out = jax.lax.reduce_window(data, -jnp.inf, jax.lax.max, window,
                                    strides, padding)
    elif pool_type in ("avg", "sum"):
        out = jax.lax.reduce_window(data, 0.0, jax.lax.add, window, strides, padding)
        if pool_type == "avg":
            out = out / float(np.prod(kernel))
    else:
        raise MXNetError(f"Pooling: unknown pool_type {pool_type}")
    return out.astype(data.dtype)


# ---------------------------------------------------------------------------
# Activations
# ---------------------------------------------------------------------------
@register("Activation")
def _activation(ctx, data, **attrs):
    """Parity: Activation (src/operator/activation-inl.h); act_type in
    relu/sigmoid/tanh/softrelu."""
    act = attrs.get("act_type", "relu")
    if act == "relu":
        return jax.nn.relu(data)
    if act == "sigmoid":
        return jax.nn.sigmoid(data)
    if act == "tanh":
        return jnp.tanh(data)
    if act == "softrelu":
        return jax.nn.softplus(data)
    if act == "gelu":  # beyond-reference: transformer-era activation
        return jax.nn.gelu(data)
    raise MXNetError(f"Activation: unknown act_type {act}")


def _prelu_params(attrs, data_shape, *rest):
    if attrs.get("act_type", "leaky") == "prelu":
        return {"gamma": (data_shape[1],)}
    return {}


def _leaky_optional(attrs):
    return set() if attrs.get("act_type", "leaky") == "prelu" else {"gamma"}


@register(
    "LeakyReLU",
    arg_names=("data", "gamma"),
    param_names=("gamma",),
    infer_params=_prelu_params,
    optional_args=_leaky_optional,
    needs_rng=True,
)
def _leaky_relu(ctx, data, gamma=None, **attrs):
    """Parity: LeakyReLU (src/operator/leaky_relu-inl.h); act_type in
    leaky/prelu/elu/rrelu."""
    act = attrs.get("act_type", "leaky")
    slope = float(parse_attr(attrs.get("slope", 0.25)))
    if act == "leaky":
        return jnp.where(data > 0, data, slope * data)
    if act == "elu":
        return jnp.where(data > 0, data, slope * jnp.expm1(data))
    if act == "prelu":
        g = gamma.reshape((1, -1) + (1,) * (data.ndim - 2))
        return jnp.where(data > 0, data, g * data)
    if act == "rrelu":
        lo = float(parse_attr(attrs.get("lower_bound", 0.125)))
        hi = float(parse_attr(attrs.get("upper_bound", 0.334)))
        if ctx.is_train:
            s = jax.random.uniform(
                ctx.rng(), (1, data.shape[1]) + (1,) * (data.ndim - 2), minval=lo, maxval=hi
            )
        else:
            s = (lo + hi) / 2.0
        return jnp.where(data > 0, data, s * data)
    raise MXNetError(f"LeakyReLU: unknown act_type {act}")


@register("Dropout", needs_rng=True)
def _dropout(ctx, data, **attrs):
    """Parity: Dropout (src/operator/dropout-inl.h); inverted dropout with
    keep-prob scaling at train time, identity at eval."""
    p = float(parse_attr(attrs.get("p", 0.5)))
    if not ctx.is_train or p <= 0.0:
        return data
    keep = 1.0 - p
    mask = jax.random.bernoulli(ctx.rng(), keep, data.shape)
    return jnp.where(mask, data / keep, 0.0).astype(data.dtype)


@register("LRN")
def _lrn(ctx, data, **attrs):
    """Parity: LRN (src/operator/lrn-inl.h) cross-channel normalization:
    out = data / (knorm + alpha/nsize * sum_sq_window)^beta."""
    alpha = float(parse_attr(attrs.get("alpha", 1e-4)))
    beta = float(parse_attr(attrs.get("beta", 0.75)))
    knorm = float(parse_attr(attrs.get("knorm", 2.0)))
    nsize = int(parse_attr(attrs["nsize"]))
    half = nsize // 2
    ch_axis = data.ndim - 1 if attrs.get("__layout__") == "NHWC" else 1
    sq = jnp.square(data)
    window = [1] * data.ndim
    window[ch_axis] = nsize
    strides = (1,) * data.ndim
    padding = [(0, 0)] * data.ndim
    padding[ch_axis] = (half, nsize - 1 - half)
    ssum = jax.lax.reduce_window(sq, 0.0, jax.lax.add, tuple(window), strides,
                                 padding)
    return data * jnp.power(knorm + alpha / nsize * ssum, -beta)


# ---------------------------------------------------------------------------
# Concat / SliceChannel
# ---------------------------------------------------------------------------
@register("Concat", varargs=True, aliases=("concat",))
def _concat(ctx, *args, **attrs):
    """Parity: Concat (src/operator/concat-inl.h); attr dim (default 1)."""
    dim = int(parse_attr(attrs.get("dim", 1)))
    return jnp.concatenate(args, axis=dim)


def _slice_channel_outputs(attrs):
    return int(parse_attr(attrs.get("num_outputs", 1)))


@register("SliceChannel", num_outputs=-1, aliases=("split",))
def _slice_channel(ctx, data, **attrs):
    """Parity: SliceChannel/split (src/operator/slice_channel-inl.h)."""
    num = int(parse_attr(attrs["num_outputs"]))
    axis = int(parse_attr(attrs.get("axis", 1)))
    squeeze = parse_bool(attrs.get("squeeze_axis", False))
    parts = jnp.split(data, num, axis=axis)
    if squeeze:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


# ---------------------------------------------------------------------------
# Pad / UpSampling / Crop (layer variant)
# ---------------------------------------------------------------------------
@register("Pad", aliases=("pad",))
def _pad(ctx, data, **attrs):
    """Parity: Pad (src/operator/pad-inl.h); pad_width in MXNet's flat
    (before,after)-per-axis order; modes constant/edge/reflect."""
    pw = tuple(parse_attr(attrs["pad_width"]))
    mode = attrs.get("mode", "constant")
    value = float(parse_attr(attrs.get("constant_value", 0.0)))
    pads = [(pw[2 * i], pw[2 * i + 1]) for i in range(data.ndim)]
    if mode == "constant":
        return jnp.pad(data, pads, mode="constant", constant_values=value)
    if mode == "edge":
        return jnp.pad(data, pads, mode="edge")
    if mode == "reflect":
        return jnp.pad(data, pads, mode="reflect")
    raise MXNetError(f"Pad: unknown mode {mode}")


def _upsampling_params(attrs, data_shape, *rest):
    if attrs.get("sample_type", "nearest") == "bilinear":
        scale = int(parse_attr(attrs["scale"]))
        num_filter = int(parse_attr(attrs.get("num_filter", data_shape[1])))
        k = 2 * scale - scale % 2
        return {"weight": (num_filter, 1, k, k)}
    return {}


def _upsampling_optional(attrs):
    return set() if attrs.get("sample_type", "nearest") == "bilinear" else {"weight"}


@register(
    "UpSampling",
    arg_names=("data", "weight"),
    param_names=("weight",),
    varargs=False,
    infer_params=_upsampling_params,
    optional_args=_upsampling_optional,
)
def _upsampling(ctx, data, weight=None, **attrs):
    """Parity: UpSampling (src/operator/upsampling-inl.h); nearest repeats,
    bilinear is a deconvolution with a (learnable) bilinear kernel."""
    scale = int(parse_attr(attrs["scale"]))
    sample_type = attrs.get("sample_type", "nearest")
    if sample_type == "nearest":
        out = jnp.repeat(jnp.repeat(data, scale, axis=2), scale, axis=3)
        return out
    # bilinear: transposed conv with stride=scale, groups=C
    k = 2 * scale - scale % 2
    p = int(np.ceil((scale - 1) / 2.0))
    c = data.shape[1]
    dn = jax.lax.conv_dimension_numbers(data.shape, (c, 1, k, k), ("NCHW", "OIHW", "NCHW"))
    out = jax.lax.conv_general_dilated(
        data,
        weight,
        window_strides=(1, 1),
        padding=[(k - 1 - p, k - 1 - p + scale - 1), (k - 1 - p, k - 1 - p + scale - 1)],
        lhs_dilation=(scale, scale),
        dimension_numbers=dn,
        precision=conv_precision(data, weight),
        feature_group_count=c,
    )
    return out


@register("Crop", arg_names=("data", "crop_like"), optional_args=lambda a: set()
          if int(parse_attr(a.get("num_args", 1))) > 1 else {"crop_like"})
def _crop_layer(ctx, data, crop_like=None, **attrs):
    """Parity: Crop layer (src/operator/crop-inl.h) — crop spatial dims to
    crop_like's (or h_w attr), with offset or center crop."""
    if crop_like is not None:
        th, tw = crop_like.shape[2], crop_like.shape[3]
    else:
        th, tw = tuple(parse_attr(attrs["h_w"]))
    offset = parse_attr(attrs.get("offset", (0, 0)))
    center = parse_bool(attrs.get("center_crop", False))
    h, w = data.shape[2], data.shape[3]
    if center:
        oy, ox = (h - th) // 2, (w - tw) // 2
    else:
        oy, ox = int(offset[0]), int(offset[1])
    return data[:, :, oy : oy + th, ox : ox + tw]
