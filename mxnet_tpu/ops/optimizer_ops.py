"""Fused optimizer-update kernels.

Parity: src/operator/optimizer_op.cc (sgd_update, sgd_mom_update,
adam_update, rmsprop_update) — the reference's fused CUDA kernels called by
python/mxnet/optimizer.py.  Here each is one jitted XLA computation, so the
clip+decay+update chain fuses exactly as the hand-written kernels do.
Semantics (rescale_grad, clip_gradient, wd applied to weight) follow
optimizer_op-inl.h.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..base import parse_attr
from .registry import register


_REQUIRED = object()


def _scalar(attrs, name, default=_REQUIRED):
    """Scalar hyperparameter: a traced jax array passes through (so lr
    schedules can feed a jitted train step without recompiling); strings
    and numbers parse to float.  Missing required attrs raise by name."""
    val = attrs.get(name, default)
    if val is _REQUIRED:
        raise KeyError(f"optimizer update requires attr {name!r}")
    if val is None:
        return None
    if hasattr(val, "dtype") and hasattr(val, "shape"):
        if getattr(val, "ndim", 0) != 0:
            raise ValueError(f"attr {name!r} must be a scalar, got shape "
                             f"{val.shape}")
        return val
    return float(parse_attr(val))


def _prep_grad(grad, weight, attrs):
    rescale = float(parse_attr(attrs.get("rescale_grad", 1.0)))
    clip = parse_attr(attrs.get("clip_gradient", None))
    wd = float(parse_attr(attrs.get("wd", 0.0)))
    g = grad * rescale
    if clip is not None and float(clip) > 0:
        g = jnp.clip(g, -float(clip), float(clip))
    return g + wd * weight


@register("sgd_update", arg_names=("weight", "grad"))
def _sgd_update(ctx, weight, grad, **attrs):
    lr = _scalar(attrs, "lr")
    return weight - lr * _prep_grad(grad, weight, attrs)


@register(
    "sgd_mom_update",
    arg_names=("weight", "grad", "mom"),
    num_outputs=2,
    output_names=("weight", "mom"),
)
def _sgd_mom_update(ctx, weight, grad, mom, **attrs):
    """mom = momentum*mom - lr*grad';  weight += mom (optimizer_op-inl.h)."""
    lr = _scalar(attrs, "lr")
    momentum = float(parse_attr(attrs.get("momentum", 0.0)))
    g = _prep_grad(grad, weight, attrs)
    new_mom = momentum * mom - lr * g
    return weight + new_mom, new_mom


@register(
    "adam_update",
    arg_names=("weight", "grad", "mean", "var"),
    num_outputs=3,
    output_names=("weight", "mean", "var"),
)
def _adam_update(ctx, weight, grad, mean, var, **attrs):
    lr = _scalar(attrs, "lr")
    beta1 = float(parse_attr(attrs.get("beta1", 0.9)))
    beta2 = float(parse_attr(attrs.get("beta2", 0.999)))
    eps = float(parse_attr(attrs.get("epsilon", 1e-8)))
    g = _prep_grad(grad, weight, attrs)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    new_weight = weight - lr * new_mean / (jnp.sqrt(new_var) + eps)
    return new_weight, new_mean, new_var


@register(
    "rmsprop_update",
    arg_names=("weight", "grad", "n"),
    num_outputs=2,
    output_names=("weight", "n"),
)
def _rmsprop_update(ctx, weight, grad, n, **attrs):
    lr = _scalar(attrs, "lr")
    gamma1 = float(parse_attr(attrs.get("gamma1", 0.95)))
    eps = float(parse_attr(attrs.get("epsilon", 1e-8)))
    clip_weights = parse_attr(attrs.get("clip_weights", None))
    g = _prep_grad(grad, weight, attrs)
    new_n = gamma1 * n + (1 - gamma1) * jnp.square(g)
    new_weight = weight - lr * g / jnp.sqrt(new_n + eps)
    if clip_weights is not None and float(clip_weights) > 0:
        cw = float(clip_weights)
        new_weight = jnp.clip(new_weight, -cw, cw)
    return new_weight, new_n
