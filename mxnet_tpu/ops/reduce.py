"""Reduction ops (parity: src/operator/tensor/broadcast_reduce_op_*.cc).

Axis semantics follow the reference: ``axis`` may be int/tuple/empty (empty
= reduce all), ``keepdims`` bool; argmax/argmin/argmax_channel return float
indices (MXNet convention: outputs are float arrays holding indices).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..base import parse_attr, parse_bool
from .registry import register


def _axis_of(attrs, data):
    axis = parse_attr(attrs.get("axis", None))
    if axis is None or axis == () or axis == []:
        return None
    if isinstance(axis, int):
        return (axis,)
    return tuple(axis)


def _reduce(fn, name):
    def impl(ctx, data, **attrs):
        axis = _axis_of(attrs, data)
        keepdims = parse_bool(attrs.get("keepdims", False))
        return fn(data, axis=axis, keepdims=keepdims)

    return impl


_REDUCE = {
    "sum": jnp.sum,
    "mean": jnp.mean,
    "prod": jnp.prod,
    "nansum": jnp.nansum,
    "nanprod": jnp.nanprod,
    "max": jnp.max,
    "min": jnp.min,
}
_ALIASES = {"sum": ("sum_axis",), "max": ("max_axis",), "min": ("min_axis",)}
for _name, _fn in _REDUCE.items():
    register(_name, aliases=_ALIASES.get(_name, ()))(_reduce(_fn, _name))


@register("norm")
def _norm(ctx, data, **attrs):
    """Parity: norm — L2 over the whole array (broadcast_reduce_op_value.cc)."""
    return jnp.sqrt(jnp.sum(jnp.square(data))).reshape((1,))


def _arg_reduce(fn):
    def impl(ctx, data, **attrs):
        axis = parse_attr(attrs.get("axis", None))
        keepdims = parse_bool(attrs.get("keepdims", False))
        if axis is None:
            out = fn(data.reshape(-1), axis=0)
            return out.astype(data.dtype)
        out = fn(data, axis=axis)
        if keepdims:
            out = jnp.expand_dims(out, axis)
        return out.astype(data.dtype)

    return impl


register("argmax")(_arg_reduce(jnp.argmax))
register("argmin")(_arg_reduce(jnp.argmin))


@register("argmax_channel")
def _argmax_channel(ctx, data, **attrs):
    """Parity: argmax_channel — argmax over axis 1 (channel), returns float."""
    return jnp.argmax(data, axis=1).astype(data.dtype)
