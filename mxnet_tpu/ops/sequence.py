"""Sequence ops (parity: src/operator/sequence_{last,mask,reverse}-inl.h).

Time-major (T, N, ...) layout like the reference; optional
``sequence_length`` input gated by use_sequence_length.  These lower to
gathers/selects — no scalar loops, jit-safe.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..base import parse_attr, parse_bool
from .registry import register


def _seq_optional(attrs):
    if parse_bool(attrs.get("use_sequence_length", False)):
        return set()
    return {"sequence_length"}


@register(
    "SequenceLast",
    arg_names=("data", "sequence_length"),
    optional_args=_seq_optional,
)
def _sequence_last(ctx, data, sequence_length=None, **attrs):
    if sequence_length is None:
        return data[-1]
    idx = sequence_length.astype(jnp.int32) - 1
    batch = jnp.arange(data.shape[1])
    return data[idx, batch]


@register(
    "SequenceMask",
    arg_names=("data", "sequence_length"),
    optional_args=_seq_optional,
)
def _sequence_mask(ctx, data, sequence_length=None, **attrs):
    value = float(parse_attr(attrs.get("value", 0.0)))
    if sequence_length is None:
        return data + 0
    t = data.shape[0]
    steps = jnp.arange(t)[:, None]  # (T, 1)
    mask = steps < sequence_length.astype(jnp.int32)[None, :]  # (T, N)
    mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    return jnp.where(mask, data, value).astype(data.dtype)


@register(
    "SequenceReverse",
    arg_names=("data", "sequence_length"),
    optional_args=_seq_optional,
)
def _sequence_reverse(ctx, data, sequence_length=None, **attrs):
    if sequence_length is None:
        return jnp.flip(data, axis=0)
    t = data.shape[0]
    lengths = sequence_length.astype(jnp.int32)  # (N,)
    steps = jnp.arange(t)[:, None]  # (T,1)
    # index of the element to read for output position t: len-1-t inside the
    # sequence, t itself beyond it.
    rev_idx = jnp.where(steps < lengths[None, :], lengths[None, :] - 1 - steps, steps)
    batch = jnp.arange(data.shape[1])[None, :]
    return data[rev_idx, batch]
