"""CTC loss (parity: plugin/warpctc — WarpCTC op).

The reference delegates to Baidu's warp-ctc CUDA kernels; here the CTC
forward-backward recursion is a log-space ``lax.scan`` over time — the
TPU-idiomatic formulation (static shapes, vectorized over the batch and the
extended label axis; no per-sequence host loops).

Semantics match the plugin: blank label = 0
(plugin/warpctc/warpctc-inl.h), forward output is softmax over the
alphabet, backward emits d(sum CTC loss)/d(activations) ignoring the head
gradient (loss-layer contract, like SoftmaxOutput).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import parse_attr
from .registry import register

_NEG = -1e30


def _log_add(a, b):
    m = jnp.maximum(a, b)
    return m + jnp.log1p(jnp.exp(-jnp.abs(a - b)))


def ctc_loss(logits, labels, input_lengths=None, label_lengths=None, blank=0):
    """Batched CTC negative log-likelihood.

    logits (T, N, C) pre-softmax; labels (N, L) int with 0 = padding/blank
    (warp-ctc convention: label id 0 is reserved for blank, so valid labels
    are >= 1 and trailing zeros are padding).  Returns (N,) losses.
    """
    t, n, c = logits.shape
    l = labels.shape[1]
    logp = jax.nn.log_softmax(logits, axis=-1)  # (T, N, C)

    if label_lengths is None:
        label_lengths = jnp.sum((labels != blank).astype(jnp.int32), axis=1)
    if input_lengths is None:
        input_lengths = jnp.full((n,), t, jnp.int32)

    # extended label sequence: blank, l1, blank, l2, ..., blank  (length 2L+1)
    s = 2 * l + 1
    ext = jnp.full((n, s), blank, labels.dtype)
    ext = ext.at[:, 1::2].set(labels)
    ext_valid = jnp.arange(s)[None, :] < (2 * label_lengths + 1)[:, None]

    # alpha recursion.  allow skip from s-2 when ext[s] != blank and
    # ext[s] != ext[s-2]
    ext_prev2 = jnp.pad(ext, ((0, 0), (2, 0)), constant_values=blank)[:, :s]
    can_skip = (ext != blank) & (ext != ext_prev2)
    can_skip = can_skip & (jnp.arange(s)[None, :] >= 2)

    batch = jnp.arange(n)

    def emit(lp_t):
        # lp_t (N, C) -> per extended-label emission logprob (N, S)
        return lp_t[batch[:, None], ext]

    init = jnp.full((n, s), _NEG)
    init = init.at[:, 0].set(logp[0, batch, blank])
    init = init.at[:, 1].set(
        jnp.where(label_lengths > 0, emit(logp[0])[:, 1], _NEG))

    def step(alpha, lp_t):
        a_prev1 = jnp.pad(alpha, ((0, 0), (1, 0)), constant_values=_NEG)[:, :s]
        a_prev2 = jnp.pad(alpha, ((0, 0), (2, 0)), constant_values=_NEG)[:, :s]
        acc = _log_add(alpha, a_prev1)
        acc = jnp.where(can_skip, _log_add(acc, a_prev2), acc)
        new = acc + emit(lp_t)
        new = jnp.where(ext_valid, new, _NEG)
        return new, new

    _, alphas = jax.lax.scan(step, init, logp[1:])
    alphas = jnp.concatenate([init[None], alphas], axis=0)  # (T, N, S)

    # read out at each sequence's final frame, positions 2L and 2L-1
    t_last = jnp.clip(input_lengths - 1, 0, t - 1)
    final = alphas[t_last, batch]  # (N, S)
    end1 = final[batch, 2 * label_lengths]
    end2 = jnp.where(label_lengths > 0,
                     final[batch, jnp.maximum(2 * label_lengths - 1, 0)], _NEG)
    return -_log_add(end1, end2)


@register(
    "WarpCTC",
    arg_names=("data", "label"),
)
def _warp_ctc(ctx, data, label, **attrs):
    """Parity: WarpCTC (plugin/warpctc/warpctc-inl.h).

    data: (T*N, C) activations (the plugin's flat layout) or (T, N, C);
    label: (N, L) with 0-padding.  Forward = softmax(data); backward =
    gradient of the summed CTC loss w.r.t. data, head gradient ignored.
    """
    label_length = int(parse_attr(attrs.get("label_length", label.shape[-1])))
    input_length = int(parse_attr(attrs.get("input_length", 0)))

    flat = data.ndim == 2
    if flat:
        n = label.shape[0]
        t = input_length if input_length > 0 else data.shape[0] // n
        logits = data.reshape(t, n, data.shape[-1])
    else:
        logits = data
    labels = label.reshape(label.shape[0], -1)[:, :label_length].astype(jnp.int32)

    tshape = logits.shape

    @jax.custom_vjp
    def fwd(x, lab):
        return jax.nn.softmax(x, axis=-1)

    def f(x, lab):
        return jax.nn.softmax(x, axis=-1), (x, lab)

    def b(res, g):
        x, lab = res
        lg = lambda z: jnp.sum(ctc_loss(z.reshape(tshape), lab))
        return (jax.grad(lg)(x), jnp.zeros_like(lab))

    fwd.defvjp(f, b)
    return fwd(data, labels)


@register(
    "_contrib_CTCLoss",
    arg_names=("data", "label"),
    aliases=("ctc_loss",),
)
def _ctc_loss_op(ctx, data, label, **attrs):
    """Per-sequence CTC loss vector (contract of MXNet's later
    _contrib_CTCLoss): data (T, N, C) pre-softmax activations,
    label (N, L) 0-padded; returns (N,) losses.  Differentiable via the
    scan-based forward-backward; no custom head-grad semantics (feed
    through MakeLoss to train, as users of _contrib_CTCLoss do)."""
    labels = label.reshape(label.shape[0], -1).astype(jnp.int32)
    return ctc_loss(data, labels)
