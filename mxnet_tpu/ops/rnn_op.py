"""Fused RNN op — lax.scan over time, the TPU analogue of cuDNN fused RNN.

Parity: src/operator/rnn-inl.h (+ cudnn_rnn-inl.h) (reference).  Inputs
follow the reference: data (T, N, input_size) time-major, a single packed
``parameters`` 1-D vector (rnn_single_param_size / rnn_param_size,
rnn-inl.h:33-66), state (layers*dirs, N, H) and state_cell for LSTM.
Outputs: output (T, N, H*dirs) [+ final state(s) when state_outputs].

Packing order (per layer, per direction): W_ih (G*H x in), W_hh (G*H x H),
then all biases b_ih (G*H), b_hh (G*H) after all weights — cuDNN's layout,
which the reference adopts.  Gate order matches the unfused cells
(python/mxnet/rnn/rnn_cell.py:264-277): i, g(transform), f, o for LSTM;
r, z, n for GRU.

TPU-native notes: the scan body is a fused (N,G*H) matmul per step on the
MXU; XLA unrolls nothing — compile time is O(1) in sequence length, unlike
the reference's symbolic unrolling.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base import mxu_precision, MXNetError, parse_attr, parse_bool
from .registry import register

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def rnn_param_size(num_layers, input_size, state_size, bidirectional, mode):
    """Total packed parameter count (parity: rnn_param_size, rnn-inl.h:57)."""
    gates = _GATES[mode]
    dirs = 2 if bidirectional else 1
    size = 0
    for layer in range(num_layers):
        in_size = input_size if layer == 0 else state_size * dirs
        for _ in range(dirs):
            size += gates * state_size * (in_size + state_size)  # W_ih, W_hh
            size += 2 * gates * state_size  # b_ih, b_hh
    return size


def _unpack_params(params, num_layers, input_size, state_size, bidirectional, mode):
    gates = _GATES[mode]
    dirs = 2 if bidirectional else 1
    offset = 0
    weights = []
    for layer in range(num_layers):
        in_size = input_size if layer == 0 else state_size * dirs
        for d in range(dirs):
            w_ih = params[offset : offset + gates * state_size * in_size].reshape(
                gates * state_size, in_size)
            offset += gates * state_size * in_size
            w_hh = params[offset : offset + gates * state_size * state_size].reshape(
                gates * state_size, state_size)
            offset += gates * state_size * state_size
            weights.append((w_ih, w_hh))
    biases = []
    for layer in range(num_layers):
        for d in range(dirs):
            b_ih = params[offset : offset + gates * state_size]
            offset += gates * state_size
            b_hh = params[offset : offset + gates * state_size]
            offset += gates * state_size
            biases.append((b_ih, b_hh))
    return weights, biases


def _cell_step(mode, state_size):
    """Single-timestep transition: (carry, gates_preact) -> (new_h, new_c)."""

    def lstm(c, h, pre):
        i, g, f, o = jnp.split(pre, 4, axis=-1)
        i = jax.nn.sigmoid(i)
        g = jnp.tanh(g)
        f = jax.nn.sigmoid(f)
        o = jax.nn.sigmoid(o)
        new_c = f * c + i * g
        new_h = o * jnp.tanh(new_c)
        return new_h, new_c

    def gru(h, pre_x, pre_h):
        rx, zx, nx = jnp.split(pre_x, 3, axis=-1)
        rh, zh, nh = jnp.split(pre_h, 3, axis=-1)
        r = jax.nn.sigmoid(rx + rh)
        z = jax.nn.sigmoid(zx + zh)
        n = jnp.tanh(nx + r * nh)
        return (1 - z) * n + z * h

    return lstm if mode == "lstm" else gru


def _run_layer(x, w_ih, w_hh, b_ih, b_hh, h0, c0, mode, reverse=False):
    """Scan one direction of one layer over time (x: (T, N, in))."""
    if reverse:
        x = jnp.flip(x, axis=0)
    # hoist the input projection out of the scan: one big (T*N, G*H) matmul
    pre_x = jnp.einsum("tni,gi->tng", x, w_ih,
                       precision=mxu_precision(x, w_ih)) + b_ih

    if mode == "lstm":
        step = _cell_step("lstm", None)

        def body(carry, px):
            h, c = carry
            pre = px + jnp.dot(h, w_hh.T, precision=mxu_precision(h, w_hh)) + b_hh
            new_h, new_c = step(c, h, pre)
            return (new_h, new_c), new_h

        (hT, cT), ys = jax.lax.scan(body, (h0, c0), pre_x)
    elif mode == "gru":
        step = _cell_step("gru", None)

        def body(h, px):
            pre_h = jnp.dot(h, w_hh.T, precision=mxu_precision(h, w_hh)) + b_hh
            new_h = step(h, px, pre_h)
            return new_h, new_h

        hT, ys = jax.lax.scan(body, h0, pre_x)
        cT = None
    else:
        act = jax.nn.relu if mode == "rnn_relu" else jnp.tanh

        def body(h, px):
            new_h = act(px + jnp.dot(h, w_hh.T, precision=mxu_precision(h, w_hh)) + b_hh)
            return new_h, new_h

        hT, ys = jax.lax.scan(body, h0, pre_x)
        cT = None
    if reverse:
        ys = jnp.flip(ys, axis=0)
    return ys, hT, cT


def _rnn_params_hook(attrs, data_shape, *rest):
    mode = attrs.get("mode", "lstm")
    state_size = int(parse_attr(attrs["state_size"]))
    num_layers = int(parse_attr(attrs["num_layers"]))
    bidirectional = parse_bool(attrs.get("bidirectional", False))
    dirs = 2 if bidirectional else 1
    n = data_shape[1]
    shapes = {
        "parameters": (rnn_param_size(num_layers, data_shape[2], state_size,
                                      bidirectional, mode),),
        "state": (num_layers * dirs, n, state_size),
    }
    if mode == "lstm":
        shapes["state_cell"] = (num_layers * dirs, n, state_size)
    return shapes


def _rnn_optional(attrs):
    if attrs.get("mode", "lstm") != "lstm":
        return {"state_cell"}
    return set()


def _rnn_num_outputs(attrs):
    if not parse_bool(attrs.get("state_outputs", False)):
        return 1
    return 3 if attrs.get("mode", "lstm") == "lstm" else 2


@register(
    "RNN",
    arg_names=("data", "parameters", "state", "state_cell"),
    param_names=("parameters",),
    output_names=("output", "state", "state_cell"),
    infer_params=_rnn_params_hook,
    optional_args=_rnn_optional,
    num_outputs_fn=_rnn_num_outputs,
    needs_rng=True,
)
def _rnn(ctx, data, parameters, state, state_cell=None, **attrs):
    """Parity: RNN op (src/operator/rnn-inl.h registration 'RNN')."""
    mode = attrs.get("mode", "lstm")
    if mode not in _GATES:
        raise MXNetError(f"RNN: unknown mode {mode}")
    state_size = int(parse_attr(attrs["state_size"]))
    num_layers = int(parse_attr(attrs["num_layers"]))
    bidirectional = parse_bool(attrs.get("bidirectional", False))
    p_dropout = float(parse_attr(attrs.get("p", 0.0)))
    state_outputs = parse_bool(attrs.get("state_outputs", False))
    dirs = 2 if bidirectional else 1
    t, n, input_size = data.shape

    weights, biases = _unpack_params(parameters, num_layers, input_size,
                                     state_size, bidirectional, mode)
    x = data
    h_finals, c_finals = [], []
    for layer in range(num_layers):
        outs = []
        for d in range(dirs):
            idx = layer * dirs + d
            w_ih, w_hh = weights[idx]
            b_ih, b_hh = biases[idx]
            h0 = state[idx]
            c0 = state_cell[idx] if mode == "lstm" else None
            ys, hT, cT = _run_layer(x, w_ih, w_hh, b_ih, b_hh, h0, c0, mode,
                                    reverse=(d == 1))
            outs.append(ys)
            h_finals.append(hT)
            if mode == "lstm":
                c_finals.append(cT)
        x = outs[0] if dirs == 1 else jnp.concatenate(outs, axis=-1)
        if p_dropout > 0.0 and ctx.is_train and layer < num_layers - 1:
            keep = 1.0 - p_dropout
            mask = jax.random.bernoulli(ctx.rng(), keep, x.shape)
            x = jnp.where(mask, x / keep, 0.0).astype(x.dtype)

    if not state_outputs:
        return x
    h_out = jnp.stack(h_finals, axis=0)
    if mode == "lstm":
        c_out = jnp.stack(c_finals, axis=0)
        return (x, h_out, c_out)
    return (x, h_out)
