"""Elementwise ops: unary math, binary (+scalar, +broadcast), logic.

Parity surface: src/operator/tensor/elemwise_unary_op.cc,
elemwise_binary_op_basic.cc, elemwise_binary_scalar_op_*.cc,
elemwise_binary_broadcast_op_*.cc, elemwise_sum.cc (reference, SURVEY.md
Appendix A).  All ops are thin jnp lambdas — XLA fuses chains of these into
single kernels, which *is* the TPU-native replacement for mshadow's
expression templates (reference mshadow expression engine).

MXNet semantics preserved:
- ``elemwise_*`` requires same-shape operands (no silent broadcast);
  ``broadcast_*`` are the broadcasting variants.
- logic ops return float arrays of 0/1 (reference mshadow_op.h comparisons).
- ``smooth_l1`` takes scalar sigma via attr.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError, parse_attr
from .registry import register

# ---------------------------------------------------------------------------
# unary math ops (reference: elemwise_unary_op.cc list, SURVEY.md:535-540)
# ---------------------------------------------------------------------------
_GAMMA = lambda x: jnp.exp(jax.scipy.special.gammaln(x))

_UNARY = {
    "abs": jnp.abs,
    "arccos": jnp.arccos,
    "arccosh": jnp.arccosh,
    "arcsin": jnp.arcsin,
    "arcsinh": jnp.arcsinh,
    "arctan": jnp.arctan,
    "arctanh": jnp.arctanh,
    "ceil": jnp.ceil,
    "cos": jnp.cos,
    "cosh": jnp.cosh,
    "degrees": jnp.degrees,
    "exp": jnp.exp,
    "expm1": jnp.expm1,
    "fix": jnp.trunc,
    "floor": jnp.floor,
    "gamma": _GAMMA,
    "gammaln": jax.scipy.special.gammaln,
    "log": jnp.log,
    "log10": jnp.log10,
    "log1p": jnp.log1p,
    "log2": jnp.log2,
    "negative": jnp.negative,
    "radians": jnp.radians,
    "rint": jnp.rint,
    "round": jnp.round,
    "rsqrt": lambda x: jax.lax.rsqrt(x),
    "sign": jnp.sign,
    "sin": jnp.sin,
    "sinh": jnp.sinh,
    "sqrt": jnp.sqrt,
    "square": jnp.square,
    "tan": jnp.tan,
    "tanh": jnp.tanh,
    # not standalone in the reference (mshadow_op.h functors) but exposed for
    # convenience; Activation provides the parity path.
    "sigmoid": jax.nn.sigmoid,
    "relu": jax.nn.relu,
}

for _name, _fn in _UNARY.items():
    register(_name)(lambda ctx, data, _fn=_fn, **attrs: _fn(data))

register("_copy", aliases=("identity",))(lambda ctx, data, **a: data + 0)


@register("BlockGrad", aliases=("stop_gradient",))
def _block_grad(ctx, data, **attrs):
    """Identity forward, zero gradient (reference: block_grad in
    elemwise_unary_op.cc; SURVEY.md:538)."""
    return jax.lax.stop_gradient(data)


@register("Cast", aliases=("cast",))
def _cast(ctx, data, **attrs):
    """Parity: Cast op (elemwise_unary_op.cc)."""
    return data.astype(jnp.dtype(attrs["dtype"]))


# ---------------------------------------------------------------------------
# binary elementwise (same-shape contract, reference elemwise_binary_op_basic)
# ---------------------------------------------------------------------------
def _same_shape(lhs, rhs, name):
    if lhs.shape != rhs.shape:
        raise MXNetError(
            f"{name}: shapes {lhs.shape} and {rhs.shape} differ; use broadcast_{name.strip('_')}"
        )


def _binary(fn, name, check=True):
    def impl(ctx, lhs, rhs, **attrs):
        if check:
            _same_shape(lhs, rhs, name)
        return fn(lhs, rhs)

    return impl


_BINARY = {
    "elemwise_add": jnp.add,
    "elemwise_sub": jnp.subtract,
    "elemwise_mul": jnp.multiply,
    "elemwise_div": jnp.divide,
    "_power": jnp.power,
    "_maximum": jnp.maximum,
    "_minimum": jnp.minimum,
    "_hypot": jnp.hypot,
}
_BINARY_ALIASES = {
    "elemwise_add": ("_plus", "_add", "_Plus"),
    "elemwise_sub": ("_minus", "_sub", "_Minus"),
    "elemwise_mul": ("_mul", "_Mul"),
    "elemwise_div": ("_div", "_Div"),
    "_power": ("_Power",),
    "_maximum": ("_Maximum",),
    "_minimum": ("_Minimum",),
    "_hypot": (),
}
for _name, _fn in _BINARY.items():
    register(_name, arg_names=("lhs", "rhs"), aliases=_BINARY_ALIASES[_name])(
        _binary(_fn, _name)
    )

# _grad_add: same as add; used by grad aggregation (elemwise_binary_op_basic.cc)
register("_grad_add", arg_names=("lhs", "rhs"))(_binary(jnp.add, "_grad_add"))


@register("smooth_l1")
def _smooth_l1(ctx, data, **attrs):
    """Parity: smooth_l1 (elemwise_binary_op_trig/extended); scalar sigma."""
    sigma = float(parse_attr(attrs.get("scalar", attrs.get("sigma", 1.0))))
    s2 = sigma * sigma
    a = jnp.abs(data)
    return jnp.where(a < 1.0 / s2, 0.5 * s2 * jnp.square(data), a - 0.5 / s2)


_LOGIC = {
    "_equal": jnp.equal,
    "_not_equal": jnp.not_equal,
    "_greater": jnp.greater,
    "_greater_equal": jnp.greater_equal,
    "_lesser": jnp.less,
    "_lesser_equal": jnp.less_equal,
}
for _name, _fn in _LOGIC.items():
    register(_name, arg_names=("lhs", "rhs"))(
        _binary(lambda l, r, _fn=_fn: _fn(l, r).astype(l.dtype), _name)
    )

# ---------------------------------------------------------------------------
# scalar variants (reference elemwise_binary_scalar_op_*.cc)
# ---------------------------------------------------------------------------
def _scalar_op(fn, reverse=False):
    def impl(ctx, data, **attrs):
        s = jnp.asarray(parse_attr(attrs["scalar"]), dtype=data.dtype)
        return fn(s, data) if reverse else fn(data, s)

    return impl


_SCALAR = {
    "_plus_scalar": (jnp.add, False),
    "_minus_scalar": (jnp.subtract, False),
    "_rminus_scalar": (jnp.subtract, True),
    "_mul_scalar": (jnp.multiply, False),
    "_div_scalar": (jnp.divide, False),
    "_rdiv_scalar": (jnp.divide, True),
    "_power_scalar": (jnp.power, False),
    "_rpower_scalar": (jnp.power, True),
    "_maximum_scalar": (jnp.maximum, False),
    "_minimum_scalar": (jnp.minimum, False),
    "_hypot_scalar": (jnp.hypot, False),
    "_equal_scalar": (lambda a, b: jnp.equal(a, b).astype(a.dtype), False),
    "_not_equal_scalar": (lambda a, b: jnp.not_equal(a, b).astype(a.dtype), False),
    "_greater_scalar": (lambda a, b: jnp.greater(a, b).astype(a.dtype), False),
    "_greater_equal_scalar": (lambda a, b: jnp.greater_equal(a, b).astype(a.dtype), False),
    "_lesser_scalar": (lambda a, b: jnp.less(a, b).astype(a.dtype), False),
    "_lesser_equal_scalar": (lambda a, b: jnp.less_equal(a, b).astype(a.dtype), False),
}
for _name, (_fn, _rev) in _SCALAR.items():
    register(_name, aliases=(_name.replace("_", "_Plus", 1),) if False else ())(
        _scalar_op(_fn, _rev)
    )

# ---------------------------------------------------------------------------
# broadcast variants (reference elemwise_binary_broadcast_op_*.cc)
# ---------------------------------------------------------------------------
_BROADCAST = {
    "broadcast_add": jnp.add,
    "broadcast_sub": jnp.subtract,
    "broadcast_mul": jnp.multiply,
    "broadcast_div": jnp.divide,
    "broadcast_power": jnp.power,
    "broadcast_maximum": jnp.maximum,
    "broadcast_minimum": jnp.minimum,
    "broadcast_hypot": jnp.hypot,
    "broadcast_equal": lambda a, b: jnp.equal(a, b).astype(a.dtype),
    "broadcast_not_equal": lambda a, b: jnp.not_equal(a, b).astype(a.dtype),
    "broadcast_greater": lambda a, b: jnp.greater(a, b).astype(a.dtype),
    "broadcast_greater_equal": lambda a, b: jnp.greater_equal(a, b).astype(a.dtype),
    "broadcast_lesser": lambda a, b: jnp.less(a, b).astype(a.dtype),
    "broadcast_lesser_equal": lambda a, b: jnp.less_equal(a, b).astype(a.dtype),
    "broadcast_plus": jnp.add,
    "broadcast_minus": jnp.subtract,
}
for _name, _fn in _BROADCAST.items():
    register(_name, arg_names=("lhs", "rhs"))(_binary(_fn, _name, check=False))


@register("broadcast_axis", aliases=("broadcast_axes",))
def _broadcast_axis(ctx, data, **attrs):
    """Parity: broadcast_axis (broadcast_reduce_op_value.cc)."""
    axes = parse_attr(attrs.get("axis", ()))
    sizes = parse_attr(attrs.get("size", ()))
    if isinstance(axes, int):
        axes = (axes,)
    if isinstance(sizes, int):
        sizes = (sizes,)
    shape = list(data.shape)
    for ax, sz in zip(axes, sizes):
        if shape[ax] != 1:
            raise MXNetError("broadcast_axis: source axis must have size 1")
        shape[ax] = sz
    return jnp.broadcast_to(data, tuple(shape))


@register("broadcast_to")
def _broadcast_to(ctx, data, **attrs):
    shape = tuple(parse_attr(attrs["shape"]))
    # MXNet allows 0 meaning "keep source dim"
    shape = tuple(s if s != 0 else d for s, d in zip(shape, data.shape))
    return jnp.broadcast_to(data, shape)


@register("ElementWiseSum", varargs=True, aliases=("add_n", "_sum"))
def _element_wise_sum(ctx, *args, **attrs):
    """Parity: ElementWiseSum (src/operator/tensor/elemwise_sum.cc); the
    gradient-aggregation workhorse (NDArray ElementwiseSum,
    src/ndarray/ndarray.cc:302)."""
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


@register("clip")
def _clip(ctx, data, **attrs):
    """Parity: clip (matrix_op.cc)."""
    return jnp.clip(
        data, float(parse_attr(attrs["a_min"])), float(parse_attr(attrs["a_max"]))
    )


@register("softmax")
def _softmax_op(ctx, data, **attrs):
    """True softmax ACTIVATION over ``axis`` (default -1) with an honest
    autodiff gradient — the modern op (src/operator/nn/softmax.cc in
    later reference versions).  Deliberately registered under the
    lowercase name so it wins over the deprecated capital-``Softmax``
    alias of SoftmaxOutput, whose custom backward assumes an implicit
    label and silently poisons any graph using softmax as an activation
    (caught by the a2c example's dead policy gradient)."""
    axis = int(parse_attr(attrs.get("axis", -1)))
    return jax.nn.softmax(data, axis=axis)


@register("log_softmax")
def _log_softmax_op(ctx, data, **attrs):
    """log(softmax(data)) computed stably (src/operator/nn/softmax.cc
    log_softmax in later reference versions)."""
    axis = int(parse_attr(attrs.get("axis", -1)))
    return jax.nn.log_softmax(data, axis=axis)
