"""Matrix / shape-manipulation ops.

Parity: src/operator/tensor/matrix_op.cc + matrix_op-inl.h (1589 LoC in the
reference), ordering_op-inl.h (sort/topk/argsort — reference uses CUB; here
jax.lax.sort/top_k lower straight to XLA, SURVEY.md §2.2 'cub' row).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base import mxu_precision, MXNetError, parse_attr, parse_bool
from .registry import register


@register("dot", arg_names=("lhs", "rhs"))
def _dot(ctx, lhs, rhs, **attrs):
    """Parity: dot (matrix_op.cc). transpose_a/transpose_b attrs.

    1-D x 1-D -> scalar-as-(1,) like the reference; 2-D matmul hits the MXU.
    """
    ta = parse_bool(attrs.get("transpose_a", False))
    tb = parse_bool(attrs.get("transpose_b", False))
    if lhs.ndim == 1 and rhs.ndim == 1:
        return jnp.dot(lhs, rhs).reshape((1,))
    a = lhs.T if ta else lhs
    b = rhs.T if tb else rhs
    return jnp.dot(a, b, precision=mxu_precision(a, b))


@register("batch_dot", arg_names=("lhs", "rhs"))
def _batch_dot(ctx, lhs, rhs, **attrs):
    """Parity: batch_dot (matrix_op.cc) — (B,M,K)x(B,K,N)."""
    ta = parse_bool(attrs.get("transpose_a", False))
    tb = parse_bool(attrs.get("transpose_b", False))
    a = jnp.swapaxes(lhs, -1, -2) if ta else lhs
    b = jnp.swapaxes(rhs, -1, -2) if tb else rhs
    return jnp.matmul(a, b, precision=mxu_precision(a, b))


@register("transpose")
def _transpose(ctx, data, **attrs):
    axes = parse_attr(attrs.get("axes", None))
    if axes is None or axes == ():
        return jnp.transpose(data)
    return jnp.transpose(data, tuple(axes))


@register("SwapAxis", aliases=("swapaxes",))
def _swapaxis(ctx, data, **attrs):
    """Parity: SwapAxis (src/operator/swapaxis-inl.h)."""
    return jnp.swapaxes(
        data, int(parse_attr(attrs.get("dim1", 0))), int(parse_attr(attrs.get("dim2", 0)))
    )


def _infer_reshape(shape, target):
    """MXNet v0.9 reshape codes: 0 copies the input dim, -1 infers."""
    target = list(target)
    for i, t in enumerate(target):
        if t == 0:
            target[i] = shape[i]
    if -1 in target:
        known = int(np.prod([t for t in target if t != -1]))
        total = int(np.prod(shape))
        target[target.index(-1)] = total // max(known, 1)
    return tuple(int(t) for t in target)


@register("Reshape", aliases=("reshape",))
def _reshape(ctx, data, **attrs):
    """Parity: Reshape (matrix_op.cc); supports 0 / -1 shape codes."""
    shape = parse_attr(attrs.get("shape", attrs.get("target_shape", None)))
    return jnp.reshape(data, _infer_reshape(data.shape, tuple(shape)))


@register("Flatten", aliases=("flatten",))
def _flatten(ctx, data, **attrs):
    """Parity: Flatten — collapse all but axis 0 (matrix_op.cc)."""
    return jnp.reshape(data, (data.shape[0], -1))


@register("expand_dims")
def _expand_dims(ctx, data, **attrs):
    return jnp.expand_dims(data, int(parse_attr(attrs["axis"])))


@register("crop", aliases=("slice",))
def _slice(ctx, data, **attrs):
    """Parity: crop/slice (matrix_op.cc) — begin/end per-axis slice."""
    begin = tuple(parse_attr(attrs["begin"]))
    end = tuple(parse_attr(attrs["end"]))
    idx = tuple(
        slice(b, e) for b, e in zip(begin, end)
    ) + (Ellipsis,)
    return data[idx]


@register("slice_axis")
def _slice_axis(ctx, data, **attrs):
    """Parity: slice_axis (matrix_op.cc); end may be None for 'to the end'."""
    axis = int(parse_attr(attrs["axis"]))
    begin = int(parse_attr(attrs["begin"]))
    end = parse_attr(attrs.get("end", None))
    idx = [slice(None)] * data.ndim
    idx[axis] = slice(begin, None if end in (None, "None") else int(end))
    return data[tuple(idx)]


@register("flip")
def _flip(ctx, data, **attrs):
    axis = parse_attr(attrs["axis"])
    if isinstance(axis, int):
        axis = (axis,)
    return jnp.flip(data, axis=tuple(axis))


@register("repeat")
def _repeat(ctx, data, **attrs):
    reps = int(parse_attr(attrs["repeats"]))
    axis = parse_attr(attrs.get("axis", None))
    return jnp.repeat(data, reps, axis=None if axis is None else int(axis))


@register("tile")
def _tile(ctx, data, **attrs):
    return jnp.tile(data, tuple(parse_attr(attrs["reps"])))


# --- ordering (reference ordering_op-inl.h; CUB -> lax.sort/top_k) ---------
@register("sort")
def _sort(ctx, data, **attrs):
    axis = parse_attr(attrs.get("axis", -1))
    is_ascend = parse_bool(attrs.get("is_ascend", True))
    axis = None if axis in (None, "None") else int(axis)
    if axis is None:
        data = data.reshape(-1)
        axis = 0
    out = jnp.sort(data, axis=axis)
    return out if is_ascend else jnp.flip(out, axis=axis)


@register("argsort")
def _argsort(ctx, data, **attrs):
    axis = parse_attr(attrs.get("axis", -1))
    is_ascend = parse_bool(attrs.get("is_ascend", True))
    axis = None if axis in (None, "None") else int(axis)
    if axis is None:
        data = data.reshape(-1)
        axis = 0
    idx = jnp.argsort(data, axis=axis)
    if not is_ascend:
        idx = jnp.flip(idx, axis=axis)
    return idx.astype(data.dtype)


@register("topk", num_outputs=2, output_names=("output", "indices"))
def _topk(ctx, data, **attrs):
    """Parity: topk (ordering_op-inl.h:478).  ret_typ selects outputs:
    'indices' (default) | 'value' | 'both' | 'mask'."""
    axis = parse_attr(attrs.get("axis", -1))
    k = int(parse_attr(attrs.get("k", 1)))
    ret_typ = attrs.get("ret_typ", "indices")
    is_ascend = parse_bool(attrs.get("is_ascend", False))
    axis = data.ndim - 1 if axis in (None, "None") else int(axis) % data.ndim
    moved = jnp.moveaxis(data, axis, -1)
    vals, idxs = jax.lax.top_k(-moved if is_ascend else moved, k)
    if is_ascend:
        vals = -vals
    vals = jnp.moveaxis(vals, -1, axis)
    idxs = jnp.moveaxis(idxs, -1, axis).astype(data.dtype)
    if ret_typ == "value":
        return vals
    if ret_typ == "both":
        return (vals, idxs)
    if ret_typ == "mask":
        onehot = jax.nn.one_hot(jnp.moveaxis(idxs, axis, -1).astype(jnp.int32),
                                data.shape[axis], dtype=data.dtype)
        mask = jnp.moveaxis(onehot.sum(axis=-2), -1, axis)
        return mask
    return idxs


@register("_identity_with_attr_like_rhs", arg_names=("lhs", "rhs"))
def _identity_like_rhs(ctx, lhs, rhs, **attrs):
    return lhs + jnp.zeros_like(rhs)


@register("_crop_assign", arg_names=("lhs", "rhs"))
def _crop_assign(ctx, lhs, rhs, **attrs):
    begin = tuple(parse_attr(attrs["begin"]))
    end = tuple(parse_attr(attrs["end"]))
    idx = tuple(slice(b, e) for b, e in zip(begin, end))
    return lhs.at[idx].set(rhs)


@register("_crop_assign_scalar")
def _crop_assign_scalar(ctx, data, **attrs):
    begin = tuple(parse_attr(attrs["begin"]))
    end = tuple(parse_attr(attrs["end"]))
    scalar = parse_attr(attrs.get("scalar", 0.0))
    idx = tuple(slice(b, e) for b, e in zip(begin, end))
    return data.at[idx].set(scalar)
