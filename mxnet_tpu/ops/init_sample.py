"""Creation + sampling ops.

Parity: src/operator/tensor/init_op.cc (_zeros/_ones/_arange) and
sample_op.cc (uniform/normal).  Sampling ops draw from explicit JAX PRNG
keys via OpCtx.rng() — the pure replacement for mshadow's stateful
per-device random resource (include/mxnet/resource.h kRandom).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import parse_attr
from .registry import register


def _dtype_of(attrs, default=jnp.float32):
    dt = attrs.get("dtype", None)
    return jnp.dtype(dt) if dt is not None else jnp.dtype(default)


@register("_zeros", arg_names=())
def _zeros(ctx, **attrs):
    return jnp.zeros(tuple(parse_attr(attrs["shape"])), dtype=_dtype_of(attrs))


@register("_ones", arg_names=())
def _ones(ctx, **attrs):
    return jnp.ones(tuple(parse_attr(attrs["shape"])), dtype=_dtype_of(attrs))


@register("_full", arg_names=())
def _full(ctx, **attrs):
    return jnp.full(
        tuple(parse_attr(attrs["shape"])),
        parse_attr(attrs["value"]),
        dtype=_dtype_of(attrs),
    )


@register("_arange", arg_names=())
def _arange(ctx, **attrs):
    """Parity: _arange (init_op.cc); supports repeat like the reference."""
    start = parse_attr(attrs.get("start", 0))
    stop = parse_attr(attrs.get("stop", None))
    step = parse_attr(attrs.get("step", 1.0))
    repeat = int(parse_attr(attrs.get("repeat", 1)))
    if stop in (None, "None"):
        start, stop = 0, start
    out = jnp.arange(start, stop, step, dtype=_dtype_of(attrs))
    if repeat > 1:
        out = jnp.repeat(out, repeat)
    return out


@register("uniform", arg_names=(), needs_rng=True, aliases=("_sample_uniform", "random_uniform"))
def _uniform(ctx, **attrs):
    """Parity: uniform (sample_op.cc); low/high bounds."""
    low = float(parse_attr(attrs.get("low", 0.0)))
    high = float(parse_attr(attrs.get("high", 1.0)))
    shape = tuple(parse_attr(attrs["shape"]))
    return jax.random.uniform(
        ctx.rng(), shape, dtype=_dtype_of(attrs), minval=low, maxval=high
    )


@register("normal", arg_names=(), needs_rng=True, aliases=("_sample_normal", "random_normal"))
def _normal(ctx, **attrs):
    """Parity: normal (sample_op.cc); loc/scale."""
    loc = float(parse_attr(attrs.get("loc", 0.0)))
    scale = float(parse_attr(attrs.get("scale", 1.0)))
    shape = tuple(parse_attr(attrs["shape"]))
    return loc + scale * jax.random.normal(ctx.rng(), shape, dtype=_dtype_of(attrs))


@register("_set_value", arg_names=())
def _set_value(ctx, **attrs):
    """Parity: _set_value NDArray function (src/ndarray/ndarray.cc:748)."""
    return jnp.full(
        tuple(parse_attr(attrs["shape"])), parse_attr(attrs["src"]), dtype=_dtype_of(attrs)
    )
