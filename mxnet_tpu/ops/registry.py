"""Operator registry — the single source of truth for the op surface.

TPU-native replacement for the reference's *three* op registration systems
(legacy OperatorProperty, include/mxnet/operator.h:166-297; NNVM FCompute,
include/mxnet/op_attr_types.h:24-63; deprecated SimpleOp,
src/operator/operator_util.cc).  One registry serves both execution styles:

- imperative:  mxnet_tpu.ndarray autogenerates ``nd.<op>`` functions that
  dispatch through a jit cache (parity: MXImperativeInvoke,
  src/c_api/c_api_ndarray.cc:19-280 — the jit cache plays the role of the
  engine PushAsync; PjRt async dispatch is the engine),
- symbolic:    mxnet_tpu.symbol autogenerates ``sym.<Op>`` constructors; the
  executor traces registered forward fns into one XLA computation.

Each op is a pure function ``fn(ctx, *inputs, **attrs)`` over jax arrays.
Gradients come from jax.vjp — ops needing MXNet's special backward semantics
(loss output ops that ignore head gradients) wrap themselves in
jax.custom_vjp at definition site.
"""
from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence

import jax

from ..base import MXNetError, frozen_attrs

_OPS: dict[str, "OpDef"] = {}


class OpCtx:
    """Per-invocation context handed to op implementations.

    Carries mode and randomness — the TPU-shaped analogue of the
    reference's OpContext {is_train, RunContext, requested resources}
    (include/mxnet/op_attr_types.h:32-63).  Randomness: instead of a
    mutable mshadow PRNG resource, ops pull fresh subkeys derived from an
    explicit key (pure & replayable inside jit).
    """

    __slots__ = ("is_train", "_key", "_nsplit", "platform")

    def __init__(self, is_train: bool = False, key=None, platform=None):
        self.is_train = is_train
        self._key = key
        self._nsplit = 0
        # the platform this graph will EXECUTE on ("tpu"/"cpu"), threaded
        # from the executor's bind ctx / the trainer's mesh.  Ops that
        # pick platform-specific lowerings (Pallas vs lax) must use this,
        # not jax.default_backend(): a registered accelerator plugin can
        # be the default backend while the computation is being lowered
        # for a CPU mesh (e.g. dryrun_multichip on a TPU-attached host).
        self.platform = platform

    def rng(self):
        if self._key is None:
            raise MXNetError("op requires a PRNG key but none was supplied")
        # trace-ok: OpCtx lives for one trace; the key-split counter is
        # trace-time bookkeeping that gives each rng() call a distinct fold
        self._nsplit += 1
        return jax.random.fold_in(self._key, self._nsplit)


@dataclass
class OpDef:
    name: str
    fn: Callable  # fn(ctx, *inputs, **attrs) -> out | tuple | (outs, aux_updates)
    arg_names: Sequence[str] = ("data",)
    # subset of arg_names that are learned parameters (auto-created as
    # variables during symbol composition, like Convolution's weight/bias)
    param_names: Sequence[str] = ()
    aux_names: Sequence[str] = ()  # auxiliary states (BatchNorm moving stats)
    num_outputs: int = 1
    output_names: Sequence[str] = ("output",)
    needs_rng: bool = False
    varargs: bool = False  # variadic inputs (Concat, ElementWiseSum, add_n)
    # infer_params(attrs, *known_input_shapes) -> {param_or_aux_name: shape}
    infer_params: Optional[Callable] = None
    # which positional args may be omitted (e.g. bias under no_bias)
    optional_args: Callable = None  # optional_args(attrs) -> set of dropped names
    # attr-dependent output count: num_outputs_fn(attrs) -> int
    num_outputs_fn: Callable = None
    attr_defaults: dict = field(default_factory=dict)
    doc: str = ""

    def resolve_arg_names(self, attrs) -> list:
        names = list(self.arg_names)
        if self.optional_args is not None:
            dropped = self.optional_args(attrs)
            names = [n for n in names if n not in dropped]
        return names


def register(
    name,
    *,
    arg_names=("data",),
    param_names=(),
    aux_names=(),
    num_outputs=1,
    output_names=("output",),
    needs_rng=False,
    varargs=False,
    infer_params=None,
    optional_args=None,
    attr_defaults=None,
    num_outputs_fn=None,
    aliases=(),
):
    """Decorator registering an op implementation under ``name``.

    Parity: MXNET_REGISTER_OP_PROPERTY (include/mxnet/operator.h:538) and
    NNVM_REGISTER_OP — collapsed into one mechanism.
    """

    def deco(fn):
        op = OpDef(
            name=name,
            fn=fn,
            arg_names=tuple(arg_names),
            param_names=tuple(param_names),
            aux_names=tuple(aux_names),
            num_outputs=num_outputs,
            output_names=tuple(output_names),
            needs_rng=needs_rng,
            varargs=varargs,
            infer_params=infer_params,
            optional_args=optional_args,
            attr_defaults=dict(attr_defaults or {}),
            num_outputs_fn=num_outputs_fn,
            doc=fn.__doc__ or "",
        )
        _OPS[name] = op
        for alias in aliases:
            _OPS[alias] = op
        return fn

    return deco


def get(name: str) -> OpDef:
    try:
        return _OPS[name]
    except KeyError:
        raise MXNetError(f"operator '{name}' is not registered") from None


def exists(name: str) -> bool:
    return name in _OPS


def list_ops() -> list:
    """Parity: MXSymbolListAtomicSymbolCreators introspection."""
    return sorted(_OPS)


# --------------------------------------------------------------------------
# Imperative dispatch with a jit cache.
#
# Key insight (SURVEY.md §7): the reference pays an engine-push per op; we
# pay a dict lookup + PjRt async dispatch of a cached executable.  The cache
# key is (op, static attrs, is_train); jax.jit's internal cache handles
# shape/dtype polymorphism beneath it.
# --------------------------------------------------------------------------
@functools.lru_cache(maxsize=8192)
def _jitted(name: str, fattrs: tuple, is_train: bool, with_key: bool):
    op = _OPS[name]
    attrs = {k: v for k, v in fattrs}

    if with_key:

        def run(key, *inputs):
            ctx = OpCtx(is_train=is_train, key=key)
            return op.fn(ctx, *inputs, **attrs)

    else:

        def run(*inputs):
            ctx = OpCtx(is_train=is_train)
            return op.fn(ctx, *inputs, **attrs)

    return jax.jit(run)


def invoke(name: str, inputs, attrs=None, is_train: bool = True, key=None):
    """Imperative op invocation on raw jax arrays.

    Parity: MXImperativeInvoke (src/c_api/c_api_ndarray.cc:19-280).
    Returns raw outputs (single array, tuple, or (outs, aux) for aux ops —
    imperative calls of aux ops drop the aux updates, as the reference's
    imperative BatchNorm does with its in-place aux TBlobs).
    """
    op = get(name)
    attrs = dict(attrs or {})
    if op.needs_rng and key is None:
        from .. import random as _random

        key = _random.next_key()
    fn = _jitted(op.name, frozen_attrs(attrs), bool(is_train), key is not None)
    from .. import profiler as _prof

    if _prof.is_running() and _prof.mode() == "all":
        # parity: imperative ops profiled under mode='all'
        # (MXNET_PROFILER_MODE, env_var.md:64-67); sync for accurate dur
        holder = {}

        def _sync():
            import jax as _jax

            if "out" in holder:
                _jax.block_until_ready(holder["out"])

        with _prof.span(op.name, category="imperative", sync=_sync):
            holder["out"] = out = fn(key, *inputs) if key is not None else fn(*inputs)
    else:
        out = fn(key, *inputs) if key is not None else fn(*inputs)
    from .. import engine

    engine.on_push(out)
    return out
