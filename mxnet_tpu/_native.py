"""FFI bindings for libmxtpu, the native C++ runtime.

Parity rationale (SURVEY.md §2.1): the reference's engine, storage
manager and RecordIO layer are C++; this module loads our TPU-native C++
equivalents (src/*.cc) and exposes them to Python.  Everything degrades
gracefully: if the library is missing it is built on demand with g++, and
if that fails the callers fall back to their pure-Python paths.

Two interchangeable FFI backends (parity: SURVEY.md §2.3, the
reference's `_ctypes/` vs `cython/` pair selected by
MXNET_ENABLE_CYTHON, `python/mxnet/base.py`):

- ``ctypes`` — the CDLL bindings below; always available wherever the
  native library itself is.
- ``cext`` — `_mxtpu_ext.so` (src/py_ext.cc), a CPython-C-API module
  linked against the SAME libmxtpu (rpath $ORIGIN), so both backends
  drive one engine scheduler and one storage pool and are
  interchangeable mid-process.  Record batches come back as a list of
  bytes built in one crossing, and engine ops carry a plain INCREF'd
  callable instead of a per-op ctypes CFUNCTYPE trampoline.

The global default is the compiled backend when it loads, like the
reference; ``MXTPU_FFI=ctypes|cext`` pins it, and every wrapper class
takes ``backend=`` for per-object override (tests A/B them in-process,
tests/test_ffi_backends.py).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_LIB = None
_LIB_LOCK = threading.Lock()
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_LIB_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "lib", "libmxtpu.so")

ENGINE_FN = ctypes.CFUNCTYPE(None, ctypes.c_void_p)


def _build():
    src_dir = os.path.join(_REPO_ROOT, "src")
    if not os.path.isdir(src_dir):
        return False
    try:
        subprocess.run(["make", "-C", src_dir], check=True,
                       capture_output=True, timeout=120)
        return os.path.isfile(_LIB_PATH)
    except Exception:
        return False


def _bind(lib):
    lib.mxe_create.restype = ctypes.c_void_p
    lib.mxe_create.argtypes = [ctypes.c_int]
    lib.mxe_destroy.argtypes = [ctypes.c_void_p]
    lib.mxe_new_var.restype = ctypes.c_int64
    lib.mxe_new_var.argtypes = [ctypes.c_void_p]
    lib.mxe_push.restype = ctypes.c_int
    lib.mxe_push.argtypes = [
        ctypes.c_void_p, ENGINE_FN, ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int, ctypes.c_int]
    lib.mxe_push_ex.restype = ctypes.c_int
    lib.mxe_push_ex.argtypes = [
        ctypes.c_void_p, ENGINE_FN, ctypes.c_void_p, ENGINE_FN,
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int, ctypes.c_int]
    lib.mxe_wait_for_var.restype = ctypes.c_int
    lib.mxe_wait_for_var.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.mxe_wait_all.argtypes = [ctypes.c_void_p]
    lib.mxe_pending.restype = ctypes.c_int64
    lib.mxe_pending.argtypes = [ctypes.c_void_p]

    lib.mxr_open.restype = ctypes.c_void_p
    lib.mxr_open.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
    lib.mxr_close.argtypes = [ctypes.c_void_p]
    lib.mxr_reset.argtypes = [ctypes.c_void_p]
    lib.mxr_next.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.mxr_next.argtypes = [ctypes.c_void_p,
                             ctypes.POINTER(ctypes.c_uint64)]
    lib.mxr_next_batch.restype = ctypes.c_int64
    lib.mxr_next_batch.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_int64]
    lib.mxr_index.restype = ctypes.c_int64
    lib.mxr_index.argtypes = [ctypes.c_char_p,
                              ctypes.POINTER(ctypes.c_uint64),
                              ctypes.c_int64]
    lib.mxr_writer_open.restype = ctypes.c_void_p
    lib.mxr_writer_open.argtypes = [ctypes.c_char_p]
    lib.mxr_write.restype = ctypes.c_int
    lib.mxr_write.argtypes = [ctypes.c_void_p,
                              ctypes.POINTER(ctypes.c_uint8),
                              ctypes.c_uint64]
    lib.mxr_writer_close.argtypes = [ctypes.c_void_p]

    lib.mxs_alloc.restype = ctypes.c_void_p
    lib.mxs_alloc.argtypes = [ctypes.c_uint64]
    lib.mxs_free.argtypes = [ctypes.c_void_p]
    lib.mxs_direct_free.argtypes = [ctypes.c_void_p]
    lib.mxs_pool_bytes.restype = ctypes.c_uint64
    lib.mxs_release_all.argtypes = []

    return lib


def get_lib():
    """The loaded libmxtpu, or None when native support is unavailable."""
    global _LIB
    if _LIB is not None:
        return _LIB if _LIB is not False else None
    with _LIB_LOCK:
        if _LIB is not None:
            return _LIB if _LIB is not False else None
        if not os.path.isfile(_LIB_PATH) and not _build():
            _LIB = False
            return None
        try:
            _LIB = _bind(ctypes.CDLL(_LIB_PATH))
        except OSError:
            _LIB = False
            return None
        except AttributeError:
            # a stale prebuilt .so predating a required symbol: rebuild
            # once, then keep the documented graceful fallback to the
            # pure-Python paths rather than letting AttributeError escape
            try:
                # make would consider a freshly-copied stale .so up to
                # date; force the relink
                os.unlink(_LIB_PATH)
            except OSError:
                pass
            try:
                if _build():
                    _LIB = _bind(ctypes.CDLL(_LIB_PATH))
                    return _LIB
            except (OSError, AttributeError):
                pass
            _LIB = False
            return None
        return _LIB


def available() -> bool:
    return get_lib() is not None


# --------------------------------------------------------------------------
# Compiled FFI backend (_mxtpu_ext.so)
# --------------------------------------------------------------------------
_EXT = None
_EXT_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "lib", "_mxtpu_ext.so")


def get_ext():
    """The compiled FFI module, or None when it cannot be loaded."""
    global _EXT
    if _EXT is not None:
        return _EXT if _EXT is not False else None
    # resolve the core lib BEFORE taking the lock: get_lib() takes the
    # same non-reentrant _LIB_LOCK (and triggers the on-demand make,
    # which builds the ext too)
    if get_lib() is None:
        _EXT = False
        return None
    with _LIB_LOCK:
        if _EXT is not None:
            return _EXT if _EXT is not False else None
        if not os.path.isfile(_EXT_PATH):
            _build()
        try:
            import importlib.machinery
            import importlib.util

            loader = importlib.machinery.ExtensionFileLoader(
                "_mxtpu_ext", _EXT_PATH)
            spec = importlib.util.spec_from_loader("_mxtpu_ext", loader)
            mod = importlib.util.module_from_spec(spec)
            loader.exec_module(mod)
            _EXT = mod
        except (OSError, ImportError):
            _EXT = False
            return None
        return _EXT


def ffi_backend(override=None) -> str:
    """Resolve the FFI backend name: explicit override > MXTPU_FFI env >
    compiled-if-available (the reference's MXNET_ENABLE_CYTHON default)."""
    choice = override or os.environ.get("MXTPU_FFI", "").strip().lower()
    if choice == "cext":
        if get_ext() is None:
            raise RuntimeError("MXTPU_FFI=cext but _mxtpu_ext.so is "
                               "unavailable")
        return "cext"
    if choice == "ctypes":
        return "ctypes"
    if choice:
        raise ValueError(f"unknown FFI backend {choice!r} "
                         "(expected 'ctypes' or 'cext')")
    return "cext" if get_ext() is not None else "ctypes"


# --------------------------------------------------------------------------
# Engine wrapper
# --------------------------------------------------------------------------
class NativeEngine:
    """Host-side async dependency engine (parity: Engine::PushAsync /
    NewVariable / WaitForVar / WaitForAll, include/mxnet/engine.h:75-229).

    Python callables are pushed with read (const_vars) / write
    (mutable_vars) dependencies; the C++ scheduler guarantees writers
    serialize and readers parallelize per var.  Exceptions inside
    callbacks are captured and re-raised at the next wait point.

    Runs on either FFI backend (``backend='ctypes'|'cext'``, default
    per ffi_backend()); the engine semantics are identical — the cext
    path just skips the per-op CFUNCTYPE trampoline and the Python-side
    closure-lifetime registry (the C module owns the op's ref).
    """

    def __init__(self, num_threads=0, backend=None):
        self._be = ffi_backend(backend)
        if self._be == "cext":
            ext = get_ext()
            self._ext = ext
            self._handle = ext.eng_create(int(num_threads))
            self._errors = []
            import atexit

            atexit.register(self._shutdown)
            return
        lib = get_lib()
        if lib is None:
            raise RuntimeError("libmxtpu unavailable")
        self._lib = lib
        self._handle = lib.mxe_create(int(num_threads))
        self._callbacks = {}          # keep CFUNCTYPE refs alive
        self._retired = []            # tokens safe to free (see _on_retire)
        self._cb_lock = threading.Lock()
        self._cb_id = 0
        self._errors = []
        # ONE persistent retirement trampoline shared by every op: the C
        # worker invokes it with the op's token strictly AFTER the op's
        # own closure returned (mxe_push_ex contract), making it the
        # provably-safe release point for that closure.  This CFUNCTYPE
        # itself is never freed while the engine lives.
        self._retire_cb = ENGINE_FN(self._on_retire)
        # tear down while the interpreter can still service callbacks —
        # a worker hitting a Python trampoline during interpreter
        # finalization would crash
        import atexit

        atexit.register(self._shutdown)

    def _shutdown(self):
        if getattr(self, "_handle", None) is None:
            return
        if self._be == "cext":
            try:
                self._ext.eng_wait_all(self._handle)
                self._ext.eng_destroy(self._handle)
            finally:
                self._handle = None
            return
        try:
            self._lib.mxe_wait_all(self._handle)
            self._reap()
            self._lib.mxe_destroy(self._handle)
        finally:
            self._handle = None

    def new_var(self) -> int:
        if self._be == "cext":
            return int(self._ext.eng_new_var(self._handle))
        return int(self._lib.mxe_new_var(self._handle))

    def _on_retire(self, token_ptr):
        # runs on a C worker thread AFTER the op closure fully unwound
        with self._cb_lock:
            self._retired.append(int(token_ptr or 0))

    def push(self, fn, const_vars=(), mutable_vars=(), priority=0):
        if self._be == "cext":
            self._ext.eng_push(self._handle, fn, tuple(const_vars),
                               tuple(mutable_vars), self._errors,
                               int(priority))
            return
        self._reap()
        with self._cb_lock:
            self._cb_id += 1
            token = self._cb_id

        def trampoline(_ctx, _fn=fn):
            try:
                _fn()
            except BaseException as e:  # surfaced at wait points
                self._errors.append(e)

        cfn = ENGINE_FN(trampoline)
        with self._cb_lock:
            self._callbacks[token] = cfn
        nc, nm = len(const_vars), len(mutable_vars)
        carr = (ctypes.c_int64 * max(nc, 1))(*const_vars)
        marr = (ctypes.c_int64 * max(nm, 1))(*mutable_vars)
        rc = self._lib.mxe_push_ex(self._handle, cfn, None, self._retire_cb,
                                   ctypes.c_void_p(token), carr, nc, marr,
                                   nm, int(priority))
        if rc != 0:
            with self._cb_lock:
                self._callbacks.pop(token, None)
            if rc == -2:
                raise ValueError(
                    "unknown engine var id in const/mutable var lists "
                    "(freed, or created on a different engine?)")
            raise ValueError(
                "duplicate or overlapping const/mutable var lists "
                "(parity: ThreadedEngine::CheckDuplicate)")

    def _reap(self):
        """Free closures of retired ops.  Safe at ANY time from ANY
        thread: a token only enters _retired from the C-side retirement
        hook, which fires strictly after the op's trampoline returned."""
        with self._cb_lock:
            for token in self._retired:
                self._callbacks.pop(token, None)
            self._retired.clear()

    def wait_for_var(self, var: int):
        if self._be == "cext":
            self._ext.eng_wait_for_var(self._handle, int(var))
        else:
            self._lib.mxe_wait_for_var(self._handle, int(var))
            self._reap()
        self._raise_pending()

    def wait_all(self):
        if self._be == "cext":
            self._ext.eng_wait_all(self._handle)
        else:
            self._lib.mxe_wait_all(self._handle)
            self._reap()
        self._raise_pending()

    def pending(self) -> int:
        if self._be == "cext":
            return int(self._ext.eng_pending(self._handle))
        return int(self._lib.mxe_pending(self._handle))

    def _raise_pending(self):
        if self._errors:
            err = self._errors.pop(0)
            raise err

    def __del__(self):
        try:
            self._shutdown()
        except Exception:
            pass


# --------------------------------------------------------------------------
# RecordIO wrappers
# --------------------------------------------------------------------------
class NativeRecordReader:
    """Sharded sequential RecordIO reader (parity: dmlc::InputSplit +
    RecordIOChunkReader as used by iter_image_recordio.cc:259-368)."""

    def __init__(self, path, part_index=0, num_parts=1, backend=None):
        self._be = ffi_backend(backend)
        if self._be == "cext":
            self._ext = get_ext()
            self._handle = self._ext.rec_open(path, int(part_index),
                                              int(num_parts))
            return
        lib = get_lib()
        if lib is None:
            raise RuntimeError("libmxtpu unavailable")
        self._lib = lib
        self._handle = lib.mxr_open(path.encode(), int(part_index),
                                    int(num_parts))
        if not self._handle:
            raise IOError(f"cannot open {path}")

    def read(self):
        """Next record payload as bytes, or None at end of shard."""
        if self._be == "cext":
            return self._ext.rec_next(self._handle)
        length = ctypes.c_uint64()
        ptr = self._lib.mxr_next(self._handle, ctypes.byref(length))
        if not ptr:
            return None
        return ctypes.string_at(ptr, length.value)

    def read_batch(self, max_records=1024, buf_bytes=1 << 24):
        """Up to max_records payloads with ONE FFI crossing (the
        per-record crossing is what makes naive native readers lose to
        Python's buffered file IO).  The cext backend builds the bytes
        list inside the crossing — no staging buffer at all."""
        if self._be == "cext":
            return self._ext.rec_next_batch(self._handle, int(max_records))
        if not hasattr(self, "_batch_buf") or len(self._batch_buf) < buf_bytes:
            self._batch_buf = (ctypes.c_uint8 * buf_bytes)()
            self._batch_lens = (ctypes.c_uint64 * max(max_records, 1024))()
        if len(self._batch_lens) < max_records:
            self._batch_lens = (ctypes.c_uint64 * max_records)()
        n = self._lib.mxr_next_batch(self._handle, self._batch_buf,
                                     buf_bytes, self._batch_lens,
                                     max_records)
        if n <= 0:
            # either true end-of-shard, or a single record larger than
            # buf_bytes (the C side rewinds it): fall back to the
            # resizable per-record path so oversized records are not
            # silently dropped as EOF
            rec = self.read()
            return [rec] if rec is not None else []
        raw = memoryview(self._batch_buf)
        # numpy view over lens: ctypes element access is ~1us each and
        # dominates at high record rates
        lens = np.frombuffer(self._batch_lens, dtype=np.uint64, count=n)
        ends = np.cumsum(lens)
        starts = ends - lens
        return [bytes(raw[int(s):int(e)]) for s, e in zip(starts, ends)]

    def reset(self):
        if self._be == "cext":
            self._ext.rec_reset(self._handle)
            return
        self._lib.mxr_reset(self._handle)

    def close(self):
        if self._handle is None:
            return
        if self._be == "cext":
            self._ext.rec_close(self._handle)
        else:
            self._lib.mxr_close(self._handle)
        self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __iter__(self):
        while True:
            rec = self.read()
            if rec is None:
                return
            yield rec


def native_index(path, backend=None):
    """Offsets of every record in a RecordIO file (fast .idx rebuild).

    Two-pass: mxr_index counts records past the cap without writing, so
    a cap-0 call sizes the buffer exactly (no 128MB worst-case alloc)."""
    if ffi_backend(backend) == "cext":
        return np.asarray(get_ext().rec_index(path), dtype=np.uint64)
    lib = get_lib()
    if lib is None:
        raise RuntimeError("libmxtpu unavailable")
    total = lib.mxr_index(path.encode(), (ctypes.c_uint64 * 1)(), 0)
    if total < 0:
        raise IOError(f"cannot open {path}")
    buf = (ctypes.c_uint64 * max(total, 1))()
    n = lib.mxr_index(path.encode(), buf, total)
    if n < 0:
        raise IOError(f"cannot open {path}")
    n = min(n, total)
    return np.ctypeslib.as_array(buf, shape=(max(total, 1),))[:n].copy()


class NativeRecordWriter:
    def __init__(self, path, backend=None):
        self._be = ffi_backend(backend)
        if self._be == "cext":
            self._ext = get_ext()
            self._handle = self._ext.rec_writer_open(path)
            return
        lib = get_lib()
        if lib is None:
            raise RuntimeError("libmxtpu unavailable")
        self._lib = lib
        self._handle = lib.mxr_writer_open(path.encode())
        if not self._handle:
            raise IOError(f"cannot open {path} for writing")

    def write(self, buf: bytes):
        if self._be == "cext":
            self._ext.rec_write(self._handle, buf)
            return
        arr = (ctypes.c_uint8 * len(buf)).from_buffer_copy(buf)
        if self._lib.mxr_write(self._handle, arr, len(buf)) != 0:
            raise IOError("record write failed")

    def close(self):
        if self._handle is None:
            return
        if self._be == "cext":
            self._ext.rec_writer_close(self._handle)
        else:
            self._lib.mxr_writer_close(self._handle)
        self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# --------------------------------------------------------------------------
# Storage arena wrapper
# --------------------------------------------------------------------------
class NativeArena:
    """Pooled host staging buffers (parity: Storage::Alloc/Free with
    GPUPooledStorageManager recycling).  Returns numpy views over
    arena-owned memory; free() recycles into the size-class pool."""

    def __init__(self, backend=None):
        self._be = ffi_backend(backend)
        if self._be == "cext":
            self._ext = get_ext()
            self._ptr_of = {}
            return
        lib = get_lib()
        if lib is None:
            raise RuntimeError("libmxtpu unavailable")
        self._lib = lib
        self._ptr_of = {}  # id(view) -> raw pointer (free() needs it even
                           # when free is the first call ever made)

    def alloc(self, shape, dtype=np.float32):
        dtype = np.dtype(dtype)
        count = int(np.prod(shape))
        nbytes = count * dtype.itemsize
        if self._be == "cext":
            ptr, view = self._ext.storage_alloc(max(nbytes, 1))
            arr = np.frombuffer(view, dtype=dtype, count=count)
            arr = arr.reshape(shape)
            self._ptr_of[ptr] = ptr
            return arr
        ptr = self._lib.mxs_alloc(max(nbytes, 1))
        if not ptr:
            raise MemoryError(f"arena alloc of {nbytes} bytes failed")
        buf = (ctypes.c_uint8 * max(nbytes, 1)).from_address(ptr)
        arr = np.frombuffer(buf, dtype=dtype, count=count)
        arr = arr.reshape(shape)
        arr.flags.writeable = True
        # key by the stable buffer address: id(arr) can be reused by CPython
        # after the view is collected, silently orphaning the native block
        self._ptr_of[ptr] = ptr
        return arr

    def free(self, arr):
        ptr = self._ptr_of.pop(int(arr.ctypes.data), None)
        if ptr is None:
            return
        if self._be == "cext":
            self._ext.storage_free(ptr)
        else:
            self._lib.mxs_free(ptr)

    def pool_bytes(self) -> int:
        if self._be == "cext":
            return int(self._ext.storage_pool_bytes())
        return int(self._lib.mxs_pool_bytes())

    def release_all(self):
        if self._be == "cext":
            self._ext.storage_release_all()
            return
        self._lib.mxs_release_all()


# --------------------------------------------------------------------------
# JPEG decode (parity: the reference's OpenCV/libjpeg decode inside OpenMP
# workers, iter_image_recordio.cc:259-368 — runs without the GIL so the
# decode thread pool actually scales)
# --------------------------------------------------------------------------
_JPEG_LIB = None
_JPEG_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "lib", "libmxtpu_jpeg.so")


def _get_jpeg_lib():
    """libmxtpu_jpeg.so is built separately from the core lib so a host
    without libjpeg-dev keeps full engine/recordio/storage support."""
    global _JPEG_LIB
    if _JPEG_LIB is not None:
        return _JPEG_LIB if _JPEG_LIB is not False else None
    with _LIB_LOCK:
        if _JPEG_LIB is not None:
            return _JPEG_LIB if _JPEG_LIB is not False else None
        if not os.path.isfile(_JPEG_PATH):
            _build()  # `make all` builds it when libjpeg is present
        try:
            lib = ctypes.CDLL(_JPEG_PATH)
            lib.mxj_dims.restype = ctypes.c_int
            lib.mxj_dims.argtypes = [
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64,
                ctypes.POINTER(ctypes.c_uint32),
                ctypes.POINTER(ctypes.c_uint32),
                ctypes.POINTER(ctypes.c_uint32)]
            lib.mxj_decode.restype = ctypes.c_int
            lib.mxj_decode.argtypes = [
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64,
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64]
            _JPEG_LIB = lib
        except (OSError, AttributeError):
            _JPEG_LIB = False
            return None
        return _JPEG_LIB


def decode_jpeg(buf) -> "np.ndarray | None":
    """Decode a JPEG byte string to an RGB uint8 HWC array via libjpeg.

    Returns None when native support is unavailable or the stream is not
    decodable (callers fall back to PIL)."""
    lib = _get_jpeg_lib()
    if lib is None:
        return None
    raw = bytes(buf)
    # borrow the bytes buffer directly (no copy); `raw` stays referenced
    # for the duration of both calls
    src = ctypes.cast(ctypes.c_char_p(raw), ctypes.POINTER(ctypes.c_uint8))
    w = ctypes.c_uint32()
    h = ctypes.c_uint32()
    c = ctypes.c_uint32()
    if lib.mxj_dims(src, len(raw), ctypes.byref(w), ctypes.byref(h),
                    ctypes.byref(c)) != 0:
        return None
    out = np.empty((h.value, w.value, 3), np.uint8)
    if lib.mxj_decode(src, len(raw),
                      out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                      out.nbytes) != 0:
        return None
    return out
