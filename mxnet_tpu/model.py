"""Legacy FeedForward estimator + shared training internals.

Parity: python/mxnet/model.py (reference): _create_kvstore (:40),
save_checkpoint/load_checkpoint (:319-385), FeedForward (:387).
Checkpoint format: ``prefix-symbol.json`` (graph JSON) +
``prefix-%04d.params`` (param dict with arg:/aux: prefixes, matching the
reference's NDArray::Save naming convention).
"""
from __future__ import annotations

import logging

import numpy as np

from . import ndarray as nd
from . import symbol as sym_mod
from .base import MXNetError


def _create_kvstore(kvstore, num_device, arg_params):
    """Parity: model.py:40-77 — decide (kvstore instance, update_on_kvstore)."""
    from . import kvstore as kvs

    update_on_kvstore = True
    if kvstore is None:
        kv = None
        update_on_kvstore = False
    elif isinstance(kvstore, kvs.KVStore):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore:
            kv = None
            update_on_kvstore = False
        else:
            kv = kvs.create(kvstore)
            if kvstore == "local":
                # reference: big params => aggregation-only local store
                max_size = max(
                    (int(np.prod(p.shape)) for p in arg_params.values()), default=0
                )
                if max_size > 1024 * 1024 * 16:
                    update_on_kvstore = False
    else:
        raise TypeError("kvstore must be KVStore, str or None")
    return (kv, update_on_kvstore if kv else False)


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params):
    """Parity: model.py:319 save_checkpoint."""
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    save_dict = {f"arg:{k}": v for k, v in arg_params.items()}
    save_dict.update({f"aux:{k}": v for k, v in aux_params.items()})
    nd.save(f"{prefix}-{epoch:04d}.params", save_dict)
    logging.info("Saved checkpoint to \"%s-%04d.params\"", prefix, epoch)


def load_checkpoint(prefix, epoch):
    """Parity: model.py:355 load_checkpoint -> (symbol, arg_params, aux_params)."""
    symbol = sym_mod.load(f"{prefix}-symbol.json")
    save_dict = nd.load(f"{prefix}-{epoch:04d}.params")
    arg_params, aux_params = {}, {}
    for k, v in save_dict.items():
        tp, name = k.split(":", 1)
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
    return (symbol, arg_params, aux_params)


class FeedForward:
    """Legacy estimator API (parity: model.py:387).

    Internally delegates to Module — the reference's
    _train_multi_device loop (model.py:132-316) is the same fit loop.
    """

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, allow_extra_params=False,
                 begin_epoch=0, learning_rate=0.01, **kwargs):
        from .initializer import Uniform

        self.symbol = symbol
        self.ctx = ctx
        self.num_epoch = num_epoch
        self.epoch_size = epoch_size
        self.optimizer = optimizer
        self.initializer = initializer or Uniform(0.01)
        self.numpy_batch_size = numpy_batch_size
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.allow_extra_params = allow_extra_params
        self.begin_epoch = begin_epoch
        self.learning_rate = learning_rate
        self.kwargs = dict(kwargs)
        self._module = None

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch, **kwargs)

    @staticmethod
    def create(symbol, X, y=None, ctx=None, num_epoch=None, epoch_size=None,
               optimizer="sgd", initializer=None, eval_data=None,
               eval_metric="acc", epoch_end_callback=None,
               batch_end_callback=None, kvstore="local", logger=None, **kwargs):
        """Parity: FeedForward.create (model.py) — build + fit."""
        model = FeedForward(symbol, ctx=ctx, num_epoch=num_epoch,
                            epoch_size=epoch_size, optimizer=optimizer,
                            initializer=initializer, **kwargs)
        model.fit(X, y, eval_data=eval_data, eval_metric=eval_metric,
                  epoch_end_callback=epoch_end_callback,
                  batch_end_callback=batch_end_callback, kvstore=kvstore,
                  logger=logger)
        return model

    def _init_iter(self, X, y, is_train):
        from .io import DataIter, NDArrayIter

        if isinstance(X, DataIter):
            return X
        if isinstance(X, (np.ndarray, nd.NDArray)):
            X = X.asnumpy() if isinstance(X, nd.NDArray) else X
            if y is not None:
                y = y.asnumpy() if isinstance(y, nd.NDArray) else np.asarray(y)
            batch = min(self.numpy_batch_size, X.shape[0])
            return NDArrayIter(X, y, batch_size=batch, shuffle=is_train,
                               last_batch_handle="discard" if is_train else "pad")
        raise TypeError("X must be DataIter or array")

    def _get_module(self, data_iter):
        from .module import Module

        data_names = [d[0] for d in data_iter.provide_data]
        label_names = [l[0] for l in data_iter.provide_label]
        ctx = self.ctx
        if ctx is not None and not isinstance(ctx, list):
            ctx = [ctx]
        return Module(self.symbol, data_names=data_names,
                      label_names=label_names, context=ctx)

    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            logger=None, work_load_list=None, monitor=None):
        train_data = self._init_iter(X, y, is_train=True)
        if eval_data is not None and isinstance(eval_data, tuple):
            eval_data = self._init_iter(eval_data[0], eval_data[1], is_train=False)
        self._module = self._get_module(train_data)
        optimizer_params = {"learning_rate": self.learning_rate}
        for k in ("momentum", "wd", "clip_gradient", "lr_scheduler", "rescale_grad"):
            if k in self.kwargs:
                optimizer_params[k] = self.kwargs[k]
        self._module.fit(
            train_data, eval_data=eval_data, eval_metric=eval_metric,
            epoch_end_callback=epoch_end_callback,
            batch_end_callback=batch_end_callback, kvstore=kvstore,
            optimizer=self.optimizer,
            optimizer_params=tuple(optimizer_params.items()),
            initializer=self.initializer, arg_params=self.arg_params,
            aux_params=self.aux_params, allow_missing=True,
            begin_epoch=self.begin_epoch, num_epoch=self.num_epoch,
            monitor=monitor)
        self.arg_params, self.aux_params = self._module.get_params()

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        data_iter = self._init_iter(X, None, is_train=False)
        if self._module is None or not self._module.binded:
            self._module = self._get_module(data_iter)
            self._module.bind(data_shapes=data_iter.provide_data,
                              label_shapes=data_iter.provide_label or None,
                              for_training=False)
            self._module.init_params(arg_params=self.arg_params,
                                     aux_params=self.aux_params,
                                     allow_missing=False)
        out = self._module.predict(data_iter, num_batch=num_batch, reset=reset)
        if isinstance(out, list):
            return [o.asnumpy() for o in out]
        return out.asnumpy()

    def score(self, X, y=None, eval_metric="acc", num_batch=None):
        data_iter = self._init_iter(X, y, is_train=False)
        if self._module is None:
            self._module = self._get_module(data_iter)
            self._module.bind(data_shapes=data_iter.provide_data,
                              label_shapes=data_iter.provide_label,
                              for_training=False)
            self._module.init_params(arg_params=self.arg_params,
                                     aux_params=self.aux_params)
        res = self._module.score(data_iter, eval_metric, num_batch=num_batch)
        return res[0][1]

    def save(self, prefix, epoch=None):
        if epoch is None:
            epoch = self.num_epoch or 0
        save_checkpoint(prefix, epoch, self.symbol, self.arg_params or {},
                        self.aux_params or {})
