"""Weight initializers (parity: python/mxnet/initializer.py).

Uniform/Normal/Orthogonal/Xavier/MSRAPrelu/Bilinear/One/Zero/Load/Mixed,
with the reference's name-based dispatch (``_weight`` -> init_weight,
``_bias``/``_gamma``/``_beta``/moving stats -> canonical defaults).
"""
from __future__ import annotations

import json
import re

import numpy as np

from . import ndarray as nd
from .base import MXNetError
from .ndarray import NDArray


class Initializer:
    def __call__(self, name, arr):
        if not isinstance(name, str):
            raise TypeError("name must be str")
        if name.startswith("upsampling") or name.endswith("_bilinear"):
            self._init_bilinear(name, arr)
        elif name.endswith("_gamma"):
            self._init_gamma(name, arr)
        elif name.endswith("_beta"):
            self._init_beta(name, arr)
        elif name.endswith("_weight"):
            self._init_weight(name, arr)
        elif name.endswith("_bias"):
            self._init_bias(name, arr)
        elif name.endswith("_moving_mean") or name.endswith("_moving_avg"):
            self._init_zero(name, arr)
        elif name.endswith("_moving_var"):
            self._init_one(name, arr)
        elif name.endswith("_parameters"):
            self._init_rnn_fused(name, arr)
        else:
            self._init_default(name, arr)

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), getattr(self, "_kwargs", {})])

    def _init_bilinear(self, name, arr):
        shape = arr.shape
        weight = np.zeros(int(np.prod(shape)), dtype=np.float32)
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(weight.size):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr[:] = weight.reshape(shape)

    def _init_zero(self, name, arr):
        arr[:] = 0.0

    def _init_one(self, name, arr):
        arr[:] = 1.0

    def _init_gamma(self, name, arr):
        arr[:] = 1.0

    def _init_beta(self, name, arr):
        arr[:] = 0.0

    def _init_bias(self, name, arr):
        arr[:] = 0.0

    def _init_weight(self, name, arr):
        raise NotImplementedError

    def _init_rnn_fused(self, name, arr):
        # the RNN op's flat cuDNN-style parameter vector: per-matrix
        # fan-in is unknowable from the 1-D shape, so use the cuDNN/
        # PyTorch-style small uniform.  (The reference's initializer
        # RAISES for this name; silently zeroing it kills gradient flow
        # through stacked layers — regression caught by speech-demo.)
        arr[:] = np.random.uniform(-0.07, 0.07, arr.shape).astype(np.float32)

    def _init_default(self, name, arr):
        arr[:] = 0.0


class Constant(Initializer):
    """Fill with a constant value regardless of the name pattern (used by
    per-variable ``init=`` attributes, e.g. SSD's conv4_3 L2-norm scale)."""

    def __init__(self, value=0.0):
        self.value = value
        self._kwargs = {"value": value}

    def __call__(self, name, arr):
        arr[:] = self.value


class Uniform(Initializer):
    def __init__(self, scale=0.07):
        self.scale = scale
        self._kwargs = {"scale": scale}

    def _init_weight(self, name, arr):
        arr[:] = np.random.uniform(-self.scale, self.scale, arr.shape).astype(np.float32)


class Normal(Initializer):
    def __init__(self, sigma=0.01):
        self.sigma = sigma
        self._kwargs = {"sigma": sigma}

    def _init_weight(self, name, arr):
        arr[:] = np.random.normal(0, self.sigma, arr.shape).astype(np.float32)


class One(Initializer):
    def _init_weight(self, name, arr):
        arr[:] = 1.0


class Zero(Initializer):
    def _init_weight(self, name, arr):
        arr[:] = 0.0


class Orthogonal(Initializer):
    """Parity: initializer.py Orthogonal (Saxe et al.)."""

    def __init__(self, scale=1.414, rand_type="uniform"):
        self.scale = scale
        self.rand_type = rand_type
        self._kwargs = {"scale": scale, "rand_type": rand_type}

    def _init_weight(self, name, arr):
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = np.random.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = np.random.normal(0.0, 1.0, (nout, nin))
        u, _, v = np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == (nout, nin) else v
        arr[:] = (self.scale * q).reshape(arr.shape).astype(np.float32)


class Xavier(Initializer):
    """Parity: initializer.py Xavier (rnd_type/factor_type/magnitude)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)
        self._kwargs = {"rnd_type": rnd_type, "factor_type": factor_type,
                        "magnitude": magnitude}

    def _init_weight(self, name, arr):
        shape = arr.shape
        hw_scale = float(np.prod(shape[2:])) if len(shape) > 2 else 1.0
        fan_in = shape[1] * hw_scale if len(shape) > 1 else shape[0]
        fan_out = shape[0] * hw_scale
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise MXNetError(f"invalid factor_type {self.factor_type}")
        scale = np.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            arr[:] = np.random.uniform(-scale, scale, shape).astype(np.float32)
        elif self.rnd_type == "gaussian":
            arr[:] = np.random.normal(0, scale, shape).astype(np.float32)
        else:
            raise MXNetError(f"invalid rnd_type {self.rnd_type}")


class MSRAPrelu(Xavier):
    """Parity: initializer.py MSRAPrelu."""

    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


class Bilinear(Initializer):
    def _init_weight(self, name, arr):
        self._init_bilinear(name, arr)


class Load:
    """Initialize from saved param dict, default-init the rest
    (parity: initializer.py Load)."""

    def __init__(self, param, default_init=None, verbose=False):
        if isinstance(param, str):
            param = nd.load(param)
        self.param = {}
        for name, arr in param.items():
            self.param[name.replace("arg:", "").replace("aux:", "")] = arr
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, name, arr):
        if name in self.param:
            if self.param[name].shape != arr.shape:
                raise MXNetError(f"shape mismatch for {name}")
            arr[:] = self.param[name].asnumpy()
        else:
            if self.default_init is None:
                raise MXNetError(f"no init for {name}")
            self.default_init(name, arr)


class Mixed:
    """Pattern-dispatched initializers (parity: initializer.py Mixed)."""

    def __init__(self, patterns, initializers):
        if len(patterns) != len(initializers):
            raise MXNetError("patterns and initializers must pair up")
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for pat, init in self.map:
            if pat.match(name):
                init(name, arr)
                return
        raise MXNetError(f"no initializer pattern matches {name}")


def create(dumps_json: str) -> Initializer:
    """Rebuild an initializer from Initializer.dumps() JSON — consumed by
    Module.init_params for per-variable ``init=`` symbol attributes
    (parity: the reference's InitDesc + __init__ attr protocol)."""
    name, kwargs = json.loads(dumps_json)
    registry = {
        "uniform": Uniform, "normal": Normal, "one": One, "zero": Zero,
        "constant": Constant, "orthogonal": Orthogonal, "xavier": Xavier,
        "msraprelu": MSRAPrelu, "bilinear": Bilinear,
    }
    if name not in registry:
        raise MXNetError(f"unknown initializer '{name}'")
    return registry[name](**kwargs)
