"""Device contexts.

Parity with python/mxnet/context.py (reference), re-targeted at TPU.
``mx.tpu(i)`` is first-class; ``mx.gpu(i)`` aliases the i-th accelerator so
reference scripts run unchanged.  A Context resolves lazily to a concrete
``jax.Device`` — on a CPU-only host (tests force JAX_PLATFORMS=cpu with 8
virtual devices) every context maps into the virtual device list, which is
how the reference's "multi-device on CPU-only machines" tests work
(tests/python/unittest/test_multi_device_exec.py).
"""
from __future__ import annotations

import jax


class Context:
    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 4: "tpu"}
    devstr2type = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "tpu": 4}
    _default_ctx = None

    def __init__(self, device_type, device_id: int = 0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        else:
            self.device_typeid = Context.devstr2type[device_type]
            self.device_id = device_id

    @property
    def device_type(self) -> str:
        return Context.devtype2str[self.device_typeid]

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_typeid == other.device_typeid
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_typeid, self.device_id))

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    def __enter__(self):
        self._old_ctx = Context._default_ctx
        Context._default_ctx = self
        return self

    def __exit__(self, *exc):
        Context._default_ctx = self._old_ctx

    # --- TPU-native resolution -------------------------------------------------
    @property
    def jax_device(self) -> "jax.Device":
        """Concrete jax.Device for this context.

        Accelerator contexts (tpu/gpu) prefer the default backend's devices;
        cpu contexts use the CPU backend.  device_id indexes modulo the
        available device count so reference scripts with gpu(0..3) still run
        on smaller topologies.
        """
        devs = _device_list(self.device_type)
        return devs[self.device_id % len(devs)]

    def empty_cache(self):  # parity: MXStorageEmptyCache; XLA manages pools
        return None


def _device_list(device_type: str):
    """Devices a Context's device_id indexes into: the devices THIS
    process can address.  Under jax.distributed `jax.devices()` is the
    global list — another host's device is non-addressable, and
    resolving `cpu(0)` there would make every NDArray constructor fail
    on rank > 0.  The process-spanning view lives in `process_mesh()`."""
    if device_type in ("gpu", "tpu"):
        default = jax.local_devices()
        if default and default[0].platform != "cpu":
            return default
        # CPU-only host: accelerator contexts fold onto virtual CPU devices.
        return jax.local_devices(backend="cpu")
    return jax.local_devices(backend="cpu")


def _global_device_list(device_type: str):
    """The cross-process device list (`process_mesh` spans hosts once
    jax.distributed is initialized — docs/multihost.md)."""
    if device_type in ("gpu", "tpu"):
        default = jax.devices()
        if default and default[0].platform != "cpu":
            return default
        return jax.devices("cpu")
    return jax.devices("cpu")


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def cpu_pinned(device_id: int = 0) -> Context:
    return Context("cpu_pinned", device_id)


def gpu(device_id: int = 0) -> Context:
    return Context("gpu", device_id)


def tpu(device_id: int = 0) -> Context:
    return Context("tpu", device_id)


def num_devices(device_type: str = "tpu") -> int:
    return len(_device_list(device_type))


def process_mesh():
    """The process-level ("batch", "model") device mesh (parallel.mesh.
    global_mesh over the accelerator devices; MXTPU_MESH_SHAPE picks the
    factorization, default pure data parallel).  This is what group2ctx
    PartitionSpec annotations and mesh-spanning executor groups resolve
    against — the named-axis replacement for raw device-id lists.  Once
    jax.distributed is initialized the mesh SPANS hosts (its "batch"
    axis grows across processes): the same SPMD program covers 8 chips
    or a pod slice, with GSPMD routing the cross-host collectives."""
    from .parallel.mesh import global_mesh

    return global_mesh(_global_device_list("tpu"))


def mesh_sharding(spec=None):
    """NamedSharding on the process mesh for a PartitionSpec (or a plain
    tuple of axis names / None spelled the PartitionSpec way).  ``None``
    means replicated.  The group2ctx value
    ``{"tp": mx.context.mesh_sharding(("model",))}`` places that group's
    parameters sharded over the mesh's model axis instead of pinning
    them to one device id."""
    from jax.sharding import NamedSharding, PartitionSpec

    if spec is None:
        spec = PartitionSpec()
    elif not isinstance(spec, PartitionSpec):
        spec = PartitionSpec(*spec) if isinstance(spec, (tuple, list)) \
            else PartitionSpec(spec)
    return NamedSharding(process_mesh(), spec)


def current_context() -> Context:
    if Context._default_ctx is None:
        Context._default_ctx = Context("cpu", 0)
    return Context._default_ctx


def default_accelerator_context() -> Context:
    """tpu(0) when an accelerator backend exists, else cpu(0)."""
    devs = jax.devices()
    if devs and devs[0].platform != "cpu":
        return tpu(0)
    return cpu(0)
