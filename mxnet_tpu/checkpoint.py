"""Async sharded checkpointing + preemption-safe resume — the survival
layer under the training loops (docs/fault_tolerance.md).

Production scale demands survival, not just speed (ROADMAP item 1): a
multi-day run must lose at most one checkpoint window to a preemption,
a corrupted file must fall back to the previous complete checkpoint
instead of training on garbage, and capture must not stall the
zero-per-batch-host-sync training loop the async stack (PR 4/5/7/10)
was built around.  Three properties carry the design:

- **capture is a device-side snapshot** — ``jnp.copy`` per array,
  dispatched asynchronously *behind* the in-flight training steps, so
  the snapshot reflects exactly the state after the last dispatched
  step without draining the AsyncWindow; the slow device→host fetch and
  the file IO run on a background writer thread (the Orbax-style async
  device snapshot, keyed the way our program cache keys artifacts —
  TVM arXiv:1802.04799 motivates persisting by structural signature),
- **a checkpoint is complete iff its manifest says so** — arrays land
  in a temp directory (write + flush + fsync per file), the manifest
  (per-array crc32 checksum, shape/dtype, shard layout, the bound
  graph's ``structural_signature``, step/epoch/batch cursor, RNG state)
  is written last, then ONE atomic ``os.replace`` publishes the
  directory.  A crash at any byte leaves either the previous complete
  checkpoint or a ``.tmp`` directory the next run sweeps,
- **resume never trusts a file** — :func:`latest` walks newest→oldest,
  re-hashing every array against the manifest, and falls back (with a
  warning) past truncated/corrupt checkpoints; a manifest whose
  structural signature disagrees with the current bind raises instead
  of loading mismatched weights.

Env knobs (docs/how_to/env_var.md round 15): ``MXTPU_CKPT_DIR`` (arming
the train loops), ``MXTPU_CKPT_EVERY`` (steps between snapshots,
default 0 = only on preemption/epoch), ``MXTPU_CKPT_KEEP`` (complete
checkpoints retained, default 3).
"""
from __future__ import annotations

import json
import logging
import os
import shutil
import signal
import threading
import time
import zlib

import numpy as np

from . import telemetry as _tm
from .base import MXNetError

__all__ = [
    "CheckpointError", "CheckpointCorrupt", "Preempted",
    "save", "load", "latest", "list_checkpoints", "validate",
    "CheckpointWrite", "CheckpointManager",
]

_logger = logging.getLogger("mxnet_tpu.checkpoint")

MANIFEST = "manifest.json"
_PREFIX = "ckpt-"
FORMAT_VERSION = 1

# --- telemetry families (docs/telemetry.md) --------------------------------
_TM_WRITE_SEC = _tm.histogram(
    "checkpoint_write_seconds",
    "wall time of one checkpoint write on the background writer thread "
    "(device->host fetch + file IO + fsync + atomic publish)")
_TM_BYTES = _tm.counter(
    "checkpoint_bytes_total",
    "array payload bytes written into published checkpoints")
_TM_RESUME = _tm.counter(
    "checkpoint_resume_total",
    "training-state restores (status=ok: newest complete checkpoint; "
    "fallback: a newer corrupt/incomplete checkpoint was skipped first)",
    labels=("status",))


class CheckpointError(MXNetError):
    """Checkpoint write/restore failure."""


class CheckpointCorrupt(CheckpointError):
    """A checkpoint that exists on disk but fails validation
    (truncated file, checksum mismatch, unreadable manifest)."""


class Preempted(MXNetError):
    """Raised by a training loop after a SIGTERM-triggered boundary
    checkpoint landed — the run was asked to die and its state is safe;
    the message carries the checkpoint path to resume from."""


# ------------------------------------------------------------------ env
def ckpt_dir():
    return os.environ.get("MXTPU_CKPT_DIR", "").strip() or None


def ckpt_every() -> int:
    try:
        return max(int(os.environ.get("MXTPU_CKPT_EVERY", "0") or 0), 0)
    except ValueError:
        return 0


def ckpt_keep() -> int:
    try:
        return max(int(os.environ.get("MXTPU_CKPT_KEEP", "3") or 3), 1)
    except ValueError:
        return 3


# ------------------------------------------------------------------ snapshot
def snapshot(arrays: dict) -> dict:
    """Device-side copy of every jax array in ``arrays`` (numpy values
    pass through).  The copies are dispatched asynchronously and ordered
    AFTER every in-flight donated-step program, so they capture the
    post-last-dispatched-step state without a host sync and without the
    next step's donation invalidating them.

    Multi-host exception (docs/multihost.md): on a mesh spanning other
    processes ``jnp.copy`` is a cross-process program, and checkpoint
    cadence is NOT symmetric across hosts (a busy writer skips a
    snapshot) — asymmetric collective dispatch deadlocks the fabric.
    Fully-replicated arrays therefore capture via a LOCAL host fetch
    (no program, no rendezvous); only non-replicated arrays keep the
    device copy, which their (symmetric, sharded-update) producers
    guarantee is dispatched on every host."""
    import jax
    import jax.numpy as jnp

    me = jax.process_index()
    out = {}
    for name, v in arrays.items():
        if isinstance(v, jax.Array):
            spans = any(d.process_index != me
                        for d in getattr(v.sharding, "device_set", ()))
            if spans and v.is_fully_replicated:
                out[name] = np.asarray(v)
            else:
                out[name] = jnp.copy(v)
        else:
            out[name] = np.asarray(v)
    return out


def _sharding_desc(v):
    try:
        sh = getattr(v, "sharding", None)
        if sh is None:
            return "host"
        spec = getattr(sh, "spec", None)
        ndev = len(getattr(sh, "device_set", ()) or ())
        return f"{type(sh).__name__}({spec})/{max(ndev, 1)}dev"
    except Exception:  # noqa: BLE001 — layout is advisory metadata
        return "unknown"


def _fsync_dir(path):
    try:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:
        pass  # platforms without directory fsync


class CheckpointWrite:
    """Handle for one (possibly background) checkpoint write.

    ``path`` is the final directory the write will publish; ``wait()``
    joins the writer and re-raises its error; ``alive`` says whether the
    writer is still running."""

    def __init__(self, path):
        self.path = path
        self.exc = None
        self._thread = None
        self.skipped = False

    @property
    def alive(self):
        return self._thread is not None and self._thread.is_alive()

    def wait(self):
        if self._thread is not None:
            self._thread.join()
        if self.exc is not None:
            raise self.exc
        return self.path


def save(directory, step, arrays: dict, meta=None, keep=None,
         background=True) -> CheckpointWrite:
    """Write checkpoint ``ckpt-<step>`` under ``directory``.

    ``arrays`` maps name -> jax array / numpy array; device arrays are
    snapshotted (async device copy) BEFORE this call returns, so the
    caller may keep training immediately — the device→host fetch and
    all file IO happen on the writer thread when ``background``.
    ``meta`` is JSON-serializable run state (step cursor, RNG key,
    signature, ...).  Retention prunes the oldest complete checkpoints
    beyond ``keep`` (default ``MXTPU_CKPT_KEEP``) after a successful
    publish.  Returns a :class:`CheckpointWrite`."""
    from . import faults as _faults

    directory = os.path.abspath(directory)
    os.makedirs(directory, exist_ok=True)
    keep = ckpt_keep() if keep is None else max(int(keep), 1)
    step = int(step)
    final = os.path.join(directory, f"{_PREFIX}{step:012d}")
    handle = CheckpointWrite(final)
    if os.path.isdir(final) and os.path.exists(
            os.path.join(final, MANIFEST)):
        handle.skipped = True  # this step is already published
        return handle
    snap = snapshot(arrays)
    meta = dict(meta or {})

    def _write():
        t0 = time.perf_counter()
        tmp = os.path.join(directory,
                           f".tmp-{_PREFIX}{step:012d}-{os.getpid()}")
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        try:
            # the injection site covers the whole write: an err here is
            # a writer crash mid-checkpoint — no manifest ever appears,
            # resume must fall back to the previous complete checkpoint
            _faults.maybe_fail("ckpt_write")
            entries = {}
            total = 0
            for i, (name, v) in enumerate(sorted(snap.items())):
                host = np.asarray(v)  # the device->host fetch
                fname = f"a{i:05d}.npy"
                fpath = os.path.join(tmp, fname)
                with open(fpath, "wb") as f:
                    np.save(f, host, allow_pickle=False)
                    f.flush()
                    os.fsync(f.fileno())
                with open(fpath, "rb") as f:
                    crc = zlib.crc32(f.read())
                entries[name] = {
                    "file": fname,
                    "shape": list(host.shape),
                    "dtype": str(host.dtype),
                    "crc32": int(crc),
                    "bytes": int(host.nbytes),
                    "sharding": _sharding_desc(snap[name]),
                }
                total += int(host.nbytes)
            manifest = {
                "version": FORMAT_VERSION,
                "step": step,
                "time": time.time(),
                "arrays": entries,
                "meta": meta,
            }
            mpath = os.path.join(tmp, MANIFEST)
            with open(mpath, "w") as f:
                json.dump(manifest, f, indent=1, default=str)
                f.flush()
                os.fsync(f.fileno())
            _fsync_dir(tmp)
            # one atomic publish: complete checkpoints are exactly the
            # directories holding a manifest under their final name
            if os.path.isdir(final):
                shutil.rmtree(final, ignore_errors=True)
            os.replace(tmp, final)
            _fsync_dir(directory)
            if _tm.enabled():
                _TM_BYTES.inc(total)
                _TM_WRITE_SEC.observe(time.perf_counter() - t0)
            _prune(directory, keep)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise

    if not background:
        _write()
        return handle

    def _runner():
        try:
            _write()
        except BaseException as e:  # noqa: BLE001 — surfaced via wait()
            handle.exc = e
            _logger.warning("background checkpoint write for step %d "
                            "failed: %r", step, e)

    t = threading.Thread(target=_runner, daemon=False,
                         name=f"mxtpu-ckpt-writer-{step}")
    handle._thread = t
    t.start()
    return handle


def _prune(directory, keep):
    """Retention: drop the oldest complete checkpoints beyond ``keep``
    and sweep stale temp directories from crashed writers."""
    complete = list_checkpoints(directory)
    for _, path in complete[:-keep] if keep else []:
        shutil.rmtree(path, ignore_errors=True)
    for name in os.listdir(directory):
        if name.startswith(".tmp-" + _PREFIX):
            full = os.path.join(directory, name)
            # only sweep another pid's leftovers / our published steps:
            # an in-flight writer's tmp dir ends with our live pid
            if not name.endswith(f"-{os.getpid()}"):
                shutil.rmtree(full, ignore_errors=True)


def list_checkpoints(directory):
    """``[(step, path)]`` of COMPLETE checkpoints (manifest present),
    oldest first.  Directories without a manifest are invisible —
    they are torn writes."""
    out = []
    if not directory or not os.path.isdir(directory):
        return out
    for name in os.listdir(directory):
        if not name.startswith(_PREFIX):
            continue
        path = os.path.join(directory, name)
        if not os.path.exists(os.path.join(path, MANIFEST)):
            continue
        try:
            step = int(name[len(_PREFIX):])
        except ValueError:
            continue
        out.append((step, path))
    out.sort()
    return out


def validate(path) -> dict:
    """Re-hash every array file against the manifest.  Returns the
    manifest dict; raises :class:`CheckpointCorrupt` naming the first
    offending file."""
    mpath = os.path.join(path, MANIFEST)
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as exc:
        raise CheckpointCorrupt(
            f"checkpoint {path!r}: unreadable manifest: {exc}") from exc
    if manifest.get("version") != FORMAT_VERSION:
        raise CheckpointCorrupt(
            f"checkpoint {path!r}: format version "
            f"{manifest.get('version')!r} (this build reads "
            f"{FORMAT_VERSION})")
    for name, ent in manifest.get("arrays", {}).items():
        fpath = os.path.join(path, ent["file"])
        try:
            with open(fpath, "rb") as f:
                crc = zlib.crc32(f.read())
        except OSError as exc:
            raise CheckpointCorrupt(
                f"checkpoint {path!r}: array {name!r} file "
                f"{ent['file']!r} unreadable: {exc}") from exc
        if crc != int(ent["crc32"]):
            raise CheckpointCorrupt(
                f"checkpoint {path!r}: array {name!r} failed its "
                f"checksum (file {ent['file']!r}) — truncated or "
                "corrupt")
    return manifest


def load(path, validate_data=True):
    """Read one checkpoint: ``(arrays, manifest)`` with arrays as host
    numpy, names as saved.  ``validate_data`` re-hashes first (resume
    always should; tooling that just peeks metadata may skip)."""
    manifest = validate(path) if validate_data else None
    if manifest is None:
        with open(os.path.join(path, MANIFEST)) as f:
            manifest = json.load(f)
    arrays = {}
    for name, ent in manifest.get("arrays", {}).items():
        arrays[name] = np.load(os.path.join(path, ent["file"]),
                               allow_pickle=False)
    return arrays, manifest


def latest(directory, validate_data=True):
    """Newest checkpoint that VALIDATES, or ``None``.

    Walks newest→oldest; a corrupt/truncated checkpoint is skipped with
    a warning (and counts a ``fallback`` resume when an older one is
    eventually used) — resuming on garbage is the one unacceptable
    outcome."""
    candidates = list_checkpoints(directory)
    fell_back = False
    for step, path in reversed(candidates):
        if not validate_data:
            return path
        try:
            validate(path)
            if fell_back and _tm.enabled():
                _TM_RESUME.inc(status="fallback")
            return path
        except CheckpointCorrupt as exc:
            fell_back = True
            _logger.warning(
                "skipping corrupt checkpoint %s (falling back to the "
                "previous complete one): %s", path, exc)
    return None


# ------------------------------------------------------------------ manager
class CheckpointManager:
    """Policy + lifecycle glue for a training loop.

    Owns the directory, the ``every``/``keep`` cadence, the in-flight
    background write (at most ONE — a slow writer skips the next
    snapshot rather than queueing unboundedly), and the SIGTERM
    preemption flag the loops poll at window boundaries."""

    def __init__(self, directory, every=None, keep=None):
        if not directory:
            raise MXNetError("CheckpointManager needs a directory "
                             "(set MXTPU_CKPT_DIR or pass one)")
        self.directory = os.path.abspath(directory)
        self.every = ckpt_every() if every is None else max(int(every), 0)
        self.keep = ckpt_keep() if keep is None else max(int(keep), 1)
        self._write = None
        self._last_step = None
        self.preempted = False
        self._prev_handler = None

    @classmethod
    def from_env(cls):
        """A manager when ``MXTPU_CKPT_DIR`` is set, else ``None``."""
        d = ckpt_dir()
        return cls(d) if d else None

    # -- cadence ---------------------------------------------------------
    def due(self, step) -> bool:
        """Should the loop snapshot at this step?  (Pure host-side int
        math — safe on the per-batch hot path.)"""
        if self.every <= 0:
            return False
        if self._last_step is not None and step <= self._last_step:
            return False
        return step % self.every == 0

    def save(self, step, arrays, meta=None, background=True):
        """Snapshot + write.  A still-running background write makes
        this a no-op (returns None) — checkpoints are best-effort
        overlap, and a writer slower than the cadence must not stack
        threads."""
        if self._write is not None and self._write.alive:
            if not background:
                self._write.wait()
            else:
                _logger.warning(
                    "checkpoint writer for step %s still running; "
                    "skipping the step-%d snapshot (slow storage? "
                    "raise MXTPU_CKPT_EVERY)",
                    os.path.basename(self._write.path), step)
                return None
        self._write = save(self.directory, step, arrays, meta=meta,
                           keep=self.keep, background=background)
        self._last_step = int(step)
        return self._write

    def wait(self):
        """Join the in-flight write (epoch/exit boundary)."""
        if self._write is not None:
            self._write.wait()

    def latest(self):
        return latest(self.directory)

    # -- preemption ------------------------------------------------------
    def install_preempt_handler(self):
        """SIGTERM -> set :attr:`preempted`; the training loop saves a
        boundary checkpoint and raises :class:`Preempted` at the next
        window boundary, so a preempted run loses at most one window.
        Chains any previous handler; main-thread only (no-op
        elsewhere)."""
        try:
            prev = signal.getsignal(signal.SIGTERM)

            def _handler(signum, frame):
                self.preempted = True
                _logger.warning("SIGTERM: checkpoint at the next window "
                                "boundary, then exiting")
                if callable(prev) and prev not in (signal.SIG_DFL,
                                                   signal.SIG_IGN):
                    prev(signum, frame)

            self._prev_handler = prev
            signal.signal(signal.SIGTERM, _handler)
            return True
        except (ValueError, OSError):  # non-main thread
            return False

    def uninstall_preempt_handler(self):
        if self._prev_handler is not None:
            try:
                signal.signal(signal.SIGTERM, self._prev_handler)
            except (ValueError, OSError):
                pass
            self._prev_handler = None


def resolve_resume(resume, manager=None):
    """Turn a ``fit(resume=...)`` value into a checkpoint path or None.

    ``True``/``"auto"`` discover the newest complete checkpoint in the
    manager's directory (or ``MXTPU_CKPT_DIR``); a string path is used
    directly — a directory of checkpoints resolves to its newest
    complete one, an explicit ``ckpt-*`` directory is validated as-is.
    """
    if resume in (None, False):
        return None
    if resume is True or resume == "auto":
        directory = manager.directory if manager is not None else ckpt_dir()
        if not directory:
            raise MXNetError(
                "resume=True needs a checkpoint directory: set "
                "MXTPU_CKPT_DIR or pass a CheckpointManager/path")
        return latest(directory)
    path = str(resume)
    if os.path.exists(os.path.join(path, MANIFEST)):
        validate(path)
        return path
    return latest(path)
