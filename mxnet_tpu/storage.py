"""Host storage pool surface (parity: include/mxnet/storage.h +
src/storage/pooled_storage_manager.h and the MXStorageEmptyCache C API).

Device memory belongs to PjRt/XLA on this stack; what the reference's
pooled storage manager still buys on TPU is HOST staging — the per-batch
buffers the data pipeline fills before `device_put`.  This module fronts
the native size-class arena (src/storage.cc via _native.NativeArena):

- ``staging_empty(shape, dtype)`` — pooled numpy buffer (recycled by
  power-of-two size class on ``staging_free``)
- ``pool_bytes()`` — bytes currently parked in free lists
- ``release_all()`` — drop the pool (parity: MXStorageEmptyCache)

``MXTPU_STORAGE_POOL=0`` disables pooling (plain numpy allocation), the
analogue of the reference's MXNET_GPU_MEM_POOL_RESERVE escape hatch;
numpy is also the automatic fallback when the native library is absent.

The built-in image iterators (image.py ImageIter/ImageRecordIter) route
their per-batch staging buffers through this pool via
``stage_to_device`` — copy-on-stage: the jax array is created with an
explicit copy (``jnp.array(buf)``), so the pooled buffer is recycled the
moment the call returns and can never alias a live device array (the
hazard that kept the pool unwired in earlier revisions).  Recycled
np.empty beats np.zeros per batch: no page-zeroing of the ~N MB batch
buffer on every iteration (measure with tools/bench_io.py --pool/
--no-pool).
"""
from __future__ import annotations

import threading

import numpy as np

from .base import get_env

_ARENA = None
_ARENA_LOCK = threading.Lock()
_DISABLED = object()


def _arena():
    global _ARENA
    if _ARENA is None:
        with _ARENA_LOCK:
            if _ARENA is None:  # racing first callers must share ONE
                # arena: buffers freed through a second instance would
                # never return to the pool
                if get_env("MXTPU_STORAGE_POOL", 1, int) == 0:
                    _ARENA = _DISABLED
                else:
                    try:
                        from ._native import NativeArena, available

                        _ARENA = NativeArena() if available() else _DISABLED
                    except Exception:
                        _ARENA = _DISABLED
    return _ARENA


def staging_empty(shape, dtype=np.float32):
    """A host buffer from the pool (uninitialized, like np.empty)."""
    if isinstance(shape, (int, np.integer)):
        shape = (int(shape),)
    a = _arena()
    if a is _DISABLED:
        return np.empty(shape, dtype)
    return a.alloc(tuple(shape), np.dtype(dtype))


def staging_free(arr):
    """Return a staging_empty buffer to the pool (no-op for plain numpy)."""
    a = _arena()
    if a is not _DISABLED:
        a.free(arr)


def stage_to_device(buf):
    """Copy a (pooled) host buffer into a fresh jax array and recycle it.

    jnp.array copies by default (unlike jnp.asarray, which may alias
    aligned host memory on the CPU backend), so by the time this returns
    the pool is free to hand ``buf`` to the next batch.
    """
    import jax.numpy as jnp

    arr = jnp.array(buf)
    staging_free(buf)
    return arr


def pool_bytes() -> int:
    """Bytes held in the pool's free lists (0 when pooling is off)."""
    a = _arena()
    return 0 if a is _DISABLED else a.pool_bytes()


def release_all():
    """Drop every pooled block (parity: MXStorageEmptyCache)."""
    a = _arena()
    if a is not _DISABLED:
        a.release_all()


class pooling_disabled:
    """Context manager: run a block with the staging pool off (plain
    numpy), restoring the previous arena afterwards — for A/B
    measurement (tools/bench_io.py) and tests."""

    def __enter__(self):
        global _ARENA
        self._saved = _ARENA
        _ARENA = _DISABLED
        return self

    def __exit__(self, *exc):
        global _ARENA
        _ARENA = self._saved
        return False
