"""Sharded-host multi-process input pipeline.

Parity: src/io/iter_image_recordio.cc:150-368 (reference) — there, one
process decodes with an OpenMP preprocess-thread pool because C++ threads
scale with cores.  A Python host adds GIL and allocator contention on the
numpy/augment stages, so the TPU-native equivalent fans the WHOLE
pipeline out across worker *processes*:

    worker p of N:  InputSplit shard p/N (filesystem.py, dmlc-core
                    semantics) -> jpeg decode -> augment -> batch,
                    written ONCE, straight into a shared-memory ring slot
                    (ImageRecordIter._next_into with ring views as the
                    output buffers — no pickling, no pipe copies)
    consumer:       pops finished slots, stages them through the pooled
                    host arena to the device (storage.stage_to_device),
                    recycles the slot

Stack ``MultiProcessImageRecordIter -> io.DevicePrefetchIter`` to overlap
the host pipeline with device compute.  Scaling is measured by the
default ``python tools/bench_io.py`` run (mp_pipeline rows); the design
scales decode with host cores x processes the way the reference's
preprocess_threads scales with cores (docs/how_to/perf.md Data-IO
section).
"""
from __future__ import annotations

import multiprocessing as mp
import os
import queue as queue_mod

import numpy as np

from . import ndarray as nd
from .base import MXNetError
from .io import DataBatch, DataIter
from .io import DataDesc


def _attach_shm(name):
    from multiprocessing import shared_memory
    import inspect

    # the parent owns the segments' lifetime: workers must attach WITHOUT
    # resource-tracker registration or the tracker double-unlinks (3.13+
    # has track=False for exactly this; 3.12's attach-path registration
    # is imbalanced, so there we unregister straight after attaching)
    if "track" in inspect.signature(
            shared_memory.SharedMemory.__init__).parameters:
        return shared_memory.SharedMemory(name=name, track=False)
    # pre-3.13 attach does not register with the resource tracker, so a
    # plain attach is already untracked (an explicit unregister here
    # would make the tracker's cache go negative and raise at exit)
    return shared_memory.SharedMemory(name=name)


def _worker(path, data_shape, batch_size, label_width, wid, num_workers,
            part_index, num_parts, slot_names, free_q, full_q, stop,
            barrier, seed, iter_kwargs):
    """Decode worker: runs the full shard->decode->augment->batch pipeline
    over InputSplit shard ``wid``/``num_workers``, writing each batch
    straight into a free ring slot.  Device-free by construction (only
    ImageRecordIter._next_into is used, never .next()).

    Epoch discipline: after a full pass over the shard the worker posts
    its "end" sentinel and WAITS at the shared barrier until the consumer
    has drained the epoch — otherwise a fast worker's next-epoch batches
    would interleave into the current epoch's count."""
    from .image import ImageRecordIter

    shms = {name: _attach_shm(name) for name in slot_names}
    data_elems = batch_size * int(np.prod(data_shape))
    # host-level sharding (part_index/num_parts, the distributed
    # contract) COMPOSES with the worker fan-out: this worker owns
    # global shard host_part*num_workers + wid of num_parts*num_workers
    it = ImageRecordIter(path_imgrec=path, data_shape=data_shape,
                         batch_size=batch_size, label_width=label_width,
                         part_index=part_index * num_workers + wid,
                         num_parts=num_parts * num_workers,
                         seed=seed + wid, **iter_kwargs)
    try:
        while not stop.is_set():
            it.reset()
            while True:
                slot = free_q.get()
                if slot is None or stop.is_set():
                    return
                buf = shms[slot].buf
                data = np.ndarray((batch_size,) + tuple(data_shape),
                                  np.float32, buffer=buf)
                labels = np.ndarray((batch_size, label_width), np.float32,
                                    buffer=buf, offset=data_elems * 4)
                try:
                    pad = it._next_into(data, labels)  # noqa: SLF001
                except StopIteration:
                    free_q.put(slot)  # hand the unused slot back
                    break
                except Exception as exc:  # noqa: BLE001
                    # a decode/augment failure must surface in the
                    # CONSUMER immediately (the single-process iterator
                    # raises in place; dying silently here would turn it
                    # into a stall_timeout hang)
                    import traceback

                    free_q.put(slot)
                    full_q.put(("error", wid,
                                "".join(traceback.format_exception(exc))))
                    return
                full_q.put(("batch", slot, pad))
            full_q.put(("end", wid))
            try:
                barrier.wait()  # consumer joins once the epoch is drained
            except Exception:  # noqa: BLE001 — aborted barrier = shutdown
                return
    finally:
        for shm in shms.values():
            shm.close()


class MultiProcessImageRecordIter(DataIter):
    """N-process RecordIO image pipeline over a shared-memory ring.

    path_imgrec/data_shape/batch_size/label_width and the augmentation
    kwargs match ImageRecordIter (each worker builds one over its own
    InputSplit shard).  ``num_workers`` decode processes publish finished
    batches into ``slots`` ring slots (default 2*workers+2).
    ``part_index``/``num_parts`` keep the distributed host-sharding
    contract: this host's shard is subdivided across its workers
    (global shard part_index*num_workers+wid of num_parts*num_workers).

    Epoch semantics: one epoch = every worker completing one pass over
    its shard (each worker wrap-pads its own final batch, like the
    reference's sharded iterators); workers free-run ahead into the next
    epoch while the consumer drains the current one.  ``close()`` (or
    garbage collection) shuts the processes down and unlinks the ring.
    """

    def __init__(self, path_imgrec, data_shape, batch_size, label_width=1,
                 num_workers=2, part_index=0, num_parts=1, slots=None,
                 seed=0, start_method=None, stall_timeout=300.0,
                 **iter_kwargs):
        super().__init__()
        from multiprocessing import shared_memory

        self.batch_size = int(batch_size)
        self.data_shape = tuple(int(x) for x in data_shape)
        self.label_width = int(label_width)
        self.num_workers = int(num_workers)
        if self.num_workers < 1:
            raise MXNetError("num_workers must be >= 1")
        self._stall_timeout = float(stall_timeout)
        # forkserver by default: plain fork of a parent whose jax/TPU
        # client already started threads is a deadlock class, and spawn
        # re-executes the parent's __main__ (breaks script/REPL parents);
        # the forkserver's clean server process forks device-free workers
        # that import only mxnet_tpu.mp_io
        default = "forkserver" if hasattr(os, "fork") else "spawn"
        method = start_method or os.environ.get("MXTPU_MP_START", default)
        ctx = mp.get_context(method)
        n_slots = int(slots) if slots else 2 * self.num_workers + 2
        data_elems = self.batch_size * int(np.prod(self.data_shape))
        slot_bytes = 4 * (data_elems + self.batch_size * self.label_width)
        self._data_elems = data_elems
        self._shms = [shared_memory.SharedMemory(create=True,
                                                 size=slot_bytes)
                      for _ in range(n_slots)]
        self._shm_by_name = {s.name: s for s in self._shms}
        self._free_q = ctx.Queue()
        for s in self._shms:
            self._free_q.put(s.name)
        self._full_q = ctx.Queue()
        self._stop = ctx.Event()
        # workers + consumer meet here at every epoch boundary (reusable)
        self._barrier = ctx.Barrier(self.num_workers + 1)
        self._ends = set()
        self._closed = False
        self._procs = [
            ctx.Process(
                target=_worker,
                args=(path_imgrec, self.data_shape, self.batch_size,
                      self.label_width, wid, self.num_workers,
                      int(part_index), int(num_parts),
                      [s.name for s in self._shms], self._free_q,
                      self._full_q, self._stop, self._barrier, seed,
                      iter_kwargs),
                daemon=True)
            for wid in range(self.num_workers)
        ]
        for p in self._procs:
            p.start()

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        return [DataDesc("softmax_label", shape)]

    def reset(self):
        # workers free-run; the consumer just opens the next epoch window
        pass

    def next(self):
        from . import telemetry as _tm
        from .io import _TM_BATCHES

        with _tm.span("MultiProcessImageRecordIter.next",
                      category="data-io",
                      histogram_name="data_batch_wait_seconds",
                      iterator="MultiProcessImageRecordIter"):
            batch = self._next_impl()
        _TM_BATCHES.inc(iterator="MultiProcessImageRecordIter")
        return batch

    def _next_impl(self):
        from . import storage

        if self._closed:
            raise MXNetError("iterator is closed")
        while True:
            try:
                msg = self._full_q.get(timeout=self._stall_timeout)
            except queue_mod.Empty:
                dead = [w for w, p in enumerate(self._procs)
                        if not p.is_alive()]
                raise MXNetError(
                    f"input workers stalled for {self._stall_timeout}s "
                    f"(dead workers: {dead or 'none'})") from None
            if msg[0] == "error":
                raise MXNetError(
                    f"input worker {msg[1]} failed:\n{msg[2]}")
            if msg[0] == "end":
                self._ends.add(msg[1])
                if len(self._ends) == self.num_workers:
                    self._ends = set()
                    self._barrier.wait(timeout=self._stall_timeout)
                    raise StopIteration
                continue
            _, slot, pad = msg
            buf = self._shm_by_name[slot].buf
            view = np.ndarray((self.batch_size,) + self.data_shape,
                              np.float32, buffer=buf)
            lview = np.ndarray((self.batch_size, self.label_width),
                               np.float32, buffer=buf,
                               offset=self._data_elems * 4)
            # one copy into the pooled staging arena (recycled by
            # stage_to_device), then the slot goes straight back to the
            # ring — the consumer never blocks on device transfer
            data = storage.staging_empty(
                (self.batch_size,) + self.data_shape, np.float32)
            np.copyto(data, view)
            labels = lview.copy()
            self._free_q.put(slot)
            label_out = labels[:, 0] if self.label_width == 1 else labels
            return DataBatch([nd.NDArray(storage.stage_to_device(data))],
                             [nd.array(label_out)], pad=pad)

    def close(self):
        """Stop workers, drain the ring, unlink the shared memory."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        try:
            self._barrier.abort()  # wake workers parked at an epoch end
        except Exception:  # noqa: BLE001
            pass
        for _ in self._procs:  # wake workers blocked on free_q.get()
            self._free_q.put(None)
        for p in self._procs:
            p.join(timeout=10)
            if p.is_alive():
                p.terminate()
                p.join(timeout=5)
        # drain queues so their feeder threads don't block interpreter exit
        for q in (self._full_q, self._free_q):
            try:
                while True:
                    q.get_nowait()
            except (queue_mod.Empty, OSError):
                pass
            q.close()
        for shm in self._shms:
            try:
                shm.close()
                shm.unlink()
            except Exception:  # noqa: BLE001 — double-close on interpreter exit
                pass
        self._shms = []

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass
