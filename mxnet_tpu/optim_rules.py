"""Pure multi-tensor optimizer update rules.

Shared by the two compiled update paths:

- ``trainer.FusedTrainer`` traces one rule per parameter inside the
  whole-step program (fwd+bwd+update in a single XLA computation),
- ``kvstore_fused.FusedUpdateEngine`` tree-maps one rule over every key
  of a flat bucket inside the bucketed kvstore update program (the
  Module path's jit-fused push).

Each rule builder takes the optimizer's static hyperparameters and
returns ``(init_state, update)`` closures over the fused jitted kernels
in ops/optimizer_ops.py, so clip+decay+update stays one XLA kernel per
tensor.  ``lr`` arrives per-call as a traced scalar — lr schedules (and
Adam's per-step bias correction, computed on host) never retrace the
compiled program.  ``wd_mult`` is a static per-tensor float and folds
into the compile.

AMP fp32 master weights (docs/amp.md) need NO rule variants: every
rule here is already pure fp32-capable elementwise math, so the bucket
programs simply run ``update(master, grad.astype(f32), rule_state,
...)`` against the fp32 master carried as the trailing state slot and
cast the fresh low-precision parameter afterwards — per-key, flat
(sharded), and sparse (row-gathered) forms alike.  The master layout
is owned by optimizer.create_state / kvstore_fused, keeping these
kernels bit-identical between fp32 and mixed-precision training.
"""
from __future__ import annotations

import jax.numpy as jnp

from . import ops
from .base import parse_bool


def _sgd_rule(opt_params):
    momentum = opt_params.get("momentum", 0.0)
    base_wd = float(opt_params.get("wd", 0.0))
    attrs = {k: opt_params[k] for k in ("rescale_grad", "clip_gradient")
             if k in opt_params}

    def init_state(w):
        return (jnp.zeros_like(w),) if momentum else ()

    def update(w, g, state, lr, wd_mult=1.0):
        octx = ops.OpCtx()
        wd = base_wd * wd_mult
        if momentum:
            new_w, new_m = ops.get("sgd_mom_update").fn(
                octx, w, g, state[0], momentum=momentum, lr=lr, wd=wd,
                **attrs)
            return new_w, (new_m,)
        return ops.get("sgd_update").fn(octx, w, g, lr=lr, wd=wd,
                                        **attrs), ()

    return init_state, update


def _adam_rule(opt_params):
    base_wd = float(opt_params.get("wd", 0.0))
    attrs = {k: opt_params[k] for k in ("rescale_grad",
                                       "clip_gradient", "beta1", "beta2",
                                       "epsilon") if k in opt_params}

    def init_state(w):
        return (jnp.zeros_like(w), jnp.zeros_like(w))

    def update(w, g, state, lr, wd_mult=1.0):
        octx = ops.OpCtx()
        new_w, m, v = ops.get("adam_update").fn(octx, w, g, state[0],
                                                state[1], lr=lr,
                                                wd=base_wd * wd_mult,
                                                **attrs)
        return new_w, (m, v)

    return init_state, update


def _rmsprop_rule(opt_params):
    if parse_bool(opt_params.get("centered", False)):
        # the centered (Alex Graves) variant carries 3 state slots and
        # different math — silently training the plain variant under a
        # centered config would diverge from the Module path (a bare
        # gamma2 key with centered=False is fine: the Module path also
        # ignores it for the plain variant)
        raise ValueError("the fused rmsprop rule is the plain "
                         "(Tieleman-Hinton) variant; use Module for "
                         "centered RMSProp")
    base_wd = float(opt_params.get("wd", 0.0))
    attrs = {k: opt_params[k] for k in ("rescale_grad", "clip_gradient",
                                       "gamma1", "epsilon",
                                       "clip_weights") if k in opt_params}

    def init_state(w):
        return (jnp.zeros_like(w),)

    def update(w, g, state, lr, wd_mult=1.0):
        octx = ops.OpCtx()
        new_w, n = ops.get("rmsprop_update").fn(
            octx, w, g, state[0], lr=lr, wd=base_wd * wd_mult, **attrs)
        return new_w, (n,)

    return init_state, update


_RULES = {"sgd": _sgd_rule, "adam": _adam_rule, "rmsprop": _rmsprop_rule}


# ---------------------------------------------------------------------------
# Flat-vector rule variants (the cross-replica sharded update path,
# arXiv:2004.13336).
#
# Every rule above is elementwise, so a whole flat bucket can run as ONE
# vector computation with per-ELEMENT lr/wd vectors instead of one
# per-key program slice — which is what lets the kvstore's sharded
# update split the bucket across mesh replicas with a plain
# with_sharding_constraint: each replica computes its 1/N slice, the
# optimizer state stays resident as the sharded flat vector, and the
# fresh parameters all-gather in-trace.  The math mirrors
# ops/optimizer_ops.py operation-for-operation (same multiply/add order,
# scalar hyperparams stay weakly-typed Python floats) so the sharded
# path is bit-compatible with the per-key bucket programs.
# ---------------------------------------------------------------------------
def _flat_prep(g, w, wd_el, opt_params):
    """_prep_grad over a flat vector: wd arrives per element (already
    base_wd * wd_mult, cast to the bucket dtype the way the weak-typed
    Python float in the per-key kernel would be)."""
    rescale = float(opt_params.get("rescale_grad", 1.0))
    clip = opt_params.get("clip_gradient", None)
    g = g * rescale
    if clip is not None and float(clip) > 0:
        g = jnp.clip(g, -float(clip), float(clip))
    return g + wd_el * w


def _sgd_flat(opt_params):
    momentum = opt_params.get("momentum", 0.0)

    def nslots():
        return 1 if momentum else 0

    def update(w, g, state, lr_el, wd_el):
        g = _flat_prep(g, w, wd_el, opt_params)
        if momentum:
            new_m = momentum * state[0] - lr_el * g
            return w + new_m, (new_m,)
        return w - lr_el * g, ()

    return nslots(), update


def _adam_flat(opt_params):
    beta1 = float(opt_params.get("beta1", 0.9))
    beta2 = float(opt_params.get("beta2", 0.999))
    eps = float(opt_params.get("epsilon", 1e-8))

    def update(w, g, state, lr_el, wd_el):
        m, v = state
        g = _flat_prep(g, w, wd_el, opt_params)
        new_m = beta1 * m + (1 - beta1) * g
        new_v = beta2 * v + (1 - beta2) * jnp.square(g)
        new_w = w - lr_el * new_m / (jnp.sqrt(new_v) + eps)
        return new_w, (new_m, new_v)

    return 2, update


def _rmsprop_flat(opt_params):
    if parse_bool(opt_params.get("centered", False)):
        raise ValueError("the fused rmsprop rule is the plain "
                         "(Tieleman-Hinton) variant; use Module for "
                         "centered RMSProp")
    gamma1 = float(opt_params.get("gamma1", 0.95))
    eps = float(opt_params.get("epsilon", 1e-8))
    clip_weights = opt_params.get("clip_weights", None)

    def update(w, g, state, lr_el, wd_el):
        n = state[0]
        g = _flat_prep(g, w, wd_el, opt_params)
        new_n = gamma1 * n + (1 - gamma1) * jnp.square(g)
        new_w = w - lr_el * g / jnp.sqrt(new_n + eps)
        if clip_weights is not None and float(clip_weights) > 0:
            cw = float(clip_weights)
            new_w = jnp.clip(new_w, -cw, cw)
        return new_w, (new_n,)

    return 1, update


_FLAT_RULES = {"sgd": _sgd_flat, "adam": _adam_flat, "rmsprop": _rmsprop_flat}


# ---------------------------------------------------------------------------
# Sparse (row-wise lazy) rule variants — the touched-rows-only update of
# the kvstore's sparse buckets (sparse.make_row_program).
#
# Every per-key rule above is elementwise over its tensor, so applying
# it to the GATHERED touched rows of an embedding table is the exact
# per-row math of the dense kernel — what changes is the *domain*: only
# rows a batch looked up are gathered, updated, and scattered back.
# That is the reference's lazy_update semantics: momentum/Adam state of
# an untouched row is not decayed, its weight sees no wd, and both stay
# byte-identical until the row is next touched.  (The dense path decays
# every row every step — the two paths agree exactly only for plain SGD
# with wd=0; the lazy difference is intentional and documented in
# docs/sparse.md.)
# ---------------------------------------------------------------------------
_SPARSE_NSLOTS = {"adam": 2, "rmsprop": 1}


def sparse_rule(rule_name, opt_params):
    """(n_state_slots, row_update) — the row-wise lazy variant of
    ``_RULES[rule_name]`` for sparse bucket programs, or ``None`` when
    the rule has no sparse form.  ``row_update`` IS the per-key rule's
    update closure (same fused kernels, same operand order), applied to
    gathered ``(rows, ...)`` stacks instead of whole tensors."""
    builder = _RULES.get(rule_name)
    if builder is None:
        return None
    _init, update = builder(dict(opt_params))
    if rule_name == "sgd":
        nslots = 1 if opt_params.get("momentum") else 0
    else:
        nslots = _SPARSE_NSLOTS[rule_name]
    return nslots, update


def flat_rule(rule_name, opt_params):
    """(n_state_slots, update) — the flat-vector variant of
    ``_RULES[rule_name]`` for the sharded bucket program, or ``None``
    when the rule has no flat form (the caller keeps the per-key
    replicated program)."""
    builder = _FLAT_RULES.get(rule_name)
    if builder is None:
        return None
    return builder(dict(opt_params))
