"""Pure multi-tensor optimizer update rules.

Shared by the two compiled update paths:

- ``trainer.FusedTrainer`` traces one rule per parameter inside the
  whole-step program (fwd+bwd+update in a single XLA computation),
- ``kvstore_fused.FusedUpdateEngine`` tree-maps one rule over every key
  of a flat bucket inside the bucketed kvstore update program (the
  Module path's jit-fused push).

Each rule builder takes the optimizer's static hyperparameters and
returns ``(init_state, update)`` closures over the fused jitted kernels
in ops/optimizer_ops.py, so clip+decay+update stays one XLA kernel per
tensor.  ``lr`` arrives per-call as a traced scalar — lr schedules (and
Adam's per-step bias correction, computed on host) never retrace the
compiled program.  ``wd_mult`` is a static per-tensor float and folds
into the compile.
"""
from __future__ import annotations

import jax.numpy as jnp

from . import ops
from .base import parse_bool


def _sgd_rule(opt_params):
    momentum = opt_params.get("momentum", 0.0)
    base_wd = float(opt_params.get("wd", 0.0))
    attrs = {k: opt_params[k] for k in ("rescale_grad", "clip_gradient")
             if k in opt_params}

    def init_state(w):
        return (jnp.zeros_like(w),) if momentum else ()

    def update(w, g, state, lr, wd_mult=1.0):
        octx = ops.OpCtx()
        wd = base_wd * wd_mult
        if momentum:
            new_w, new_m = ops.get("sgd_mom_update").fn(
                octx, w, g, state[0], momentum=momentum, lr=lr, wd=wd,
                **attrs)
            return new_w, (new_m,)
        return ops.get("sgd_update").fn(octx, w, g, lr=lr, wd=wd,
                                        **attrs), ()

    return init_state, update


def _adam_rule(opt_params):
    base_wd = float(opt_params.get("wd", 0.0))
    attrs = {k: opt_params[k] for k in ("rescale_grad",
                                       "clip_gradient", "beta1", "beta2",
                                       "epsilon") if k in opt_params}

    def init_state(w):
        return (jnp.zeros_like(w), jnp.zeros_like(w))

    def update(w, g, state, lr, wd_mult=1.0):
        octx = ops.OpCtx()
        new_w, m, v = ops.get("adam_update").fn(octx, w, g, state[0],
                                                state[1], lr=lr,
                                                wd=base_wd * wd_mult,
                                                **attrs)
        return new_w, (m, v)

    return init_state, update


def _rmsprop_rule(opt_params):
    if parse_bool(opt_params.get("centered", False)):
        # the centered (Alex Graves) variant carries 3 state slots and
        # different math — silently training the plain variant under a
        # centered config would diverge from the Module path (a bare
        # gamma2 key with centered=False is fine: the Module path also
        # ignores it for the plain variant)
        raise ValueError("the fused rmsprop rule is the plain "
                         "(Tieleman-Hinton) variant; use Module for "
                         "centered RMSProp")
    base_wd = float(opt_params.get("wd", 0.0))
    attrs = {k: opt_params[k] for k in ("rescale_grad", "clip_gradient",
                                       "gamma1", "epsilon",
                                       "clip_weights") if k in opt_params}

    def init_state(w):
        return (jnp.zeros_like(w),)

    def update(w, g, state, lr, wd_mult=1.0):
        octx = ops.OpCtx()
        new_w, n = ops.get("rmsprop_update").fn(
            octx, w, g, state[0], lr=lr, wd=base_wd * wd_mult, **attrs)
        return new_w, (n,)

    return init_state, update


_RULES = {"sgd": _sgd_rule, "adam": _adam_rule, "rmsprop": _rmsprop_rule}
