"""Monitor — per-tensor stat taps during execution.

Parity: python/mxnet/monitor.py (reference): installs an executor monitor
callback (GraphExecutor::SetMonitorCallback, graph_executor.cc:63), pattern
matches tensor names, and reports one statistic per matched tensor every
`interval` batches.  API-compatible (install/tic/toc/toc_print and the
(batch, name, stat_string) result rows); internals are this framework's
own: the tap accumulates finished records per flush window and formatting
is centralized in one scalar renderer.
"""
from __future__ import annotations

import logging
import re

from . import ndarray as nd
from .ndarray import NDArray


def _rms(x):
    """Default statistic: ||x||_2 / sqrt(n) — the root-mean-square of the
    tensor, matching the reference monitor's default."""
    return nd.norm(x) / float(max(x.size, 1)) ** 0.5


def _render(value):
    """One stat value -> display string.  stat_func may return a scalar
    NDArray, a python number, or a list of either."""
    items = value if isinstance(value, (list, tuple)) else [value]
    parts = []
    for item in items:
        if isinstance(item, NDArray) and item.size == 1:
            item = item.asscalar()
        parts.append(str(item))
    return "\t".join(parts) + "\t"


class Monitor:
    """Tap internal outputs of installed executors.

    interval:  flush window in batches (tic activates every interval-th)
    stat_func: NDArray -> stat (scalar NDArray / number / list); default
               root-mean-square
    pattern:   regex a tensor name must match to be recorded
    sort:      order toc() rows by tensor name
    """

    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        self.stat_func = stat_func if stat_func is not None else _rms
        self.interval = interval
        self.sort = sort
        self.re_prog = re.compile(pattern)
        self.activated = False
        self.step = 0
        self.exes = []
        self._records = []

    # executor callback (name, array) — records only while a tic window
    # is open and the name matches
    def stat_helper(self, name, array):
        if self.activated and self.re_prog.match(name):
            self._records.append((self.step, name, self.stat_func(array)))

    def install(self, exe):
        """Hook this monitor into an executor's internal-output taps."""
        exe.set_monitor_callback(self.stat_helper)
        self.exes.append(exe)

    def tic(self):
        """Open a recording window if this batch index is due.

        No device sync happens here: stat dispatch is async (stat_func
        runs as lazy NDArray math) and the read already lands in
        ``toc()``'s ``_render`` — a ``wait_to_read`` loop over every arg
        array per interval would serialize the training loop's bounded
        async window (``engine_pipeline_depth`` pinned to 0)."""
        if self.step % self.interval == 0:
            self._records = []
            self.activated = True
        self.step += 1

    def toc(self):
        """Close the window; returns [(batch, tensor_name, stat_str)]."""
        if not self.activated:
            return []
        self.activated = False
        taken, self._records = self._records, []
        if self.sort:
            taken.sort(key=lambda rec: rec[1])
        return [(batch, name, _render(value))
                for batch, name, value in taken]

    def toc_print(self):
        """toc() + log every row (the reference's printing entry point)."""
        for batch, name, stat in self.toc():
            logging.info("Batch: %7d %30s %s", batch, name, stat)

    # legacy alias kept for parity with the reference's internal name
    @property
    def queue(self):
        return self._records
