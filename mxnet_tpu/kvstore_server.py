"""Parameter-server process — the ``dist_*`` kvstore backend.

Parity: src/kvstore/kvstore_dist_server.h (reference) + python/mxnet/
kvstore_server.py.  The reference runs a ps-lite ``KVServer`` over ZMQ:
``DataHandle`` accumulates worker pushes into ``merge_buf_``; in **sync**
mode it waits for all workers, runs the updater once on the merged
gradient and replies to parked pulls (kvstore_dist_server.h:164-199); in
**async** mode it updates immediately per push (:200-210).  Controller
commands (kStopServer / kSyncMode / server_optimizer) arrive via
``CommandHandle`` (:121-133).

TPU-native redesign: on TPU pods the *synchronous* data-parallel path
does not need a parameter server at all — gradients ride ICI/DCN
collectives inside the compiled step (see parallel/mesh.py and
kvstore.py 'device').  The PS here exists for the semantics a collective
cannot express: ``dist_async`` (workers update a shared model without
barriers) and ``update_on_kvstore`` server-side optimizers.  Transport is
a length-prefixed-pickle TCP loop instead of ZMQ/ps-lite; everything
stays on the host (params live as numpy, the TPU is untouched), matching
the reference where server processes are CPU-only.

Launch contract (tools/launch.py): every process gets
``MXTPU_ROLE`` (worker|server), ``MXTPU_SERVER_RANK``,
``MXTPU_NUM_WORKERS``, ``MXTPU_NUM_SERVERS`` and ``MXTPU_PS_SERVERS``
(comma-separated host:port, one per server).  Server processes run the
*same user script* as workers: importing :mod:`mxnet_tpu` calls
:func:`_init_kvstore_server_module`, which (like the reference's
kvstore_server.py:70-90) detects the server role, serves until told to
stop, then exits the process.
"""
from __future__ import annotations

import io
import os
import pickle
import socket
import socketserver
import struct
import sys
import threading
import time
from collections import OrderedDict

import numpy as np

# controller command heads (parity: kvstore_dist_server.h:33-38)
K_STOP_SERVER = 0
K_SYNC_MODE = 1
K_SET_OPTIMIZER = 2


def _role():
    return os.environ.get("MXTPU_ROLE", os.environ.get("DMLC_ROLE", "worker"))


def _logical_key(part_key):
    """'3' / '3#p1' -> 3; non-integer logical keys pass through as str."""
    base = str(part_key).split("#p", 1)[0]
    try:
        return int(base)
    except ValueError:
        return base


class _SysModulesUnpickler(pickle.Unpickler):
    """Unpickler that resolves classes from sys.modules without touching
    the import machinery.  The server's main thread is parked *inside*
    ``import mxnet_tpu`` (holding the package import lock), so a plain
    pickle.loads on a handler thread — which __import__s the class's
    module and waits on that lock — would deadlock.  Everything a pickled
    optimizer needs (mxnet_tpu.optimizer, numpy) is fully imported before
    the server starts."""

    def find_class(self, module, name):
        mod = sys.modules.get(module)
        if mod is not None:
            return getattr(mod, name)
        return super().find_class(module, name)


def _loads_no_import(data):
    return _SysModulesUnpickler(io.BytesIO(data)).load()


# Transport messages are dicts of str/int/float/bytes/ndarray.  The
# reference's ps-lite transport is a binary protocol; a pickle transport
# must not be an arbitrary-object-deserialization RCE surface, so the
# unpickler allowlists exactly the globals numpy payloads need.  The one
# richer payload — the K_SET_OPTIMIZER body — travels as *bytes inside*
# a data message and is unpickled separately under the documented
# trusted-cluster assumption (see _control).
_SAFE_GLOBALS = {
    ("numpy", "ndarray"), ("numpy", "dtype"),
    ("numpy.core.multiarray", "_reconstruct"),
    ("numpy._core.multiarray", "_reconstruct"),
    ("numpy.core.multiarray", "scalar"),
    ("numpy._core.multiarray", "scalar"),
    ("numpy.core.numeric", "_frombuffer"),
    ("numpy._core.numeric", "_frombuffer"),
}


class _DataUnpickler(pickle.Unpickler):
    def find_class(self, module, name):
        if (module, name) in _SAFE_GLOBALS:
            mod = sys.modules.get(module)
            if mod is None:
                mod = __import__(module, fromlist=[name])
            return getattr(mod, name)
        raise pickle.UnpicklingError(
            f"global {module}.{name} is not allowed on the kvstore transport")


def send_msg(sock, obj):
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(struct.pack("<Q", len(payload)) + payload)


def recv_msg(sock):
    header = _recv_exact(sock, 8)
    if header is None:
        return None
    (length,) = struct.unpack("<Q", header)
    payload = _recv_exact(sock, length)
    if payload is None:
        return None
    return _DataUnpickler(io.BytesIO(payload)).load()


def _recv_exact(sock, n):
    buf = bytearray()
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except ConnectionResetError:
            # a peer that died (or a worker's retry/backoff path that
            # abandoned a broken stream) reads as EOF, not a handler
            # traceback — the retransmitted request arrives on a fresh
            # connection
            return None
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


class _ServerState:
    """Shared mutable server state guarded by one lock + condvar."""

    def __init__(self, num_workers):
        self.num_workers = num_workers
        self.store = {}            # key -> np.ndarray (the weights)
        self.merge_buf = {}        # key -> [accumulated np.ndarray, set(ranks)]
        self.updater = None        # fn(key, recv, stored) -> None (mutates stored)
        self.sync_mode = False
        self.barrier_count = 0
        self.barrier_gen = 0
        self.stopped = False
        self.lock = threading.Lock()
        self.cond = threading.Condition(self.lock)
        # failure detection (parity: ps-lite heartbeats surfaced through
        # KVStore::get_num_dead_node, kvstore_dist.h:151-160): workers
        # beat via a dedicated connection; any ranked message refreshes.
        self.heartbeats = {}       # rank -> time.monotonic() of last sign of life
        self.stopped_ranks = set()  # ranks that sent a clean kStopServer
        self.start_time = time.monotonic()
        # idempotent retransmit (ISSUE-11): workers attach a request id
        # to every NON-idempotent message (push/barrier/init/control);
        # a retransmitted rid whose first delivery already applied must
        # replay the cached reply, never re-apply — a re-applied sync
        # push double-counts the gradient, a re-applied barrier releases
        # the round early.  ``rid_inflight`` parks retransmissions that
        # race the first delivery (e.g. a barrier parked server-side
        # whose client connection died).
        self.rid_done = OrderedDict()   # rid -> cached reply
        self.rid_inflight = set()
        self.rid_cap = 4096

    def dead_nodes(self, timeout):
        """Worker ranks with no sign of life within ``timeout`` seconds.
        Never-connected ranks count from server start; ranks that sent a
        clean kStopServer are not dead — they are done (counting them
        would double them against stop_count and shut the server down
        while half the cluster still trains)."""
        now = time.monotonic()
        return [r for r in range(self.num_workers)
                if r not in self.stopped_ranks
                and now - self.heartbeats.get(r, self.start_time) > timeout]

    def should_stop(self, dead_timeout):
        """Every *live* worker has requested a stop (a crashed worker can
        never send kStopServer; without this the server leaks forever —
        round-1 advisor finding on _send_stop)."""
        if self.stop_count >= self.num_workers:
            return True
        return (self.stop_count > 0 and
                self.stop_count >= self.num_workers
                - len(self.dead_nodes(dead_timeout)))

    def default_update(self, key, recv, stored):
        # parity: kvstore_dist_server.h:229-236 — without an optimizer the
        # server merely accumulates (workers pull aggregated grads and
        # update locally: update_on_kvstore=False mode).
        stored[...] = recv


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        st: _ServerState = self.server.state
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        while True:
            msg = recv_msg(sock)
            if msg is None:
                return
            cmd = msg["cmd"]
            rank = msg.get("rank", -1)
            if isinstance(rank, int) and rank >= 0:
                with st.cond:
                    st.heartbeats[rank] = time.monotonic()
            if cmd == "heartbeat":
                send_msg(sock, {"ok": True})
            elif cmd == "dead_nodes":
                with st.cond:
                    dead = st.dead_nodes(float(msg.get("timeout", 60)))
                send_msg(sock, {"dead": dead})
            elif cmd == "pull":
                # pulls are idempotent — retransmissions just re-read
                send_msg(sock, {"value": self._pull(st, msg["key"],
                                                    msg.get("rank", -1))})
            elif cmd in ("init", "push", "barrier", "control"):
                send_msg(sock, self._apply_once(st, cmd, msg))
                if cmd == "control" and msg["head"] == K_STOP_SERVER:
                    with st.cond:
                        if st.stop_count >= st.num_workers:
                            return
            else:
                send_msg(sock, {"error": f"unknown cmd {cmd}"})

    def _apply_once(self, st, cmd, msg):
        """Apply one non-idempotent message exactly once.  A message
        carrying a request id that was already applied replays the
        cached reply (the worker retransmitted after a broken
        connection); one still in flight (a parked barrier whose client
        socket died) blocks until the first delivery completes."""
        rid = msg.get("rid")
        if rid is not None:
            with st.cond:
                while rid in st.rid_inflight:
                    st.cond.wait()
                if rid in st.rid_done:
                    return st.rid_done[rid]
                st.rid_inflight.add(rid)
        reply = None
        try:
            reply = self._apply(st, cmd, msg)
        finally:
            if rid is not None:
                # discard + cache under ONE lock hold: a retransmission
                # woken between them would re-apply the message
                with st.cond:
                    st.rid_inflight.discard(rid)
                    if reply is not None:
                        st.rid_done[rid] = reply
                        while len(st.rid_done) > st.rid_cap:
                            st.rid_done.popitem(last=False)
                    st.cond.notify_all()
        return reply

    def _apply(self, st, cmd, msg):
        if cmd == "init":
            with st.cond:
                st.store[msg["key"]] = np.array(msg["value"], copy=True)
        elif cmd == "push":
            self._push(st, msg)
        elif cmd == "barrier":
            self._barrier(st)
        else:  # control
            self._control(st, msg["head"], msg.get("body"))
        return {"ok": True}

    # parity: DataHandle (kvstore_dist_server.h:136-227)
    def _push(self, st, msg):
        key, recv = msg["key"], np.asarray(msg["value"])
        rank = msg.get("rank", -1)
        with st.cond:
            if key not in st.store:
                # first push defines the key (reference inits on first push
                # when workers race init; our init is explicit, keep safe)
                st.store[key] = np.zeros_like(recv)
            if st.sync_mode:
                buf = st.merge_buf.get(key)
                if buf is None:
                    buf = st.merge_buf[key] = [recv.copy(), set()]
                else:
                    buf[0] += recv
                buf[1].add(rank)
                if len(buf[1]) == st.num_workers:
                    (st.updater or st.default_update)(key, buf[0], st.store[key])
                    del st.merge_buf[key]
                    st.cond.notify_all()
            else:
                (st.updater or st.default_update)(key, recv, st.store[key])

    def _pull(self, st, key, rank=-1):
        with st.cond:
            # sync mode: park the pull ONLY while a merge this worker has
            # already contributed to is in flight — it wants the post-
            # update value (parity: parked pull replies,
            # kvstore_dist_server.h:186-198).  A pull from a worker that
            # has NOT contributed belongs to the previous round (our
            # client pulls synchronously), so it gets the last completed
            # value immediately — parking it would deadlock the cluster
            # under worker skew.
            while (st.sync_mode and key in st.merge_buf
                   and rank in st.merge_buf[key][1]):
                st.cond.wait()
            # copy under the lock: the live array is mutated in place by
            # concurrent updaters while the reply is pickled
            return st.store[key].copy()

    def _barrier(self, st):
        with st.cond:
            gen = st.barrier_gen
            st.barrier_count += 1
            if st.barrier_count == st.num_workers:
                st.barrier_count = 0
                st.barrier_gen += 1
                st.cond.notify_all()
            else:
                while st.barrier_gen == gen:
                    st.cond.wait()

    # parity: CommandHandle (kvstore_dist_server.h:121-133)
    def _control(self, st, head, body):
        with st.cond:
            if head == K_SYNC_MODE:
                st.sync_mode = True
            elif head == K_SET_OPTIMIZER:
                # NB: resolved via sys.modules, not `from . import` — the
                # server blocks inside `import mxnet_tpu` (the main thread
                # holds the package import lock), so a relative import
                # from this handler thread would deadlock.  Both modules
                # are fully imported before _init_kvstore_server_module
                # runs (see __init__.py ordering).
                opt = sys.modules[__package__ + ".optimizer"]
                nd = sys.modules[__package__ + ".ndarray"]

                optimizer = _loads_no_import(body)
                updater = opt.get_updater(optimizer)

                def np_updater(key, recv, stored, _u=updater, _nd=nd):
                    # the store key is the string part-key ('3' or '3#p0');
                    # lr_mult/wd_mult/idx2name are indexed by the logical
                    # int key — recover it so per-param lr/wd rules apply
                    # in distributed training too (parity: the server's
                    # DecodeKey, kvstore_dist_server.h:221-224)
                    w = _nd.array(stored)
                    _u(_logical_key(key), _nd.array(recv), w)
                    stored[...] = w.asnumpy()

                st.updater = np_updater
            elif head == K_STOP_SERVER:
                st.stop_count += 1
                rank = body if isinstance(body, int) else -1
                if rank >= 0:
                    st.stopped_ranks.add(rank)
                if st.stop_count >= st.num_workers:
                    st.stopped = True
                st.cond.notify_all()


class _TCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class KVStoreServer:
    """Blocking server run-loop (parity: python/mxnet/kvstore_server.py
    KVStoreServer — blocks in RunServer with a controller callback)."""

    def __init__(self, num_workers=None, port=None, host=None):
        self.num_workers = num_workers or int(
            os.environ.get("MXTPU_NUM_WORKERS", os.environ.get("DMLC_NUM_WORKER", "1")))
        rank = int(os.environ.get("MXTPU_SERVER_RANK", "0"))
        servers = os.environ.get("MXTPU_PS_SERVERS", "").split(",")
        if port is None:
            port = int(servers[rank].rsplit(":", 1)[1]) if servers[0] else 9090
        if host is None:
            # bind the address advertised for THIS server rank (127.0.0.1
            # for local launches) — not 0.0.0.0, which would expose the
            # pickle transport to anything that can reach the port.
            # MXTPU_PS_BIND overrides for multi-homed hosts.
            advertised = (servers[rank].rsplit(":", 1)[0]
                          if servers[0] else "127.0.0.1")
            host = os.environ.get("MXTPU_PS_BIND", advertised or "127.0.0.1")
        self.host = host
        self.port = port
        self.state = _ServerState(self.num_workers)
        self.state.stop_count = 0

    def run(self):
        """Serve until every live worker has sent kStopServer.

        Crashed workers are detected via heartbeat staleness
        (MXTPU_PS_DEAD_TIMEOUT_S, default 60s) so the server still exits
        when the remaining workers stop."""
        dead_timeout = float(os.environ.get("MXTPU_PS_DEAD_TIMEOUT_S", "60"))
        srv = _TCPServer((self.host, self.port), _Handler)
        srv.state = self.state
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        # the staleness the server already tracks, surfaced (ISSUE-13
        # satellite): the run loop wakes every 2s anyway — publish the
        # dead count on the kvstore_dead_workers gauge so /healthz and
        # scrapers see it without an extra RPC round
        from . import telemetry as _tm
        from .kvstore import _TM_DEAD_WORKERS

        with self.state.cond:
            while not self.state.stopped:
                if self.state.should_stop(dead_timeout):
                    self.state.stopped = True
                    break
                if _tm.enabled():
                    _TM_DEAD_WORKERS.set(
                        len(self.state.dead_nodes(dead_timeout)))
                self.state.cond.wait(timeout=2.0)
        srv.shutdown()
        srv.server_close()


def _init_kvstore_server_module():
    """Parity: kvstore_server.py:70-90 — if this process was launched in
    the server role, serve then exit (never returns to user code)."""
    if _role() == "server":
        # The main thread parks here while still *inside* `import
        # mxnet_tpu`, holding the package import lock.  Handler threads
        # perform imports (lazy `from . import ...` in the op engine,
        # pickle class lookups) that would wait on that lock forever.
        # The package body has fully executed at this point (the hook is
        # the last statement of __init__.py), so mark it initialized to
        # let _find_and_load return it without locking.
        pkg = sys.modules.get(__package__)
        spec = getattr(pkg, "__spec__", None)
        if spec is not None and getattr(spec, "_initializing", False):
            spec._initializing = False
        server = KVStoreServer()
        server.run()
        sys.exit(0)
