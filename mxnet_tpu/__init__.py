"""mxnet_tpu — a TPU-native framework with the capabilities of MXNet v0.9.4.

Not a port: the compute substrate is JAX/XLA (jit, vjp, sharding, Pallas),
the API surface is MXNet's (nd/sym/mod/kv/io) so reference user code maps
1:1.  See SURVEY.md at the repo root for the blueprint and per-module
docstrings for reference citations.
"""
import jax as _jax

from .base import MXNetError, AttrScope, NameManager, __version__, get_env as _get_env

# float32 arrays get true-fp32 matmuls (parity with the reference's fp32
# math); the fast path on TPU is explicit bfloat16 dtypes, which this
# setting does not affect.  Override with MXNET_TPU_MATMUL_PRECISION
# (e.g. "bfloat16" to trade accuracy for speed on fp32 data).
_jax.config.update(
    "jax_default_matmul_precision",
    _get_env("MXNET_TPU_MATMUL_PRECISION", "float32", str),
)
from .context import Context, cpu, cpu_pinned, gpu, tpu, current_context, num_devices
from . import engine
from . import random
from . import ops
from . import ndarray
from . import ndarray as nd
from .ndarray import NDArray
from . import symbol
from . import symbol as sym
from .symbol import Symbol, Variable, Group
from . import executor
from .executor import Executor

__all__ = [
    "MXNetError",
    "AttrScope",
    "NameManager",
    "Context",
    "cpu",
    "gpu",
    "tpu",
    "current_context",
    "nd",
    "NDArray",
    "engine",
    "random",
]
