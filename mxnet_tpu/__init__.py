"""mxnet_tpu — a TPU-native framework with the capabilities of MXNet v0.9.4.

Not a port: the compute substrate is JAX/XLA (jit, vjp, sharding, Pallas),
the API surface is MXNet's (nd/sym/mod/kv/io) so reference user code maps
1:1.  See SURVEY.md at the repo root for the blueprint and per-module
docstrings for reference citations.
"""
from .base import MXNetError, AttrScope, NameManager, __version__
from .context import Context, cpu, cpu_pinned, gpu, tpu, current_context, num_devices
from . import engine
from . import random
from . import ops
from . import ndarray
from . import ndarray as nd
from .ndarray import NDArray

__all__ = [
    "MXNetError",
    "AttrScope",
    "NameManager",
    "Context",
    "cpu",
    "gpu",
    "tpu",
    "current_context",
    "nd",
    "NDArray",
    "engine",
    "random",
]
