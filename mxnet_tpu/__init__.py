"""mxnet_tpu — a TPU-native framework with the capabilities of MXNet v0.9.4.

Not a port: the compute substrate is JAX/XLA (jit, vjp, sharding, Pallas),
the API surface is MXNet's (nd/sym/mod/kv/io) so reference user code maps
1:1.  See SURVEY.md at the repo root for the blueprint and per-module
docstrings for reference citations.
"""
import os as _os

import jax as _jax

# Platform selection must happen before ANY backend initializes (some TPU
# plugins ignore JAX_PLATFORMS).  MXTPU_PLATFORM=cpu pins a process to
# host XLA — used by multi-process launches on a single-accelerator box;
# server-role processes (parameter server) are host-only and never touch
# the accelerator (parity: reference servers are CPU processes).
_platform = _os.environ.get("MXTPU_PLATFORM")
if _platform is None and _os.environ.get(
        "MXTPU_ROLE", _os.environ.get("DMLC_ROLE")) == "server":
    _platform = "cpu"
if _platform:
    _jax.config.update("jax_platforms", _platform)

from .base import MXNetError, AttrScope, NameManager, __version__, get_env as _get_env

# float32 arrays get true-fp32 matmuls (parity with the reference's fp32
# math); the fast path on TPU is explicit bfloat16 dtypes, which this
# setting does not affect.  Override with MXNET_TPU_MATMUL_PRECISION
# (e.g. "bfloat16" to trade accuracy for speed on fp32 data).
_jax.config.update(
    "jax_default_matmul_precision",
    _get_env("MXNET_TPU_MATMUL_PRECISION", "float32", str),
)
from .context import Context, cpu, cpu_pinned, gpu, tpu, current_context, num_devices
from . import engine
from . import random
from . import ops
from . import ndarray
from . import ndarray as nd
from .ndarray import NDArray
from . import sparse
from .sparse import RowSparseNDArray
ndarray.sparse = sparse  # reference surface: mx.nd.sparse.row_sparse_array
from . import symbol
from . import symbol as sym
from .symbol import Symbol, Variable, Group
from . import executor
from .executor import Executor
from . import amp
from . import passes
from . import initializer
from . import initializer as init
from .initializer import Initializer, Uniform, Normal, Xavier, Orthogonal, MSRAPrelu, Mixed, Load
from . import optimizer
from .optimizer import Optimizer
from . import metric
from . import lr_scheduler
from . import callback
from . import io
from . import recordio
from . import filesystem
from . import storage
from . import image
from . import kvstore as kv
from . import kvstore_server
from . import checkpoint
from . import faults
from . import model
from .model import FeedForward, save_checkpoint, load_checkpoint
from . import executor_manager
from . import predict
from . import module
from . import module as mod
from .module import Module, BucketingModule, SequentialModule, PythonModule
from . import monitor
from . import monitor as mon
from .monitor import Monitor
from . import resource
from .resource import ResourceRequest, ResourceManager
from . import rnn
from . import operator
from . import profiler
from . import telemetry
from . import rtc
from . import visualization
from . import visualization as viz
from . import test_utils

__all__ = [
    "MXNetError",
    "AttrScope",
    "NameManager",
    "Context",
    "cpu",
    "gpu",
    "tpu",
    "current_context",
    "nd",
    "NDArray",
    "engine",
    "random",
]

# Must be the LAST statement: server-role processes serve the parameter
# store here and exit without reaching user code (parity: reference
# mxnet/__init__.py importing kvstore_server at the bottom).
kvstore_server._init_kvstore_server_module()
