"""HTTP serving front-end for the slot-pool scheduler.

Extends the telemetry HTTP skeleton (`telemetry/exporters.py`) into a
request-serving process: stdlib ``ThreadingHTTPServer`` (one thread per
connection — each handler thread just blocks on its request's event
while the single engine thread batches everyone's decode), no
dependencies, same ops endpoints the training stack already exposes.

Endpoints:

``POST /generate``
    body: ``{"prompt": [token ids], "max_tokens": 16, "temperature": 0,
    "top_k": null, "eos_id": null, "deadline_ms": null, "seed": 0}``.
    200: ``{"tokens": [...], "outcome": "ok", "ttft_ms": ..,
    "queue_wait_ms": .., "latency_ms": ..}``.  429 when the bounded
    admission queue is full (body carries ``Retry-After`` guidance),
    504 when the deadline expires (partial ``tokens`` included), 400 on
    malformed input, 500 on an engine error.  A ``traceparent`` request
    header (the router forwards one per attempt — docs/tracing.md)
    threads the trace through the scheduler; the reply echoes the
    trace id.  TTFT is measured from REQUEST RECEIPT — the handler
    stamps the arrival before reading the body, so queue wait and
    parse time are inside it, not silently dropped.
``GET /metrics`` / ``/metrics.json``
    Prometheus text / JSON snapshot of the process registry — the
    serving families (docs/telemetry.md) plus everything else the
    process emits.
``GET /spans.json``
    This process's bounded span buffer + host identity + clock offset —
    what ``tools/fleetstat.py trace <id>`` joins across the fleet
    (docs/tracing.md).
``GET /healthz``
    ``{"status", "draining", "slots", "occupied", "queue_depth",
    "queue_size", "ticks"}`` — liveness + the saturation and drain
    signals an orchestrator (and the serving router,
    ``serving/router.py``) scales and balances on.  ``status`` is
    ``"draining"`` after ``/admin/drain`` (and ``"drained"`` once
    nothing is in flight — safe to restart).  With the paged KV
    backend a ``paged`` object carries ``{block, pages_total,
    pages_free, prefix_pages}``.
``POST /admin/drain`` / ``POST /admin/undrain``
    Rolling-restart support (docs/fault_tolerance.md): stop admitting
    (new ``/generate`` calls get 503 + Retry-After), finish queued and
    in-flight requests, report drain progress; ``undrain`` re-opens
    admission (a cancelled drain, or the post-restart re-open).
    Idempotent.
"""
from __future__ import annotations

import json
import math
import threading
import time

from .. import telemetry as _tm
from ..base import MXNetError
from ..telemetry import tracing as _tracing
from .scheduler import (AdmissionQueueFull, SchedulerDraining,
                        SlotScheduler)

__all__ = ["start_server", "serve_decoder"]

_GENERATE_FIELDS = {"prompt", "max_tokens", "temperature", "top_k",
                    "eos_id", "deadline_ms", "seed"}


def _number(body, name, integral=False, lo=None, hi=None):
    """Pull an optional numeric field out of a /generate body, rejecting
    wrong types (bools included), non-finite values (json.loads happily
    parses NaN/Infinity), and out-of-range values — malformed sampling
    params must die here with a 400, not inside the engine thread."""
    v = body.get(name)
    if v is None:
        return None
    ok = int if integral else (int, float)
    if isinstance(v, bool) or not isinstance(v, ok):
        kind = "an integer" if integral else "a number"
        raise MXNetError(f"{name} must be {kind}, got {v!r}")
    if not math.isfinite(v):
        raise MXNetError(f"{name} must be finite, got {v!r}")
    if lo is not None and v < lo:
        raise MXNetError(f"{name} must be >= {lo}, got {v!r}")
    if hi is not None and v > hi:
        raise MXNetError(f"{name} must be <= {hi}, got {v!r}")
    return v


def _parse_generate(body):
    """Validate a /generate JSON body into Request kwargs (raises
    MXNetError with a client-facing message)."""
    if not isinstance(body, dict):
        raise MXNetError("body must be a JSON object")
    unknown = set(body) - _GENERATE_FIELDS
    if unknown:
        raise MXNetError(f"unknown fields {sorted(unknown)}; "
                         f"accepted: {sorted(_GENERATE_FIELDS)}")
    prompt = body.get("prompt")
    if (not isinstance(prompt, list) or not prompt
            or not all(isinstance(t, int) and not isinstance(t, bool)
                       and t >= 0 for t in prompt)):
        raise MXNetError("prompt must be a non-empty list of token ids")
    kwargs = {}
    for name, dst, integral, lo, hi in (
            ("max_tokens", "max_new_tokens", True, 1, None),
            ("temperature", "temperature", False, 0, None),
            ("top_k", "top_k", True, 1, None),
            ("eos_id", "eos_id", True, 0, None),
            ("deadline_ms", "deadline_ms", True, 0, None),
            ("seed", "seed", True, 0, 2 ** 32 - 1)):
        v = _number(body, name, integral=integral, lo=lo, hi=hi)
        if v is not None:
            kwargs[dst] = v
    kwargs.setdefault("max_new_tokens", 16)
    return prompt, kwargs


def _request_json(req):
    out = {
        "id": req.id,
        "tokens": [int(t) for t in req.tokens],
        "n_tokens": len(req.tokens),
        "outcome": req.outcome,
        "ttft_ms": round(req.ttft * 1000.0, 3) if req.ttft is not None
        else None,
        "queue_wait_ms": round(req.queue_wait * 1000.0, 3)
        if req.queue_wait is not None else None,
    }
    if req.trace is not None:
        out["trace"] = req.trace
    return out


def start_server(scheduler: SlotScheduler, port: int = 0,
                 addr: str = "127.0.0.1", registry=None):
    """Serve the scheduler over HTTP on a daemon thread.  ``port=0``
    binds an ephemeral port — read it back from
    ``server.server_address``.  ``server.shutdown()`` stops serving
    (the scheduler is closed separately: ``scheduler.close()``)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    reg = registry or _tm.get_registry()

    class _Handler(BaseHTTPRequestHandler):
        def _reply(self, code, payload, ctype="application/json",
                   headers=()):
            body = payload if isinstance(payload, bytes) \
                else json.dumps(payload).encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            for k, v in headers:
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            path = self.path.split("?", 1)[0]
            if path in ("/", "/metrics"):
                self._reply(200, _tm.generate_text(reg).encode("utf-8"),
                            "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/metrics.json":
                self._reply(200, _tm.json_snapshot(reg))
            elif path == "/spans.json":
                self._reply(200, _tracing.spans_payload())
            elif path == "/healthz":
                status = "ok"
                if scheduler.draining:
                    status = "drained" if scheduler.drained else "draining"
                payload = {
                    "status": status,
                    "draining": scheduler.draining,
                    "slots": scheduler.num_slots,
                    "occupied": scheduler.occupied,
                    "queue_depth": scheduler.queue_depth,
                    "queue_size": scheduler.queue_size,
                    "ticks": scheduler.stats["ticks"],
                }
                paged = scheduler.paged_stats()
                if paged is not None:
                    payload["paged"] = paged
                self._reply(200, payload)
            else:
                self._reply(404, {"error": f"no such path {path!r}"})

        def do_POST(self):
            path = self.path.split("?", 1)[0]
            if path == "/admin/drain":
                scheduler.drain()
                self._reply(200, {
                    "status": "drained" if scheduler.drained
                    else "draining",
                    "occupied": scheduler.occupied,
                    "queue_depth": scheduler.queue_depth,
                })
                return
            if path == "/admin/undrain":
                # a drain that was cancelled (or the post-restart
                # re-open of the rolling-upgrade runbook)
                scheduler.undrain()
                self._reply(200, {"status": "ok",
                                  "occupied": scheduler.occupied})
                return
            if path != "/generate":
                self._reply(404, {"error": f"no such path {path!r}"})
                return
            # TTFT origin (ISSUE 16): stamp receipt BEFORE the body is
            # read or parsed — serve_ttft_seconds must cover queue wait
            # and parse time, not start when a slot frees up
            t_arrival = time.monotonic()
            ctx = _tracing.parse_traceparent(
                self.headers.get("traceparent"))
            try:
                length = int(self.headers.get("Content-Length", "0"))
                body = json.loads(self.rfile.read(length) or b"{}")
                prompt, kwargs = _parse_generate(body)
            except MXNetError as exc:
                self._reply(400, {"error": str(exc)})
                return
            except (ValueError, UnicodeDecodeError) as exc:
                self._reply(400, {"error": f"malformed JSON: {exc}"})
                return
            kwargs["arrival"] = t_arrival
            if ctx is not None:
                kwargs.update(trace=ctx["trace"], parent=ctx["parent"],
                              sampled=ctx["sampled"])
            try:
                req = scheduler.submit(prompt, **kwargs)
            except SchedulerDraining as exc:
                # the orchestrator asked this replica to die: clients
                # retry against another replica, not this one
                self._reply(503, {"error": str(exc)},
                            headers=(("Retry-After", "5"),))
                return
            except AdmissionQueueFull as exc:
                self._reply(429, {"error": str(exc)},
                            headers=(("Retry-After", "1"),))
                return
            except (MXNetError, TypeError, ValueError) as exc:
                # backstop for values _parse_generate let through that
                # Request.__init__ still rejects — a 400, not a dropped
                # connection from an unwound handler thread
                self._reply(400, {"error": str(exc)})
                return
            # block this connection thread on the terminal outcome; the
            # engine enforces the deadline, the +5s slack only guards
            # against a wedged engine
            limit = None
            if req.deadline is not None:
                import time as _time

                limit = max(req.deadline - _time.monotonic(), 0.0) + 5.0
            req.wait(limit)
            payload = _request_json(req)
            if req.outcome == "ok":
                self._reply(200, payload)
            elif req.outcome == "timeout":
                self._reply(504, payload)
            elif req.outcome is None:
                payload["error"] = "engine did not reach a terminal state"
                self._reply(500, payload)
            else:
                payload["error"] = repr(req.error) if req.error else \
                    req.outcome
                self._reply(500, payload)

        def log_message(self, *args):  # health probes are chatty
            pass

    class _Server(ThreadingHTTPServer):
        daemon_threads = True
        # the stdlib default backlog of 5 resets bursty concurrent
        # connects long before the bounded admission queue (the real
        # backpressure signal, HTTP 429) ever gets to answer them
        request_queue_size = 128

    srv = _Server((addr, port), _Handler)
    thread = threading.Thread(target=srv.serve_forever, daemon=True,
                              name="mxtpu-serve-http")
    thread.start()
    return srv


def serve_decoder(decoder, port=0, addr="127.0.0.1", **scheduler_kwargs):
    """Convenience bring-up: scheduler + HTTP server around a bound
    KVDecoder.  Returns ``(server, scheduler)``."""
    scheduler = SlotScheduler(decoder, **scheduler_kwargs)
    server = start_server(scheduler, port=port, addr=addr)
    return server, scheduler
