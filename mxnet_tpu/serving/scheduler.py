"""Slot-pool continuous-batching scheduler.

The serving core: a fixed number of decode *slots* sharing ONE
`KVDecoder` batch.  Every engine tick runs one jitted decode step over
all slots (`KVDecoder.step_slots` — a single XLA program regardless of
which slots are live); a request that finishes (eos / token budget /
cache capacity / deadline) frees its slot **mid-flight**, and queued
requests are admitted into free slots at the next iteration without
recompiling anything: admission is a bucketed-length prefill
(`prefill_padded`, one program per bucket, warmed after the first
request of each bucket) plus one traced-slot-index cache write
(`adopt_row`).  The decode jits live in the same process as the PR-2
program cache, so a warm server performs ZERO traces per tick —
asserted via `executor_compile_total{kind=decode_*}` by
tests/test_serving.py.

Host/device split follows the training hot loop's rule: per-slot
``start``/``cursor`` windows, queued requests, and sampling live on the
HOST (numpy); no tick reads device state except the one (B, V) logits
fetch that sampling needs anyway.  Per-request sampling params
(temperature / top_k / seed) are host-side, so heterogeneous requests
co-batch freely.

Backpressure is explicit: the admission queue is bounded
(``MXTPU_SERVE_QUEUE``); a full queue raises
:class:`AdmissionQueueFull`, which the HTTP layer maps to 429.
Deadlines (``MXTPU_SERVE_DEADLINE_MS`` default, per-request override)
are enforced both while queued and mid-generation.
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque

import numpy as np

from .. import telemetry as _tm
from ..base import MXNetError
from ..telemetry import tracing as _tracing
from . import paged_kv as _paged_kv

__all__ = ["Request", "SlotScheduler", "AdmissionQueueFull"]

# --- serving metric families (docs/telemetry.md, serving section) ----------
_TM_REQS = _tm.counter(
    "serve_requests_total",
    "requests by terminal outcome: ok (completed), rejected (admission "
    "queue full), timeout (deadline while queued or generating), error, "
    "shutdown", labels=("outcome",))
_TM_TOKENS = _tm.counter(
    "serve_tokens_total", "tokens generated and delivered to requests")
_TM_QUEUE = _tm.gauge(
    "serve_queue_depth", "requests waiting in the bounded admission queue")
_TM_OCCUPANCY = _tm.gauge(
    "serve_slot_occupancy", "decode slots currently running a request")
_TM_TTFT = _tm.histogram(
    "serve_ttft_seconds",
    "time-to-first-token: request ARRIVAL (HTTP receipt, before "
    "parse/queue — the server passes its receipt stamp into Request) "
    "to the first sampled token: queue wait + admission prefill")
_TM_QWAIT = _tm.histogram(
    "serve_queue_wait_seconds",
    "time a request spent in the bounded admission queue before a "
    "slot freed up — the queueing component of serve_ttft_seconds, "
    "reported separately so saturation (queue wait) and compute "
    "(prefill) are tellable apart at the replica")
_TM_REQ_SEC = _tm.histogram(
    "serve_request_seconds", "request latency: arrival to terminal outcome")
_TM_REUSE = _tm.counter(
    "serve_slot_reuse_total",
    "admissions into a slot that already served an earlier request — "
    "continuous batching in action (0 means every request got a cold slot)")
_TM_TICK = _tm.histogram(
    "serve_tick_seconds",
    "one engine tick: a fused decode step over all slots + host sampling")


class SchedulerDraining(MXNetError):
    """Submitted while draining (POST /admin/drain): the server is
    finishing in-flight work before a restart — resubmit elsewhere."""


class AdmissionQueueFull(MXNetError):
    """The bounded admission queue is full — shed load (HTTP 429)."""


def _env_int(name, default):
    v = os.environ.get(name)
    return default if not v else int(v)


class Request:
    """One generation request and its (thread-safe) result slot.

    ``wait(timeout)`` blocks until a terminal outcome; ``tokens`` then
    holds everything generated (possibly partial on ``timeout``).
    """

    _ids = itertools.count()

    def __init__(self, prompt, max_new_tokens=16, temperature=0.0,
                 top_k=None, eos_id=None, deadline_ms=None, seed=0,
                 arrival=None, trace=None, parent=None, sampled=False):
        prompt = np.asarray(prompt)
        if prompt.ndim != 1 or prompt.size == 0:
            raise MXNetError(
                f"prompt must be a non-empty 1-D token-id sequence, got "
                f"shape {prompt.shape}")
        if max_new_tokens < 1:
            raise MXNetError("max_new_tokens must be >= 1")
        temperature = float(temperature)
        if not np.isfinite(temperature) or temperature < 0:
            raise MXNetError(
                f"temperature must be a finite number >= 0, got "
                f"{temperature!r}")
        if top_k is not None:
            top_k = int(top_k)
            if top_k < 1:
                raise MXNetError(f"top_k must be >= 1, got {top_k}")
        if deadline_ms is not None and not (
                np.isfinite(deadline_ms) and deadline_ms >= 0):
            raise MXNetError(
                f"deadline_ms must be a finite number >= 0, got "
                f"{deadline_ms!r}")
        seed = int(seed)
        if not 0 <= seed < 2 ** 32:
            raise MXNetError(f"seed must be in [0, 2**32), got {seed}")
        self.id = next(Request._ids)
        self.prompt = prompt.astype(np.int64)
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = temperature
        self.top_k = top_k
        self.eos_id = eos_id
        # TTFT origin (ISSUE 16): the server stamps monotonic receipt
        # time BEFORE reading/parsing the body and passes it here, so
        # serve_ttft_seconds includes the full queue wait
        self.arrival = (time.monotonic() if arrival is None
                        else float(arrival))
        self.deadline = (self.arrival + deadline_ms / 1000.0
                         if deadline_ms else None)
        # trace context (telemetry/tracing.py): the W3C traceparent the
        # router minted; spans are recorded only when `sampled` rode in
        # on the flags byte AND tracing is on in this process
        self.trace = trace
        self.parent = parent
        self.sampled = bool(sampled)
        self.queue_wait = None
        self.tokens = []
        self.outcome = None   # ok | timeout | error | shutdown
        self.error = None
        self.ttft = None
        self._rng = np.random.RandomState(seed)
        self._event = threading.Event()

    def wait(self, timeout=None):
        """Block until the request reaches a terminal outcome (or the
        wait times out — ``outcome`` is then still None)."""
        self._event.wait(timeout)
        return self

    @property
    def done(self):
        return self._event.is_set()


class _ContiguousSlots:
    """The PR-6 contiguous slot pool behind the backend interface the
    scheduler drives: one ``(L, slots, H, max_len, dh)`` cache pair,
    left-padded bucketed prefill + ``adopt_row`` admission, per-slot
    ``[start, cursor]`` windows.  The paged twin is
    :class:`~mxnet_tpu.serving.paged_kv.PagedSlots`."""

    paged = False

    def __init__(self, decoder, num_slots, prefill_buckets):
        self.decoder = decoder
        self.num_slots = num_slots
        self.prefill_buckets = prefill_buckets
        self.cache = decoder.init_slot_state(num_slots)
        self.start = np.zeros(num_slots, np.int32)
        self.cursor = np.zeros(num_slots, np.int32)

    def stats(self):
        return None

    def admit(self, slot, prompt, trace=None):
        """Bucketed left-padded prefill + one traced-slot cache write;
        returns the next-token logits row of the last prompt token.
        ``trace`` is accepted for backend-interface parity with the
        paged pool (which records kv_admit/kv_evict spans); the
        contiguous pool has no per-admit KV events to attribute."""
        plen = int(prompt.size)
        bucket = next(b for b in self.prefill_buckets if b >= plen)
        padded = np.zeros((1, bucket), np.int64)
        padded[0, bucket - plen:] = prompt
        row, logits = self.decoder.prefill_padded(padded, [plen])
        self.cache = self.decoder.adopt_row(self.cache, row, slot)
        self.start[slot] = bucket - plen
        self.cursor[slot] = bucket
        return logits[0, -1]

    def step(self, tokens, occupied):
        """ONE jitted decode step over the whole pool; advances the
        occupied rows' windows.  Never starves (each slot owns its full
        max_len row) — the empty second return keeps the interface."""
        tokens = np.asarray(tokens).copy()
        start = self.start.copy()
        cursor = self.cursor.copy()
        free = ~occupied
        # free rows ride along; pin their write to position 0 —
        # adopt_row overwrites the whole row on admission
        tokens[free] = 0
        start[free] = 0
        cursor[free] = 0
        self.cache, logits = self.decoder.step_slots(
            self.cache, tokens, start, cursor)
        self.cursor[occupied] += 1
        return logits, []

    def exhausted(self, slot):
        return self.cursor[slot] >= self.decoder.max_len

    def release(self, slot):
        self.start[slot] = 0
        self.cursor[slot] = 0


class SlotScheduler:
    """Continuous batching over one :class:`~mxnet_tpu.models.decode.
    KVDecoder`.

    ``prefill_buckets``: padded prompt lengths the admission prefill
    compiles for (default: powers of two from 8 up to the decoder's
    ``max_len``).  A request's prompt is left-padded to the smallest
    bucket that fits, so the number of prefill programs is
    O(log max_len) and a warm server admits without tracing.

    ``paged``/``kv_block``/``num_pages``/``prefix_cache`` select the
    paged KV backend (`serving/paged_kv.py`): block-table indirection
    over a shared page pool with prompt-prefix reuse.  Default follows
    ``MXTPU_KV_BLOCK`` (0/unset = contiguous).  ``paged_kernel``
    overrides ``MXTPU_PAGED_KERNEL`` — the paged step's attention
    lowering (gather / Pallas page-walk kernel / lax pagewalk; ISSUE
    18), resolved once at construction through ``mxnet_tpu.autotune``.
    """

    def __init__(self, decoder, num_slots=None, queue_size=None,
                 default_deadline_ms=None, prefill_buckets=None,
                 idle_wait=0.05, paged=None, kv_block=None,
                 num_pages=None, prefix_cache=None, paged_kernel=None):
        self.decoder = decoder
        # `is not None` (not truthiness): an explicit 0 must reach the
        # guards below, not silently become the env/default value
        self.num_slots = int(
            num_slots if num_slots is not None
            else _env_int("MXTPU_SERVE_SLOTS", 4))
        self.queue_size = int(
            queue_size if queue_size is not None
            else _env_int("MXTPU_SERVE_QUEUE", 16))
        self.default_deadline_ms = (
            default_deadline_ms
            if default_deadline_ms is not None
            else _env_int("MXTPU_SERVE_DEADLINE_MS", 30000))
        if self.num_slots < 1:
            raise MXNetError("need at least one decode slot")
        if self.queue_size < 0:
            raise MXNetError("queue_size must be >= 0 (0 disables "
                             "queueing: every submit sheds load)")
        if prefill_buckets is None:
            prefill_buckets, b = [], 8
            while b < decoder.max_len:
                prefill_buckets.append(b)
                b *= 2
            prefill_buckets.append(decoder.max_len)
        self.prefill_buckets = tuple(sorted(set(prefill_buckets)))
        if self.prefill_buckets[-1] > decoder.max_len:
            raise MXNetError(
                f"prefill bucket {self.prefill_buckets[-1]} exceeds the "
                f"decoder's max_len {decoder.max_len}")

        blk = kv_block if kv_block is not None else _paged_kv.kv_block()
        if paged is None:
            paged = blk > 0
        if paged:
            self.backend = _paged_kv.PagedSlots(
                decoder, self.num_slots, block=(blk or None),
                num_pages=num_pages, prefix_cache=prefix_cache,
                prefill_buckets=self.prefill_buckets,
                kernel=paged_kernel)
        else:
            self.backend = _ContiguousSlots(
                decoder, self.num_slots, self.prefill_buckets)
        self.slots = [None] * self.num_slots
        self._next_tok = np.zeros(self.num_slots, np.int64)
        self._slot_used = [False] * self.num_slots
        self._queue = deque()
        self._cond = threading.Condition()
        self._stop = False
        self._draining = False
        self._idle_wait = float(idle_wait)
        # rolled-up engine stats (bench + /healthz): mean slot occupancy
        # = slot_ticks / ticks
        self.stats = {"ticks": 0, "slot_ticks": 0, "admitted": 0,
                      "completed": 0}
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name="mxtpu-serve-engine-%d" % id(self))
        self._thread.start()

    # ------------------------------------------------------------ client API
    def submit(self, prompt, **kwargs):
        """Enqueue a generation request; returns the :class:`Request`.
        Raises :class:`AdmissionQueueFull` when the bounded queue is full
        and :class:`MXNetError` for requests that can never be served
        (prompt longer than the largest prefill bucket)."""
        kwargs.setdefault("deadline_ms", self.default_deadline_ms or None)
        req = Request(prompt, **kwargs)
        vocab = getattr(self.decoder, "vocab", None)
        if req.top_k is not None and vocab and req.top_k > vocab:
            _TM_REQS.inc(outcome="rejected")
            raise MXNetError(
                f"top_k {req.top_k} exceeds the vocab size {vocab}")
        if req.prompt.size > self.prefill_buckets[-1]:
            _TM_REQS.inc(outcome="rejected")
            raise MXNetError(
                f"prompt length {req.prompt.size} exceeds the largest "
                f"prefill bucket {self.prefill_buckets[-1]}")
        with self._cond:
            if self._stop:
                raise MXNetError("scheduler is shut down")
            if self._draining:
                _TM_REQS.inc(outcome="rejected")
                raise SchedulerDraining(
                    "scheduler is draining: not admitting new requests "
                    "(in-flight and queued requests will finish)")
            if len(self._queue) >= self.queue_size:
                _TM_REQS.inc(outcome="rejected")
                raise AdmissionQueueFull(
                    f"admission queue full ({self.queue_size} waiting)")
            self._queue.append(req)
            _TM_QUEUE.set(len(self._queue))
            self._cond.notify()
        return req

    def generate(self, prompt, timeout=None, **kwargs):
        """submit() + wait(): returns the finished :class:`Request`."""
        req = self.submit(prompt, **kwargs)
        limit = timeout
        if limit is None and req.deadline is not None:
            limit = max(req.deadline - time.monotonic(), 0.0) + 5.0
        return req.wait(limit)

    # ------------------------------------------------------------- draining
    def drain(self):
        """Stop admitting new requests; queued and in-flight requests
        finish normally (the rolling-restart half of the survival
        layer: an orchestrator drains a replica, waits for
        :attr:`drained`, then restarts it under live traffic).
        Idempotent; ``submit`` raises :class:`SchedulerDraining` until
        shutdown or :meth:`undrain`."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()

    def undrain(self):
        """Re-open admission (a drain that was cancelled)."""
        with self._cond:
            self._draining = False
            self._cond.notify_all()

    @property
    def draining(self):
        return self._draining

    @property
    def drained(self):
        """True when a draining scheduler has no queued or in-flight
        work left — safe to restart."""
        with self._cond:
            return (self._draining and not self._queue
                    and all(r is None for r in self.slots))

    @property
    def paged(self):
        return self.backend.paged

    def paged_stats(self):
        """Page-pool occupancy for ``/healthz`` (None when running the
        contiguous backend): {block, pages_total, pages_free,
        prefix_pages}."""
        return self.backend.stats()

    @property
    def occupied(self):
        return sum(1 for r in self.slots if r is not None)

    @property
    def queue_depth(self):
        with self._cond:
            return len(self._queue)

    def close(self, timeout=10.0):
        """Stop the engine thread; queued and in-flight requests finish
        with outcome ``shutdown``."""
        with self._cond:
            if self._stop:
                return
            self._stop = True
            self._cond.notify_all()
        self._thread.join(timeout)
        with self._cond:
            queued = list(self._queue)
            self._queue.clear()
        _TM_QUEUE.set(0)
        # the engine never touches the queue after _stop, so queued
        # requests are safe to terminate here either way
        for req in queued:
            self._terminal(req, "shutdown")
        if self._thread.is_alive():
            # engine wedged past the join timeout (e.g. blocked inside a
            # jitted call): leave the slots to it — it may still finish
            # them, and _terminal is idempotent if it does so later; the
            # in-flight clients' own wait() deadlines bound their hang
            return
        for req in self.slots:
            if req is not None:
                self._terminal(req, "shutdown")
        # race-ok: reached only after _thread.join() proved the engine
        # thread dead (is_alive() returns above otherwise) — the join is
        # the happens-before edge static analysis can't see
        self.slots = [None] * self.num_slots
        _TM_OCCUPANCY.set(0)

    # ---------------------------------------------------------- engine loop
    def _run(self):
        while True:
            with self._cond:
                while (not self._stop and not self._queue
                       and all(r is None for r in self.slots)):
                    self._cond.wait(self._idle_wait)
                if self._stop:
                    return
            # the engine thread must OUTLIVE any single bad request: an
            # exception anywhere in an iteration terminates the affected
            # requests with outcome `error` and the loop keeps serving —
            # a dead engine would hang every in-flight and future client
            try:
                now = time.monotonic()
                self._expire_queued(now)
                self._admit(now)
                if any(r is not None for r in self.slots):
                    self._tick()
            except Exception as exc:  # noqa: BLE001 — requests must
                #                       terminate, not hang their clients
                for i, req in enumerate(self.slots):
                    if req is not None:
                        req.error = exc
                        self._finish_slot(i, "error")

    def _expire_queued(self, now):
        with self._cond:
            keep = deque()
            for req in self._queue:
                if req.deadline is not None and now > req.deadline:
                    self._terminal(req, "timeout")
                else:
                    keep.append(req)
            if len(keep) != len(self._queue):
                self._queue = keep
                _TM_QUEUE.set(len(keep))

    def _admit(self, now):
        """Move queued requests into free slots: bucketed prefill + one
        traced-slot cache write each; the first token is sampled straight
        from the prefill logits (that fetch IS the TTFT)."""
        while True:
            free = next((i for i, r in enumerate(self.slots) if r is None),
                        None)
            if free is None:
                return
            with self._cond:
                if not self._queue:
                    return
                req = self._queue.popleft()
                _TM_QUEUE.set(len(self._queue))
            req.queue_wait = time.monotonic() - req.arrival
            _TM_QWAIT.observe(req.queue_wait)
            traced = req.sampled and _tracing.trace_on()
            if traced:
                _tracing.record_span(
                    "queue_wait", "replica", req.trace, req.queue_wait,
                    parent=req.parent, request=req.id)
            t_admit0 = time.perf_counter()
            try:
                # the whole admission for THIS request — prefill, first
                # sample, cache write — fails only this request; the
                # slot stays free and the engine moves on
                from .. import faults as _faults

                _faults.maybe_fail("serve_admit")
                t_pf0 = time.perf_counter()
                logits = self.backend.admit(
                    free, req.prompt,
                    trace=(req.trace if traced else None))
                pf_dur = time.perf_counter() - t_pf0
                first = self._sample(req, np.asarray(logits, np.float32))
            except Exception as exc:  # noqa: BLE001
                self.backend.release(free)
                req.error = exc
                self._terminal(req, "error")
                continue
            self._next_tok[free] = first
            if self._slot_used[free]:
                _TM_REUSE.inc()
            self._slot_used[free] = True
            self.slots[free] = req
            req.tokens.append(first)
            req.ttft = time.monotonic() - req.arrival
            _TM_TTFT.observe(req.ttft)
            _TM_TOKENS.inc()
            self.stats["admitted"] += 1
            _TM_OCCUPANCY.set(self.occupied)
            if traced:
                plen = int(req.prompt.size)
                bucket = next(b for b in self.prefill_buckets
                              if b >= plen)
                _tracing.record_span(
                    "prefill", "replica", req.trace, pf_dur,
                    parent=req.parent, bucket=bucket, prompt_len=plen,
                    request=req.id)
                _tracing.record_span(
                    "admit", "replica", req.trace,
                    time.perf_counter() - t_admit0, parent=req.parent,
                    slot=free, request=req.id)
            self._maybe_finish(free, time.monotonic())

    def _tick(self):
        """ONE jitted decode step over the whole pool + host sampling."""
        from .. import faults as _faults

        # SIGKILL-shaped chaos: MXTPU_FAULT_PLAN="replica_kill:
        # crash_after:n" dies mid-decode — the death the router's
        # re-route/502 paths must survive (tests/test_serving_fleet.py)
        _faults.fire("replica_kill")
        # injected slow replica (MXTPU_FAULT_PLAN="serve_slow:drop:1"):
        # park the engine thread so queue wait and TTFT genuinely
        # inflate — the SLO plane's violation paths ride this in tests
        if _faults.active() and _faults.should_drop("serve_slow"):
            time.sleep(_tm.health._fault_slow_s())
        t0 = time.perf_counter()
        occupied = [i for i, r in enumerate(self.slots) if r is not None]
        # sampled decode-tick spans (ISSUE 16): every TICK_EVERY-th tick
        # records one span per sampled live request — pure host dict
        # writes after the tick, so the zero-host-sync invariant holds;
        # requests are captured NOW because _finish_slot clears slots
        tick_reqs = ()
        if _tracing.trace_on() \
                and self.stats["ticks"] % _tracing.TICK_EVERY == 0:
            tick_reqs = [(i, self.slots[i]) for i in occupied
                         if self.slots[i].sampled]
        occ_mask = np.array([r is not None for r in self.slots])
        logits, starved = self.backend.step(self._next_tok, occ_mask)
        logits = np.asarray(logits, np.float32)   # the ONE host sync/tick
        t_fetch = time.perf_counter()
        now = time.monotonic()
        for i in occupied:
            if i in starved:
                # page pool exhausted mid-generation: deliver what was
                # generated so far (the paged analog of the contiguous
                # cache-window truncation — documented in serving.md)
                self._finish_slot(i, "ok")
                continue
            req = self.slots[i]
            nxt = self._sample(req, logits[i])
            req.tokens.append(nxt)
            self._next_tok[i] = nxt
            _TM_TOKENS.inc()
            self._maybe_finish(i, now)
        self.stats["ticks"] += 1
        self.stats["slot_ticks"] += len(occupied)
        tick_dur = time.perf_counter() - t0
        _TM_TICK.observe(tick_dur)
        if _tm.perf.enabled() and occupied:
            # perf-attribution plane (docs/perf_attr.md): the tick wall
            # splits into the decode dispatch (step + the one logits
            # fetch above) and the host sampling loop — perf_counter
            # stamps the tick already takes, no extra device sync
            _tm.perf.record_dispatch(
                "decode_step_paged"
                if getattr(self.backend, "paged", False)
                else "decode_step_slots", t_fetch - t0)
            _tm.perf.record_step_buckets(
                wall_s=tick_dur, dispatch=t_fetch - t0,
                sample=tick_dur - (t_fetch - t0))
        for i, req in tick_reqs:
            _tracing.record_span(
                "decode_tick", "replica", req.trace, tick_dur,
                parent=req.parent, slot=i, tick=self.stats["ticks"] - 1,
                tokens=len(req.tokens), request=req.id)

    def _maybe_finish(self, slot, now):
        req = self.slots[slot]
        if req.deadline is not None and now > req.deadline:
            self._finish_slot(slot, "timeout")
        elif (req.eos_id is not None and req.tokens
              and req.tokens[-1] == req.eos_id):
            self._finish_slot(slot, "ok")
        elif len(req.tokens) >= req.max_new_tokens:
            self._finish_slot(slot, "ok")
        elif self.backend.exhausted(slot):
            # cache window exhausted: the checkpoint's positional table
            # ends here — deliver what fits (documented truncation)
            self._finish_slot(slot, "ok")

    def _finish_slot(self, slot, outcome):
        req = self.slots[slot]
        self.slots[slot] = None
        self.backend.release(slot)
        self._next_tok[slot] = 0
        self.stats["completed"] += 1
        _TM_OCCUPANCY.set(self.occupied)
        self._terminal(req, outcome)

    def _terminal(self, req, outcome):
        if req.outcome is not None:   # idempotent: first outcome wins
            return
        req.outcome = outcome
        _TM_REQS.inc(outcome=outcome)
        wall = time.monotonic() - req.arrival
        _TM_REQ_SEC.observe(wall)
        if req.sampled and _tracing.trace_on():
            # the terminal span covers the whole request (arrival →
            # outcome) and mirrors into the PR-5 flight ring so
            # post-mortem dumps carry the trace id
            _tracing.record_span(
                "request", "replica", req.trace, wall,
                parent=req.parent, outcome=outcome,
                tokens=len(req.tokens), request=req.id)
            _tm.record_step(
                loop="serve", trace=req.trace, outcome=outcome,
                wall_s=wall, ttft_s=req.ttft)
        req._event.set()

    @staticmethod
    def _sample(req, logits):
        """Host-side per-request sampling — same math as
        KVDecoder.generate, but with each request's own params/rng so
        heterogeneous requests co-batch."""
        if req.temperature <= 0:
            return int(logits.argmax())
        lg = logits / req.temperature
        if req.top_k:
            # clamp to the vocab: submit() validates against the
            # decoder's vocab when known, this keeps np.partition safe
            # for decoders that don't expose one
            k = min(req.top_k, lg.shape[-1])
            kth = np.partition(lg, -k)[-k]
            lg = np.where(lg < kth, -np.inf, lg)
        z = lg - lg.max()
        prob = np.exp(z)
        prob /= prob.sum()
        return int(req._rng.choice(lg.shape[-1], p=prob))
