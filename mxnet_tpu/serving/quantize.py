"""Post-training int8 weight quantization for the predict path.

In the spirit of TVM (arXiv:1802.04799): inference graphs are lowered
with quantized constants and the dequantization folded into consumers.
Here the mechanism is XLA fusion instead of a graph rewrite — weights
are stored as **int8 device arrays + per-channel fp scales** and the
``q.astype(compute) * scale`` dequantization is emitted *inside* the
already-jitted inference program, so the cast/multiply fuse into the
matmul (or gather) that consumes the weight.  Device memory holds int8
(4x smaller than fp32 — the KV-decode weight footprint drops
accordingly); compute stays in the program's compute dtype, which keeps
the pass numerically boring: symmetric per-channel scales bound the
per-weight error at ``max|w|/254`` per channel.

Scheme: per-channel symmetric.  For a weight ``w`` with output channels
on ``axis`` (axis 0 for both ``FullyConnected`` ``(out, in)`` layouts
and conv ``(O, I, kH, kW)``), ``scale_c = max|w_c| / 127`` and
``q = round(w / scale)`` clipped to [-127, 127] (-128 unused, keeping
the grid symmetric).  Rows that are entirely zero get scale 1 so the
roundtrip stays exact.

This is the int8 analog of the bf16 predict dtype
(``MXTPU_PREDICT_DTYPE``): same dequantize-in-compute philosophy, half
the storage of bf16 again, scales carrying the dynamic range the int8
grid lacks.

Backend-agnostic by construction: the ``_DequantView`` param dict
dequantizes on read inside whatever program traces it, so the int8
path composes unchanged with the contiguous slot pool AND the paged
KV backend (`serving/paged_kv.py`) — behind the fleet router every
replica can serve int8 paged (test-pinned in
tests/test_serving_fleet.py).
"""
from __future__ import annotations

import numpy as np

__all__ = ["QuantizedTensor", "quantize_per_channel", "quantize_params",
           "default_weight_filter", "prepare_inference_params"]


class QuantizedTensor:
    """int8 payload + per-channel fp32 scale, dequantized lazily.

    ``dequantize()`` emits ``q.astype(dtype) * scale`` — called inside a
    jit trace the int8 array is the captured constant and the
    cast/multiply fuse into the consumer; called eagerly it materializes
    the fp weight (tests, debugging).
    """

    __slots__ = ("q", "scale", "dtype", "axis")

    def __init__(self, q, scale, dtype=np.float32, axis=0):
        self.q = q
        self.scale = scale
        self.dtype = dtype
        self.axis = axis

    @property
    def shape(self):
        return tuple(self.q.shape)

    @property
    def nbytes(self):
        return int(np.prod(self.shape)) + 4 * int(np.prod(self.scale.shape))

    def dequantize(self):
        import jax.numpy as jnp

        return self.q.astype(self.dtype) * jnp.asarray(self.scale,
                                                       self.dtype)

    def __repr__(self):
        return (f"QuantizedTensor(shape={self.shape}, axis={self.axis}, "
                f"dtype={np.dtype(self.dtype).name})")


def quantize_per_channel(w, axis=0):
    """``w`` (numpy, any float dtype) -> (int8 q, fp32 scale) with the
    scale shaped to broadcast against ``w`` (size-1 on every axis but
    ``axis``)."""
    w = np.asarray(w, np.float32)
    reduce_axes = tuple(i for i in range(w.ndim) if i != axis)
    amax = np.abs(w).max(axis=reduce_axes, keepdims=True) \
        if reduce_axes else np.abs(w)
    scale = amax / 127.0
    scale = np.where(scale == 0.0, 1.0, scale).astype(np.float32)
    q = np.clip(np.rint(w / scale), -127, 127).astype(np.int8)
    return q, scale


def default_weight_filter(name, arr):
    """The weights the pass touches by default: float 2-D matmul /
    embedding tables and 4-D conv kernels named ``*weight`` (biases,
    norms, and positional tables stay fp — they are tiny and their
    precision is load-bearing)."""
    if not name.endswith("weight"):
        return False
    dtype = np.dtype(getattr(arr, "dtype", np.float32))
    if dtype.kind != "f":
        return False
    ndim = len(getattr(arr, "shape", ()))
    return ndim in (2, 4)


def quantize_params(params, dtype=np.float32, weight_filter=None,
                    device_put=True):
    """Quantize a name->array dict.  Returns a new dict where every
    filtered entry is a :class:`QuantizedTensor` (int8 on device when
    ``device_put``) and everything else passes through untouched.
    """
    import jax
    import jax.numpy as jnp

    weight_filter = weight_filter or default_weight_filter
    out = {}
    for name, arr in params.items():
        host = np.asarray(arr.asnumpy() if hasattr(arr, "asnumpy") else arr)
        if not weight_filter(name, host):
            out[name] = arr
            continue
        q, scale = quantize_per_channel(host, axis=0)
        if device_put:
            q = jax.device_put(q)
            scale = jax.device_put(scale)
        out[name] = QuantizedTensor(q, scale, dtype=jnp.dtype(dtype))
    return out


def prepare_inference_params(symbol, arg_params, aux_params, quantize="int8",
                             dtype=np.float32, weight_filter=None,
                             device_put=True):
    """Rewrite (symbol, params) for serving: Conv+BN fold, THEN int8.

    Ordering is the whole point: inference-mode Conv+BN folding
    (passes/convbn.py) multiplies each conv's weight rows by the BN
    scale ``gamma/sqrt(var+eps)`` — the per-channel symmetric scales
    below must be computed from the FOLDED weights, or the int8 grid
    would be sized to a dynamic range the deployed weights no longer
    have (channels with large BN scale would clip, channels with small
    BN scale would waste grid).  ``Predictor`` reproduces this ordering
    internally; this helper is the explicit form for serving code that
    manages its own executors.

    Returns ``(symbol, params, aux_params, n_folded)`` where ``params``
    maps each quantized weight to a :class:`QuantizedTensor` (and
    passes everything else through); ``quantize=None`` skips the int8
    step and returns the folded fp params.
    """
    from ..passes import apply_convbn_fold

    symbol, arg_params, aux_params, n_folded = apply_convbn_fold(
        symbol, arg_params, aux_params)
    if quantize == "int8":
        arg_params = quantize_params(arg_params, dtype=dtype,
                                     weight_filter=weight_filter,
                                     device_put=device_put)
    return symbol, arg_params, aux_params, n_folded
