"""Inference serving subsystem: continuous batching + int8 predict.

The first subsystem that SERVES traffic instead of training it — the
"millions of users, heavy traffic" workload of the ROADMAP north star,
exercising the predict-API surface of the source paper (the C-predict
ABI / `predict.py`) as a long-running server process.

Five pieces:

- :mod:`.scheduler` — slot-pool continuous batching over a
  `KVDecoder`: one jitted decode step per tick across all occupied
  slots, mid-flight slot reuse, bounded admission queue, deadlines.
- :mod:`.paged_kv` — paged KV cache: block-table indirection over a
  shared device page pool (``MXTPU_KV_BLOCK``) with prompt-prefix
  reuse (``MXTPU_PREFIX_CACHE``), so long and short requests co-batch
  without padding waste and shared system prompts are computed once.
- :mod:`.server` — stdlib HTTP front-end (``POST /generate`` with 429
  backpressure, plus the ops ``/metrics``, ``/healthz`` and the
  ``/admin/drain|undrain`` rolling-restart hooks); see
  ``tools/serve.py`` for the process entrypoint.
- :mod:`.router` — the serving-fleet front (``tools/serve.py
  --router``): least-loaded balancing over N replicas
  (``MXTPU_SERVE_REPLICAS`` or coordinator self-registration), bounded
  idempotent retries, draining rolling upgrades.
- :mod:`.quantize` — post-training int8 weight quantization
  (per-channel symmetric, int8 storage, dequantize-in-compute) for
  `Predictor` and `KVDecoder` — the TVM-style (arXiv:1802.04799)
  quantized-inference lowering, done through XLA fusion.

Requests are traceable end to end (docs/tracing.md): the router mints
a W3C ``traceparent`` per ``POST /generate``, the scheduler and paged
KV record host-side spans for every stage of a sampled request, and
the router keeps multi-window SLO burn rates at ``GET /slo``
(``MXTPU_TRACE``, ``MXTPU_SLO_TTFT_MS``, ``MXTPU_SLO_AVAIL``).

Env knobs (docs/how_to/env_var.md rounds 10 + 19 + 20):
``MXTPU_SERVE_SLOTS``, ``MXTPU_SERVE_QUEUE``,
``MXTPU_SERVE_DEADLINE_MS``, ``MXTPU_PREDICT_INT8``,
``MXTPU_SERVE_REPLICAS``, ``MXTPU_ROUTER_SCRAPE_S``,
``MXTPU_ROUTER_RETRIES``, ``MXTPU_KV_BLOCK``, ``MXTPU_PREFIX_CACHE``,
``MXTPU_TRACE``, ``MXTPU_TRACE_SAMPLE``, ``MXTPU_SPAN_RING``,
``MXTPU_SLO_TTFT_MS``, ``MXTPU_SLO_AVAIL``.
Metric families: docs/telemetry.md (serving + serving-fleet +
tracing/SLO sections).
"""
from . import quantize  # noqa: F401
from .paged_kv import PagedSlots, PoolExhausted  # noqa: F401
from .quantize import QuantizedTensor, quantize_params  # noqa: F401
from .router import (  # noqa: F401
    NoReplicaAvailable, ReplicaDied, ReplicaRouter, ReplicaTimeout,
    RouterRetriesExhausted, register_replica, start_router,
)
from .scheduler import (  # noqa: F401
    AdmissionQueueFull, Request, SlotScheduler,
)
from .server import serve_decoder, start_server  # noqa: F401
