"""Inference serving subsystem: continuous batching + int8 predict.

The first subsystem that SERVES traffic instead of training it — the
"millions of users, heavy traffic" workload of the ROADMAP north star,
exercising the predict-API surface of the source paper (the C-predict
ABI / `predict.py`) as a long-running server process.

Three pieces:

- :mod:`.scheduler` — slot-pool continuous batching over a
  `KVDecoder`: one jitted decode step per tick across all occupied
  slots, mid-flight slot reuse, bounded admission queue, deadlines.
- :mod:`.server` — stdlib HTTP front-end (``POST /generate`` with 429
  backpressure, plus the ops ``/metrics`` and ``/healthz``); see
  ``tools/serve.py`` for the process entrypoint.
- :mod:`.quantize` — post-training int8 weight quantization
  (per-channel symmetric, int8 storage, dequantize-in-compute) for
  `Predictor` and `KVDecoder` — the TVM-style (arXiv:1802.04799)
  quantized-inference lowering, done through XLA fusion.

Env knobs (docs/how_to/env_var.md round 10): ``MXTPU_SERVE_SLOTS``,
``MXTPU_SERVE_QUEUE``, ``MXTPU_SERVE_DEADLINE_MS``,
``MXTPU_PREDICT_INT8``.  Metric families: docs/telemetry.md (serving
section).
"""
from . import quantize  # noqa: F401
from .quantize import QuantizedTensor, quantize_params  # noqa: F401
from .scheduler import (  # noqa: F401
    AdmissionQueueFull, Request, SlotScheduler,
)
from .server import serve_decoder, start_server  # noqa: F401
